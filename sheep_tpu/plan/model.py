"""One planner to rule the rungs: the Plan object + its cost model.

The repo grew ~8 execution paths (mesh / sharded-tail / single-chip /
hybrid / host / stream / ext / spill / distext) selected by a tangle of
ladder order, ``SHEEP_*`` env knobs, and governor pricing spread across
the driver, the governor, and the ops modules.  This module is the
composition layer the ROADMAP's "one planner" item demands: ONE
:func:`plan_build` call that, per build, resolves

  the execution path   the kept rung order (availability x priced
                       feasibility), first kept = the rung that runs
  native threads T     resources.governor.native_thread_plan
  ext/spill block      the governor's fitted ext block, prior-corrected
  handoff windows W    the streamed-tail window policy
  distext legs N       resources.governor.distext_leg_plan
  jump depth / chunking  the lifting-table cap + chunk-loop gates

and records every one as a :class:`Decision` carrying its **provenance**:

  ``default``   nothing overrode the built-in policy
  ``priced``    the governor's ANALYTIC cost model changed it (a rung
                skipped, a block halved, a thread count vetoed)
  ``learned``   a measured prior (plan/priors.py — past ``ladder.plan``
                traces, ``.sum`` rollups, bench records) CORRECTED the
                analytic answer
  ``forced``    an explicit ``SHEEP_*`` knob or caller argument pinned
                it — the operator's word, never second-guessed

Parity contract (the acceptance): with no prior store configured, every
decision reproduces what the pre-planner code chose — the analytic
arithmetic still lives in resources/governor.py and is called, not
copied, so an A/B arm or forced-knob test sees the exact same path; the
planner only ADDS the measured-prior correction and the provenance
record.  Priors correct only the MEMORY side (keep/skip verdicts, block
fitting); measured seconds are reported beside each candidate in
``sheep plan --explain`` but never reorder the ladder — rung order
encodes correctness/availability constraints the clock knows nothing
about.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

from ..resources.governor import (EXT_BLOCK_ENV, EXT_BLOCK_FLOOR,
                                  EXT_RECORD_BYTES, NATIVE_THREADS_ENV,
                                  SPILL_BLOCK, ResourceGovernor,
                                  distext_forced_legs, distext_leg_plan,
                                  ext_block_edges, native_thread_plan,
                                  rung_peak_nbytes)
from .priors import PriorStore, mem_ratio

PROV_DEFAULT = "default"
PROV_PRICED = "priced"
PROV_LEARNED = "learned"
PROV_FORCED = "forced"

#: the full degradation ladder (runtime/driver.RuntimeConfig mirrors it)
DEFAULT_LADDER = ("mesh", "single", "host", "stream", "ext", "spill")


@dataclass
class Decision:
    """One resolved knob: what the plan chose, who decided, and why."""

    name: str
    value: object
    provenance: str
    knob: str | None = None       # the SHEEP_* registry knob that forces it
    analytic: object = None       # what the pure-analytic model said
    prior: dict | None = None     # the prior that corrected it
    reason: str = ""

    def to_dict(self) -> dict:
        out = {"name": self.name, "value": self.value,
               "provenance": self.provenance}
        if self.knob:
            out["knob"] = self.knob
        if self.analytic is not None and self.analytic != self.value:
            out["analytic"] = self.analytic
        if self.prior is not None:
            out["prior"] = dict(self.prior)
        if self.reason:
            out["reason"] = self.reason
        return out


@dataclass
class Plan:
    """One build's resolved plan: the kept rung order, every candidate's
    priced-vs-learned cost, and the per-knob decisions."""

    n: int
    links: int
    rungs: list[str]
    candidates: list[dict]
    decisions: dict[str, Decision] = field(default_factory=dict)
    native_threads: dict = field(default_factory=dict)
    headroom_bytes: int | None = None
    budget_bytes: int | None = None
    rss: int | None = None

    @property
    def chosen(self) -> str:
        return self.rungs[0] if self.rungs else "?"

    def decision(self, name: str) -> Decision:
        return self.decisions[name]

    def decisions_dict(self) -> list[dict]:
        return [d.to_dict() for d in self.decisions.values()]

    def corrections(self) -> list[Decision]:
        """The decisions history actually changed (provenance learned)."""
        return [d for d in self.decisions.values()
                if d.provenance == PROV_LEARNED]

    def to_dict(self) -> dict:
        return {
            "n": self.n, "links": self.links,
            "rungs": list(self.rungs), "chosen": self.chosen,
            "candidates": [dict(c) for c in self.candidates],
            "decisions": self.decisions_dict(),
            "headroom_bytes": self.headroom_bytes,
            "budget_bytes": self.budget_bytes,
        }

    def explain(self) -> list[str]:
        """The --explain text: chosen rung, candidate costs (priced vs
        historical), and each decision with its provenance."""
        def fb(x):
            if x is None:
                return "-"
            x = float(x)
            for unit, shift in (("G", 30), ("M", 20), ("K", 10)):
                if abs(x) >= (1 << shift):
                    return f"{x / (1 << shift):.1f}{unit}"
            return f"{int(x)}B"

        lines = [f"plan: n={self.n} links={self.links}"
                 + (f"  budget={fb(self.budget_bytes)} "
                    f"headroom={fb(self.headroom_bytes)}"
                    if self.budget_bytes is not None
                    else "  (unbudgeted)")]
        lines.append(f"chosen rung: {self.chosen}"
                     f"  (ladder {' -> '.join(self.rungs) or '-'})")
        head = (f"  {'RUNG':<8} {'PRICED':>9} {'LEARNED':>9} "
                f"{'HISTORY':>10} VERDICT")
        lines += ["candidates", head]
        for c in self.candidates:
            hist = c.get("prior_s")
            hist_s = f"{hist['mean']:.2f}s*{hist['count']}" if hist else "-"
            corrected = c.get("corrected_bytes")
            lines.append(
                f"  {c['rung']:<8} {fb(c.get('est_bytes')):>9} "
                f"{(fb(corrected) if corrected is not None else '-'):>9} "
                f"{hist_s:>10} {c['verdict']}"
                + (f"  [prior {c['prior']['key']} x{c['prior']['mean']:.2f}]"
                   if c.get("prior") else ""))
        lines.append("decisions")
        for d in self.decisions.values():
            line = f"  {d.name:<16} = {d.value!r:<12} [{d.provenance}]"
            if d.knob:
                line += f" knob {d.knob}"
            if d.provenance == PROV_LEARNED and d.analytic is not None:
                line += f"  (analytic said {d.analytic!r}"
                if d.prior:
                    line += (f"; corrected by prior {d.prior['key']} "
                             f"mean x{d.prior['mean']:.2f} "
                             f"over {d.prior['count']} run(s)")
                line += ")"
            elif d.reason:
                line += f"  ({d.reason})"
            lines.append(line)
        for d in self.corrections():
            lines.append(f"history corrected: {d.name} {d.analytic!r} -> "
                         f"{d.value!r} via {d.prior['key'] if d.prior else '?'}")
        return lines


def available_rungs(ladder=DEFAULT_LADDER, devices: int | None = None,
                    num_workers: int | None = None,
                    edges_path: str | None = None,
                    known=None) -> list[str]:
    """Availability filter (the driver's pre-plan step): drop mesh
    without >= 2 devices/workers, drop ext without a whole-input .dat.
    Pure function of its arguments — the driver passes the live device
    count (and its registered rung set: tests install synthetic rungs),
    the CLI passes what it knows."""
    known = set(DEFAULT_LADDER) if known is None else set(known)
    rungs = [r for r in ladder if r in known]
    if (devices is not None and devices < 2) \
            or (num_workers is not None and num_workers < 2):
        rungs = [r for r in rungs if r != "mesh"]
    if not (edges_path and edges_path.endswith(".dat")
            and os.path.exists(edges_path)):
        rungs = [r for r in rungs if r != "ext"]
    return rungs or ["host"]


def _fit_ext_block(n: int, head: int | None, ratio: float) -> int:
    """The governor's ext-block fitting loop (ext_fitted_block) with a
    measured-prior correction factor on the priced peak.  ratio=1.0
    reproduces the analytic fit bit for bit."""
    block = ext_block_edges()
    if os.environ.get(EXT_BLOCK_ENV, ""):
        return block  # pinned: the operator's word, resume identity
    if head is None:
        return block
    while block > EXT_BLOCK_FLOOR \
            and ratio * (32 * n + EXT_RECORD_BYTES * block) > head:
        block //= 2
    return block


def plan_build(n: int, links: int, *,
               rungs: list[str] | None = None,
               ladder=DEFAULT_LADDER, ladder_forced: bool = False,
               governor: ResourceGovernor | None = None,
               num_workers: int | None = None,
               devices: int | None = None,
               edges_path: str | None = None,
               priors: PriorStore | None = None,
               platform: str = "cpu",
               assume_rss: int | None = None,
               with_distext: bool = False) -> Plan:
    """Resolve one build's plan.  ``rungs`` (already availability- and
    resume-filtered) skips the filter; ``priors`` defaults to the
    ``SHEEP_PLAN_PRIORS`` store (None when unset — pure analytic);
    ``assume_rss`` pins the measured-RSS input so a plan can be
    reproduced deterministically (the CLI's --assume-rss)."""
    gov = governor if governor is not None else ResourceGovernor.from_env()
    if priors is None:
        priors = PriorStore.from_env()
    if rungs is None:
        rungs = available_rungs(ladder, devices, num_workers, edges_path)
    rss = assume_rss if assume_rss is not None else None
    if assume_rss is not None:
        head = gov.mem_budget - assume_rss \
            if gov.mem_budget is not None else None
    else:
        # through the governor, not a private rss read: deterministic
        # harnesses monkeypatch governor.rss_bytes and the plan must see
        # the same world the governor does
        head = gov.mem_headroom()

    decisions: dict[str, Decision] = {}

    # -- native threads (governor arithmetic; provenance from its reason)
    tplan = native_thread_plan(n, gov)
    t = tplan["threads"]
    if tplan["forced"]:
        t_prov = PROV_FORCED
    elif "vetoed" in tplan["reason"] or "leg cores" in tplan["reason"]:
        t_prov = PROV_PRICED
    else:
        t_prov = PROV_DEFAULT
    decisions["native_threads"] = Decision(
        "native_threads", t, t_prov, knob=NATIVE_THREADS_ENV,
        reason=tplan["reason"])

    # -- ext block: analytic fit vs prior-corrected fit
    ext_prior = mem_ratio(priors, "ext", n)
    analytic_block = _fit_ext_block(n, head, 1.0)
    block = _fit_ext_block(n, head, ext_prior["mean"]) if ext_prior \
        else analytic_block
    if os.environ.get(EXT_BLOCK_ENV, ""):
        b_prov, b_reason = PROV_FORCED, f"pinned by {EXT_BLOCK_ENV}"
    elif ext_prior and block != analytic_block:
        b_prov = PROV_LEARNED
        b_reason = (f"measured rss ran x{ext_prior['mean']:.2f} the "
                    f"analytic price on this host")
    elif block != ext_block_edges():
        b_prov = PROV_PRICED
        b_reason = "halved to the memory headroom"
    else:
        b_prov, b_reason = PROV_DEFAULT, ""
    decisions["ext_block"] = Decision(
        "ext_block", block, b_prov, knob=EXT_BLOCK_ENV,
        analytic=analytic_block,
        prior=ext_prior if b_prov == PROV_LEARNED else None,
        reason=b_reason)

    # -- rung pricing: the governor's plan_rungs loop, prior-corrected.
    # The last rung always survives (something must run).
    candidates: list[dict] = []
    kept: list[str] = []
    verdict_changed = False
    any_skip = False
    for i, rung in enumerate(rungs):
        try:
            est = rung_peak_nbytes(
                rung, n, links, num_workers or 1,
                ext_block=block if rung == "ext" else None,
                threads=t)
        except ValueError:
            # a rung the cost model does not know (tests install
            # synthetic rungs): unpriceable, never skipped
            cand = {"rung": rung, "est_bytes": None, "verdict": "keep"}
            kept.append(rung)
            candidates.append(cand)
            continue
        prior = mem_ratio(priors, rung, n)
        corrected = int(est * prior["mean"]) if prior else None
        effective = corrected if corrected is not None else est
        cand = {"rung": rung, "est_bytes": int(est), "verdict": "keep"}
        if corrected is not None:
            cand["corrected_bytes"] = corrected
            cand["prior"] = prior
        ps = priors.lookup("rung_s", rung, links) if priors else None
        if ps:
            cand["prior_s"] = ps
        if head is not None and effective > head and i < len(rungs) - 1:
            cand["verdict"] = "skip"
            any_skip = True
            if est <= head:
                verdict_changed = True  # analytic said keep; history said no
        else:
            if head is not None and est > head \
                    and effective <= head and i < len(rungs) - 1:
                verdict_changed = True  # history rescued an analytic skip
            kept.append(rung)
        candidates.append(cand)
    if ladder_forced:
        r_prov, r_reason = PROV_FORCED, "ladder pinned by the caller"
    elif verdict_changed:
        r_prov = PROV_LEARNED
        r_reason = "a measured prior changed a keep/skip verdict"
    elif any_skip:
        r_prov, r_reason = PROV_PRICED, "governor-priced rungs skipped"
    else:
        r_prov, r_reason = PROV_DEFAULT, ""
    decisions["rungs"] = Decision(
        "rungs", list(kept), r_prov, knob=None,
        analytic=[r for i, r in enumerate(rungs)
                  if head is None
                  or candidates[i]["est_bytes"] is None
                  or candidates[i]["est_bytes"] <= head
                  or i == len(rungs) - 1],
        prior=next((c["prior"] for c in candidates
                    if c.get("prior") and r_prov == PROV_LEARNED), None),
        reason=r_reason)

    # -- handoff windows (ops/build.handoff_windows policy, jax-free)
    wv = os.environ.get("SHEEP_HANDOFF_WINDOWS", "")
    if wv != "":
        w, w_prov = max(1, int(wv)), PROV_FORCED
    elif platform == "cpu":
        w, w_prov = 1, PROV_DEFAULT
    else:
        w, w_prov = (4 if links >= (1 << 20) else 1), PROV_DEFAULT
    decisions["handoff_windows"] = Decision(
        "handoff_windows", w, w_prov, knob="SHEEP_HANDOFF_WINDOWS")

    # -- jump-table depth cap (the chunk drivers' lv ceiling)
    levels = gov.shrunk_levels(10, n) if gov.active else 10
    decisions["levels"] = Decision(
        "levels", levels,
        PROV_PRICED if levels < 10 else PROV_DEFAULT, knob=None,
        analytic=10,
        reason="jump tables shrunk to headroom" if levels < 10 else "")

    # -- chunk-loop gates (recorded overrides; the loops read them live)
    for name, knob, dflt in (("pipeline_chunks", "SHEEP_PIPELINE_CHUNKS",
                              "1"),
                             ("plateau_adapt", "SHEEP_PLATEAU_ADAPT",
                              "1")):
        v = os.environ.get(knob, "")
        decisions[name] = Decision(
            name, (v or dflt) != "0",
            PROV_FORCED if v != "" else PROV_DEFAULT, knob=knob)

    # -- spill block (compile-time constant today; recorded so --explain
    # shows the whole surface)
    decisions["spill_block"] = Decision(
        "spill_block", SPILL_BLOCK, PROV_DEFAULT, knob=None)

    # -- distext legs (only meaningful with a whole-input file, but the
    # decision is cheap and the provenance story should be complete)
    if with_distext or distext_forced_legs():
        forced_legs = distext_forced_legs()
        dplan = distext_leg_plan(n, gov)
        if forced_legs:
            d_prov, d_reason = PROV_FORCED, "pinned by SHEEP_DISTEXT_LEGS"
        else:
            free = distext_leg_plan(
                n, ResourceGovernor(mem_budget=None,
                                    disk_budget=gov.disk_budget,
                                    scratch_dir=gov.scratch_dir))
            d_prov = PROV_PRICED if dplan["legs"] < free["legs"] \
                else PROV_DEFAULT
            d_reason = ("cut to the aggregate per-leg budget"
                        if d_prov == PROV_PRICED else "")
        decisions["distext_legs"] = Decision(
            "distext_legs", dplan["legs"], d_prov,
            knob="SHEEP_DISTEXT_LEGS", reason=d_reason)

    return Plan(n=n, links=links, rungs=kept, candidates=candidates,
                decisions=decisions, native_threads=dict(tplan),
                headroom_bytes=head, budget_bytes=gov.mem_budget,
                rss=rss)


def plan_distext_legs(n: int = 0,
                      governor: ResourceGovernor | None = None,
                      priors: PriorStore | None = None) -> dict:
    """The distext leg planner, routed through the plan layer (ISSUE
    15): the governor's arithmetic (distext_leg_plan) plus the decision
    record.  Returns the governor dict EXTENDED with ``provenance`` —
    existing consumers (ops/distext.run_distext) read the same keys."""
    gov = governor if governor is not None else ResourceGovernor.from_env()
    out = distext_leg_plan(n, gov)
    if out["forced"]:
        out["provenance"] = PROV_FORCED
    else:
        free = distext_leg_plan(
            n, ResourceGovernor(mem_budget=None,
                                disk_budget=gov.disk_budget,
                                scratch_dir=gov.scratch_dir))
        out["provenance"] = PROV_PRICED if out["legs"] < free["legs"] \
            else PROV_DEFAULT
    return out


#: the transport cost model's assumed bandwidths (ISSUE 16): sequential
#: local disk stream vs one worker-wire crossing.  Deliberately coarse
#: round numbers — the decision only has to be right about the SHAPE
#: (waves of legs over cores vs waves over workers), and the pin knob
#: (SHEEP_WORKER_TRANSPORT) is the operator's word when it is not.
TRANSPORT_DISK_BPS = 256 << 20
TRANSPORT_WIRE_BPS = 128 << 20

#: pin the per-leg transport decision: "ship" | "local" | "" (priced)
WORKER_TRANSPORT_ENV = "SHEEP_WORKER_TRANSPORT"


def plan_transport(records: int, legs: int, remote_workers: int,
                   pin: str | None = None,
                   host_cores: int | None = None) -> dict:
    """Price network-ship vs local-disk dispatch for the distext legs
    (the transport decision recorded in the ``distext.plan`` event).

    The model (PERF_NOTES "network-ship vs local-disk pricing rule"):
    a LOCAL leg streams its slice from the supervisor's disk, and the
    legs time-share the host — cost ~= ceil(legs / host_cores) waves of
    ``slice_bytes / DISK_BPS``.  A SHIPPED leg pays one wire crossing,
    then folds on a worker's own core; crossings pipeline with the
    previous wave's folds (the prefetch-overlap shape), so cost ~=
    ceil(legs / workers) disk-speed waves plus ONE un-overlapped first
    crossing.  Ship wins only when it is STRICTLY cheaper — on a tie the
    bytes stay home.  No remote workers configured = "local" by default;
    ``SHEEP_WORKER_TRANSPORT`` pins either way (provenance "forced")."""
    if pin is None:
        pin = os.environ.get(WORKER_TRANSPORT_ENV, "")
    legs = max(1, int(legs))
    per_leg_bytes = (max(0, int(records)) * 12) // legs
    out = {"per_leg_bytes": per_leg_bytes, "remote_workers":
           int(remote_workers), "ship_s": None, "local_s": None,
           "reason": ""}
    if pin in ("ship", "local"):
        out.update(transport=pin, provenance=PROV_FORCED,
                   reason=f"pinned by {WORKER_TRANSPORT_ENV}")
        return out
    if pin:
        raise ValueError(f"{WORKER_TRANSPORT_ENV}={pin!r} must be "
                         f"'ship' or 'local'")
    if remote_workers < 1:
        out.update(transport="local", provenance=PROV_DEFAULT,
                   reason="no remote workers configured")
        return out
    if host_cores is None:
        try:
            host_cores = len(os.sched_getaffinity(0))
        except (AttributeError, OSError):
            host_cores = os.cpu_count() or 1
    stream_s = per_leg_bytes / TRANSPORT_DISK_BPS
    wire_s = per_leg_bytes / TRANSPORT_WIRE_BPS
    local_waves = -(-legs // max(1, host_cores))
    ship_waves = -(-legs // max(1, remote_workers))
    local_s = local_waves * stream_s
    ship_s = ship_waves * stream_s + wire_s
    out.update(ship_s=round(ship_s, 6), local_s=round(local_s, 6))
    if ship_s < local_s:
        out.update(transport="ship", provenance=PROV_PRICED,
                   reason=f"{remote_workers} worker(s) beat "
                          f"{host_cores} local core(s): "
                          f"{ship_waves} shipped wave(s) + one wire "
                          f"crossing < {local_waves} local wave(s)")
    else:
        out.update(transport="local", provenance=PROV_PRICED,
                   reason="shipping the slices does not beat the local "
                          "disk waves")
    return out


#: pin the rebalancer's migrate decision: "go" | "stay" | "" (priced)
REBALANCE_PIN_ENV = "SHEEP_REBALANCE_PIN"


def plan_migration(records: int, tenant_qps: float, src_qps: float,
                   dest_qps: float, pin: str | None = None,
                   horizon_s: float = 60.0) -> dict:
    """Price a live tenant migration for the rebalancer (ISSUE 17,
    serve/rebalance.py): is moving this tenant from its hot cluster to
    the cool one worth the transfer?

    The model reuses the transport bandwidth constants: the phase-1
    snapshot pays one wire crossing plus one local landing stream
    (``bytes/WIRE + bytes/DISK``); the phase-2 delta rides under live
    traffic and the phase-3 cutover is fenced milliseconds, so the
    snapshot dominates.  GO only when BOTH hold: the qps imbalance
    between the clusters strictly SHRINKS after the move (otherwise the
    migration is churn, not balance), and the transfer amortizes inside
    ``horizon_s`` of the imbalance it removes.  Ties stay home — the
    same strictly-cheaper discipline as :func:`plan_transport`.
    ``SHEEP_REBALANCE_PIN`` is the operator's word (provenance
    "forced"); the rebalancer's own hysteresis/cooldown gates run
    BEFORE this pricing, not inside it."""
    if pin is None:
        pin = os.environ.get(REBALANCE_PIN_ENV, "")
    blob = max(0, int(records)) * 12
    out = {"blob_bytes": blob, "tenant_qps": round(tenant_qps, 3),
           "src_qps": round(src_qps, 3),
           "dest_qps": round(dest_qps, 3),
           "cost_s": None, "reason": ""}
    if pin in ("go", "stay"):
        out.update(migrate=pin, provenance=PROV_FORCED,
                   reason=f"pinned by {REBALANCE_PIN_ENV}")
        return out
    if pin:
        raise ValueError(f"{REBALANCE_PIN_ENV}={pin!r} must be "
                         f"'go' or 'stay'")
    cost_s = blob / TRANSPORT_WIRE_BPS + blob / TRANSPORT_DISK_BPS
    out["cost_s"] = round(cost_s, 6)
    before = abs(src_qps - dest_qps)
    after = abs((src_qps - tenant_qps) - (dest_qps + tenant_qps))
    out["imbalance_before"] = round(before, 3)
    out["imbalance_after"] = round(after, 3)
    if tenant_qps <= 0 or after >= before:
        out.update(migrate="stay", provenance=PROV_DEFAULT,
                   reason="moving this tenant does not shrink the "
                          "cluster qps imbalance")
        return out
    if cost_s > horizon_s:
        out.update(migrate="stay", provenance=PROV_PRICED,
                   reason=f"snapshot transfer ({cost_s:.1f}s) does not "
                          f"amortize inside the {horizon_s:g}s horizon")
        return out
    out.update(migrate="go", provenance=PROV_PRICED,
               reason=f"imbalance {before:.1f} -> {after:.1f} qps for a "
                      f"{cost_s:.2f}s transfer")
    return out


#: pin the re-sequence decision: "go" | "stay" | "" (priced)
RESEQ_PIN_ENV = "SHEEP_RESEQ_PIN"
#: amortization horizon for the rebuild (seconds)
RESEQ_HORIZON_ENV = "SHEEP_RESEQ_HORIZON_S"
#: assumed carry-fold throughput of the streamed rebuild — deliberately
#: coarse (same discipline as TRANSPORT_*): the decision only has to be
#: right about the SHAPE (a rebuild is seconds, not hours), and
#: SHEEP_RESEQ_PIN is the operator's word when it is not
RESEQ_FOLD_BPS = 64 << 20


def plan_reseq(records: int, inserted: int, seq_drift: int,
               pin: str | None = None,
               horizon_s: float | None = None,
               priors=None) -> dict:
    """Price a full re-sequence rebuild for the serve tier (ISSUE 18,
    serve/reseq.py): the detector already fired — is the streamed fold
    over ``.dat + log`` worth running NOW?

    The model: the rebuild streams ``(records + inserted) * 12`` bytes
    off local disk and folds them (``bytes/DISK + bytes/FOLD``); the
    counting-sort sequence pass and the partition sweep are noise beside
    the fold.  GO when the rebuild amortizes inside ``horizon_s`` AND
    there is real drift to recover (``seq_drift > 0``) — a drift-free
    forced rebuild is the operator's call (``SHEEP_RESEQ_PIN=go`` or the
    RESEQ verb's force), not the planner's.  The daemon's own detector
    gates (SHEEP_RESEQ_DRIFT / _DRIFT_MIN) run BEFORE this pricing,
    exactly like the rebalancer's hysteresis.

    ``priors`` (a plan/priors.py PriorStore) replaces the analytic
    RESEQ_FOLD_BPS guess with this host's MEASURED fold throughput —
    harvested from past ``reseq.fold`` trace spans, the way plan_build
    learns rung seconds.  The decision then carries provenance
    ``learned``; when history thins (< MIN_CORRECT_SAMPLES at this
    scale) the analytic constant is the fallback, same as everywhere."""
    if pin is None:
        pin = os.environ.get(RESEQ_PIN_ENV, "")
    if horizon_s is None:
        horizon_s = float(os.environ.get(RESEQ_HORIZON_ENV, "") or 60.0)
    blob = (max(0, int(records)) + max(0, int(inserted))) * 12
    out = {"blob_bytes": blob, "records": max(0, int(records)),
           "inserted": max(0, int(inserted)),
           "seq_drift": max(0, int(seq_drift)),
           "cost_s": None, "reason": ""}
    if pin in ("go", "stay"):
        out.update(decision=pin, provenance=PROV_FORCED,
                   reason=f"pinned by {RESEQ_PIN_ENV}")
        return out
    if pin:
        raise ValueError(f"{RESEQ_PIN_ENV}={pin!r} must be "
                         f"'go' or 'stay'")
    from .priors import fold_bps as _fold_bps
    prior = _fold_bps(priors, blob)
    bps = prior["mean"] if prior else RESEQ_FOLD_BPS
    cost_s = blob / TRANSPORT_DISK_BPS + blob / bps
    out["cost_s"] = round(cost_s, 6)
    out["fold_bps"] = int(bps)
    priced_prov = PROV_LEARNED if prior else PROV_PRICED
    learned = (f" (measured fold {bps / (1 << 20):.0f} MB/s over "
               f"{prior['count']} run(s))" if prior else "")
    if prior:
        out["prior"] = prior
        out["analytic_cost_s"] = round(
            blob / TRANSPORT_DISK_BPS + blob / RESEQ_FOLD_BPS, 6)
    if seq_drift <= 0:
        out.update(decision="stay", provenance=PROV_DEFAULT,
                   reason="no sequence drift to recover")
        return out
    if cost_s > horizon_s:
        out.update(decision="stay", provenance=priced_prov,
                   reason=f"rebuild ({cost_s:.1f}s) does not amortize "
                          f"inside the {horizon_s:g}s horizon" + learned)
        return out
    out.update(decision="go", provenance=priced_prov,
               reason=f"{seq_drift} drifted insert(s) recovered for a "
                      f"{cost_s:.2f}s streamed rebuild" + learned)
    return out


# -- the anti-entropy scrub job (ISSUE 20, serve/scrub.py) ------------------

SCRUB_PIN_ENV = "SHEEP_SCRUB_PIN"
#: the budget one background scrub pass may spend re-reading sealed bytes
SCRUB_HORIZON_ENV = "SHEEP_SCRUB_HORIZON_S"
#: crc32c re-verification throughput (memory-bound streaming checksum;
#: conservative so pricing declines before the disk does)
SCRUB_SUM_BPS = 512 << 20


def plan_scrub(artifacts: int, bytes_total: int,
               pin: str | None = None,
               horizon_s: float | None = None) -> dict:
    """Price one background scrub pass (ISSUE 20): re-reading every
    sealed artifact costs ``bytes/DISK + bytes/SUM`` — GO when that fits
    inside ``horizon_s`` (default 30s), else STAY and let the operator
    raise the horizon, tighten the interval, or pin.  The daemon's
    interval knob (SHEEP_SCRUB_INTERVAL_S) gates WHEN pricing runs,
    exactly like the reseq detector gates plan_reseq; an inline ``SCRUB``
    verb is the operator's force and skips pricing entirely."""
    if pin is None:
        pin = os.environ.get(SCRUB_PIN_ENV, "")
    if horizon_s is None:
        horizon_s = float(os.environ.get(SCRUB_HORIZON_ENV, "") or 30.0)
    blob = max(0, int(bytes_total))
    out = {"artifacts": max(0, int(artifacts)), "blob_bytes": blob,
           "cost_s": None, "reason": ""}
    if pin in ("go", "stay"):
        out.update(decision=pin, provenance=PROV_FORCED,
                   reason=f"pinned by {SCRUB_PIN_ENV}")
        return out
    if pin:
        raise ValueError(f"{SCRUB_PIN_ENV}={pin!r} must be "
                         f"'go' or 'stay'")
    if not artifacts:
        out.update(decision="stay", provenance=PROV_DEFAULT,
                   reason="nothing sealed to re-verify")
        return out
    cost_s = blob / TRANSPORT_DISK_BPS + blob / SCRUB_SUM_BPS
    out["cost_s"] = round(cost_s, 6)
    if cost_s > horizon_s:
        out.update(decision="stay", provenance=PROV_PRICED,
                   reason=f"re-verifying {blob >> 20} MiB "
                          f"({cost_s:.1f}s) exceeds the {horizon_s:g}s "
                          f"scrub horizon")
        return out
    out.update(decision="go", provenance=PROV_PRICED,
               reason=f"{artifacts} sealed artifact(s), "
                      f"{cost_s:.2f}s to re-verify")
    return out
