"""Out-of-core streaming build: edge blocks from host DRAM into the device.

The reference's OOM story is partial loads with more partials than cores
(scripts/horizontal-dist.sh:22-24, README:112-122): workers stream
edge-disjoint slices and the associative tree merge stitches them.  The
device analog keeps only O(n + B) state resident: a carry forest (two
length-n arrays) plus one B-edge block.  Each block step rebuilds the forest
from (carry links + block links) with the fixpoint kernel — correct because
a forest re-enters as its own link set and the merge is associative
(lib/jnode.cpp:174-201).  pst accumulates as a segment-sum per block.

Shapes are static (one compilation for any number of blocks), and JAX's
async dispatch overlaps the host memmap read of block k+1 with the device
compute of block k — the double-buffering the reference gets from OS
readahead.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .. import INVALID_JNID
from ..core.forest import Forest
from .forest import forest_fixpoint, pst_weights
from .sort import degree_histogram


@functools.partial(jax.jit, static_argnames=("n",))
def stream_block_step(parent: jnp.ndarray, pst: jnp.ndarray,
                      tail: jnp.ndarray, head: jnp.ndarray,
                      pos: jnp.ndarray, n: int):
    """Fold one edge block into the carry forest.

    parent int32 [n] (n = root sentinel), pst int32 [n], tail/head int32 [B]
    (pad with n), pos int32 [n+1] vid->position with pos[n] = n.
    """
    sent = jnp.int32(n)
    pt = pos[jnp.minimum(tail, sent)]
    ph = pos[jnp.minimum(head, sent)]
    lo = jnp.minimum(pt, ph)
    hi = jnp.maximum(pt, ph)
    # pst: every block edge with a present earlier endpoint, absent-endpoint
    # edges included (pst-only contract); loops/padding (lo == hi) excluded.
    pst = pst + pst_weights(jnp.where(lo == hi, sent, lo), n)
    dead = (lo >= hi) | (hi >= sent)
    blo = jnp.where(dead, sent, lo)
    bhi = jnp.where(dead, sent, hi)
    # carry forest re-enters as its own links
    kid = jnp.arange(n, dtype=jnp.int32)
    clive = parent < sent
    clo = jnp.where(clive, kid, sent)
    chi = jnp.where(clive, parent, sent)
    mlo = jnp.concatenate([clo, blo])
    mhi = jnp.concatenate([chi, bhi])
    new_parent, rounds = forest_fixpoint(mlo, mhi, n)
    return new_parent, pst, rounds


def build_graph_streaming(blocks, n: int, pos: np.ndarray,
                          block_edges: int):
    """Fold an iterator of (tail, head) uint32 blocks into a Forest.

    ``pos``: vid -> position table over n slots (positions of the shared
    sequence; INVALID for absent vids).  Returns (Forest over n positions,
    total_rounds).  Memory: O(n + block_edges) device-resident.
    """
    sent = np.int32(n)
    posx = np.full(n + 1, n, dtype=np.int32)
    take = min(len(pos), n)
    p = pos[:take].astype(np.int64)
    posx[:take] = np.where((p < 0) | (p >= n), n, p).astype(np.int32)
    pos_d = jnp.asarray(posx)

    parent = jnp.full(n, sent, jnp.int32)
    pst = jnp.zeros(n, jnp.int32)
    round_counts = []  # device arrays; summing later keeps dispatch async
    for tail, head in blocks:
        b = len(tail)
        t = np.full(block_edges, n, dtype=np.int64)
        h = np.full(block_edges, n, dtype=np.int64)
        t[:b] = tail
        h[:b] = head
        parent, pst, rounds = stream_block_step(
            parent, pst, jnp.asarray(t, jnp.int32), jnp.asarray(h, jnp.int32),
            pos_d, n)
        round_counts.append(rounds)
    total_rounds = int(sum(int(r) for r in round_counts)) if round_counts else 0
    parent_np = np.asarray(parent).astype(np.int64)
    out = np.full(n, INVALID_JNID, dtype=np.uint32)
    live = parent_np < n
    out[live] = parent_np[live].astype(np.uint32)
    return Forest(out, np.asarray(pst).astype(np.uint32)), total_rounds


def streaming_degree_histogram(blocks, n: int) -> np.ndarray:
    """Degree histogram from an edge-block iterator (device bincount)."""
    deg = jnp.zeros(n, jnp.int32)
    for tail, head in blocks:
        deg = deg + degree_histogram(jnp.asarray(tail, jnp.int32),
                                     jnp.asarray(head, jnp.int32), n)
    return np.asarray(deg).astype(np.int64)
