"""Out-of-core streaming build: edge blocks from host DRAM into the device.

The reference's OOM story is partial loads with more partials than cores
(scripts/horizontal-dist.sh:22-24, README:112-122): workers stream
edge-disjoint slices and the associative tree merge stitches them.  The
device analog keeps only O(n + B) state resident: a carry forest (two
length-n arrays) plus one B-edge block.  Each block step rebuilds the forest
from (carry links + block links) with the fixpoint kernel — correct because
a forest re-enters as its own link set and the merge is associative
(lib/jnode.cpp:174-201).  pst accumulates as a segment-sum per block.

Shapes are static (one compilation for any number of blocks), and JAX's
async dispatch overlaps the host memmap read of block k+1 with the device
compute of block k — the double-buffering the reference gets from OS
readahead.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .. import INVALID_JNID
from ..core.forest import Forest
from .forest import forest_fixpoint, pst_weights
from .sort import degree_histogram


@functools.partial(jax.jit, static_argnames=("n",))
def stream_block_step(parent: jnp.ndarray, pst: jnp.ndarray,
                      tail: jnp.ndarray, head: jnp.ndarray,
                      pos: jnp.ndarray, n: int):
    """Fold one edge block into the carry forest.

    parent int32 [n] (n = root sentinel), pst int32 [n], tail/head int32 [B]
    (pad with values >= V), pos int32 [V+1] over the FULL vid space (V =
    max vid + 1, which can far exceed the n active positions — zero-degree
    vids exist between active ones), absent vids and the pad slot mapped
    to n.
    """
    sent = jnp.int32(n)
    vid_cap = jnp.int32(pos.shape[0] - 1)
    pt = pos[jnp.minimum(tail, vid_cap)]
    ph = pos[jnp.minimum(head, vid_cap)]
    lo = jnp.minimum(pt, ph)
    hi = jnp.maximum(pt, ph)
    # pst: every block edge with a present earlier endpoint, absent-endpoint
    # edges included (pst-only contract); loops/padding (lo == hi) excluded.
    pst = pst + pst_weights(jnp.where(lo == hi, sent, lo), n)
    dead = (lo >= hi) | (hi >= sent)
    blo = jnp.where(dead, sent, lo)
    bhi = jnp.where(dead, sent, hi)
    # carry forest re-enters as its own links
    kid = jnp.arange(n, dtype=jnp.int32)
    clive = parent < sent
    clo = jnp.where(clive, kid, sent)
    chi = jnp.where(clive, parent, sent)
    mlo = jnp.concatenate([clo, blo])
    mhi = jnp.concatenate([chi, bhi])
    new_parent, rounds = forest_fixpoint(mlo, mhi, n)
    return new_parent, pst, rounds


def build_graph_streaming(blocks, n: int, pos: np.ndarray,
                          block_edges: int):
    """Fold an iterator of (tail, head) uint32 blocks into a Forest.

    ``pos``: vid -> position table over the FULL vid space (length >= max
    vid + 1; INVALID for absent vids).  Returns (Forest over n positions,
    total_rounds).  Memory: O(n + V + block_edges) device-resident.
    """
    sent = np.int32(n)
    pos_d = jnp.asarray(_full_vid_pos(pos, n))
    vid_pad = len(pos)  # pad records map to the table's sentinel slot

    parent = jnp.full(n, sent, jnp.int32)
    pst = jnp.zeros(n, jnp.int32)
    round_counts = []  # device arrays; summing later keeps dispatch async
    for tail, head in blocks:
        b = len(tail)
        t = np.full(block_edges, vid_pad, dtype=np.int64)
        h = np.full(block_edges, vid_pad, dtype=np.int64)
        t[:b] = tail
        h[:b] = head
        parent, pst, rounds = stream_block_step(
            parent, pst, jnp.asarray(t, jnp.int32), jnp.asarray(h, jnp.int32),
            pos_d, n)
        round_counts.append(rounds)
    total_rounds = int(sum(int(r) for r in round_counts)) if round_counts else 0
    parent_np = np.asarray(parent).astype(np.int64)
    out = np.full(n, INVALID_JNID, dtype=np.uint32)
    live = parent_np < n
    out[live] = parent_np[live].astype(np.uint32)
    return Forest(out, np.asarray(pst).astype(np.uint32)), total_rounds


def _full_vid_pos(pos: np.ndarray, n: int) -> np.ndarray:
    """Sanitize a vid->position table for device use: full vid space plus
    one trailing sentinel slot; absent/invalid entries map to n."""
    posx = np.full(len(pos) + 1, n, dtype=np.int32)
    p = pos.astype(np.int64)
    posx[:-1] = np.where((p < 0) | (p >= n), n, p).astype(np.int32)
    return posx


@functools.partial(jax.jit, static_argnames=("n",))
def _block_links(tail, head, pos, n: int):
    """Map one padded edge block to (lo, hi, pst_block) in one dispatch.

    ``pos``: the _full_vid_pos table ([V+1], sentinel slot last)."""
    sent = jnp.int32(n)
    vid_cap = jnp.int32(pos.shape[0] - 1)
    pt = pos[jnp.minimum(tail, vid_cap)]
    ph = pos[jnp.minimum(head, vid_cap)]
    lo = jnp.minimum(pt, ph)
    hi = jnp.maximum(pt, ph)
    pst = pst_weights(jnp.where(lo == hi, sent, lo), n)
    dead = (lo >= hi) | (hi >= sent)
    return jnp.where(dead, sent, lo), jnp.where(dead, sent, hi), pst


def build_graph_streaming_hosted(blocks, n: int, pos: np.ndarray,
                                 block_edges: int):
    """Production OOM streaming build: hosted chunked reduction per block.

    Same contract as :func:`build_graph_streaming` but the per-block fold
    uses the host-orchestrated reducer (ops.forest.reduce_links_hosted):
    bounded per-dispatch execution time (no device faults at scale) and
    carry compaction between blocks — the carry is the live link set, at
    most ~n entries once reduction converges, concatenated with each new
    block's links.  Returns (Forest over n positions, total_rounds).
    """
    from .forest import parent_from_links, reduce_links_hosted

    pos_d = jnp.asarray(_full_vid_pos(pos, n))
    vid_pad = len(pos)

    from .forest import _pad_pow2

    carry_lo = carry_hi = None
    pst = jnp.zeros(n, jnp.int32)
    total_rounds = 0
    for tail, head in blocks:
        b = len(tail)
        t = np.full(block_edges, vid_pad, dtype=np.int64)
        h = np.full(block_edges, vid_pad, dtype=np.int64)
        t[:b] = tail
        h[:b] = head
        lo, hi, pst_b = _block_links(
            jnp.asarray(t, jnp.int32), jnp.asarray(h, jnp.int32), pos_d, n)
        pst = pst + pst_b
        if carry_lo is not None:
            lo = jnp.concatenate([carry_lo, lo])
            hi = jnp.concatenate([carry_hi, hi])
        # Mid-stream the carry only needs to stay BOUNDED (a few rounds
        # kill the duplicate/star bulk); full convergence happens once,
        # after the last block — ~3-5 rounds per block instead of ~30.
        lo, hi, live, rounds, _ = reduce_links_hosted(
            lo, hi, n, stop_live=2 * n)
        total_rounds += rounds
        target = _pad_pow2(live)
        carry_lo, carry_hi = lo[:target], hi[:target]
    if carry_lo is None:
        return Forest(np.full(n, INVALID_JNID, np.uint32),
                      np.zeros(n, np.uint32)), 0
    # Final fold ends like the hybrid: reduce to the platform-tuned
    # handoff threshold and let the native union-find chase the residue —
    # the device-convergence tail was measured at hundreds of rounds on
    # the last few thousand links (SCALE_r03: 781 total rounds).
    from .build import (default_handoff_factor, handoff_input_ok,
                        reduce_and_finish_native)
    # same production reduce+tail as the hybrid — the streaming windowed
    # handoff when enabled, the serial fetch (with the speculative
    # snapshot stream on accelerators) otherwise.  pst here is the
    # accumulated per-block count, NOT recoverable from the carry links
    # (they were rewritten by the mid-stream folds), so the fold always
    # receives it precomputed.
    pst_np = np.asarray(pst).astype(np.uint32)
    res = reduce_and_finish_native(
        carry_lo, carry_hi, n, stop_live=default_handoff_factor() * n,
        handoff_input=handoff_input_ok(), pst_h=pst_np)
    total_rounds += res[4]
    if res[0] == "device":  # converged before the handoff threshold
        parent = parent_from_links(res[1], res[2], n)
        parent_np = np.asarray(parent).astype(np.int64)
        out = np.full(n, INVALID_JNID, dtype=np.uint32)
        live_mask = parent_np < n
        out[live_mask] = parent_np[live_mask].astype(np.uint32)
        return Forest(out, pst_np), total_rounds
    _, parent_h, pst_out, _, _ = res
    return Forest(parent_h.copy(), pst_out.copy()), total_rounds


def streaming_degree_histogram(blocks, n: int) -> np.ndarray:
    """Degree histogram from an edge-block iterator (device bincount)."""
    deg = jnp.zeros(n, jnp.int32)
    for tail, head in blocks:
        deg = deg + degree_histogram(jnp.asarray(tail, jnp.int32),
                                     jnp.asarray(head, jnp.int32), n)
    return np.asarray(deg).astype(np.int64)
