"""Pallas TPU kernel: fused multi-level pointer jump for the reduce round.

Why: one reduce round lifts every live link's ``lo`` through L binary-lifted
ancestor tables (ops/forest.py ``_jump``).  As jnp, each level materializes
an E-sized gather result and an E-sized select in HBM — ~2L E-passes per
round, and the per-op rate on the measured backend is flat (~85-150M
elem/s, PERF_NOTES.md), so passes are the whole cost.  This kernel fuses a
GROUP of levels into one pass: the lo/hi block and the loop-carried lo stay
in VMEM across levels, so g levels cost ~one E-read + one E-write instead
of ~2g E-passes.

VMEM is the constraint: every level's table ([n+1] int32) must be resident,
so the group size is chosen from a ~12MB budget — all 10 levels fit at
n <= 2^18, pairs at 2^20, singles at 2^21; above that the jnp path stands
(one table alone outgrows VMEM).  ``fused_jump`` composes groups greedily
and is a drop-in replacement for the descent loop in ``_jump``.

Gated off by default (SHEEP_PALLAS=1 to enable in ops.forest): the axon
backend's Pallas support is probed by scripts/pallas_probe.py stage 1, and
until a real window validates compiled execution, only interpret-mode
correctness is claimed (tests/test_pallas_jump.py runs the kernel
interpreted on CPU against the jnp oracle).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: VMEM budget for resident tables (bytes); conservative vs the ~16MB arena
#: to leave room for the lo/hi/out blocks and compiler scratch.
_TABLE_BUDGET = 12 << 20

#: edge-block length per grid step (int32 x 3 blocks = 1.5MB of VMEM)
_BLOCK_E = 1 << 17


def _jump_group_kernel(*refs):
    """Greedy descent through the resident table group (largest stride
    first — tables arrive already ordered deepest-first).

    refs = (table_ref_0, ..., table_ref_{g-1}, lo_ref, hi_ref, out_ref);
    each table is its own 1D ref so every gather is the exact 1D
    ``f_ref[l]`` shape scripts/pallas_probe.py stage 2 validates on the
    backend — a 2D ``tables_ref[i, lo]`` gather is a different lowering
    path Mosaic may not support even where the 1D one works.
    """
    *table_refs, lo_ref, hi_ref, out_ref = refs
    lo = lo_ref[...]
    hi = hi_ref[...]
    for tref in table_refs:  # static unroll: g is compile-time
        nlo = tref[lo]
        lo = jnp.where(nlo < hi, nlo, lo)
    out_ref[...] = lo


def levels_per_call(n: int) -> int:
    """How many ancestor tables fit in the VMEM budget for vertex count n."""
    per_table = 4 * (n + 1)
    return max(0, _TABLE_BUDGET // per_table)


@functools.partial(jax.jit, static_argnames=("interpret",))
def jump_group(tables: tuple, lo: jnp.ndarray, hi: jnp.ndarray,
               interpret: bool = False) -> jnp.ndarray:
    """One fused pass: descend ``lo`` through the table tuple (deepest
    first), keeping lo < hi invariant.  lo/hi int32 [E], E % _BLOCK_E == 0
    is NOT required (the tail block is masked by padding semantics: callers
    pass sentinel-padded arrays whose sentinel never moves)."""
    e = lo.shape[0]
    block = min(_BLOCK_E, e)
    grid = (e + block - 1) // block
    width = tables[0].shape[0]
    return pl.pallas_call(
        _jump_group_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((width,), lambda i: (0,))  # resident tables
                  for _ in tables] + [
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((block,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct(lo.shape, lo.dtype),
        interpret=interpret,
    )(*tables, lo, hi)


def fused_jump(lo: jnp.ndarray, hi: jnp.ndarray, n: int, levels: int,
               interpret: bool = False):
    """Self-contained fused jump (builds its own one-step table); the
    production entry point is :func:`fused_descend`, which takes the table
    from the caller so mesh rounds can pmin-combine it first."""
    sent = jnp.int32(n)
    lo = lo.astype(jnp.int32)
    hi = hi.astype(jnp.int32)
    f = jnp.full(n + 1, sent, jnp.int32).at[lo].min(hi)
    return fused_descend(lo, hi, n, levels, f, interpret=interpret)


def fused_descend(lo: jnp.ndarray, hi: jnp.ndarray, n: int, levels: int,
                  f: jnp.ndarray, interpret: bool = False):
    """Descent through a given one-step table f: build the binary-lifted
    tables (n-sized work, cheap next to E), then descend in VMEM-sized
    groups.  Returns (lo, moved_count) like ops.forest._jump.

    Falls back to the jnp descent when even one table exceeds the VMEM
    budget (n > ~2^21) — callers should consult :func:`levels_per_call`
    first and skip Pallas entirely in that regime.
    """
    lo = lo.astype(jnp.int32)
    hi = hi.astype(jnp.int32)
    lo_in = lo
    tables = [f]
    for _ in range(levels - 1):
        tables.append(tables[-1][tables[-1]])
    g = levels_per_call(n)
    if g == 0:
        for table in reversed(tables):
            nlo = table[lo]
            lo = jnp.where(nlo < hi, nlo, lo)
        return lo, jnp.sum(lo != lo_in, dtype=jnp.int32)
    deepest_first = list(reversed(tables))
    for start in range(0, levels, g):
        group = tuple(deepest_first[start:start + g])
        lo = jump_group(group, lo, hi, interpret=interpret)
    return lo, jnp.sum(lo != lo_in, dtype=jnp.int32)
