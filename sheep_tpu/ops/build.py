"""Fused single-device build step: edges -> (sequence, elimination forest).

This is the whole ``graph2tree`` compute path as one jitted program with
static shapes — the device analog of load+sort+map (SURVEY §3.1): degree
histogram, (degree, vid) sort, edge->link mapping, forest fixpoint, pst
segment-sum.  The mesh-sharded variant lives in sheep_tpu.parallel.
"""

from __future__ import annotations

import functools
import os
import threading
import time

import numpy as np
import jax
import jax.numpy as jnp

from .. import INVALID_JNID
from ..core.forest import Forest
from ..obs import trace as obs
from .forest import forest_fixpoint, pst_weights
from .sort import degree_histogram, degree_order, edge_links


@functools.partial(jax.jit, static_argnames=("n",))
def build_step(tail: jnp.ndarray, head: jnp.ndarray, n: int):
    """Full forward step on edge records (uint32/int32 [E]) over n vid slots.

    Returns (seq, pos, num_active, parent, pst, rounds) — all int32, all
    length n except the scalars.  Positions/parents live in full n-slot
    space; entries for zero-degree vids sit at the tail and are roots with
    pst 0.  ``parent[v] == n`` marks roots.
    """
    deg = degree_histogram(tail, head, n)
    seq, pos, m = degree_order(deg)
    lo, hi = edge_links(tail, head, pos, n)
    parent, rounds = forest_fixpoint(lo, hi, n)
    pst = pst_weights(lo, n)
    return seq, pos, m, parent, pst, rounds


@functools.partial(jax.jit, static_argnames=("n", "with_pst"))
def prepare_links(tail: jnp.ndarray, head: jnp.ndarray, n: int,
                  with_pst: bool = True):
    """Phases before the fixpoint, in one dispatch: degree histogram,
    (degree, vid) sort, edge->link mapping, pst segment-sum.

    Returns (seq, pos, num_active, lo, hi, pst) — pst is computed here
    because the fixpoint rewrites lo in place and pst must count the
    *original* links (jtree.cpp:47-49).  ``with_pst=False`` drops that
    full-E scatter pass (pst is None) for callers that recompute pst on
    the host from their own edge copy (build_graph_hybrid's prefetch) —
    on a backend where every op is priced per element, one pass of E is
    ~1/6 of the whole prep program.
    """
    deg = degree_histogram(tail, head, n)
    seq, pos, m = degree_order(deg)
    lo, hi = edge_links(tail, head, pos, n)
    pst = pst_weights(lo, n) if with_pst else None
    return seq, pos, m, lo, hi, pst


def _finish(seq, m, parent, pst):
    m = int(m)
    seq = _as_u32(np.ascontiguousarray(np.asarray(seq)[:m]))
    # Trimmed to the m active slots; parents of active nodes are active
    # positions (< m), so the converter's n=m sentinel check is exact.
    from .forest import _to_forest
    return seq, _to_forest(np.asarray(parent)[:m], np.asarray(pst)[:m], m)


def build_graph_device(tail: np.ndarray, head: np.ndarray,
                       num_vertices: int | None = None):
    """Host-facing device build: returns (seq uint32 [m], Forest over m).

    Uses the host-orchestrated chunked fixpoint (ops.forest), which is the
    production path on real hardware: bounded per-dispatch execution time
    (no device faults at large n) and live-edge compaction between chunks.
    """
    from .forest import forest_fixpoint_hosted

    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if n == 0:
        return np.empty(0, np.uint32), Forest(
            np.empty(0, np.uint32), np.empty(0, np.uint32))
    seq, _, m, lo, hi, pst = prepare_links(
        jnp.asarray(tail), jnp.asarray(head), n)
    parent, _ = forest_fixpoint_hosted(lo, hi, n)
    return _finish(seq, m, parent, pst)


def _host_seq_pst(tail_np: np.ndarray, head_np: np.ndarray, n: int,
                  seq: np.ndarray | None = None):
    """Host-side (seq, pst) identical to the device's prepare_links outputs.

    Same order (degree asc, vid asc — tested equal across all four build
    implementations) and same pst semantics (one count per non-self-loop
    record at the position of its earlier-in-sequence endpoint, absent
    heads included).  A given ``seq`` replaces the degree sort.  Chunked
    gathers keep the peak at ~3 int32 arrays of one block, not of E.
    """
    from ..core.sequence import degree_sequence, sequence_positions

    seq_h = degree_sequence(tail_np, head_np, n) if seq is None \
        else np.asarray(seq, dtype=np.uint32)
    pos = sequence_positions(seq_h, n - 1)
    pst = np.zeros(n, np.int64)
    block = 1 << 24
    for s in range(0, len(tail_np), block):
        # absent vids carry INVALID (0xFFFFFFFF), which as int64 is >= n
        # for every supported n, so min() picks the present endpoint and
        # the lo < n filter drops both-absent pairs
        pt = pos[tail_np[s:s + block]].astype(np.int64)
        ph = pos[head_np[s:s + block]].astype(np.int64)
        lo = np.minimum(pt, ph)
        live = (pt != ph) & (lo < n)
        pst += np.bincount(lo[live], minlength=n)[:n]
    return seq_h, pst.astype(np.uint32)


def build_graph_hybrid(tail: np.ndarray, head: np.ndarray,
                       num_vertices: int | None = None,
                       handoff_factor: int | None = None,
                       host_edges: tuple[np.ndarray, np.ndarray] | None = None,
                       seq: np.ndarray | None = None,
                       perf: dict | None = None):
    """Flagship heterogeneous build: TPU reduction + native union-find tail.

    The device runs the bandwidth-parallel phases (histogram, degree sort,
    link mapping, pst, and a few reduction rounds that kill the ~90% of
    links that are duplicates or star-collapsible); once at most
    ``handoff_factor * n`` live links remain, they transfer to the host and
    the C++ runtime finishes with the exact sequential union-find
    (sheep_native.cpp), which chases pointers at rates no batched device
    round can match.  Sound because every chunk round preserves threshold
    connectivity, and the elimination forest is a function of threshold
    connectivity only (module docstring of ops.forest).

    The handoff itself is the STREAMING WINDOWED tail by default (round
    7, :func:`stream_handoff_enabled` / SHEEP_STREAM_HANDOFF): the
    reduced live set fetches as W ascending hi-quantile windows
    (SHEEP_HANDOFF_WINDOWS; shared quantile rule with the mesh tail
    shard), each folded through the RESUMABLE native union-find
    (native.LinksFold) the moment it lands — fold k overlaps fetch k+1
    and the full link table never materializes host-side.  On the cpu
    backend the fetch is a zero-copy view, so the stream instead drops
    the pre-fold device sort and (host_seq_mode) moves the degree
    sequence to the native counting sort, shrinking the device program
    to the link mapping.  Any stream failure falls back to the serial
    fetch mid-build.

    Returns (seq uint32 [m], Forest over m), bit-identical to the oracle.

    ``handoff_factor`` tunes how reduced the link set must be before the
    transfer (default 8, env SHEEP_HANDOFF_FACTOR): measured on the
    1-core host, stopping after the first dedupe round (factor 8) beats
    reducing all the way to 2n by 3.3x — the native union-find retires
    links far faster than extra device rounds do.

    ``host_edges`` — the same edge records as host numpy arrays, when the
    caller has them (after any real load phase the graph is resident in
    host RAM whether or not it was also uploaded).  With a host copy, seq
    and pst are recomputed on the host concurrently with the device
    reduction instead of fetched from the device — bit-identical either
    way, but 2n*4B less d2h traffic, which on a tunneled backend
    (~10MB/s, scripts/tunnel_probe.py) is seconds at 2^22+.  Numpy
    tail/head inputs serve as their own host copy automatically.

    ``perf`` — optional dict receiving the reduce+fetch breakdown and
    speculation counters (loop_s / fetch_tail_s / overlap / spec_* —
    see reduce_and_fetch_links), for bench/profile observability.

    ``seq`` — an externally given elimination order (the `-s`/`-r` case):
    skips the device degree histogram + sort entirely (two fewer full-E
    passes plus the E-sized sort), maps links straight through the
    position table, and honors the absent-vid pst contract (edges to
    vids outside the sequence count toward pst, never the tree —
    jtree.cpp:47-49).
    """
    from .forest import parent_from_links

    if handoff_factor is None:
        handoff_factor = default_handoff_factor()
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if seq is not None and len(seq):
        n = max(n, int(np.asarray(seq).max()) + 1)
    if n == 0:
        return np.empty(0, np.uint32), Forest(
            np.empty(0, np.uint32), np.empty(0, np.uint32))
    if host_edges is None \
            and isinstance(tail, np.ndarray) and isinstance(head, np.ndarray) \
            and (jax.devices()[0].platform != "cpu"
                 or (stream_handoff_enabled() and handoff_input_ok())):
        # auto-detect where the host copy buys something real: on
        # accelerators it saves the 2n*4B seq/pst d2h; on the cpu
        # backend it used to be off (the host recompute competed with
        # the reduce loop for the same cores), but under the streaming
        # immediate handoff there IS no reduce loop — the copy instead
        # enables the host-seq prep below
        host_edges = (tail, head)
    given_seq = None
    _lazy_pst = None
    acc_ok = False  # may the tail fold count pst from its own stream?
    if seq is None and host_edges is not None and host_seq_mode() \
            and stream_handoff_enabled() and handoff_input_ok():
        # streaming cpu prep (round 7): the native counting-sort degree
        # sequence (~6x the XLA histogram+sort on the same silicon)
        # computed host-side UP FRONT, so the device program shrinks to
        # the link mapping alone.  Bit-identical: the host sequence
        # equals the device's (degree asc, vid asc — tested across all
        # four build implementations) and given_seq_links encodes the
        # same absent-vid contract the device mapping uses.  Every
        # active vid is in this sequence, so no pst-only link is ever
        # masked out — the streamed multiset stays intact and the fold
        # may count pst itself (acc_ok).
        from ..core.sequence import degree_sequence
        with obs.span("prep.seq", n=n):
            seq = degree_sequence(host_edges[0], host_edges[1], n)
        acc_ok = True
    if seq is not None:
        # `-s` fast path: no histogram, no device sort — links map through
        # the given position table (absent-vid contract lives in
        # ops.sort.given_seq_links, shared with the mesh builders)
        from .sort import given_seq_links
        given_seq = np.asarray(seq, dtype=np.uint32)
        with obs.span("prep.map", n=n):
            lo, hi, pst = given_seq_links(tail, head, given_seq, n,
                                          with_pst=host_edges is None)
        m = len(given_seq)
        dev_seq = None
        if pst is None:
            # pst counts the pre-dead-mask lo, so it can't be recovered
            # from the masked arrays — the rare prefetch-failure fallback
            # just reruns the mapping with the scatter included
            def _lazy_pst():
                return given_seq_links(tail, head, given_seq, n)[2]
    else:
        # with a host edge copy the prefetch thread recomputes pst
        # host-side — skip the device's full-E pst scatter; same when
        # the streaming fold will count pst in its own read pass (the
        # immediate-handoff platforms).  Keep the original lo handle so
        # the rare fallback can still materialize pst on device.
        with obs.span("prep.device", n=n):
            dev_seq, _, m, lo, hi, pst = prepare_links(
                jnp.asarray(tail), jnp.asarray(head), n,
                with_pst=host_edges is None
                and not (stream_handoff_enabled() and handoff_input_ok()))
        # full-graph prep: every vid holds a position, so the link
        # multiset carries no maskable pst-only records — the streaming
        # fold may accumulate pst when the loop skips straight to handoff
        acc_ok = True
        if pst is None:
            orig_lo = lo

            def _lazy_pst():
                # module-level pst_weights, eager: one scatter op through
                # jax's global op cache, no throwaway per-closure jit
                return pst_weights(orig_lo, n)
    # every downstream consumer (prefetch fallback, _finish) reads `seq`:
    # the given host order when supplied, else the device-computed one
    seq = given_seq if given_seq is not None else dev_seq
    # overlap seq/pst with the reduction rounds: with a host edge copy,
    # recompute them on the host (no d2h at all); otherwise stream them
    # down on a second thread — on the tunneled backend d2h runs ~10MB/s
    # (scripts/tunnel_probe.py) and the reduce phase blocks on its own
    # per-chunk round trips, so either way the work hides behind the
    # chunk loop
    import threading
    fetched: dict = {}
    pre = None
    if acc_ok and given_seq is not None:
        # host-seq streaming prep: seq/m are host-known already and pst
        # comes from the tail fold's own read pass — nothing to prefetch
        # (the fallback paths resolve pst through _lazy_pst)
        fetched = {"seq": given_seq, "m": len(given_seq)}
    else:
        def _prefetch():
            try:
                if host_edges is not None:
                    t_np, h_np = host_edges
                    with obs.span("prep.host", n=n):
                        fetched["seq"], fetched["pst"] = _host_seq_pst(
                            t_np, h_np, n, seq=given_seq)
                    # host seq is already trimmed to the m active slots,
                    # so its length replaces the device scalar fetch
                    # (~70ms tunneled)
                    fetched["m"] = len(fetched["seq"])
                else:
                    fetched["seq"] = np.asarray(seq)
                    if pst is not None:
                        fetched["pst"] = np.asarray(pst)
            except Exception:  # fall back to the synchronous fetch below
                fetched.clear()

        pre = threading.Thread(target=_prefetch, daemon=True)
        pre.start()

    def _pst_resolved():
        # host-prefetched pst when the thread landed it; else the device
        # pst — materialized lazily when prepare_links skipped the scatter
        if "pst" in fetched:
            return fetched["pst"]
        return pst if pst is not None else _lazy_pst()

    def _pst_after_fetch():
        # resolved only after the link fetch/stream has begun, so the
        # seq/pst prefetch keeps overlapping it
        if pre is not None:
            pre.join()
        return _as_u32(np.asarray(_pst_resolved()))

    # immediate-handoff only where its trade was measured to win — the
    # shared handoff_input_ok gate (same for the stream's final fold and
    # the profiler, so the sites can't drift).  The tail is the shared
    # production reduce+finish: the streaming windowed handoff (fold of
    # window k overlapping fetch of window k+1) when enabled, the serial
    # fetch + monolithic fold otherwise — bit-identical either way.
    res = reduce_and_finish_native(
        lo, hi, n, stop_live=handoff_factor * n,
        handoff_input=handoff_input_ok(), pst_h=_pst_after_fetch,
        accumulate_pst_ok=acc_ok, perf=perf)
    if res[0] == "device":  # converged before the handoff threshold
        _, a, b, live, rounds = res
        if pre is not None:
            pre.join()
        parent = parent_from_links(a, b, n)
        return _finish(fetched.get("seq", seq), fetched.get("m", m), parent,
                       _pst_resolved())
    _, parent_h, pst_out, live, rounds = res
    m = int(fetched.get("m", m))
    seq_np = _as_u32(np.ascontiguousarray(
        np.asarray(fetched.get("seq", seq))[:m]))
    return seq_np, Forest(parent_h[:m].copy(), pst_out[:m].copy())


def handoff_input_ok() -> bool:
    """THE immediate-handoff gate, shared by every caller (the hybrid,
    the streaming final fold, scripts/hybrid_profile) so the sites can't
    drift: skip the device dedupe rounds only where the d2h copy is free
    (cpu backend) AND the native union-find consumes the undeduped links
    (the pure-python UF pays per link; a byte-bound accelerator fetch
    wants the dedupe rounds to shrink the volume first)."""
    from ..core.forest import native_or_none
    return jax.devices()[0].platform == "cpu" \
        and native_or_none("auto") is not None


def default_handoff_factor() -> int:
    """Platform-tuned handoff threshold (stop_live = factor * n).

    On cpu the "transfer" is free, so hand off as early as possible (8n ~
    after the first dedupe round; measured 3.3x faster than reducing to
    2n).  On a real accelerator the handoff is a device->host copy over
    the link (0.5GB at 2^23 for 8n), so reduce further first.  The
    pure-python fallback pays per link: keep reducing to 2n without the
    native runtime.  Env override: SHEEP_HANDOFF_FACTOR.
    """
    import os

    from ..core.forest import native_or_none
    if native_or_none("auto") is None:
        default = "2"
    else:
        default = "8" if jax.devices()[0].platform == "cpu" else "3"
    return int(os.environ.get("SHEEP_HANDOFF_FACTOR", default))


def pack_handoff(n: int) -> bool:
    """THE 6-byte-packing policy, shared by the serial fetch
    (fetch_links_host) and the overlapped stream (_StreamFetcher) so
    SHEEP_PACK_HANDOFF means ONE thing across both paths (ADVICE r05:
    the stream used to pack on n alone, so a pack-off A/B arm with
    overlap on still packed).  Default: pack where the fetch is
    byte-bound (accelerator tunnel), not on cpu; packing needs n < 2^24.
    """
    pack = os.environ.get("SHEEP_PACK_HANDOFF", "")
    if pack == "":
        pack = "0" if jax.devices()[0].platform == "cpu" else "1"
    return pack == "1" and n < (1 << 24)


def fetch_links_host(lo, hi, live: int, n: int):
    """THE production link-fetch policy, shared with scripts/hybrid_profile
    so the profiler's d2h phase can never drift from what the hybrid
    actually does: 64K-granular cut (each distinct slice length is a fresh
    XLA program; tunneled compiles are slow), 6-byte packing where the
    link is byte-bound (:func:`pack_handoff`), dead-sentinel filter.
    Returns (lo_h, hi_h uint-safe int arrays, packed: bool).
    """
    cut = min(int(lo.shape[0]), -(-live // (1 << 16)) * (1 << 16))
    packed = pack_handoff(n)
    if packed:
        from .forest import pack_links_6b, unpack_links_6b
        buf = np.asarray(pack_links_6b(lo[:cut], hi[:cut]))[:live]
        lo_h, hi_h = unpack_links_6b(buf)
    else:
        lo_h = np.asarray(lo[:cut])[:live]
        hi_h = np.asarray(hi[:cut])[:live]
    keep = lo_h < n  # a few scattered dead slots may remain in the prefix
    return lo_h[keep], hi_h[keep], packed


@functools.partial(jax.jit, static_argnames=("length",))
def _slice_rows(buf, start, length: int):
    """Fixed-length row slice with a DYNAMIC start: one compiled program
    per (buffer shape, length) instead of one per offset — tunneled
    compiles run 30-130s each, so the streamed fetch must reuse a single
    program across all of its slices."""
    return jax.lax.dynamic_slice_in_dim(buf, start, length, 0)


def _overlap_enabled() -> bool:
    """Overlapped speculative handoff gate (SHEEP_OVERLAP_HANDOFF
    overrides): default ON for accelerators — where the link d2h is a
    real transfer worth hiding behind device rounds — and OFF on the cpu
    backend, where the fetch is a near-free copy and the immediate-
    handoff path already skips rounds entirely."""
    v = os.environ.get("SHEEP_OVERLAP_HANDOFF", "")
    if v != "":
        return v == "1"
    return jax.devices()[0].platform != "cpu"


class _StreamFetcher:
    """Background slice-streamed d2h of one link snapshot.

    The snapshot (lo, hi) is an immutable device-array pair with the
    live-prefix guarantee (all live links in the first ``live`` slots),
    so fetching it concurrently with later chunk dispatches is safe.
    Transfers run as fixed-length slices of a 6-byte-packed buffer
    (n < 2^24; int32 pairs otherwise) so progress is observable between
    slices and an abort loses at most one slice of link time.
    """

    def __init__(self, lo, hi, n: int, live: int, slice_links: int,
                 autostart: bool = True):
        self.n = n
        self.live = live
        self.packed = pack_handoff(n)  # ONE policy with fetch_links_host
        self.bytes_per_link = 6 if self.packed else 8
        width = int(lo.shape[0])  # pow2-padded
        # the env knob is an arbitrary int: round DOWN to a power of two
        # (floor 512) so slice_len always divides the pow2 width — a
        # non-dividing slice would silently skip tail links (wrong forest)
        slice_links = 1 << max(9, slice_links.bit_length() - 1)
        self.slice_len = min(slice_links, width)
        self.total_slices = min(-(-live // self.slice_len),
                                width // self.slice_len)
        self.done_slices = 0
        self.failed = False
        #: per-slice fetch seconds (obs.trace.timed — the one timing
        #: path); ``busy_s`` below is the derived view
        self._slice_s: list = []
        self._abort = False
        self._slices: list = []
        # one elementwise pack over the padded width: pow2 shapes only,
        # so the compile family stays bounded
        if self.packed:
            from .forest import pack_links_6b
            self._dev = pack_links_6b(lo, hi)
        else:
            self._dev = (lo.astype(jnp.int32), hi.astype(jnp.int32))
        self._thread = threading.Thread(target=self._run, daemon=True)
        if autostart:
            self._thread.start()

    # subclass seams (the window-queue stream, _WindowStream): gate a
    # slice before its fetch, observe one landing.  Base: free-running.
    def _wait_turn(self, i: int) -> None:
        pass

    def _on_slice(self) -> None:
        pass

    @property
    def busy_s(self) -> float:
        """Thread time actually spent fetching slices (the overlap
        accounting's ``serialized`` fetch term)."""
        return sum(self._slice_s)

    def _run(self) -> None:
        try:
            for i in range(self.total_slices):
                self._wait_turn(i)
                if self._abort:
                    return
                start = i * self.slice_len
                with obs.timed("fetch.slice", out=self._slice_s, slice=i):
                    if self.packed:
                        self._slices.append(
                            np.asarray(_slice_rows(self._dev, start,
                                                   self.slice_len)))
                    else:
                        lo_d, hi_d = self._dev
                        self._slices.append(
                            (np.asarray(_slice_rows(lo_d, start,
                                                    self.slice_len)),
                             np.asarray(_slice_rows(hi_d, start,
                                                    self.slice_len))))
                self.done_slices = i + 1
                self._on_slice()
        except Exception:
            self.failed = True
        finally:
            self._dev = None  # release the device buffer promptly
            self._on_slice()

    def finished(self) -> bool:
        return not self.failed and self.done_slices >= self.total_slices

    def remaining_bytes(self) -> int:
        return (self.total_slices - self.done_slices) * self.slice_len \
            * self.bytes_per_link

    def join(self, timeout: float | None = None,
             mark_failed: bool = True) -> bool:
        """Wait for the stream; True if it is STILL RUNNING afterwards.
        A wedged transfer (sick tunnel mid-slice) must never block the
        build forever — ``mark_failed`` callers treat a timed-out join
        as failed and fall back to the serial fetch, bounded by the
        caller's own budget.  The daemon thread is left behind; slice
        appends are atomic, so a later collect() snapshot stays
        consistent."""
        self._thread.join(timeout)
        alive = self._thread.is_alive()
        if alive and mark_failed:
            self.failed = True
        return alive

    def abort(self, timeout: float = 5.0) -> None:
        """Stop at the next slice boundary; wait only briefly.  A
        slow-but-healthy in-flight slice (queued behind pipelined chunk
        dispatches) must NOT poison the fetcher as failed — the caller
        keeps whatever slices have landed and the thread drains itself
        within one slice; mark_failed=False so only a real _run
        exception disables later speculation."""
        self._abort = True
        self.join(timeout, mark_failed=False)

    def fetched_bytes(self) -> int:
        return self.done_slices * self.slice_len * self.bytes_per_link

    def collect(self) -> tuple[np.ndarray, np.ndarray]:
        """Host (lo, hi) of every fetched slice (unfiltered — dead
        sentinel slots remain; callers mask lo < n)."""
        if not self._slices:
            return (np.empty(0, np.int32), np.empty(0, np.int32))
        if self.packed:
            from .forest import unpack_links_6b
            return unpack_links_6b(np.concatenate(self._slices))
        los, his = zip(*self._slices)
        return np.concatenate(los), np.concatenate(his)


class _WindowStream(_StreamFetcher):
    """Window-queue generalization of the snapshot stream (the streaming
    windowed handoff's transfer side): a hi-SORTED device link table
    streams as fixed-length slices grouped into W equal-count windows —
    contiguous count-slices of the sorted table ARE the hi-quantile
    windows (parallel.chunked.hi_window_bounds rule) — and the fetch
    thread runs at most :data:`PREFETCH` windows ahead of the fold
    consumer.  Resident host memory is therefore O(live/W * PREFETCH),
    never the full table; :meth:`window` hands window k to the fold and
    frees its slices while k+1 keeps streaming underneath.
    """

    #: windows allowed in flight beyond the one being folded (double
    #: buffering: fold k while k+1 lands and k+2 streams)
    PREFETCH = 2

    def __init__(self, lo, hi, n: int, live: int, slice_links: int,
                 windows: int):
        super().__init__(lo, hi, n, live, slice_links, autostart=False)
        self._cv = threading.Condition()
        self._consumed = -1  # highest window already handed to the fold
        w = max(1, min(windows, self.total_slices))
        self.windows = w
        self._cuts = [(k * self.total_slices) // w for k in range(w + 1)]
        self._thread.start()

    def _window_of(self, i: int) -> int:
        import bisect
        return bisect.bisect_right(self._cuts, i) - 1

    def _wait_turn(self, i: int) -> None:
        with self._cv:
            while (not self._abort
                   and self._window_of(i)
                   > self._consumed + 1 + self.PREFETCH):
                self._cv.wait(0.5)

    def _on_slice(self) -> None:
        with self._cv:
            self._cv.notify_all()

    def window(self, k: int, timeout_s: float | None = None):
        """Block until window k has fully landed, then return its host
        (lo, hi) int arrays (unfiltered — callers mask lo < n) and free
        the backing slices.  Raises RuntimeError on a failed or wedged
        stream (the caller falls back to the serial fetch)."""
        lo_w, hi_w = self.collect_range(self._cuts[k], self._cuts[k + 1],
                                        timeout_s)
        with self._cv:
            self._consumed = max(self._consumed, k)
            self._cv.notify_all()
        return lo_w, hi_w

    def collect_range(self, s0: int, s1: int,
                      timeout_s: float | None = None):
        if timeout_s is None:
            # generous watchdog, same spirit as _SpecHandoff.complete: a
            # wedged transfer must never hold the build forever
            timeout_s = ((s1 - s0) * self.slice_len * self.bytes_per_link
                         / 5e5 + 120.0)
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self.done_slices < s1 and not self.failed:
                left = deadline - time.monotonic()
                if left <= 0:
                    self.failed = True
                    break
                self._cv.wait(min(left, 0.5))
        if self.failed:
            raise RuntimeError("window stream failed or timed out")
        part = self._slices[s0:s1]
        for i in range(s0, s1):  # bound resident memory to the window
            self._slices[i] = None
        if not part:
            return np.empty(0, np.int32), np.empty(0, np.int32)
        if self.packed:
            from .forest import unpack_links_6b
            return unpack_links_6b(np.concatenate(part))
        los, his = zip(*part)
        return np.concatenate(los), np.concatenate(his)

    def abort(self, timeout: float = 5.0) -> None:
        self._abort = True
        with self._cv:
            self._cv.notify_all()
        self.join(timeout, mark_failed=False)


def stream_handoff_enabled() -> bool:
    """THE streaming-windowed-handoff gate (SHEEP_STREAM_HANDOFF
    overrides; default on): the hybrid's tail consumes the reduced live
    set as ascending hi-quantile windows, each folded through the
    resumable native union-find (native.LinksFold — python twin without
    the runtime) the moment it lands, so the fold of window k overlaps
    the fetch of window k+1 and the full tail link table never
    materializes host-side.  Any stream failure falls back to the serial
    fetch mid-build, exactly like _SpecHandoff's failure path."""
    v = os.environ.get("SHEEP_STREAM_HANDOFF", "")
    if v != "":
        return v == "1"
    # an EXPLICIT legacy-overlap arm (SHEEP_OVERLAP_HANDOFF=1) keeps the
    # speculative-snapshot path unless the stream is explicitly chosen
    # too, so the round-4/5 A/B arms keep measuring what they name
    if os.environ.get("SHEEP_OVERLAP_HANDOFF", "") == "1":
        return False
    return True


def handoff_windows(live: int) -> int:
    """Window-count policy (SHEEP_HANDOFF_WINDOWS overrides).  On the
    cpu backend the device->host fetch is a zero-copy view — there is
    nothing to overlap, and the blocked kernel's internal quantile
    bucketing already IS the windowing — so ONE window is optimal.  On a
    real accelerator the fetch is a genuine transfer: 4 windows keep the
    fold busy behind the stream while each window stays large enough to
    amortize its slice dispatches; tiny handoffs stay monolithic."""
    v = os.environ.get("SHEEP_HANDOFF_WINDOWS", "")
    if v != "":
        return max(1, int(v))
    if jax.devices()[0].platform == "cpu":
        return 1
    return 4 if live >= (1 << 20) else 1


def host_seq_mode() -> bool:
    """Host-computed degree sequence for the streaming hybrid
    (SHEEP_STREAM_HOST_SEQ overrides).  Where device and host share the
    silicon (cpu backend), the native counting-sort sequence is ~6x the
    XLA histogram+sort and the device program shrinks to the link
    mapping alone — measured the difference between a ~7.6s and a ~3.5s
    hybrid at 2^22 on the 1-core bench host.  On a real accelerator the
    device sort is cheap and a host sequence would serialize in front of
    the mapping, so default off there."""
    v = os.environ.get("SHEEP_STREAM_HOST_SEQ", "")
    if v != "":
        return v == "1"
    return jax.devices()[0].platform == "cpu"


def _as_u32(a: np.ndarray) -> np.ndarray:
    """uint32 without a copy where possible: contiguous int32 (the fetch
    dtype) reinterprets for free — exact under the package-wide
    nonnegative-int32 value contract — instead of the unconditional
    .astype() that used to copy multi-hundred-MB link arrays through the
    handoff path."""
    a = np.asarray(a)
    if a.dtype == np.uint32:
        return a
    if a.dtype == np.int32 and a.flags["C_CONTIGUOUS"]:
        return a.view(np.uint32)
    return a.astype(np.uint32, copy=False)


def _stream_tail(lo, hi, live: int, n: int, pst_h, accumulate: bool,
                 perf: dict | None):
    """The streaming windowed handoff tail: fetch the reduced live set
    as W ascending hi-quantile windows and fold each straight into the
    resumable union-find.  Returns (parent, pst) uint32 [n], or None on
    ANY failure — the caller falls back to the serial fetch (the device
    arrays are still alive), exactly like _SpecHandoff degrades.

    ``accumulate`` True means the windows together carry the ORIGINAL
    link multiset (immediate handoff, zero reduce rounds) and pst is
    counted inside the fold's own read pass — the device/host pst
    resolver ``pst_h`` is then never touched.  False: ``pst_h`` (array
    or zero-arg callable) resolves AFTER the stream has started, so a
    caller's pst prefetch keeps overlapping the first window's fetch.
    """
    from ..core.forest import host_hi_window_bounds, links_fold

    t_start = time.perf_counter()
    w = handoff_windows(int(live))
    platform = jax.devices()[0].platform
    # SHEEP_STREAM_DEVICE_WINDOWS=1 forces the accelerator transfer path
    # (device hi-sort + _WindowStream slices) on the cpu backend — the
    # same trick the overlap tests use, so the window-queue machinery is
    # exercised without hardware
    device_windows = platform != "cpu" \
        or os.environ.get("SHEEP_STREAM_DEVICE_WINDOWS", "") == "1"
    stream = None
    fetch_s: list[float] = []
    fold_s: list[float] = []
    links_folded = 0
    try:
        if device_windows:
            # device-side windowing: ONE hi-sort program, then windows
            # are contiguous equal-count slices streamed double-buffered
            slo, shi = _sort_by_hi_prog(lo, hi)
            slice_links = int(os.environ.get("SHEEP_OVERLAP_SLICE",
                                             str(1 << 18)))
            stream = _WindowStream(slo, shi, n, int(live), slice_links, w)
            w = stream.windows

            def windows_iter():
                for k in range(w):
                    yield stream.window(k)
        else:
            # cpu backend: the "fetch" is a zero-copy view (it blocks on
            # the async device program — that wait IS the old fetch_tail
            # wall); windows split host-side by the shared quantile rule
            def windows_iter():
                lo_h = np.asarray(lo)[:int(live)]
                hi_h = np.asarray(hi)[:int(live)]
                keep = lo_h < n
                if w == 1:
                    yield lo_h[keep], hi_h[keep]
                    return
                lo_k = lo_h[keep]
                hi_k = hi_h[keep]
                bounds = host_hi_window_bounds(hi_k[hi_k < n], w, n)
                for k in range(w):
                    sel = hi_k >= bounds[k]
                    if k + 1 < w:  # last window keeps any pst-only tail
                        sel &= hi_k < bounds[k + 1]
                    yield lo_k[sel], hi_k[sel]

        it = windows_iter()
        pst_arr = None
        if not accumulate:
            pst_arr = _as_u32(pst_h() if callable(pst_h) else pst_h)
        fold = links_fold(n, pst_arr)
        # one accumulation path for the fetch/fold pairs (obs.trace.timed
        # — spans when SHEEP_TRACE is on, the same measured series either
        # way); the perf keys below are derived views of these lists
        for k in range(w):
            with obs.timed("handoff.fetch", out=fetch_s, window=k):
                wlo, whi = next(it)
                keep = wlo < n
                if not keep.all():
                    wlo, whi = wlo[keep], whi[keep]
            with obs.timed("handoff.fold", out=fold_s, window=k,
                           links=len(wlo)):
                fold.block(_as_u32(wlo), _as_u32(whi))
            links_folded += len(wlo)
        parent, pst_out = fold.finish()
    except Exception as exc:
        if stream is not None:
            stream.abort()
        if perf is not None:
            perf["stream_mode"] = f"fallback:{type(exc).__name__}"
        return None
    if perf is not None:
        wall = time.perf_counter() - t_start
        fetch_busy = stream.busy_s if stream is not None else sum(fetch_s)
        from ..core.forest import native_or_none
        native = native_or_none("auto")
        perf.update({
            "stream_mode": "windowed",
            "fetch_windows": w,
            "window_fetch_s": [round(x, 4) for x in fetch_s],
            "window_fold_s": [round(x, 4) for x in fold_s],
            "fold_s": round(sum(fold_s), 4),
            # THE shared overlap accounting (obs.trace.overlap_stats)
            **obs.overlap_stats(fetch_busy + sum(fold_s), wall),
            "handoff_links": links_folded,
            "packed_handoff": stream.packed if stream is not None
            else False,
            # worker threads under the fold (round 14): >1 means the
            # windows folded on real parallel cores while the fetch ran
            # ahead — the knob that makes the overlap real off 1 core
            "native_threads": native.resolve_threads()
            if native is not None else 1,
        })
    return parent, pst_out


@functools.partial(jax.jit)
def _sort_by_hi_prog(lo, hi):
    """Cached program wrapper of ops.forest.sort_links_by_hi (one compile
    per table shape — tunneled compiles are slow)."""
    from .forest import sort_links_by_hi
    return sort_links_by_hi(lo, hi)


class _SpecHandoff:
    """Speculative overlapped handoff policy (VERDICT r04 item 1).

    Soundness: every chunk output has the same threshold connectivity as
    the input links (ops.forest module proof), the elimination forest is
    a function of threshold connectivity only, and the native union-find
    accepts an arbitrary-order multiset — so ANY complete snapshot hands
    off exactly, and a UNION of (partial or complete) snapshots does too
    (connectivity of a union of same-connectivity sets is unchanged).
    That makes speculation free of correctness risk: partial buffers from
    abandoned fetches are simply kept and fed to the union-find alongside
    one complete snapshot; the only cost of a wrong guess is bytes.

    Policy: once live <= SHEEP_OVERLAP_SPEC_FACTOR * n (default 8) and
    the snapshot is at least SHEEP_OVERLAP_MIN_MB (default 4), start
    streaming it while the chunk loop keeps reducing.  At each later
    chunk: if the stream finished, stop the loop (the handoff set is
    already on the host — remaining device rounds would be pure waste);
    if the bytes still in flight exceed 1.25x a fresh fetch of the
    now-smaller snapshot, abandon (keeping the partial) and restart on
    the smaller one.  At loop end, either wait out the stream (when its
    remainder is cheaper than a fresh final fetch) or abandon and fetch
    the final set directly.  On a fast link the stream wins early and
    skips device rounds; on a slow link the rule degrades to today's
    serial fetch, minus nothing.
    """

    MARGIN = 1.25

    def __init__(self, n: int):
        self.n = n
        self.bpl = 6 if pack_handoff(n) else 8
        self.spec_live = int(os.environ.get(
            "SHEEP_OVERLAP_SPEC_FACTOR", "8")) * n
        self.slice_links = int(os.environ.get(
            "SHEEP_OVERLAP_SLICE", str(1 << 18)))
        self.min_bytes = int(float(os.environ.get(
            "SHEEP_OVERLAP_MIN_MB", "4")) * (1 << 20))
        self.active: _StreamFetcher | None = None
        self.kept: list[tuple[np.ndarray, np.ndarray]] = []
        self.dead = False  # a failed fetch disables further speculation
        self.stats: dict = {"overlap": True, "spec_starts": 0,
                            "spec_restarts": 0, "spec_wasted_mb": 0.0,
                            "spec_stopped_loop": False,
                            "spec_mode": "never_started"}

    @staticmethod
    def maybe(n: int) -> "_SpecHandoff | None":
        from ..core.forest import native_or_none
        if not _overlap_enabled() or native_or_none("auto") is None:
            return None
        return _SpecHandoff(n)

    def _start(self, lo, hi, live: int) -> None:
        try:
            self.active = _StreamFetcher(lo, hi, self.n, live,
                                         self.slice_links)
            self.stats["spec_starts"] += 1
            self.stats.setdefault("spec_start_live", live)
        except Exception:
            self.active = None
            self.dead = True

    def _abandon(self) -> None:
        f = self.active
        self.active = None
        if f is None:
            return
        f.abort()
        self.stats["spec_wasted_mb"] = round(
            self.stats["spec_wasted_mb"] + f.fetched_bytes() / (1 << 20), 2)
        if not f.failed and f.done_slices:
            self.kept.append(f.collect())
        if f.failed:
            self.dead = True

    def on_chunk(self, lo, hi, live) -> bool:
        """reduce_links_hosted ``watch`` hook: True stops the loop."""
        live = int(live)
        if self.dead:
            return False
        if self.active is not None:
            if self.active.failed:
                self._abandon()
                return False
            if self.active.finished():
                self.stats["spec_stopped_loop"] = True
                return True
            if self.active.remaining_bytes() > \
                    live * self.bpl * self.MARGIN:
                self.stats["spec_restarts"] += 1
                self._abandon()
                # restarts honor the same min_bytes floor as first
                # starts (ADVICE r05): a late-loop restart on a tiny
                # snapshot pays a pack dispatch and possibly a fresh
                # slice-program compile (30-130s tunneled) to save a
                # fetch that costs less than either
                if not self.dead and live * self.bpl >= self.min_bytes:
                    self._start(lo, hi, live)
            return False
        if live <= self.spec_live and live * self.bpl >= self.min_bytes:
            self._start(lo, hi, live)
        return False

    def abort_all(self) -> None:
        """Converged without a handoff: nothing to collect."""
        if self.active is not None:
            self.active.abort()
            self.active = None
        self.kept = []

    def complete(self, lo, hi, live: int) -> tuple[np.ndarray, np.ndarray]:
        """Produce the host handoff link set at loop end: one complete
        snapshot (streamed or freshly fetched) plus any kept partials."""
        live = int(live)
        mode = "plain"
        lo_h = hi_h = None
        f = self.active
        if f is not None and not f.failed:
            if f.finished():
                mode = "spec_complete"
            elif f.remaining_bytes() <= live * self.bpl:
                mode = "spec_wait"
                # generous watchdog: remaining bytes at a worst-observed
                # 0.5MB/s tunnel trough plus grace; a wedged stream must
                # not hold the build (falls back to the serial fetch)
                f.join(timeout=f.remaining_bytes() / 5e5 + 120.0)
            else:
                self._abandon()
                f = None
                mode = "restart_final"
            if f is not None and not f.failed:
                lo_h, hi_h = f.collect()
                self.active = None
        if lo_h is None:
            # never started / failed / abandoned-at-end: fetch the final
            # reduced set the serial way (production fetch policy)
            lo_h, hi_h, _ = fetch_links_host(lo, hi, live, self.n)
            if mode == "spec_wait":
                # the watchdog fired mid-wait: record it honestly (the
                # A/B decision reader must distinguish a wedged stream
                # from one that never started) and count its bytes
                mode = "spec_wait_timeout"
                if f is not None:
                    self.stats["spec_wasted_mb"] = round(
                        self.stats["spec_wasted_mb"]
                        + f.fetched_bytes() / (1 << 20), 2)
            elif mode not in ("restart_final",):
                mode = "plain"
        if self.kept:
            klo, khi = zip(*self.kept)
            lo_h = np.concatenate([lo_h, *klo])
            hi_h = np.concatenate([hi_h, *khi])
            self.kept = []
        keep = lo_h < self.n
        self.stats["spec_mode"] = mode
        return np.ascontiguousarray(lo_h[keep]), \
            np.ascontiguousarray(hi_h[keep])


def reduce_and_fetch_links(lo, hi, n: int, stop_live: int,
                           handoff_input: bool = False, perf=None):
    """THE production reduce+handoff middle of the hybrid, shared with
    scripts/hybrid_profile so the profiler can never drift from what the
    hybrid ships: chunk rounds to ``stop_live`` with the speculative
    overlapped fetch on accelerators (:class:`_SpecHandoff`; serial
    fetch elsewhere).

    Returns (kind, a, b, live, rounds) where kind is "device" (converged
    before the threshold: a/b are device link arrays for
    parent_from_links) or "host" (a/b are host int arrays of the fetched
    handoff links, already lo<n-filtered).  ``perf``, when a dict, gains
    loop_s / fetch_tail_s (the serialized equivalents of the old
    profiler's reduce / d2h phases) and the speculation counters.
    """
    from .forest import reduce_links_hosted

    spec = _SpecHandoff.maybe(n)
    t0 = time.perf_counter()
    with obs.span("reduce.loop", stop_live=stop_live):
        lo, hi, live, rounds, converged = reduce_links_hosted(
            lo, hi, n, stop_live=stop_live, handoff_input=handoff_input,
            watch=spec.on_chunk if spec is not None else None)
    t1 = time.perf_counter()
    if converged:
        if spec is not None:
            spec.abort_all()
        if perf is not None:
            perf["loop_s"] = round(t1 - t0, 4)
            perf["fetch_tail_s"] = 0.0
            if spec is not None:
                perf.update(spec.stats)
        return "device", lo, hi, int(live), rounds
    with obs.span("handoff.fetch", live=int(live),
                  spec=spec is not None):
        if spec is not None:
            lo_h, hi_h = spec.complete(lo, hi, int(live))
        else:
            lo_h, hi_h, _ = fetch_links_host(lo, hi, int(live), n)
    if perf is not None:
        perf["loop_s"] = round(t1 - t0, 4)
        perf["fetch_tail_s"] = round(time.perf_counter() - t1, 4)
        # the ACTUAL handed-off link count (ADVICE r05): with
        # speculation, a/b can be a strictly larger early snapshot plus
        # kept partials, so `live` alone misreads the handoff volume
        perf["handoff_links"] = int(len(lo_h))
        perf["packed_handoff"] = pack_handoff(n)
        if spec is not None:
            perf.update(spec.stats)
    return "host", lo_h, hi_h, int(live), rounds


def reduce_and_finish_native(lo, hi, n: int, stop_live: int,
                             handoff_input: bool = False, pst_h=None,
                             accumulate_pst_ok: bool = False, perf=None):
    """THE production reduce + handoff + native-tail of the hybrid,
    shared with ops.stream's final fold and scripts/hybrid_profile so
    none of them can drift from what the hybrid ships.

    With the streaming windowed handoff enabled (the default —
    :func:`stream_handoff_enabled`) the tail is :func:`_stream_tail`:
    W ascending hi-quantile windows, each folded through the resumable
    native union-find the moment it lands, fold k overlapping fetch k+1,
    the full link table never host-resident; any stream failure falls
    back to the serial fetch of the still-alive device arrays.  Disabled,
    the tail is the legacy serial path (reduce_and_fetch_links +
    finish_native_host) including the speculative overlapped snapshot on
    accelerators.

    Returns ("device", lo, hi, live, rounds) when the reduce loop
    converged before the handoff threshold (the links already form the
    forest — no native tail ran), else ("forest", parent, pst, live,
    rounds) with parent/pst uint32 [n].

    ``pst_h`` — array or zero-arg callable resolving the prep-time pst;
    consulted only when the fold cannot count pst itself.
    ``accumulate_pst_ok`` — the caller vouches the INPUT links are the
    original multiset with no pst-only record masked out (full-graph
    prep, or an internally derived full-coverage sequence); the fold
    then accumulates pst in its own read pass whenever the loop took the
    immediate-handoff exit (zero rounds — any chunk round rewrites the
    multiset, after which only the prep-time pst is right).

    ``perf`` gains loop_s and fetch_tail_s — fetch_tail_s is now the
    whole tail wall (fetch + fold minus their overlap) — plus the
    per-window breakdown (fetch_windows, window_fetch_s / window_fold_s,
    overlap_s / overlap_frac, stream_mode) and handoff_links.
    """
    from .forest import reduce_links_hosted

    if not stream_handoff_enabled():
        kind, a, b, live, rounds = reduce_and_fetch_links(
            lo, hi, n, stop_live=stop_live, handoff_input=handoff_input,
            perf=perf)
        if kind == "device":
            return "device", a, b, live, rounds
        t0 = time.perf_counter()
        with obs.span("handoff.fold", links=len(a)):
            parent, pst = finish_native_host(a, b, n, pst_h)
        if perf is not None:
            # serial tail accounting mirrors the streamed one: the fold
            # is part of the handoff bill either way
            perf["fold_s"] = round(time.perf_counter() - t0, 4)
            perf["fetch_tail_s"] = round(
                perf.get("fetch_tail_s", 0.0) + perf["fold_s"], 4)
            perf["fetch_windows"] = 0
        return "forest", parent, pst, live, rounds
    t0 = time.perf_counter()
    # handoff_sort=False: the streaming tail feeds the cache-blocked
    # kernel (raw order reads faster than the sort costs) or sorts by hi
    # itself for the window slices — either way _sorted_once is waste
    with obs.span("reduce.loop", stop_live=stop_live):
        lo, hi, live, rounds, converged = reduce_links_hosted(
            lo, hi, n, stop_live=stop_live, handoff_input=handoff_input,
            handoff_sort=False)
    t1 = time.perf_counter()
    if perf is not None:
        perf["loop_s"] = round(t1 - t0, 4)
        perf["overlap"] = False  # the spec-snapshot stream is superseded
    if converged:
        if perf is not None:
            perf["fetch_tail_s"] = 0.0
        return "device", lo, hi, int(live), rounds
    accumulate = accumulate_pst_ok and rounds == 0
    out = _stream_tail(lo, hi, int(live), n, pst_h, accumulate, perf)
    if out is None:
        # stream failed: serial fetch of the SAME device arrays (still
        # alive) + monolithic fold — bit-identical, just unoverlapped.
        # ``accumulate`` holds for the serial fold too (same multiset),
        # so pst_in=None lets the kernel count pst exactly as planned.
        with obs.span("handoff.fetch", live=int(live), fallback=True):
            lo_h, hi_h, packed = fetch_links_host(lo, hi, int(live), n)
        if perf is not None:
            perf["handoff_links"] = int(len(lo_h))
            perf["packed_handoff"] = packed
        with obs.span("handoff.fold", links=len(lo_h), fallback=True):
            out = finish_native_host(lo_h, hi_h, n,
                                     None if accumulate else pst_h)
    parent, pst = out
    if perf is not None:
        perf["fetch_tail_s"] = round(time.perf_counter() - t1, 4)
    return "forest", parent, pst, int(live), rounds


def finish_native_host(lo_h: np.ndarray, hi_h: np.ndarray, n: int, pst_h):
    """Exact union-find tail on HOST link arrays: returns (parent, pst)
    uint32 [n].  pst_h may be a zero-arg callable resolved here — after
    the link fetch — so a caller's prefetch thread keeps overlapping it.
    Dtype conversion goes through the no-copy reinterpret (_as_u32): the
    old unconditional .astype(np.uint32) duplicated multi-hundred-MB
    arrays that were already uint32-exact int32."""
    if callable(pst_h):
        pst_h = pst_h()
    from ..core.forest import native_or_none
    native = native_or_none("auto")
    if native is not None:
        return native.build_forest_links(
            _as_u32(lo_h), _as_u32(hi_h), n, pst_h)
    from ..core.forest import build_forest_links
    forest = build_forest_links(np.asarray(lo_h, dtype=np.int64),
                                np.asarray(hi_h, dtype=np.int64), n,
                                pst=pst_h, impl="python")
    return forest.parent, forest.pst_weight


def handoff_finish_native(lo, hi, live: int, n: int, pst_h):
    """Fetch a reduced link set and finish with the exact sequential
    union-find (the hybrid tail): returns (parent, pst) uint32 [n].

    lo/hi: device int32 arrays whose first ``live`` slots contain the live
    links (plus possibly a few dead sentinels — filtered here); pst_h: the
    accumulated pst counts, host-side — an array, or a zero-arg callable
    resolved only after the link fetch (lets a caller's prefetch thread
    overlap that fetch).  The fetch is 64K-granular (each distinct slice
    length is a fresh XLA program; tunneled compiles are slow) and
    6-byte-packed where the link is byte-bound (SHEEP_PACK_HANDOFF
    overrides; needs n < 2^24).
    """
    lo_h, hi_h, _ = fetch_links_host(lo, hi, live, n)
    return finish_native_host(lo_h, hi_h, n, pst_h)
