"""Fused single-device build step: edges -> (sequence, elimination forest).

This is the whole ``graph2tree`` compute path as one jitted program with
static shapes — the device analog of load+sort+map (SURVEY §3.1): degree
histogram, (degree, vid) sort, edge->link mapping, forest fixpoint, pst
segment-sum.  The mesh-sharded variant lives in sheep_tpu.parallel.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .. import INVALID_JNID
from ..core.forest import Forest
from .forest import forest_fixpoint, pst_weights
from .sort import degree_histogram, degree_order, edge_links


@functools.partial(jax.jit, static_argnames=("n",))
def build_step(tail: jnp.ndarray, head: jnp.ndarray, n: int):
    """Full forward step on edge records (uint32/int32 [E]) over n vid slots.

    Returns (seq, pos, num_active, parent, pst, rounds) — all int32, all
    length n except the scalars.  Positions/parents live in full n-slot
    space; entries for zero-degree vids sit at the tail and are roots with
    pst 0.  ``parent[v] == n`` marks roots.
    """
    deg = degree_histogram(tail, head, n)
    seq, pos, m = degree_order(deg)
    lo, hi = edge_links(tail, head, pos, n)
    parent, rounds = forest_fixpoint(lo, hi, n)
    pst = pst_weights(lo, n)
    return seq, pos, m, parent, pst, rounds


def build_graph_device(tail: np.ndarray, head: np.ndarray,
                       num_vertices: int | None = None):
    """Host-facing fused build: returns (seq uint32 [m], Forest over m)."""
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if n == 0:
        return np.empty(0, np.uint32), Forest(
            np.empty(0, np.uint32), np.empty(0, np.uint32))
    seq, _, m, parent, pst, _ = build_step(
        jnp.asarray(tail), jnp.asarray(head), n)
    m = int(m)
    seq = np.asarray(seq)[:m].astype(np.uint32)
    # Trimmed to the m active slots; parents of active nodes are active
    # positions (< m), so the converter's n=m sentinel check is exact.
    from .forest import _to_forest
    return seq, _to_forest(np.asarray(parent)[:m], np.asarray(pst)[:m], m)
