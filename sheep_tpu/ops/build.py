"""Fused single-device build step: edges -> (sequence, elimination forest).

This is the whole ``graph2tree`` compute path as one jitted program with
static shapes — the device analog of load+sort+map (SURVEY §3.1): degree
histogram, (degree, vid) sort, edge->link mapping, forest fixpoint, pst
segment-sum.  The mesh-sharded variant lives in sheep_tpu.parallel.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from .. import INVALID_JNID
from ..core.forest import Forest
from .forest import forest_fixpoint, pst_weights
from .sort import degree_histogram, degree_order, edge_links


@functools.partial(jax.jit, static_argnames=("n",))
def build_step(tail: jnp.ndarray, head: jnp.ndarray, n: int):
    """Full forward step on edge records (uint32/int32 [E]) over n vid slots.

    Returns (seq, pos, num_active, parent, pst, rounds) — all int32, all
    length n except the scalars.  Positions/parents live in full n-slot
    space; entries for zero-degree vids sit at the tail and are roots with
    pst 0.  ``parent[v] == n`` marks roots.
    """
    deg = degree_histogram(tail, head, n)
    seq, pos, m = degree_order(deg)
    lo, hi = edge_links(tail, head, pos, n)
    parent, rounds = forest_fixpoint(lo, hi, n)
    pst = pst_weights(lo, n)
    return seq, pos, m, parent, pst, rounds


@functools.partial(jax.jit, static_argnames=("n", "with_pst"))
def prepare_links(tail: jnp.ndarray, head: jnp.ndarray, n: int,
                  with_pst: bool = True):
    """Phases before the fixpoint, in one dispatch: degree histogram,
    (degree, vid) sort, edge->link mapping, pst segment-sum.

    Returns (seq, pos, num_active, lo, hi, pst) — pst is computed here
    because the fixpoint rewrites lo in place and pst must count the
    *original* links (jtree.cpp:47-49).  ``with_pst=False`` drops that
    full-E scatter pass (pst is None) for callers that recompute pst on
    the host from their own edge copy (build_graph_hybrid's prefetch) —
    on a backend where every op is priced per element, one pass of E is
    ~1/6 of the whole prep program.
    """
    deg = degree_histogram(tail, head, n)
    seq, pos, m = degree_order(deg)
    lo, hi = edge_links(tail, head, pos, n)
    pst = pst_weights(lo, n) if with_pst else None
    return seq, pos, m, lo, hi, pst


def _finish(seq, m, parent, pst):
    m = int(m)
    seq = np.asarray(seq)[:m].astype(np.uint32)
    # Trimmed to the m active slots; parents of active nodes are active
    # positions (< m), so the converter's n=m sentinel check is exact.
    from .forest import _to_forest
    return seq, _to_forest(np.asarray(parent)[:m], np.asarray(pst)[:m], m)


def build_graph_device(tail: np.ndarray, head: np.ndarray,
                       num_vertices: int | None = None):
    """Host-facing device build: returns (seq uint32 [m], Forest over m).

    Uses the host-orchestrated chunked fixpoint (ops.forest), which is the
    production path on real hardware: bounded per-dispatch execution time
    (no device faults at large n) and live-edge compaction between chunks.
    """
    from .forest import forest_fixpoint_hosted

    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if n == 0:
        return np.empty(0, np.uint32), Forest(
            np.empty(0, np.uint32), np.empty(0, np.uint32))
    seq, _, m, lo, hi, pst = prepare_links(
        jnp.asarray(tail), jnp.asarray(head), n)
    parent, _ = forest_fixpoint_hosted(lo, hi, n)
    return _finish(seq, m, parent, pst)


def _host_seq_pst(tail_np: np.ndarray, head_np: np.ndarray, n: int,
                  seq: np.ndarray | None = None):
    """Host-side (seq, pst) identical to the device's prepare_links outputs.

    Same order (degree asc, vid asc — tested equal across all four build
    implementations) and same pst semantics (one count per non-self-loop
    record at the position of its earlier-in-sequence endpoint, absent
    heads included).  A given ``seq`` replaces the degree sort.  Chunked
    gathers keep the peak at ~3 int32 arrays of one block, not of E.
    """
    from ..core.sequence import degree_sequence, sequence_positions

    seq_h = degree_sequence(tail_np, head_np, n) if seq is None \
        else np.asarray(seq, dtype=np.uint32)
    pos = sequence_positions(seq_h, n - 1)
    pst = np.zeros(n, np.int64)
    block = 1 << 24
    for s in range(0, len(tail_np), block):
        # absent vids carry INVALID (0xFFFFFFFF), which as int64 is >= n
        # for every supported n, so min() picks the present endpoint and
        # the lo < n filter drops both-absent pairs
        pt = pos[tail_np[s:s + block]].astype(np.int64)
        ph = pos[head_np[s:s + block]].astype(np.int64)
        lo = np.minimum(pt, ph)
        live = (pt != ph) & (lo < n)
        pst += np.bincount(lo[live], minlength=n)[:n]
    return seq_h, pst.astype(np.uint32)


def build_graph_hybrid(tail: np.ndarray, head: np.ndarray,
                       num_vertices: int | None = None,
                       handoff_factor: int | None = None,
                       host_edges: tuple[np.ndarray, np.ndarray] | None = None,
                       seq: np.ndarray | None = None):
    """Flagship heterogeneous build: TPU reduction + native union-find tail.

    The device runs the bandwidth-parallel phases (histogram, degree sort,
    link mapping, pst, and a few reduction rounds that kill the ~90% of
    links that are duplicates or star-collapsible); once at most
    ``handoff_factor * n`` live links remain, they transfer to the host and
    the C++ runtime finishes with the exact sequential union-find
    (sheep_native.cpp), which chases pointers at rates no batched device
    round can match.  Sound because every chunk round preserves threshold
    connectivity, and the elimination forest is a function of threshold
    connectivity only (module docstring of ops.forest).

    Returns (seq uint32 [m], Forest over m), bit-identical to the oracle.

    ``handoff_factor`` tunes how reduced the link set must be before the
    transfer (default 8, env SHEEP_HANDOFF_FACTOR): measured on the
    1-core host, stopping after the first dedupe round (factor 8) beats
    reducing all the way to 2n by 3.3x — the native union-find retires
    links far faster than extra device rounds do.

    ``host_edges`` — the same edge records as host numpy arrays, when the
    caller has them (after any real load phase the graph is resident in
    host RAM whether or not it was also uploaded).  With a host copy, seq
    and pst are recomputed on the host concurrently with the device
    reduction instead of fetched from the device — bit-identical either
    way, but 2n*4B less d2h traffic, which on a tunneled backend
    (~10MB/s, scripts/tunnel_probe.py) is seconds at 2^22+.  Numpy
    tail/head inputs serve as their own host copy automatically.

    ``seq`` — an externally given elimination order (the `-s`/`-r` case):
    skips the device degree histogram + sort entirely (two fewer full-E
    passes plus the E-sized sort), maps links straight through the
    position table, and honors the absent-vid pst contract (edges to
    vids outside the sequence count toward pst, never the tree —
    jtree.cpp:47-49).
    """
    from .forest import reduce_links_hosted, parent_from_links

    if handoff_factor is None:
        handoff_factor = default_handoff_factor()
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if seq is not None and len(seq):
        n = max(n, int(np.asarray(seq).max()) + 1)
    if n == 0:
        return np.empty(0, np.uint32), Forest(
            np.empty(0, np.uint32), np.empty(0, np.uint32))
    if host_edges is None and jax.devices()[0].platform != "cpu" \
            and isinstance(tail, np.ndarray) and isinstance(head, np.ndarray):
        # auto-detect only where the d2h saving is real: on the cpu
        # backend the device "fetch" is a near-free copy and the host
        # recompute would compete with the reduce loop for the same cores
        host_edges = (tail, head)
    given_seq = None
    _lazy_pst = None
    if seq is not None:
        # `-s` fast path: no histogram, no device sort — links map through
        # the given position table (absent-vid contract lives in
        # ops.sort.given_seq_links, shared with the mesh builders)
        from .sort import given_seq_links
        given_seq = np.asarray(seq, dtype=np.uint32)
        lo, hi, pst = given_seq_links(tail, head, given_seq, n,
                                      with_pst=host_edges is None)
        m = len(given_seq)
        dev_seq = None
        if pst is None:
            # pst counts the pre-dead-mask lo, so it can't be recovered
            # from the masked arrays — the rare prefetch-failure fallback
            # just reruns the mapping with the scatter included
            def _lazy_pst():
                return given_seq_links(tail, head, given_seq, n)[2]
    else:
        # with a host edge copy the prefetch thread recomputes pst
        # host-side — skip the device's full-E pst scatter; keep the
        # original lo handle so the rare prefetch-failure fallback can
        # still materialize pst on device afterwards
        dev_seq, _, m, lo, hi, pst = prepare_links(
            jnp.asarray(tail), jnp.asarray(head), n,
            with_pst=host_edges is None)
        if pst is None:
            orig_lo = lo

            def _lazy_pst():
                # module-level pst_weights, eager: one scatter op through
                # jax's global op cache, no throwaway per-closure jit
                return pst_weights(orig_lo, n)
    # every downstream consumer (prefetch fallback, _finish) reads `seq`:
    # the given host order when supplied, else the device-computed one
    seq = given_seq if given_seq is not None else dev_seq
    # overlap seq/pst with the reduction rounds: with a host edge copy,
    # recompute them on the host (no d2h at all); otherwise stream them
    # down on a second thread — on the tunneled backend d2h runs ~10MB/s
    # (scripts/tunnel_probe.py) and the reduce phase blocks on its own
    # per-chunk round trips, so either way the work hides behind the
    # chunk loop
    import threading
    fetched: dict = {}

    def _prefetch():
        try:
            if host_edges is not None:
                t_np, h_np = host_edges
                fetched["seq"], fetched["pst"] = _host_seq_pst(
                    t_np, h_np, n, seq=given_seq)
                # host seq is already trimmed to the m active slots, so its
                # length replaces the device scalar fetch (~70ms tunneled)
                fetched["m"] = len(fetched["seq"])
            else:
                fetched["seq"] = np.asarray(seq)
                fetched["pst"] = np.asarray(pst)
        except Exception:  # fall back to the synchronous fetch below
            fetched.clear()

    pre = threading.Thread(target=_prefetch, daemon=True)
    pre.start()
    # immediate-handoff only where its trade was measured to win — the
    # shared handoff_input_ok gate (same for the stream's final fold and
    # the profiler, so the sites can't drift)
    lo, hi, live, rounds, converged = reduce_links_hosted(
        lo, hi, n, stop_live=handoff_factor * n,
        handoff_input=handoff_input_ok())
    def _pst_resolved():
        # host-prefetched pst when the thread landed it; else the device
        # pst — materialized lazily when prepare_links skipped the scatter
        # (prefetch failure is the only path that reaches the lazy case)
        if "pst" in fetched:
            return fetched["pst"]
        return pst if pst is not None else _lazy_pst()

    if converged:
        pre.join()
        parent = parent_from_links(lo, hi, n)
        return _finish(fetched.get("seq", seq), fetched.get("m", m), parent,
                       _pst_resolved())
    def _pst_after_fetch():
        # joined only after the big link fetch inside handoff_finish_native
        # has completed, so the seq/pst prefetch keeps overlapping it
        pre.join()
        return np.asarray(_pst_resolved()).astype(np.uint32)

    parent_h, pst_out = handoff_finish_native(lo, hi, live, n,
                                              _pst_after_fetch)
    m = int(fetched.get("m", m))
    seq_np = np.asarray(fetched.get("seq", seq))[:m].astype(np.uint32)
    return seq_np, Forest(parent_h[:m].copy(), pst_out[:m].copy())


def handoff_input_ok() -> bool:
    """THE immediate-handoff gate, shared by every caller (the hybrid,
    the streaming final fold, scripts/hybrid_profile) so the sites can't
    drift: skip the device dedupe rounds only where the d2h copy is free
    (cpu backend) AND the native union-find consumes the undeduped links
    (the pure-python UF pays per link; a byte-bound accelerator fetch
    wants the dedupe rounds to shrink the volume first)."""
    from ..core.forest import native_or_none
    return jax.devices()[0].platform == "cpu" \
        and native_or_none("auto") is not None


def default_handoff_factor() -> int:
    """Platform-tuned handoff threshold (stop_live = factor * n).

    On cpu the "transfer" is free, so hand off as early as possible (8n ~
    after the first dedupe round; measured 3.3x faster than reducing to
    2n).  On a real accelerator the handoff is a device->host copy over
    the link (0.5GB at 2^23 for 8n), so reduce further first.  The
    pure-python fallback pays per link: keep reducing to 2n without the
    native runtime.  Env override: SHEEP_HANDOFF_FACTOR.
    """
    import os

    from ..core.forest import native_or_none
    if native_or_none("auto") is None:
        default = "2"
    else:
        default = "8" if jax.devices()[0].platform == "cpu" else "3"
    return int(os.environ.get("SHEEP_HANDOFF_FACTOR", default))


def fetch_links_host(lo, hi, live: int, n: int):
    """THE production link-fetch policy, shared with scripts/hybrid_profile
    so the profiler's d2h phase can never drift from what the hybrid
    actually does: 64K-granular cut (each distinct slice length is a fresh
    XLA program; tunneled compiles are slow), 6-byte packing where the
    link is byte-bound (SHEEP_PACK_HANDOFF overrides; needs n < 2^24),
    dead-sentinel filter.  Returns (lo_h, hi_h uint-safe int arrays,
    packed: bool).
    """
    import os

    cut = min(int(lo.shape[0]), -(-live // (1 << 16)) * (1 << 16))
    pack = os.environ.get("SHEEP_PACK_HANDOFF", "")
    if pack == "":  # default: pack where the fetch is byte-bound (tunnel)
        pack = "0" if jax.devices()[0].platform == "cpu" else "1"
    packed = pack == "1" and n < (1 << 24)
    if packed:
        from .forest import pack_links_6b, unpack_links_6b
        buf = np.asarray(pack_links_6b(lo[:cut], hi[:cut]))[:live]
        lo_h, hi_h = unpack_links_6b(buf)
    else:
        lo_h = np.asarray(lo[:cut])[:live]
        hi_h = np.asarray(hi[:cut])[:live]
    keep = lo_h < n  # a few scattered dead slots may remain in the prefix
    return lo_h[keep], hi_h[keep], packed


def handoff_finish_native(lo, hi, live: int, n: int, pst_h):
    """Fetch a reduced link set and finish with the exact sequential
    union-find (the hybrid tail): returns (parent, pst) uint32 [n].

    lo/hi: device int32 arrays whose first ``live`` slots contain the live
    links (plus possibly a few dead sentinels — filtered here); pst_h: the
    accumulated pst counts, host-side — an array, or a zero-arg callable
    resolved only after the link fetch (lets a caller's prefetch thread
    overlap that fetch).  The fetch is 64K-granular (each distinct slice
    length is a fresh XLA program; tunneled compiles are slow) and
    6-byte-packed where the link is byte-bound (SHEEP_PACK_HANDOFF
    overrides; needs n < 2^24).
    """
    import os

    from ..core.forest import native_or_none

    lo_h, hi_h, _ = fetch_links_host(lo, hi, live, n)
    if callable(pst_h):
        pst_h = pst_h()
    native = native_or_none("auto")
    if native is not None:
        return native.build_forest_links(
            lo_h.astype(np.uint32), hi_h.astype(np.uint32), n, pst_h)
    from ..core.forest import build_forest_links
    forest = build_forest_links(lo_h.astype(np.int64),
                                hi_h.astype(np.int64), n, pst=pst_h,
                                impl="python")
    return forest.parent, forest.pst_weight
