"""Device-side sequence engine: degree histogram + (degree, vid) sort.

The reference's orders (lib/sequence.h): ascending degree with ascending-vid
tie-break, computed from the undirected-doubled degree (each edge record
counts both endpoints; self-loops count twice).  Every distributed variant
sorts an identical replicated histogram (sequence.h:65-93), which is exactly
how the mesh path works here too (psum the histogram, replicated sort —
sheep_tpu.parallel).

Shapes are static: the sequence is returned full-length over all n vid
slots, with zero-degree vertices pushed to the tail via an infinite sort key
(the reference drops them, graph_wrapper.h:97-100); ``num_active`` says how
many leading entries are real.  Positions of zero-degree vids are INVALID.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

_I32_MAX = np.int32(np.iinfo(np.int32).max)


@functools.partial(jax.jit, static_argnames=("n",))
def degree_histogram(tail: jnp.ndarray, head: jnp.ndarray, n: int) -> jnp.ndarray:
    """Undirected-doubled degrees (graph_wrapper.h:87-89 semantics)."""
    deg = jnp.zeros(n, jnp.int32)
    deg = deg.at[tail.astype(jnp.int32)].add(1)
    deg = deg.at[head.astype(jnp.int32)].add(1)
    return deg


@jax.jit
def degree_order(deg: jnp.ndarray):
    """(seq, pos, num_active) from a dense degree histogram.

    seq: int32 [n] — vids sorted by (degree asc, vid asc), zero-degree last.
    pos: int32 [n] — vid -> sequence position; INVALID (=n) for zero-degree.
    """
    n = deg.shape[0]
    vid = jnp.arange(n, dtype=jnp.int32)
    key = jnp.where(deg > 0, deg.astype(jnp.int32), _I32_MAX)
    # packed-single-key (deg, vid) sort via the shared helper + gate
    # (key <= INT32_MAX keeps the packed int64 positive)
    from .forest import sort_links
    _, seq = sort_links(key, vid)
    pos_all = jnp.zeros(n, jnp.int32).at[seq].set(vid)
    pos = jnp.where(deg > 0, pos_all, jnp.int32(n))
    return seq, pos, jnp.sum(deg > 0).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("n",))
def edge_links(tail: jnp.ndarray, head: jnp.ndarray, pos: jnp.ndarray, n: int):
    """Map edge records to sentinel-padded (lo, hi) position links.

    Self-loops become sentinels (excluded from the tree, jtree.cpp:48).
    """
    pt = pos[tail.astype(jnp.int32)]
    ph = pos[head.astype(jnp.int32)]
    lo = jnp.minimum(pt, ph)
    hi = jnp.maximum(pt, ph)
    dead = lo == hi
    sent = jnp.int32(n)
    return jnp.where(dead, sent, lo), jnp.where(dead, sent, hi)


def given_seq_links(tail, head, seq, n: int, with_pst: bool = True):
    """Links + pst for an externally-given (possibly subset) sequence —
    THE one encoding of the absent-vid contract (jtree.cpp:47-49): an
    edge whose earlier endpoint is present counts toward pst even when
    the other endpoint is absent from the sequence; only fully-present
    links enter the tree; self-loops/padding never count.

    Returns (lo, hi, pst) device arrays, lo/hi sentinel-masked for the
    fixpoint.  Shared by the hybrid's `-s` fast path and the mesh-of-one
    builder so the contract lives in exactly one place.

    ``with_pst=False`` skips the full-E pst scatter (pst is None) for
    callers that recompute pst host-side from their own edge copy; note
    pst counts the PRE-dead-mask lo (present lo, absent hi still counts),
    so it cannot be recovered from the returned masked arrays — rerun
    with with_pst=True if it turns out to be needed after all.
    """
    from ..core.sequence import sequence_positions
    from .forest import pst_weights

    pos_np = sequence_positions(seq, n - 1).astype(np.int64)
    pos_np = np.where((pos_np < 0) | (pos_np >= n), n, pos_np)
    pos_d = jnp.asarray(pos_np, jnp.int32)
    lo, hi = edge_links(jnp.asarray(tail), jnp.asarray(head), pos_d, n)
    pst = pst_weights(jnp.where(lo == hi, jnp.int32(n), lo), n) \
        if with_pst else None
    dead = hi >= jnp.int32(n)
    sent = jnp.int32(n)
    return jnp.where(dead, sent, lo), jnp.where(dead, sent, hi), pst


def degree_sequence_device(tail: np.ndarray, head: np.ndarray,
                           num_vertices: int | None = None) -> np.ndarray:
    """Host-facing: the reference's degreeSequence on device (active only)."""
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if n == 0:
        return np.empty(0, dtype=np.uint32)
    deg = degree_histogram(jnp.asarray(tail), jnp.asarray(head), n)
    seq, _, m = degree_order(deg)
    return np.asarray(seq)[: int(m)].astype(np.uint32)
