"""Single-device JAX kernels + the jax-free external-memory build.

Resolution is LAZY (PEP 562): importing ``sheep_tpu.ops`` — or its
jax-free member ``ops.extmem`` (ISSUE 9) — must not initialize a jax
backend.  The out-of-core build's whole acceptance is peak RSS inside
``SHEEP_MEM_BUDGET``, and a backend's baseline footprint would be most
of a small budget; everything that was eagerly re-exported here before
still resolves by name exactly as it did (``from sheep_tpu.ops import
build_graph_hybrid`` triggers the jax import at that moment, not at
package import).
"""

_LAZY = {
    # .sort
    "degree_histogram": "sort",
    "degree_order": "sort",
    "edge_links": "sort",
    "degree_sequence_device": "sort",
    # .forest
    "forest_fixpoint": "forest",
    "forest_fixpoint_hosted": "forest",
    "fixpoint_chunk": "forest",
    "reduce_links_hosted": "forest",
    "parent_from_links": "forest",
    "pst_weights": "forest",
    "merge_parents": "forest",
    "build_forest_device": "forest",
    "merge_forests_device": "forest",
    # .build
    "build_step": "build",
    "build_graph_device": "build",
    "build_graph_hybrid": "build",
    "prepare_links": "build",
    # .stream
    "build_graph_streaming": "stream",
    "build_graph_streaming_hosted": "stream",
    "stream_block_step": "stream",
    "streaming_degree_histogram": "stream",
    # .extmem (jax-free)
    "build_forest_extmem": "extmem",
    "streaming_degree_sequence": "extmem",
    "range_degree_histogram": "extmem",
    "should_use_extmem": "extmem",
    # .distext (jax-free, ISSUE 13)
    "run_distext": "distext",
    "should_use_distext": "distext",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    value = getattr(importlib.import_module(f".{mod}", __name__), name)
    globals()[name] = value  # cache: next access skips the indirection
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
