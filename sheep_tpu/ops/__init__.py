from .sort import (
    degree_histogram,
    degree_order,
    edge_links,
    degree_sequence_device,
)
from .forest import (
    forest_fixpoint,
    forest_fixpoint_hosted,
    fixpoint_chunk,
    reduce_links_hosted,
    parent_from_links,
    pst_weights,
    merge_parents,
    build_forest_device,
    merge_forests_device,
)
from .build import (build_step, build_graph_device, build_graph_hybrid,
                    prepare_links)
from .stream import (build_graph_streaming,
                     build_graph_streaming_hosted, stream_block_step,
                     streaming_degree_histogram)

__all__ = [
    "degree_histogram",
    "degree_order",
    "edge_links",
    "degree_sequence_device",
    "forest_fixpoint",
    "forest_fixpoint_hosted",
    "fixpoint_chunk",
    "reduce_links_hosted",
    "parent_from_links",
    "pst_weights",
    "merge_parents",
    "build_forest_device",
    "merge_forests_device",
    "build_step",
    "build_graph_device",
    "build_graph_hybrid",
    "prepare_links",
    "build_graph_streaming",
    "build_graph_streaming_hosted",
    "stream_block_step",
    "streaming_degree_histogram",
]
