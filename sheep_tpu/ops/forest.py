"""Elimination-forest construction as a fixed-shape XLA fixpoint kernel.

The reference builds the forest with an inherently sequential pointer-chasing
loop: stream vertices in sequence order, union-find re-adoption per edge
(lib/jtree.cpp:34-55, lib/unionfind.h:78-102).  A line-for-line port would be
a latency-bound scalar loop — the worst possible TPU program.  This module
replaces it with a bandwidth-bound batched algorithm based on a structural
fact about the tree itself:

    The elimination forest is the single-linkage merge hierarchy of the
    position graph under edge weight w({lo,hi}) = hi.  Proof sketch: run
    Kruskal ascending by weight.  Every edge of weight h is incident on h,
    and all earlier edges have both endpoints < h, so at the moment weight-h
    edges are processed, h is the maximum of its component and every
    component adjacent to h via a weight-h edge has some maximum r < h.
    Merging assigns parent[r] = h — exactly the reference's
    ``adopt(root(nbr), X)`` step (lib/jnode.h:158-162).  Hence the forest is
    a function of *threshold connectivity* only: any edge-multiset transform
    that preserves, for every t, the connected components of the subgraph of
    edges with weight <= t, preserves the forest.

One transform suffices, iterated to fixpoint over static-shape int32 edge
arrays (dead edges parked at a sentinel so shapes never change):

  T   bounded pointer jump.  With f(v) = v's current minimum up-neighbor
      (one scatter-min over the live edges), relabel an edge (lo, hi) to
      (f^k(lo), hi) for the largest k with f^k(lo) < hi: lo and f^k(lo)
      are already connected at threshold f^k(lo) < hi, so threshold
      connectivity is preserved.  Values along an f-chain are strictly
      increasing, so the maximal ancestor below hi is found by binary
      lifting — square f into ancestor tables f^2, f^4, ... and take
      strides greedily from the largest down.  (Self-loops and duplicates
      need no special handling: they rewrite like any edge and never
      perturb the scatter-min.)

Every applied rewrite strictly increases some live-edge ``lo`` field and
``lo`` is bounded by n, so the iteration terminates unconditionally — the
loop runs until no edge moves, no round cap needed.  At the fixpoint every
live edge (lo, hi) has f(lo) >= hi, and f(lo) <= hi by definition of f, so
hi == f(lo): each vertex has at most one distinct up-neighbor, the edge set
*is* a functional forest, and that forest is its own merge hierarchy — i.e.
the answer.  ``parent[v]`` is then just a scatter-min of hi by lo.
``pst_weight`` is order-free (one count per non-loop edge at its lower
endpoint, lib/jtree.cpp:47-49) and is a single segment-sum over the
*original* links.

An earlier revision also rewrote hub stars into chains with a per-round
lexicographic ``lax.sort``; the jump transform alone reaches the same
fixpoint (measured: identical parents, ~20% more rounds) and a sort-free
round is ~5x cheaper, since it is all gathers and scatter-mins.  The
lifting depth per round is capped (``jump_levels``, default 6 → jumps up
to 2^5 per round): deeper tables barely reduce the round count on
power-law graphs but pay ``levels`` extra gathers every round.

The same kernel implements the distributed tree merge (lib/jnode.cpp:174-250,
the MPI_Reduce custom op): a partial forest re-enters as its (kid, parent)
link set, and merging k partials is rebuilding from their concatenated links
— associativity for free, which sheep_tpu.parallel exploits over the mesh.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .. import INVALID_JNID
from ..core.forest import Forest
from ..obs import trace as _obs

_I32_MAX = np.int32(np.iinfo(np.int32).max)

#: lifting depth per round — jumps advance up to 2^(levels-1) ancestors
_JUMP_LEVELS = 6


def _pack64_sorts() -> bool:
    """Trace-time gate for the packed single-key link sort.

    SHEEP_SORT_PACK64=1/0 forces it; unset defaults to on for the cpu
    backend (measured 4.2x vs the 2-key variadic sort at 2^20-2^22 —
    XLA:CPU's variadic sort carries every operand through a slow generic
    comparator loop, while a single s64 key hits the fast radix path)
    and off for accelerators, where s64 is emulated in 32-bit lanes and
    the trade needs an on-chip A/B before it can be the default.

    Caveat (same shape as the _use_pallas gate): the decision reads the
    DEFAULT backend at trace time.  Host-side work pinned to CPU via
    jax.default_device while an accelerator is the default backend gets
    the 2-key branch; set SHEEP_SORT_PACK64=1 explicitly there.
    """
    import os
    v = os.environ.get("SHEEP_SORT_PACK64", "")
    if v in ("0", "1"):
        return v == "1"
    return jax.devices()[0].platform == "cpu"


def sort_links(lo: jnp.ndarray, hi: jnp.ndarray):
    """Lexicographic (lo, hi) sort of int32 link arrays.

    When :func:`_pack64_sorts` allows, packs each pair into one int64
    ((lo << 32) | hi — exact for the package-wide nonnegative-int32
    value contract, sentinels included) and sorts a single key; the
    scoped ``jax.enable_x64`` keeps the wider dtype local to these few
    ops even under a jit trace of an otherwise-x32 program.
    """
    if _pack64_sorts():
        from ..utils.compat import enable_x64
        with enable_x64():
            # pure-lax packing: jnp binary ops re-canonicalize the scalar
            # operand to i32 on older jax (even inside the scoped x64
            # context, when tracing under an outer x32 jit), which trips
            # the StableHLO verifier with i64 << i32.  convert_element_type
            # + same-shape lax bit ops sidestep dtype canonicalization on
            # every jax generation.
            def i64(x):
                return lax.convert_element_type(x, jnp.int64)
            shift = i64(jnp.full(lo.shape, 32, jnp.int32))
            mask = i64(jnp.full(lo.shape, 0xFFFFFFFF, jnp.uint32))
            key = lax.bitwise_or(lax.shift_left(i64(lo), shift), i64(hi))
            key = lax.sort(key)
            # values are nonnegative (package-wide int32 contract), so the
            # logical right shift recovers lo exactly
            return (lax.convert_element_type(
                        lax.shift_right_logical(key, shift), jnp.int32),
                    lax.convert_element_type(
                        lax.bitwise_and(key, mask), jnp.int32))
    return lax.sort((lo, hi), num_keys=2)


def sort_links_by_hi(lo: jnp.ndarray, hi: jnp.ndarray):
    """Sort the link table by ASCENDING hi (lo tie break; dead sentinel
    pairs last) — the streaming windowed handoff's device-side
    windowing: contiguous equal-count slices of the result ARE the
    hi-quantile windows (the parallel.chunked.hi_window_bounds rule),
    arriving in exactly the order the resumable native fold consumes.
    Same pack64 policy as :func:`sort_links` with the roles swapped
    ((hi << 32) | lo).
    """
    if _pack64_sorts():
        from ..utils.compat import enable_x64
        with enable_x64():
            def i64(x):
                return lax.convert_element_type(x, jnp.int64)
            shift = i64(jnp.full(lo.shape, 32, jnp.int32))
            mask = i64(jnp.full(lo.shape, 0xFFFFFFFF, jnp.uint32))
            key = lax.bitwise_or(lax.shift_left(i64(hi), shift), i64(lo))
            key = lax.sort(key)
            return (lax.convert_element_type(
                        lax.bitwise_and(key, mask), jnp.int32),
                    lax.convert_element_type(
                        lax.shift_right_logical(key, shift), jnp.int32))
    hi_s, lo_s = lax.sort((hi, lo), num_keys=2)
    return lo_s, hi_s


def _rewrite_sorted(lo: jnp.ndarray, hi: jnp.ndarray, n: int):
    """Star -> chain rewrite + dedupe on SORTED (lo, hi) arrays.  For a
    vertex v with up-neighbors h1 < h2 < ... < hk, rewrites edges
    (v,h2..hk) to (h1,h2), (h2,h3), ... — at any threshold t the connected
    set {v} + {hj <= t} is unchanged; exact duplicates die.  Returns
    (lo, hi, applied_count)."""
    sent = jnp.int32(n)
    prev_same = jnp.concatenate(
        [jnp.zeros((1,), jnp.bool_), lo[1:] == lo[:-1]])
    prev_hi = jnp.concatenate([jnp.full((1,), sent, jnp.int32), hi[:-1]])
    applied = prev_same & (lo != sent)
    lo = jnp.where(applied, prev_hi, lo)
    # prev_hi <= hi inside a sorted group; equality = duplicate edge, dead.
    dead = lo >= hi
    lo = jnp.where(dead, sent, lo)
    hi = jnp.where(dead, sent, hi)
    return lo, hi, jnp.sum(applied, dtype=jnp.int32)


def _use_pallas(n: int) -> bool:
    """Trace-time gate for the fused Pallas jump (ops/pallas_jump.py).

    SHEEP_PALLAS=1 enables the compiled kernel, =interpret runs it in
    interpreter mode (CPU-testable); unset/0 keeps the jnp descent.  Read
    at trace time — set the env before the first compile of a shape.
    """
    import os
    mode = os.environ.get("SHEEP_PALLAS", "")
    if mode not in ("1", "interpret"):
        return False
    from .pallas_jump import levels_per_call
    return levels_per_call(n) > 0


def _lift_descend(lo: jnp.ndarray, hi: jnp.ndarray, n: int, levels: int,
                  f: jnp.ndarray):
    """Binary-lifting descent through a GIVEN one-step table f [n+1]:
    square f into ancestor tables and greedily advance each lo to its
    maximal f-ancestor strictly below hi.  Returns (lo, moved_count).

    Taking f as a parameter lets the mesh path combine per-shard tables
    (lax.pmin) before lifting — and every caller shares the Pallas-fused
    kernel gate (ops/pallas_jump.py, SHEEP_PALLAS=1).
    """
    if _use_pallas(n):
        import os
        from .pallas_jump import fused_descend
        return fused_descend(lo, hi, n, levels, f,
                             interpret=os.environ.get("SHEEP_PALLAS")
                             == "interpret")
    lo_in = lo
    tables = [f]
    for _ in range(levels - 1):
        tables.append(tables[-1][tables[-1]])
    for table in reversed(tables):
        nlo = table[lo]
        lo = jnp.where(nlo < hi, nlo, lo)
    return lo, jnp.sum(lo != lo_in, dtype=jnp.int32)


def _jump(lo: jnp.ndarray, hi: jnp.ndarray, n: int, levels: int):
    """Binary-lifted pointer jump: advance each lo to its maximal
    f-ancestor strictly below hi, where f = min up-neighbor over the live
    links (slot n absorbs sentinels).  Returns (lo, moved_count)."""
    sent = jnp.int32(n)
    f = jnp.full(n + 1, sent, jnp.int32).at[lo].min(hi)
    return _lift_descend(lo, hi, n, levels, f)


def _sort_step(lo: jnp.ndarray, hi: jnp.ndarray, n: int):
    """Sort + star->chain rewrite (the while_loop kernel's accelerator; a
    pure jump round discovers a hub's chain only one link per round)."""
    lo, hi = sort_links(lo, hi)
    lo, hi, _ = _rewrite_sorted(lo, hi, n)
    return lo, hi


def _round_step(lo: jnp.ndarray, hi: jnp.ndarray, do_sort: jnp.ndarray,
                n: int, levels: int):
    """One jump round (+ sort rewrite when ``do_sort``).  Dead edges sit
    at n.  Returns (lo, hi, moved) where ``moved`` counts edges whose lo
    advanced this round; the caller loops while moved > 0 and schedules
    ``do_sort`` at exponentially spaced round indices."""
    lo, hi = lax.cond(do_sort,
                      lambda args: _sort_step(*args, n=n),
                      lambda args: args, (lo, hi))
    lo, moved = _jump(lo, hi, n, levels)
    return lo, hi, moved


@functools.partial(jax.jit, static_argnames=("n", "jump_levels"))
def forest_fixpoint(lo: jnp.ndarray, hi: jnp.ndarray, n: int,
                    jump_levels: int | None = None):
    """Parent array of the elimination forest of links (lo -> hi), lo < hi.

    Inputs are int32 position pairs; entries with lo == hi == n are ignored
    (sentinels), which is how self-loops and padding are passed in.  Returns
    (parent int32 [n] with n marking roots, rounds int32).  The loop runs
    until no edge moves — termination is guaranteed because every applied
    rewrite strictly increases a lo field bounded by n.
    """
    sent = jnp.int32(n)
    if jump_levels is None:
        # Elimination-tree depth grows roughly with sqrt-to-log factors of
        # n on power-law graphs; measured sweet spots: 6 at n=2^16, 8 at
        # n=2^18.  Deeper tables barely cut rounds but cost per round.
        jump_levels = max(_JUMP_LEVELS, int(np.ceil(np.log2(n + 2))) // 2)
    levels = max(1, min(jump_levels, int(np.ceil(np.log2(n + 2)))))

    if lo.shape[0] == 0:
        return jnp.full((n,), sent, jnp.int32), jnp.int32(0)

    def cond(state):
        _, _, moved, _ = state
        return moved > 0

    def body(state):
        lo, hi, _, rounds = state
        # Sort accelerator at exponentially spaced rounds (7, 15, 31, ...):
        # a hub star otherwise unrolls only one chain link per jump round,
        # and O(log) sorts bound that worst case without paying a sort
        # every round.  Exiting on moved == 0 is always sound — the jump
        # fixpoint alone already implies a functional forest.
        do_sort = (rounds >= 7) & ((rounds & (rounds + 1)) == 0)
        lo, hi, moved = _round_step(lo, hi, do_sort, n, levels)
        return lo, hi, moved, rounds + 1

    lo = lo.astype(jnp.int32)
    hi = hi.astype(jnp.int32)
    # Initial 'moved' must inherit lo's varying manual axes so the carry
    # types line up when this runs inside shard_map; the max is >= 1 for a
    # nonempty array, so the first round always runs.
    state = (lo, hi, jnp.maximum(jnp.max(lo), 1), jnp.int32(0))
    lo, hi, _, rounds = lax.while_loop(cond, body, state)
    parent = jnp.full(n + 1, sent, jnp.int32).at[lo].min(hi)[:n]
    return parent, rounds


# ---------------------------------------------------------------------------
# Host-orchestrated chunked fixpoint — the production path on real hardware.
#
# The single-dispatch while_loop kernel above is correct but was measured to
# be the wrong execution shape for the tunneled TPU backend (round-3 device
# diagnostics, scripts/tpu_diag.py):
#   - a while_loop execution faults once its wall-time grows past the
#     backend's per-execution budget (n>=2^20 at 8 edges/vertex), and
#   - every primitive costs ~the same ~100M elements/s, so the win comes
#     from shrinking the arrays, not the op count: one sort round kills
#     85-93% of the edges (duplicates + star collapse) within 2-4 rounds.
#
# The chunked driver therefore runs J rounds per dispatch with a
# data-independent fori_loop (bounded execution time, no faults), reads the
# live count between chunks, and re-dispatches on sliced arrays.  Measured
# round structure (scripts/round_proto.py): sort every round + 10-level
# lifting converges in ~30 rounds at 2^18 vs 42 for the exponential-sort
# schedule, and live edges drop to ~15% of E by round 2.
# ---------------------------------------------------------------------------


def _chunk_round(lo, hi, n: int, levels: int):
    """One production round: sort -> chain rewrite -> L-level jump.

    Returns (lo, hi, moved, live) where ``live`` counts non-sentinel edges
    right after the sort — the tail beyond it is dead in the *output* too
    (rewrites never resurrect an edge), which is what makes host-side
    slicing sound.
    """
    sent = jnp.int32(n)
    lo, hi = sort_links(lo, hi)
    live = jnp.sum(lo != sent, dtype=jnp.int32)
    lo, hi, rewrites = _rewrite_sorted(lo, hi, n)
    lo, jumped = _jump(lo, hi, n, levels)
    return lo, hi, rewrites + jumped, live


@functools.partial(jax.jit, static_argnames=("n", "levels", "jrounds"))
def fixpoint_chunk(lo: jnp.ndarray, hi: jnp.ndarray, n: int,
                   levels: int, jrounds: int):
    """``jrounds`` chunk rounds in one dispatch (data-independent fori_loop).

    Returns (lo, hi, stats) with stats = int32 [2] of
    (moved_last_round, live_after_last_sort) — stacked so the host reads
    both in ONE transfer: on the tunneled backend every scalar fetch is a
    ~70ms round trip (scripts/tunnel_probe.py), so per-chunk sync cost is
    one round trip, not two.
    """
    def body(_, st):
        lo, hi, _, _ = st
        return _chunk_round(lo, hi, n, levels)

    state = (lo.astype(jnp.int32), hi.astype(jnp.int32),
             jnp.int32(0), jnp.int32(lo.shape[0]))
    lo, hi, moved, live = lax.fori_loop(0, jrounds, body, state)
    return lo, hi, jnp.stack([moved, live])


@functools.partial(jax.jit, static_argnames=("n", "levels"))
def jump_chunk(lo: jnp.ndarray, hi: jnp.ndarray, n: int, levels: int):
    """One jump-only round (no sort): a cheap opener for full-size arrays.

    Round 1's sort retires only ~6% of a power-law edge set (exact input
    duplicates); the mass kill needs jump-induced lo collisions FIRST,
    which the next round's sort then dedupes.  Skipping the opener's sort
    was measured 26% faster to the hybrid handoff at 2^18 on the cpu
    backend (scripts/sched_ab.py).  Returns (lo, hi, stats) like
    :func:`fixpoint_chunk`, but with NO sort the returned ``live`` count
    carries no prefix guarantee — live edges may sit anywhere in the
    arrays, so callers must NOT compact on it (it is an upper bound on
    the live population only, sound because the jump never resurrects a
    dead edge).
    """
    sent = jnp.int32(n)
    lo = lo.astype(jnp.int32)
    hi = hi.astype(jnp.int32)
    live = jnp.sum(lo != sent, dtype=jnp.int32)
    lo, moved = _jump(lo, hi, n, levels)
    return lo, hi, jnp.stack([moved, live])


@jax.jit
def pack_links_6b(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """Pack (lo, hi) int32 pairs with values < 2^24 into uint8 [k, 6].

    The handoff fetch is byte-bound on a tunneled backend (~10MB/s,
    scripts/tunnel_probe.py); 24-bit little-endian halves cut it 25% vs
    two int32 arrays.  Sentinel values (== n) pack fine: n < 2^24 at
    every supported size, and the host filters lo < n after unpack.
    """
    lo = lo.astype(jnp.int32)
    hi = hi.astype(jnp.int32)
    return jnp.stack(
        [lo & 0xFF, (lo >> 8) & 0xFF, (lo >> 16) & 0xFF,
         hi & 0xFF, (hi >> 8) & 0xFF, (hi >> 16) & 0xFF],
        axis=1).astype(jnp.uint8)


def unpack_links_6b(buf: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-side inverse of :func:`pack_links_6b` (numpy, vectorized)."""
    b = buf.astype(np.int32)
    lo = b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16)
    hi = b[:, 3] | (b[:, 4] << 8) | (b[:, 5] << 16)
    return lo, hi


@functools.partial(jax.jit, static_argnames=("n",))
def parent_from_links(lo: jnp.ndarray, hi: jnp.ndarray, n: int):
    """Scatter-min parent extraction (valid once links form a forest)."""
    sent = jnp.int32(n)
    return jnp.full(n + 1, sent, jnp.int32).at[lo.astype(jnp.int32)].min(
        hi.astype(jnp.int32))[:n]


def _pad_pow2(x: int, lo_cap: int = 1 << 12) -> int:
    p = lo_cap
    while p < x:
        p <<= 1
    return p


@functools.partial(jax.jit, static_argnames=("n", "nc"))
def vremap_compact(lo: jnp.ndarray, hi: jnp.ndarray, n: int, nc: int):
    """Relabel the vertices of the live links into a dense space [0, nc).

    Why: one chunk round costs O(n * levels) in jump-table work (the
    ``jnp.full(n + 1)`` fill plus ``levels - 1`` table squarings in
    :func:`_lift_descend`) no matter how few links remain — measured
    ~70ms/round at n=2^22 on the cpu backend with only 8k live links,
    and the tunneled chip's per-op rate is ~10x worse.  Once compaction
    has shrunk the link arrays, relabeling the surviving vertices into a
    dense [0, nc) space makes every subsequent round O(links * levels).

    Soundness: the map (ascending rank of the vertex among the distinct
    live-link endpoints) is strictly monotone, so lo < hi ordering, the
    min-up-neighbor function, and threshold connectivity over the
    relabeled vertices are all preserved; the elimination forest is a
    function of threshold connectivity only (module docstring).  Every
    vertex that still needs a parent appears in some live link: rewrites
    never drop a vertex's last link (a non-root vertex's min-up link
    survives to the functional-forest fixpoint), so vertices absent from
    the live links are already settled (roots/isolated) and back-map to
    parent-less slots.

    Requires nc >= number of distinct live endpoints (callers pass
    nc = 2 * len(lo), a safe bound).  Returns (lo_c, hi_c, back) where
    back is int32 [nc + 1]: compact id -> original position, back[nc]
    (the compact sentinel) and unused slots hold n.
    """
    sent = jnp.int32(n)
    csent = jnp.int32(nc)
    lo = lo.astype(jnp.int32)
    hi = hi.astype(jnp.int32)
    verts = lax.sort(jnp.concatenate([lo, hi]))  # sentinels sort last
    is_live = verts < sent
    is_new = is_live & jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), verts[1:] != verts[:-1]])
    # every occurrence of a vertex gets the same rank (cumsum counts the
    # first occurrence only), so duplicate scatter writes agree
    rank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    fwd = jnp.full(n + 1, csent, jnp.int32).at[
        jnp.where(is_live, verts, jnp.int32(n + 1))].set(rank, mode="drop")
    back = jnp.full(nc + 1, sent, jnp.int32).at[
        jnp.where(is_live, rank, jnp.int32(nc + 1))].set(verts, mode="drop")
    return fwd[lo], fwd[hi], back


@jax.jit
def vremap_back(lo_c: jnp.ndarray, hi_c: jnp.ndarray, back: jnp.ndarray):
    """Inverse of :func:`vremap_compact` on link arrays (compact sentinel
    maps through back's last slot to the original n)."""
    return back[lo_c], back[hi_c]


def _vremap_enabled() -> bool:
    import os
    return os.environ.get("SHEEP_VREMAP", "1") != "0"


# ---------------------------------------------------------------------------
# Plateau-adaptive round scheduling (round-6).
#
# Measured trajectory of the chunk loop on power-law graphs (2^20-2^22,
# cpu backend): the mass-kill retires ~93% of the edges in 3-4 rounds,
# then the loop spends the REST of the build — 24 of 34 rounds at 2^20,
# ~80 of 90 at 2^22 — on a "plateau" where the live count barely moves and
# per-round ``moved`` decays into a tail of single digits.  Probing that
# tail shows why no lifting depth fixes it: the last movers are straggler
# links (lo, hi) whose f-chain toward hi does not EXIST yet — each round
# a straggler lands one chain position further, and that landing is what
# materializes the next f-step (f[y] := hi) for the stragglers behind it.
# Chains materialize one link per round; binary lifting cannot cross a
# chain that is not there (levels=cap was measured to cut 194 j=1 rounds
# to 83 at 2^22 and then stall in the same moved<=6 crawl for 30+ rounds).
#
# The crawl is inherently SEQUENTIAL — so the scheduler runs it
# sequentially, where sequential pointer-chasing is cheap: the host.
# Once the per-chunk stats (already fetched — no extra sync) show a
# plateau (live-count drop < 5% per chunk, or movers a <=1/8 fraction of
# live), the loop fetches the live links plus the one-step table f,
# walks every straggler's f-chain to its maximal ancestor below hi on
# the host — materializing chain steps as links land, exactly the
# device transform executed sparsely — and scatters the few advanced lo
# values back.  One walk drives the whole cascade to its fixpoint, so
# the tail collapses to ~one assist plus a j=1 verification chunk:
# measured 90 -> 13 rounds at 2^22, 34 -> 13 at 2^20, parents
# bit-identical to the oracle.  Soundness is the module-docstring
# argument unchanged: each advance moves lo to an f-ancestor strictly
# below hi (threshold connectivity preserved), and the "phantom"
# f-entries left behind by advanced links still witness real
# connectivity (the chain that carried the link there).  Every advance
# strictly increases a lo bounded by n, so termination is unchanged.
#
# SHEEP_PLATEAU_ADAPT=0 restores the round-5 schedule;
# SHEEP_PLATEAU_ASSIST_CAP bounds the stragglers walked per assist
# (default 2^17 — past it the assist defers to the escalated-depth
# device rounds until the mover count decays under the cap).
# ---------------------------------------------------------------------------


def _plateau_enabled() -> bool:
    import os
    return os.environ.get("SHEEP_PLATEAU_ADAPT", "1") != "0"


def _plateau_assist_cap() -> int:
    import os
    return int(os.environ.get("SHEEP_PLATEAU_ASSIST_CAP", str(1 << 17)))


@functools.partial(jax.jit, static_argnames=("n",))
def min_up_table(lo: jnp.ndarray, hi: jnp.ndarray, n: int) -> jnp.ndarray:
    """One-step jump table f [n+1]: min up-neighbor per vertex over the
    live links (slot n absorbs sentinels) — the assist's device-side
    half, one dispatch."""
    return jnp.full(n + 1, jnp.int32(n), jnp.int32).at[
        lo.astype(jnp.int32)].min(hi.astype(jnp.int32))


def plateau_assist_walk(l: np.ndarray, h: np.ndarray, f: np.ndarray,
                        n: int, cap: int | None = None,
                        max_passes: int = 4096) -> tuple[int, int, int]:
    """Host straggler walk: advance every live link's lo to its maximal
    f-ancestor strictly below hi, materializing chain steps (f[y] :=
    min(f[y], hi)) as links land, until no straggler remains.

    l, h, f: int64 numpy arrays (l and f are MUTATED in place); dead
    slots hold n, f[n] == n.  ``cap`` bounds the initial straggler set
    (the walk bails untouched past it — the caller's escalated device
    rounds shrink the set first).  Returns (walks, passes): total
    straggler advances and cascade passes run.

    Passes after the first are incremental: a settled link can only
    re-become a straggler when f at its CURRENT lo drops, and f only
    drops at patch points — so each pass rechecks the tracked set (every
    link that was ever a straggler) plus the untracked links whose lo
    sits at a freshly patched vertex, found through a sorted snapshot of
    the pre-walk lo values (untracked links never moved, so the snapshot
    is exact for them).  That keeps a deep cascade at O(stragglers) per
    pass instead of O(live).  Returns (walks, passes, stragglers) —
    stragglers is the initial straggler count (> cap on a bail).
    """
    sent_safe = np.minimum(l, n)
    cand = np.nonzero((l < n) & (h > f[sent_safe]))[0]
    if cand.size == 0:
        return 0, 0, 0
    if cap is not None and cand.size > cap:
        return 0, 0, int(cand.size)
    n0 = int(cand.size)
    order = np.argsort(l, kind="stable")
    l0_sorted = l[order]  # pre-walk snapshot (exact for untracked links)
    tracked_mask = np.zeros(l.shape[0], np.bool_)
    tracked_mask[cand] = True
    tracked = cand
    walks = 0
    passes = 0
    while passes < max_passes and cand.size:
        passes += 1
        ids = cand[f[l[cand]] < h[cand]]
        if ids.size == 0:
            break
        walks += int(ids.size)
        sl = l[ids]
        sh = h[ids]
        while True:  # vectorized descent; f is strictly increasing
            nx = f[sl]
            adv = nx < sh
            if not adv.any():
                break
            sl = np.where(adv, nx, sl)
        l[ids] = sl
        before = f[sl]
        np.minimum.at(f, sl, sh)
        patched = np.unique(sl[f[sl] < before])
        if patched.size:
            a = np.searchsorted(l0_sorted, patched, side="left")
            b = np.searchsorted(l0_sorted, patched, side="right")
            spans = [order[x:y] for x, y in zip(a, b) if y > x]
            if spans:
                fresh = np.concatenate(spans)
                fresh = fresh[~tracked_mask[fresh]]
                if fresh.size:
                    tracked_mask[fresh] = True
                    tracked = np.concatenate([tracked, fresh])
        cand = tracked
    return walks, passes, n0


def _pad_pow2_min(x: int, floor: int = 16) -> int:
    p = floor
    while p < x:
        p <<= 1
    return p


@functools.partial(jax.jit, static_argnames=("k",))
def _scatter_lo(lo: jnp.ndarray, idx: jnp.ndarray, vals: jnp.ndarray,
                k: int):
    """Scatter ``k`` advanced lo values back into the device array.
    idx/vals are padded to k with idx == len(lo) (dropped), so the
    compile family stays bounded at one program per (width, k-pow2)."""
    return lo.at[idx].set(vals, mode="drop")


class _PlateauSched:
    """Sticky plateau detector + assist driver for the hosted chunk loop.

    Consumes the (moved, live) stats the loop already fetches; once the
    plateau is on, the loop escalates lifting depth to the full cap and
    shrinks the chunk length to j=1 verification rounds around host
    assists.  ``assist`` runs the straggler walk on fetched state and
    scatters advanced lo values back (bounded: cap stragglers, one
    width-sized f fetch, walks-sized h2d)."""

    #: live-count drop per chunk under which the loop is plateaued
    RATIO = 0.95
    #: movers at most this fraction of live also signal the plateau
    MOVED_FRAC = 8

    def __init__(self):
        import os
        self.enabled = _plateau_enabled()
        self.cap = _plateau_assist_cap()
        # SHEEP_PLATEAU_FORCE=1: plateau mode from round one — the
        # detection boundaries stop mattering, so tests and dryrun arms
        # can certify the assist machinery on inputs too small to
        # plateau naturally
        self.on = self.enabled and \
            os.environ.get("SHEEP_PLATEAU_FORCE", "") == "1"
        self.prev_live: int | None = None
        self.assists = 0
        self.walks = 0
        self.bail: int | None = None  # stragglers at the last capped bail
        self.assisted = False  # a non-bailed assist attempt has run

    def observe(self, moved: int, live: int) -> None:
        if not self.enabled or self.on:
            self.prev_live = live
            return
        if self.prev_live is not None and live > self.RATIO * self.prev_live:
            self.on = True
        if moved > 0 and moved * self.MOVED_FRAC <= live:
            self.on = True
        self.prev_live = live

    def wants_assist(self, moved: int) -> bool:
        if not (self.enabled and self.on and 0 < moved <= self.cap):
            return False
        # after a capped bail, retry only once the mover count has
        # clearly decayed — straggler counts track movers, and even a
        # bailed attempt pays the full state fetch
        return self.bail is None or moved * 2 <= self.bail

    def assist(self, lo, hi, n_cur: int):
        """Run one host assist; returns (lo, advanced: bool) — advanced
        False means the walk bailed (capped) or found nothing, and the
        caller must not book a round for it."""
        l = np.asarray(lo).astype(np.int64)
        h = np.asarray(hi).astype(np.int64)
        f = np.asarray(min_up_table(lo, hi, n_cur)).astype(np.int64)
        l_orig = l.copy()
        walks, _, stragglers = plateau_assist_walk(l, h, f, n_cur,
                                                   cap=self.cap)
        if walks == 0 and stragglers > self.cap:
            self.bail = stragglers
            return lo, False
        self.bail = None
        self.assisted = True
        if not walks:
            return lo, False
        self.assists += 1
        self.walks += walks
        changed = np.nonzero(l != l_orig)[0]
        k = _pad_pow2_min(changed.size)
        idx = np.full(k, lo.shape[0], np.int32)
        vals = np.zeros(k, np.int32)
        idx[:changed.size] = changed
        vals[:changed.size] = l[changed]
        return _scatter_lo(lo, jnp.asarray(idx), jnp.asarray(vals), k), True


def _pipe_width_ok(width: int, pad: int) -> bool:
    """The pipelined-dispatch width gate: engage only at 4x-compacted
    AND width <= 2^17 — where one hidden ~80ms RTT outweighs the
    one-chunk-late compaction's stale-width compute (break-even
    W ~ 1e5 at j=8 rounds and ~100M elem/s; PERF_NOTES round 5)."""
    return 4 * width <= pad and width <= (1 << 17)


def _pipeline_chunks() -> bool:
    """Pipelined chunk dispatch gate (SHEEP_PIPELINE_CHUNKS overrides):
    default ON off-cpu — each hidden sync is a real ~80ms tunnel round
    trip there — and OFF on the cpu backend, where the stats fetch is
    instant and the one-chunk-late compaction would only cost width."""
    import os
    v = os.environ.get("SHEEP_PIPELINE_CHUNKS", "")
    if v != "":
        return v == "1"
    return jax.devices()[0].platform != "cpu"


#: per-chunk round counts — probe every round while live is collapsing
#: (rounds 1-3 kill 85-93% of edges, and an early stop at the knee saves
#: both compute and handoff transfer), then batch rounds once the arrays
#: are compact so the ~70ms-per-chunk tunnel sync amortizes.  The fixed
#: tuple bounds the (shape, jrounds) axes of what XLA compiles; the
#: vertex remap adds an n_cur axis (one fresh fixpoint_chunk compile per
#: remap, <= log4(n/4096) per run, amortized by the persistent cache).
_CHUNK_SCHEDULE = (1, 1, 1, 2, 4)


def _depth_tier(size: int, pad: int, in_schedule: bool, levels: int,
                first_levels: int, cap: int) -> int:
    """Three-tier lifting depth shared by the hosted and mesh chunk loops
    (round-4 A/B, PERF_NOTES): light ``first_levels`` while the ARRAYS
    are still at full size (full-width gathers cost most and early
    progress is dedupe/star-collapse); ``levels+2`` mid-phase;
    ``levels+6`` once compaction is below an eighth of the original
    padded size (late-phase gathers are cheap and the remaining cost is
    chain DEPTH, which deep tables cut exponentially).

    ``size`` is the current ARRAY length — the gather width actually
    paid, which is what the tier trades against depth.  Tiering on the
    live count instead was A/B'd and lost (2^22: 109.7-114.6s vs 98.5s;
    deep tiers engaged a fetch earlier, on still-wide arrays).  Measured
    vs flat levels=10 on the pure-device path: 24.7->18.0s at 2^20,
    181.8->98.5s (1.85x) at 2^22, parents bit-identical; 14/18 tiers
    slightly worse.  Caveat: compaction floors at 4096 slots, so inputs
    with pad <= 16384 never reach the deep tier — at those sizes the
    whole build is milliseconds and depth is irrelevant.
    """
    if in_schedule and size >= pad:
        return first_levels
    if size > pad // 8:
        return min(levels + 2, cap)
    return min(levels + 6, cap)


@jax.jit
def _sorted_once(lo: jnp.ndarray, hi: jnp.ndarray):
    """One plain lexicographic sort as its own cached XLA program, for
    the immediate-handoff path.  Measured at 2^22 (cpu backend, full-E
    handoff): raw order 11.1s total, sort-only 9.2s (the native UF reads
    a sorted stream 3x faster once its parent array outgrows cache),
    sort+rewrite 23.7s (the rewrite scrambles the order — chain links
    land at scattered hub positions, worse than raw for the UF), and
    sort+rewrite+re-sort 10.5s (the dedupe doesn't pay for the second
    sort).  Plain sort wins."""
    return sort_links(lo, hi)


def _live_links_np(lo, hi, n: int):
    """Host copies of the live links (lo < n) — the checkpointable state
    at a chunk boundary (runtime/snapshot.py's soundness argument)."""
    l = np.asarray(lo)
    h = np.asarray(hi)
    keep = l < n
    return l[keep], h[keep]


def reduce_links_hosted(lo, hi, n: int, stop_live: int = 0,
                        levels: int = 10, jrounds: int = 8,
                        first_levels: int = 4,
                        handoff_input: bool = False,
                        handoff_sort: bool = True,
                        watch=None, runtime=None):
    """Run chunk rounds until convergence (or until live <= stop_live),
    compacting between dispatches.

    lo/hi: int32 device or host arrays, sentinel n for dead slots.  Returns
    (lo, hi, live, rounds, converged) with lo/hi on device, all remaining
    live links in the first ``live`` slots' prefix region (plus possibly a
    few dead ones — callers must still mask lo < n).

    ``watch`` — optional hook called after each sorted chunk's stats land
    with the snapshot ``(lo, hi, live)``: immutable device arrays with the
    live-prefix guarantee, in the ORIGINAL vertex space only (the hook is
    skipped once a vertex remap is active).  Returning True stops the loop
    right there (returned converged=False).  This is how the hybrid's
    overlapped speculative handoff (ops.build) fetches an early snapshot
    concurrently with later chunks: every chunk output has the same
    threshold connectivity, so any complete snapshot — or a union of
    snapshots — hands off soundly.

    A sort-free jump-only opener round runs first, then chunks follow
    ``_CHUNK_SCHEDULE`` and repeat ``jrounds``; lifting depth escalates
    per :func:`_depth_tier` as the live set collapses (``levels`` is the
    mid-phase base: effective depth is levels+2 mid, levels+6 late,
    capped at log2(n)).  Once the arrays have compacted far enough
    (2 * cols <= n/4), the VERTEX space compacts too
    (:func:`vremap_compact`, SHEEP_VREMAP=0 disables): later rounds'
    O(n * levels) jump-table work becomes O(cols * levels), which on the
    measured backends is the whole cost of the late phase.  The returned
    links are always back in the original vertex space.

    Once the per-chunk stats show the live count has PLATEAUED, the
    round-6 adaptive scheduler takes over (:class:`_PlateauSched`,
    SHEEP_PLATEAU_ADAPT=0 disables): chunks shrink to j=1 at late-tier
    depth, the remap trigger relaxes, and the sequential straggler
    crawl that otherwise consumes most of the build's rounds (~80 of 90
    at 2^22) is resolved by bounded host assists
    (:func:`plateau_assist_walk`) — measured 90 -> 13 rounds at 2^22
    on the cpu backend, parents bit-identical.

    ``runtime`` — optional runtime.ChunkRuntime: wraps every dispatch in
    the retry/backoff/watchdog policy (halving the per-dispatch round
    count on a fault) and checkpoints the live links at each chunk
    boundary while the loop is still in the original vertex space (once a
    vertex remap engages, the last pre-remap checkpoint stands — the
    remap is an optimization detail a resume need not replay).  Fault
    tolerance trades the pipelined-dispatch overlap away: checkpoint
    boundaries need settled state, so the pipeline is disabled.
    """
    lo = jnp.asarray(lo, jnp.int32)
    hi = jnp.asarray(hi, jnp.int32)
    e = int(lo.shape[0])
    if e == 0:
        return lo, hi, 0, 0, True
    pad = _pad_pow2(e)
    if pad != e:
        fill = jnp.full(pad - e, n, jnp.int32)
        lo = jnp.concatenate([lo, fill])
        hi = jnp.concatenate([hi, fill])
    if handoff_input and stop_live and e <= stop_live:
        # The input already satisfies the handoff threshold AND the
        # caller promised the output goes straight to the native
        # union-find (``handoff_input`` — NOT the streaming folds, whose
        # carry contract needs the dedupe rounds): the opener + a sorted
        # chunk retire ~nothing before the live check stops the loop
        # anyway (measured 10.3s of a 13.8s CPU hybrid at 2^22 with
        # factor 8, where stop_live == E).  What the handoff stream
        # needs depends on whether the union-find's parent array still
        # fits in cache: below n ~ 2^21 (UF state < ~16MB) raw R-MAT
        # order chases fine (0.28s at 2^20) and any device work is a
        # loss; above it, raw order thrashes (8.2s vs 2.9s at 2^22) and
        # one plain sort on the POW2-PADDED arrays (bounded compile
        # variants, sentinels sort last) pays for itself in the native
        # tail (see _sorted_once for the rejected rewrite variants).
        # The returned count stays the sentinel-inclusive upper bound;
        # callers' lo < n filter drops dead slots.  ``handoff_sort``
        # False skips the sort: the round-6 cache-blocked kernel's
        # quantile bucketing reads RAW order faster than the sort costs
        # (1.54s raw fold vs 3.65s sort + 0.98s sorted fold at 2^22 on
        # the 1-core host), and the streaming windowed tail orders its
        # windows itself — the pre-blocked measurement above predates
        # both.
        if n >= (1 << 21) and handoff_sort:
            lo, hi = _sorted_once(lo, hi)
        return lo, hi, e, 0, False
    rounds = 0
    chunk_i = 0
    n_cur = n  # current vertex-space size (shrinks at each remap)
    back = None  # compact id -> ORIGINAL position, composed across remaps
    remap_on = _vremap_enabled()

    def _restore(lo, hi):
        return (lo, hi) if back is None else vremap_back(lo, hi, back)
    # Jump-only opener: on the full-size arrays the sort is the most
    # expensive op and round 1's sort retires almost nothing (~6%) — the
    # collisions this jump creates are what round 2's sort dedupes.  26%
    # faster to the hybrid handoff at 2^18 (scripts/sched_ab.py).  Its
    # stats are deliberately NOT fetched (each host sync is a ~70ms
    # tunnel round trip, and the streaming path calls this per block);
    # an already-converged input just costs one cheap sorted chunk below.
    if runtime is None:
        lo, hi, _ = jump_chunk(lo, hi, n, first_levels)
    else:
        (lo, hi, _), _ = runtime.dispatch(
            "chunk", lambda _j: jump_chunk(lo, hi, n, first_levels))
    rounds += 1
    # Pipelined dispatch (round 5, SHEEP_PIPELINE_CHUNKS; default ON
    # off-cpu): keep the NEXT chunk in flight while the previous chunk's
    # stats make the ~80ms tunnel round trip, so per-chunk sync hides
    # behind device compute.  Sound one-chunk-late compaction: live
    # counts decrease monotonically across chunks and rewrites never
    # resurrect a dead slot, so every live link of chunk k+1's output
    # sits within the first pad(live_k) slots.  Costs: the in-flight
    # chunk runs at the pre-compaction width, and a stop/convergence is
    # detected one chunk late (that chunk's output is discarded and its
    # rounds uncounted).  Disabled once a vertex remap engages (the
    # remap needs exact state; the pipeline drains first).
    pipeline = _pipeline_chunks() and runtime is None
    prev = None  # (lo, hi, stats) of the chunk whose stats are unread

    def _consume(stats, alo, ahi, rounds_ret):
        """THE exit policy after a chunk's stats resolve, shared by the
        sync, pipelined, and drain sites so they cannot drift: returns
        (exit_tuple | None, live, moved).  A non-None exit_tuple is the
        loop's return value, arrays restored to the original vertex
        space."""
        moved_i, live_i = (int(x) for x in np.asarray(stats))  # one sync
        # flight recorder: one event per resolved chunk — the round-level
        # record `sheep trace` rolls up (round counts from ONE code path)
        _obs.event("reduce.chunk", live=live_i, moved=moved_i,
                   rounds=rounds_ret)
        if moved_i == 0:
            rlo, rhi = _restore(alo, ahi)
            return (rlo, rhi, live_i, rounds_ret, True), live_i, moved_i
        if stop_live and live_i <= stop_live:
            rlo, rhi = _restore(alo, ahi)
            return (rlo, rhi, live_i, rounds_ret, False), live_i, moved_i
        if watch is not None and back is None and watch(alo, ahi, live_i):
            return (alo, ahi, live_i, rounds_ret, False), live_i, moved_i
        return None, live_i, moved_i

    def _compact(alo, ahi, live_i):
        target = _pad_pow2(live_i)
        if target <= alo.shape[0] // 2:
            return alo[:target], ahi[:target]
        return alo, ahi

    plate = _PlateauSched()
    while True:
        j = _CHUNK_SCHEDULE[chunk_i] if chunk_i < len(_CHUNK_SCHEDULE) \
            else jrounds
        cap = int(np.ceil(np.log2(n_cur + 2)))
        lv = _depth_tier(int(lo.shape[0]), pad,
                         chunk_i < len(_CHUNK_SCHEDULE),
                         levels, first_levels, cap)
        if plate.on:
            # plateau: late-tier depth so any straggler whose chain IS
            # materialized crosses it in one round; once an assist has
            # run, j=1 chunks so the exit check lands the moment its
            # cascade resolves (a j=8 chunk would book 8 rounds for a
            # convergence that happened in its first)
            lv = min(levels + 6, cap)
            if plate.assisted:
                j = 1
        if runtime is not None:
            # memory budget (ISSUE 5): the jump tables are the loop's
            # dominant O(n) allocation — cap the depth to the headroom
            lv = runtime.cap_levels(lv, n_cur)
        if runtime is None:
            nlo, nhi, stats = fixpoint_chunk(lo, hi, n_cur, lv, j)
        else:
            # the retry wrapper may shrink j (a dispatch that faulted asks
            # for half the rounds next attempt); account the shrunk value
            (nlo, nhi, stats), j = runtime.dispatch(
                "chunk", lambda jj: fixpoint_chunk(lo, hi, n_cur, lv, jj), j)
        rounds += j
        chunk_i += 1
        # width gate: pipeline only once the arrays are small.  Early
        # full-width chunks carry most of the compute, and the
        # one-chunk-late compaction makes them run at stale widths — a
        # forced-pipeline A/B on the instant-stats cpu backend measured
        # +29.5% end-to-end ungated and +16.7% gated at 4x-compacted
        # (PERF_NOTES round 5).  The hidden sync saves one ~80ms RTT;
        # at the backend's ~100M elem/s a j-round chunk at width W
        # costs ~j*W*12/1e8 s, so the crossover is W ~ 1e5 at j=8 —
        # hence the absolute cap alongside the relative one.  Width is
        # monotone non-increasing, so the mode never flips back —
        # except onto the plateau, whose host assists need settled
        # state between every chunk (drained below).
        use_pipe = pipeline and back is None and not plate.on \
            and _pipe_width_ok(int(lo.shape[0]), pad)
        if not use_pipe:
            if prev is not None:
                # the gate just turned off (plateau flip): drain the
                # in-flight chunk's predecessor stats first (prev's
                # arrays are this dispatch's inputs, lo/hi)
                _, _, pstats = prev
                prev = None
                exit_t, live_i, _ = _consume(pstats, lo, hi, rounds - j)
                if exit_t is not None:
                    return exit_t
                nlo, nhi = _compact(nlo, nhi, live_i)
            exit_t, live_i, moved_i = _consume(stats, nlo, nhi, rounds)
            if exit_t is not None:
                return exit_t
            lo, hi = _compact(nlo, nhi, live_i)
            plate.observe(moved_i, live_i)
            if plate.wants_assist(moved_i):
                # host straggler walk (one round's worth of the same
                # transform, executed sparsely where sequential work is
                # cheap); counted as a round — see _PlateauSched
                lo, advanced = plate.assist(lo, hi, n_cur)
                if advanced:
                    rounds += 1
            if runtime is not None and back is None:
                # chunk boundary: persist the live multiset (original
                # vertex space only — the snapshot soundness contract)
                runtime.boundary(
                    rounds, lambda: _live_links_np(lo, hi, n))
        else:
            if prev is not None:
                plo, phi, pstats = prev
                # resolves while the chunk dispatched above runs; on an
                # exit the in-flight chunk is discarded, its rounds
                # uncounted (rounds - j)
                exit_t, live_i, moved_i = _consume(pstats, plo, phi,
                                                   rounds - j)
                if exit_t is not None:
                    return exit_t
                # one-chunk-late compaction of the IN-FLIGHT output
                nlo, nhi = _compact(nlo, nhi, live_i)
                # a plateau observed here un-gates the pipeline next
                # iteration; the drain above settles state for assists
                plate.observe(moved_i, live_i)
            prev = (nlo, nhi, stats)
            lo, hi = nlo, nhi
        cols = int(lo.shape[0])
        # remap trigger: >= 4x table-work shrink normally; on the
        # plateau a 2x shrink already pays (many deep rounds may remain
        # when the assist is capped out, and the dense space halves
        # every table squaring)
        remap_den = 2 if plate.on else 4
        if remap_on and 2 * cols <= n_cur // remap_den \
                and n_cur > (1 << 16):
            if prev is not None:
                # drain the pipeline: the remap needs exact, settled
                # state (prev's arrays ARE lo/hi here)
                _, _, pstats = prev
                prev = None
                exit_t, live_i, _ = _consume(pstats, lo, hi, rounds)
                if exit_t is not None:
                    return exit_t
                lo, hi = _compact(lo, hi, live_i)
                # _compact only ever shrinks, so the remap trigger
                # (checked on the pre-drain width) still holds here
                cols = int(lo.shape[0])
            # each remap shrinks table work; the O(n_cur) forward
            # table build amortizes over every remaining round
            lo, hi, back_step = vremap_compact(lo, hi, n_cur, 2 * cols)
            back = back_step if back is None else back[back_step]
            n_cur = 2 * cols
    # unreachable


def forest_fixpoint_hosted(lo, hi, n: int, levels: int = 10,
                           jrounds: int = 8):
    """Host-orchestrated fixpoint: the production equivalent of
    :func:`forest_fixpoint` for real hardware.  Returns (parent int32
    device array [n] with n marking roots, rounds)."""
    lo, hi, live, rounds, _ = reduce_links_hosted(
        lo, hi, n, levels=levels, jrounds=jrounds)
    return parent_from_links(lo, hi, n), rounds


@functools.partial(jax.jit, static_argnames=("n",))
def pst_weights(lo: jnp.ndarray, n: int) -> jnp.ndarray:
    """Per-node postorder edge weight: one count per live link at its lo
    (jtree.cpp:47-49 equivalent; slot n absorbs sentinel links)."""
    return jnp.zeros(n + 1, jnp.int32).at[lo.astype(jnp.int32)].add(1)[:n]


def links_from_parent(parent: jnp.ndarray, n: int):
    """A forest's (kid -> parent) pairs as sentinel-padded link arrays."""
    kid = jnp.arange(n, dtype=jnp.int32)
    live = parent < n
    lo = jnp.where(live, kid, jnp.int32(n))
    hi = jnp.where(live, parent.astype(jnp.int32), jnp.int32(n))
    return lo, hi


@functools.partial(jax.jit, static_argnames=("n",))
def merge_parents(parents: jnp.ndarray, psts: jnp.ndarray, n: int):
    """Merge k same-sequence partial forests (lib/jnode.cpp:174-250).

    parents: int32 [k, n] with n marking roots; psts: int32 [k, n].
    Returns (parent int32 [n], pst int32 [n], rounds).
    """
    k = parents.shape[0]
    kid = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (k, n))
    live = parents < n
    lo = jnp.where(live, kid, jnp.int32(n)).reshape(-1)
    hi = jnp.where(live, parents.astype(jnp.int32), jnp.int32(n)).reshape(-1)
    parent, rounds = forest_fixpoint(lo, hi, n)
    return parent, psts.sum(axis=0).astype(jnp.int32), rounds


# ---------------------------------------------------------------------------
# Host-facing wrappers (numpy in / Forest out), used by tests and the CLI.
# ---------------------------------------------------------------------------

def _to_forest(parent_dev: jnp.ndarray, pst_dev: jnp.ndarray, n: int) -> Forest:
    parent = np.asarray(parent_dev).astype(np.int64)
    pst = np.asarray(pst_dev).astype(np.uint32)
    out = np.full(n, INVALID_JNID, dtype=np.uint32)
    live = parent < n
    out[live] = parent[live].astype(np.uint32)
    return Forest(out, pst)


def build_forest_device(tail: np.ndarray, head: np.ndarray,
                        seq: np.ndarray, max_vid: int | None = None) -> Forest:
    """Device-built Forest from raw edge records (test/CLI entry point)."""
    from ..core.forest import edges_to_positions

    lo, hi = edges_to_positions(tail, head, seq, max_vid)
    n = len(seq)
    # pst-only links (hi = INVALID: edge to a vertex absent from the
    # sequence) count toward pst but must be sentineled out of the fixpoint.
    pst_d = pst_weights(jnp.asarray(lo, dtype=jnp.int32), n)
    pst_only = hi >= n
    lo_d = jnp.asarray(np.where(pst_only, n, lo), dtype=jnp.int32)
    hi_d = jnp.asarray(np.where(pst_only, n, hi), dtype=jnp.int32)
    parent, _ = forest_fixpoint(lo_d, hi_d, n)
    return _to_forest(parent, pst_d, n)


def merge_forests_device(*forests: Forest) -> Forest:
    """Device merge of host Forests (equivalent to core.merge_forests)."""
    n = forests[0].n
    parents = np.stack([
        np.where(f.parent == INVALID_JNID, n, f.parent.astype(np.int64))
        for f in forests]).astype(np.int32)
    psts = np.stack([f.pst_weight.astype(np.int32) for f in forests])
    parent, pst, _ = merge_parents(jnp.asarray(parents), jnp.asarray(psts), n)
    return _to_forest(parent, pst, n)
