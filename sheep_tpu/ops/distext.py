"""Distributed out-of-core build: supervised ext legs + histogram
Allreduce + tournament forest merge (ISSUE 13).

PR 9 streams one 4x-over-budget ``.dat`` on one host (ops/extmem.py);
the PR-3 tournament supervisor already merges independently-built
forests associatively under retry/speculation/fsck.  This module is
their composition — the ROADMAP's "beyond-RAM meets beyond-one-host"
item, and the honest path to graphs 100x over any single memory budget,
where one host's two streamed passes dominate the wall clock:

  shard    the whole-input ``.dat`` splits into N contiguous record
           slices (:func:`plan_shards` — the same floor arithmetic as
           partial loads, so slices are edge-disjoint and cover the
           file).  N comes from the governor's planner
           (resources.governor.distext_leg_plan: ``SHEEP_DISTEXT_LEGS``
           pins it, else host cores / ``SHEEP_LEG_CORES`` cut to the
           aggregate budget).
  pass 1   one supervised ``hist`` leg per slice streams its range
           through its OWN :class:`~sheep_tpu.io.prefetch.BlockPrefetcher`
           (ops/extmem.range_degree_histogram) and publishes the
           per-range int64 degree histogram as a sealed, sidecar-first
           ``.hist`` artifact.  The supervisor's ``histsum`` leg is the
           Allreduce: integer adds commute, so the summed histogram —
           and the counting-sorted sequence it publishes — is
           bit-identical to the single-host pass.
  pass 2   one supervised ``distmap`` leg per slice runs the ext carry
           fold over its range (build_forest_extmem(start_edge,
           end_edge)) over the SHARED sequence, under its own
           ``SHEEP_MEM_BUDGET``, checkpointing at block boundaries with
           the slice folded into the checkpoint identity — a leg's
           checkpoint can never resume under a different shard map.
  merge    the per-leg partial forests k-way merge through the EXISTING
           tournament (``merge_trees --expect-sig`` unchanged): the
           forest of edge-disjoint partial graphs over one sequence is
           the forest of the union (lib/jnode.cpp:174-201), so the
           final tree is oracle-bit-identical by the same associativity
           that already carries the mesh path.

The fault surface is the supervisor's, unchanged: kill/EIO/ENOSPC at
block boundaries resolve inside a leg (the ext retry/checkpoint story),
and at leg boundaries (dispatch, publish, histogram merge, tournament
rounds) by retry/speculation/fsck with only dirty legs re-dispatched.

"Partitioning Trillion Edge Graphs on Edge Devices" (PAPERS.md) runs
this exact shape end-to-end on 8GB devices; "Pipelined Workflow in
Hybrid MPI/Pthread runtime for External Memory Graph Construction"
(PAPERS.md) is the per-leg read/fold overlap pattern the prefetcher
implements.

Jax-free like ops/extmem (the supervisor parent must stay lean; each
leg's whole acceptance is peak RSS inside its budget).
"""

from __future__ import annotations

import os
import shutil

import numpy as np

from ..integrity.errors import MalformedArtifact
from ..integrity.sidecar import checksummed_write, resolve_policy, verify_bytes
from ..resources.governor import (EXT_BLOCK_FLOOR, EXT_RECORD_BYTES,
                                  ResourceGovernor, distext_forced_legs)
from .extmem import dat_num_records

#: sealed per-range histogram artifact (one per pass-1 leg): the magic
#: line, five little-endian uint64 header words
#: (n, records, max_vid, start_edge, end_edge), then int64 deg[n]
HIST_MAGIC = b"sheep-hist 1\n"
_HIST_HEADER = np.dtype([("n", "<u8"), ("records", "<u8"),
                         ("max_vid", "<u8"), ("start", "<u8"),
                         ("end", "<u8")])


def write_histogram(path: str, deg: np.ndarray, records: int, max_vid: int,
                    start_edge: int, end_edge: int) -> None:
    """Seal one leg's per-range histogram, sidecar-first like every
    publish in the system.  ``deg`` is trimmed to ``max_vid + 1`` (the
    accumulator grows in pow2 steps; trailing zeros are not identity —
    two runs over the same range must produce byte-identical artifacts).
    """
    n = max_vid + 1 if records else 0
    deg = np.ascontiguousarray(deg[:n], dtype="<i8")
    head = np.zeros(1, dtype=_HIST_HEADER)
    head["n"], head["records"], head["max_vid"] = n, records, max_vid
    head["start"], head["end"] = start_edge, end_edge
    nbytes = len(HIST_MAGIC) + head.nbytes + deg.nbytes
    with checksummed_write(path, "wb", expect_bytes=nbytes,
                           extra={"range":
                                  f"{start_edge}:{end_edge}"}) as f:
        f.write(HIST_MAGIC)
        f.write(head.tobytes())
        f.write(deg.tobytes())


def read_histogram(path: str, integrity: str | None = None) -> dict:
    """Load + verify one ``.hist`` artifact: sidecar checksum, magic,
    exact length, int64 dtype, nonnegativity, and the structural
    invariants a well-formed range histogram always satisfies (every
    record adds exactly 2, the max vid really appears).  Raises
    MalformedArtifact on any corruption — this is also the ``sheep
    fsck`` checker's engine for ``.hist``."""
    mode = resolve_policy(integrity)
    with open(path, "rb") as f:
        data = f.read()
    verify_bytes(path, data, mode)
    if not data.startswith(HIST_MAGIC):
        raise MalformedArtifact(
            f"{path}: corrupt histogram — bad magic "
            f"(want {HIST_MAGIC!r})")
    off = len(HIST_MAGIC)
    if len(data) < off + _HIST_HEADER.itemsize:
        raise MalformedArtifact(
            f"{path}: corrupt histogram — {len(data)} bytes is too short "
            f"for the header")
    head = np.frombuffer(data, dtype=_HIST_HEADER, count=1, offset=off)[0]
    n = int(head["n"])
    want = off + _HIST_HEADER.itemsize + 8 * n
    if len(data) != want:
        raise MalformedArtifact(
            f"{path}: corrupt histogram — header claims n={n} "
            f"({want} bytes) but the file has {len(data)}")
    deg = np.frombuffer(data, dtype="<i8", count=n,
                        offset=off + _HIST_HEADER.itemsize)
    records = int(head["records"])
    start, end = int(head["start"]), int(head["end"])
    max_vid = int(head["max_vid"])
    problems = []
    if len(deg) and bool((deg < 0).any()):
        problems.append("negative degree count")
    if records != max(0, end - start):
        problems.append(f"records={records} != range length "
                        f"{max(0, end - start)} [{start}:{end})")
    if int(deg.sum()) != 2 * records:
        problems.append(f"degree total {int(deg.sum())} != 2 x {records} "
                        f"records (every record adds exactly 2)")
    if records and (max_vid >= n or deg[max_vid] <= 0):
        problems.append(f"max_vid {max_vid} has no degree")
    if problems:
        raise MalformedArtifact(
            f"{path}: corrupt histogram — " + "; ".join(problems))
    return {"deg": deg, "records": records, "max_vid": max_vid,
            "start": start, "end": end}


def merge_histograms(hists: list[dict],
                     expect_shards: list | None = None) -> np.ndarray:
    """The Allreduce: sum the per-range int64 histograms.  Integer adds
    commute, so the result is the whole-file histogram bit for bit (the
    counting sort over it is therefore the single-host sequence).

    ``expect_shards`` pins each histogram to its planned record slice —
    a stale artifact from a different shard map (or a reordered input
    list) is a refusal here, never a silently wrong sequence."""
    if expect_shards is not None:
        if len(hists) != len(expect_shards):
            raise MalformedArtifact(
                f"histogram merge: {len(hists)} histogram(s) for "
                f"{len(expect_shards)} planned shard(s)")
        for i, (h, (a, b)) in enumerate(zip(hists, expect_shards)):
            if (h["start"], h["end"]) != (int(a), int(b)):
                raise MalformedArtifact(
                    f"histogram merge: leg {i} covers "
                    f"[{h['start']}:{h['end']}) but the manifest's shard "
                    f"map says [{a}:{b}) — refusing a foreign shard map")
    n = max((len(h["deg"]) for h in hists), default=0)
    deg = np.zeros(n, dtype=np.int64)
    for h in hists:
        deg[: len(h["deg"])] += h["deg"]
    return deg


def plan_shards(num_records: int, legs: int) -> list[tuple[int, int]]:
    """N contiguous [start_edge, end_edge) record slices covering the
    file — the partial-load floor arithmetic (io/edges.partial_range),
    so slices are edge-disjoint, in order, and their union is exact."""
    if legs < 1:
        raise ValueError(f"legs {legs} must be >= 1")
    return [((i * num_records) // legs, ((i + 1) * num_records) // legs)
            for i in range(legs)]


def should_use_distext(path: str,
                       governor: ResourceGovernor | None = None) -> bool:
    """Should the build CLI route this graph through the distributed
    out-of-core job?  Yes when the operator forced a leg count
    (``SHEEP_DISTEXT_LEGS`` >= 2 — the env twin of ``--distext``), or
    when even the ext rung's single-leg stream cannot meet the budget:
    the fitted block has hit its floor and the floor-block stream still
    prices over the headroom, so the build must leave this process —
    every leg is a subprocess whose budget is its own, while the
    supervisor parent holds no O(n) state at all."""
    if not path.endswith(".dat"):
        return False
    if distext_forced_legs() >= 2:
        return True
    gov = governor if governor is not None else ResourceGovernor.from_env()
    head = gov.mem_headroom()
    if head is None:
        return False
    return EXT_RECORD_BYTES * EXT_BLOCK_FLOOR > head


def run_distext(graph: str, state_dir: str, config=None, runner=None,
                out_file: str | None = None, legs: int = 0):
    """Run (or resume) one distributed out-of-core build; returns the
    completed manifest.  Mirrors ``run_supervised``'s contract:
    ``state_dir`` holds the manifest, every artifact (per-range ``.hist``
    histograms, the shared sequence, per-leg partial trees, per-leg
    block checkpoints under ``ck-<key>/``), and worker logs; rerunning
    with the same dir fscks the survivors and re-dispatches only the
    dirty/missing legs.  ``legs`` pins the shard count (0 = the
    governor's planner / ``SHEEP_DISTEXT_LEGS``).

    Resume identity: the shard map persists in the manifest and a
    resumed run keeps it VERBATIM — a different forced leg count against
    an existing state dir is a refusal, not a replan (each leg's block
    checkpoint folds its record slice into its input_sig, so a foreign
    shard map could never publish anyway; the refusal is just earlier
    and clearer)."""
    from ..obs import trace as obs
    from ..resources import gc_orphan_temps
    from .. import supervisor as sup
    from ..supervisor.manifest import (load_manifest, manifest_path,
                                       plan_distext, save_manifest)
    from ..supervisor.supervise import (SupervisionFailed,
                                        TournamentSupervisor, reconcile,
                                        sweep_attempt_debris)

    config = config or sup.SupervisorConfig.from_env()
    if not graph.endswith(".dat"):
        raise SupervisionFailed(
            f"{graph}: distext shards binary .dat record streams only "
            f"(text parsing is not the beyond-RAM format)")
    os.makedirs(state_dir, exist_ok=True)
    gc_orphan_temps(state_dir)
    sweep_attempt_debris(state_dir)
    base = os.path.basename(graph)
    if base.endswith(".dat"):
        base = base[: -len(".dat")]
    prefix = os.path.join(state_dir, base)
    final = prefix + ".tre"

    gov = config.governor if config.governor is not None \
        else ResourceGovernor.from_env()
    forced = legs or distext_forced_legs()
    transport = None
    if os.path.exists(manifest_path(state_dir)):
        manifest = load_manifest(state_dir, config.integrity)
        size = os.path.getsize(graph) if os.path.exists(graph) else -1
        if manifest.graph != graph or manifest.graph_bytes != size:
            raise SupervisionFailed(
                f"{state_dir}: manifest belongs to a different build "
                f"({manifest.graph}, {manifest.graph_bytes} bytes; this "
                f"run: {graph}, {size} bytes) — refusing to resume; use "
                f"a fresh state dir")
        if manifest.shards is None:
            raise SupervisionFailed(
                f"{state_dir}: manifest is a plain tournament, not a "
                f"distext job — refusing to resume across job kinds")
        if forced and forced != len(manifest.shards):
            raise SupervisionFailed(
                f"{state_dir}: manifest shards the input across "
                f"{len(manifest.shards)} leg(s) but this run forces "
                f"{forced} — a checkpointed build never resumes under a "
                f"different shard map; use a fresh state dir")
        clean, dirty = reconcile(manifest,
                                 resolve_policy(config.integrity))
        config.events.append(("resume", clean, dirty))
    else:
        records = dat_num_records(graph)
        # the leg count routes through the planner (ISSUE 15): same
        # governor arithmetic, plus the provenance record — a forced
        # count (arg or SHEEP_DISTEXT_LEGS) is the operator's word
        from ..plan import plan_distext_legs, plan_transport
        plan = plan_distext_legs(governor=gov) if not forced else None
        n_legs = forced or plan["legs"]
        shards = plan_shards(records, n_legs)
        manifest = plan_distext(graph, prefix, final, shards,
                                config.reduction)
        transport = plan_transport(
            records, n_legs,
            len(getattr(config, "worker_addrs", None) or []))
        obs.event("distext.plan", legs=n_legs, records=records,
                  forced=bool(forced),
                  provenance=("forced" if forced
                              else plan["provenance"]),
                  block_edges=plan["block_edges"] if plan else None,
                  per_leg_peak_bytes=(plan["per_leg_peak_bytes"]
                                      if plan else None),
                  transport=transport["transport"],
                  transport_provenance=transport["provenance"],
                  workers=transport["remote_workers"])
        config.events.append(("distext-plan", n_legs, records))
    save_manifest(manifest, state_dir)
    worker_addrs = getattr(config, "worker_addrs", None) or []
    if transport is None:
        # resume path: the shard map is the manifest's, but the
        # transport decision is per-run — a resumed build prices (or
        # honors the pin) against TODAY's worker fleet
        from ..plan import plan_transport
        records = manifest.graph_bytes // EXT_RECORD_BYTES \
            if manifest.graph_bytes > 0 else 0
        transport = plan_transport(records, len(manifest.shards),
                                   len(worker_addrs))
    if worker_addrs and transport["transport"] == "ship":
        from ..supervisor.remote import RemoteRunner
        if runner is None:
            runner = sup.SubprocessRunner()
        if not getattr(runner, "remote", False):
            runner = RemoteRunner(
                worker_addrs, base=runner,
                beat_s=getattr(config, "worker_beat_s", 1.0))
    manifest = TournamentSupervisor(manifest, state_dir, config,
                                    runner).run()
    if out_file and out_file != manifest.final_tree:
        # export copy, sidecar first (the sheep_mv_artifact ordering)
        if os.path.exists(manifest.final_tree + ".sum"):
            shutil.copyfile(manifest.final_tree + ".sum",
                            out_file + ".sum")
        shutil.copyfile(manifest.final_tree, out_file)
    return manifest


def leg_checkpoint_dir(state_dir: str, key: str) -> str:
    """Where leg ``key``'s block-boundary checkpoints live (one dir per
    leg: two legs' ext folds must never share a snapshot file)."""
    return os.path.join(state_dir, f"ck-{key}")


def leg_perf_path(state_dir: str, key: str) -> str:
    """Where leg ``key``'s self-report lands (cli/distext ``--perf-out``):
    the leg's perf dict (read/fold overlap, strategies, retries) plus
    its own ``obs.metrics.proc_status`` capture (VmHWM/affinity), so a
    bench record can re-judge per-leg budgets and overlap from the
    record alone."""
    return os.path.join(state_dir, f"{key}.perf.json")


def apply_overlap_honesty(per_leg: dict, legs: int) -> bool:
    """The per-leg ``overlap_frac`` honesty rule (round 14): when the
    concurrent legs TIME-SHARE cores — the union of their affinity
    masks holds fewer cores than there are legs — a measured 0.0 is not
    "the prefetch never overlapped the fold", it is "the host could not
    have overlapped anything"; publishing the number invites a tuning
    conclusion the record cannot support.  Each affected leg row gets
    ``overlap_frac: None`` plus ``affinity_limited: True`` (the raw
    measurement survives under ``overlap_frac_raw`` so a reader can
    still see what the clock said).  Returns whether the rule fired;
    rows from hosts with enough distinct cores pass through untouched."""
    cores: set = set()
    for row in per_leg.values():
        aff = row.get("affinity_cores")
        if aff:
            cores.update(aff)
    limited = bool(per_leg) and bool(cores) and len(cores) < max(1, legs)
    if not limited:
        return False
    for row in per_leg.values():
        if "overlap_frac" in row:
            row["overlap_frac_raw"] = row["overlap_frac"]
            row["overlap_frac"] = None
        row["affinity_limited"] = True
    return True


__all__ = [
    "HIST_MAGIC",
    "apply_overlap_honesty",
    "leg_checkpoint_dir",
    "leg_perf_path",
    "merge_histograms",
    "plan_shards",
    "read_histogram",
    "run_distext",
    "should_use_distext",
    "write_histogram",
]
