"""External-memory build: beyond-RAM graphs as a first-class rung (ISSUE 9).

The paper's core property — elimination trees built on edge-disjoint
partial graphs over ONE sequence merge associatively into the tree of the
union (lib/jnode.cpp:174-201) — is exactly what makes a bounded-memory
disk-streaming build possible: fold the ``.dat`` record stream block by
block, never holding more than O(n + block) beyond the file itself.  PR
5's spill rung proved the associative fold through a memmap as a
degradation FALLBACK; this module is the fast path: every stage runs
through the native kernels at full speed and no stage — not even degree
sequencing — materializes the edge list.

Pipeline (two streaming passes over the same blocks):

  pass 1  degree sequence, out-of-core: per-block native histogram
          accumulation (sheep_degree_histogram_acc — the fused
          sheep_degree_sequence_edges kernel's uint32-histogram idea,
          restated as an accumulator) into one int64 array, then the
          host counting sort (core.sequence.degree_sequence_from_degrees
          — the ``SHEEP_STREAM_HOST_SEQ`` machinery).  Bit-identical to
          the in-RAM sequence: integer adds commute, so the accumulated
          histogram IS the whole-file histogram.
  pass 2  the carry fold: blocks arrive through the double-buffered
          async :class:`~sheep_tpu.io.prefetch.BlockPrefetcher` (the
          ``_WindowStream`` generalization — disk read of block k+1
          overlaps the fold of block k), and each block folds into the
          carry forest by one of two exact strategies, picked per block
          by the governor's priced estimates
          (resources.governor.ext_strategy_costs):

            edges  fused native records->forest (sheep_build_forest_edges
                   — the per-block links never materialize host-side),
                   then the bounded merge: (carry ∪ block-forest links)
                   through one resumable fold.  Wins when block >> n.
            links  host position mapping + ONE resumable fold over
                   (carry ∪ block links)
                   (sheep_build_forest_links_begin/_block/_finish via
                   core.forest.links_fold; python twin without the
                   native runtime).  Wins for carry-dominated blocks.

          Both are the associative merge, so ANY interleaving of picks
          converges to the bit-identical forest; pst accumulates per
          block (each record counts at its present earlier endpoint,
          absent-vid records included — jtree.cpp:47-49).

Fault story: every block read is a ``dat``-site I/O fault point
(io/edges.iter_dat_blocks + SHEEP_IO_FAULT_PLAN), and an EIO/ENOSPC
mid-stream retries from the last completed block — the in-memory carry
is still exact, so the re-opened stream (``start_edge``) resumes rather
than restarts.  Block boundaries checkpoint through the PR-1 snapshot
machinery (rung "ext", ``rounds`` = blocks folded), so a killed process
resumes bit-identically; the deterministic kill point is
``fault_point("ext-boundary")`` after each boundary, mirroring the chunk
drivers' "died between chunks".

Deliberately jax-free (like serve/): the whole point is peak RSS inside
``SHEEP_MEM_BUDGET``, and a backend's baseline footprint would be most
of a small budget.  ops/__init__ resolves lazily so importing this
module never drags the device stack in.
"""

from __future__ import annotations

import os
import time

import numpy as np

from .. import INVALID_JNID
from ..core.forest import (Forest, _positions_through, build_forest_links,
                           forest_links, links_fold, native_or_none)
from ..core.sequence import degree_sequence_from_degrees, sequence_positions
from ..integrity.errors import IntegrityError
from ..integrity.sidecar import resolve_policy
from ..io.edges import iter_dat_blocks
from ..io.prefetch import BlockPrefetcher
from ..obs import trace as obs
from ..resources.governor import (EXT_PREFETCH, ResourceGovernor,
                                  ext_block_edges, ext_strategy_costs)
from ..runtime.faults import fault_point
from ..runtime.retry import RetryPolicy
from ..runtime.snapshot import Checkpointer, Snapshot, input_signature

_REC_BYTES = 12  # XS1 record (io/edges._XS1_DTYPE)


def dat_num_records(path: str) -> int:
    return os.path.getsize(path) // _REC_BYTES


def should_use_extmem(path: str, governor: ResourceGovernor | None = None
                      ) -> bool:
    """Should the build CLI route this graph through the external-memory
    rung?  Yes when the operator opted in (``SHEEP_EXT_BLOCK`` — the env
    twin of ``--ext``, reachable from scripts) or when a configured
    memory budget cannot hold the in-RAM load + prep (priced at ~24
    bytes per record: uint32 tail/head arrays plus the mapped int32 link
    pair).  Only ``.dat`` files stream (text parsing is not the
    beyond-RAM format)."""
    if not path.endswith(".dat"):
        return False
    if os.environ.get("SHEEP_EXT_BLOCK", ""):
        return True
    gov = governor if governor is not None else ResourceGovernor.from_env()
    head = gov.mem_headroom()
    if head is None:
        return False
    try:
        nbytes = os.path.getsize(path)
    except OSError:
        return False
    return (nbytes // _REC_BYTES) * 24 > head


def range_degree_histogram(path: str, block_edges: int | None = None,
                           start_edge: int = 0,
                           end_edge: int | None = None,
                           max_retries: int = 3,
                           backoff_base_s: float = 0.05,
                           perf: dict | None = None):
    """Pass 1 over one contiguous record slice ``[start_edge, end_edge)``
    of the ``.dat`` stream: per-block native histogram accumulation
    (``sheep_degree_histogram_acc``; numpy bincount twin) through this
    range's OWN prefetcher.  Returns ``(deg int64, max_vid, records)``.

    Integer adds commute, so summing the per-range histograms of a
    disjoint cover of the file IS the whole-file histogram bit for bit —
    the Allreduce-shaped merge the distributed out-of-core build
    (ops/distext.py) runs between its two passes.

    A typed reader fault (EIO/ENOSPC mid-stream — the ``dat`` I/O fault
    site) retries from the last consumed block: the histogram is exact
    up to there (a block is only consumed after its read completed), so
    the re-opened stream resumes the accumulation rather than restarting
    the pass."""
    block = block_edges or ext_block_edges()
    native = native_or_none("auto")
    deg = np.zeros(1 << 10, dtype=np.int64)
    records = 0
    max_vid = 0
    done = 0
    read_s = 0.0
    policy = RetryPolicy(max_retries=max_retries,
                         backoff_base_s=backoff_base_s)
    attempt = 0
    with obs.span("ext.hist", block_edges=block, start_edge=start_edge,
                  end_edge=end_edge) as sp:
        while True:
            pf = BlockPrefetcher(
                iter_dat_blocks(path, block,
                                start_edge=start_edge + done * block,
                                end_edge=end_edge),
                depth=EXT_PREFETCH, trace_name="ext.seq.read")
            try:
                with pf:
                    for tail, head in pf:
                        records += len(tail)
                        mx = int(max(tail.max(initial=0),
                                     head.max(initial=0)))
                        max_vid = max(max_vid, mx)
                        if mx >= len(deg):
                            deg = np.concatenate(
                                [deg,
                                 np.zeros(mx + 1 - len(deg),
                                          dtype=np.int64)])
                        if native is not None:
                            native.degree_histogram_acc(tail, head, deg)
                        else:
                            deg += np.bincount(tail, minlength=len(deg))
                            deg += np.bincount(head, minlength=len(deg))
                        done += 1
                read_s += pf.busy_s
                break
            except OSError:
                read_s += pf.busy_s
                if attempt >= policy.max_retries:
                    raise
                policy.sleep(policy.backoff(attempt))
                attempt += 1
        sp.annotate(records=records, retries=attempt)
    if perf is not None:
        perf["hist_read_s"] = round(read_s, 4)
        perf["hist_retries"] = attempt
    return deg, max_vid, records


def streaming_degree_sequence(path: str, block_edges: int | None = None,
                              max_retries: int = 3,
                              backoff_base_s: float = 0.05,
                              perf: dict | None = None):
    """Out-of-core degree sequence: one prefetched pass over the ``.dat``
    blocks accumulating the undirected-doubled histogram
    (:func:`range_degree_histogram` over the whole file), then the host
    counting sort.  Returns ``(seq uint32, max_vid, num_records)`` —
    bit-identical to ``degree_sequence`` over the loaded file, at O(V)
    resident."""
    t0 = time.perf_counter()
    hist_perf: dict = {}
    with obs.span("ext.seq"):
        deg, max_vid, records = range_degree_histogram(
            path, block_edges, max_retries=max_retries,
            backoff_base_s=backoff_base_s, perf=hist_perf)
        seq = degree_sequence_from_degrees(deg)
    if perf is not None:
        perf["seq_s"] = round(time.perf_counter() - t0, 4)
        perf["seq_read_s"] = hist_perf["hist_read_s"]
        perf["seq_retries"] = hist_perf["hist_retries"]
    return seq, max_vid, records


def _pick_strategy(n: int, carry_links: int, block_records: int,
                   native_ok: bool) -> str:
    """Per-block strategy pick from the governor's priced estimates
    (``SHEEP_EXT_STRATEGY`` = edges|links pins it for A/B arms).  Both
    strategies are exact; the price is bytes touched, so a stale pick
    costs time, never the tree."""
    forced = os.environ.get("SHEEP_EXT_STRATEGY", "")
    if forced in ("edges", "links"):
        return forced if (forced == "links" or native_ok) else "links"
    if not native_ok:
        return "links"
    costs = ext_strategy_costs(n, carry_links, block_records)
    return "edges" if costs["edges"] <= costs["links"] else "links"


class _ExtFold:
    """The carry-fold state of pass 2: parent-so-far as its <= n
    (kid -> parent) links, the order-free pst accumulator, and the shared
    vid->position table.  O(n) resident; each :meth:`fold_block` adds one
    block and leaves the carry converged."""

    def __init__(self, n: int, pos: np.ndarray):
        self.n = n
        self.pos = pos
        self.pst = np.zeros(n, dtype=np.int64)
        self.carry_lo = np.empty(0, dtype=np.int64)
        self.carry_hi = np.empty(0, dtype=np.int64)
        self.parent = np.full(n, INVALID_JNID, dtype=np.uint32)
        self._zero = np.zeros(n, dtype=np.uint32)
        self.strategies: dict[str, int] = {}

    def _absorb(self, forest: Forest) -> None:
        self.parent = forest.parent
        self.carry_lo, self.carry_hi = forest_links(forest)

    def fold_block(self, tail: np.ndarray, head: np.ndarray) -> str:
        n = self.n
        native = native_or_none("auto")
        strat = _pick_strategy(n, len(self.carry_lo), len(tail),
                               native is not None)
        self.strategies[strat] = self.strategies.get(strat, 0) + 1
        if strat == "edges":
            # fused records->forest: the block's links never materialize
            # host-side; its pst_out is exactly this block's contribution
            # (absent-vid records counted, self-loops dropped)
            p, w = native.build_forest_edges(tail, head, self.pos, n)
            self.pst += w
            kids = np.nonzero(p != INVALID_JNID)[0]
            fold_lo = np.concatenate([self.carry_lo, kids])
            fold_hi = np.concatenate([self.carry_hi,
                                      p[kids].astype(np.int64)])
            self._absorb(build_forest_links(fold_lo, fold_hi, n,
                                            pst=self._zero))
            return strat
        # links: host mapping (the exact oracle semantics of
        # core.forest.build_forest_streaming) + one resumable fold over
        # (carry ∪ block links) — a single window, because an unsorted
        # disk stream cannot promise the cross-window ascending-hi
        # contract; the fold machinery is still the begin/_block/_finish
        # kernel underneath
        self.pos, pt, ph = _positions_through(self.pos, tail, head)
        keep = pt != ph  # drops self-loops and both-absent
        pt, ph = pt[keep], ph[keep]
        lo = np.minimum(pt, ph)
        hi = np.maximum(pt, ph)
        if len(lo):
            self.pst += np.bincount(lo, minlength=n)[:n]
        tree = hi < n
        fold = links_fold(n, pst=self._zero)
        fold.block(np.concatenate([self.carry_lo, lo[tree]]),
                   np.concatenate([self.carry_hi, hi[tree]]))
        parent, _ = fold.finish()
        self._absorb(Forest(parent, self._zero))
        return strat


def build_forest_extmem(path: str, block_edges: int | None = None,
                        seq: np.ndarray | None = None,
                        checkpoint_dir: str | None = None,
                        resume: bool = False, max_retries: int = 3,
                        backoff_base_s: float = 0.05,
                        checkpoint_every: int = 1,
                        governor: ResourceGovernor | None = None,
                        integrity: str | None = None,
                        events: list | None = None,
                        perf: dict | None = None,
                        start_edge: int = 0,
                        end_edge: int | None = None,
                        tail_edges=None):
    """The external-memory build: ``(seq uint32 [m], Forest over m)``,
    bit-identical to ``build_forest`` over the loaded file, with peak
    resident memory O(n + block) beyond the interpreter — the edge list
    itself never loads.

    ``seq`` — an externally given elimination order skips pass 1 (the
    ``-s`` case; the absent-vid pst contract holds: records naming vids
    outside the sequence count toward pst, never the tree).
    ``checkpoint_dir``/``resume`` — PR-1 snapshot machinery at block
    boundaries; ``resume`` restarts the stream at the checkpointed block
    (``iter_dat_blocks(start_edge=...)``), producing the bit-identical
    forest.  ``max_retries`` bounds in-process re-opens of the stream
    after a typed reader fault (EIO/ENOSPC mid-block — the
    ``SHEEP_IO_FAULT_PLAN`` ``dat`` site injects these): each retry
    resumes from the in-memory carry at the last completed block.
    ``perf`` gains blocks/read_s/fold_s/overlap_s/overlap_frac (realized
    read/fold overlap, same accounting as the windowed handoff) and the
    per-strategy pick counts.
    ``start_edge``/``end_edge`` — fold only the contiguous record slice
    ``[start_edge, end_edge)`` of the stream (ISSUE 13): one leg of the
    distributed out-of-core build.  The partial forests of a disjoint
    cover merge associatively to the whole-file forest (the property the
    tournament already carries); the slice is folded into the checkpoint
    identity so a leg's checkpoint can never resume under a different
    shard map.
    ``tail_edges`` — an optional ``(tail, head)`` uint32 pair folded as
    one final in-memory block AFTER the stream (ISSUE 18: the serve
    tier's WAL'd inserts riding the same fold as the ``.dat`` records —
    the re-sequence rebuild is "the offline build over .dat + log").
    The tail is folded into the checkpoint identity (count + crc), so a
    checkpoint can never resume under a different insert cut; the tail
    block itself is never checkpointed — a crash inside it resumes from
    the last STREAM boundary and refolds it, bit-identically by the
    associative-merge property.
    """
    t_start = time.perf_counter()
    events = events if events is not None else []
    gov = governor if governor is not None else ResourceGovernor.from_env()
    # under a budget the block auto-shrinks to the headroom (an explicit
    # arg or SHEEP_EXT_BLOCK pins it — it is part of the resume identity)
    block = block_edges or gov.ext_fitted_block()
    if seq is None:
        if (start_edge, end_edge) != (0, None):
            # a RANGE build always takes the shared whole-input sequence
            # (ops/distext.py's histogram merge): a sequence derived from
            # one shard's records would make the partial forests
            # unmergeable (different position spaces)
            raise ValueError(
                "a range build (start_edge/end_edge) needs an explicit "
                "seq — pass the shared whole-input sequence")
        seq, _, _ = streaming_degree_sequence(
            path, block, max_retries=max_retries,
            backoff_base_s=backoff_base_s, perf=perf)
    seq = np.asarray(seq, dtype=np.uint32)
    n = len(seq)
    if n == 0:
        return seq, Forest(np.empty(0, np.uint32), np.empty(0, np.uint32))
    # block size is part of the resume identity: boundary k means
    # "k * block_edges records folded", which only holds at this block.
    # A record slice is too: the same boundary in a different shard map
    # names different records, so the range folds into the signature.
    sig = input_signature(n, seq) + f"|ext:b{block}"
    if (start_edge, end_edge) != (0, None):
        sig += f"|range:{start_edge}:{end_edge}"
    if tail_edges is not None:
        import zlib
        t_t = np.ascontiguousarray(tail_edges[0], dtype=np.uint32)
        t_h = np.ascontiguousarray(tail_edges[1], dtype=np.uint32)
        tcrc = zlib.crc32(t_h.tobytes(), zlib.crc32(t_t.tobytes()))
        sig += f"|tail:{len(t_t)}:{tcrc:08x}"
    ckpt = Checkpointer(checkpoint_dir, checkpoint_every, governor=gov) \
        if checkpoint_dir else None
    fold = _ExtFold(n, sequence_positions(seq))
    done = 0
    if ckpt is not None and resume:
        try:
            snap = ckpt.load(integrity=integrity)
            if snap is not None:
                snap.verify(sig)
        except IntegrityError as exc:
            if resolve_policy(integrity) != "repair":
                raise
            events.append(("corrupt-checkpoint", "ext", str(exc)))
            snap = None
            ckpt.boundary = 0
        if snap is not None:
            fold.pst = snap.pst.astype(np.int64)
            fold.carry_lo = snap.lo.astype(np.int64)
            fold.carry_hi = snap.hi.astype(np.int64)
            # rebuild the carry's parent view (roots of the checkpointed
            # links); the links ARE the state, the parent is derived
            fold._absorb(build_forest_links(fold.carry_lo, fold.carry_hi,
                                            n, pst=fold._zero))
            done = snap.rounds
            events.append(("ext-resume", done))
    policy = RetryPolicy(max_retries=max_retries,
                         backoff_base_s=backoff_base_s)
    # fold_series accumulates through obs.trace.timed (one code path
    # with the windowed handoff); read_s is the prefetcher's producer
    # busy time, itself accumulated through the same helper
    stats = {"read_s": 0.0, "fold_series": [], "stream_s": 0.0}
    # progress is shared mutably with the attempt: on a mid-stream fault
    # the blocks folded BEFORE it must survive into the retry, or the
    # re-opened stream would refold them (parent is idempotent under a
    # replay, pst is not — it would double-count)
    progress = {"done": done}
    attempt = 0
    while True:
        try:
            _stream_fold(path, block, seq, sig, fold, progress, ckpt,
                         events, stats, start_edge, end_edge)
            break
        except OSError as exc:
            # a typed environmental reader fault (EIO/ENOSPC mid-stream):
            # the fold state at progress["done"] blocks is exact —
            # re-open the stream there instead of dying or restarting
            if attempt >= policy.max_retries:
                raise
            events.append(("ext-retry", attempt + 1, progress["done"],
                           f"{type(exc).__name__}: {exc}"))
            policy.sleep(policy.backoff(attempt))
            attempt += 1
    done = progress["done"]
    if tail_edges is not None and len(t_t):
        # the WAL'd tail, folded through the SAME carry-fold machinery
        # as the stream blocks (one more partial graph in the
        # associative merge); runs after every stream block so a resume
        # never double-folds it
        with obs.timed("ext.fold", out=stats["fold_series"],
                       block="tail", records=len(t_t)):
            strat = fold.fold_block(t_t, t_h)
        events.append(("ext-tail", len(t_t), strat))
    pst32 = fold.pst.astype(np.uint32)
    forest = Forest(fold.parent.copy(), pst32)
    if ckpt is not None:
        ckpt.clear()
    if perf is not None:
        wall = time.perf_counter() - t_start
        fold_s = sum(stats["fold_series"])
        native = native_or_none("auto")
        perf.update({
            "ext_blocks": done,
            "block_edges": block,
            "read_s": round(stats["read_s"], 4),
            "fold_s": round(fold_s, 4),
            # THE shared overlap accounting (obs.trace.overlap_stats):
            # read+fold serialized vs the stream's realized wall
            **obs.overlap_stats(stats["read_s"] + fold_s,
                                stats["stream_s"]),
            "wall_s": round(wall, 4),
            "strategies": dict(fold.strategies),
            "retries": attempt,
            # fold worker threads (round 14): >1 means each block folded
            # on parallel cores WHILE the prefetcher read ahead — the
            # fetch/fold overlap the 1-core records could only cap
            "threads": native.resolve_threads() if native is not None
            else 1,
        })
    return seq, forest


def _stream_fold(path: str, block: int, seq: np.ndarray, sig: str,
                 fold: _ExtFold, progress: dict,
                 ckpt: Checkpointer | None,
                 events: list, stats: dict,
                 start_edge: int = 0, end_edge: int | None = None) -> None:
    """One streaming attempt from block ``progress["done"]`` on, bumping
    it per folded block (in place, so a mid-stream fault keeps the
    completed prefix).  Reader faults (OSError) propagate to the
    caller's retry loop with the fold state intact — the prefetcher's
    producer thread re-raises them typed at the consumption point."""
    t0 = time.perf_counter()
    it = iter_dat_blocks(path, block,
                         start_edge=start_edge + progress["done"] * block,
                         end_edge=end_edge)
    with obs.span("ext.stream", start_block=progress["done"]), \
            BlockPrefetcher(it, depth=EXT_PREFETCH,
                            trace_name="ext.read") as pf:
        try:
            for tail, head in pf:
                with obs.timed("ext.fold", out=stats["fold_series"],
                               block=progress["done"], records=len(tail)):
                    strat = fold.fold_block(tail, head)
                done = progress["done"] = progress["done"] + 1
                events.append(("ext-block", done - 1,
                               len(fold.carry_lo), strat))
                if ckpt is not None:
                    if ckpt.want():
                        ckpt.save(Snapshot(
                            n=fold.n, seq=seq,
                            pst=fold.pst.astype(np.uint32),
                            lo=fold.carry_lo.astype(np.int32),
                            hi=fold.carry_hi.astype(np.int32),
                            rounds=done, boundary=0, rung="ext",
                            input_sig=sig))
                        events.append(("checkpoint", "ext",
                                       ckpt.boundary - 1))
                    else:
                        ckpt.skip()
                # the deterministic kill point: "died between blocks"
                fault_point("ext-boundary")
        finally:
            stats["read_s"] += pf.busy_s
            stats["stream_s"] += time.perf_counter() - t0
