"""Partition quality evaluator — vectorized.

Computes exactly the metrics of the reference's exhaustive evaluator
(lib/partition.cpp:428-521), but as dense segment/unique operations instead
of per-vertex hash-set scans ("evaluation is exhaustive, not efficient",
reference README:105 — here it is both):

  edges cut   undirected edges whose endpoints differ in part
  Vcom. vol   communication volume: per vertex, distinct neighbor parts
              beyond its own
  ECV(hash)   edge communication volume when each edge lives on the part of
              its hash-min endpoint (cormen_hash, partition.cpp:423-427)
  ECV(down)   edge CV under *downward* assignment — edge lives with its
              earlier-in-sequence endpoint (the paper's objective)
  ECV(up)     the reverse
  balances    max part load for each notion of load

Percentages follow the reference's printf quirk: the printed "(x%)" value is
the raw fraction of |E| (or of E/np, N/np for balances), not multiplied by
100 (partition.cpp:468-472,517-520).

The denominator |E| is the number of file records, matching LLAMA's
``getEdges()`` which includes self-loops ("XXX" note at partition.cpp:467).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

_CORMEN_MULT = np.uint64(2654435769)  # floor(0.5*(sqrt(5)-1) * 2^32)


def cormen_hash(k: np.ndarray) -> np.ndarray:
    return (k.astype(np.uint64) * _CORMEN_MULT & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def _nunique_pairs(x: np.ndarray, y: np.ndarray, y_card: int) -> int:
    key = x.astype(np.int64) * np.int64(y_card) + y.astype(np.int64)
    return len(np.unique(key))


@dataclass
class EvalReport:
    edges_cut: int
    vcom_vol: int
    ecv_hash: int
    ecv_down: int
    ecv_up: int
    vertex_balance: int
    hash_balance: int
    down_balance: int
    up_balance: int
    num_edges: int
    num_nodes: int
    num_parts: int

    def print(self, with_seq: bool = True) -> None:
        e = self.num_edges
        n = self.num_nodes
        np_ = max(self.num_parts, 1)
        # Balance denominators use truncating integer division like the
        # reference's size_t arithmetic (partition.cpp:470-472,518-520);
        # division by a zero denominator prints inf like C double division.
        div = lambda v, d: (v / d) if d else float("inf")
        print(f"edges cut: {self.edges_cut} ({div(self.edges_cut, e):f}%)")
        print(f"Vcom. vol: {self.vcom_vol} ({div(self.vcom_vol, e):f}%)")
        print(f"  balance: {self.vertex_balance} ({div(self.vertex_balance, n // np_):f}%)")
        print(f"ECV(hash): {self.ecv_hash} ({div(self.ecv_hash, e):f}%)")
        print(f"  balance: {self.hash_balance} ({div(self.hash_balance, e // np_):f}%)")
        if with_seq:
            print(f"ECV(down): {self.ecv_down} ({div(self.ecv_down, e):f}%)")
            print(f"  balance: {self.down_balance} ({div(self.down_balance, e // np_):f}%)")
            print(f"ECV(up)  : {self.ecv_up} ({div(self.ecv_up, e):f}%)")
            print(f"  balance: {self.up_balance} ({div(self.up_balance, e // np_):f}%)")


def evaluate_partition(parts: np.ndarray, tail: np.ndarray, head: np.ndarray,
                       seq: np.ndarray | None, num_parts: int,
                       max_vid: int | None = None,
                       file_edges: int | None = None) -> EvalReport:
    """``seq=None`` evaluates the sequence-free metrics only (the
    reference's evaluate(graph) overload, partition.cpp:428-473); the
    ECV(down)/(up) fields then come back zero — print with
    ``with_seq=False``."""
    from ..core.sequence import sequence_positions

    parts = parts.astype(np.int64)
    t = tail.astype(np.int64)
    h = head.astype(np.int64)
    E = file_edges if file_edges is not None else len(t)
    pos = None
    if seq is not None:
        pos = sequence_positions(seq, max_vid).astype(np.int64)

    deg_mask = np.zeros(len(parts), dtype=bool)
    deg_mask[t] = True
    deg_mask[h] = True
    n_active = int(deg_mask.sum())
    P = max(int(parts.max(initial=0)) + 1, 1)

    pt, ph = parts[t], parts[h]

    # edges cut: once per record, self-loops never differ
    edges_cut = int((pt != ph).sum())

    # directed-doubled views
    X = np.concatenate([t, h])
    Y = np.concatenate([h, t])
    pX = np.concatenate([pt, ph])
    pY = np.concatenate([ph, pt])

    # Vcom_vol: distinct (X, part[Y]) pairs, seeded with (X, part[X])
    active = np.nonzero(deg_mask)[0]
    vx = np.concatenate([X, active])
    vy = np.concatenate([pY, parts[active]])
    vcom = _nunique_pairs(vx, vy, P) - n_active

    # ECV(hash): per directed edge, part of the hash-smaller endpoint
    hX = cormen_hash(X.astype(np.uint32)).astype(np.int64)
    hY = cormen_hash(Y.astype(np.uint32)).astype(np.int64)
    hash_part = np.where(hX < hY, pX, pY)
    ecv_hash = _nunique_pairs(X, hash_part, P) - n_active
    # hash balance: once per undirected edge (the directed X<Y filter),
    # self-loops skipped; record orientation must not matter
    und = t != h
    a = np.minimum(t[und], h[und])
    b = np.maximum(t[und], h[und])
    ha = cormen_hash(a.astype(np.uint32)).astype(np.int64)
    hb = cormen_hash(b.astype(np.uint32)).astype(np.int64)
    und_hash_part = np.where(ha < hb, parts[a], parts[b])
    hash_balance = int(np.bincount(und_hash_part, minlength=P).max(initial=0))

    # ECV(down)/(up): part of the earlier/later-in-sequence endpoint
    ecv_down = ecv_up = down_balance = up_balance = 0
    if pos is not None:
        posX = pos[X]
        posY = pos[Y]
        down_part = np.where(posX < posY, pX, pY)
        up_part = np.where(posX > posY, pX, pY)
        ecv_down = _nunique_pairs(X, down_part, P) - n_active
        ecv_up = _nunique_pairs(X, up_part, P) - n_active
        down_balance = int(np.bincount(pX[posX < posY], minlength=P).max(initial=0))
        up_balance = int(np.bincount(pX[posX > posY], minlength=P).max(initial=0))

    vertex_balance = int(np.bincount(parts[active], minlength=P).max(initial=0))

    return EvalReport(
        edges_cut=edges_cut,
        vcom_vol=vcom,
        ecv_hash=ecv_hash,
        ecv_down=ecv_down,
        ecv_up=ecv_up,
        vertex_balance=vertex_balance,
        hash_balance=hash_balance,
        down_balance=down_balance,
        up_balance=up_balance,
        num_edges=E,
        num_nodes=n_active,
        num_parts=num_parts,
    )


def evaluate_partition_streamed(parts: np.ndarray, blocks_factory,
                                pos: np.ndarray | None, num_parts: int,
                                file_edges: int,
                                impl: str = "auto") -> EvalReport:
    """Exact evaluator in O(n) memory for graphs whose doubled key arrays
    would not fit in host RAM (the in-memory path peaks at ~50 GB for
    twitter-2010; reference anchor lib/partition.cpp:428-521).

    The distinct-(vertex, part) counts behind Vcom/ECV are computed with
    per-vertex part-set *bitmaps*: one uint64 per vertex covers a window of
    64 parts, edges stream through in blocks, and windows repeat for
    num_parts > 64 — ceil(P/64) passes over the edge stream, each O(n)
    memory.  Results are bit-identical to :func:`evaluate_partition`.

    ``blocks_factory``: zero-arg callable returning a fresh iterator of
    (tail, head) uint32 blocks (e.g. ``lambda: iter_dat_blocks(path, B)``).
    ``pos``: vid -> sequence position table, or None for the sequence-free
    overload.  ``parts`` must cover every vid in the stream.  ``impl``:
    auto|native|python — the per-block work runs in the C runtime when
    available (sheep_eval_block, ~4x at 1.476B edges), with the numpy
    body as the oracle/fallback.
    """
    parts = np.ascontiguousarray(parts, dtype=np.int64)
    n = len(parts)
    P = max(int(parts.max(initial=0)) + 1, 1)

    from ..core.forest import native_or_none
    native = native_or_none(impl)
    pos32 = None
    if pos is not None and native is not None:
        pos32 = np.ascontiguousarray(pos, dtype=np.uint32)

    deg_mask = np.zeros(n, dtype=np.uint8)
    edges_cut = 0
    part_loads = np.zeros(P, dtype=np.int64)          # vertex balance
    hash_loads = np.zeros(P, dtype=np.int64)          # undirected hash loads
    down_loads = np.zeros(P, dtype=np.int64)
    up_loads = np.zeros(P, dtype=np.int64)
    vcom = ecv_hash = ecv_down = ecv_up = 0

    for w0 in range(0, P, 64):
        first_window = w0 == 0
        m_vcom = np.zeros(n, dtype=np.uint64)
        m_hash = np.zeros(n, dtype=np.uint64)
        m_down = np.zeros(n, dtype=np.uint64) if pos is not None else None
        m_up = np.zeros(n, dtype=np.uint64) if pos is not None else None

        def scatter_bits(mask, X, p):
            sel = (p >= w0) & (p < w0 + 64)
            np.bitwise_or.at(mask, X[sel],
                             np.uint64(1) << (p[sel] - w0).astype(np.uint64))

        for tail, head in blocks_factory():
            if native is not None:
                # one C pass per block updates every window bitmap / load
                # counter in place — bit-identical to the numpy body
                # below, ~40x faster (np.bitwise_or.at is unbuffered)
                edges_cut += native.eval_block(
                    tail, head, parts, pos32, w0, first_window,
                    m_vcom, m_hash, m_down, m_up, deg_mask,
                    hash_loads, down_loads, up_loads, P)
                continue
            t = tail.astype(np.int64)
            h = head.astype(np.int64)
            pt, ph = parts[t], parts[h]
            if first_window:
                deg_mask[t] = 1
                deg_mask[h] = 1
                edges_cut += int((pt != ph).sum())

            for X, Y, pX, pY in ((t, h, pt, ph), (h, t, ph, pt)):
                scatter_bits(m_vcom, X, pY)
                hX = cormen_hash(X.astype(np.uint32)).astype(np.int64)
                hY = cormen_hash(Y.astype(np.uint32)).astype(np.int64)
                scatter_bits(m_hash, X, np.where(hX < hY, pX, pY))
                if pos is not None:
                    posX, posY = pos[X], pos[Y]
                    scatter_bits(m_down, X, np.where(posX < posY, pX, pY))
                    scatter_bits(m_up, X, np.where(posX > posY, pX, pY))

            if first_window:
                und = t != h
                a = np.minimum(t[und], h[und])
                b = np.maximum(t[und], h[und])
                ha = cormen_hash(a.astype(np.uint32)).astype(np.int64)
                hb = cormen_hash(b.astype(np.uint32)).astype(np.int64)
                hash_loads += np.bincount(
                    np.where(ha < hb, parts[a], parts[b]), minlength=P)
                if pos is not None:
                    post, posh = pos[t], pos[h]
                    down_loads += np.bincount(pt[post < posh], minlength=P)
                    up_loads += np.bincount(pt[post > posh], minlength=P)
                    down_loads += np.bincount(ph[posh < post], minlength=P)
                    up_loads += np.bincount(ph[posh > post], minlength=P)

        # Seed Vcom with each active vertex's own part (within this window).
        active = np.nonzero(deg_mask)[0]
        own = parts[active]
        sel = (own >= w0) & (own < w0 + 64)
        np.bitwise_or.at(m_vcom, active[sel],
                         np.uint64(1) << (own[sel] - w0).astype(np.uint64))

        vcom += int(np.bitwise_count(m_vcom).sum())
        ecv_hash += int(np.bitwise_count(m_hash).sum())
        if pos is not None:
            ecv_down += int(np.bitwise_count(m_down).sum())
            ecv_up += int(np.bitwise_count(m_up).sum())

    active = np.nonzero(deg_mask)[0]
    n_active = len(active)
    part_loads = np.bincount(parts[active], minlength=P)

    return EvalReport(
        edges_cut=edges_cut,
        vcom_vol=vcom - n_active,
        ecv_hash=ecv_hash - n_active,
        ecv_down=(ecv_down - n_active) if pos is not None else 0,
        ecv_up=(ecv_up - n_active) if pos is not None else 0,
        vertex_balance=int(part_loads.max(initial=0)),
        hash_balance=int(hash_loads.max(initial=0)),
        down_balance=int(down_loads.max(initial=0)),
        up_balance=int(up_loads.max(initial=0)),
        num_edges=file_edges,
        num_nodes=n_active,
        num_parts=num_parts,
    )
