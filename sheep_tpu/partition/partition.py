"""Vid-indexed partition container + writers.

Mirrors the reference's Partition surface (lib/partition.h:45-192): holds a
vid-indexed part array (INVALID_PART = -1 where a vid is absent from the
sequence), prints the "Actually created N partitions" summary
(partition.h:135-143), writes per-part edge files with downward edge
assignment (partition.cpp:588-681) and the isomorphic renumbered graph
(partition.cpp:528-586).
"""

from __future__ import annotations

import numpy as np

from .. import INVALID_PART
from ..core.forest import Forest
from ..core.sequence import sequence_positions
from ..io.edges import write_net, write_dat
from .tree_partition import TreePartitionOptions, partition_forest


class Partition:
    def __init__(self, parts: np.ndarray, num_parts: int):
        self.parts = parts.astype(np.int64)  # vid-indexed
        self.num_parts = int(num_parts)

    @classmethod
    def from_forest(cls, seq: np.ndarray, forest: Forest, num_parts: int,
                    opts: TreePartitionOptions | None = None,
                    strategy: str = "forward",
                    max_vid: int | None = None,
                    pre: np.ndarray | None = None) -> "Partition":
        jparts = partition_forest(forest, num_parts, opts, strategy, pre=pre)
        n = int(max_vid) + 1 if max_vid is not None else 0
        n = max(n, (int(seq.max()) + 1) if len(seq) else 0)
        vparts = np.full(n, INVALID_PART, dtype=np.int64)
        vparts[seq] = jparts
        return cls(vparts, num_parts)

    @classmethod
    def from_file(cls, seq: np.ndarray, filename: str) -> "Partition":
        """jnid-indexed parts file -> vid-indexed (lib/partition.h:55-65)."""
        jparts = np.loadtxt(filename, dtype=np.int64, ndmin=1)
        num_parts = int(jparts.max()) + 1
        n = (int(seq.max()) + 1) if len(seq) else 0
        vparts = np.full(n, INVALID_PART, dtype=np.int64)
        vparts[seq] = jparts[: len(seq)]
        return cls(vparts, num_parts)

    @property
    def max_part(self) -> int:
        return int(self.parts.max(initial=0))

    def print(self) -> None:
        print(f"Actually created {self.max_part + 1} partitions.")
        first = int((self.parts == 0).sum())
        second = int((self.parts == 1).sum())
        print(f"First two partition sizes: {first} and {second}")

    def write_partitioned_graph(self, tail: np.ndarray, head: np.ndarray,
                                seq: np.ndarray, output_prefix: str,
                                max_vid: int | None = None,
                                fmt: str = "net") -> list[str]:
        """Per-part edge files, edge -> part of its earlier-in-sequence
        endpoint (partition.cpp:623).  Edges written once, (min,max) vid
        orientation; self-loops skipped (directed-iteration X<Y filter,
        partition.cpp:616-617)."""
        assert self.max_part < 10000  # writer name format, partition.cpp:598
        pos = sequence_positions(seq, max_vid).astype(np.int64)
        a = np.minimum(tail, head).astype(np.int64)
        b = np.maximum(tail, head).astype(np.int64)
        keep = a != b
        a, b = a[keep], b[keep]
        down_is_a = pos[a] < pos[b]
        edge_part = np.where(down_is_a, self.parts[a], self.parts[b])
        paths = []
        writer = write_dat if fmt == "dat" else write_net
        for p in range(self.max_part + 1):
            sel = edge_part == p
            path = f"{output_prefix}{p:04d}"
            writer(path, a[sel].astype(np.uint32), b[sel].astype(np.uint32))
            paths.append(path)
        return paths

    def write_isomorphic_graph(self, tail: np.ndarray, head: np.ndarray,
                               seq: np.ndarray, output_filename: str,
                               max_vid: int | None = None) -> None:
        """Renumber so parts are contiguous in the new id space
        (partition.cpp:528-553): stable-sort seq by part, then write each
        undirected edge once as (new_x, new_y) with new_x < new_y."""
        order = np.argsort(self.parts[seq], kind="stable")
        new_seq = seq[order]
        pos = sequence_positions(new_seq, max_vid).astype(np.int64)
        pa = pos[tail.astype(np.int64)]
        pb = pos[head.astype(np.int64)]
        keep = pa != pb
        lo = np.minimum(pa[keep], pb[keep])
        hi = np.maximum(pa[keep], pb[keep])
        write_net(output_filename, lo.astype(np.uint32), hi.astype(np.uint32))
