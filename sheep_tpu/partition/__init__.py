from .tree_partition import (
    TreePartitionOptions,
    forward_partition,
    backward_partition,
    depth_partition,
    height_partition,
    naive_partition,
    random_partition,
    partition_forest,
    make_kids,
)
from .partition import Partition
from .evaluate import evaluate_partition, EvalReport

__all__ = [
    "TreePartitionOptions",
    "forward_partition",
    "backward_partition",
    "depth_partition",
    "height_partition",
    "naive_partition",
    "random_partition",
    "partition_forest",
    "make_kids",
    "Partition",
    "evaluate_partition",
    "EvalReport",
]
