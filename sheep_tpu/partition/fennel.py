"""Fennel streaming partitioners — the reference's competitor baselines.

``fennel_vertex`` is the in-memory vertex partitioner
(lib/partition.cpp:282-329 + ctor partition.h:68-77): greedy one-pass
placement maximizing (neighbors already in part) - a*((s+w)^y - (s)^y) with
y = 1.5; ``a`` follows the KDD'14 restreaming formula when edge-balanced
(weights = degree, capacity = 2|E|/k * balance) and the original FENNEL
formula when vertex-balanced.  Vertices stream in ascending-vid order (the
reference iterates the node iterator, not the sequence — the `seq` argument
is dead there too).  Ties choose the lowest part id; the scan stops at the
first empty part (all later parts are empty and identical); when no part
passes the hard capacity check the vertex lands in part 0, replicating the
reference's `max_part = 0` initialization.

``fennel_edges`` is the streaming *edge* partitioner prototype
(lib/partition.cpp:331-407): each edge record greedily joins the part its
endpoints already touch most.  Two evident slips in the prototype are
corrected here (intent per the paper; the reference's loop condition
`k != num_parts` never counted touches, and Y's touch bit was never set —
it wrote X's twice at :404-405); constants are parameters instead of the
hardcoded com-lj values at :336-339.
"""

from __future__ import annotations

import numpy as np

from .. import INVALID_PART


def _csr(tail: np.ndarray, head: np.ndarray, n: int):
    src = np.concatenate([tail, head]).astype(np.int64)
    dst = np.concatenate([head, tail]).astype(np.int64)
    order = np.argsort(src, kind="stable")
    dst = dst[order]
    offs = np.zeros(n + 1, dtype=np.int64)
    np.add.at(offs, src + 1, 1)
    np.cumsum(offs, out=offs)
    return offs, dst


def fennel_vertex(tail: np.ndarray, head: np.ndarray, num_parts: int,
                  balance_factor: float = 1.03,
                  edge_balanced: bool = True,
                  max_vid: int | None = None,
                  impl: str = "auto") -> np.ndarray:
    """vid-indexed parts (INVALID_PART where the vid has no edges).

    The python loop below is the semantics oracle; ``impl="auto"`` runs the
    C++ twin (sheep_native.cpp sheep_fennel_vertex) when built — the
    reference's competitor table runs on 34M-117M-edge graphs
    (data/runtimes/bipartition.time), far beyond an interpreter loop.
    """
    n_vid = int(max_vid) + 1 if max_vid is not None else (
        int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0)
    from ..core.forest import native_or_none
    native = native_or_none(impl)
    if native is not None and n_vid:
        return native.fennel_vertex(tail, head, n_vid, num_parts,
                                    balance_factor, edge_balanced)
    offs, dst = _csr(tail, head, n_vid)
    deg = np.diff(offs)
    active = deg > 0
    if len(tail) == 0 or not active.any():
        return np.full(n_vid, INVALID_PART, dtype=np.int64)
    n = float(active.sum())
    m = float(2 * len(tail))  # directed edge count
    k = float(num_parts)
    y = 1.5
    a = n * (k / m) ** y if edge_balanced else m * (k ** (y - 1.0) / n ** y)
    total_weight = 2 * len(tail) if edge_balanced else int(n)
    max_component = (total_weight // num_parts) * balance_factor

    parts = np.full(n_vid, INVALID_PART, dtype=np.int64)
    part_size = np.zeros(num_parts, dtype=np.float64)

    for X in np.nonzero(active)[0]:
        w = float(deg[X]) if edge_balanced else 1.0
        nbr_parts = parts[dst[offs[X]:offs[X + 1]]]
        nbr_parts = nbr_parts[nbr_parts != INVALID_PART]
        value = np.zeros(num_parts, dtype=np.float64)
        if len(nbr_parts):
            cnt = np.bincount(nbr_parts, minlength=num_parts)
            value += cnt[:num_parts]
        cost = a * ((part_size + w) ** y - part_size ** y)
        score = value - cost
        # consider parts [0..first_empty]; capacity-violating parts skipped
        empties = np.nonzero(part_size == 0.0)[0]
        last = int(empties[0]) if len(empties) else num_parts - 1
        score = score[: last + 1]
        ok = part_size[: last + 1] + w <= max_component
        if ok.any():
            masked = np.where(ok, score, -np.inf)
            best = int(np.argmax(masked))
        else:
            best = 0  # reference fallback: max_part initialized to 0
        parts[X] = best
        part_size[best] += w
    return parts


def fennel_edges(tail: np.ndarray, head: np.ndarray, num_parts: int,
                 balance_factor: float = 1.03,
                 max_vid: int | None = None,
                 impl: str = "auto") -> np.ndarray:
    """Per-edge-record parts (length == number of records).

    Python loop = oracle; ``impl="auto"`` dispatches to the C++ twin
    (sheep_native.cpp sheep_fennel_edges) when built.
    """
    n_vid = int(max_vid) + 1 if max_vid is not None else (
        int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0)
    e = len(tail)
    if e == 0:
        return np.empty(0, dtype=np.int64)
    from ..core.forest import native_or_none
    native = native_or_none(impl)
    if native is not None:
        return native.fennel_edges(tail, head, n_vid, num_parts,
                                   balance_factor)
    # active-vertex count, consistent with fennel_vertex (sparse vid spaces
    # would otherwise inflate n and weaken the balance penalty)
    deg = np.bincount(tail, minlength=n_vid) + np.bincount(head, minlength=n_vid)
    n = float(max(int((deg > 0).sum()), 1))
    m = float(2 * e)
    k = float(num_parts)
    y = 1.5
    a = m * (k ** (y - 1.0) / n ** y)
    max_component = (e // num_parts) * balance_factor

    eparts = np.full(e, INVALID_PART, dtype=np.int64)
    part_size = np.zeros(num_parts, dtype=np.float64)
    touches = np.zeros((n_vid, num_parts), dtype=bool)

    t = tail.astype(np.int64)
    h = head.astype(np.int64)
    for i in range(e):
        X, Y = t[i], h[i]
        value = touches[X].astype(np.float64) + touches[Y]
        cost = a * ((part_size + 1.0) ** y - part_size ** y)
        score = value - cost
        empties = np.nonzero(part_size == 0.0)[0]
        last = int(empties[0]) if len(empties) else num_parts - 1
        score = score[: last + 1]
        ok = part_size[: last + 1] + 1.0 <= max_component
        if ok.any():
            best = int(np.argmax(np.where(ok, score, -np.inf)))
        else:
            best = 0
        eparts[i] = best
        part_size[best] += 1.0
        touches[X, best] = True
        touches[Y, best] = True
    return eparts
