"""Tree partitioning algorithms (host, exact reference semantics).

``forward_partition`` is the paper algorithm (lib/partition.cpp:86-157): one
ascending pass accumulates the uncut component weight below each node; when a
node's component overflows ``max_component`` its kids' subtrees are first-fit-
decreasing bin-packed into parts; a descending pass then inherits parts from
parents and packs remaining roots (scanning bins from the most recently
opened, matching :146).  Bins (``part_size``) are global across the whole
pass.

Weight model (lib/partition.cpp:38-48): ``vtx_weight`` adds 1 per node,
``pst_weight`` adds the node's postorder edge count (the default,
partition_tree.cpp:95-96), ``pre_weight`` adds kids' preorder weights — the
reference only populates those under a non-default compile flag
(USE_PRE_WEIGHT, defs.h off by default), so here an optional ``pre`` array
may be supplied; absent, it contributes zero exactly like the reference's
default build.

Determinism note: the reference sorts kids by component weight with an
*unstable* ``std::sort`` (partition.cpp:104-106), so tie order — and
therefore exact part assignments — are implementation-defined there.  We use
a stable sort with ascending-jnid tie-break, making output deterministic;
quality metrics agree with the reference's published numbers (golden-tested
on hep-th).

These numpy/python loops are the semantics oracle; the C++ core
(native/) implements the same passes for large graphs.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import INVALID_JNID, INVALID_PART
from ..core.forest import Forest


@dataclass
class TreePartitionOptions:
    balance_factor: float = 1.03
    vtx_weight: bool = False
    pst_weight: bool = True
    pre_weight: bool = False


def node_weights(forest: Forest, opts: TreePartitionOptions,
                 pre: np.ndarray | None = None) -> np.ndarray:
    n = forest.n
    w = np.zeros(n, dtype=np.int64)
    if opts.vtx_weight:
        w += 1
    if opts.pst_weight:
        w += forest.pst_weight.astype(np.int64)
    if opts.pre_weight and pre is not None:
        # sum of kids' pre_weight == own pre contribution routed via parent
        kid_pre = np.zeros(n, dtype=np.int64)
        valid = forest.parent != INVALID_JNID
        np.add.at(kid_pre, forest.parent[valid].astype(np.int64),
                  pre[valid].astype(np.int64))
        w += kid_pre
    return w


def make_kids(parent: np.ndarray) -> list[np.ndarray]:
    """Kid lists in ascending-jnid order (lib/jnode.h:190-204 makeKids)."""
    n = len(parent)
    par = parent.astype(np.int64)
    par[parent == INVALID_JNID] = -1
    order = np.arange(n)
    valid = par >= 0
    kids: list[np.ndarray] = [np.empty(0, dtype=np.int64)] * n
    if valid.any():
        p = par[valid]
        k = order[valid]
        srt = np.argsort(p, kind="stable")  # groups by parent, kids ascending
        p, k = p[srt], k[srt]
        starts = np.searchsorted(p, np.arange(n), side="left")
        stops = np.searchsorted(p, np.arange(n), side="right")
        for i in range(n):
            if stops[i] > starts[i]:
                kids[i] = k[starts[i]:stops[i]]
    return kids


def forward_partition(forest: Forest, max_component: int,
                      weights: np.ndarray) -> np.ndarray:
    """The paper algorithm: ascending FFD pass + descending inheritance."""
    n = forest.n
    parent = forest.parent
    parts = np.full(n, INVALID_PART, dtype=np.int64)
    component_below = weights.astype(np.int64).copy()
    if n and int(weights.max()) > max_component:
        # The reference trips its live assert here (partition.cpp:114); in a
        # release build it would loop forever opening empty bins.  Fail fast:
        # a single node heavier than max_component can never be packed.
        raise ValueError(
            f"max_component {max_component} smaller than the heaviest node "
            f"({int(weights.max())}); request fewer partitions or a larger "
            f"balance factor")
    kids = make_kids(parent)
    part_size: list[int] = []

    for i in range(n):
        if component_below[i] > max_component:
            ks = kids[i]
            # descending component weight, stable (ascending jnid ties) —
            # matches the native runtime; the reference's unstable
            # std::sort leaves ties toolchain-defined (see the note in
            # sheep_native.cpp and scripts/quality_sweep.py).  Observed
            # magnitude of that toolchain freedom: hep-th ECV(down) at
            # parts=24 is 2723 here vs the reference log's 2720 — the
            # only row of the published 2..32 sweep that differs at all
            # (QUALITY_r03.json; SURVEY §7 predicted exactly this)
            ks = ks[np.argsort(-component_below[ks], kind="stable")]
            while component_below[i] > max_component:
                for kid in ks:
                    if component_below[i] <= max_component:
                        break
                    if parts[kid] != INVALID_PART:
                        continue
                    cb = component_below[kid]
                    for cur in range(len(part_size)):
                        if part_size[cur] + cb <= max_component:
                            component_below[i] -= cb
                            part_size[cur] += cb
                            parts[kid] = cur
                            break
                if component_below[i] > max_component:
                    part_size.append(0)
        p = parent[i]
        if p != INVALID_JNID:
            component_below[p] += component_below[i]

    # Descending pass: inherit from parent; pack roots from the last bin back.
    for i in range(n - 1, -1, -1):
        if parts[i] == INVALID_PART and parent[i] != INVALID_JNID:
            parts[i] = parts[parent[i]]
        while parts[i] == INVALID_PART:
            for cur in range(len(part_size) - 1, -1, -1):
                if part_size[cur] + component_below[i] <= max_component:
                    part_size[cur] += component_below[i]
                    parts[i] = cur
                    break
            if parts[i] == INVALID_PART:
                part_size.append(0)
    return parts


def backward_partition(forest: Forest, max_component: int,
                       weights: np.ndarray) -> np.ndarray:
    """Critical-path packing experiment (lib/partition.cpp:159-199)."""
    n = forest.n
    parent = forest.parent
    parts = np.full(n, INVALID_PART, dtype=np.int64)
    component_below = weights.astype(np.int64).copy()
    for i in range(n):
        p = parent[i]
        if p != INVALID_JNID:
            component_below[p] += component_below[i]

    kids = make_kids(parent)
    critical = int(np.argmax(component_below))
    while len(kids[critical]):
        ks = kids[critical]
        critical = int(ks[np.argmax(component_below[ks])])
        component_below[parent[critical]] -= component_below[critical]

    cur_part = 0
    size = 0
    c = critical
    while c != -1:
        if size + component_below[c] < max_component:
            parts[c] = cur_part
            size += component_below[c]
        else:
            cur_part += 1
            parts[c] = cur_part
            size = component_below[c]
        p = parent[c]
        c = int(p) if p != INVALID_JNID else -1

    for i in range(n - 1, -1, -1):
        if parts[i] == INVALID_PART:
            parts[i] = parts[parent[i]] if parent[i] != INVALID_JNID else cur_part
    return parts


def _chunked_by_order(order: np.ndarray, weights: np.ndarray,
                      max_component: int) -> np.ndarray:
    parts = np.empty(len(order), dtype=np.int64)
    cur_part = 0
    size = 0
    for idx in order:
        parts[idx] = cur_part
        size += int(weights[idx])
        if size >= max_component:
            cur_part += 1
            size = 0
    return parts


def depth_partition(forest: Forest, max_component: int,
                    weights: np.ndarray) -> np.ndarray:
    """Deepest-first chunking (lib/partition.cpp:202-225)."""
    n = forest.n
    parent = forest.parent
    depth = np.zeros(n, dtype=np.int64)
    for i in range(n - 1, -1, -1):
        if parent[i] != INVALID_JNID:
            depth[i] = depth[parent[i]] + 1
    order = np.argsort(-depth, kind="stable")
    return _chunked_by_order(order, weights, max_component)


def height_partition(forest: Forest, max_component: int,
                     weights: np.ndarray) -> np.ndarray:
    """Lowest-height-first chunking (lib/partition.cpp:228-251)."""
    n = forest.n
    parent = forest.parent
    height = np.zeros(n, dtype=np.int64)
    for i in range(n):
        p = parent[i]
        if p != INVALID_JNID and height[p] < height[i] + 1:
            height[p] = height[i] + 1
    order = np.argsort(height, kind="stable")
    return _chunked_by_order(order, weights, max_component)


def naive_partition(forest: Forest, max_component: int,
                    weights: np.ndarray) -> np.ndarray:
    """Sequence-order chunking (lib/partition.cpp:253-266)."""
    return _chunked_by_order(np.arange(forest.n), weights, max_component)


def random_partition(n: int, num_parts: int, seed: int | None = None) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.integers(0, num_parts, size=n).astype(np.int64)


_STRATEGIES = {
    "forward": forward_partition,
    "backward": backward_partition,
    "depth": depth_partition,
    "height": height_partition,
    "naive": naive_partition,
}


def partition_forest(forest: Forest, num_parts: int,
                     opts: TreePartitionOptions | None = None,
                     strategy: str = "forward",
                     pre: np.ndarray | None = None,
                     impl: str = "auto") -> np.ndarray:
    """jnid-indexed part assignment (lib/partition.cpp:50-61)."""
    opts = opts or TreePartitionOptions()
    weights = node_weights(forest, opts, pre)
    total = int(weights.sum())
    max_component = int((total // max(num_parts, 1)) * opts.balance_factor)
    if strategy == "forward":
        from ..core.forest import native_or_none
        native = native_or_none(impl)
        if native is not None:
            return native.forward_partition(
                forest.parent, weights, max_component).astype(np.int64)
    return _STRATEGIES[strategy](forest, max_component, weights)
