from .mesh import (AXIS, make_mesh, edge_sharding, replicated,
                   init_distributed)
from .build import (distributed_build_step, build_graph_distributed,
                    map_graph_distributed)
from .stream import build_graph_streaming_sharded
from .chunked import (build_graph_chunked_distributed,
                      build_graph_streaming_chunked,
                      build_links_chunked_sharded,
                      map_graph_chunked_distributed, reduce_links_sharded)

__all__ = [
    "build_graph_chunked_distributed",
    "build_graph_streaming_chunked",
    "build_links_chunked_sharded",
    "map_graph_chunked_distributed",
    "reduce_links_sharded",
    "AXIS",
    "make_mesh",
    "init_distributed",
    "edge_sharding",
    "replicated",
    "distributed_build_step",
    "build_graph_distributed",
    "map_graph_distributed",
    "build_graph_streaming_sharded",
]
