"""Mesh-sharded out-of-core streaming: OOM processing composed with the
device mesh.

The reference's OOM regime runs more partial loads than cores
(scripts/horizontal-dist.sh:22-24, data/oom/) — the graph fits no single
worker, so edge slices stream through while the associative merge stitches
them.  The multi-chip analog here: each host-DRAM edge block is itself
sharded over the 'workers' mesh axis, every worker maps its shard over the
shared sequence, the carry forest (replicated, two length-n arrays) re-enters
as links, the per-worker partial forests all_gather + rebuild associatively
(the per-block equivalent of the reference's mpi_merge custom op,
lib/jnode.cpp:203-250), and pst accumulates by psum.  Device-resident state
stays O(n + block/W) per worker for any edge count.

Like the in-jit merge in parallel.build, the while_loop fixpoint per block
is a correctness twin: the PRODUCTION mesh streaming path is
parallel.chunked.build_graph_streaming_chunked (bounded dispatches only —
the while_loop shape faults on real hardware past a wall-time budget), and
on the tunneled single-chip backend the hosted chunked driver (ops.stream
build_graph_streaming_hosted) is the single-device production path.  Both
twins are pinned equal by tests on random multigraphs and 2-process meshes.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import INVALID_JNID
from ..core.forest import Forest
from ..ops.forest import forest_fixpoint, links_from_parent
from ..ops.stream import _full_vid_pos
from ..utils.compat import shard_map
from .build import _gather_merge, _links_from_positions, _stage, _fetch
from .mesh import AXIS, make_mesh


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def stream_block_step_sharded(parent: jnp.ndarray, pst: jnp.ndarray,
                              tail: jnp.ndarray, head: jnp.ndarray,
                              pos: jnp.ndarray, n: int, mesh):
    """Fold one mesh-sharded edge block into the replicated carry forest.

    parent int32, pst uint32 [n] replicated (uint32 so the running
    accumulation honors the package-wide uint32 weight contract instead of
    wrapping negative at 2^31); tail/head int32 [B] sharded over
    'workers' (pad with values >= len(pos)-1); pos the _full_vid_pos table.
    Returns (parent, pst, rounds) replicated.
    """
    def body(parent, pst, t, h, posr):
        vid_cap = jnp.int32(posr.shape[0] - 1)
        blo, bhi, pst_local = _links_from_positions(
            posr[jnp.minimum(t, vid_cap)], posr[jnp.minimum(h, vid_cap)], n)
        # carry forest re-enters as its own links on every worker
        clo, chi = links_from_parent(parent, n)
        p_local, _ = forest_fixpoint(jnp.concatenate([clo, blo]),
                                     jnp.concatenate([chi, bhi]), n)
        # per-block associative merge of the partial forests (mpi_merge)
        new_parent, rounds = _gather_merge(p_local, n)
        # per-block delta is int32-safe (a block holds < 2^31 edges); the
        # running carry is uint32 so cumulative counts follow the uint32
        # weight contract rather than wrapping negative at 2^31
        return (new_parent,
                pst + lax.psum(pst_local, AXIS).astype(jnp.uint32),
                rounds)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(), P(), P(AXIS), P(AXIS), P()),
                   out_specs=(P(), P(), P()),
                   check_vma=False)
    return fn(parent, pst, tail, head, pos)


def build_graph_streaming_sharded(blocks, n: int, pos: np.ndarray,
                                  block_edges: int,
                                  num_workers: int | None = None):
    """OOM streaming over the mesh: same contract as
    ops.stream.build_graph_streaming, with every block sharded over the
    'workers' axis.  Returns (Forest over n positions, total_rounds).
    """
    mesh = make_mesh(num_workers)
    w = mesh.size
    block_pad = max(w, ((block_edges + w - 1) // w) * w)
    pos_d = _stage(_full_vid_pos(pos, n), mesh, P())
    vid_pad = len(pos)  # pad records map to the table's sentinel slot

    # staged replicated so the step is multi-process safe; the step's
    # replicated outputs feed back in as global arrays directly
    parent = _stage(np.full(n, n, dtype=np.int32), mesh, P())
    pst = _stage(np.zeros(n, dtype=np.uint32), mesh, P())
    round_counts = []
    for tail, head in blocks:
        b = len(tail)
        t = np.full(block_pad, vid_pad, dtype=np.int32)
        h = np.full(block_pad, vid_pad, dtype=np.int32)
        t[:b] = tail
        h[:b] = head
        parent, pst, rounds = stream_block_step_sharded(
            parent, pst, _stage(t, mesh, P(AXIS)), _stage(h, mesh, P(AXIS)),
            pos_d, n, mesh)
        round_counts.append(rounds)
    total_rounds = int(sum(int(_fetch(r)) for r in round_counts)) \
        if round_counts else 0
    parent_np = _fetch(parent).astype(np.int64)
    out = np.full(n, INVALID_JNID, dtype=np.uint32)
    live = parent_np < n
    out[live] = parent_np[live].astype(np.uint32)
    return Forest(out, np.asarray(_fetch(pst), dtype=np.uint32)), total_rounds
