"""Device mesh construction for the distributed build.

The reference's distribution unit is an MPI rank owning an edge-disjoint
partial graph (graph2tree.cpp:134-157).  Here the unit is a mesh axis
``'workers'``: edge records are sharded along it, the degree histogram is
psum-reduced across it (the MPI_Allreduce of lib/sequence.h:78), and the
partial forests merge with an all_gather + associative rebuild (the
MPI_Reduce custom op of lib/jnode.cpp:203-250).  Collectives ride ICI on a
real slice; multi-host meshes extend over DCN via ``jax.distributed`` with
the same code (XLA inserts the transport).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "workers"


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Join a multi-host mesh (the reference's `mpiexec` across nodes).

    Wraps ``jax.distributed.initialize``: with no arguments it relies on the
    cluster environment (TPU pods auto-detect; elsewhere set
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).  After
    this, ``jax.devices()`` spans every host and :func:`make_mesh` builds a
    global mesh whose collectives ride ICI within a slice and DCN across
    hosts — the same SPMD program, no code changes.
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(num_workers: int | None = None) -> Mesh:
    devices = jax.devices()
    if num_workers is None:
        num_workers = len(devices)
    if num_workers > len(devices):
        raise ValueError(
            f"requested {num_workers} workers but only {len(devices)} devices")
    return Mesh(devices[:num_workers], (AXIS,))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
