"""Device mesh construction for the distributed build.

The reference's distribution unit is an MPI rank owning an edge-disjoint
partial graph (graph2tree.cpp:134-157).  Here the unit is a mesh axis
``'workers'``: edge records are sharded along it, the degree histogram is
psum-reduced across it (the MPI_Allreduce of lib/sequence.h:78), and the
partial forests merge with an all_gather + associative rebuild (the
MPI_Reduce custom op of lib/jnode.cpp:203-250).  Collectives ride ICI on a
real slice; multi-host meshes extend over DCN via ``jax.distributed`` with
the same code (XLA inserts the transport).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "workers"


def init_distributed(coordinator_address: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None,
                     connect_timeout_s: float | None = None) -> None:
    """Join a multi-host mesh (the reference's `mpiexec` across nodes).

    Wraps ``jax.distributed.initialize``: with no arguments it relies on the
    cluster environment (TPU pods auto-detect; elsewhere set
    JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).  After
    this, ``jax.devices()`` spans every host and :func:`make_mesh` builds a
    global mesh whose collectives ride ICI within a slice and DCN across
    hosts — the same SPMD program, no code changes.

    ``connect_timeout_s`` bounds how long a worker waits for the
    coordinator (default: SHEEP_CONNECT_TIMEOUT env, else 300s — jax's
    own default).  An unreachable coordinator then raises a RuntimeError
    naming the address instead of hanging the process until some outer
    harness (pytest, SLURM) kills it — the failure a misconfigured
    launcher actually produces.
    """
    import os

    if connect_timeout_s is None:
        connect_timeout_s = float(os.environ.get("SHEEP_CONNECT_TIMEOUT",
                                                 "300"))
    if coordinator_address and process_id not in (None, 0):
        # Pre-probe the coordinator from worker processes: some jax
        # releases LOG(FATAL) (SIGABRT, no Python traceback) when the
        # coordination handshake times out, so an unreachable address
        # must be caught BEFORE handing control to the C++ client.
        # Process 0 hosts the service itself and is exempt.
        _probe_coordinator(coordinator_address, connect_timeout_s,
                           process_id, num_processes)
    kw = dict(coordinator_address=coordinator_address,
              num_processes=num_processes, process_id=process_id)
    try:
        jax.distributed.initialize(
            initialization_timeout=int(connect_timeout_s), **kw)
    except TypeError:  # pragma: no cover - very old jax: no timeout knob
        jax.distributed.initialize(**kw)
    except Exception as exc:
        addr = coordinator_address or \
            os.environ.get("JAX_COORDINATOR_ADDRESS", "<auto>")
        raise RuntimeError(
            f"could not join the jax.distributed coordinator at {addr} "
            f"(process {process_id}/{num_processes}, waited up to "
            f"{connect_timeout_s:.0f}s): {exc}") from exc


def _probe_coordinator(address: str, timeout_s: float,
                       process_id, num_processes) -> None:
    """Retry a plain TCP connect to ``address`` until it accepts or
    ``timeout_s`` elapses; raise a RuntimeError naming the address on
    failure.  The coordinator may legitimately come up AFTER its workers
    (launchers start all ranks at once), hence the retry loop rather than
    a single attempt."""
    import socket
    import time

    host, _, port_s = address.rpartition(":")
    try:
        port = int(port_s)
    except ValueError:
        raise RuntimeError(
            f"malformed coordinator address {address!r} "
            "(want host:port)") from None
    deadline = time.monotonic() + timeout_s
    last: Exception | None = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise RuntimeError(
                f"could not join the jax.distributed coordinator at "
                f"{address} (process {process_id}/{num_processes}, waited "
                f"up to {timeout_s:.0f}s): {last}") from last
        try:
            with socket.create_connection((host or "127.0.0.1", port),
                                          timeout=min(5.0, remaining)):
                return
        except OSError as exc:
            last = exc
            time.sleep(min(0.2, max(0.0, deadline - time.monotonic())))


def make_mesh(num_workers: int | None = None) -> Mesh:
    devices = jax.devices()
    if num_workers is None:
        num_workers = len(devices)
    if num_workers > len(devices):
        raise ValueError(
            f"requested {num_workers} workers but only {len(devices)} devices")
    return Mesh(devices[:num_workers], (AXIS,))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
