"""Mesh-sharded chunked build: the bounded-execution driver composed with
the device mesh.

Why this module exists (round-3 hardware evidence, PERF_NOTES.md): on real
TPU hardware a data-dependent ``lax.while_loop`` faults once its wall time
outgrows the backend's per-execution budget, so the production single-chip
path is the host-orchestrated chunked driver (ops/forest.py
``reduce_links_hosted``: J rounds per dispatch via ``fori_loop``, host sync
+ compaction between dispatches).  The first-generation mesh path
(parallel/build.py) still ran the while_loop *inside* ``shard_map`` — the
exact shape that faulted.  This module is the mesh analog of the chunked
driver: every device dispatch is a bounded ``fori_loop`` under ``shard_map``,
and the host loop reads one replicated stats vector per chunk.

Two round flavors compose the reference's map/reduce split
(SURVEY §2.6, lib/jnode.cpp:203-250):

  local rounds  (map)   — each worker reduces its own edge shard's links
                          with zero per-round communication: sort + star->
                          chain rewrite + jump against the LOCAL min-up
                          table.  Converged shards hold per-worker partial
                          forests over the shared sequence — exactly the
                          reference's per-rank JTree build.
  global rounds (reduce)— same transform but the jump table is the GLOBAL
                          min-up-neighbor: per-shard scatter-min tables
                          combined with ``lax.pmin`` over the axis (one
                          [n+1] all-reduce per round, the mpi_merge
                          analog).  Soundness: the threshold-connectivity
                          argument of ops/forest.py only needs each f-edge
                          to exist SOMEWHERE in the global multiset, so
                          jumping any shard's lo through the global f
                          preserves global threshold connectivity; local
                          sort/rewrite is a per-subset transform and was
                          already sound.  At global fixpoint every live
                          link (lo, hi) has f(lo) == hi, i.e. the union of
                          shards is one functional forest — the answer.

Termination is unchanged: every applied rewrite strictly increases some lo
bounded by n, so both phases converge; chunking only bounds how much runs
per dispatch.  Compaction slices the LOCAL axis of the [W, B] link arrays
(per-row sort guarantees each row's live prefix), so shards shrink in
lockstep to the pmax of per-row live counts.

Round 5 adds the **gather-tail** (reduce_links_sharded docstring): global
rounds pay one [n+1] pmin each, but the measured dense trajectory does
its mass-kill in ~3 rounds and then spends 20+ rounds collapsing chains
on a plateaued live window — so once the whole window is cheaper to move
than a few more table reduces, the links all_gather ONCE and the tail
runs replicated through the single-chip chunk loop (depth tiers +
vremap_compact vertex windowing), with zero further collectives.  That
cuts per-build collective payload ~4-7x at W=8 (MESHBENCH_r05) and is
the mesh analog of both the reference's single MPI_Reduce
(lib/jnode.cpp:228-241) and the hybrid's handoff philosophy.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.forest import (_CHUNK_SCHEDULE as _SCHEDULE, _depth_tier,
                          _lift_descend, _rewrite_sorted, pst_weights,
                          sort_links)
from ..ops.sort import degree_order
from .mesh import AXIS, make_mesh

_JROUNDS = 8
_LEVELS = 10
_FIRST_LEVELS = 4


def _row_round(lo, hi, n: int, levels: int, f_combine):
    """One chunk round on a worker's local [B] link row.

    ``f_combine``: identity for local (map) rounds, ``lax.pmin`` over the
    workers axis for global (reduce) rounds.  Returns (lo, hi, moved, live).
    """
    sent = jnp.int32(n)
    lo, hi = sort_links(lo, hi)
    live = jnp.sum(lo != sent, dtype=jnp.int32)
    lo, hi, rewrites = _rewrite_sorted(lo, hi, n)
    # one-step min-up table, combined across the mesh BEFORE lifting so
    # every worker lifts the same (global, for reduce rounds) f; the
    # shared descent carries the Pallas fast-path gate
    f = jnp.full(n + 1, sent, jnp.int32).at[lo].min(hi)
    f = f_combine(f)
    lo, jumped = _lift_descend(lo, hi, n, levels, f)
    return lo, hi, rewrites + jumped, live


@functools.partial(jax.jit,
                   static_argnames=("n", "mesh", "levels", "jrounds",
                                    "global_f"))
def chunk_sharded(lo, hi, n: int, mesh, levels: int, jrounds: int,
                  global_f: bool):
    """``jrounds`` bounded rounds on [W, B] sharded links in ONE dispatch.

    Returns (lo, hi, stats) with stats int32 [2] = (moved_total,
    live_max_per_row) replicated — one host fetch per chunk, matching the
    single-sync contract of ops.forest.fixpoint_chunk.
    """
    def body(lo, hi):
        lo = lo[0]  # [1, B] local block -> [B]
        hi = hi[0]
        combine = (lambda f: lax.pmin(f, AXIS)) if global_f \
            else (lambda f: f)

        def one(_, st):
            lo, hi, _, _ = st
            return _row_round(lo, hi, n, levels, combine)

        st = (lo.astype(jnp.int32), hi.astype(jnp.int32),
              jnp.int32(0), jnp.int32(lo.shape[0]))
        lo, hi, moved, live = lax.fori_loop(0, jrounds, one, st)
        stats = jnp.stack([lax.psum(moved, AXIS), lax.pmax(live, AXIS)])
        return lo[None, :], hi[None, :], stats

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(AXIS, None), P(AXIS, None)),
                   out_specs=(P(AXIS, None), P(AXIS, None), P()),
                   check_vma=False)
    return fn(lo, hi)


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def parent_sharded_local(lo, hi, n: int, mesh):
    """Per-shard parent extraction from per-shard converged links: [W, n]
    stacked, NO cross-worker combine — each row is that worker's partial
    forest over the shared sequence (the `-i`-without-`-r` map phase)."""
    def body(lo, hi):
        sent = jnp.int32(n)
        p = jnp.full(n + 1, sent, jnp.int32).at[
            lo[0].astype(jnp.int32)].min(hi[0].astype(jnp.int32))[:n]
        return p[None, :]

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(AXIS, None), P(AXIS, None)),
                   out_specs=P(AXIS, None), check_vma=False)
    return fn(lo, hi)


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def parent_sharded(lo, hi, n: int, mesh):
    """Global parent extraction from converged sharded links: per-shard
    scatter-min pmin-combined (valid once the union forms a forest)."""
    def body(lo, hi):
        sent = jnp.int32(n)
        p = jnp.full(n + 1, sent, jnp.int32).at[
            lo[0].astype(jnp.int32)].min(hi[0].astype(jnp.int32))
        return lax.pmin(p, AXIS)[:n]

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(AXIS, None), P(AXIS, None)),
                   out_specs=P(), check_vma=False)
    return fn(lo, hi)


@functools.partial(jax.jit, static_argnames=("n", "mesh", "with_pos",
                                              "local_pst"))
def prep_sharded(tail, head, n: int, mesh, pos=None, with_pos: bool = False,
                 local_pst: bool = False):
    """Degree sort + link mapping over the mesh (the `-i` phase).

    tail/head int32 [W, B] sharded (pad with n).  Returns (seq, pos, m,
    lo [W, B], hi [W, B], pst) with everything but lo/hi replicated.
    Matches parallel.build._sharded_build's sequence/pst semantics.
    ``local_pst``: keep pst per-worker ([W, n] stacked, each row counting
    only that shard's edges) for the map-only partials path instead of
    the psum-combined total.
    """
    def body(t, h, posr):
        sent = jnp.int32(n)
        t = t[0].astype(jnp.int32)
        h = h[0].astype(jnp.int32)
        if posr is None:
            deg_local = jnp.zeros(n + 1, jnp.int32).at[t].add(1).at[h].add(1)
            deg = lax.psum(deg_local, AXIS)[:n]
            seq, pos_r, m = degree_order(deg)
        else:
            posi = posr.astype(jnp.int32)
            absent = (posi < 0) | (posi >= n)
            pos_r = jnp.where(absent, sent, posi)
            seq = jnp.full(n, sent, jnp.int32)
            vids = jnp.arange(n, dtype=jnp.int32)
            seq = seq.at[jnp.where(absent, n, pos_r)].set(vids, mode="drop")
            m = jnp.int32(n) - jnp.sum(absent, dtype=jnp.int32)
        pos_ext = jnp.concatenate([pos_r, jnp.full((1,), sent, jnp.int32)])
        pt = pos_ext[jnp.minimum(t, jnp.int32(n))]
        ph = pos_ext[jnp.minimum(h, jnp.int32(n))]
        lo = jnp.minimum(pt, ph)
        hi = jnp.maximum(pt, ph)
        # pst counts every edge at its present earlier endpoint, including
        # edges to absent vids (jtree.cpp:47-49); self/pad (lo==hi) never
        pst_local = pst_weights(jnp.where(lo == hi, sent, lo), n)
        dead = (lo >= hi) | (hi >= sent)
        lo = jnp.where(dead, sent, lo)
        hi = jnp.where(dead, sent, hi)
        if local_pst:
            return (seq, pos_r, m, lo[None, :], hi[None, :],
                    pst_local[None, :])
        return (seq, pos_r, m, lo[None, :], hi[None, :],
                lax.psum(pst_local, AXIS))

    pst_spec = P(AXIS, None) if local_pst else P()
    if with_pos:
        fn = shard_map(lambda t, h, p: body(t, h, p), mesh=mesh,
                       in_specs=(P(AXIS, None), P(AXIS, None), P()),
                       out_specs=(P(), P(), P(), P(AXIS, None),
                                  P(AXIS, None), pst_spec),
                       check_vma=False)
        return fn(tail, head, pos)
    fn = shard_map(lambda t, h: body(t, h, None), mesh=mesh,
                   in_specs=(P(AXIS, None), P(AXIS, None)),
                   out_specs=(P(), P(), P(), P(AXIS, None),
                              P(AXIS, None), pst_spec),
                   check_vma=False)
    return fn(tail, head)


def _pad_pow2_cols(x: int, lo_cap: int = 1 << 10) -> int:
    p = lo_cap
    while p < x:
        p <<= 1
    return p


@functools.partial(jax.jit, static_argnames=("mesh",))
def gather_links_replicated(lo, hi, mesh):
    """One all_gather of the live link window: [W, B] sharded -> flat
    [W*B] replicated.  The single collective that hands the reduce TAIL
    off the mesh (see reduce_links_sharded's gather-tail)."""
    def body(lo, hi):
        l = lax.all_gather(lo[0], AXIS)
        h = lax.all_gather(hi[0], AXIS)
        return l.reshape(-1), h.reshape(-1)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(AXIS, None), P(AXIS, None)),
                   out_specs=(P(), P()), check_vma=False)
    return fn(lo, hi)


def _gather_tail_enabled(override: bool | None) -> bool:
    import os
    if override is not None:
        return override
    return os.environ.get("SHEEP_MESH_GATHER_TAIL", "1") != "0"


def _tail_shard_enabled(override: bool | None) -> bool:
    """Round-6 sharded tail gate (SHEEP_MESH_TAIL_SHARD, default on):
    see reduce_links_sharded — the round-5 gather-tail made the plateau
    collective-free but REPLICATED, so W-1 chips re-derived the same
    chain collapse; the sharded tail splits that work by vertex window
    so per-chip tail work falls with W."""
    import os
    if override is not None:
        return override
    return os.environ.get("SHEEP_MESH_TAIL_SHARD", "1") != "0"


def hi_window_bounds(sorted_hi, cnt, w: int, sent):
    """Equal-count hi-QUANTILE window boundaries: ``[w + 1]`` int32 value
    bounds over one hi-sorted array (``cnt`` live entries; sentinels
    ``== sent`` sort last), so window k keeps the links whose hi falls in
    ``[bounds[k], bounds[k+1])`` — ~cnt/w links each up to hub ties.

    THE windowing rule, shared by the mesh sharded tail
    (:func:`shard_links_by_window`) and the hybrid's streaming windowed
    handoff (ops.build), so the two partitions cannot drift.  Value
    quantiles, not equal-width spans: equal width was measured badly
    skewed on power-law inputs (70% of live links in one window at W=8).
    """
    dt = sorted_hi.dtype
    if w > 1:
        ks = (jnp.arange(1, w, dtype=jnp.int32) * cnt) // jnp.int32(w)
        mid = sorted_hi[ks]
    else:
        mid = jnp.zeros((0,), dt)
    return jnp.concatenate([jnp.zeros((1,), dt), mid,
                            jnp.full((1,), sent, dt)])


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def shard_links_by_window(lo, hi, n: int, mesh):
    """Replicated flat links -> [W, B] sharded by CONTIGUOUS hi window.

    Window boundaries are the live-count QUANTILES of the hi
    distribution (one replicated sort; deterministic, so every worker
    derives identical boundaries with zero communication): worker i
    keeps the links whose hi falls in [q_i, q_{i+1}) with q_0 = 0 and
    q_W = n, i.e. ~live/W links each.  Equal-width windows were
    measured badly skewed on power-law graphs (70% of the live links on
    one chip at W=8 — the plateau window concentrates in the middle of
    the position space); value-quantiles balance up to hub ties, and a
    single heavy hi is a STAR, which one local sort-rewrite collapses
    anyway.  Soundness is the map-phase argument: local rounds are
    per-subset transforms, and ANY partition of the multiset preserves
    union threshold connectivity.  Windows are contiguous ON PURPOSE:
    chains ascend through positions, so a contiguous window keeps each
    chain segment whole on one worker where local rounds can collapse
    it; a modulo shard would scatter every chain and leave the local
    phase nothing to do.
    """
    w = mesh.size

    def body(lo, hi):
        i = lax.axis_index(AXIS).astype(jnp.int32)
        sent = jnp.int32(n)
        live = lo < sent
        cnt = jnp.sum(live, dtype=jnp.int32)
        sh = lax.sort(hi)  # sentinels (= n) sort last
        bounds = hi_window_bounds(sh, cnt, w, sent)
        lower = bounds[i]
        upper = bounds[i + 1]
        mine = live & (hi >= lower) & (hi < upper)
        return (jnp.where(mine, lo, sent)[None, :],
                jnp.where(mine, hi, sent)[None, :])

    fn = shard_map(body, mesh=mesh, in_specs=(P(), P()),
                   out_specs=(P(AXIS, None), P(AXIS, None)),
                   check_vma=False)
    return fn(lo, hi)


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def row_live_counts(lo, n: int, mesh):
    """Replicated [W] vector of per-row live-link counts (the sharded
    tail's per-chip work observability; measurement path only)."""
    def body(lo):
        c = jnp.sum(lo[0] != jnp.int32(n), dtype=jnp.int32)
        return lax.all_gather(c, AXIS)

    fn = shard_map(body, mesh=mesh, in_specs=(P(AXIS, None),),
                   out_specs=P(), check_vma=False)
    return fn(lo)


def _tail_shard_local_rounds() -> int:
    """Round cap for the sharded tail's local pass
    (SHEEP_MESH_TAIL_SHARD_ROUNDS, default 5 — the chunk schedule's
    probing prefix, where the mass dedupe/star-collapse lands): past it
    the marginal local round retires little (the window's own straggler
    crawl), while the replicated finish pays ~finish_live * round for
    EVERY extra round it has to grind — the 2^18 model measured cap 13
    costing W=2 more per-chip work than no shard at all, and cap 5
    strictly decreasing across W=2/4/8."""
    import os
    return int(os.environ.get("SHEEP_MESH_TAIL_SHARD_ROUNDS", "5"))


def _gather_tail_factor() -> float:
    """Gather when W * cols <= factor * (n+1).  Default 2.0: the gather
    moves 8 * W * cols bytes, i.e. <= 4 pmin-round payloads at the
    threshold — and the measured dense trajectory (2^13-2^18 traces)
    pays ~3 sharded rounds to mass-kill and then 20+ plateau rounds
    that the gather-tail makes collective-free.  Row padding makes the
    plateau window ~2(n+1), so factor 1.0 would never fire densely."""
    import os
    return float(os.environ.get("SHEEP_MESH_GATHER_FACTOR", "2.0"))


def reduce_links_sharded(lo, hi, n: int, mesh, global_f: bool,
                         levels: int = _LEVELS, jrounds: int = _JROUNDS,
                         first_levels: int = _FIRST_LEVELS,
                         fetch=None, gather_tail: bool | None = None,
                         tail_shard: bool | None = None,
                         comm: dict | None = None, runtime=None,
                         max_rounds: int | None = None):
    """Host-orchestrated chunk loop on [W, B] sharded links.

    ``global_f`` False = map phase (per-shard independent), True = reduce
    phase (per-round pmin of the jump table).  Returns (lo, hi, rounds,
    replicated) — replicated False: [W, B] sharded with per-row live
    prefixes; True: flat replicated arrays (the gather-tail fired).
    ``fetch``: replicated-array -> numpy (multi-process safe override;
    default np.asarray).

    **Gather-tail (round-5, VERDICT r04 item 4 — the ICI-honest reduce).**
    A global round costs one [n+1] int32 all-reduce (4(n+1) bytes of
    pmin payload per worker per round) no matter how few links remain,
    and most global rounds run AFTER the early mass-kill has collapsed
    the live set — the round-4 design paid ~30 full-table collectives
    per build where the reference pays one MPI_Reduce total
    (lib/jnode.cpp:228-241).  So once the whole live window is cheaper
    to move than ~SHEEP_MESH_GATHER_FACTOR more pmin rounds
    (W * cols <= factor * (n+1), i.e. one 8*W*cols-byte all_gather vs
    8(n+1) bytes for a round-trip-equivalent of table reduces), the
    links all_gather ONCE into replicated arrays and the tail runs
    through the single-chip chunk loop (ops.forest.reduce_links_hosted)
    with ZERO further collectives — executed SPMD-replicated, so every
    worker deterministically holds the identical result, and the tail
    inherits the single-chip kit: depth-tier escalation, vremap_compact,
    and the round-6 plateau scheduler + straggler assist.
    Soundness: the gathered multiset is exactly the union of shard link
    sets — the same global threshold connectivity — and the forest is a
    function of threshold connectivity only.  SHEEP_MESH_GATHER_TAIL=0
    (or gather_tail=False) restores the round-4 behavior.  The gather
    never fires before the first sharded chunk has run (round-6 fix,
    ADVICE r05): a sparse input whose whole window already fits the
    gather budget would otherwise bypass the mesh at round 0 and run
    the ENTIRE reduce replicated on every worker.

    **Sharded tail (round-6, VERDICT r05 item 3).**  The round-5 tail
    was replicated: W-1 chips re-derived the identical plateau chain
    collapse, so per-chip tail work was CONSTANT in W — the builder's
    own scaling model capped W=8 at ~2% of north star.  With
    SHEEP_MESH_TAIL_SHARD (default on; tail_shard overrides), the
    gathered links are re-sharded by CONTIGUOUS hi vertex window
    (:func:`shard_links_by_window` — chain segments stay whole on one
    worker), each worker collapses its window's segments with LOCAL
    rounds (zero inter-chip collectives, the map-phase machinery), and
    only the converged per-window forests — a far smaller union whose
    vertices hold at most one up-link per window — re-gather for the
    replicated finish.  Per-chip tail work becomes
    O(live/W * local_rounds) + O(union) instead of O(live * rounds),
    strictly decreasing with W (measured columns in MESHBENCH).

    ``comm`` — optional dict accumulating the collective-volume model
    (per-worker logical payload bytes): sharded_global_rounds,
    pmin_payload_bytes (4(n+1) per global round), gather_payload_bytes
    (8*W*cols summed over BOTH gathers when the tail shards),
    tail_rounds (replicated, collective-free), plus the sharded-tail
    observability columns: tail_shard_rounds (local window rounds),
    tail_shard_row_live (per-chip live at the shard handoff),
    tail_gather_live / tail_finish_live (live counts entering the
    shard phase and the replicated finish).

    ``runtime`` — optional runtime.ChunkRuntime (see
    ops/forest.reduce_links_hosted): each sharded dispatch runs under the
    retry/backoff policy (halving jrounds on a fault), and — for global-f
    (reduce) phases — each chunk boundary checkpoints the link multiset
    via one all_gather (multi-process safe; the flat union of shard links
    is the complete, rung-portable build state).  Map phases (global_f
    False) get retries but no checkpoints: their per-worker partials are
    not a single multiset.  The gather-tail inherits the same runtime, so
    checkpointing continues seamlessly once the tail goes replicated.
    """
    fetch = fetch or np.asarray
    cols0 = int(lo.shape[1])
    if cols0 == 0:
        return lo, hi, 0, False
    w = mesh.size
    rounds = 0
    chunk_i = 0
    cap = int(np.ceil(np.log2(n + 2)))
    do_gather = global_f and _gather_tail_enabled(gather_tail)
    do_shard = _tail_shard_enabled(tail_shard) and w > 1
    gather_at = _gather_tail_factor() * (n + 1)
    if comm is not None:
        comm.setdefault("sharded_global_rounds", 0)
        comm.setdefault("pmin_payload_bytes", 0)
        comm.setdefault("gather_payload_bytes", 0)
        comm.setdefault("tail_rounds", 0)
        comm.setdefault("tail_shard_rounds", 0)

    def _finish_hosted(flat_lo, flat_hi, rounds):
        """Replicated single-chip finish of the gathered union."""
        from ..ops.forest import reduce_links_hosted
        flat_lo, flat_hi, _, tail_rounds, _ = reduce_links_hosted(
            flat_lo, flat_hi, n, levels=levels, jrounds=jrounds,
            first_levels=first_levels, runtime=runtime)
        if comm is not None:
            comm["tail_rounds"] += tail_rounds
        return flat_lo, flat_hi, rounds + tail_rounds, True

    while True:
        cols = int(lo.shape[1])
        # round-0 bypass guard (chunk_i >= 1): the tail rationale only
        # applies AFTER the mass-kill — see the docstring
        if do_gather and chunk_i >= 1 and w * cols <= gather_at:
            flat_lo, flat_hi = gather_links_replicated(lo, hi, mesh)
            if comm is not None:
                comm["gather_payload_bytes"] += 8 * w * cols
                comm["tail_gather_live"] = int(fetch(jnp.sum(
                    flat_lo != jnp.int32(n), dtype=jnp.int32)))
            if not do_shard:
                return _finish_hosted(flat_lo, flat_hi, rounds)
            # sharded tail: window the union, collapse each window's
            # chain segments locally (zero collectives), then gather
            # the much smaller per-window forests for the finish
            slo, shi = shard_links_by_window(flat_lo, flat_hi, n, mesh)
            if comm is not None:
                rl = [int(x) for x in fetch(row_live_counts(slo, n, mesh))]
                comm["tail_shard_row_live"] = rl
            # local rounds are capped: the cheap parallel work (star
            # collapse + short segments) lands in the first ~dozen
            # rounds; a window's long-chain crawl is exactly what the
            # replicated finish's plateau assist resolves best, so past
            # the cap the remaining links just move on
            slo, shi, local_rounds, _ = reduce_links_sharded(
                slo, shi, n, mesh, global_f=False, levels=levels,
                jrounds=jrounds, first_levels=first_levels, fetch=fetch,
                runtime=runtime, max_rounds=_tail_shard_local_rounds())
            rounds += local_rounds
            if comm is not None:
                comm["tail_shard_rounds"] += local_rounds
            fcols = int(slo.shape[1])
            flat_lo, flat_hi = gather_links_replicated(slo, shi, mesh)
            if comm is not None:
                comm["gather_payload_bytes"] += 8 * w * fcols
                comm["tail_finish_live"] = int(fetch(jnp.sum(
                    flat_lo != jnp.int32(n), dtype=jnp.int32)))
            return _finish_hosted(flat_lo, flat_hi, rounds)
        j = _SCHEDULE[chunk_i] if chunk_i < len(_SCHEDULE) else jrounds
        if max_rounds is not None:
            j = max(1, min(j, max_rounds - rounds))
        if global_f:
            # reduce rounds: flat base depth — the MESHBENCH rerun
            # measured the deep tier consistently 8-10% WORSE here with
            # unchanged round counts (deeper tables add gather cost but
            # merge chains are short enough that rounds don't drop)
            lv = min(levels, cap)
        else:
            # map rounds: same escalation as the hosted twin (PERF_NOTES
            # round-4 A/B: 1.85x at 2^22), tiered on the array width
            lv = _depth_tier(cols, cols0,
                             chunk_i < len(_SCHEDULE),
                             levels, first_levels, cap)
        if runtime is not None:
            # memory budget (ISSUE 5): jump-table depth tracks headroom
            lv = runtime.cap_levels(lv, n)
        if runtime is None:
            lo, hi, stats = chunk_sharded(lo, hi, n, mesh, lv, j, global_f)
        else:
            (lo, hi, stats), j = runtime.dispatch(
                "mesh_chunk",
                lambda jj: chunk_sharded(lo, hi, n, mesh, lv, jj, global_f),
                j)
        rounds += j
        chunk_i += 1
        if comm is not None and global_f:
            comm["sharded_global_rounds"] += j
            comm["pmin_payload_bytes"] += j * 4 * (n + 1)
        moved_i, live_i = (int(x) for x in fetch(stats))  # one sync
        # flight recorder: the mesh loop's per-chunk record (same shape
        # as the hosted loop's "reduce.chunk" — one rollup code path)
        from ..obs import trace as _obs
        _obs.event("reduce.chunk", live=live_i, moved=moved_i,
                   rounds=rounds, mesh=True)
        if moved_i == 0:
            return lo, hi, rounds, False
        if max_rounds is not None and rounds >= max_rounds:
            # bounded phase (the sharded tail's local pass): the caller
            # finishes elsewhere — returning unconverged is sound, every
            # chunk output has the input's threshold connectivity
            return lo, hi, rounds, False
        target = _pad_pow2_cols(live_i)
        if target <= int(lo.shape[1]) // 2:
            lo, hi = lo[:, :target], hi[:, :target]
        if runtime is not None and global_f:
            # chunk boundary: the flat union of shard links is the
            # complete resumable state (rung-portable — see driver.py)
            def _mesh_links(lo=lo, hi=hi):
                flat_lo, flat_hi = gather_links_replicated(lo, hi, mesh)
                l, h = fetch(flat_lo), fetch(flat_hi)
                keep = l < n
                return l[keep], h[keep]
            runtime.boundary(rounds, _mesh_links)


def _extract_parent(lo, hi, n: int, mesh, gathered: bool):
    """Parent extraction for either reduce_links_sharded outcome: the
    gather-tail's replicated links take the single-chip scatter-min
    (identical on every worker, no collective — the comm model's final
    parent pmin term drops); sharded links take the pmin-combined
    extraction.  One helper so the one-shot build and the streaming fold
    cannot drift."""
    if gathered:
        from ..ops.forest import parent_from_links
        return parent_from_links(lo, hi, n)
    return parent_sharded(lo, hi, n, mesh)


def build_links_chunked_sharded(tail_2d, head_2d, n: int, mesh,
                                pos=None, fetch=None, timings=None,
                                unified: bool = True,
                                gather_tail: bool | None = None,
                                tail_shard: bool | None = None,
                                comm: dict | None = None, runtime=None):
    """Full chunked mesh build from staged [W, B] edge arrays.

    Returns (seq, pos, m, parent, pst) — all replicated device arrays,
    parent [n] int32 with n marking roots.  ``timings``: optional dict
    that receives wall-clock seconds for the prep/map/reduce phases and
    the per-phase round counts (the MESHBENCH instrumentation hook).
    ``gather_tail``/``tail_shard``/``comm``: see reduce_links_sharded
    (the ICI-honest tail handoff, the round-6 per-chip tail sharding,
    and their collective-volume accounting).

    ``unified`` (default): run global-f rounds from the FIRST round —
    measured 1.77x (W=2) to 2.07x (W=8) faster than the map-then-reduce
    split at 2^18 on the virtual mesh (MESHBENCH_r04.json, the committed
    run of record), bit-identical parents, because
    the unified fixpoint converges in the same round count as the
    split's reduce phase alone: with the globally combined jump table
    available every round, the per-shard local map phase is redundant
    work.  The split form (unified=False) remains for measurement and
    because it IS the reference's transportable-partials contract — the
    map-only path (per-worker partial trees for the file-path
    tournament) still uses local rounds by construction.
    """
    import time as _time
    fetch = fetch or np.asarray
    t0 = _time.perf_counter()
    if pos is None:
        seq, pos_r, m, lo, hi, pst = prep_sharded(tail_2d, head_2d, n, mesh)
    else:
        seq, pos_r, m, lo, hi, pst = prep_sharded(
            tail_2d, head_2d, n, mesh, pos=pos, with_pos=True)
    jax.block_until_ready(lo)
    t1 = _time.perf_counter()
    if unified:
        lo, hi, red_rounds, gathered = reduce_links_sharded(
            lo, hi, n, mesh, global_f=True, fetch=fetch,
            gather_tail=gather_tail, tail_shard=tail_shard, comm=comm,
            runtime=runtime)
        map_rounds = 0
        t2 = t1
    else:
        # map: shards reduce independently to per-worker partial forests
        lo, hi, map_rounds, _ = reduce_links_sharded(
            lo, hi, n, mesh, global_f=False, fetch=fetch, runtime=runtime)
        jax.block_until_ready(lo)
        t2 = _time.perf_counter()
        # reduce: global-f rounds stitch the partials into one forest
        lo, hi, red_rounds, gathered = reduce_links_sharded(
            lo, hi, n, mesh, global_f=True, fetch=fetch,
            gather_tail=gather_tail, tail_shard=tail_shard, comm=comm,
            runtime=runtime)
    parent = _extract_parent(lo, hi, n, mesh, gathered)
    jax.block_until_ready(parent)
    t3 = _time.perf_counter()
    if timings is not None:
        timings.update(prep_s=t1 - t0, map_s=t2 - t1, reduce_s=t3 - t2,
                       map_rounds=map_rounds, reduce_rounds=red_rounds,
                       unified=unified)
    return seq, pos_r, m, parent, pst


def stage_edges_2d(tail, head, n: int, mesh, block: int | None = None):
    """Host edges -> [W, B] sharded int32 device arrays (pad with n)."""
    w = mesh.size
    e = len(tail)
    b = block if block is not None else (e + w - 1) // w
    b = max(1, b)
    t = np.full((w, b), n, dtype=np.int32)
    h = np.full((w, b), n, dtype=np.int32)
    flat_t = np.asarray(tail)
    flat_h = np.asarray(head)
    for i in range(w):
        sl = slice(i * b, min((i + 1) * b, e))
        k = max(0, sl.stop - sl.start)
        if k:
            t[i, :k] = flat_t[sl]
            h[i, :k] = flat_h[sl]
    sharding = NamedSharding(mesh, P(AXIS, None))
    if jax.process_count() == 1:
        return jax.device_put(t, sharding), jax.device_put(h, sharding)
    mk = jax.make_array_from_callback
    return (mk(t.shape, sharding, lambda idx: t[idx]),
            mk(h.shape, sharding, lambda idx: h[idx]))


@functools.partial(jax.jit, static_argnames=("n", "cn", "mesh"))
def prep_stream_sharded(parent, tail, head, pos, n: int, cn: int, mesh):
    """One streamed block's links + the carry forest's links, sharded.

    parent int32 [n] replicated (n marks roots); tail/head int32 [W, B]
    sharded vid records (pad with values >= len(pos)-1); pos the
    vid->position table with a sentinel slot at the end.  The carry forest
    re-enters as its (kid -> parent) links, SHARDED: worker i owns carry
    rows [i*cn, (i+1)*cn) — any shard may host any link, so splitting the
    carry over the axis keeps per-worker state O(n/W + B) for the link
    arrays.  Returns (lo, hi [W, B+cn] sharded, pst_delta [n] replicated).
    """
    def body(parent, t, h, posr):
        sent = jnp.int32(n)
        vid_cap = jnp.int32(posr.shape[0] - 1)
        pt = posr[jnp.minimum(t[0].astype(jnp.int32), vid_cap)]
        ph = posr[jnp.minimum(h[0].astype(jnp.int32), vid_cap)]
        lo = jnp.minimum(pt, ph)
        hi = jnp.maximum(pt, ph)
        pst_local = pst_weights(jnp.where(lo == hi, sent, lo), n)
        dead = (lo >= hi) | (hi >= sent)
        lo = jnp.where(dead, sent, lo)
        hi = jnp.where(dead, sent, hi)
        # carry shard: this worker's slice of the forest's links
        i = lax.axis_index(AXIS)
        base = i.astype(jnp.int32) * jnp.int32(cn)
        kid = base + jnp.arange(cn, dtype=jnp.int32)
        in_range = kid < jnp.int32(n)
        cpar = lax.dynamic_slice(
            jnp.concatenate([parent.astype(jnp.int32),
                             jnp.full((cn,), sent, jnp.int32)]),
            (base,), (cn,))
        clive = in_range & (cpar < sent)
        clo = jnp.where(clive, kid, sent)
        chi = jnp.where(clive, cpar, sent)
        lo = jnp.concatenate([clo, lo])
        hi = jnp.concatenate([chi, hi])
        return lo[None, :], hi[None, :], lax.psum(pst_local, AXIS)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(), P(AXIS, None), P(AXIS, None), P()),
                   out_specs=(P(AXIS, None), P(AXIS, None), P()),
                   check_vma=False)
    return fn(parent, tail, head, pos)


def build_graph_streaming_chunked(blocks, n: int, pos: np.ndarray,
                                  block_edges: int,
                                  num_workers: int | None = None):
    """OOM streaming over the mesh with bounded dispatches only.

    Same contract as parallel.stream.build_graph_streaming_sharded —
    (Forest over n positions, total_rounds) — but each block folds through
    the chunked sharded reducer (unified global-f rounds; see
    build_links_chunked_sharded for why the local map phase is redundant
    work) instead of an in-jit while_loop fixpoint.  The carry forest
    re-enters sharded, so worker-resident link state stays O(n/W + B/W)
    per block.
    """
    from .. import INVALID_JNID
    from ..core.forest import Forest
    from ..ops.stream import _full_vid_pos
    from .build import _fetch

    mesh = make_mesh(num_workers)
    w = mesh.size
    block_pad = max(w, ((block_edges + w - 1) // w) * w)
    b = block_pad // w
    cn = (n + w - 1) // w
    repl = NamedSharding(mesh, P())
    shard2d = NamedSharding(mesh, P(AXIS, None))

    def put(x, sharding):
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_callback(
            x.shape, sharding, lambda idx: x[idx])

    pos_d = put(_full_vid_pos(pos, n).astype(np.int32), repl)
    vid_pad = len(pos)  # pad records map to the table's sentinel slot
    parent = put(np.full(n, n, dtype=np.int32), repl)
    pst = np.zeros(n, dtype=np.int64)
    total_rounds = 0
    for tail, head in blocks:
        k = len(tail)
        if k > w * b:
            raise ValueError(
                f"streamed block of {k} edges exceeds block_edges="
                f"{block_edges} (padded capacity {w * b})")
        t = np.full((w, b), vid_pad, dtype=np.int32)
        h = np.full((w, b), vid_pad, dtype=np.int32)
        for i in range(w):
            sl = slice(i * b, min((i + 1) * b, k))
            cnt = max(0, sl.stop - sl.start)
            if cnt:
                t[i, :cnt] = tail[sl]
                h[i, :cnt] = head[sl]
        lo, hi, pst_delta = prep_stream_sharded(
            parent, put(t, shard2d), put(h, shard2d), pos_d, n, cn, mesh)
        # unified global-f rounds from the start (see
        # build_links_chunked_sharded: the split's local map phase is
        # redundant when the combined jump table is available per round)
        lo, hi, r, gathered = reduce_links_sharded(lo, hi, n, mesh,
                                                   global_f=True,
                                                   fetch=_fetch)
        parent = _extract_parent(lo, hi, n, mesh, gathered)
        # int64 host accumulation: per-block deltas are int32-safe, the
        # running sum follows the uint32 weight contract via the final cast
        pst += _fetch(pst_delta).astype(np.int64)
        total_rounds += r
    parent_np = _fetch(parent).astype(np.int64)
    out = np.full(n, INVALID_JNID, dtype=np.uint32)
    live = parent_np < n
    out[live] = parent_np[live].astype(np.uint32)
    return Forest(out, (pst & 0xFFFFFFFF).astype(np.uint32)), total_rounds


def _stage_inputs(tail, head, num_vertices, num_workers, seq):
    """Shared host-facing prologue of the chunked wrappers: mesh, vertex
    count inference, edge staging, and the given-seq position-table
    device staging (including the multi-process make_array branch —
    kept in ONE place so a fix cannot drift between the merge and map
    wrappers).  Returns (mesh, n, t2d, h2d, pos_d); n == 0 signals the
    empty graph (arrays None then), pos_d is None when seq is None.
    """
    mesh = make_mesh(num_workers)
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 \
            if len(tail) else 0
    if seq is not None and len(seq):
        n = max(n, int(seq.max()) + 1)
    if n == 0:
        return mesh, 0, None, None, None
    t2d, h2d = stage_edges_2d(tail, head, n, mesh)
    pos_d = None
    if seq is not None:
        from ..core.sequence import sequence_positions
        pos_np = sequence_positions(seq, n - 1).astype(np.int64)
        sharding = NamedSharding(mesh, P())
        pos_d = jax.device_put(pos_np.astype(np.int32), sharding) \
            if jax.process_count() == 1 else jax.make_array_from_callback(
                pos_np.shape, sharding,
                lambda idx: pos_np.astype(np.int32)[idx])
    return mesh, n, t2d, h2d, pos_d


def map_graph_chunked_distributed(tail, head, num_vertices=None,
                                  num_workers=None, seq=None):
    """Map-only chunked mesh build: (seq uint32 [m], [Forest] * W).

    The bounded-dispatch twin of parallel.build.map_graph_distributed
    (`-i` without `-r`): each worker's edge shard reduces with LOCAL
    chunk rounds only (reduce_links_sharded global_f=False) to a partial
    forest over the shared sequence, ready for the file-path merge
    tournament.  Per-worker pst counts only that shard's edges
    (graph2tree.cpp:148 rank-suffixed saves semantics).
    """
    from .build import _fetch, _to_forest

    mesh, n, t2d, h2d, pos_d = _stage_inputs(
        tail, head, num_vertices, num_workers, seq)
    if n == 0:
        return np.empty(0, np.uint32), []
    if pos_d is None:
        dseq, _, m, lo, hi, psts = prep_sharded(t2d, h2d, n, mesh,
                                                local_pst=True)
        m = int(_fetch(m))
        out_seq = _fetch(dseq)[:m].astype(np.uint32)
    else:
        dseq, _, m, lo, hi, psts = prep_sharded(
            t2d, h2d, n, mesh, pos=pos_d, with_pos=True, local_pst=True)
        m = len(seq)
        out_seq = np.asarray(seq, dtype=np.uint32)
    lo, hi, _, _ = reduce_links_sharded(lo, hi, n, mesh, global_f=False,
                                        fetch=_fetch)
    parents = _fetch(parent_sharded_local(lo, hi, n, mesh))
    psts_np = _fetch(psts)
    return out_seq, [_to_forest(parents[i], psts_np[i], n, m)
                     for i in range(mesh.size)]


def build_graph_chunked_distributed(tail, head, num_vertices=None,
                                    num_workers=None, seq=None,
                                    timings=None):
    """Host-facing chunked mesh build: (seq uint32 [m], Forest over m).

    Same contract as parallel.build.build_graph_distributed, but every
    device dispatch is bounded — the execution shape real hardware needs.
    """
    from ..core.forest import Forest
    from .build import _fetch, _to_forest

    mesh, n, t2d, h2d, pos_d = _stage_inputs(
        tail, head, num_vertices, num_workers, seq)
    if n == 0:
        return (np.empty(0, np.uint32),
                Forest(np.empty(0, np.uint32), np.empty(0, np.uint32)))
    dseq, _, m, parent, pst = build_links_chunked_sharded(
        t2d, h2d, n, mesh, pos=pos_d, fetch=_fetch, timings=timings)
    if seq is None:
        m = int(_fetch(m))
        out_seq = _fetch(dseq)[:m].astype(np.uint32)
    else:
        m = len(seq)
        out_seq = np.asarray(seq, dtype=np.uint32)
    return out_seq, _to_forest(_fetch(parent), _fetch(pst), n, m)
