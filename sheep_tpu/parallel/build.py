"""Mesh-sharded distributed build: the `-i -r` path as one SPMD program.

Reference semantics being reproduced (SURVEY §3.1):

  - ``-i`` mpiSequence (lib/sequence.h:65-93): per-rank degree histogram,
    MPI_Allreduce(SUM), then every rank sorts the identical histogram.
    Here: per-shard ``bincount`` + ``lax.psum`` + replicated sort.
  - map (lib/jtree.cpp insert loop per rank on its partial graph): here the
    batched forest fixpoint on the local edge shard.
  - ``-r`` mpi_merge (lib/jnode.cpp:203-250, a non-commutative MPI_Reduce
    custom op): the merge is associative over same-sequence partials, so a
    single all_gather of the per-shard (kid, parent) links followed by one
    fixpoint rebuild is equivalent to any reduction-tree order — including
    the reference's binary MPI tree and the file path's REDUCTION=2
    tournament.  pst weights are a plain psum.

Edges are padded to a multiple of the worker count with (n, n) phantom
records: the phantom vid occupies histogram slot n which is sliced away, and
its links map to the kernel sentinel, so padding cannot perturb results.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax import shard_map
from jax.sharding import PartitionSpec as P

from .. import INVALID_JNID
from ..core.forest import Forest
from ..ops.forest import forest_fixpoint, pst_weights
from ..ops.sort import degree_order
from .mesh import AXIS, make_mesh


def _sharded_build(tail, head, n: int):
    """Per-shard body; runs under shard_map over the 'workers' axis."""
    sent = jnp.int32(n)
    t = tail.astype(jnp.int32)
    h = head.astype(jnp.int32)

    # --- distributed degree sort (mpiSequence) ---
    deg_local = jnp.zeros(n + 1, jnp.int32).at[t].add(1).at[h].add(1)
    deg = lax.psum(deg_local, AXIS)[:n]
    seq, pos, m = degree_order(deg)  # replicated, identical on every worker

    # --- map: local partial forest over the shared sequence ---
    pos_ext = jnp.concatenate([pos, jnp.full((1,), sent, jnp.int32)])
    pt = pos_ext[t]
    ph = pos_ext[h]
    lo = jnp.minimum(pt, ph)
    hi = jnp.maximum(pt, ph)
    dead = lo >= hi  # self-loops and phantom padding
    lo = jnp.where(dead, sent, lo)
    hi = jnp.where(dead, sent, hi)
    parent_local, _ = forest_fixpoint(lo, hi, n)
    pst_local = pst_weights(lo, n)

    # --- reduce: associative merge of the partial forests ---
    parents = lax.all_gather(parent_local, AXIS)  # [W, n]
    kid = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), parents.shape)
    live = parents < n
    mlo = jnp.where(live, kid, sent).reshape(-1)
    mhi = jnp.where(live, parents, sent).reshape(-1)
    parent, rounds = forest_fixpoint(mlo, mhi, n)
    pst = lax.psum(pst_local, AXIS)
    return seq, pos, m, parent, pst, rounds


@functools.partial(jax.jit, static_argnames=("n", "mesh"))
def distributed_build_step(tail: jnp.ndarray, head: jnp.ndarray, n: int, mesh):
    """Jitted SPMD build over `mesh`: edge shards in, replicated forest out.

    tail/head must have length divisible by the mesh size (pad with n).
    Returns (seq, pos, num_active, parent, pst, merge_rounds); ``parent[v]
    == n`` marks roots, everything in full n-slot position space.
    """
    fn = shard_map(
        functools.partial(_sharded_build, n=n),
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(), P(), P(), P(), P(), P()),
        # The merge fixpoint's while_loop carries worker-varying state, so
        # replication of the (genuinely replicated: same all_gather input on
        # every worker, deterministic compute) outputs can't be statically
        # inferred.
        check_vma=False,
    )
    return fn(tail, head)


def build_graph_distributed(tail: np.ndarray, head: np.ndarray,
                            num_vertices: int | None = None,
                            num_workers: int | None = None):
    """Host-facing distributed build: (seq uint32 [m], Forest over m)."""
    mesh = make_mesh(num_workers)
    w = mesh.size
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if n == 0:
        return np.empty(0, np.uint32), Forest(
            np.empty(0, np.uint32), np.empty(0, np.uint32))
    e = len(tail)
    e_pad = max(w, ((e + w - 1) // w) * w)
    t = np.full(e_pad, n, dtype=np.int64)
    h = np.full(e_pad, n, dtype=np.int64)
    t[:e] = tail
    h[:e] = head
    seq, _, m, parent, pst, _ = distributed_build_step(
        jnp.asarray(t, jnp.int32), jnp.asarray(h, jnp.int32), n, mesh)
    m = int(m)
    seq = np.asarray(seq)[:m].astype(np.uint32)
    parent = np.asarray(parent)[:m].astype(np.int64)
    out = np.full(m, INVALID_JNID, dtype=np.uint32)
    live = parent < n
    out[live] = parent[live].astype(np.uint32)
    return seq, Forest(out, np.asarray(pst)[:m].astype(np.uint32))
