"""Mesh-sharded distributed build: the `-i -r` path as one SPMD program.

Reference semantics being reproduced (SURVEY §3.1):

  - ``-i`` mpiSequence (lib/sequence.h:65-93): per-rank degree histogram,
    MPI_Allreduce(SUM), then every rank sorts the identical histogram.
    Here: per-shard ``bincount`` + ``lax.psum`` + replicated sort.
  - map (lib/jtree.cpp insert loop per rank on its partial graph): here the
    batched forest fixpoint on the local edge shard.
  - ``-r`` mpi_merge (lib/jnode.cpp:203-250, a non-commutative MPI_Reduce
    custom op): the merge is associative over same-sequence partials, so a
    single all_gather of the per-shard (kid, parent) links followed by one
    fixpoint rebuild is equivalent to any reduction-tree order — including
    the reference's binary MPI tree and the file path's REDUCTION=2
    tournament.  pst weights are a plain psum.

Edges are padded to a multiple of the worker count with (n, n) phantom
records: the phantom vid occupies histogram slot n which is sliced away, and
its links map to the kernel sentinel, so padding cannot perturb results.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from .. import INVALID_JNID
from ..core.forest import Forest
from ..ops.forest import forest_fixpoint, pst_weights
from ..ops.sort import degree_order
from .mesh import AXIS, make_mesh


def _links_from_positions(pt, ph, n: int):
    """Shared per-shard link mapping: position pairs -> (lo, hi, pst_local).

    The pst/absent-vid contract (jtree.cpp:47-49): every edge whose
    earlier endpoint is present counts toward pst — including edges to
    absent vids (position >= n), which never insert and stay postorder
    forever; only self-loops/padding/both-absent (lo == hi) are excluded.
    The returned lo/hi are sentinel-masked for the fixpoint, which must
    see only fully-present links.
    """
    sent = jnp.int32(n)
    lo = jnp.minimum(pt, ph)
    hi = jnp.maximum(pt, ph)
    pst_local = pst_weights(jnp.where(lo == hi, sent, lo), n)
    dead = (lo >= hi) | (hi >= sent)
    return jnp.where(dead, sent, lo), jnp.where(dead, sent, hi), pst_local


def _gather_merge(parent_local, n: int):
    """All-gather the per-worker partial forests and rebuild associatively
    (the reference's non-commutative MPI_Reduce custom op,
    lib/jnode.cpp:203-250).  Returns (parent, rounds), replicated."""
    sent = jnp.int32(n)
    parents = lax.all_gather(parent_local, AXIS)  # [W, n]
    kid = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), parents.shape)
    live = parents < sent
    mlo = jnp.where(live, kid, sent).reshape(-1)
    mhi = jnp.where(live, parents, sent).reshape(-1)
    return forest_fixpoint(mlo, mhi, n)


def _sharded_build(tail, head, given_pos, n: int, do_merge: bool = True):
    """Per-shard body; runs under shard_map over the 'workers' axis.

    ``given_pos``: None computes the degree sequence on device (the `-i`
    sort); otherwise a replicated vid->position table is used as-is (the
    `-r`-without-`-i` case, where the sequence comes from a file).
    ``do_merge``: False skips the reduce and returns per-worker partials
    (the `-i`-without-`-r` case, whose trees feed the file-path tournament).
    """
    sent = jnp.int32(n)
    t = tail.astype(jnp.int32)
    h = head.astype(jnp.int32)

    # --- distributed degree sort (mpiSequence) ---
    if given_pos is None:
        deg_local = jnp.zeros(n + 1, jnp.int32).at[t].add(1).at[h].add(1)
        deg = lax.psum(deg_local, AXIS)[:n]
        seq, pos, m = degree_order(deg)  # replicated, identical per worker
    else:
        posi = given_pos.astype(jnp.int32)
        # INVALID (0xFFFFFFFF) slots arrive as -1 after the int32 view.
        absent = (posi < 0) | (posi >= n)
        pos = jnp.where(absent, sent, posi)
        seq = jnp.full(n, sent, jnp.int32)
        vids = jnp.arange(n, dtype=jnp.int32)
        # absent vids scatter out-of-bounds and are dropped
        seq = seq.at[jnp.where(absent, n, pos)].set(vids, mode="drop")
        m = jnp.int32(n) - jnp.sum(absent, dtype=jnp.int32)

    # --- map: local partial forest over the shared sequence ---
    pos_ext = jnp.concatenate([pos, jnp.full((1,), sent, jnp.int32)])
    lo, hi, pst_local = _links_from_positions(pos_ext[t], pos_ext[h], n)
    parent_local, map_rounds = forest_fixpoint(lo, hi, n)

    if not do_merge:
        parents = lax.all_gather(parent_local, AXIS)  # [W, n]
        psts = lax.all_gather(pst_local, AXIS)
        return seq, pos, m, parents, psts, lax.pmax(map_rounds, AXIS)

    # --- reduce: associative merge of the partial forests ---
    # NOTE: this in-jit while_loop fixpoint is fine for the merge's input
    # (<= W*n tree links, most of which are already final) but on the
    # tunneled TPU backend very long data-dependent loops fault (see
    # ops/forest.py).  The bounded-dispatch production twin is
    # parallel.chunked (map = local chunk rounds, reduce = pmin-combined
    # jump table); this in-jit path remains the single-dispatch
    # correctness twin and the shape the dryrun compiles.
    parent, rounds = _gather_merge(parent_local, n)
    pst = lax.psum(pst_local, AXIS)
    return seq, pos, m, parent, pst, rounds


@functools.partial(jax.jit,
                   static_argnames=("n", "mesh", "with_pos", "do_merge"))
def distributed_build_step(tail: jnp.ndarray, head: jnp.ndarray, n: int, mesh,
                           pos: jnp.ndarray | None = None,
                           with_pos: bool = False, do_merge: bool = True):
    """Jitted SPMD build over `mesh`: edge shards in, replicated forest out.

    tail/head must have length divisible by the mesh size (pad with n).
    Returns (seq, pos, num_active, parent, pst, merge_rounds); ``parent[v]
    == n`` marks roots, everything in full n-slot position space.  With
    ``do_merge=False`` parent/pst come back stacked [W, n] (per-worker
    partials).  ``with_pos`` switches to an externally-given replicated
    vid->position table instead of the on-device degree sort.
    """
    body = functools.partial(_sharded_build, n=n, do_merge=do_merge)
    if with_pos:
        fn = shard_map(
            lambda t, h, p: body(t, h, p),
            mesh=mesh,
            in_specs=(P(AXIS), P(AXIS), P()),
            out_specs=(P(), P(), P(), P(), P(), P()),
            check_vma=False,
        )
        return fn(tail, head, pos)
    fn = shard_map(
        lambda t, h: body(t, h, None),
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(), P(), P(), P(), P(), P()),
        # The merge fixpoint's while_loop carries worker-varying state, so
        # replication of the (genuinely replicated: same all_gather input on
        # every worker, deterministic compute) outputs can't be statically
        # inferred.
        check_vma=False,
    )
    return fn(tail, head)


def _pad_edges(tail, head, n, w):
    e = len(tail)
    e_pad = max(w, ((e + w - 1) // w) * w)
    t = np.full(e_pad, n, dtype=np.int64)
    h = np.full(e_pad, n, dtype=np.int64)
    t[:e] = tail
    h[:e] = head
    return t.astype(np.int32), h.astype(np.int32)


def _stage(x_np, mesh, spec):
    """Host numpy -> device array under `spec`.  Single-process: a plain
    transfer.  Multi-process (after init_distributed, the mpiexec-across-
    nodes analog): every process holds the full array — the reference's
    shared-filesystem load — and contributes the shards it addresses, so
    the result is one global array spanning the DCN mesh."""
    from jax.sharding import NamedSharding
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        # host memory straight into the shards — no staging copy on the
        # default device first
        return jax.device_put(x_np, sharding)
    return jax.make_array_from_callback(
        x_np.shape, sharding, lambda idx: x_np[idx])


def _fetch(x):
    """Replicated device array -> host numpy, multi-process safe (reads
    this process's addressable copy; out_specs P() replicates)."""
    if isinstance(x, jax.Array) and jax.process_count() > 1:
        return np.asarray(x.addressable_data(0))
    return np.asarray(x)


def _to_forest(parent, pst, n, m):
    # Trim to the m active slots, then reuse the ops converter.  Passing
    # n=m is sound: live parents of active nodes are themselves active
    # positions (< m), and both the root sentinel n and any padding slot
    # value are >= m, so they map to INVALID either way.
    from ..ops.forest import _to_forest as ops_to_forest
    return ops_to_forest(np.asarray(parent)[:m], np.asarray(pst)[:m], m)


def _mesh_kernel() -> str:
    """Which multi-worker kernel the public wrappers route through:
    "chunked" (default — bounded dispatches, the execution shape real
    hardware needs) or "loop" (the single-dispatch while_loop twin —
    fewer host syncs, still the dryrun's compile-coverage shape).
    Anything else is an error: a typo must not silently select the
    kernel that faults on real hardware at scale."""
    import os
    kernel = os.environ.get("SHEEP_MESH_KERNEL", "chunked")
    if kernel not in ("chunked", "loop"):
        raise ValueError(
            f"SHEEP_MESH_KERNEL={kernel!r} must be 'chunked' or 'loop'")
    return kernel


def _run_distributed(tail, head, num_vertices, num_workers, seq, do_merge,
                     mesh=None):
    """Shared prologue + dispatch for the host-facing wrappers.

    Returns (out_seq, parent, pst, n, m, mesh_size) with parent/pst either
    merged [n] or stacked [W, n] depending on ``do_merge``; n == 0 signals
    the empty graph.  ``mesh``: pass an already-built mesh to avoid
    constructing it twice.
    """
    if mesh is None:
        mesh = make_mesh(num_workers)
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    if seq is not None and len(seq):
        n = max(n, int(seq.max()) + 1)
    if n == 0:
        return np.empty(0, np.uint32), None, None, 0, 0, mesh.size
    if mesh.size == 1:
        # A 1-worker mesh is a plain whole-graph build (merge of one
        # partial).  Use the chunked hosted kernel: identical results, and
        # it is the execution shape real hardware needs — the in-jit
        # while_loop below faults on long runs there (ops/forest.py).
        # (The merged case normally never reaches here: the public
        # wrapper routes it through the flagship hybrid first.)
        return _single_worker_build(tail, head, n, seq, do_merge)
    t_np, h_np = _pad_edges(tail, head, n, mesh.size)
    t = _stage(t_np, mesh, P(AXIS))
    h = _stage(h_np, mesh, P(AXIS))
    if seq is None:
        dseq, _, m, parent, pst, _ = distributed_build_step(
            t, h, n, mesh, do_merge=do_merge)
        m = int(_fetch(m))
        out_seq = _fetch(dseq)[:m].astype(np.uint32)
    else:
        from ..core.sequence import sequence_positions
        pos = sequence_positions(seq, n - 1)
        pos = _stage(pos.astype(np.int64).astype(np.int32), mesh, P())
        _, _, m, parent, pst, _ = distributed_build_step(
            t, h, n, mesh, pos=pos, with_pos=True, do_merge=do_merge)
        m = len(seq)
        out_seq = np.asarray(seq, dtype=np.uint32)
    return out_seq, _fetch(parent), _fetch(pst), n, m, mesh.size


def _single_worker_build(tail, head, n, seq, do_merge):
    """The mesh-of-one case via the hosted kernel (same output contract)."""
    from ..ops.build import prepare_links
    from ..ops.forest import forest_fixpoint_hosted

    # vids are < n < 2^31: cast straight to int32, no int64 staging copy
    # (two 8-byte staging arrays would cost ~2GB at the 134M-edge scale)
    t = jnp.asarray(np.asarray(tail), jnp.int32)
    h = jnp.asarray(np.asarray(head), jnp.int32)
    if seq is None:
        dseq, pos, m, lo, hi, pst = prepare_links(t, h, n)
        m = int(m)
        out_seq = np.asarray(dseq)[:m].astype(np.uint32)
    else:
        from ..ops.sort import given_seq_links
        lo, hi, pst = given_seq_links(t, h, seq, n)
        m = len(seq)
        out_seq = np.asarray(seq, dtype=np.uint32)
    parent, _ = forest_fixpoint_hosted(lo, hi, n)
    if not do_merge:
        parent = parent[None, :]
        pst = pst[None, :]
    return out_seq, parent, pst, n, m, 1


def _selfcheck_forest(seq, forest, what: str):
    """Integrity tier 3 at the build/merge boundary: run the vectorized
    fast oracle (core.validate.check_forest_fast) on the forest this path
    is about to hand downstream.  O(n) numpy on host — negligible next to
    the build — and it turns a sick-backend wrong answer into a typed
    IntegrityError at the boundary where it happened.  SHEEP_SELFCHECK=0
    opts out (the oracle itself is exercised by tests either way)."""
    import os
    if os.environ.get("SHEEP_SELFCHECK", "1") == "0":
        return seq, forest
    from ..core.validate import check_forest_fast
    from ..integrity.errors import IntegrityError
    problems = check_forest_fast(forest)
    if problems:
        raise IntegrityError(
            f"{what} produced an invalid forest: " + "; ".join(problems))
    return seq, forest


def build_graph_distributed(tail: np.ndarray, head: np.ndarray,
                            num_vertices: int | None = None,
                            num_workers: int | None = None,
                            seq: np.ndarray | None = None):
    """Host-facing distributed build: (seq uint32 [m], Forest over m).

    ``seq``: an externally-given elimination order (the `-r`-without-`-i`
    case); None runs the device degree sort.  A mesh of one worker routes
    through the flagship hybrid (device reduction + native union-find
    tail — measured ~4x the pure-device path on-chip), which with a given
    ``seq`` also skips the device degree sort entirely.

    SHEEP_CHECKPOINT_DIR (the scripts' restart contract,
    dist-partition.sh -C) reroutes through the fault-tolerant runtime:
    checkpoint/resume at chunk boundaries, retry-with-backoff, and the
    mesh -> single-chip -> host degradation ladder (sheep_tpu.runtime).
    Results are bit-identical; the hybrid/pipelined fast paths are
    traded for survivability.
    """
    import os
    if os.environ.get("SHEEP_CHECKPOINT_DIR"):
        from ..runtime.driver import build_graph_resilient
        return build_graph_resilient(tail, head, num_vertices=num_vertices,
                                     num_workers=num_workers, seq=seq)
    mesh = make_mesh(num_workers)
    if mesh.size == 1 and len(tail):
        from ..ops.build import build_graph_hybrid
        return _selfcheck_forest(
            *build_graph_hybrid(tail, head, num_vertices=num_vertices,
                                seq=seq), what="hybrid build")
    if _mesh_kernel() == "chunked":
        # production default: bounded dispatches only — the in-jit
        # while_loop fixpoint below faults on real hardware once its
        # wall time outgrows the backend's per-execution budget
        # (PERF_NOTES; SHEEP_MESH_KERNEL=loop selects the
        # single-dispatch twin, which stays the dryrun's compile shape)
        from .chunked import build_graph_chunked_distributed
        return _selfcheck_forest(
            *build_graph_chunked_distributed(
                tail, head, num_vertices=num_vertices,
                num_workers=num_workers, seq=seq),
            what="chunked mesh build")
    out_seq, parent, pst, n, m, _ = _run_distributed(
        tail, head, num_vertices, num_workers, seq, do_merge=True, mesh=mesh)
    if n == 0:
        return out_seq, Forest(np.empty(0, np.uint32), np.empty(0, np.uint32))
    return _selfcheck_forest(out_seq, _to_forest(parent, pst, n, m),
                             what="mesh build")


def map_graph_distributed(tail: np.ndarray, head: np.ndarray,
                          num_vertices: int | None = None,
                          num_workers: int | None = None,
                          seq: np.ndarray | None = None):
    """Map-only (`-i` without `-r`): per-worker partial forests, no merge.

    Returns (seq uint32 [m], [Forest over m] * W) — each partial tree covers
    the full vertex set over the shared sequence, ready for the file-path
    merge tournament (reference graph2tree.cpp:148,158 rank-suffixed saves).
    """
    if _mesh_kernel() == "chunked":
        from .chunked import map_graph_chunked_distributed
        return map_graph_chunked_distributed(
            tail, head, num_vertices=num_vertices,
            num_workers=num_workers, seq=seq)
    out_seq, parents, psts, n, m, w = _run_distributed(
        tail, head, num_vertices, num_workers, seq, do_merge=False)
    if n == 0:
        return out_seq, []
    return out_seq, [_to_forest(parents[i], psts[i], n, m) for i in range(w)]
