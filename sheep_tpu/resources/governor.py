"""Memory/disk budgets: measured use + analytic estimates -> refusals.

The paper's whole design is a memory argument — the elimination-tree build
is a graph *reduction* precisely so it fits in small memory — yet nothing
enforced one: an over-large chunk OOMs, a full disk kills a checkpoint
mid-run.  This module is the enforcement point.  Two env-configured
budgets (``SHEEP_MEM_BUDGET``, ``SHEEP_DISK_BUDGET``, human sizes like
``512M``/``2G``) feed one :class:`ResourceGovernor` that every layer which
allocates or writes consults:

  memory   measured RSS (``/proc/self/status`` VmRSS, the same number the
           OOM killer acts on) against the budget, plus ANALYTIC per-chunk
           estimates (links/n/dtype arithmetic below) for allocations that
           have not happened yet — the chunk drivers shrink work
           (jrounds, lifting depth) under pressure and the ladder routes
           around rungs whose estimated peak cannot fit
           (runtime/driver.py: the spill rung is the floor).
  disk     ``statvfs`` free space AND a cap on the bytes sheep's own
           artifacts may occupy under a managed directory (checkpoint /
           supervisor state dirs).  Writers preflight BEFORE writing
           (io/atomic.py), and the checkpoint/state-dir owners run the
           retention GC (resources/gc.py) when the cap trips.

Every refusal is a typed :class:`~sheep_tpu.resources.errors.ResourceError`
raised before bytes land — never a torn artifact, never a published lie.

The estimates are deliberately coarse (they exist to pick a survivable
plan, not to bill by the byte): each one prices the dominant arrays of a
code path from first principles (n, live links, itemsize) and is
documented at its definition.  Overestimating degrades earlier — safe;
underestimating is caught by the measured-RSS backstop at the next
dispatch boundary.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from .errors import DiskExhausted, MemoryBudgetExceeded

MEM_BUDGET_ENV = "SHEEP_MEM_BUDGET"
DISK_BUDGET_ENV = "SHEEP_DISK_BUDGET"
SCRATCH_DIR_ENV = "SHEEP_SCRATCH_DIR"
EXT_BLOCK_ENV = "SHEEP_EXT_BLOCK"
DISTEXT_LEGS_ENV = "SHEEP_DISTEXT_LEGS"
LEG_CORES_ENV = "SHEEP_LEG_CORES"
NATIVE_THREADS_ENV = "SHEEP_NATIVE_THREADS"

#: free space a preflighted write must leave behind (the filesystem needs
#: breathing room for directory blocks, the sidecar, and the journal; a
#: write that would land the disk at 100% is a refusal, not a success)
DISK_SLACK = 1 << 20

#: fraction of the memory budget at which the chunk drivers start
#: shrinking work BEFORE the hard refusal point
MEM_SOFT_FRAC = 0.9

_UNITS = {"": 1, "b": 1,
          "k": 1 << 10, "kb": 1 << 10,
          "m": 1 << 20, "mb": 1 << 20,
          "g": 1 << 30, "gb": 1 << 30,
          "t": 1 << 40, "tb": 1 << 40}


def parse_size(spec: str | None) -> int | None:
    """``"512M"`` -> bytes; ``None``/``""``/``"0"`` -> None (no budget).
    Suffixes are binary (K=1024) and case-insensitive; a bare integer is
    bytes.  Raises ValueError on garbage — a misspelled budget must never
    silently mean "unlimited"."""
    if spec is None:
        return None
    s = spec.strip().lower()
    if s in ("", "0", "none", "unlimited"):
        return None
    num = s.rstrip("kmgtb")
    unit = s[len(num):]
    if unit not in _UNITS:
        raise ValueError(f"unparseable size {spec!r} "
                         f"(want e.g. 512M, 2G, 1048576)")
    try:
        val = float(num)
    except ValueError:
        raise ValueError(f"unparseable size {spec!r} "
                         f"(want e.g. 512M, 2G, 1048576)")
    if val < 0:
        raise ValueError(f"negative size {spec!r}")
    return int(val * _UNITS[unit])


def rss_bytes() -> int:
    """This process's resident set in bytes — VmRSS from
    ``/proc/self/status`` (what the OOM killer counts), with a
    peak-RSS getrusage fallback off Linux (conservative: peak >= current,
    so the fallback can only degrade EARLIER, never OOM later)."""
    try:
        with open("/proc/self/status", "rb") as f:
            for line in f:
                if line.startswith(b"VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


def disk_free(path: str) -> int:
    """Bytes available to this process on ``path``'s filesystem."""
    st = os.statvfs(path if os.path.isdir(path)
                    else (os.path.dirname(os.path.abspath(path)) or "."))
    return st.f_bavail * st.f_frsize


def dir_usage(directory: str) -> int:
    """Total bytes of the regular files under ``directory`` — what the
    disk budget is charged against.  Symlinks are not followed (a link
    into a data dir must not bill the budget for the graph itself)."""
    total = 0
    for dirpath, _, names in os.walk(directory):
        for name in names:
            try:
                st = os.lstat(os.path.join(dirpath, name))
            except OSError:
                continue
            total += st.st_size
    return total


# ---------------------------------------------------------------------------
# Analytic allocation estimates.  int32 link arrays dominate every path;
# each estimate prices the dominant terms of its code path and nothing else.
# ---------------------------------------------------------------------------


def _pad_pow2(x: int, lo_cap: int = 1 << 10) -> int:
    p = lo_cap
    while p < x:
        p <<= 1
    return p


def snapshot_nbytes(n: int, links: int) -> int:
    """An uncompressed checkpoint .npz (runtime/snapshot.py): seq + pst
    uint32 [n] each, lo + hi int32 [links] each, plus zip bookkeeping."""
    return 8 * n + 8 * links + 4096


def chunk_tables_nbytes(n: int, levels: int) -> int:
    """The lifting phase's jump tables: ``levels`` int32 [n+1] rows (the
    doubling table is built level by level but all rows are live during
    the descent)."""
    return 4 * (n + 1) * max(1, levels)


def native_thread_tables_nbytes(n: int, threads: int) -> int:
    """Priced resident bytes of the threaded native kernels' per-thread
    partial tables (round 14): each EXTRA thread folds its slice into a
    private union-find + parent pair over the full [n] position space —
    8n bytes — and the transient pst/histogram partials ride inside the
    estimate's deliberate coarseness (module docstring: over-pricing
    degrades earlier, which is the safe direction).  T=1 prices zero:
    the serial kernels' state is already in every rung's own term."""
    return 8 * n * max(0, threads - 1)


def rung_peak_nbytes(rung: str, n: int, links: int,
                     workers: int = 1, levels: int = 10,
                     ext_block: int | None = None,
                     threads: int = 1) -> int:
    """Rough peak resident bytes of one degradation-ladder rung
    (runtime/driver.py) reducing ``links`` live links over ``n``
    positions.  Terms:

      mesh/single  pow2-padded int32 lo/hi (double-buffered across a
                   dispatch: XLA holds input and output live) + the jump
                   tables + the replicated parent/pst/seq vectors.
      host         the numpy floor casts links to int64 (16 bytes/link
                   for lo+hi), plus the int64 union-find array and the
                   uint32 parent/pst.
      stream       the resumable windowed fold (round 7): uf/parent/pst
                   uint32 [n] (12n), ONE uint32 window pair at a time
                   (8 * min(links, SPILL_BLOCK)), plus the quantile
                   partition's transient hi copy + per-window boolean
                   mask (~5 bytes/link) — the int32 input table itself
                   is the caller's.  Sits between host (16 bytes/link
                   cast) and spill (which pays a scratch file).
      ext          the external-memory rung (round 8): the edge list
                   never loads — the priced peak is the O(n) fold state
                   (uf/parent/pst, 12n) + the vid->position table (4n) +
                   the int64 carry pair (<= 16n) + the prefetch queue's
                   raw record blocks ((EXT_PREFETCH + 1) * 8n uint32
                   pairs per block of ext_block_edges()) + one block's
                   transient int64 mapping (16 bytes/edge).  NO links
                   term at all: for beyond-RAM inputs it prices between
                   stream (which holds the whole int32 table) and spill
                   (which holds nothing but one fold block).
      spill        links live in a memory-mapped scratch file; resident
                   state is the union-find fold's O(n) arrays plus one
                   block of links (SPILL_BLOCK) and the carry (<= n
                   kid->parent pairs).

    ``threads`` > 1 adds the threaded native kernels' per-thread partial
    tables (round 14, :func:`native_thread_tables_nbytes`) to the rungs
    that run through the native fold — host, stream, ext, spill — so a
    budget that fits the serial build but not T partial tables vetoes
    the thread count, not the rung.
    """
    pad = _pad_pow2(max(1, links))
    tthreads = native_thread_tables_nbytes(n, threads)
    if rung in ("mesh", "single"):
        return (2 * 4 * pad * 2
                + chunk_tables_nbytes(n, levels)
                + 12 * (n + 1))
    if rung == "host":
        return 16 * links + 8 * n + 8 * n + tthreads
    if rung == "stream":
        return 12 * n + 8 * min(links, SPILL_BLOCK) + 5 * links + tthreads
    if rung == "ext":
        block = ext_block if ext_block is not None else ext_block_edges()
        return 32 * n + EXT_RECORD_BYTES * block + tthreads
    if rung == "spill":
        return 8 * SPILL_BLOCK + 16 * n + 8 * n + tthreads
    raise ValueError(f"unknown rung {rung!r}")


#: links per fold block of the spill rung (8 bytes resident each): 4M
#: links = 32MB resident — small against any realistic budget, large
#: enough that the per-block union-find amortizes.
SPILL_BLOCK = 1 << 22

#: edge records per streamed block of the external-memory build (ISSUE 9;
#: SHEEP_EXT_BLOCK overrides): 512K records = 6MB raw on disk, ~4MB as
#: the prefetched uint32 pair — with the double-buffered prefetch queue
#: the in-flight data stays small enough that ext prices under the
#: stream rung for any beyond-RAM link count, large enough that the
#: fused per-block kernel amortizes its O(n) merge passes.
EXT_BLOCK_DEFAULT = 1 << 19

#: priced in-flight bytes per record of one ext block: the raw 12-byte
#: read buffer + the (prefetch-depth + 1) uint32 pairs + the transient
#: int64/uint32 mapping of the block being folded, rounded UP (measured
#: ~44-98 B/record across both passes on the bench host) — over-pricing
#: degrades earlier, which is the safe direction (module docstring).
EXT_RECORD_BYTES = 64

#: blocks the ext prefetcher keeps in flight beyond the one being folded
#: (io/prefetch.py double buffering: fold k while k+1 is resident and k+2
#: streams off the disk)
EXT_PREFETCH = 2


def serve_tenant_nbytes(n: int, vids: int, inserted: int) -> int:
    """Priced resident bytes of one serve tenant's core (serve/state.py,
    serve/tenants.py): the tree arrays seq+parent+pst are uint32 [n]
    (12n), the vid-indexed partition is int64 + the uint32 position
    table (12/vid), inserted edges are kept as two Python int lists
    (~2x28 bytes each as CPython ints + list slots), plus the subtree
    cache the first SUBTREE query materializes (16n int64).  Prices the
    eviction policy, not a bill — over-pricing evicts earlier, which is
    the safe direction (module docstring)."""
    return 28 * n + 12 * vids + 64 * inserted + (1 << 16)


def ext_block_edges() -> int:
    """The ext rung's block size in EDGE RECORDS (``SHEEP_EXT_BLOCK``
    overrides; accepts a bare count or a human size like ``2M`` = 2^21
    records — the binary-suffix grammar of the budgets, applied to
    records).  Floor 1: a zero/empty override must not turn the stream
    into an infinite loop."""
    spec = os.environ.get(EXT_BLOCK_ENV, "")
    if not spec:
        return EXT_BLOCK_DEFAULT
    return max(1, parse_size(spec) or EXT_BLOCK_DEFAULT)


def ext_strategy_costs(n: int, carry_links: int, block_records: int) -> dict:
    """Priced bytes-touched estimates of the two per-block fold strategies
    of the external-memory build (ops/extmem.py), used to pick per block:

      edges  the fused native records->forest kernel builds a PER-BLOCK
             forest (its internal uint32 map pass touches ~12 bytes per
             record), then the carry merge replays (carry + <= n block
             forest links) through one fold: + 8 bytes per merge link.
      links  the block maps host-side to int64 position pairs (~24 bytes
             per record incl. the fold's own read) and folds WITH the
             carry in one pass: + 8 bytes per carry link, no second
             O(n) merge.

    The crossover is block ~ 2n/3: big blocks amortize the edges
    strategy's extra O(n) merge, small blocks (the carry-dominated tail
    of a stream, or a tiny SHEEP_EXT_BLOCK) don't.  Deliberately coarse
    (module docstring): both strategies are exact, so a mispriced pick
    costs time, never correctness.
    """
    return {
        "edges": 12 * block_records + 8 * (carry_links + n),
        "links": 24 * block_records + 8 * carry_links,
    }


#: the ext rung's block floor (ext_fitted_block): below this the
#: per-block O(n) merge swamps the stream, so a budget that cannot hold
#: even this block has no single-process out-of-core path left
EXT_BLOCK_FLOOR = 1 << 14


def distext_forced_legs() -> int:
    """The operator-pinned leg count of the distributed out-of-core
    build (``SHEEP_DISTEXT_LEGS``); 0 = unset (the planner picks)."""
    spec = os.environ.get(DISTEXT_LEGS_ENV, "")
    if not spec:
        return 0
    legs = int(spec)
    if legs < 0:
        raise ValueError(f"{DISTEXT_LEGS_ENV}={legs} must be >= 0")
    return legs


def distext_leg_plan(n: int = 0, governor: "ResourceGovernor | None" = None
                     ) -> dict:
    """The distext planner's ARITHMETIC (ISSUE 13): how many supervised
    ext legs to shard a ``.dat`` across, and what one leg's priced peak
    is.  Callers route through ``sheep_tpu.plan.plan_distext_legs``
    (ISSUE 15), which adds the provenance record; this function stays
    the single source of the numbers.

    ``SHEEP_DISTEXT_LEGS`` pins N (the operator's word).  Otherwise N
    starts at the host's concurrency budget — ``host_cores //
    SHEEP_LEG_CORES`` (the same arithmetic the supervisor throttles
    attempts with), floor 2 so a distext request always shards — and is
    then cut while the AGGREGATE of per-leg peaks (the ext formula at
    the leg's fitted block; each leg is its own process under its own
    ``SHEEP_MEM_BUDGET``, but they run concurrently on one host) cannot
    fit the configured budget.  Returns
    ``{"legs", "per_leg_peak_bytes", "block_edges", "forced"}``."""
    gov = governor if governor is not None else ResourceGovernor.from_env()
    block = gov.ext_fitted_block(n)
    per_leg = rung_peak_nbytes("ext", n, 0, ext_block=block)
    forced = distext_forced_legs()
    if forced:
        return {"legs": forced, "per_leg_peak_bytes": per_leg,
                "block_edges": block, "forced": True}
    leg_cores = int(os.environ.get(LEG_CORES_ENV, "0") or 0)
    # quota-aware (round 14): a container limited to q cpu-seconds/second
    # reports every host core in the affinity mask — sizing legs off that
    # number just time-shares the quota (utils/envinfo.effective_cores)
    from ..utils.envinfo import effective_cores
    host = effective_cores()
    legs = max(2, host // max(1, leg_cores))
    budget = gov.mem_budget
    while legs > 2 and budget is not None and legs * per_leg > budget:
        legs -= 1
    return {"legs": legs, "per_leg_peak_bytes": per_leg,
            "block_edges": block, "forced": False}


def native_thread_plan(n: int, governor: "ResourceGovernor | None" = None
                       ) -> dict:
    """Resolve the threaded native kernels' thread count (round 14) —
    the value the driver exports as ``SHEEP_NATIVE_THREADS`` for the
    kernels to read.  The driver reaches this through
    ``sheep_tpu.plan.plan_build`` (ISSUE 15), which records the choice
    as a provenance-carrying Decision; the resolution rules live here.

    Resolution order:

      pinned   an explicit ``SHEEP_NATIVE_THREADS`` is the operator's
               word (A/B arms, the forced-T bench) — never second-
               guessed, reported ``forced``.
      cores    otherwise T starts at the host's EFFECTIVE core count
               (affinity ∩ cgroup quota, utils/envinfo.effective_cores)
               capped by the per-leg cores budget ``SHEEP_LEG_CORES``
               when one is set — a distext leg or supervised worker
               running beside siblings must not oversubscribe the cores
               the supervisor granted it.
      budget   the per-thread partial tables cost
               :func:`native_thread_tables_nbytes` (8n per extra
               thread); T shrinks until they fit the current memory
               headroom — a budget can veto threading entirely.

    Returns ``{"threads", "forced", "cores", "leg_cores",
    "partial_bytes", "reason"}``; ``reason`` names the binding
    constraint so the ``ladder.plan`` trace event can explain the
    choice."""
    forced = os.environ.get(NATIVE_THREADS_ENV, "")
    if forced:
        t = max(1, min(64, int(forced)))
        return {"threads": t, "forced": True, "cores": None,
                "leg_cores": None,
                "partial_bytes": native_thread_tables_nbytes(n, t),
                "reason": (f"pinned by {NATIVE_THREADS_ENV} (the library "
                           f"still clamps to granted cores unless "
                           f"SHEEP_NATIVE_OVERSUB=1)")}
    from ..utils.envinfo import effective_cores
    cores = effective_cores()
    leg_cores = int(os.environ.get(LEG_CORES_ENV, "0") or 0)
    t = min(cores, leg_cores) if leg_cores else cores
    t = max(1, min(64, t))
    reason = (f"leg cores budget ({LEG_CORES_ENV}={leg_cores})"
              if leg_cores and leg_cores < cores
              else f"host effective cores ({cores})")
    gov = governor if governor is not None else ResourceGovernor.from_env()
    head = gov.mem_headroom()
    if head is not None:
        vetoed = t
        while t > 1 and native_thread_tables_nbytes(n, t) > head:
            t -= 1
        if t < vetoed:
            reason = (f"memory budget vetoed {vetoed} -> {t} "
                      f"(partial tables 8n/thread vs headroom)")
    return {"threads": t, "forced": False, "cores": cores,
            "leg_cores": leg_cores or None,
            "partial_bytes": native_thread_tables_nbytes(n, t),
            "reason": reason}


@dataclass
class ResourceGovernor:
    """One process's budget state.  ``None`` budget = unlimited (every
    check passes; pressure is never reported) — the unbudgeted fast path
    costs two attribute reads."""

    mem_budget: int | None = None
    disk_budget: int | None = None
    scratch_dir: str | None = None

    @classmethod
    def from_env(cls, **overrides) -> "ResourceGovernor":
        kw: dict = dict(
            mem_budget=parse_size(os.environ.get(MEM_BUDGET_ENV)),
            disk_budget=parse_size(os.environ.get(DISK_BUDGET_ENV)),
            scratch_dir=os.environ.get(SCRATCH_DIR_ENV) or None,
        )
        kw.update(overrides)
        return cls(**kw)

    @property
    def active(self) -> bool:
        return self.mem_budget is not None or self.disk_budget is not None

    # -- memory ------------------------------------------------------------

    def mem_headroom(self) -> int | None:
        """Bytes left under the memory budget (may be negative), or None
        when no budget is set."""
        if self.mem_budget is None:
            return None
        return self.mem_budget - rss_bytes()

    def mem_pressure(self, frac: float = MEM_SOFT_FRAC) -> bool:
        """True once measured RSS crosses ``frac`` of the budget — the
        soft threshold at which chunk drivers shrink work."""
        if self.mem_budget is None:
            return False
        return rss_bytes() > frac * self.mem_budget

    def check_mem(self, need: int, what: str) -> None:
        """Refuse an allocation the analytic model prices over the
        remaining headroom.  No-op without a budget."""
        head = self.mem_headroom()
        if head is not None and need > head:
            raise MemoryBudgetExceeded(
                f"{what}: needs ~{need >> 20}MB but only "
                f"{max(0, head) >> 20}MB of the "
                f"{self.mem_budget >> 20}MB memory budget remains "
                f"(rss {rss_bytes() >> 20}MB)")

    def ext_fitted_block(self, n: int = 0) -> int:
        """The ext rung's block size under THIS budget: the default (or
        env) block, halved until the priced peak fits the current
        headroom (floor 16K records — below that the per-block O(n)
        merge swamps the stream).  An EXPLICIT ``SHEEP_EXT_BLOCK`` is
        the operator's word and is never second-guessed — it is also
        part of the checkpoint's resume identity, so auto-fitting only
        applies where no one pinned it."""
        block = ext_block_edges()
        if os.environ.get(EXT_BLOCK_ENV, ""):
            return block
        head = self.mem_headroom()
        if head is None:
            return block
        while block > EXT_BLOCK_FLOOR \
                and 32 * n + EXT_RECORD_BYTES * block > head:
            block //= 2
        return block

    def plan_rungs(self, rungs: list[str], n: int, links: int,
                   workers: int = 1, threads: int = 1
                   ) -> tuple[list[str], list[tuple]]:
        """[The driver now plans through ``sheep_tpu.plan.plan_build``
        (ISSUE 15), which runs this same arithmetic plus measured-prior
        corrections; this method remains the analytic reference the
        planner is parity-tested against.]

        Drop ladder rungs whose estimated peak cannot fit the memory
        headroom (the LAST rung always survives — something must run, and
        the spill floor is sized to fit any budget that fits n).  The ext
        rung prices at its FITTED block (ext_fitted_block): it can shrink
        its stream to the headroom, and skipping it for a default it
        would never use would waste the fastest beyond-RAM path.
        ``threads`` prices the threaded native kernels' per-thread
        partial tables into the native-fold rungs (round 14).  Returns
        (kept_rungs, [(rung, estimate, "skip"|"keep"), ...])."""
        head = self.mem_headroom()
        if head is None or not rungs:
            return rungs, []
        kept, trace = [], []
        for i, rung in enumerate(rungs):
            est = rung_peak_nbytes(
                rung, n, links, workers,
                ext_block=self.ext_fitted_block(n) if rung == "ext"
                else None,
                threads=threads)
            if est > head and i < len(rungs) - 1:
                trace.append((rung, est, "skip"))
            else:
                kept.append(rung)
                trace.append((rung, est, "keep"))
        return kept, trace

    def shrunk_levels(self, levels: int, n: int) -> int:
        """Cap the lifting depth so the jump tables fit the CURRENT
        memory headroom (never below 2 — depth 2 still terminates, just
        slower).  Unbudgeted: unchanged."""
        head = self.mem_headroom()
        if head is None or levels <= 2:
            return levels
        per_level = 4 * (n + 1)
        fit = int(head // (2 * per_level)) if per_level else levels
        return max(2, min(levels, fit))

    # -- disk --------------------------------------------------------------

    def preflight_write(self, path: str, need: int) -> None:
        """Refuse a write of ~``need`` bytes that the target filesystem
        cannot hold with :data:`DISK_SLACK` to spare.  This is the
        universal half of the preflight (io/atomic.py calls it when the
        writer can estimate its size); the budget half lives with the
        managed-directory owners (:meth:`check_dir_budget`)."""
        if need <= 0:
            return
        free = disk_free(path)
        if need + DISK_SLACK > free:
            raise DiskExhausted(
                f"{path}: refusing to write ~{need} bytes with only "
                f"{free} free (slack {DISK_SLACK})")

    def dir_budget_deficit(self, directory: str, need: int) -> int:
        """Bytes the ``SHEEP_DISK_BUDGET`` cap is short for ``need`` more
        bytes under ``directory`` (<= 0 means it fits; 0 when no budget)."""
        if self.disk_budget is None:
            return 0
        return dir_usage(directory) + need - self.disk_budget

    def check_dir_budget(self, directory: str, need: int,
                         what: str) -> None:
        deficit = self.dir_budget_deficit(directory, need)
        if deficit > 0:
            raise DiskExhausted(
                f"{what}: {directory} would exceed the "
                f"{self.disk_budget}-byte disk budget by {deficit} bytes "
                f"(retention GC could not reclaim enough)")
