"""Resource-exhaustion hardening: budgets, refusals, reclamation.

Three modules, layered bottom-up:

  errors.py    the ResourceError taxonomy (DiskExhausted / WriteFault /
               MemoryBudgetExceeded), all OSError subclasses so existing
               recovery paths already speak the language
  governor.py  SHEEP_MEM_BUDGET / SHEEP_DISK_BUDGET enforcement: measured
               RSS + statvfs + analytic per-chunk allocation estimates ->
               typed refusals BEFORE the OOM killer or ENOSPC can strike
  gc.py        retention-policy reclamation for managed directories
               (keep-last-k + keep-resumable), orphan-temp sweeping

The deterministic I/O fault layer that drives all of this under test
lives with the writers it wraps (io/faultfs.py, SHEEP_IO_FAULT_PLAN).
"""

from .errors import (DiskExhausted, MemoryBudgetExceeded, ResourceError,
                     WriteFault)
from .gc import gc_orphan_temps, is_orphan_temp, retention_gc
from .governor import (DISK_BUDGET_ENV, MEM_BUDGET_ENV, ResourceGovernor,
                       dir_usage, disk_free, parse_size, rss_bytes,
                       snapshot_nbytes)

__all__ = [
    "DISK_BUDGET_ENV",
    "DiskExhausted",
    "MEM_BUDGET_ENV",
    "MemoryBudgetExceeded",
    "ResourceError",
    "ResourceGovernor",
    "WriteFault",
    "dir_usage",
    "disk_free",
    "gc_orphan_temps",
    "is_orphan_temp",
    "parse_size",
    "retention_gc",
    "rss_bytes",
    "snapshot_nbytes",
]
