"""Retention-policy GC for managed artifact directories.

When the disk budget (or the filesystem itself) runs short, the owners of
a managed directory — the checkpoint dir (runtime/snapshot.Checkpointer)
and the supervisor state dir (supervisor/supervise) — reclaim space HERE,
under one policy with two invariants:

  keep-resumable   nothing a resume needs is ever deleted: the caller
                   names the protected set explicitly (the live snapshot
                   + sidecar, the manifest, every artifact a pending leg
                   still consumes, the final tree).  Protection is by
                   real path, so a candidate reached through a different
                   spelling cannot dodge it.
  keep-last-k      of the UNPROTECTED candidates, the k newest (mtime)
                   survive — an operator poking at yesterday's artifacts
                   gets a grace window; k=0 reclaims everything
                   unprotected.

Candidates are reclaimed oldest-first until the requested bytes are free
(or the candidates run out).  Sidecars travel with their artifacts in
BOTH directions: deleting ``foo.tre`` deletes ``foo.tre.sum`` (a sidecar
with no artifact vouches for nothing), and a sidecar is never deleted
while its artifact survives.  Orphaned atomic-write temps
(``.{name}.*.tmp`` — a killed writer's debris, io/atomic.py) are always
candidates regardless of age: no resume ever reads one.
"""

from __future__ import annotations

import os
import re

from ..integrity.sidecar import SIDECAR_SUFFIX

#: the io/atomic.py temp naming: .{basename}.{random}.tmp
_TMP_RE = re.compile(r"^\..*\.tmp$")


def is_orphan_temp(name: str) -> bool:
    return bool(_TMP_RE.match(name))


def is_live_temp(name: str, live_bases) -> bool:
    """Is this dot-temp a LIVE writer's in-flight file?  ``live_bases``
    are the final basenames concurrent writers are currently producing
    (io/atomic.py names their temps ``.{base}.{random}.tmp``).  The
    "orphan temps are debris" assumption only holds when nothing is
    writing — a mid-run sweep (the supervisor's ENOSPC recovery, with
    sibling attempts still in flight IN PROCESS) must leave these alone
    or it unlinks a healthy attempt's rename source out from under it."""
    if not _TMP_RE.match(name):
        return False
    return any(name.startswith(f".{b}.") for b in live_bases)


def _candidates(directory: str, protect: set[str]) -> list[tuple]:
    """(mtime, size, path, is_temp) of every reclaimable file directly
    under ``directory`` (non-recursive: managed dirs are flat; a
    recursive sweep could eat a nested state dir someone pointed inside).
    Sidecars are folded into their artifact's entry."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    have = set(names)
    for name in names:
        if name.endswith(SIDECAR_SUFFIX) \
                and name[: -len(SIDECAR_SUFFIX)] in have:
            continue  # travels with its artifact
        path = os.path.join(directory, name)
        real = os.path.realpath(path)
        if real in protect or not os.path.isfile(path):
            continue
        try:
            st = os.lstat(path)
        except OSError:
            continue
        size = st.st_size
        sc = path + SIDECAR_SUFFIX
        if os.path.exists(sc):
            try:
                size += os.lstat(sc).st_size
            except OSError:
                pass
        out.append((st.st_mtime, size, path, is_orphan_temp(name)))
    return out


def gc_orphan_temps(directory: str, live_bases=()) -> list[str]:
    """Remove orphaned atomic-write temps under ``directory``.  A temp
    under the dot-name is unpublished debris from a killed or faulted
    writer — no reader ever opens one — EXCEPT the in-flight temps of
    writers that are still running: mid-run callers (the supervisor's
    leg-failure sweep, with sibling attempts live in process) pass the
    final basenames those writers are producing as ``live_bases`` so
    their rename sources survive (:func:`is_live_temp`).  Resume entry
    points have no concurrent writers and pass nothing."""
    removed = []
    try:
        names = os.listdir(directory)
    except OSError:
        return removed
    for name in names:
        if is_orphan_temp(name) and not is_live_temp(name, live_bases):
            path = os.path.join(directory, name)
            try:
                os.unlink(path)
                removed.append(path)
            except OSError:
                pass
    return removed


def retention_gc(directory: str, protect=(), keep_last: int = 1,
                 need: int = 0, live_bases=()) -> tuple[int, list[str]]:
    """Reclaim at least ``need`` bytes from ``directory`` (0 = reclaim
    every eligible candidate) under the module-docstring policy.

    ``protect``: paths a resume still needs — never touched.
    ``keep_last``: newest unprotected non-temp survivors.
    ``live_bases``: final basenames of writes currently in flight —
    their dot-temps are rename sources, not debris (:func:`is_live_temp`).

    Returns (bytes_freed, removed_paths).  Best-effort: an unlinkable
    candidate is skipped, not fatal (the caller's budget re-check decides
    whether enough was reclaimed).
    """
    protect_real = {os.path.realpath(p) for p in protect}
    cands = sorted(c for c in _candidates(directory, protect_real)
                   if not is_live_temp(os.path.basename(c[2]),
                                       live_bases))
    # keep-last-k applies to real artifacts only; orphan temps are
    # always reclaimable
    non_temp = [c for c in cands if not c[3]]
    keep = {c[2] for c in non_temp[len(non_temp) - keep_last:]} \
        if keep_last > 0 else set()
    freed = 0
    removed: list[str] = []
    for _, size, path, _ in cands:
        if need and freed >= need:
            break
        if path in keep:
            continue
        ok = True
        for p in (path, path + SIDECAR_SUFFIX):
            try:
                os.unlink(p)
                removed.append(p)
            except FileNotFoundError:
                pass
            except OSError:
                ok = False
        if ok:
            freed += size
    return freed, removed
