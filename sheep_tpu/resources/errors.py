"""The resource-exhaustion error taxonomy: refusals, not surprises.

All types subclass :class:`ResourceError`, which itself subclasses
``OSError`` — the environmental failure domain (full disk, exhausted
memory budget) surfaces to callers through the same channel the OS itself
would use, so every existing ``except OSError`` recovery path (the
supervisor's attempt failure handling, the CLI top-levels) already treats
a budget refusal exactly like the real fault it prevents.  The split from
:class:`~sheep_tpu.integrity.errors.IntegrityError` matters operationally:

  IntegrityError   the bytes are WRONG — retrying the same write cannot
                   help; the artifact (or its producer) is sick.
  ResourceError    the bytes never landed — the environment is out of
                   room.  The artifact under the final name is untouched
                   (writers never publish on refusal) and the run is
                   RESUMABLE once space/memory is reclaimed.

:class:`DiskExhausted` carries ``errno == ENOSPC`` and
:class:`WriteFault` carries ``errno == EIO``, so code that branches on
``exc.errno`` (and the shell, via exit status) cannot tell an injected
fault (io/faultfs.py) from the real one — which is the whole point of
deterministic fault injection.
"""

from __future__ import annotations

import errno


class ResourceError(OSError):
    """Base of every resource-budget refusal in sheep_tpu."""


class DiskExhausted(ResourceError):
    """No room to write: the filesystem is (or would be left) too full,
    or the ``SHEEP_DISK_BUDGET`` cap would be exceeded.  The failed write
    published nothing; a later run resumes from the last durable state."""

    def __init__(self, msg: str):
        super().__init__(errno.ENOSPC, msg)


class WriteFault(ResourceError):
    """An I/O error (EIO / short write) mid-write: the device lied or
    died.  The failed write published nothing."""

    def __init__(self, msg: str):
        super().__init__(errno.EIO, msg)


class MemoryBudgetExceeded(ResourceError):
    """An allocation the analytic model prices over ``SHEEP_MEM_BUDGET``
    headroom was refused BEFORE it could OOM the process.  The chunk
    drivers respond by shrinking (chunk rounds, lifting depth) or
    degrading to the spill rung — never by dying."""

    def __init__(self, msg: str):
        super().__init__(errno.ENOMEM, msg)
