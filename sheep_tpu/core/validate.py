"""Self-validation oracle — analog of ``graph2tree -c`` (lib/jtree.cpp:238-301).

Checks the defining elimination-tree invariant per edge: for an edge whose
endpoints sit at positions lo < hi, walking parent pointers up from lo must
reach hi within the forest (hi lies on lo's root path), without overshooting.
Also checks structural sanity: parents strictly later than children, pst sum
equals the number of non-loop edge records, bounded walk lengths.
"""

from __future__ import annotations

import numpy as np

from .. import INVALID_JNID
from .forest import Forest, edges_to_positions


def is_valid_forest(forest: Forest, tail: np.ndarray, head: np.ndarray,
                    seq: np.ndarray, max_vid: int | None = None) -> bool:
    n = forest.n
    parent = forest.parent.astype(np.int64)
    parent[forest.parent == INVALID_JNID] = -1

    if n != len(seq):
        return False
    ids = np.arange(n)
    linked = parent >= 0
    if not np.all(parent[linked] > ids[linked]):
        return False

    lo, hi = edges_to_positions(tail, head, seq, max_vid)
    if int(forest.pst_weight.sum()) != len(lo):
        return False
    if len(lo) and np.bincount(lo, minlength=n).astype(np.int64).tolist() != \
            forest.pst_weight.astype(np.int64).tolist():
        return False

    for l, h in zip(lo.tolist(), hi.tolist()):
        if h >= n:
            continue  # pst-only link: endpoint absent from the sequence
        cur = l
        steps = 0
        while cur < h:
            cur = parent[cur]
            steps += 1
            if cur < 0 or steps > n:
                return False
        if cur != h:
            return False
    return True
