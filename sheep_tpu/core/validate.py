"""Tiered self-validation oracles — analog of ``graph2tree -c``
(lib/jtree.cpp:238-301).

Two tiers (ISSUE 2):

  FAST  :func:`check_forest_fast` — vectorized O(n + E) invariants usable
         at every chunk / merge / partition boundary: parent pointers in
         range and strictly monotone (parent > kid, so no cycles), pst
         conservation (total == kept edge records), and the per-node pst
         histogram.  Returns a list of human-readable problems; the
         runtime raises IntegrityError when it is non-empty.

  EXACT :func:`is_valid_forest` — the defining elimination-tree invariant
         per edge: for an edge whose endpoints sit at positions lo < hi,
         hi must lie on lo's root path.  The default implementation is a
         chunked binary-lifting (pointer-doubling) walk — O(E log n)
         vectorized, seconds on HepTh-scale graphs where the per-edge
         python loop took minutes.  The loop walker survives as the
         reference implementation (``exact="loop"``, or env
         SHEEP_VALIDATE_LOOP=1 — a test-only flag).

Why binary lifting is exact here: a valid forest's root path from lo is a
strictly increasing position sequence, so "ascend by the largest power-of-
two step that does not overshoot hi" lands exactly on the maximal path
node <= hi; hi is on the path iff that node IS hi.  Monotonicity is
checked first, so the lifted walk never runs on a cyclic parent array.
"""

from __future__ import annotations

import os

import numpy as np

from .. import INVALID_JNID
from .forest import Forest, edges_to_positions


def check_forest_fast(forest: Forest, lo: np.ndarray | None = None,
                      hi: np.ndarray | None = None) -> list[str]:
    """The fast tier: vectorized structural + conservation invariants.

    ``lo``/``hi``: the link multiset in position space (as produced by
    forest.edges_to_positions — pst-only links included, hi >= n).  When
    given, pst conservation and the per-node histogram are checked too;
    without links only the structural invariants run (still enough to
    catch OOB / cyclic parents from a corrupt artifact or a sick rung).
    """
    problems: list[str] = []
    n = forest.n
    parent = forest.parent.astype(np.int64)
    parent[forest.parent == INVALID_JNID] = -1
    linked = parent >= 0
    if len(forest.pst_weight) != n:
        problems.append(
            f"pst_weight length {len(forest.pst_weight)} != n {n}")
    oob = linked & (parent >= n)
    if oob.any():
        j = int(np.flatnonzero(oob)[0])
        problems.append(f"parent[{j}]={int(parent[j])} out of range "
                        f"(n={n})")
        return problems  # later checks would index OOB
    ids = np.arange(n, dtype=np.int64)
    non_mono = linked & (parent <= ids)
    if non_mono.any():
        j = int(np.flatnonzero(non_mono)[0])
        problems.append(
            f"parent[{j}]={int(parent[j])} is not strictly later than its "
            f"kid (monotonicity violated — possible cycle)")
    if lo is not None:
        lo = np.asarray(lo, dtype=np.int64)
        total = int(forest.pst_weight.astype(np.int64).sum())
        if total != len(lo):
            problems.append(
                f"pst conservation violated: sum(pst)={total} != "
                f"{len(lo)} kept edge records")
        hist = np.bincount(lo, minlength=n)[:n] if len(lo) else \
            np.zeros(n, dtype=np.int64)
        if not np.array_equal(hist,
                              forest.pst_weight.astype(np.int64)):
            bad = np.flatnonzero(
                hist != forest.pst_weight.astype(np.int64))
            j = int(bad[0])
            problems.append(
                f"pst histogram mismatch at {len(bad)} node(s); first: "
                f"pst[{j}]={int(forest.pst_weight[j])} but {int(hist[j])} "
                f"records have lo={j}")
    return problems


def _ancestor_table(parent: np.ndarray, n: int) -> list[np.ndarray]:
    """Binary-lifting jump tables: levels[k][v] = 2^k-th ancestor of v,
    with a sentinel row (index n, for roots) that maps to itself."""
    up = np.empty(n + 1, dtype=np.int64)
    up[:n] = np.where(parent >= 0, parent, n)
    up[n] = n
    levels = [up]
    span = 1
    while span < n:
        levels.append(levels[-1][levels[-1]])
        span <<= 1
    return levels


def _walk_contains_lifted(parent: np.ndarray, lo: np.ndarray,
                          hi: np.ndarray, n: int,
                          chunk: int = 1 << 20) -> bool:
    """For every link, is hi on lo's root path?  Chunked pointer-doubling:
    O(E log n) gathers, ~chunk resident positions at a time."""
    if len(lo) == 0:
        return True
    levels = _ancestor_table(parent, n)
    for a in range(0, len(lo), chunk):
        cur = lo[a:a + chunk].astype(np.int64).copy()
        target = hi[a:a + chunk].astype(np.int64)
        for up in reversed(levels):
            step = up[cur]
            take = step <= target  # sentinel n > any target: roots stall
            np.copyto(cur, step, where=take)
        if not np.array_equal(cur, target):
            return False
    return True


def _walk_contains_loop(parent: np.ndarray, lo: np.ndarray,
                        hi: np.ndarray, n: int) -> bool:
    """The reference per-edge walk (jtree.cpp:260-276) — the slow oracle
    the vectorized walker is tested against."""
    for l, h in zip(lo.tolist(), hi.tolist()):
        cur = l
        steps = 0
        while cur < h:
            cur = parent[cur]
            steps += 1
            if cur < 0 or steps > n:
                return False
        if cur != h:
            return False
    return True


def is_valid_forest(forest: Forest, tail: np.ndarray, head: np.ndarray,
                    seq: np.ndarray, max_vid: int | None = None,
                    exact: str = "auto") -> bool:
    """The exact tier: fast invariants, then the per-edge root-path walk.

    ``exact``: "auto" (vectorized binary lifting; SHEEP_VALIDATE_LOOP=1
    flips to the loop), "lifted", or "loop" (the reference walker)."""
    if exact not in ("auto", "lifted", "loop"):
        raise ValueError(f"exact must be auto|lifted|loop, got {exact!r}")
    if exact == "auto":
        exact = "loop" if os.environ.get("SHEEP_VALIDATE_LOOP") == "1" \
            else "lifted"
    n = forest.n
    if n != len(seq):
        return False
    lo, hi = edges_to_positions(tail, head, seq, max_vid)
    if check_forest_fast(forest, lo, hi):
        return False
    parent = forest.parent.astype(np.int64)
    parent[forest.parent == INVALID_JNID] = -1
    tree = hi < n  # hi >= n: pst-only link (endpoint absent), no walk
    lo_t, hi_t = lo[tree], hi[tree]
    if exact == "loop":
        return _walk_contains_loop(parent, lo_t, hi_t, n)
    return _walk_contains_lifted(parent, lo_t, hi_t, n)
