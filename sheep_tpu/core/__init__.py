from .sequence import degree_sequence, sequence_positions, default_sequence
from .forest import (
    Forest,
    edges_to_positions,
    build_forest,
    build_forest_links,
    build_forest_streaming,
    merge_forests,
)
from .facts import Facts, compute_facts
from .validate import check_forest_fast, is_valid_forest

__all__ = [
    "degree_sequence",
    "sequence_positions",
    "default_sequence",
    "Forest",
    "edges_to_positions",
    "build_forest",
    "build_forest_streaming",
    "build_forest_links",
    "merge_forests",
    "Facts",
    "compute_facts",
    "check_forest_fast",
    "is_valid_forest",
]
