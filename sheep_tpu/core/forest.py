"""Elimination-forest construction — exact sequential semantics (host oracle).

The reference builds its "JTree" by streaming vertices in sequence order
(lib/jtree.cpp:34-55): when vertex X is inserted, each already-inserted
neighbor's subtree root is re-parented to X via union-find
(lib/jnode.h:158-162), and each not-yet-inserted neighbor increments X's
``pst_weight`` (self-loops excluded, jtree.cpp:48).

This module uses an equivalent *link-processing* formulation that the whole
framework is built around:

    Map each edge {u,v} to sequence positions (lo, hi) with lo < hi.
    - ``pst_weight[lo] += 1`` per edge (order-free: a pure segment-sum).
    - Process links (lo -> hi) in ascending-hi order with union-find whose
      representative is the max-position element of each component:
          r = find(lo); if r != hi: parent[r] = hi; union.

This yields the *identical* parent array: when hi's edges are processed, hi
is still a root (links only attach earlier roots to later vertices), and
within one hi-group, link order does not affect the parent array (distinct
component roots each get parent hi; repeats are no-ops).  The same routine
implements the associative tree *merge* (lib/jnode.cpp:174-201): a tree's
(kid, parent) pairs are simply re-inserted as links, so merging k partial
trees is "concatenate their links and rebuild" — which is what the batched
TPU kernel (sheep_tpu.ops.forest) and the mesh-collective merge
(sheep_tpu.parallel) exploit.

This numpy/python implementation is the correctness oracle for the C++ and
JAX paths; it is exact but not fast.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import INVALID_JNID
from .sequence import sequence_positions


@dataclass
class Forest:
    """Elimination forest over jnid space (positions in the sequence)."""

    parent: np.ndarray      # uint32 [n], INVALID_JNID for roots
    pst_weight: np.ndarray  # uint32 [n]

    @property
    def n(self) -> int:
        return len(self.parent)

    def copy(self) -> "Forest":
        return Forest(self.parent.copy(), self.pst_weight.copy())


def edges_to_positions(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
                       max_vid: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Map edge records to (lo, hi) position pairs, dropping self-loops."""
    pos = sequence_positions(seq, max_vid)
    pt = pos[tail].astype(np.int64)
    ph = pos[head].astype(np.int64)
    keep = pt != ph  # drops self-loops; position map is injective on seq
    pt, ph = pt[keep], ph[keep]
    lo = np.minimum(pt, ph)
    hi = np.maximum(pt, ph)
    return lo, hi


def _find(uf: np.ndarray, x: int) -> int:
    """Find with path compression; representative = max element of component."""
    root = x
    while uf[root] != root:
        root = uf[root]
    while uf[x] != root:
        uf[x], x = root, uf[x]
    return root


def build_forest_links(lo: np.ndarray, hi: np.ndarray, n: int,
                       pst: np.ndarray | None = None) -> Forest:
    """Build the elimination forest from links (lo -> hi), lo < hi elementwise.

    ``pst`` lets callers pass precomputed pst-weights (used by merge, where
    links are tree edges that must not be re-counted).  When None, each link
    contributes 1 to pst_weight[lo].
    """
    if pst is None:
        pst = np.bincount(lo, minlength=n).astype(np.uint32)
    parent = np.full(n, INVALID_JNID, dtype=np.uint32)
    uf = np.arange(n, dtype=np.int64)
    order = np.argsort(hi, kind="stable")
    lo_s, hi_s = lo[order], hi[order]
    for i in range(len(lo_s)):
        h = int(hi_s[i])
        r = _find(uf, int(lo_s[i]))
        if r != h:
            # r is the max of its component and h > r: attach and re-root.
            parent[r] = h
            uf[r] = h
    return Forest(parent, pst.astype(np.uint32))


def build_forest(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
                 max_vid: int | None = None) -> Forest:
    """Build from raw edge records over a (possibly partial) graph."""
    lo, hi = edges_to_positions(tail, head, seq, max_vid)
    return build_forest_links(lo, hi, len(seq))


def forest_links(forest: Forest) -> tuple[np.ndarray, np.ndarray]:
    """A tree's (kid, parent) pairs as link arrays."""
    kids = np.nonzero(forest.parent != INVALID_JNID)[0].astype(np.int64)
    return kids, forest.parent[kids].astype(np.int64)


def merge_forests(*forests: Forest) -> Forest:
    """Associative merge of same-sequence partial forests.

    Equivalent to the reference's pairwise merge (lib/jnode.cpp:174-201) /
    MPI_Reduce custom op (:203-250): pst_weights add; parent links from all
    inputs are replayed as links in ascending-parent order.
    """
    assert len(forests) >= 1
    n = forests[0].n
    assert all(f.n == n for f in forests)
    pst = np.zeros(n, dtype=np.uint64)
    los, his = [], []
    for f in forests:
        pst += f.pst_weight
        k, p = forest_links(f)
        los.append(k)
        his.append(p)
    lo = np.concatenate(los) if los else np.empty(0, dtype=np.int64)
    hi = np.concatenate(his) if his else np.empty(0, dtype=np.int64)
    return build_forest_links(lo, hi, n, pst=pst.astype(np.uint32))
