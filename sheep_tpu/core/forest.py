"""Elimination-forest construction — exact sequential semantics (host oracle).

The reference builds its "JTree" by streaming vertices in sequence order
(lib/jtree.cpp:34-55): when vertex X is inserted, each already-inserted
neighbor's subtree root is re-parented to X via union-find
(lib/jnode.h:158-162), and each not-yet-inserted neighbor increments X's
``pst_weight`` (self-loops excluded, jtree.cpp:48).

This module uses an equivalent *link-processing* formulation that the whole
framework is built around:

    Map each edge {u,v} to sequence positions (lo, hi) with lo < hi.
    - ``pst_weight[lo] += 1`` per edge (order-free: a pure segment-sum).
    - Process links (lo -> hi) in ascending-hi order with union-find whose
      representative is the max-position element of each component:
          r = find(lo); if r != hi: parent[r] = hi; union.

This yields the *identical* parent array: when hi's edges are processed, hi
is still a root (links only attach earlier roots to later vertices), and
within one hi-group, link order does not affect the parent array (distinct
component roots each get parent hi; repeats are no-ops).  The same routine
implements the associative tree *merge* (lib/jnode.cpp:174-201): a tree's
(kid, parent) pairs are simply re-inserted as links, so merging k partial
trees is "concatenate their links and rebuild" — which is what the batched
TPU kernel (sheep_tpu.ops.forest) and the mesh-collective merge
(sheep_tpu.parallel) exploit.

This numpy/python implementation is the correctness oracle for the C++ and
JAX paths; it is exact but not fast.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import INVALID_JNID
from .sequence import sequence_positions


@dataclass
class Forest:
    """Elimination forest over jnid space (positions in the sequence)."""

    parent: np.ndarray      # uint32 [n], INVALID_JNID for roots
    pst_weight: np.ndarray  # uint32 [n]

    @property
    def n(self) -> int:
        return len(self.parent)

    def copy(self) -> "Forest":
        return Forest(self.parent.copy(), self.pst_weight.copy())


def edges_to_positions(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
                       max_vid: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Map edge records to (lo, hi) position pairs, dropping self-loops.

    Partial-sequence contract (mirrors the reference, where a neighbor never
    appearing in the sequence stays "not yet inserted" forever and so counts
    toward pst_weight, jtree.cpp:47-49): an edge with exactly one endpoint in
    the sequence yields (lo = present position, hi = INVALID); both-absent
    edges and self-loops are dropped.  Callers treat hi >= len(seq) as
    "pst-only" — no tree link.
    """
    pos = sequence_positions(seq, max_vid)
    pos, pt, ph = _positions_through(pos, tail, head)
    keep = pt != ph  # drops self-loops and both-absent (INVALID == INVALID)
    pt, ph = pt[keep], ph[keep]
    lo = np.minimum(pt, ph)
    hi = np.maximum(pt, ph)
    return lo, hi


def _positions_through(pos: np.ndarray, tail: np.ndarray, head: np.ndarray):
    """Gather endpoint positions, extending the table over any vids beyond
    it (they are simply absent — INVALID).  Returns (pos, pt, ph); the
    possibly-extended table is returned so block-streaming callers can
    keep it across blocks."""
    mx = int(max(tail.max(initial=0), head.max(initial=0))) if len(tail) else 0
    if mx >= len(pos):
        pos = np.concatenate(
            [pos, np.full(mx + 1 - len(pos), INVALID_JNID, np.uint32)])
    return pos, pos[tail].astype(np.int64), pos[head].astype(np.int64)


def native_or_none(impl: str):
    """Resolve the ``impl`` dispatch: the native module, or None for the
    python oracle.  "auto" prefers native when built; "native" requires it."""
    if impl not in ("auto", "python", "native"):
        raise ValueError(f"impl must be auto|python|native, got {impl!r}")
    if impl == "python":
        return None
    from .. import native
    if native.available():
        return native
    if impl == "native":
        raise RuntimeError("native runtime unavailable (build failed?)")
    return None


def _find(uf: np.ndarray, x: int) -> int:
    """Find with path compression; representative = max element of component."""
    root = x
    while uf[root] != root:
        root = uf[root]
    while uf[x] != root:
        uf[x], x = root, uf[x]
    return root


def build_forest_links(lo: np.ndarray, hi: np.ndarray, n: int,
                       pst: np.ndarray | None = None,
                       impl: str = "auto") -> Forest:
    """Build the elimination forest from links (lo -> hi), lo < hi elementwise.

    ``pst`` lets callers pass precomputed pst-weights (used by merge, where
    links are tree edges that must not be re-counted).  When None, each link
    contributes 1 to pst_weight[lo].

    ``impl``: "auto" uses the C++ runtime when built (sheep_tpu.native),
    "python" forces this module's loop (the oracle), "native" requires C++.
    """
    forest, _ = _build_forest_links_pre(lo, hi, n, pst, False, impl)
    return forest


def _build_forest_links_pre(lo, hi, n, pst, compute_pre: bool, impl: str):
    """Shared worker: returns (Forest, pre | None).

    ``compute_pre`` adds the reference's USE_PRE_WEIGHT accounting
    (lib/jnode.h:174-176 meetKid): each tree link adds 1 to pre[r] where r
    is lo's component root *before* this hi-group's adoptions — unions are
    deferred to the end of the group, matching adoptKids running after the
    whole edge scan (jtree.cpp:102)."""
    native = native_or_none(impl)
    if native is not None:
        out = native.build_forest_links(lo, hi, n, pst,
                                        compute_pre=compute_pre)
        if compute_pre:
            return Forest(out[0], out[1]), out[2]
        return Forest(out[0], out[1]), None
    if pst is None:
        pst = np.bincount(lo, minlength=n).astype(np.uint32)
    parent = np.full(n, INVALID_JNID, dtype=np.uint32)
    pre = np.zeros(n, dtype=np.uint32) if compute_pre else None
    uf = np.arange(n, dtype=np.int64)
    linked = hi < n  # hi >= n marks pst-only links (absent endpoint)
    lo, hi = lo[linked], hi[linked]
    order = np.argsort(hi, kind="stable")
    lo_s, hi_s = lo[order], hi[order]
    m = len(lo_s)
    i = 0
    while i < m:
        h = int(hi_s[i])
        adopted = []
        while i < m and int(hi_s[i]) == h:
            r = _find(uf, int(lo_s[i]))
            if pre is not None:
                pre[r] += 1
            if r != h and parent[r] == INVALID_JNID:
                # r is the max of its component and h > r: attach.
                parent[r] = h
                adopted.append(r)
            i += 1
        for r in adopted:  # deferred re-root (adoptKids)
            uf[r] = h
    return Forest(parent, pst.astype(np.uint32)), pre


class PyLinksFold:
    """Python-oracle twin of the native resumable fold
    (:class:`sheep_tpu.native.LinksFold`): the exact link build consumed
    one ascending-hi window at a time against shared union-find state.

    This is the parity oracle for the streaming windowed handoff and the
    fallback when the native runtime is unavailable.  Same contract:
    windows ascend by hi (an equal-hi group may split across adjacent
    windows — exact, because within one hi-group distinct component roots
    each adopt exactly once and repeats are no-ops regardless of order);
    an out-of-order window raises ValueError.  ``pst`` None accumulates
    pst from the streamed records (original-multiset callers only).
    """

    def __init__(self, n: int, pst: np.ndarray | None = None):
        self.n = n
        self.accumulate_pst = pst is None
        self.parent = np.full(n, INVALID_JNID, dtype=np.uint32)
        self._pst = np.zeros(n, dtype=np.int64) if pst is None \
            else np.asarray(pst, dtype=np.int64).copy()
        self._uf = np.arange(n, dtype=np.int64)
        self._bound = 0

    def block(self, lo: np.ndarray, hi: np.ndarray) -> None:
        n = self.n
        lo = np.asarray(lo, dtype=np.int64)
        hi = np.asarray(hi, dtype=np.int64)
        if len(lo) and int(lo.max()) >= n:
            raise ValueError(f"malformed link: lo >= n ({n})")
        if self.accumulate_pst and len(lo):
            self._pst += np.bincount(lo, minlength=n)[:n]
        linked = hi < n
        lo, hi = lo[linked], hi[linked]
        if len(hi) and int(hi.min()) < self._bound:
            raise ValueError(
                "out-of-order fold window: a linked hi precedes the "
                "previous window's range — windows must ascend by hi")
        order = np.argsort(hi, kind="stable")
        lo_s, hi_s = lo[order], hi[order]
        uf, parent = self._uf, self.parent
        m = len(lo_s)
        i = 0
        while i < m:
            h = int(hi_s[i])
            adopted = []
            while i < m and int(hi_s[i]) == h:
                r = _find(uf, int(lo_s[i]))
                if r != h and parent[r] == INVALID_JNID:
                    parent[r] = h
                    adopted.append(r)
                i += 1
            for r in adopted:  # deferred re-root (adoptKids)
                uf[r] = h
        if m:
            self._bound = max(self._bound, int(hi_s[-1]))

    def finish(self) -> tuple[np.ndarray, np.ndarray]:
        return self.parent, self._pst.astype(np.uint32)


def host_hi_window_bounds(hi: np.ndarray, w: int, n: int) -> list[int]:
    """Equal-count hi-quantile window boundaries over an UNSORTED host hi
    array — the numpy twin of parallel.chunked.hi_window_bounds
    (np.partition at the quantile ranks, no full sort, no device
    dispatch).  Window k keeps hi in [bounds[k], bounds[k+1]); used by
    the cpu-side split of the streaming windowed handoff (ops.build) and
    the driver's stream rung, so every windowing site shares one rule."""
    cnt = len(hi)
    ks = sorted({(k * cnt) // w for k in range(1, w)})
    if not ks or cnt == 0:
        return [0, n]
    mid = np.partition(np.asarray(hi), ks)[ks]
    return [0, *(int(x) for x in mid), n]


def links_fold(n: int, pst: np.ndarray | None = None, impl: str = "auto"):
    """Resolve a resumable link fold: the native
    :class:`~sheep_tpu.native.LinksFold` when built, else the
    :class:`PyLinksFold` oracle.  Both expose ``block(lo, hi)`` +
    ``finish() -> (parent, pst)`` with identical semantics."""
    native = native_or_none(impl)
    if native is not None:
        return native.LinksFold(n, pst)
    return PyLinksFold(n, pst)


def pre_weights(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
                max_vid: int | None = None, impl: str = "auto") -> np.ndarray:
    """The reference's pre_weight array for a graph + sequence.

    pre[k] = number of graph edges between parent(k) and k's subtree at
    adoption time (lib/jnode.h:174-176); the partitioner's -u weight model
    sums each node's kids' pre (lib/partition.cpp:44-46).  Computed by
    re-running the link build with meetKid accounting.
    """
    lo, hi = edges_to_positions(tail, head, seq, max_vid)
    _, pre = _build_forest_links_pre(lo, hi, len(seq), None, True, impl)
    return pre


def build_forest(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
                 max_vid: int | None = None, impl: str = "auto") -> Forest:
    """Build from raw edge records over a (possibly partial) graph."""
    native = native_or_none(impl)
    if native is not None:
        pos = sequence_positions(seq, max_vid)
        if native.blocked_enabled():
            # fused round-6 kernel: records group straight into the
            # cache-blocked union-find; the intermediate link arrays
            # (~0.5GB of stream traffic at 2^23) never materialize
            p, w = native.build_forest_edges(tail, head, pos, len(seq))
            return Forest(p, w)
        lo, hi = native.edges_to_links(tail, head, pos)
        p, w = native.build_forest_links(lo, hi, len(seq))
        return Forest(p, w)
    lo, hi = edges_to_positions(tail, head, seq, max_vid)
    return build_forest_links(lo, hi, len(seq), impl=impl)


def build_forest_streaming(blocks, seq: np.ndarray,
                           max_vid: int | None = None,
                           impl: str = "auto") -> Forest:
    """Bounded-memory forest build from edge blocks (the host OOM path).

    The reference's OOM regime streams edge slices through workers and
    stitches them with the associative merge (jnode.cpp:174-201,
    data/oom/); this is that fold on one host: per block, map records
    through the position table, run the exact union-find on (carry links +
    block links), and keep only the resulting forest's links as the carry.
    O(n + block) resident for any edge count, bit-identical to the
    whole-graph build.  pst accumulates per block (each link counts at its
    present earlier endpoint, including links to absent vids —
    jtree.cpp:47-49).
    """
    n = len(seq)
    pos = sequence_positions(seq, max_vid)
    pst = np.zeros(n, dtype=np.int64)
    zero_pst = np.zeros(n, dtype=np.uint32)  # pst tracked here, not per fold
    carry_lo = np.empty(0, dtype=np.int64)
    carry_hi = np.empty(0, dtype=np.int64)
    forest = Forest(np.full(n, INVALID_JNID, dtype=np.uint32),
                    np.zeros(n, dtype=np.uint32))
    for tail, head in blocks:
        pos, pt, ph = _positions_through(pos, tail, head)
        keep = pt != ph  # drops self-loops and both-absent
        pt, ph = pt[keep], ph[keep]
        lo = np.minimum(pt, ph)
        hi = np.maximum(pt, ph)
        # lo is the present endpoint even for pst-only links (hi INVALID)
        pst += np.bincount(lo, minlength=n)[:n]
        tree = hi < n
        fold_lo = np.concatenate([carry_lo, lo[tree]])
        fold_hi = np.concatenate([carry_hi, hi[tree]])
        forest = build_forest_links(fold_lo, fold_hi, n, pst=zero_pst,
                                    impl=impl)
        carry_lo, carry_hi = forest_links(forest)
    return Forest(forest.parent, pst.astype(np.uint32))


def forest_links(forest: Forest) -> tuple[np.ndarray, np.ndarray]:
    """A tree's (kid, parent) pairs as link arrays."""
    kids = np.nonzero(forest.parent != INVALID_JNID)[0].astype(np.int64)
    return kids, forest.parent[kids].astype(np.int64)


def merge_forests(*forests: Forest) -> Forest:
    """Associative merge of same-sequence partial forests.

    Equivalent to the reference's pairwise merge (lib/jnode.cpp:174-201) /
    MPI_Reduce custom op (:203-250): pst_weights add; parent links from all
    inputs are replayed as links in ascending-parent order.

    Merging partial forests is only meaningful over the SAME sequence —
    trees of different length cannot share one, so a length clash is a
    typed IncompatibleMerge, not an assert (a stripped ``python -O`` run
    must not zip mismatched trees silently).
    """
    from ..integrity.errors import IncompatibleMerge
    if len(forests) < 1:
        raise IncompatibleMerge("merge of zero forests")
    n = forests[0].n
    sizes = [f.n for f in forests]
    if any(s != n for s in sizes):
        raise IncompatibleMerge(
            f"cannot merge forests of differing length {sizes} — partial "
            f"trees must come from the same sequence over the same graph")
    pst = np.zeros(n, dtype=np.uint64)
    los, his = [], []
    for f in forests:
        pst += f.pst_weight
        k, p = forest_links(f)
        los.append(k)
        his.append(p)
    lo = np.concatenate(los) if los else np.empty(0, dtype=np.int64)
    hi = np.concatenate(his) if his else np.empty(0, dtype=np.int64)
    return build_forest_links(lo, hi, n, pst=pst.astype(np.uint32))
