"""Tree facts ("FAQs") — the reference's smoke-test analytics.

Single ascending pass over the forest (lib/jnode.cpp:256-290), printed by
``graph2tree -f`` / ``partition_tree -f`` with the exact TREEFAQS grammar
(lib/jnode.h:285-291), which downstream plot scripts grep.

Width here is the *default-path* width ``1 + pst_weight`` (lib/jnode.h:258-
260, no jxn tables); fill is then 0 by construction.  Quirks replicated
faithfully: ``core_id`` is the first id whose width matches the running max,
which is always id 0; ``halo_id`` is the first id of width > 3.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .. import INVALID_JNID
from .forest import Forest


@dataclass
class Facts:
    vert_cnt: int
    edge_cnt: int
    width: int
    fill: int
    vert_height: int
    edge_height: int
    root_cnt: int
    halo_id: int
    core_id: int

    def print(self) -> None:
        print(f"TREEFAQS: width:{self.width}\troots:{self.root_cnt}")
        print(f"\tvheight:{self.vert_height}\teheight:{self.edge_height}")
        print(f"\tverts:{self.vert_cnt}\tedges:{self.edge_cnt}")
        print(f"\thalo:{self.halo_id}\tcore:{self.core_id}")
        print(f"\tfill:{self.fill}")


def compute_facts(forest: Forest, widths: np.ndarray | None = None) -> Facts:
    n = forest.n
    parent = forest.parent
    pst = forest.pst_weight.astype(np.int64)
    if widths is None:
        widths = 1 + pst
    fill = int((widths - pst - 1).sum())

    vheight = np.zeros(n, dtype=np.int64)
    eheight = np.zeros(n, dtype=np.int64)
    vert_height = 0
    edge_height = 0
    root_cnt = 0
    # Sequential ascending DP (kids always precede parents).
    par = parent.astype(np.int64)
    par[parent == INVALID_JNID] = -1
    for i in range(n):
        vheight[i] += 1
        eheight[i] += pst[i]
        p = par[i]
        if p >= 0:
            if vheight[p] < vheight[i]:
                vheight[p] = vheight[i]
            if eheight[p] < eheight[i]:
                eheight[p] = eheight[i]
        else:
            vert_height = max(vert_height, int(vheight[i]))
            edge_height = max(edge_height, int(eheight[i]))
            root_cnt += 1

    halo = np.nonzero(widths > 3)[0]
    return Facts(
        vert_cnt=n,
        edge_cnt=int(pst.sum()),
        width=int(widths.max(initial=0)),
        fill=fill,
        vert_height=vert_height,
        edge_height=edge_height,
        root_cnt=root_cnt,
        halo_id=int(halo[0]) if len(halo) else INVALID_JNID,
        core_id=0 if n else INVALID_JNID,
    )
