"""Sequence engine: vertex elimination orders.

The default Sheep order is *ascending degree, ties broken by ascending vid*
(lib/sequence.h:52-63 degreeSequence; identical comparator in mpiSequence
:85-91 and fileSequence :114-120).  Only vertices with nonzero degree enter
the sequence (the node iterator skips 0-degree vertices,
graph_wrapper.h:97-100; fileSequence filters degree==0, sequence.h:110-112).

All variants in the reference (serial, MPI-Allreduce, file-streaming) compute
the *same* order given the same whole-graph degrees — every MPI rank sorts an
identical replicated histogram.  Here the host version is a numpy lexsort;
the device/mesh versions live in sheep_tpu.ops / sheep_tpu.parallel and are
tested equal to this one.
"""

from __future__ import annotations

import numpy as np


def host_degree_histogram(tail: np.ndarray, head: np.ndarray,
                          n: int) -> np.ndarray:
    """Undirected-doubled degrees on host: native C++ when built, numpy
    bincount otherwise.  Each record adds 1 to both endpoints; a self-loop
    adds 2 (graph_wrapper.h:87-89 semantics)."""
    from .. import native
    if native.available():
        return native.degree_histogram(tail, head, n)
    return (np.bincount(tail, minlength=n)
            + np.bincount(head, minlength=n)).astype(np.int64)


def degree_sequence_from_degrees(deg: np.ndarray,
                                 impl: str = "auto") -> np.ndarray:
    """Sequence from a dense degree histogram (vid-indexed)."""
    if impl != "python":
        from .. import native
        if native.available():
            seq = native.degree_sequence_from_degrees(deg)
            if seq is not None:  # None: degree range too wide for buckets
                return seq
    vids = np.nonzero(deg)[0]
    order = np.lexsort((vids, deg[vids]))  # primary: degree asc, tie: vid asc
    return vids[order].astype(np.uint32)


def degree_sequence(tail: np.ndarray, head: np.ndarray,
                    num_vertices: int | None = None) -> np.ndarray:
    """Ascending-degree sequence from edge records (whole graph)."""
    n = num_vertices
    if n is None:
        n = int(max(tail.max(initial=0), head.max(initial=0))) + 1 if len(tail) else 0
    from .. import native
    if native.available() and native.blocked_enabled():
        # fused round-6 kernel (uint32 histogram + counting sort in one
        # native call); None = range outgrew its buckets, fall through
        seq = native.degree_sequence_from_edges(tail, head, n)
        if seq is not None:
            return seq
    return degree_sequence_from_degrees(host_degree_histogram(tail, head, n))


def default_sequence(deg: np.ndarray) -> np.ndarray:
    """Vertices in vid order, degree-0 skipped (lib/sequence.h:43-50)."""
    return np.nonzero(deg)[0].astype(np.uint32)


def sequence_positions(seq: np.ndarray, max_vid: int | None = None) -> np.ndarray:
    """Invert a sequence into a vid->position map; 0xFFFFFFFF where absent."""
    n = int(max_vid) + 1 if max_vid is not None else (int(seq.max()) + 1 if len(seq) else 0)
    n = max(n, int(seq.max()) + 1 if len(seq) else 0)
    pos = np.full(n, 0xFFFFFFFF, dtype=np.uint32)
    pos[seq] = np.arange(len(seq), dtype=np.uint32)
    return pos
