"""Treewidth / fill-in ("jxn") mode — the parameterized insert path.

Reference semantics (lib/jtree.cpp:65-231, lib/jnode.h:158-253): when kids /
pst / jxn tables are requested, each inserted vertex X additionally records

  kids(X)  the subtree roots X adopts (adoption deferred until the insert
           is known to succeed),
  pst(X)   X's not-yet-inserted neighbor vids, sorted and deduplicated
           (pst_weight still counts edge multiplicity),
  jxn(X)   the elimination fill-in: union of the kids' jxns plus pst(X),
           minus X itself — sorted by vid.

``width(X) = 1 + |jxn(X)|`` (lib/jnode.h:258-260); the max over the tree is
an upper bound on treewidth + 1 for the given elimination sequence.  A
vertex whose postorder multiplicity or merged jxn would exceed
``width_limit`` is rejected and deferred to the tail (``wide_seq``,
jtree.cpp:107-109,139-140).  Deferred and unvisited vertices then form a
root chain whose jxns are the trivially-shrinking remaining-vertex set
(jtree.cpp:152-222).  ``find_max_width`` stops early once the running max
width can no longer be exceeded; ``do_rooting`` switches to the chain as
soon as a node's width equals the remaining-vertex count.

Deviation from the reference, documented: on a width-limit rejection the
reference has already scribbled ``parent(root) = current`` for met kids and
cannot revoke it (the "XXX cannot be revoked" comment at jtree.cpp:99 only
defers union-find, not the parent writes), leaving stale parent pointers on
roots the deleted jnid never adopted.  Here the rejection is atomic — no
state leaks — which is the evident intent.

This is a host-side feature in the reference and stays host-side here: the
dynamic, data-dependent set unions are the antithesis of XLA-friendly
shapes, and the default distributed path never builds these tables
(SURVEY §7 structural insight).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from .. import INVALID_JNID
from .forest import Forest


@dataclass
class JxnOptions:
    """Mirror of JTree::Options (lib/jtree.h:71-108)."""

    verbose: bool = False
    make_pad: bool = True
    make_kids: bool = False
    make_pst: bool = False
    make_jxn: bool = False
    memory_limit: int = 1 << 30
    width_limit: int = 0  # 0 = unlimited (CLI -w unset)
    find_max_width: bool = False
    # The reference also declares ``rooting_limit`` (lib/jtree.h:84) but
    # never reads it outside the option-validity matrix (jtree.h:106) — it
    # is dead there, so it is deliberately not mirrored here.
    do_rooting: bool = False

    def effective_width_limit(self) -> int:
        return self.width_limit if self.width_limit > 0 else (1 << 62)


@dataclass
class JxnTree:
    """Forest plus the optional kids/pst/jxn tables, all jnid-indexed."""

    forest: Forest
    seq: np.ndarray                      # jnid -> vid (effective order)
    kids: list[list[int]] | None = None
    pst: list[np.ndarray] | None = None  # sorted dedup'd vids
    jxn: list[np.ndarray] | None = None  # sorted vids

    @property
    def widths(self) -> np.ndarray:
        """1 + |jxn| where jxn exists, else 1 + pst_weight."""
        n = self.forest.n
        w = 1 + self.forest.pst_weight.astype(np.int64)
        if self.jxn is not None:
            for i, jx in enumerate(self.jxn):
                if jx is not None:
                    w[i] = 1 + len(jx)
        return w


class _Csr:
    """Host CSR adjacency of the undirected-doubled graph."""

    def __init__(self, tail: np.ndarray, head: np.ndarray, n: int):
        src = np.concatenate([tail, head]).astype(np.int64)
        dst = np.concatenate([head, tail]).astype(np.int64)
        order = np.argsort(src, kind="stable")
        self.dst = dst[order]
        self.offs = np.zeros(n + 1, dtype=np.int64)
        np.add.at(self.offs, src + 1, 1)
        np.cumsum(self.offs, out=self.offs)
        self.deg = np.diff(self.offs)

    def neighbors(self, v: int) -> np.ndarray:
        return self.dst[self.offs[v]:self.offs[v + 1]]


def _find(uf: list[int], x: int) -> int:
    root = x
    while uf[root] != root:
        root = uf[root]
    while uf[x] != root:
        uf[x], x = root, uf[x]
    return root


def build_jxn_tree(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
                   opts: JxnOptions,
                   num_vertices: int | None = None) -> JxnTree:
    n_vid = num_vertices
    if n_vid is None:
        mx = int(max(tail.max(initial=0), head.max(initial=0))) if len(tail) else -1
        n_vid = max(mx + 1, int(seq.max(initial=0)) + 1 if len(seq) else 0)
    csr = _Csr(tail, head, n_vid)
    wlimit = opts.effective_width_limit()

    index = np.full(n_vid, INVALID_JNID, dtype=np.uint32)
    parent: list[int] = []
    pst_weight: list[int] = []
    out_seq: list[int] = []
    kids_tbl: list[list[int]] = []
    pst_tbl: list[np.ndarray] = []
    jxn_tbl: list[np.ndarray | None] = []
    uf: list[int] = []
    mem_used = 0

    def check_mem(extra_items: int) -> None:
        nonlocal mem_used
        mem_used += 4 * extra_items
        if mem_used > opts.memory_limit:
            raise MemoryError(
                f"pst/jxn tables exceed memory_limit={opts.memory_limit}")

    wide_seq: list[int] = []
    stopped_at: int | None = None  # seq index where normal insertion stopped
    current_width = 0
    seq_list = [int(v) for v in seq]

    for si, X in enumerate(seq_list):
        if not opts.make_pad and csr.deg[X] == 0:
            continue
        current = len(parent)
        pw = 0
        pvids: list[int] = []
        ks: list[int] = []
        ks_seen: set[int] = set()  # O(1) met-root dedup (meetKid's check)
        fail = False
        for nbr in csr.neighbors(X).tolist():
            nid = int(index[nbr])
            if nid != INVALID_JNID:
                r = _find(uf, nid)
                if r not in ks_seen:
                    ks_seen.add(r)
                    ks.append(r)
            elif nbr != X:
                pw += 1
                if pw > wlimit:
                    fail = True
                    break
                pvids.append(nbr)
        jx: np.ndarray | None = None
        if not fail:
            pvids_u = np.unique(np.asarray(pvids, dtype=np.int64))
            if opts.make_jxn:
                pieces = [jxn_tbl[k] for k in ks if jxn_tbl[k] is not None
                          and len(jxn_tbl[k])]
                pieces.append(pvids_u)
                jx = np.unique(np.concatenate(pieces)) if pieces else \
                    np.empty(0, dtype=np.int64)
                jx = jx[jx != X]
                if len(jx) > wlimit:
                    fail = True
        if fail:
            # The reference runs the find_max_width bound check on FAILED
            # inserts too, before X joins wide_seq (jtree.cpp:130-136).
            if opts.find_max_width and \
                    current_width >= len(wide_seq) + (len(seq_list) - si):
                return _finish(parent, pst_weight, out_seq, kids_tbl,
                               pst_tbl, jxn_tbl, opts)
            wide_seq.append(X)
            continue

        # Commit (atomic)
        parent.append(INVALID_JNID)
        pst_weight.append(pw)
        out_seq.append(X)
        uf.append(current)
        for r in ks:
            parent[r] = current
            uf[r] = current
        kids_tbl.append(ks)
        if opts.make_pst:
            check_mem(len(pvids_u))
            pst_tbl.append(pvids_u)
        if opts.make_jxn:
            check_mem(len(jx))
        jxn_tbl.append(jx)
        index[X] = current

        # ``remaining`` counts X itself plus everything still to insert,
        # matching std::distance(seq_itr, cend()) + wide_seq.size() at
        # jtree.cpp:134,141 (seq_itr still points at X there).
        remaining = len(wide_seq) + (len(seq_list) - si)
        if opts.find_max_width:
            current_width = max(current_width, 1 + (len(jx) if jx is not None
                                                    else pw))
            if current_width >= remaining:
                return _finish(parent, pst_weight, out_seq, kids_tbl, pst_tbl,
                               jxn_tbl, opts)
        # width falls back to 1 + pst_weight when jxn tables are off
        # (lib/jnode.h:258-260), so rooting works in pst-only mode too.
        cur_w = 1 + (len(jx) if jx is not None else pw)
        if opts.do_rooting and cur_w == remaining:
            stopped_at = si + 1
            break

    # Tail phase: deferred + unvisited vertices become a root chain.
    rest = wide_seq + (seq_list[stopped_at:] if stopped_at is not None else [])
    for ti, X in enumerate(rest):
        current = len(parent)
        parent.append(INVALID_JNID)
        uf.append(current)
        out_seq.append(X)
        ks = []
        if ti == 0:
            for kid in range(current):
                if parent[kid] == INVALID_JNID:
                    parent[kid] = current
                    uf[kid] = current
                    ks.append(kid)
        else:
            prev = current - 1
            parent[prev] = current
            uf[prev] = current
            ks.append(prev)
        kids_tbl.append(ks)
        pw = 0
        pvids = []
        for nbr in csr.neighbors(X).tolist():
            if index[nbr] == INVALID_JNID and nbr != X:
                pw += 1
                pvids.append(nbr)
        pst_weight.append(pw)
        if opts.make_pst:
            pvids_u = np.unique(np.asarray(pvids, dtype=np.int64))
            # the reference's arena charges tail-phase pst allocations too
            # (newPst -> JDataTable, jtree.cpp:168,177)
            check_mem(len(pvids_u))
            pst_tbl.append(pvids_u)
        # jxn is the trivially-shrinking remaining set (jtree.cpp:182-186);
        # only materialized (and charged against memory_limit) in jxn mode.
        if opts.make_jxn:
            jx = np.sort(np.asarray(rest[ti + 1:], dtype=np.int64))
            check_mem(len(jx))
            jxn_tbl.append(jx)
        else:
            jxn_tbl.append(None)
        index[X] = current
        if ti == 0 and opts.find_max_width:
            return _finish(parent, pst_weight, out_seq, kids_tbl, pst_tbl,
                           jxn_tbl, opts)

    return _finish(parent, pst_weight, out_seq, kids_tbl, pst_tbl, jxn_tbl,
                   opts)


def _finish(parent, pst_weight, out_seq, kids_tbl, pst_tbl, jxn_tbl,
            opts: JxnOptions) -> JxnTree:
    forest = Forest(np.asarray(parent, dtype=np.uint32),
                    np.asarray(pst_weight, dtype=np.uint32))
    return JxnTree(
        forest=forest,
        seq=np.asarray(out_seq, dtype=np.uint32),
        kids=kids_tbl if opts.make_kids else None,
        pst=pst_tbl if opts.make_pst else None,
        jxn=jxn_tbl if opts.make_jxn else None,
    )


def build_forest_jxn(tail: np.ndarray, head: np.ndarray, seq: np.ndarray,
                     opts: JxnOptions, impl: str = "auto"):
    """CLI adapter: returns (forest, effective_seq, widths-or-None).

    Dispatches to the C++ twin (sheep_native.cpp sheep_jxn_build) when
    built — the reference runs -kejx on million-vertex graphs, far beyond
    the python oracle's reach.  The oracle (build_jxn_tree) additionally
    materializes the kids/pst/jxn tables for tests and library callers.
    """
    from .forest import native_or_none
    native = native_or_none(impl)
    if native is not None:
        n_vid = int(max(tail.max(initial=0), head.max(initial=0))) \
            if len(tail) else -1
        n_vid = max(n_vid + 1, int(seq.max(initial=0)) + 1 if len(seq) else 0)
        parent, pst, out_seq, widths = native.jxn_build(
            tail, head, seq, n_vid, opts.width_limit, opts.memory_limit,
            opts.make_pad, opts.make_pst, opts.make_jxn,
            opts.find_max_width, opts.do_rooting)
        forest = Forest(parent, pst)
        return forest, out_seq, (widths if opts.make_jxn else None)
    tree = build_jxn_tree(tail, head, seq, opts)
    widths = tree.widths if opts.make_jxn else None
    return tree.forest, tree.seq, widths
