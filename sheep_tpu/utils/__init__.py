from .synth import rmat_edges

__all__ = ["rmat_edges"]
