"""JAX version compatibility shims.

The package targets the current jax API surface (``jax.shard_map``,
``jax.enable_x64``), but deployment containers pin older releases where
those names still live under ``jax.experimental``.  Importing through this
module keeps every call site on one spelling; the fallbacks can be deleted
once the fleet's minimum jax passes 0.4.x.
"""

from __future__ import annotations

import jax

try:  # newer jax re-exports shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - exercised only on old jax
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

import inspect as _inspect

if "check_vma" in _inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:  # pragma: no cover - old jax spells the replication check check_rep
    def shard_map(f, *, check_vma=True, **kw):
        return _shard_map(f, check_rep=check_vma, **kw)

if hasattr(jax, "enable_x64"):
    enable_x64 = jax.enable_x64
else:  # pragma: no cover - exercised only on old jax
    from jax.experimental import enable_x64  # type: ignore

__all__ = ["shard_map", "enable_x64"]
