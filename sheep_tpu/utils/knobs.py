"""The ``SHEEP_*`` knob registry (ISSUE 15).

Fifteen PRs grew ~100 environment knobs, each documented (if at all) in
the docstring nearest its ``os.environ.get`` — the planner refactor
makes them *overrides* of one cost model, which only works if there is
one authoritative list of what can be overridden.  This module IS that
list: every knob's name, value type, default, owning subsystem, and a
one-line doc, declared once.

Consumers:

  sheep_tpu/plan   each :class:`~sheep_tpu.plan.model.Decision` names
                   the registry knob that can force it, so ``sheep plan
                   --explain`` can say "set SHEEP_EXT_BLOCK to pin this".
  README.md        the "Configuration knobs" table is GENERATED from
                   this registry (``python -m sheep_tpu.utils.knobs
                   --markdown``) between the KNOBS:BEGIN/END markers;
                   a test asserts it is in sync.
  tests/test_knobs the enforcement: a grep over the package's env reads
                   (Python string literals and the native kernels'
                   ``std::getenv`` calls) fails on any knob missing
                   here, and on any registry entry no code reads —
                   a knob cannot be added or retired silently.

Value types: ``flag`` (0/1), ``int``, ``float``, ``str``, ``size``
(human sizes, ``512M``/``2G`` — resources.governor.parse_size), ``path``,
``plan`` (a fault-plan grammar), ``list`` (comma-separated specs).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Knob:
    name: str
    type: str
    default: str      # rendered default ("" = unset; prose is allowed)
    subsystem: str
    doc: str


_K = Knob

#: every SHEEP_* knob, grouped by subsystem, in table render order.
KNOBS: dict[str, Knob] = {k.name: k for k in [
    # -- planner (ISSUE 15) ------------------------------------------------
    _K("SHEEP_PLAN_PRIORS", "path", "",
       "plan", "measured-prior store the planner folds into its cost "
       "model (learned from ladder.plan traces + bench records); unset "
       "= analytic model only"),
    # -- resource budgets (ISSUE 5) ----------------------------------------
    _K("SHEEP_MEM_BUDGET", "size", "",
       "resources", "memory budget; the governor prices rungs/threads/"
       "blocks against it and refuses what cannot fit"),
    _K("SHEEP_DISK_BUDGET", "size", "",
       "resources", "cap on sheep-owned bytes under managed dirs; "
       "retention GC reclaims when tripped"),
    _K("SHEEP_SCRATCH_DIR", "path", "",
       "resources", "where the spill rung's scratch files live "
       "(fallback: checkpoint dir, then the system temp dir)"),
    _K("SHEEP_LEG_CORES", "int", "0",
       "resources", "CPU cores per supervised leg; caps concurrency, "
       "pins subprocess legs, and caps the native thread plan"),
    # -- build runtime (ISSUE 1) -------------------------------------------
    _K("SHEEP_CHECKPOINT_DIR", "path", "",
       "runtime", "chunk-boundary checkpoint directory of the resilient "
       "build"),
    _K("SHEEP_RESUME", "flag", "0",
       "runtime", "resume from the checkpoint dir instead of starting "
       "fresh"),
    _K("SHEEP_MAX_RETRIES", "int", "3",
       "runtime", "per-dispatch (and per-leg) retry budget"),
    _K("SHEEP_BACKOFF_BASE", "float", "0.05",
       "runtime", "retry backoff base seconds (exponential, capped)"),
    _K("SHEEP_WATCHDOG_S", "float", "",
       "runtime", "per-dispatch watchdog; a dispatch stuck past this is "
       "treated as faulted"),
    _K("SHEEP_CHECKPOINT_EVERY", "str", "1",
       "runtime", "checkpoint cadence in boundaries; 'auto' tunes it "
       "from measured snapshot cost"),
    _K("SHEEP_PROMOTE_AFTER", "int", "16",
       "runtime", "healthy dispatches before a rung promotes back to "
       "the pipelined fast path (0 = never)"),
    _K("SHEEP_EDGES_PATH", "path", "",
       "runtime", "the whole-input .dat file; arms the ext rung for "
       "library/script builds"),
    _K("SHEEP_FAULT_INJECT", "plan", "",
       "runtime", "deterministic runtime fault plan kind@site:nth "
       "(chunk loops, boundaries)"),
    # -- integrity (ISSUE 2) -----------------------------------------------
    _K("SHEEP_INTEGRITY", "str", "strict",
       "integrity", "artifact read policy: strict / repair / trust"),
    _K("SHEEP_SELFCHECK", "flag", "0",
       "integrity", "structural forest self-check after the parallel "
       "build"),
    _K("SHEEP_VALIDATE_LOOP", "flag", "0",
       "integrity", "exact per-vertex root-path validator (slow oracle) "
       "instead of the vectorized check"),
    # -- device/mesh reduce core (ISSUES 4/8) ------------------------------
    _K("SHEEP_WORKERS", "int", "devices",
       "mesh", "worker count of the fused SPMD build (default: visible "
       "devices)"),
    _K("SHEEP_MESH_KERNEL", "str", "chunked",
       "mesh", "mesh build kernel: chunked or fused"),
    _K("SHEEP_MESH_GATHER_TAIL", "flag", "1",
       "mesh", "gather the mesh tail for the replicated finish"),
    _K("SHEEP_MESH_GATHER_FACTOR", "float", "2.0",
       "mesh", "live-links factor below which the mesh tail gathers"),
    _K("SHEEP_MESH_TAIL_SHARD", "flag", "1",
       "mesh", "shard the gathered tail by hi-quantile windows before "
       "the replicated finish"),
    _K("SHEEP_MESH_TAIL_SHARD_ROUNDS", "int", "5",
       "mesh", "max sharded tail rounds before falling back replicated"),
    _K("SHEEP_PIPELINE_CHUNKS", "flag", "1",
       "mesh", "pipelined (async) chunk dispatch in the chunk loops"),
    _K("SHEEP_PLATEAU_ADAPT", "flag", "1",
       "mesh", "plateau-adaptive chunk scheduler (j=1 late tiers + host "
       "straggler assist)"),
    _K("SHEEP_PLATEAU_FORCE", "flag", "0",
       "mesh", "force the plateau assist from round one (A/B arm)"),
    _K("SHEEP_PLATEAU_ASSIST_CAP", "int", "131072",
       "mesh", "max stragglers the host assist walks per round"),
    _K("SHEEP_VREMAP", "flag", "1",
       "mesh", "live-vertex remap compaction between chunk rounds"),
    _K("SHEEP_SORT_PACK64", "str", "",
       "mesh", "pack64 device sort arm: 1 forces, 0 disables, unset "
       "auto"),
    _K("SHEEP_PALLAS", "str", "",
       "mesh", "pallas jump-table kernel: 1 on-device, 'interpret' "
       "interpreter mode, unset off"),
    _K("SHEEP_ICI_GBPS", "float", "",
       "mesh", "assumed per-link ICI bandwidth for bench modeling"),
    # -- streaming handoff / hybrid tail (ISSUE 8) -------------------------
    _K("SHEEP_STREAM_HANDOFF", "flag", "1",
       "stream", "streaming windowed handoff for the hybrid tail"),
    _K("SHEEP_HANDOFF_WINDOWS", "int", "cpu 1 / accel 4",
       "stream", "hi-quantile window count W of the streamed handoff"),
    _K("SHEEP_HANDOFF_FACTOR", "int", "8",
       "stream", "live-links factor gating the handoff to the native "
       "tail"),
    _K("SHEEP_STREAM_DEVICE_WINDOWS", "flag", "0",
       "stream", "force the accelerator window-queue transfer path "
       "(tests/A-B on cpu)"),
    _K("SHEEP_STREAM_HOST_SEQ", "flag", "cpu 1",
       "stream", "host-native counting-sort degree sequence for the "
       "streaming hybrid"),
    _K("SHEEP_PACK_HANDOFF", "flag", "0",
       "stream", "pack (h<<32|lo) handoff records across serial+stream "
       "fetches"),
    _K("SHEEP_OVERLAP_HANDOFF", "flag", "0",
       "stream", "legacy speculative-snapshot overlap arm (round 4/5 "
       "A/B)"),
    _K("SHEEP_OVERLAP_SLICE", "int", "262144",
       "stream", "links per async fetch slice of the overlap path"),
    _K("SHEEP_OVERLAP_MIN_MB", "int", "4",
       "stream", "minimum fetch size worth overlapping"),
    _K("SHEEP_OVERLAP_SPEC_FACTOR", "int", "8",
       "stream", "speculative-snapshot size factor of the legacy "
       "overlap arm"),
    # -- out-of-core + distributed ext (ISSUES 9/13) -----------------------
    _K("SHEEP_EXT_BLOCK", "size", "524288 records",
       "extmem", "ext rung block size in edge records; pinning it is "
       "part of the checkpoint resume identity"),
    _K("SHEEP_EXT_STRATEGY", "str", "priced",
       "extmem", "per-block fold strategy: edges / links (unset = the "
       "governor's priced pick)"),
    _K("SHEEP_DISTEXT_LEGS", "int", "0",
       "extmem", "pin the distributed out-of-core leg count (0 = the "
       "planner picks)"),
    # -- native kernels (ISSUES 4/14) --------------------------------------
    _K("SHEEP_NATIVE_BLOCKED", "flag", "1",
       "native", "cache-blocked quantile-bucketed native kernels"),
    _K("SHEEP_NATIVE_THREADS", "int", "planned",
       "native", "native kernel thread count T (the planner resolves "
       "it from effective cores; a pin is the operator's word)"),
    _K("SHEEP_NATIVE_OVERSUB", "flag", "0",
       "native", "let a forced T exceed granted cores (time-sharing "
       "opt-in; read by the C++ runtime)"),
    _K("SHEEP_NATIVE_THREAD_FLOOR", "size", "262144",
       "native", "problem size below which threading disengages (0 "
       "engages always; read by the C++ runtime)"),
    _K("SHEEP_NATIVE_TIME", "flag", "0",
       "native", "stderr phase timers inside the native kernels (dev "
       "observability; read by the C++ runtime)"),
    # -- supervisor (ISSUE 3) ----------------------------------------------
    _K("SHEEP_DEADLINE_S", "float", "30",
       "supervisor", "heartbeat wall-clock deadline; a worker silent "
       "past this is dead"),
    _K("SHEEP_STALE_POLLS", "int", "0",
       "supervisor", "declare a silent worker dead after this many "
       "consecutive beat-free supervisor polls instead of wall clock "
       "alone (deterministic under whole-process stalls; 0 = off)"),
    _K("SHEEP_HEARTBEAT_S", "float", "1",
       "supervisor", "worker heartbeat interval"),
    _K("SHEEP_HEARTBEAT_FILE", "path", "",
       "supervisor", "where a worker beats (set per attempt by the "
       "supervisor's runner)"),
    _K("SHEEP_SPECULATE_S", "float", "",
       "supervisor", "age at which a still-beating straggler gets a "
       "speculative twin (unset = off)"),
    _K("SHEEP_FAULT_PLAN", "plan", "",
       "supervisor", "deterministic tournament chaos kind@round:leg "
       "(kill/corrupt/hang/stop)"),
    # -- remote build workers (ISSUE 16) -----------------------------------
    _K("SHEEP_WORKER_ADDRS", "list", "",
       "worker", "remote build workers host:port the distext "
       "supervisor may ship legs to (unset = local legs only)"),
    _K("SHEEP_WORKER_BEAT_S", "float", "1",
       "worker", "wire heartbeat interval for remote legs (BEAT "
       "frames; feeds the same staleness machinery as local .hb "
       "mtimes)"),
    _K("SHEEP_WORKER_SPECULATE_S", "float", "",
       "worker", "silent-wire age (since the last BEAT) at which a "
       "remote leg gets a speculative twin; first finisher wins "
       "(unset = generic SHEEP_SPECULATE_S only)"),
    _K("SHEEP_WORKER_TRANSPORT", "str", "",
       "worker", "pin the per-leg transport decision: ship / local "
       "(unset = the planner prices network-ship vs local-disk)"),
    # -- io faults (ISSUE 5) -----------------------------------------------
    _K("SHEEP_IO_FAULT_PLAN", "plan", "",
       "io", "deterministic I/O fault plan kind@site:nth over the "
       "write/read sites"),
    # -- observability (ISSUES 10/12) --------------------------------------
    _K("SHEEP_TRACE", "path", "",
       "obs", "flight-recorder JSONL path; unset = tracing off "
       "(no-op singletons)"),
    _K("SHEEP_TRACE_MAX_MB", "float", "0",
       "obs", "rotate the active trace to numbered .NNNN.trace "
       "segments past this size (0 = never)"),
    _K("SHEEP_TRACE_SAMPLE", "str", "1",
       "obs", "span sampling rate 1/N for per-request spans"),
    # -- serve daemon (ISSUES 6/7/11) --------------------------------------
    _K("SHEEP_SERVE_DEADLINE_S", "float", "",
       "serve", "default per-request deadline"),
    _K("SHEEP_SERVE_MAX_INFLIGHT", "int", "64",
       "serve", "admission cap; overload shed past it (inserts first)"),
    _K("SHEEP_SERVE_SNAP_EVERY", "int", "256",
       "serve", "snapshot seal cadence in applied inserts"),
    _K("SHEEP_SERVE_DRIFT", "float", "0.5",
       "serve", "cut-insert drift fraction triggering background "
       "repartition"),
    _K("SHEEP_SERVE_DRIFT_MIN", "int", "64",
       "serve", "minimum cut inserts before drift can trigger"),
    _K("SHEEP_SERVE_GROUP_COMMIT_MAX", "int", "256",
       "serve", "max records one shared group-commit fsync may cover; "
       "a full window seals immediately"),
    _K("SHEEP_SERVE_GROUP_COMMIT_DELAY_S", "float", "0.002",
       "serve", "max extra wait for companions before the group fsync "
       "(a lone insert never waits)"),
    _K("SHEEP_SERVE_FAULT_PLAN", "plan", "",
       "serve", "serve-layer fault plan kind@site:nth "
       "(kill/hang/slow at req/query/insert/gc-append/gc-unsynced/"
       "wal/apply and the reseq-hist/fold/swap/seal phase "
       "boundaries)"),
    _K("SHEEP_SERVE_TENANTS", "list", "",
       "serve", "tenant specs name=dir[:graph[:k]] behind one daemon"),
    _K("SHEEP_SERVE_MAX_RESIDENT", "int", "0",
       "serve", "max resident tenants; coldest evicts to sealed "
       "snapshot (0 = unlimited)"),
    # -- replication / failover (ISSUE 7) ----------------------------------
    _K("SHEEP_SERVE_ROLE", "str", "leader",
       "replicate", "process role: leader / follower"),
    _K("SHEEP_SERVE_PEERS", "list", "",
       "replicate", "peer specs (host:port or state dirs) for "
       "replication + failover polling"),
    _K("SHEEP_SERVE_NODE_ID", "str", "",
       "replicate", "stable node identity for elections and lag "
       "reporting"),
    _K("SHEEP_SERVE_REPL_ACKS", "int", "1",
       "replicate", "follower acks an insert OK requires beyond the "
       "leader fsync"),
    _K("SHEEP_SERVE_REPL_HB_S", "float", "1",
       "replicate", "replication stream heartbeat interval"),
    _K("SHEEP_SERVE_FAILOVER_S", "float", "5",
       "replicate", "silent-stream age at which followers elect"),
    _K("SHEEP_SERVE_MAX_LAG", "int", "0",
       "replicate", "bounded-staleness refusal for follower reads "
       "(0 = serve any lag)"),
    _K("SHEEP_SERVE_NETFAULT_PLAN", "plan", "",
       "replicate", "network fault plan drop/partition/slow/dup at "
       "the replication sites (repl/hb), the worker-wire sites "
       "(wleg/wbeat/wart), the migration sites (msnap/mdelta/mcut), "
       "and the re-sequence swap announcement (reseq)"),
    # -- router (ISSUE 11) -------------------------------------------------
    _K("SHEEP_ROUTE_CLUSTERS", "list", "",
       "route", "cluster member lists the router hashes tenants "
       "across"),
    _K("SHEEP_ROUTE_VNODES", "int", "64",
       "route", "virtual nodes per cluster on the consistent-hash "
       "ring"),
    _K("SHEEP_ROUTE_RID", "str", "adaptive",
       "route", "rid stamping: always / never / adaptive (writes "
       "always; reads when recording)"),
    # -- live migration + rebalancer (ISSUE 17) ----------------------------
    _K("SHEEP_MIGRATE_TIMEOUT_S", "float", "120",
       "migrate", "per-migration wall budget; past it the driver "
       "aborts cleanly back to the source (or finishes the remap if "
       "the cutover already landed)"),
    _K("SHEEP_MIGRATE_LAG_CUT", "int", "8",
       "migrate", "delta lag in records at or under which the driver "
       "enters the epoch-fenced cutover"),
    _K("SHEEP_MIGRATE_POLL_S", "float", "0.05",
       "migrate", "driver poll cadence while the delta lag drains"),
    _K("SHEEP_MIGRATE_RETRIES", "int", "8",
       "migrate", "wire-leg retry budget per migration RPC (each "
       "retry is a counted re-dispatch; exhausting it aborts)"),
    _K("SHEEP_REBALANCE", "flag", "0",
       "migrate", "router self-rebalancer: watch the fleet scrape and "
       "live-migrate the busiest tenant off a sustained-hot cluster"),
    _K("SHEEP_REBALANCE_INTERVAL_S", "float", "5",
       "migrate", "seconds between rebalancer fleet-scrape verdicts"),
    _K("SHEEP_REBALANCE_COOLDOWN_S", "float", "30",
       "migrate", "quiet period after a migration lands before the "
       "next is considered (anti-flap)"),
    _K("SHEEP_REBALANCE_HYSTERESIS", "float", "1.5",
       "migrate", "hot cluster must out-qps the coolest by this "
       "factor before a move is considered"),
    _K("SHEEP_REBALANCE_MIN_QPS", "float", "5",
       "migrate", "below this hot-cluster qps the fleet is quiet and "
       "every verdict holds"),
    _K("SHEEP_REBALANCE_PIN", "str", "",
       "migrate", "pin the rebalancer's pricing verdict: go / stay "
       "(unset = plan_migration prices the move)"),
    # -- re-sequencing (ISSUE 18) ------------------------------------------
    _K("SHEEP_RESEQ", "flag", "1",
       "reseq", "background crash-safe re-sequence when the "
       "sequence-drift detector fires (0 = repartition-only drift "
       "handling)"),
    _K("SHEEP_RESEQ_DRIFT", "float", "0.25",
       "reseq", "fraction of post-cut inserts that are out-of-sequence "
       "(or degree-rank-moved) before a re-sequence triggers"),
    _K("SHEEP_RESEQ_DRIFT_MIN", "int", "256",
       "reseq", "minimum post-cut inserts before sequence drift can "
       "trigger"),
    _K("SHEEP_RESEQ_RANK", "int", "8",
       "reseq", "degree-rank displacement (in histogram buckets) past "
       "which an insert counts as sequence drift"),
    _K("SHEEP_RESEQ_PIN", "str", "",
       "reseq", "pin the re-sequence pricing verdict: go / stay "
       "(unset = plan_reseq prices the rebuild)"),
    _K("SHEEP_RESEQ_HORIZON_S", "float", "60",
       "reseq", "priced rebuild cost above this horizon stays (drift "
       "keeps accruing until forced or cheaper)"),
    # -- anti-entropy / scrubbing (ISSUE 20) -------------------------------
    _K("SHEEP_SCRUB_VERIFY_N", "int", "256",
       "scrub", "VERIFY-frame cadence in applied records: the leader "
       "stamps a state-crc checkpoint into the replication stream "
       "every N records (0 = off); divergence is detected within one "
       "cadence"),
    _K("SHEEP_SCRUB_INTERVAL_S", "float", "0",
       "scrub", "background artifact-scrub period per daemon (0 = "
       "off; the SCRUB verb still runs one inline)"),
    _K("SHEEP_SCRUB_PACE_S", "float", "0",
       "scrub", "sleep between artifacts inside one scrub pass so the "
       "re-read never starves foreground I/O"),
    _K("SHEEP_SCRUB_PIN", "str", "",
       "scrub", "pin the background scrub pricing verdict: go / stay "
       "(unset = plan_scrub prices the pass)"),
    _K("SHEEP_SCRUB_HORIZON_S", "float", "30",
       "scrub", "priced re-verification cost above this horizon stays "
       "(the interval re-offers the pass later)"),
    _K("SHEEP_SCRUB_ALLOW_CORRUPT", "flag", "0",
       "scrub", "enable the CORRUPT verb (bench/test divergence "
       "injector that flips one live byte); production daemons refuse "
       "it unset"),
    # -- multi-process / dist CLI ------------------------------------------
    _K("SHEEP_COORDINATOR", "str", "",
       "dist", "jax.distributed coordinator address"),
    _K("SHEEP_NUM_PROCESSES", "int", "",
       "dist", "process count of the multi-process mesh"),
    _K("SHEEP_PROCESS_ID", "int", "",
       "dist", "this process's index in the multi-process mesh"),
    _K("SHEEP_CONNECT_TIMEOUT", "float", "60",
       "dist", "coordinator connect timeout seconds"),
    # -- partition / evaluate ----------------------------------------------
    _K("SHEEP_DDUP_GRAPH", "flag", "0",
       "partition", "deduplicate parallel edges like the reference's "
       "ddup tooling"),
    _K("SHEEP_EVAL_STREAM", "flag", "auto",
       "partition", "streamed (bounded-memory) partition evaluator; "
       "unset = auto by size"),
    _K("SHEEP_EVAL_STREAM_THRESHOLD", "int", "33554432",
       "partition", "edge count above which the evaluator streams"),
    # -- bench / scripts (repo tooling, not the package) -------------------
    _K("SHEEP_BENCH_SIZES", "str", "",
       "bench", "bench.py size list (log2 exponents)"),
    _K("SHEEP_BENCH_PATHS", "str", "",
       "bench", "bench.py path arms to run"),
    _K("SHEEP_BENCH_REPS", "int", "3",
       "bench", "best-of repetitions per bench arm"),
    _K("SHEEP_BENCH_LOG_N", "int", "",
       "bench", "single bench size override"),
    _K("SHEEP_BENCH_EDGE_FACTOR", "int", "4",
       "bench", "edges per vertex of the synthetic bench graphs"),
    _K("SHEEP_BENCH_TIMEOUT", "float", "",
       "bench", "per-arm bench timeout"),
    _K("SHEEP_BENCH_STARTUP_TIMEOUT", "float", "",
       "bench", "bench subprocess startup timeout"),
    _K("SHEEP_BENCH_NO_FALLBACK", "flag", "0",
       "bench", "fail instead of falling back to cpu when the backend "
       "is sick"),
    _K("SHEEP_BENCH_NO_PROBE", "flag", "0",
       "bench", "skip the backend probe before benching"),
    _K("SHEEP_BENCH_THREADS_AB", "flag", "0",
       "bench", "per-size forced-thread A/B arm in bench.py"),
    _K("SHEEP_MESHBENCH_REPS", "int", "3",
       "bench", "mesh_bench repetitions"),
    _K("SHEEP_PROFILE_REPS", "int", "3",
       "bench", "hybrid_profile repetitions"),
    _K("SHEEP_SCALE_BLOCK", "size", "",
       "bench", "scale_run block size override"),
    _K("SHEEP_SCALE_STREAM", "flag", "0",
       "bench", "scale_run streamed arm"),
    _K("SHEEP_SCALE_SKIP_ORACLE", "flag", "0",
       "bench", "skip the in-RAM oracle arm of scale_run"),
    _K("SHEEP_REFSCALE_STREAM", "flag", "0",
       "bench", "reference_scale_run streamed arm"),
    _K("SHEEP_WATCH_INTERVAL", "float", "",
       "bench", "tpu_watcher poll interval"),
    _K("SHEEP_WATCH_MAX_HOURS", "float", "",
       "bench", "tpu_watcher give-up horizon"),
    _K("SHEEP_WATCH_PROBE_TIMEOUT", "float", "",
       "bench", "tpu_watcher probe timeout"),
    # -- shell drivers (scripts/*.sh) --------------------------------------
    _K("SHEEP_BIN", "path", "bin/",
       "shell", "where the shell drivers find the sheep binaries"),
    _K("SHEEP_PROCS", "int", "",
       "shell", "worker process count of the shell drivers"),
    _K("SHEEP_STATE_DIR", "path", "",
       "shell", "supervised tournament state dir of dist-partition.sh"),
    _K("SHEEP_SUPERVISED", "flag", "0",
       "shell", "route dist-partition.sh through the supervisor (-S)"),
    _K("SHEEP_HEARTBEAT_DIR", "path", "$RDIR/heartbeats",
       "shell", "where shell workers put their heartbeat files"),
    _K("SHEEP_HB_PID", "int", "",
       "shell", "internal: the shell heartbeat loop's pid (lib.sh)"),
]}


def knob(name: str) -> Knob:
    return KNOBS[name]


def missing_from_registry(names) -> list[str]:
    """Knob names read somewhere but not declared here (the enforcement
    test's question)."""
    return sorted(set(names) - set(KNOBS))


MARK_BEGIN = "<!-- KNOBS:BEGIN (generated by sheep_tpu.utils.knobs) -->"
MARK_END = "<!-- KNOBS:END -->"


def markdown_table() -> str:
    """The README "Configuration knobs" table, grouped by subsystem —
    regenerate with ``python -m sheep_tpu.utils.knobs --markdown``."""
    lines = [MARK_BEGIN,
             "| knob | type | default | subsystem | what it does |",
             "|---|---|---|---|---|"]
    for k in KNOBS.values():
        default = k.default if k.default != "" else "unset"
        lines.append(f"| `{k.name}` | {k.type} | {default} | "
                     f"{k.subsystem} | {k.doc} |")
    lines.append(MARK_END)
    return "\n".join(lines) + "\n"


def readme_in_sync(readme_text: str) -> bool:
    """Whether ``readme_text`` embeds exactly the current table."""
    want = markdown_table().strip()
    a = readme_text.find(MARK_BEGIN)
    b = readme_text.find(MARK_END)
    if a < 0 or b < 0:
        return False
    return readme_text[a: b + len(MARK_END)].strip() == want


def main(argv: list[str] | None = None) -> int:
    import sys
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "--markdown":
        sys.stdout.write(markdown_table())
        return 0
    if argv and argv[0] == "--check":
        path = argv[1] if len(argv) > 1 else "README.md"
        with open(path, encoding="utf-8") as f:
            ok = readme_in_sync(f.read())
        print("in sync" if ok else "STALE: regenerate with "
              "python -m sheep_tpu.utils.knobs --markdown")
        return 0 if ok else 1
    print("USAGE: python -m sheep_tpu.utils.knobs --markdown | "
          "--check [README.md]")
    return 2


if __name__ == "__main__":
    import sys
    sys.exit(main())
