"""Synthetic graph generators for benchmarks and stress tests.

R-MAT / Graph500-style Kronecker edges (the reference's ``.dat`` XS1 format
is "XS1/Graph500 binary", lib/readerwriter.h:36-40, and BASELINE.json's
config 5 is a scale-26 Kronecker) — power-law degree structure comparable to
the twitter/uk web graphs the reference benchmarks on.
"""

from __future__ import annotations

import numpy as np


def rmat_edges(log_n: int, num_edges: int, seed: int = 0,
               a: float = 0.57, b: float = 0.19, c: float = 0.19
               ) -> tuple[np.ndarray, np.ndarray]:
    """R-MAT edge records (tail, head) uint32 over 2**log_n vid slots."""
    rng = np.random.default_rng(seed)
    tail = np.zeros(num_edges, dtype=np.uint32)
    head = np.zeros(num_edges, dtype=np.uint32)
    # uint16 entropy instead of float64: the PRNG cost scales with output
    # bytes (4x fewer), and this 1-core host generates doubles at only
    # ~10M/s — at 2^25 x 44 (the twitter-scale stand-in) float64 draws
    # alone cost ~1h.  Quadrant probabilities quantize to 1/65536, which
    # is noise for benchmark graphs.
    qa = np.uint16(min(round(a * 65536), 65535))
    qab = np.uint16(min(round((a + b) * 65536), 65535))
    qabc = np.uint16(min(round((a + b + c) * 65536), 65535))
    for bit in range(log_n):
        u = rng.integers(0, 1 << 16, num_edges, dtype=np.uint16)
        tbit = u >= qab
        hbit = ((u >= qa) & (u < qab)) | (u >= qabc)
        tail |= tbit.astype(np.uint32) << np.uint32(bit)
        head |= hbit.astype(np.uint32) << np.uint32(bit)
    return tail, head
