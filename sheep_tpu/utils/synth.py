"""Synthetic graph generators for benchmarks and stress tests.

R-MAT / Graph500-style Kronecker edges (the reference's ``.dat`` XS1 format
is "XS1/Graph500 binary", lib/readerwriter.h:36-40, and BASELINE.json's
config 5 is a scale-26 Kronecker) — power-law degree structure comparable to
the twitter/uk web graphs the reference benchmarks on.
"""

from __future__ import annotations

import numpy as np


def rmat_edges(log_n: int, num_edges: int, seed: int = 0,
               a: float = 0.57, b: float = 0.19, c: float = 0.19
               ) -> tuple[np.ndarray, np.ndarray]:
    """R-MAT edge records (tail, head) uint32 over 2**log_n vid slots."""
    rng = np.random.default_rng(seed)
    tail = np.zeros(num_edges, dtype=np.uint32)
    head = np.zeros(num_edges, dtype=np.uint32)
    for bit in range(log_n):
        u = rng.random(num_edges)
        tbit = u >= (a + b)
        hbit = ((u >= a) & (u < a + b)) | (u >= a + b + c)
        tail |= tbit.astype(np.uint32) << np.uint32(bit)
        head |= hbit.astype(np.uint32) << np.uint32(bit)
    return tail, head
