"""Environment capture for benchmark records (VERDICT r05 item 5).

Round 5 closed with an unexplained 2.8x gap between the driver's bench
numbers and a clean serialized rerun of the same code at 2^22 — and the
records carried nothing that could attribute it (was the host loaded?
pinned differently? a different backend?).  Every benchmark record now
embeds this capture so driver-vs-clean divergences are attributable from
the artifact alone: host load at measurement time, core count and the
process's actual affinity mask (thread pins), cpu model, thread-count
env pins, and the jax backend when one is already up.

Deliberately import-light: no jax import (a capture must never be the
thing that initializes a backend), /proc reads are best-effort, and any
failure degrades to omitting the field, never to raising.
"""

from __future__ import annotations

import os
import sys


def env_capture(platform: str | None = None) -> dict:
    """One dict of host/environment facts for embedding in a record."""
    rec: dict = {"nproc": os.cpu_count()}
    try:
        rec["loadavg_1m_5m_15m"] = [round(x, 2) for x in os.getloadavg()]
    except OSError:
        pass
    try:
        rec["affinity_cores"] = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        pass
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    rec["cpu_model"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    pins = {k: v for k, v in os.environ.items()
            if k in ("OMP_NUM_THREADS", "XLA_FLAGS", "TASKSET",
                     "GOMP_CPU_AFFINITY", "JAX_PLATFORMS")}
    if pins:
        rec["thread_env"] = pins
    if platform is not None:
        rec["backend"] = platform
    elif "jax" in sys.modules:  # never initialize one just to report it
        try:
            rec["backend"] = sys.modules["jax"].devices()[0].platform
        except Exception:
            pass
    return rec
