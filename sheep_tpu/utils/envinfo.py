"""Environment capture for benchmark records (VERDICT r05 item 5).

Round 5 closed with an unexplained 2.8x gap between the driver's bench
numbers and a clean serialized rerun of the same code at 2^22 — and the
records carried nothing that could attribute it (was the host loaded?
pinned differently? a different backend?).  Every benchmark record now
embeds this capture so driver-vs-clean divergences are attributable from
the artifact alone: host load at measurement time, core count and the
process's actual affinity mask (thread pins), the cgroup cpu quota (a
container limited to 4 cpu-seconds/second reports every host core in
nproc/affinity — round 14's threaded kernels size themselves off the
EFFECTIVE count, and the record must show which number the host lied
about), cpu model, thread-count env pins, the native runtime's OpenMP
ceiling when it is already loaded, and the jax backend when one is up.

Deliberately import-light: no jax import (a capture must never be the
thing that initializes a backend), no native-library build (reported
only when the module is already loaded), /proc and /sys reads are
best-effort, and any failure degrades to omitting the field, never to
raising.
"""

from __future__ import annotations

import math
import os
import sys


def cpu_quota_cores(root: str = "/sys/fs/cgroup") -> float | None:
    """The cgroup cpu quota as fractional cores, or None when unlimited
    or undetectable.  Reads v2 ``cpu.max`` ("<quota> <period>" in µs,
    "max" = unlimited) and falls back to v1 ``cpu/cpu.cfs_quota_us`` /
    ``cpu.cfs_period_us`` (-1 = unlimited)."""
    try:
        with open(os.path.join(root, "cpu.max")) as f:
            quota_s, period_s = (f.read().split() + ["100000"])[:2]
        if quota_s != "max":
            period = int(period_s)
            if period > 0:
                return int(quota_s) / period
            return None
        return None
    except (OSError, ValueError):
        pass
    try:
        with open(os.path.join(root, "cpu", "cpu.cfs_quota_us")) as f:
            quota = int(f.read().strip())
        if quota <= 0:  # -1 = unlimited
            return None
        with open(os.path.join(root, "cpu", "cpu.cfs_period_us")) as f:
            period = int(f.read().strip())
        return quota / period if period > 0 else None
    except (OSError, ValueError):
        return None


def effective_cores(root: str = "/sys/fs/cgroup") -> int:
    """Cores this process can actually burn concurrently: the minimum of
    the affinity mask (else nproc) and the cgroup quota, floor 1 — the
    governor's input for sizing thread counts and leg counts (a quota'd
    container that reports 16 affinity cores must not spawn 16 threads
    to time-share 4 cpu-seconds/second)."""
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    quota = cpu_quota_cores(root)
    if quota is not None:
        cores = min(cores, max(1, math.ceil(quota)))
    return max(1, cores)


def env_capture(platform: str | None = None) -> dict:
    """One dict of host/environment facts for embedding in a record."""
    rec: dict = {"nproc": os.cpu_count()}
    try:
        rec["loadavg_1m_5m_15m"] = [round(x, 2) for x in os.getloadavg()]
    except OSError:
        pass
    try:
        rec["affinity_cores"] = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        pass
    quota = cpu_quota_cores()
    if quota is not None:
        rec["cpu_quota_cores"] = round(quota, 2)
    rec["effective_cores"] = effective_cores()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.lower().startswith("model name"):
                    rec["cpu_model"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    pins = {k: v for k, v in os.environ.items()
            if k in ("OMP_NUM_THREADS", "XLA_FLAGS", "TASKSET",
                     "GOMP_CPU_AFFINITY", "JAX_PLATFORMS",
                     "SHEEP_NATIVE_THREADS", "SHEEP_LEG_CORES")}
    if pins:
        rec["thread_env"] = pins
    # the native runtime's OpenMP view — only when something else
    # already paid for loading it (this capture never triggers a build)
    native = sys.modules.get("sheep_tpu.native")
    if native is not None and getattr(native, "_lib", None) is not None:
        try:
            rec["omp_compiled"] = native.omp_compiled()
            rec["omp_max_threads"] = native.omp_max_threads()
        except Exception:
            pass
    if platform is not None:
        rec["backend"] = platform
    elif "jax" in sys.modules:  # never initialize one just to report it
        try:
            rec["backend"] = sys.modules["jax"].devices()[0].platform
        except Exception:
            pass
    return rec
