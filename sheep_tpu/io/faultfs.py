"""Deterministic I/O fault injection: every write site, hurtable on cue.

FATE/DESTINI-style lesson (PAPERS.md): recovery code that has never seen
its fault fire is broken until proven otherwise — and real ENOSPC/EIO
never fires on a healthy CI disk.  This module is the hook: every durable
writer in the package opens its file through :func:`arm`/:func:`wrap`
(io/atomic.py does it for all of them), and an installed
:class:`IoFaultPlan` hurts exactly the ``nth`` write at a named site.
Grammar — the I/O sibling of the supervisor's ``SHEEP_FAULT_PLAN``
(supervisor/chaos.py) and the runtime's ``SHEEP_FAULT_INJECT``::

    SHEEP_IO_FAULT_PLAN = entry[,entry...]
    entry               = kind @ site : nth
    kind                = enospc | eio | short | slow | rot
    site                = tre | seq | dat | net | sidecar | ckpt |
                          wal | snap | hist | manifest | other | *
    nth                 = 0-based index of the write at that site
                          (for ``rot``: of the SEAL at that site)

e.g. ``SHEEP_IO_FAULT_PLAN=enospc@ckpt:1,short@tre:0``.  Sites are
artifact CLASSES, derived from the target path (:func:`site_for`) with
the supervisor's ``.aN`` attempt suffix stripped, so the same plan names
the same logical write whether the artifact lands directly or via a
temp-name publish.  Each entry fires exactly once; per-site indices count
from :func:`reset_counters` (per build/test), so "hurt ckpt write 1"
means the same write on every run.

The kinds model the distinct environmental failure shapes, each driving a
DIFFERENT recovery path:

  enospc  the disk fills mid-write: OSError(ENOSPC) from write().
          Recovery: the atomic writer discards its temp, nothing
          publishes, the caller's typed DiskExhausted path (GC + retry,
          or abort-resumable) runs.
  eio     the device fails: OSError(EIO).  Recovery: same discard
          invariant; retry territory for the supervisor.
  short   a torn write: a PREFIX of the first write lands in the temp
          file, then ENOSPC.  This is the case that distinguishes
          "atomic publish" from "hopeful publish" — the torn bytes must
          never appear under a final name.
  slow    writes stall (default 50ms each, ``:nth`` still selects the
          open): the watchdog/heartbeat shape.  Never fails the write.
  rot     silent POST-SEAL corruption (ISSUE 20): the write itself
          succeeds, the sidecar vouches for the published bytes — and
          then one byte of the artifact flips under its final name, the
          way a rotting disk or a torn page the kernel never surfaced
          would.  No error is raised at injection time; ONLY a later
          re-verification (the scrubber, fsck, the anti-entropy stream)
          can notice.  Fires from :func:`rot_after_seal` (io/atomic.py
          calls it after every atomic publish) and counts SEALS per
          site in its own counter space, so ``rot@snap:0`` means "the
          first snapshot sealed", independent of how many write-opens
          the same site saw.

Faults are injected at the Python file layer, byte-for-byte deterministic
under every runner — no filesystem setup, no privileges, works in CI.
"""

from __future__ import annotations

import errno
import os
import re
import time
from dataclasses import dataclass, field

IO_FAULT_PLAN_ENV = "SHEEP_IO_FAULT_PLAN"

KINDS = ("enospc", "eio", "short", "slow", "rot")

#: the kinds that fire on a write-open (everything except ``rot``, which
#: has its own post-seal channel so write counters never consume it)
_WRITE_KINDS = ("enospc", "eio", "short", "slow")

#: suffix -> site class (checked in order; .sum first so a tree's sidecar
#: is "sidecar", not "tre").  ``wal``/``snap`` are the serve daemon's
#: durability sites (ISSUE 6): the write-ahead log appends and the serving
#: snapshot seals, so kill/ENOSPC-at-every-insert-boundary recovery is
#: injectable with the same grammar as every offline site.
_SITE_SUFFIXES = ((".sum", "sidecar"), (".tre", "tre"), (".seq", "seq"),
                  (".dat", "dat"), (".net", "net"), (".npz", "ckpt"),
                  (".wal", "wal"), (".snap", "snap"), (".hist", "hist"))

_ATTEMPT_RE = re.compile(r"\.a\d+$")

_SLOW_S = 0.05


def site_for(path: str) -> str:
    """The fault-site class of a write target.  The supervisor's
    ``<output>.aN`` attempt temps resolve to their final class, and
    ``manifest.json`` is its own site (the one artifact that is pure
    orchestration state)."""
    base = os.path.basename(path)
    if base.endswith(".sum"):
        # a sidecar names its artifact's class; strip any attempt suffix
        # hiding between the artifact name and .sum (<out>.tre.a2.sum)
        base = _ATTEMPT_RE.sub("", base[: -len(".sum")]) + ".sum"
    else:
        base = _ATTEMPT_RE.sub("", base)
    if base == "manifest.json":
        return "manifest"
    for suffix, site in _SITE_SUFFIXES:
        if base.endswith(suffix):
            return site
    return "other"


@dataclass
class IoFault:
    kind: str
    site: str
    nth: int

    def matches(self, site: str, index: int) -> bool:
        return (self.site == "*" or self.site == site) and index == self.nth


@dataclass
class IoFaultPlan:
    """Parsed plan; entries pop as they fire (recovery writes run clean)."""

    faults: list[IoFault] = field(default_factory=list)

    def take(self, site: str, index: int,
             kinds: tuple | None = None) -> str | None:
        """Pop-and-return the first entry matching ``(site, index)``;
        ``kinds`` restricts which entries are eligible (the write channel
        must never consume a ``rot`` entry and vice versa)."""
        for i, f in enumerate(self.faults):
            if kinds is not None and f.kind not in kinds:
                continue
            if f.matches(site, index):
                del self.faults[i]
                return f.kind
        return None


def parse_io_fault_plan(spec: str) -> IoFaultPlan:
    faults = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            kind, at = entry.split("@", 1)
            site, nth = at.split(":", 1)
        except ValueError:
            raise ValueError(
                f"{IO_FAULT_PLAN_ENV} entry {entry!r}: want kind@site:nth "
                f"(e.g. enospc@ckpt:1)")
        kind = kind.strip()
        if kind not in KINDS:
            raise ValueError(
                f"{IO_FAULT_PLAN_ENV} entry {entry!r}: kind {kind!r} must "
                f"be one of {'/'.join(KINDS)}")
        faults.append(IoFault(kind=kind, site=site.strip(), nth=int(nth)))
    return IoFaultPlan(faults=faults)


_plan: IoFaultPlan | None = None
_env_spec: str | None = None
_counters: dict[str, int] = {}
_rot_counters: dict[str, int] = {}


def install_plan(plan: IoFaultPlan | None) -> None:
    """Install (or with None, clear) the active plan and reset counters."""
    global _plan, _env_spec
    _plan = plan
    _env_spec = None
    _counters.clear()
    _rot_counters.clear()


def clear_plan() -> None:
    install_plan(None)


def reset_counters() -> None:
    _counters.clear()
    _rot_counters.clear()


def _active_plan() -> IoFaultPlan | None:
    """The installed plan, else the env plan — parsed ONCE per spec value
    so per-site counters and already-fired entries survive across writes
    within the process."""
    global _plan, _env_spec
    if _plan is not None:
        return _plan
    spec = os.environ.get(IO_FAULT_PLAN_ENV, "")
    if not spec:
        return None
    if spec != _env_spec:
        _plan = parse_io_fault_plan(spec)
        _env_spec = spec
        return _plan
    return None


def arm(path: str) -> str | None:
    """Record one write-open of ``path``'s site and return the fault kind
    armed for it (None = healthy).  Called once per atomic_write."""
    site = site_for(path)
    index = _counters.get(site, 0)
    _counters[site] = index + 1
    plan = _active_plan()
    if plan is None:
        return None
    kind = plan.take(site, index, kinds=_WRITE_KINDS)
    if kind is not None:
        from ..obs import trace as _obs
        _obs.event("io.fault", site=site, index=index, kind=kind)
    return kind


def rot_after_seal(path: str) -> bool:
    """``rot@site:nth`` — flip one byte of the PUBLISHED artifact at
    ``path``, leaving its sidecar untouched (module docstring).  Called by
    io/atomic.py after every atomic publish and by the serve tier's WAL
    archiver; counts seals per site in its own counter space.  Returns
    True when a byte flipped."""
    site = site_for(path)
    index = _rot_counters.get(site, 0)
    _rot_counters[site] = index + 1
    plan = _active_plan()
    if plan is None:
        return False
    if plan.take(site, index, kinds=("rot",)) is None:
        return False
    try:
        size = os.path.getsize(path)
    except OSError:
        return False
    if size == 0:
        return False
    off = size // 2
    with open(path, "r+b") as f:
        f.seek(off)
        byte = f.read(1)
        f.seek(off)
        f.write(bytes([byte[0] ^ 0x01]))
        f.flush()
        os.fsync(f.fileno())
    from ..obs import trace as _obs
    _obs.event("io.fault", site=site, index=index, kind="rot")
    return True


def hurt_read(path: str) -> None:
    """Arm-and-fire for READ sites (ISSUE 9): the streaming ``.dat``
    block reader calls this once per block, so ``kind@dat:nth`` hurts the
    nth BLOCK READ of an out-of-core stream exactly like it hurts the nth
    write of an offline run — one grammar, one counter space per site
    (reads and writes at the same site share indices; a build that does
    both is told so by its plan, not surprised).  Writers go through
    :func:`arm`/:func:`wrap` because their fault must tear the file;
    readers just need the typed OSError at the right moment: eio/enospc
    raise (ENOSPC models a reader whose backing filesystem went sick
    mid-stream — same errno the retry logic classifies), ``short`` maps
    to EIO (a torn read IS an I/O error to the consumer), ``slow`` stalls
    like the write kind."""
    kind = arm(path)
    if kind is None:
        return
    if kind == "slow":
        time.sleep(_SLOW_S)
        return
    if kind == "enospc":
        raise OSError(errno.ENOSPC,
                      "injected ENOSPC (SHEEP_IO_FAULT_PLAN) reading "
                      + path)
    raise OSError(errno.EIO,
                  f"injected {kind} (SHEEP_IO_FAULT_PLAN) reading {path}")


class FaultyFile:
    """File proxy that hurts writes per the armed kind.  Only the write
    path is proxied — flush/fileno/close pass through, so io/atomic.py's
    fsync/rename discipline sees the real file object underneath."""

    def __init__(self, f, kind: str, text: bool):
        self._f = f
        self._kind = kind
        self._text = text
        self._wrote = False

    def write(self, data):
        if self._f.closed:
            # a GC'd zipfile flushing its directory after the writer
            # already aborted and cleaned up: nothing durable can land
            # (the temp is gone) — swallow instead of raising from __del__
            return len(data)
        k = self._kind
        if k == "slow":
            time.sleep(_SLOW_S)
            return self._f.write(data)
        if k == "eio":
            raise OSError(errno.EIO, "injected EIO (SHEEP_IO_FAULT_PLAN)")
        if k == "enospc":
            raise OSError(errno.ENOSPC,
                          "injected ENOSPC (SHEEP_IO_FAULT_PLAN)")
        if k == "short":
            if not self._wrote:
                self._wrote = True
                half = data[: max(1, len(data) // 2)]
                self._f.write(half)
                self._f.flush()
            raise OSError(errno.ENOSPC,
                          "injected short write (SHEEP_IO_FAULT_PLAN): "
                          "a torn prefix landed in the temp file")
        raise AssertionError(f"unknown fault kind {k!r}")

    def flush(self):
        if self._f.closed:
            return None  # see write(): post-abort __del__ tolerance
        return self._f.flush()

    def fileno(self):
        return self._f.fileno()

    def close(self):
        return self._f.close()

    def seek(self, *args, **kwargs):
        if self._f.closed:
            return 0  # see write(): post-abort __del__ tolerance
        return self._f.seek(*args, **kwargs)

    def tell(self):
        if self._f.closed:
            return 0  # see write(): post-abort __del__ tolerance
        return self._f.tell()

    def __getattr__(self, name):
        # seeking writers (the npz zipfile layer) need read/seek/tell/
        # mode/...; everything but write() passes through untouched
        return getattr(self._f, name)


def wrap(f, kind: str | None, text: bool):
    """The file the writer should use: the real one when healthy, the
    fault proxy when a plan entry armed this open."""
    if kind is None:
        return f
    return FaultyFile(f, kind, text)
