"""Reusable async block prefetcher: read block k+1 while block k folds.

This is the I/O generalization of the streaming windowed handoff's
``_WindowStream`` (ops/build.py, ISSUE 8): that class overlaps a DEVICE
transfer queue with the fold consuming it; this one overlaps an arbitrary
block *producer* — a ``.dat`` memmap stream (io/edges.iter_dat_blocks),
the spill rung's scratch-file slices (runtime/driver.py), anything that
yields blocks — with whatever consumes them.  Same contract as the window
queue: a background thread runs at most ``depth`` blocks ahead of the
consumer (double buffering by default, so resident memory beyond the
consumer's own state is O(depth x block)), a producer failure surfaces in
the consumer's iteration with the ORIGINAL exception (an injected EIO
from the fault plan must reach the retry/degrade logic typed, not wrapped
into anonymity), and abandoning the iterator releases the thread at the
next block boundary.

The producer's time inside ``next()`` accumulates through the flight
recorder's shared timing helper (obs.trace.timed — one span per block
when ``SHEEP_TRACE`` is on, the same measured series either way), and
``busy_s`` is the derived view callers feed to the ONE overlap
accounting (obs.trace.overlap_stats) the windowed handoff and the ext
build share (PERF_NOTES r07: measured, not assumed — on a 1-core host
the overlap capacity is ~zero and the records must say so honestly).
"""

from __future__ import annotations

import threading

from ..obs import trace as obs

#: blocks the producer may run ahead of the consumer (double buffering:
#: fold block k while k+1 is resident and k+2 is being read)
DEFAULT_DEPTH = 2


class BlockPrefetcher:
    """Iterate ``source`` on a background thread, at most ``depth`` blocks
    ahead of the consumer.  Use as an iterator (``for block in pf:``) or a
    context manager (guarantees the thread is released on early exit).
    ``trace_name`` names the per-block read span in the flight recorder."""

    _END = object()

    def __init__(self, source, depth: int = DEFAULT_DEPTH,
                 trace_name: str = "prefetch.read"):
        if depth < 1:
            raise ValueError(f"prefetch depth {depth} must be >= 1")
        self.depth = depth
        self.trace_name = trace_name
        self._read_s: list = []  # per-block producer seconds (obs.timed)
        self.blocks = 0          # blocks produced so far
        self._src = iter(source)
        self._buf: list = []
        self._exc: BaseException | None = None
        self._done = False
        self._abort = False
        self._cv = threading.Condition()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    @property
    def busy_s(self) -> float:
        """Producer time actually spent reading blocks (the overlap
        accounting's serialized read term)."""
        return sum(self._read_s)

    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    while len(self._buf) >= self.depth and not self._abort:
                        self._cv.wait(0.5)
                    if self._abort:
                        return
                try:
                    with obs.timed(self.trace_name, out=self._read_s,
                                   block=self.blocks):
                        item = next(self._src)
                except StopIteration:
                    return
                with self._cv:
                    self._buf.append(item)
                    self.blocks += 1
                    self._cv.notify_all()
        except BaseException as exc:  # re-raised typed on the consumer side
            with self._cv:
                self._exc = exc
                self._cv.notify_all()
        finally:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    def __iter__(self):
        return self

    def __next__(self):
        with self._cv:
            while True:
                if self._buf:
                    item = self._buf.pop(0)
                    self._cv.notify_all()
                    return item
                if self._exc is not None:
                    exc, self._exc = self._exc, None
                    self._done = True
                    raise exc
                if self._done:
                    raise StopIteration
                self._cv.wait(0.5)

    def close(self) -> None:
        """Release the producer thread at its next block boundary and
        drop any buffered blocks.  Idempotent; safe mid-iteration (the
        early-exit path of a failed consumer)."""
        with self._cv:
            self._abort = True
            self._buf.clear()
            self._cv.notify_all()
        self._thread.join(timeout=30.0)

    def __enter__(self) -> "BlockPrefetcher":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
