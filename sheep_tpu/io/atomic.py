"""Crash-safe file writes: temp file + flush + fsync + atomic rename.

Every durable artifact this package writes (trees, sequences, partition
edge files, runtime checkpoints) goes through :func:`atomic_write`, so a
killed process can never leave a half-written file under the final name —
a reader either sees the old complete file or the new complete one.  This
is the file-level analog of the shell contract in scripts/lib.sh
("producers write to a temp name and atomically mv into place"), enforced
at the library layer so Python callers cannot forget it.

The temp file lives in the SAME directory as the target (rename is only
atomic within a filesystem), and the directory entry is fsync'd after the
rename so the new name survives a power loss, not just a process kill.
"""

from __future__ import annotations

import contextlib
import os
import tempfile


def _fsync_dir(path: str) -> None:
    """Best-effort fsync of the directory containing ``path`` (some
    filesystems/platforms disallow opening directories — not fatal)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb"):
    """Context manager yielding a file object; on clean exit the data is
    flushed, fsync'd, and atomically renamed onto ``path``.  On an
    exception (or a kill) the target is untouched and the temp file is
    removed (or left as an orphaned dot-file a later run may clean).

    ``mode``: "wb" (default) or "w" for text.
    """
    if mode not in ("wb", "w"):
        raise ValueError(f"atomic_write mode must be 'w' or 'wb', got {mode!r}")
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=f".{base}.", suffix=".tmp")
    f = os.fdopen(fd, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
        _fsync_dir(path)
    except BaseException:
        try:
            f.close()
        except Exception:
            pass
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise
