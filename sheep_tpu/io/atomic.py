"""Crash-safe, exhaustion-aware file writes: preflight + temp file +
flush + fsync + atomic rename.

Every durable artifact this package writes (trees, sequences, partition
edge files, runtime checkpoints, supervisor manifests) goes through
:func:`atomic_write`, so a killed process can never leave a half-written
file under the final name — a reader either sees the old complete file or
the new complete one.  This is the file-level analog of the shell contract
in scripts/lib.sh ("producers write to a temp name and atomically mv into
place"), enforced at the library layer so Python callers cannot forget it.

The temp file lives in the SAME directory as the target (rename is only
atomic within a filesystem), and the directory entry is fsync'd after the
rename so the new name survives a power loss, not just a process kill.

Resource exhaustion (ISSUE 5) extends the contract from "a kill never
publishes garbage" to "NOTHING ever publishes garbage":

  preflight   a writer that can estimate its size (``expect_bytes``)
              is refused up front when the filesystem cannot hold it
              with slack (resources/governor.py) — a typed
              :class:`~sheep_tpu.resources.errors.DiskExhausted`, raised
              before any bytes land.
  typed fail  a REAL mid-write ENOSPC/EIO (and the injected kind —
              io/faultfs.py, ``SHEEP_IO_FAULT_PLAN``) unlinks the temp
              and re-raises as DiskExhausted/WriteFault, same errno, so
              recovery code has one exception surface for "the
              environment ran out", real or rehearsed.
  temp GC     a partial temp a kill DID strand (unlink never ran) is
              swept by :func:`sheep_tpu.resources.gc.gc_orphan_temps`
              at every resume entry point — orphaned debris never
              accumulates into its own disk-exhaustion cause.

Fault injection wraps the yielded file object (faultfs.wrap), so the
injected failure fires through the exact code path a real one would take:
writer -> OSError -> temp discarded -> typed re-raise -> nothing
published.
"""

from __future__ import annotations

import contextlib
import errno
import os
import tempfile

from ..resources.errors import DiskExhausted, WriteFault
from . import faultfs


def _fsync_dir(path: str) -> None:
    """Best-effort fsync of the directory containing ``path`` (some
    filesystems/platforms disallow opening directories — not fatal)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _typed(exc: OSError, path: str) -> OSError:
    """The typed face of an environmental write failure; other OSErrors
    pass through unchanged."""
    if isinstance(exc, (DiskExhausted, WriteFault)):
        return exc
    if exc.errno == errno.ENOSPC:
        return DiskExhausted(f"{path}: write failed with ENOSPC "
                             f"({exc}); nothing was published")
    if exc.errno == errno.EIO:
        return WriteFault(f"{path}: write failed with EIO "
                          f"({exc}); nothing was published")
    return exc


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb",
                 expect_bytes: int | None = None,
                 pre_publish=None):
    """Context manager yielding a file object; on clean exit the data is
    flushed, fsync'd, and atomically renamed onto ``path``.  On an
    exception (or a kill) the target is untouched and the temp file is
    removed (or left as an orphaned dot-file a later resume sweeps —
    resources/gc.gc_orphan_temps).

    ``mode``: "wb" (default) or "w" for text.
    ``expect_bytes``: the writer's size estimate, enabling the disk
    preflight (a refusal raises DiskExhausted before any bytes land).
    ``pre_publish``: called with the (complete, fsync'd) temp path after
    the data is durable but BEFORE the rename — the sidecar-first seam
    (integrity/sidecar.py): a failure here aborts the publish with the
    target untouched, so an artifact can never appear under its final
    name ahead of (or without) the sidecar that vouches for it.
    """
    if mode not in ("wb", "w"):
        raise ValueError(f"atomic_write mode must be 'w' or 'wb', got {mode!r}")
    d = os.path.dirname(os.path.abspath(path)) or "."
    if expect_bytes is not None:
        from ..resources.governor import ResourceGovernor
        ResourceGovernor.from_env().preflight_write(d, expect_bytes)
    base = os.path.basename(path)
    fault = faultfs.arm(path)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=f".{base}.", suffix=".tmp")
    f = os.fdopen(fd, mode)
    w = faultfs.wrap(f, fault, text=(mode == "w"))
    try:
        yield w
        f.flush()
        os.fsync(f.fileno())
        f.close()
        if pre_publish is not None:
            pre_publish(tmp)
        os.replace(tmp, path)
        _fsync_dir(path)
        # post-seal silent-corruption seam (ISSUE 20): a `rot@site:nth`
        # plan entry flips one published byte AFTER the rename — the
        # artifact lied to no writer, only a re-verification can see it
        faultfs.rot_after_seal(path)
    except BaseException as exc:
        try:
            f.close()
        except Exception:
            pass
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        if isinstance(exc, OSError):
            typed = _typed(exc, path)
            if typed is not exc:
                raise typed from exc
        raise
