"""Edge-list file formats.

Two on-disk formats, byte/char-compatible with the reference
(lib/readerwriter.h:36-102):

- ``.dat``  XS1 / Graph500 binary: little-endian 12-byte records
  ``{uint32 tail, uint32 head, float32 weight}``.
- ``.net``  SNAP whitespace-separated text: ``tail head`` per line
  (comment lines starting with '#' are skipped, matching operator-stream
  semantics of ``stream >> X`` which the reference relies on only for
  well-formed files).

Dispatch on the ``.dat`` suffix mirrors lib/sequence.h:124-128 and
lib/partition.cpp:677.

An :class:`EdgeList` is just a pair of uint32 numpy arrays (tail, head) plus
bookkeeping.  Graphs are undirected: every record is one undirected edge;
degree/adjacency semantics double it (LLAMA's LL_L_UNDIRECTED_DOUBLE,
graph_wrapper.h:51).  Multi-edges are preserved (the reference's DDUP_GRAPH
option is off by default) and self-loops are preserved in the record stream
(they contribute 2 to their endpoint's degree but are excluded from tree
pst-weights, jtree.cpp:48).

Partial loads (`graph2tree -l part/num_parts`, graph_wrapper.h:48-49) are
contiguous record ranges: part k of n (1-indexed) covers records
[floor((k-1)*E/n), floor(k*E/n)).  The union over k is the whole file and
parts are edge-disjoint, which is the property the distributed tree merge
relies on.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass, field

import numpy as np

from ..integrity.errors import MalformedArtifact
from ..integrity.sidecar import (checksummed_write, read_sidecar,
                                 resolve_policy, verify_bytes)

_XS1_DTYPE = np.dtype(
    [("tail", "<u4"), ("head", "<u4"), ("weight", "<f4")]
)


@dataclass
class EdgeList:
    """A batch of undirected edge records."""

    tail: np.ndarray  # uint32 [E]
    head: np.ndarray  # uint32 [E]
    #: total records in the underlying file (== len(tail) unless partial load)
    file_edges: int = 0
    #: record range [start, stop) of this (possibly partial) load
    start: int = 0

    def __post_init__(self):
        if self.file_edges == 0:
            self.file_edges = len(self.tail)

    @property
    def num_edges(self) -> int:
        return len(self.tail)

    @property
    def max_vid(self) -> int:
        if self.num_edges == 0:
            return 0
        return int(max(self.tail.max(), self.head.max()))

    def degrees(self, num_vertices: int | None = None) -> np.ndarray:
        """Per-vertex degree of the undirected-doubled graph.

        Each record adds 1 to both endpoints; a self-loop adds 2 to its
        vertex (LLAMA doubled-graph semantics, graph_wrapper.h:87-89).
        """
        n = num_vertices if num_vertices is not None else self.max_vid + 1
        deg = np.bincount(self.tail, minlength=n)
        deg += np.bincount(self.head, minlength=n)
        return deg.astype(np.int64)


def partial_range(num_records: int, part: int, num_parts: int) -> tuple[int, int]:
    """Record range of partial load `part`/`num_parts` (part is 1-indexed)."""
    if num_parts <= 0:
        return 0, num_records
    if not (1 <= part <= num_parts):
        raise ValueError(f"part {part} out of range 1..{num_parts}")
    start = ((part - 1) * num_records) // num_parts
    stop = (part * num_records) // num_parts
    return start, stop


def read_dat(path: str, part: int = 0, num_parts: int = 0,
             integrity: str | None = None) -> EdgeList:
    mode = resolve_policy(integrity)
    nbytes = os.path.getsize(path)
    rec_size = _XS1_DTYPE.itemsize
    if nbytes % rec_size:
        msg = (f"{path}: corrupt .dat — {nbytes} bytes is not a multiple "
               f"of the {rec_size}-byte XS1 record (torn trailing record)")
        if mode == "strict":
            raise MalformedArtifact(msg)
        if mode == "repair":
            warnings.warn(msg + "; repair drops the partial record")
    num_records = nbytes // rec_size
    start, stop = partial_range(num_records, part, num_parts) if num_parts else (0, num_records)
    if mode != "trust" and read_sidecar(path) is not None:
        # a sidecar exists: verify the WHOLE file (corruption anywhere
        # invalidates the load) and slice records from the same bytes
        with open(path, "rb") as f:
            data = f.read()
        verify_bytes(path, data, mode)
        raw = np.frombuffer(data, dtype=_XS1_DTYPE,
                            count=num_records)[start:stop]
    else:
        with open(path, "rb") as f:
            f.seek(start * rec_size)
            raw = np.fromfile(f, dtype=_XS1_DTYPE, count=stop - start)
    return EdgeList(
        tail=np.ascontiguousarray(raw["tail"]),
        head=np.ascontiguousarray(raw["head"]),
        file_edges=num_records,
        start=start,
    )


def _salvage_net_lines(path: str, data: bytes):
    """Repair-mode .net parse: keep exactly the well-formed ``tail head``
    lines, drop (and count) everything else.  Any byte damage can only
    REMOVE edges from the result, never invent pairings that span lines —
    which is what makes repair output a subset-or-equal of the clean edge
    multiset under token-invalidating corruption."""
    tails, heads, dropped = [], [], 0
    for ln in data.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith(b"#"):
            continue
        toks = ln.split()
        if len(toks) != 2:
            dropped += 1
            continue
        try:
            t, h = int(toks[0]), int(toks[1])
        except ValueError:
            dropped += 1
            continue
        if not (0 <= t <= 0xFFFFFFFF and 0 <= h <= 0xFFFFFFFF):
            dropped += 1
            continue
        tails.append(t)
        heads.append(h)
    if dropped:
        warnings.warn(f"{path}: repair dropped {dropped} malformed line(s)")
    return (np.array(tails, dtype=np.uint32),
            np.array(heads, dtype=np.uint32))


def read_net(path: str, part: int = 0, num_parts: int = 0,
             integrity: str | None = None) -> EdgeList:
    mode = resolve_policy(integrity)
    # np.loadtxt is slow for big graphs; use fromstring on the filtered text.
    with open(path, "rb") as f:
        data = f.read()
    verify_bytes(path, data, mode)
    if b"#" in data:
        lines = [ln for ln in data.splitlines() if not ln.lstrip().startswith(b"#")]
        data = b"\n".join(lines)
    if mode == "repair":
        tails, heads = _salvage_net_lines(path, data)
    else:
        toks = data.split()
        try:
            flat = np.array(toks, dtype=np.int64) if toks else \
                np.empty(0, dtype=np.int64)
        except (ValueError, OverflowError):
            bad = next((i for i, t in enumerate(toks) if not t.isdigit()),
                       0)
            raise MalformedArtifact(
                f"{path}: corrupt .net — non-integer token "
                f"{toks[bad][:40]!r} (token {bad}); repair mode would drop "
                f"the malformed lines")
        out_of_range = (flat < 0) | (flat > 0xFFFFFFFF)
        if out_of_range.any():
            j = int(np.flatnonzero(out_of_range)[0])
            raise MalformedArtifact(
                f"{path}: corrupt .net — token {int(flat[j])} (token {j}) "
                f"is not a uint32 vid")
        if flat.size % 2 != 0:
            raise MalformedArtifact(
                f"{path}: corrupt .net — odd token count {flat.size} "
                f"(a dangling tail with no head)")
        tails = flat[0::2].astype(np.uint32)
        heads = flat[1::2].astype(np.uint32)
    num_records = len(tails)
    if num_parts:
        start, stop = partial_range(num_records, part, num_parts)
        tails, heads = tails[start:stop].copy(), heads[start:stop].copy()
    else:
        start = 0
        tails, heads = tails.copy(), heads.copy()
    return EdgeList(tail=tails, head=heads, file_edges=num_records, start=start)


def dedup_edges(edges: EdgeList) -> EdgeList:
    """Drop duplicate undirected records and self-loops — the reference's
    compile-time DDUP_GRAPH option (defs.h:43, graph_wrapper.h:52), off by
    default.  Records are canonicalized to (min, max) orientation.

    Like the reference, dedup applies to the *loaded record range*: each
    partial load dedups its own slice (graph_wrapper.h dedups the per-rank
    loaded graph), so duplicates spanning different parts survive a
    distributed run in both implementations.  ``file_edges`` becomes the
    deduped count of this load, matching LLAMA's post-dedup getEdges();
    ``start`` keeps the raw file offset of the slice.
    """
    a = np.minimum(edges.tail, edges.head).astype(np.uint64)
    b = np.maximum(edges.tail, edges.head).astype(np.uint64)
    keep = a != b
    key = np.unique(a[keep] << np.uint64(32) | b[keep])
    return EdgeList(tail=(key >> np.uint64(32)).astype(np.uint32),
                    head=(key & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                    file_edges=len(key), start=edges.start)


def load_edges(path: str, part: int = 0, num_parts: int = 0,
               dedup: bool = False, integrity: str | None = None) -> EdgeList:
    """Suffix-dispatching loader (``.dat`` binary, else SNAP text).

    ``dedup`` mirrors DDUP_GRAPH; the CLIs honor SHEEP_DDUP_GRAPH=1 for the
    same effect without recompiling (the reference needs a rebuild).
    ``integrity``: strict/repair/trust (default: env SHEEP_INTEGRITY).
    """
    if path.endswith(".dat"):
        el = read_dat(path, part, num_parts, integrity=integrity)
    else:
        el = read_net(path, part, num_parts, integrity=integrity)
    if dedup or os.environ.get("SHEEP_DDUP_GRAPH", "") == "1":
        el = dedup_edges(el)
    return el


def iter_dat_blocks(path: str, block_edges: int, part: int = 0,
                    num_parts: int = 0, start_edge: int = 0,
                    end_edge: int | None = None):
    """Stream a ``.dat`` file as (tail, head) uint32 blocks — the
    out-of-core path: nothing but the current block is materialized.
    Honors partial-load ranges like :func:`read_dat`.

    Blocks are plain buffered reads, NOT a whole-file memmap (ISSUE 9):
    every memmap page ever touched stays counted in RSS until unmapped,
    so a streamed multi-GB file would "grow" the process to the file
    size and bust any measured-peak memory budget — the exact number the
    external-memory build is accepted on.  seek+read keeps the resident
    set at O(block) no matter the file.

    ``start_edge`` skips that many records of the (possibly partial)
    range before the first block — the resume path of the external-memory
    build (ops/extmem.py): a checkpoint at block boundary k restarts the
    stream at ``k * block_edges`` instead of re-reading the prefix.

    ``end_edge`` is ``start_edge``'s twin (ISSUE 13): the stream stops
    after that many records of the range, so ``[start_edge, end_edge)``
    is a contiguous record slice — the per-leg shard of the distributed
    out-of-core build (ops/distext.py).  Both offsets count from the
    range start, so a leg that resumes at block k passes
    ``start_edge=shard_start + k * block_edges, end_edge=shard_end`` and
    reads exactly the unfolded remainder of its shard.  An empty slice
    (``end_edge <= start_edge``) yields no blocks.

    Raw records only: SHEEP_DDUP_GRAPH is NOT applied here (block-local
    dedup would differ from load-level dedup); a warning is emitted so the
    two paths are never silently inconsistent.

    Integrity: the record-size check runs up front like :func:`read_dat`;
    when a sidecar exists and the whole file is streamed (no partial
    range, no start_edge), the checksum accumulates incrementally across
    blocks and a mismatch raises AT THE END of the stream — bounded
    memory is kept, and a corrupted file still fails the run instead of
    feeding garbage into the fold.

    Fault injection: each block read is a ``dat``-site fault point
    (``SHEEP_IO_FAULT_PLAN`` ``kind@dat:nth``, io/faultfs.hurt_read), so
    EIO/ENOSPC mid-stream is rehearsable — the ext build's retry/resume
    path exists because this hook can prove it works."""
    from . import faultfs
    mode = resolve_policy(None)
    if os.environ.get("SHEEP_DDUP_GRAPH", "") == "1":
        warnings.warn("SHEEP_DDUP_GRAPH is ignored by the streaming block "
                      "reader; dedup the file up front instead")
    nbytes = os.path.getsize(path)
    if nbytes % _XS1_DTYPE.itemsize and mode != "trust":
        msg = (f"{path}: corrupt .dat — {nbytes} bytes is not a multiple "
               f"of the {_XS1_DTYPE.itemsize}-byte XS1 record")
        if mode == "strict":
            raise MalformedArtifact(msg)
        warnings.warn(msg + "; repair drops the partial record")
    num_records = nbytes // _XS1_DTYPE.itemsize
    if num_records == 0:
        return  # an empty file yields no blocks (mmap would reject it)
    start, stop = partial_range(num_records, part, num_parts) if num_parts \
        else (0, num_records)
    sc = read_sidecar(path) if mode != "trust" else None
    whole = (start, stop) == (0, num_records) and start_edge == 0 \
        and end_edge is None
    base = start
    if end_edge is not None:
        stop = min(stop, base + max(0, end_edge))
    if start_edge:
        start = min(stop, base + start_edge)
    if sc is not None and sc["size"] != nbytes:
        msg = (f"{path}: checksum mismatch (size {nbytes} != recorded "
               f"{sc['size']})")
        if mode == "strict":
            from ..integrity.errors import ChecksumMismatch
            raise ChecksumMismatch(msg)
        warnings.warn(msg)
        sc = None
    from ..integrity.sidecar import crc_update
    crc = 0
    with open(path, "rb") as f:
        for a in range(start, stop, block_edges):
            b = min(a + block_edges, stop)
            faultfs.hurt_read(path)
            f.seek(a * _XS1_DTYPE.itemsize)
            rec = np.fromfile(f, dtype=_XS1_DTYPE, count=b - a)
            if len(rec) < b - a:
                raise MalformedArtifact(
                    f"{path}: short read at record {a} (file truncated "
                    f"mid-stream?)")
            if sc is not None and whole:
                crc = crc_update(rec.tobytes(), crc, sc["algo"])
            yield np.ascontiguousarray(rec["tail"]), \
                np.ascontiguousarray(rec["head"])
    if sc is not None and whole:
        # trailing torn bytes (if any) are part of the recorded sum
        tail_bytes = nbytes - num_records * _XS1_DTYPE.itemsize
        if tail_bytes:
            with open(path, "rb") as f:
                f.seek(num_records * _XS1_DTYPE.itemsize)
                crc = crc_update(f.read(), crc, sc["algo"])
        if (crc & 0xFFFFFFFF) != sc["sum"]:
            from ..integrity.errors import ChecksumMismatch
            msg = (f"{path}: checksum mismatch detected at end of stream "
                   f"({sc['algo']} {crc & 0xFFFFFFFF:08x} != recorded "
                   f"{sc['sum']:08x}) — the consumed blocks are suspect")
            if mode == "strict":
                raise ChecksumMismatch(msg)
            warnings.warn(msg)


def iter_net_blocks(path: str, block_bytes: int = 1 << 26,
                    integrity: str | None = None):
    """Stream a SNAP ``.net`` text file as (tail, head) uint32 blocks.

    The reference's fileSequence streams text files record by record
    (lib/sequence.h:95-128); here chunks of ~block_bytes are read, split at
    the last newline, comment lines dropped, and the tokens parsed in bulk.
    A trailing half-record (odd token count in the whole file) raises like
    :func:`read_net`.

    Integrity: stream-verified block-wise like the ``.dat`` path
    (:func:`iter_dat_blocks`) — when a sidecar exists, its recorded size is
    checked up front and the checksum accumulates over the raw chunks as
    they are read, raising AT THE END of the stream on a mismatch: bounded
    memory is kept, and a corrupted file still fails the run instead of
    feeding garbage into the fold.  (An abandoned generator never reaches
    the end-of-stream check; the consumed prefix was parseable but
    unvouched — same contract as the ``.dat`` streamer.)
    """
    mode = resolve_policy(integrity)
    sc = read_sidecar(path) if mode != "trust" else None
    if sc is not None and sc["size"] != os.path.getsize(path):
        msg = (f"{path}: checksum mismatch (size {os.path.getsize(path)} "
               f"!= recorded {sc['size']})")
        if mode == "strict":
            from ..integrity.errors import ChecksumMismatch
            raise ChecksumMismatch(msg)
        warnings.warn(msg)
        sc = None
    from ..integrity.sidecar import crc_update
    crc = 0
    carry = b""
    pending = None  # a dangling tail token whose head is in the next chunk
    with open(path, "rb") as f:
        while True:
            chunk = f.read(block_bytes)
            if not chunk:
                break
            if sc is not None:
                crc = crc_update(chunk, crc, sc["algo"])
            buf = carry + chunk
            cut = buf.rfind(b"\n")
            if cut < 0:
                carry = buf
                continue
            carry, text = buf[cut + 1:], buf[:cut]
            if b"#" in text:
                text = b"\n".join(ln for ln in text.splitlines()
                                  if not ln.lstrip().startswith(b"#"))
            toks = text.split()
            if pending is not None:
                toks.insert(0, pending)
                pending = None
            if len(toks) % 2:
                pending = toks.pop()
            if toks:
                flat = _net_tokens(path, toks)
                yield flat[0::2].copy(), flat[1::2].copy()
    if carry.strip() and not carry.lstrip().startswith(b"#"):
        toks = carry.split()
        if pending is not None:
            toks.insert(0, pending)
            pending = None
        if len(toks) % 2:
            raise MalformedArtifact(f"{path}: odd token count")
        if toks:
            flat = _net_tokens(path, toks)
            yield flat[0::2].copy(), flat[1::2].copy()
    elif pending is not None:
        raise MalformedArtifact(f"{path}: odd token count")
    if sc is not None and (crc & 0xFFFFFFFF) != sc["sum"]:
        msg = (f"{path}: checksum mismatch detected at end of stream "
               f"({sc['algo']} {crc & 0xFFFFFFFF:08x} != recorded "
               f"{sc['sum']:08x}) — the consumed blocks are suspect")
        if mode == "strict":
            from ..integrity.errors import ChecksumMismatch
            raise ChecksumMismatch(msg)
        warnings.warn(msg)


def _net_tokens(path: str, toks) -> np.ndarray:
    """Bulk-parse SNAP text tokens with a typed error on garbage."""
    try:
        return np.array(toks, dtype=np.uint32)
    except (ValueError, OverflowError) as exc:
        raise MalformedArtifact(
            f"{path}: corrupt .net — non-integer token in stream ({exc})")


def write_dat(path: str, tail: np.ndarray, head: np.ndarray) -> None:
    # Crash-safe like every writer in this package (io/atomic.py): the
    # per-part edge files feed the next pipeline stage through a polling
    # filesystem handoff, so a torn record prefix must be impossible.
    # checksummed_write additionally seals a .sum sidecar next to it and
    # (ISSUE 5) preflights the disk with the exact record size.
    rec = np.empty(len(tail), dtype=_XS1_DTYPE)
    rec["tail"] = tail
    rec["head"] = head
    rec["weight"] = 1.0
    with checksummed_write(path, "wb", expect_bytes=rec.nbytes) as f:
        f.write(rec.tobytes())


def write_net(path: str, tail: np.ndarray, head: np.ndarray) -> None:
    # preflight at the uint32 text ceiling (two 10-digit vids + sep/NL)
    with checksummed_write(path, "w",
                           expect_bytes=22 * len(tail)) as f:
        for x, y in zip(tail.tolist(), head.tolist()):
            f.write(f"{x} {y}\n")


def write_edges(path: str, tail: np.ndarray, head: np.ndarray) -> None:
    if path.endswith(".dat"):
        write_dat(path, tail, head)
    else:
        write_net(path, tail, head)
