"""``.tre`` tree-file I/O.

Byte-compatible with the reference's JNodeTable persistence
(lib/jnode.cpp:164-168 save / :76-102 mmap-open): a little-endian ``uint32
end_id`` header followed by ``max_id`` records of ``{uint32 parent, uint32
pst_weight}``.  ``INVALID_JNID`` (0xFFFFFFFF) marks roots.  In the default
build path ``end_id == max_id == len(seq)``.

Integrity (ISSUE 2): writes seal a ``.sum`` sidecar (integrity.sidecar);
reads verify it and harden every way the bytes can lie — a truncated
header, a record region that is not a multiple of 8 bytes, an ``end_id``
that claims more nodes than are stored, an out-of-range or non-monotone
parent pointer.  All failures are typed IntegrityErrors, never a silently
wrong tree.  ``sig`` (optional) records the producing build's input
signature in the sidecar so merge_trees can refuse cross-build merges.
"""

from __future__ import annotations

import warnings

import numpy as np

from .. import INVALID_JNID
from ..integrity.errors import MalformedArtifact
from ..integrity.sidecar import checksummed_write, resolve_policy, verify_bytes

_NODE_DTYPE = np.dtype([("parent", "<u4"), ("pst_weight", "<u4")])


def write_tree(path: str, parent: np.ndarray, pst_weight: np.ndarray,
               sig: str | None = None) -> None:
    assert len(parent) == len(pst_weight)
    rec = np.empty(len(parent), dtype=_NODE_DTYPE)
    rec["parent"] = parent
    rec["pst_weight"] = pst_weight
    # Crash-safe: the shell pipeline polls for .tre files appearing on a
    # shared filesystem (scripts/lib.sh sheep_wait_for), so a consumer
    # must never observe a torn header/record prefix from a killed writer.
    # Exhaustion-aware (ISSUE 5): the exact size preflights the disk.
    extra = {"sig": sig} if sig else None
    with checksummed_write(path, "wb", extra=extra,
                           expect_bytes=4 + rec.nbytes) as f:
        f.write(np.uint32(len(parent)).tobytes())
        f.write(rec.tobytes())


def read_tree(path: str,
              integrity: str | None = None) -> tuple[np.ndarray, np.ndarray]:
    """Returns (parent, pst_weight) uint32 arrays of length end_id.

    ``integrity``: strict (default) / repair / trust — see
    integrity.sidecar.  Structural corruption raises MalformedArtifact in
    every mode; only the checksum layer and best-effort salvage differ.
    """
    mode = resolve_policy(integrity)
    with open(path, "rb") as f:
        data = f.read()
    verify_bytes(path, data, mode)
    if len(data) < 4:
        raise MalformedArtifact(
            f"{path}: corrupt tree — {len(data)} bytes is too short for "
            f"the uint32 end_id header")
    end_id = int(np.frombuffer(data[:4], dtype="<u4")[0])
    body = data[4:]
    if len(body) % _NODE_DTYPE.itemsize:
        msg = (f"{path}: corrupt tree — record region of {len(body)} bytes "
               f"is not a multiple of {_NODE_DTYPE.itemsize} (torn record)")
        if mode != "repair":
            raise MalformedArtifact(msg)
        warnings.warn(msg + "; dropping the partial trailing record")
        body = body[: len(body) - len(body) % _NODE_DTYPE.itemsize]
    rec = np.frombuffer(body, dtype=_NODE_DTYPE)
    if end_id > len(rec):
        raise MalformedArtifact(
            f"{path}: corrupt tree — end_id {end_id} > {len(rec)} stored "
            f"nodes (header lies about the payload)")
    rec = rec[:end_id]
    parent = rec["parent"].copy()
    # Reject corrupt trees up front: every parent must be INVALID or a valid
    # LATER node id (elimination forests only ever link to strictly later
    # positions; the reference dies on such input via live asserts, and
    # downstream passes here index by parent and must never see an OOB or
    # cyclic value).
    linked = parent != INVALID_JNID
    bad = linked & (parent >= end_id)
    if bad.any():
        raise MalformedArtifact(
            f"{path}: corrupt tree — node {int(np.flatnonzero(bad)[0])} has "
            f"parent {int(parent[bad][0])} >= end_id {end_id}")
    ids = np.arange(end_id, dtype=np.uint32)
    non_mono = linked & (parent <= ids)
    if non_mono.any():
        j = int(np.flatnonzero(non_mono)[0])
        raise MalformedArtifact(
            f"{path}: corrupt tree — node {j} has parent {int(parent[j])} "
            f"<= itself (parents must be strictly later positions)")
    return parent, rec["pst_weight"].copy()
