"""``.tre`` tree-file I/O.

Byte-compatible with the reference's JNodeTable persistence
(lib/jnode.cpp:164-168 save / :76-102 mmap-open): a little-endian ``uint32
end_id`` header followed by ``max_id`` records of ``{uint32 parent, uint32
pst_weight}``.  ``INVALID_JNID`` (0xFFFFFFFF) marks roots.  In the default
build path ``end_id == max_id == len(seq)``.
"""

from __future__ import annotations

import numpy as np

from .. import INVALID_JNID
from .atomic import atomic_write

_NODE_DTYPE = np.dtype([("parent", "<u4"), ("pst_weight", "<u4")])


def write_tree(path: str, parent: np.ndarray, pst_weight: np.ndarray) -> None:
    assert len(parent) == len(pst_weight)
    rec = np.empty(len(parent), dtype=_NODE_DTYPE)
    rec["parent"] = parent
    rec["pst_weight"] = pst_weight
    # Crash-safe: the shell pipeline polls for .tre files appearing on a
    # shared filesystem (scripts/lib.sh sheep_wait_for), so a consumer
    # must never observe a torn header/record prefix from a killed writer.
    with atomic_write(path, "wb") as f:
        f.write(np.uint32(len(parent)).tobytes())
        f.write(rec.tobytes())


def read_tree(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Returns (parent, pst_weight) uint32 arrays of length end_id."""
    with open(path, "rb") as f:
        end_id = int(np.frombuffer(f.read(4), dtype="<u4")[0])
        rec = np.fromfile(f, dtype=_NODE_DTYPE)
    if end_id > len(rec):
        raise ValueError(f"{path}: end_id {end_id} > {len(rec)} stored nodes")
    rec = rec[:end_id]
    parent = rec["parent"].copy()
    # Reject corrupt trees up front: every parent must be INVALID or a valid
    # node id (the reference dies on such input via live asserts; downstream
    # passes here index by parent and must never see an OOB value).
    bad = (parent != INVALID_JNID) & (parent >= end_id)
    if bad.any():
        raise ValueError(
            f"{path}: corrupt tree — node {int(np.flatnonzero(bad)[0])} has "
            f"parent {int(parent[bad][0])} >= end_id {end_id}")
    return parent, rec["pst_weight"].copy()
