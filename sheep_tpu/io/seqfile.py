"""Sequence (vertex elimination order) file I/O.

Text format by default — one vid per line — matching the reference's default
(USE_BIN_SEQUENCE off; lib/sequence.h:153-168).  The binary variant
(``binary=True``) writes ``{uint64 size}{uint32 vid[size]}`` exactly like
lib/sequence.h:133-151.
"""

from __future__ import annotations

import numpy as np

from .atomic import atomic_write


def write_sequence(seq: np.ndarray, path: str, binary: bool = False) -> None:
    # Crash-safe (see io/atomic.py): downstream workers poll for the .seq
    # file and must never read a truncated sequence as a complete one.
    seq = np.asarray(seq, dtype=np.uint32)
    if binary:
        with atomic_write(path, "wb") as f:
            f.write(np.uint64(len(seq)).tobytes())
            f.write(seq.astype("<u4").tobytes())
    else:
        with atomic_write(path, "w") as f:
            f.write("\n".join(map(str, seq.tolist())))
            if len(seq):
                f.write("\n")


def read_sequence(path: str, binary: bool = False) -> np.ndarray:
    if binary:
        with open(path, "rb") as f:
            size = int(np.frombuffer(f.read(8), dtype="<u8")[0])
            return np.frombuffer(f.read(4 * size), dtype="<u4").copy()
    with open(path, "rb") as f:
        data = f.read()
    if not data.strip():
        return np.empty(0, dtype=np.uint32)
    return np.array(data.split(), dtype=np.uint32)
