"""Sequence (vertex elimination order) file I/O.

Text format by default — one vid per line — matching the reference's default
(USE_BIN_SEQUENCE off; lib/sequence.h:153-168).  The binary variant
(``binary=True``) writes ``{uint64 size}{uint32 vid[size]}`` exactly like
lib/sequence.h:133-151.

Integrity (ISSUE 2): writes seal a ``.sum`` sidecar; reads verify it and
SNIFF the on-disk format, so a binary ``.seq`` opened as text (or vice
versa) raises a clear MalformedArtifact instead of silently mis-parsing
into a garbage elimination order.  ``binary="auto"`` (used by fsck) trusts
the sniff instead of the caller.
"""

from __future__ import annotations

import warnings

import numpy as np

from ..integrity.errors import MalformedArtifact
from ..integrity.sidecar import checksummed_write, resolve_policy, verify_bytes

#: the only bytes a well-formed TEXT sequence may contain
_TEXT_BYTES = frozenset(b"0123456789 \t\r\n")


def write_sequence(seq: np.ndarray, path: str, binary: bool = False) -> None:
    # Crash-safe (see io/atomic.py): downstream workers poll for the .seq
    # file and must never read a truncated sequence as a complete one.
    # Exhaustion-aware (ISSUE 5): the size estimate preflights the disk —
    # a refusal is a typed DiskExhausted before any bytes land (text rows
    # are priced at the uint32 ceiling of 11 bytes/line).
    seq = np.asarray(seq, dtype=np.uint32)
    if binary:
        with checksummed_write(path, "wb",
                               expect_bytes=8 + 4 * len(seq)) as f:
            f.write(np.uint64(len(seq)).tobytes())
            f.write(seq.astype("<u4").tobytes())
    else:
        with checksummed_write(path, "w",
                               expect_bytes=11 * len(seq)) as f:
            f.write("\n".join(map(str, seq.tolist())))
            if len(seq):
                f.write("\n")


def _looks_text(data: bytes) -> bool:
    """True when every byte (sampled head + tail) is digit/whitespace."""
    sample = data[:4096] + data[-4096:] if len(data) > 8192 else data
    return all(b in _TEXT_BYTES for b in sample)


def _binary_consistent(data: bytes) -> bool:
    """True when the bytes parse exactly as {uint64 size}{uint32 vid[size]}."""
    if len(data) < 8:
        return False
    size = int(np.frombuffer(data[:8], dtype="<u8")[0])
    return 8 + 4 * size == len(data)


def read_sequence(path: str, binary: bool | str = False,
                  integrity: str | None = None) -> np.ndarray:
    """Read an elimination order.  ``binary``: False (text), True, or
    "auto" to sniff the on-disk format (the fsck path)."""
    mode = resolve_policy(integrity)
    with open(path, "rb") as f:
        data = f.read()
    verify_bytes(path, data, mode)
    if binary == "auto":
        binary = not _looks_text(data) or (_binary_consistent(data)
                                           and len(data) >= 8)
    if binary:
        return _parse_binary(path, data, mode)
    return _parse_text(path, data, mode)


def _parse_binary(path: str, data: bytes, mode: str) -> np.ndarray:
    if len(data) < 8:
        raise MalformedArtifact(
            f"{path}: corrupt binary sequence — {len(data)} bytes is too "
            f"short for the uint64 size header")
    size = int(np.frombuffer(data[:8], dtype="<u8")[0])
    want = 8 + 4 * size
    if want != len(data):
        if _looks_text(data):
            raise MalformedArtifact(
                f"{path}: this is a TEXT sequence (digits/whitespace only) "
                f"opened as binary — pass binary=False")
        msg = (f"{path}: corrupt binary sequence — header claims {size} "
               f"vids ({want} bytes) but the file has {len(data)}")
        if mode != "repair":
            raise MalformedArtifact(msg)
        avail = (len(data) - 8) // 4
        if avail < size:  # truncated: keep the complete prefix
            warnings.warn(msg + f"; repair keeps the {avail} complete vids")
            size = avail
        else:  # oversized: the header is authoritative, ignore the tail
            warnings.warn(msg + "; repair ignores the trailing bytes")
    return np.frombuffer(data, dtype="<u4", count=size, offset=8).copy()


def _parse_text(path: str, data: bytes, mode: str) -> np.ndarray:
    if not data.strip():
        return np.empty(0, dtype=np.uint32)
    if not _looks_text(data):
        if _binary_consistent(data):
            raise MalformedArtifact(
                f"{path}: this is a BINARY sequence "
                f"({{uint64 size}}{{uint32 vid[]}}) opened as text — pass "
                f"binary=True")
        bad = next(i for i, b in enumerate(data) if b not in _TEXT_BYTES)
        raise MalformedArtifact(
            f"{path}: corrupt text sequence — non-digit byte "
            f"0x{data[bad]:02x} at offset {bad}")
    toks = data.split()
    try:
        vals = np.array(toks, dtype=np.int64)
    except (ValueError, OverflowError) as exc:
        raise MalformedArtifact(
            f"{path}: corrupt text sequence — unparseable token ({exc})")
    out_of_range = (vals < 0) | (vals > 0xFFFFFFFF)
    if out_of_range.any():
        j = int(np.flatnonzero(out_of_range)[0])
        raise MalformedArtifact(
            f"{path}: corrupt text sequence — token {toks[j].decode()!r} "
            f"is not a uint32 vid")
    return vals.astype(np.uint32)
