from .atomic import atomic_write
from .edges import (
    EdgeList,
    load_edges,
    write_edges,
    read_dat,
    read_net,
    write_dat,
    write_net,
    partial_range,
)
from .seqfile import read_sequence, write_sequence
from .trefile import read_tree, write_tree

__all__ = [
    "atomic_write",
    "EdgeList",
    "load_edges",
    "write_edges",
    "read_dat",
    "read_net",
    "write_dat",
    "write_net",
    "partial_range",
    "read_sequence",
    "write_sequence",
    "read_tree",
    "write_tree",
]
