"""Flight recorder (ISSUE 10): one measurement substrate for every rung.

Two halves, deliberately dependency-free so the jax-free paths (serve/,
ops/extmem) can import them without dragging a backend in:

  obs.trace    hierarchical spans + events appended crash-safely to a
               JSONL file named by ``SHEEP_TRACE`` (unset = disabled at
               ~zero cost), wired through the whole build path: ladder
               decisions, chunk rounds, windowed-handoff fetch/fold
               pairs, ext-block read/fold, native kernel calls,
               checkpoint/WAL fsyncs, fault firings.  The per-phase
               rollup and the shared overlap accounting
               (:func:`~sheep_tpu.obs.trace.overlap_stats`) replace the
               three ad-hoc timing systems that grew before it
               (SHEEP_NATIVE_TIME stderr timers, the hand-built perf
               dicts, prefetch ``busy_s``) — the old record keys remain
               as derived views of the one code path.
  obs.metrics  a tiny counters/gauges/fixed-bucket-histogram registry
               (no deps) the serve daemon exports over the wire
               (``METRICS`` verb, Prometheus text format) and summarizes
               into ``STATS`` (per-verb counts + p50/p99).

``sheep trace`` (cli/trace.py) renders a trace file: per-phase rollup,
the ladder-rung decision explanation (governor price vs measured), and a
text timeline — the precursor of the planner's ``plan --explain``.
"""

from .metrics import (Counter, Gauge, Histogram, Registry,
                      parse_prometheus, proc_status, relabel,
                      set_process_gauges)
from .trace import (current_rid, enabled, event, new_rid, overlap_stats,
                    read_trace, read_trace_chain, repair_trace, rid_scope,
                    rollup, span, timed, trace_segments, trace_summary)

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "parse_prometheus", "proc_status", "relabel", "set_process_gauges",
    "current_rid", "enabled", "event", "new_rid", "overlap_stats",
    "read_trace", "read_trace_chain", "repair_trace", "rid_scope",
    "rollup", "span", "timed", "trace_segments", "trace_summary",
]
