"""Merged fleet timelines: stitch N processes' traces by rid (ISSUE 12).

A routed request crosses 3+ processes (router -> leader -> follower WAL
fsync) and leaves one ``.trace`` file per process.  Each file's spans
carry the request's ``rid`` (obs/trace.py :func:`~sheep_tpu.obs.trace.
rid_scope`), so the rid is the join key — but each file's timestamps are
offsets on its OWN monotonic clock.  Merging needs a per-file clock
offset, and this module estimates it two ways, honestly labeled:

  wall    every meta line records the wall clock at recorder open
          (``t0``), so ``t0 + t`` is a wall-clock estimate.  Wall clocks
          on one host agree to well under a millisecond, but across
          hosts (or under NTP steps) the error is unbounded — the method
          is recorded and the bound reported as unknown.
  rid     when two files share rids, causality bounds the offset: the
          requesting side's span CONTAINS the serving side's work in
          real time, so each shared rid yields an interval the offset
          must lie in; intersecting them gives a midpoint estimate AND
          an honest ``±bound`` (half the surviving interval's width).
          This is the per-connection handshake estimate: every routed
          request is a handshake sample.

The flagship rendering is the failover story: router retry, the dead
leader's final spans, the promoted leader's first fsync — one rid, one
tree, three files.  ``sheep trace --merge`` (cli/trace.py) is the CLI.
"""

from __future__ import annotations

import glob as _glob
import os

from .trace import TRACE_SUFFIX, read_trace


class TraceSource:
    """One trace file, read and wall-aligned: records with ``_abs``
    (meta-t0 + t) stamped, plus the offset correction the estimator
    fills in (seconds to ADD to ``_abs`` to land on the reference
    clock)."""

    __slots__ = ("path", "label", "records", "offset", "bound", "method")

    def __init__(self, path: str, label: str, records: list[dict]):
        self.path = path
        self.label = label
        self.records = records
        self.offset = 0.0
        self.bound: float | None = None
        self.method = "wall"

    def rid_spans(self) -> dict[str, list[dict]]:
        out: dict[str, list[dict]] = {}
        for r in self.records:
            rid = r.get("rid")
            if rid is not None and r.get("k") == "span":
                out.setdefault(rid, []).append(r)
        return out


def collect_trace_paths(specs) -> list[str]:
    """Dirs (walked for ``*.trace`` incl. rotated segments), globs, and
    literal files -> a deduped path list."""
    out: list[str] = []
    for spec in specs:
        if os.path.isdir(spec):
            for dirpath, _, names in os.walk(spec):
                for nm in sorted(names):
                    if nm.endswith(TRACE_SUFFIX):
                        out.append(os.path.join(dirpath, nm))
        elif os.path.isfile(spec):
            out.append(spec)
        else:
            out.extend(sorted(_glob.glob(spec)))
    seen: set = set()
    res = []
    for p in out:
        ap = os.path.abspath(p)
        if ap not in seen:
            seen.add(ap)
            res.append(p)
    return res


def _short_labels(paths: list[str]) -> list[str]:
    """Distinct short labels: basename minus .trace, parent-dir
    qualified on collision."""
    bases = []
    for p in paths:
        b = os.path.basename(p)
        if b.endswith(TRACE_SUFFIX):
            b = b[:-len(TRACE_SUFFIX)]
        bases.append(b)
    labels = []
    for p, b in zip(paths, bases):
        if bases.count(b) > 1:
            b = os.path.basename(os.path.dirname(os.path.abspath(p))) \
                + "/" + b
        labels.append(b)
    return labels


def load_sources(paths: list[str],
                 mode: str = "repair") -> list["TraceSource"]:
    """Read every file (repair mode by default: merged timelines exist
    to read the wreckage of killed runs) and wall-align its records:
    each record gets ``_abs`` = its governing meta segment's wall t0
    plus its monotonic offset."""
    sources = []
    for path, label in zip(paths, _short_labels(paths)):
        records, _, _ = read_trace(path, mode)
        cur_t0 = 0.0
        out = []
        for r in records:
            k = r.get("k")
            if k == "meta":
                cur_t0 = float(r.get("t0", 0.0))
            elif k in ("span", "ev"):
                rr = dict(r)
                rr["_abs"] = cur_t0 + float(r.get("t", 0.0))
                out.append(rr)
        sources.append(TraceSource(path, label, out))
    return sources


def _span_window(spans: list[dict]) -> tuple[float, float]:
    """The [start, end] envelope of one file's spans for one rid."""
    starts = [s["_abs"] for s in spans]
    ends = [s["_abs"] + float(s.get("dur", 0.0)) for s in spans]
    return min(starts), max(ends)


def estimate_offsets(sources: list["TraceSource"]) -> None:
    """Fill each source's (offset, bound, method) relative to the
    reference — the file with the most rid-bearing spans (the router,
    in a fleet).  For every file sharing rids with the reference, each
    shared rid's containment (the longer side's span envelope brackets
    the shorter's in real time) yields an offset interval; their
    intersection gives the estimate and the honest ±bound.  Files with
    no shared rid (or an empty intersection — clocks too strange to
    bracket) stay wall-aligned with bound None."""
    if not sources:
        return

    def _ref_key(s: "TraceSource"):
        spans = s.rid_spans()
        total_dur = sum(float(sp.get("dur", 0.0))
                        for recs in spans.values() for sp in recs)
        # most distinct rids wins; ties break toward the longest total
        # rid-span duration (the CONTAINING side — the router's spans
        # bracket everyone else's, making it the natural reference)
        return (len(spans), total_dur)

    ref = max(sources, key=_ref_key)
    ref.offset, ref.bound, ref.method = 0.0, 0.0, "reference"
    ref_rids = ref.rid_spans()
    for src in sources:
        if src is ref:
            continue
        lo, hi = float("-inf"), float("inf")
        paired = 0
        mine = src.rid_spans()
        for rid, spans in mine.items():
            other = ref_rids.get(rid)
            if not other:
                continue
            a0, a1 = _span_window(other)   # reference side
            b0, b1 = _span_window(spans)   # this file's side
            # correction c satisfies containment of the shorter window
            # inside the longer: c in [a0-b0, a1-b1] (sorted — either
            # side may be the container)
            c0, c1 = a0 - b0, a1 - b1
            if c0 > c1:
                c0, c1 = c1, c0
            lo, hi = max(lo, c0), min(hi, c1)
            paired += 1
        if paired and lo <= hi:
            src.offset = (lo + hi) / 2
            src.bound = (hi - lo) / 2
            src.method = f"rid({paired})"
        # else: wall alignment stands, bound honestly unknown (None)


def merge_by_rid(sources: list["TraceSource"]) -> dict[str, list[dict]]:
    """rid -> time-ordered records across every source, each stamped
    with ``_src`` (the source label) and ``_t`` (reference-clock
    seconds)."""
    rids: dict[str, list[dict]] = {}
    for s in sources:
        for r in s.records:
            rid = r.get("rid")
            if rid is None:
                continue
            rr = dict(r)
            rr["_src"] = s.label
            rr["_t"] = r["_abs"] + s.offset
            rids.setdefault(rid, []).append(rr)
    for recs in rids.values():
        recs.sort(key=lambda r: r["_t"])
    return rids


def _fmt_s(s: float) -> str:
    if s >= 1:
        return f"{s:.3f}s"
    return f"{s * 1000:.2f}ms"


def _fmt_off(s: float) -> str:
    return f"{'+' if s >= 0 else ''}{s * 1000:.3f}ms"


def render_merged(sources: list["TraceSource"],
                  rids: dict[str, list[dict]],
                  only_rid: str | None = None,
                  max_rids: int = 20) -> str:
    lines = [f"merged timeline: {len(sources)} file(s), "
             f"{len(rids)} rid(s)"]
    width = max((len(s.label) for s in sources), default=8)
    for s in sources:
        if s.method == "reference":
            tag = "reference clock"
        elif s.bound is not None:
            tag = (f"offset {_fmt_off(s.offset)} "
                   f"±{s.bound * 1000:.3f}ms ({s.method}-aligned)")
        else:
            tag = ("wall-clock aligned (no shared rid; "
                   "offset bound UNKNOWN)")
        lines.append(f"  {s.label:<{width}}  {tag}")
    lines.append("")
    show = [only_rid] if only_rid else \
        sorted(rids, key=lambda r: rids[r][0]["_t"])
    elided = max(0, len(show) - max_rids)
    for rid in show[:max_rids]:
        recs = rids.get(rid)
        if not recs:
            lines.append(f"rid {rid}: no records")
            continue
        t0 = recs[0]["_t"]
        srcs = sorted({r["_src"] for r in recs})
        lines.append(f"rid {rid}  ({len(recs)} record(s) across "
                     f"{'/'.join(srcs)})")
        for r in recs:
            rel = r["_t"] - t0
            name = r.get("name", "?")
            if r.get("k") == "span":
                tail = _fmt_s(float(r.get("dur", 0.0)))
            else:
                tail = "ev"
            extra = " ".join(f"{k}={v}" for k, v in
                             list(r.get("a", {}).items())[:4])
            lines.append(f"  {_fmt_off(rel):>12}  {r['_src']:<{width}} "
                         f"{name:<18} {tail:>9}"
                         + (f"  [{extra}]" if extra else ""))
        lines.append("")
    if elided:
        lines.append(f"... {elided} more rid(s) elided (-n raises the "
                     f"cap, --rid picks one)")
    return "\n".join(lines) + "\n"


def merged_json(sources: list["TraceSource"],
                rids: dict[str, list[dict]],
                only_rid: str | None = None) -> dict:
    out_rids = {}
    for rid, recs in rids.items():
        if only_rid and rid != only_rid:
            continue
        t0 = recs[0]["_t"]
        out_rids[rid] = [{
            "src": r["_src"],
            "k": r.get("k"),
            "name": r.get("name"),
            "t_s": round(r["_t"] - t0, 6),
            "dur_s": round(float(r.get("dur", 0.0)), 6)
            if r.get("k") == "span" else None,
            "a": r.get("a", {}),
        } for r in recs]
    return {
        "files": [{
            "path": s.path,
            "label": s.label,
            "offset_s": round(s.offset, 6),
            "offset_bound_s": round(s.bound, 6)
            if s.bound is not None else None,
            "method": s.method,
        } for s in sources],
        "rids": out_rids,
    }
