"""Counters, gauges, and fixed-bucket histograms — no dependencies.

The serve daemon's quantitative face (ISSUE 10): every request verb gets
a counter and a latency histogram, replication lag and applied seqno are
gauges, and the whole registry renders as Prometheus text exposition
format over the ``METRICS`` verb (serve/daemon.py) so any standard
scraper — or ``nc`` — can read it.  ``STATS`` derives its per-verb
counts and p50/p99 from the same registry, so the wire summary and the
scrape can never disagree.

Deliberately tiny: fixed bucket boundaries (quantiles are bucket
upper-bound estimates, which is what Prometheus itself gives you),
label support limited to one flat label set per child, a single lock
per registry.  Each daemon owns its own :class:`Registry` so in-process
test clusters do not share counters.
"""

from __future__ import annotations

import os
import threading
import time

#: request-latency bucket upper bounds in seconds (powers-of-~2.5 from
#: 100us to 10s; +Inf is implicit)
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: sliding-window view (ISSUE 12): a ring of per-slot bucket snapshots —
#: WINDOW_SLOTS slots of WINDOW_SLOT_S seconds each (~30s of history) so
#: ``sheep top`` shows CURRENT latency while the lifetime series stays
#: cumulative for scrapers
WINDOW_SLOTS = 15
WINDOW_SLOT_S = 2.0


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotone counter; ``labels(**kv)`` returns the child for one
    label set (created on first use)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", _lock=None):
        self.name = name
        self.help = help
        self._lock = _lock or threading.Lock()
        self.value = 0.0
        self._children: dict[tuple, Counter] = {}

    def labels(self, **kv) -> "Counter":
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Counter(self.name, self.help, _lock=self._lock)
                self._children[key] = child
        return child

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def _render(self, out: list) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        if self._children:
            for key, child in sorted(self._children.items()):
                out.append(f"{self.name}{_label_str(dict(key))} "
                           f"{_num(child.value)}")
        else:
            out.append(f"{self.name} {_num(self.value)}")

    def snapshot(self) -> dict:
        """{label-tuple-or-(): value} for STATS derivation."""
        with self._lock:
            if self._children:
                return {k: c.value for k, c in self._children.items()}
            return {(): self.value}

    def children(self) -> dict:
        """{label-tuple: child} — how STATS walks the per-verb series."""
        with self._lock:
            return dict(self._children)


class Gauge(Counter):
    kind = "gauge"

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def labels(self, **kv) -> "Gauge":
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Gauge(self.name, self.help, _lock=self._lock)
                self._children[key] = child
        return child


class Histogram:
    """Fixed-bucket latency histogram.  ``observe(seconds)``;
    ``quantile(q)`` returns the upper bound of the bucket holding the
    q-th observation (the standard bucket-estimate; exact enough for
    p50/p99 alerting, cheap enough for the request path)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS, _lock=None,
                 clock=None):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self._lock = _lock or threading.Lock()
        self._clock = clock or time.monotonic
        self.counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf
        self.sum = 0.0
        self.count = 0
        # the sliding-window ring: per-slot bucket counts + the slot
        # index each position last served (stale positions re-zero lazily
        # on the next observe that lands in them)
        self._w_counts = [[0] * (len(self.buckets) + 1)
                          for _ in range(WINDOW_SLOTS)]
        self._w_stamp = [-1] * WINDOW_SLOTS
        self._children: dict[tuple, Histogram] = {}

    def labels(self, **kv) -> "Histogram":
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Histogram(self.name, self.help, self.buckets,
                                  _lock=self._lock, clock=self._clock)
                self._children[key] = child
        return child

    def children(self) -> dict:
        """{label-tuple: child} — how STATS walks the per-verb series."""
        with self._lock:
            return dict(self._children)

    def observe(self, v: float) -> None:
        i = 0
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                break
        else:
            i = len(self.buckets)
        slot = int(self._clock() / WINDOW_SLOT_S)
        pos = slot % WINDOW_SLOTS
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if self._w_stamp[pos] != slot:
                self._w_stamp[pos] = slot
                wc = self._w_counts[pos]
                for j in range(len(wc)):
                    wc[j] = 0
            self._w_counts[pos][i] += 1

    # -- the sliding-window view (ISSUE 12) --------------------------------

    def window_counts(self) -> list[int]:
        """Bucket counts over the last ~WINDOW_SLOTS*WINDOW_SLOT_S
        seconds (slots whose stamp is inside the window)."""
        now_slot = int(self._clock() / WINDOW_SLOT_S)
        lo = now_slot - WINDOW_SLOTS + 1
        out = [0] * (len(self.buckets) + 1)
        with self._lock:
            for stamp, wc in zip(self._w_stamp, self._w_counts):
                if lo <= stamp <= now_slot:
                    for j, c in enumerate(wc):
                        out[j] += c
        return out

    def window_count(self) -> int:
        return sum(self.window_counts())

    def window_quantile(self, q: float) -> float:
        """The bucket-upper-bound q-quantile over the sliding window —
        what ``sheep top`` renders as CURRENT latency (0.0 when the
        window is empty; the lifetime :meth:`quantile` is untouched)."""
        counts = self.window_counts()
        total = sum(counts)
        if total == 0:
            return 0.0
        want = max(1, int(q * total + 0.999999))
        cum = 0
        for i, c in enumerate(counts):
            cum += c
            if cum >= want:
                return self.buckets[i] if i < len(self.buckets) \
                    else self.buckets[-1]
        return self.buckets[-1]

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile in seconds (0.0 when
        empty; the last finite bucket bound when q lands in +Inf)."""
        with self._lock:
            total = self.count
            if total == 0:
                return 0.0
            want = max(1, int(q * total + 0.999999))
            cum = 0
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= want:
                    return self.buckets[i] if i < len(self.buckets) \
                        else self.buckets[-1]
        return self.buckets[-1]

    def _render_one(self, out: list, labels: dict) -> None:
        cum = 0
        for ub, c in zip(self.buckets, self.counts):
            cum += c
            lb = dict(labels)
            lb["le"] = _num(ub)
            out.append(f"{self.name}_bucket{_label_str(lb)} {cum}")
        lb = dict(labels)
        lb["le"] = "+Inf"
        out.append(f"{self.name}_bucket{_label_str(lb)} {self.count}")
        out.append(f"{self.name}_sum{_label_str(labels)} "
                   f"{_num(round(self.sum, 9))}")
        out.append(f"{self.name}_count{_label_str(labels)} {self.count}")

    def _render(self, out: list) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        if self._children:
            for key, child in sorted(self._children.items()):
                child._render_one(out, dict(key))
        else:
            self._render_one(out, {})


def _num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Registry:
    """Named metrics, one namespace; ``render()`` is the scrape body."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        m = self._get(name, lambda: Counter(name, help))
        assert isinstance(m, Counter) and m.kind == "counter", name
        return m

    def gauge(self, name: str, help: str = "") -> Gauge:
        m = self._get(name, lambda: Gauge(name, help))
        assert isinstance(m, Gauge), name
        return m

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        m = self._get(name, lambda: Histogram(name, help, buckets))
        assert isinstance(m, Histogram), name
        return m

    def render(self) -> str:
        """Prometheus text exposition format; always newline-terminated
        (the METRICS verb's ``bytes=`` count includes it)."""
        out: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for _, m in metrics:
            m._render(out)  # type: ignore[attr-defined]
        return "\n".join(out) + "\n"


# -- scrape plumbing (the fleet fan-in, ISSUE 12) ---------------------------


def parse_prometheus(body: str) -> list[tuple[str, dict, float]]:
    """Parse text exposition format into ``(name, labels, value)``
    samples — the read half the fleet aggregator and ``sheep top`` share.
    Unparseable lines are skipped (a scrape is advisory input, never a
    crash)."""
    out: list[tuple[str, dict, float]] = []
    for ln in body.splitlines():
        if not ln or ln.startswith("#"):
            continue
        head, sep, val = ln.rpartition(" ")
        if not sep:
            continue
        try:
            fval = float(val)
        except ValueError:
            continue
        name, labels = head, {}
        if head.endswith("}") and "{" in head:
            name, _, inner = head.partition("{")
            for part in inner[:-1].split(","):
                if not part:
                    continue
                k, _, v = part.partition("=")
                labels[k] = v.strip('"')
        out.append((name, labels, fval))
    return out


def relabel(body: str, extra: dict,
            seen_headers: set | None = None) -> str:
    """Merge ``extra`` labels into every sample line of a scrape body —
    how the router's fleet scrape stamps ``instance``/``cluster`` onto
    each member's series.  A label the sample ALREADY carries wins over
    ``extra`` (a fleet-derived gauge's own ``cluster=`` must not be
    clobbered by the stamping pass).  ``seen_headers`` (when given)
    dedupes ``# HELP``/``# TYPE`` lines across members sharing metric
    names."""
    out: list[str] = []
    for ln in body.splitlines():
        if not ln:
            continue
        if ln.startswith("#"):
            if seen_headers is not None:
                if ln in seen_headers:
                    continue
                seen_headers.add(ln)
            out.append(ln)
            continue
        head, sep, val = ln.rpartition(" ")
        if not sep:
            out.append(ln)
            continue
        name, labels = head, {}
        if head.endswith("}") and "{" in head:
            name, _, inner = head.partition("{")
            for part in inner[:-1].split(","):
                if not part:
                    continue
                k, _, v = part.partition("=")
                labels[k] = v.strip('"')
        for k, v in extra.items():
            labels.setdefault(k, str(v))
        out.append(f"{name}{_label_str(labels)} {val}")
    return "\n".join(out) + ("\n" if out else "")


# -- standard process self-accounting (ISSUE 12 satellite) ------------------
#
# What scripts/servebench.py grew as ``_proc_capture`` per benched
# process, promoted into the registry: every METRICS payload self-reports
# VmRSS/VmHWM/threads/fds/uptime/pid, refreshed on scrape.


def proc_status(pid: int | None = None) -> dict:
    """Per-process accounting from ``/proc/<pid>/status`` (this process
    by default): pid, vmrss/vmhwm (raw kB strings), threads,
    cpus_allowed_list, open fd count, and sched affinity."""
    pid = os.getpid() if pid is None else pid
    rec: dict = {"pid": pid}
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                key, _, rest = line.partition(":")
                if key in ("VmRSS", "VmHWM", "Threads",
                           "Cpus_allowed_list"):
                    rec[key.lower()] = rest.strip()
    except OSError as exc:
        rec["error"] = str(exc)
    try:
        rec["fds"] = len(os.listdir(f"/proc/{pid}/fd"))
    except OSError:
        pass
    try:
        rec["affinity_cores"] = sorted(os.sched_getaffinity(pid))
    except (AttributeError, OSError):
        pass
    return rec


def _kb_bytes(s) -> int | None:
    try:
        return int(str(s).split()[0]) * 1024
    except (ValueError, IndexError, AttributeError):
        return None


def set_process_gauges(registry: "Registry",
                       started_at: float | None = None) -> None:
    """Refresh the standard ``sheep_process_*`` gauges from /proc —
    called at scrape time so the payload self-reports current
    accounting (``started_at`` is a ``time.monotonic`` origin for the
    uptime gauge)."""
    st = proc_status()
    g = registry.gauge
    g("sheep_process_pid", "process id").set(st["pid"])
    rss = _kb_bytes(st.get("vmrss"))
    if rss is not None:
        g("sheep_process_vmrss_bytes", "resident set size").set(rss)
    hwm = _kb_bytes(st.get("vmhwm"))
    if hwm is not None:
        g("sheep_process_vmhwm_bytes",
          "resident set high-water mark").set(hwm)
    try:
        g("sheep_process_threads", "thread count").set(
            int(st.get("threads", 0)))
    except (TypeError, ValueError):
        pass
    if "fds" in st:
        g("sheep_process_open_fds", "open file descriptors").set(
            st["fds"])
    if started_at is not None:
        g("sheep_process_uptime_seconds", "process uptime").set(
            round(time.monotonic() - started_at, 3))
