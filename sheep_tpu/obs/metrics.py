"""Counters, gauges, and fixed-bucket histograms — no dependencies.

The serve daemon's quantitative face (ISSUE 10): every request verb gets
a counter and a latency histogram, replication lag and applied seqno are
gauges, and the whole registry renders as Prometheus text exposition
format over the ``METRICS`` verb (serve/daemon.py) so any standard
scraper — or ``nc`` — can read it.  ``STATS`` derives its per-verb
counts and p50/p99 from the same registry, so the wire summary and the
scrape can never disagree.

Deliberately tiny: fixed bucket boundaries (quantiles are bucket
upper-bound estimates, which is what Prometheus itself gives you),
label support limited to one flat label set per child, a single lock
per registry.  Each daemon owns its own :class:`Registry` so in-process
test clusters do not share counters.
"""

from __future__ import annotations

import threading

#: request-latency bucket upper bounds in seconds (powers-of-~2.5 from
#: 100us to 10s; +Inf is implicit)
DEFAULT_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                   0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _label_str(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotone counter; ``labels(**kv)`` returns the child for one
    label set (created on first use)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "", _lock=None):
        self.name = name
        self.help = help
        self._lock = _lock or threading.Lock()
        self.value = 0.0
        self._children: dict[tuple, Counter] = {}

    def labels(self, **kv) -> "Counter":
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Counter(self.name, self.help, _lock=self._lock)
                self._children[key] = child
        return child

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n

    def _render(self, out: list) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        if self._children:
            for key, child in sorted(self._children.items()):
                out.append(f"{self.name}{_label_str(dict(key))} "
                           f"{_num(child.value)}")
        else:
            out.append(f"{self.name} {_num(self.value)}")

    def snapshot(self) -> dict:
        """{label-tuple-or-(): value} for STATS derivation."""
        with self._lock:
            if self._children:
                return {k: c.value for k, c in self._children.items()}
            return {(): self.value}

    def children(self) -> dict:
        """{label-tuple: child} — how STATS walks the per-verb series."""
        with self._lock:
            return dict(self._children)


class Gauge(Counter):
    kind = "gauge"

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def labels(self, **kv) -> "Gauge":
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Gauge(self.name, self.help, _lock=self._lock)
                self._children[key] = child
        return child


class Histogram:
    """Fixed-bucket latency histogram.  ``observe(seconds)``;
    ``quantile(q)`` returns the upper bound of the bucket holding the
    q-th observation (the standard bucket-estimate; exact enough for
    p50/p99 alerting, cheap enough for the request path)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS, _lock=None):
        self.name = name
        self.help = help
        self.buckets = tuple(buckets)
        self._lock = _lock or threading.Lock()
        self.counts = [0] * (len(self.buckets) + 1)  # +1 = +Inf
        self.sum = 0.0
        self.count = 0
        self._children: dict[tuple, Histogram] = {}

    def labels(self, **kv) -> "Histogram":
        key = tuple(sorted(kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = Histogram(self.name, self.help, self.buckets,
                                  _lock=self._lock)
                self._children[key] = child
        return child

    def children(self) -> dict:
        """{label-tuple: child} — how STATS walks the per-verb series."""
        with self._lock:
            return dict(self._children)

    def observe(self, v: float) -> None:
        i = 0
        for i, ub in enumerate(self.buckets):
            if v <= ub:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def quantile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile in seconds (0.0 when
        empty; the last finite bucket bound when q lands in +Inf)."""
        with self._lock:
            total = self.count
            if total == 0:
                return 0.0
            want = max(1, int(q * total + 0.999999))
            cum = 0
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= want:
                    return self.buckets[i] if i < len(self.buckets) \
                        else self.buckets[-1]
        return self.buckets[-1]

    def _render_one(self, out: list, labels: dict) -> None:
        cum = 0
        for ub, c in zip(self.buckets, self.counts):
            cum += c
            lb = dict(labels)
            lb["le"] = _num(ub)
            out.append(f"{self.name}_bucket{_label_str(lb)} {cum}")
        lb = dict(labels)
        lb["le"] = "+Inf"
        out.append(f"{self.name}_bucket{_label_str(lb)} {self.count}")
        out.append(f"{self.name}_sum{_label_str(labels)} "
                   f"{_num(round(self.sum, 9))}")
        out.append(f"{self.name}_count{_label_str(labels)} {self.count}")

    def _render(self, out: list) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        if self._children:
            for key, child in sorted(self._children.items()):
                child._render_one(out, dict(key))
        else:
            self._render_one(out, {})


def _num(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Registry:
    """Named metrics, one namespace; ``render()`` is the scrape body."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        m = self._get(name, lambda: Counter(name, help))
        assert isinstance(m, Counter) and m.kind == "counter", name
        return m

    def gauge(self, name: str, help: str = "") -> Gauge:
        m = self._get(name, lambda: Gauge(name, help))
        assert isinstance(m, Gauge), name
        return m

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        m = self._get(name, lambda: Histogram(name, help, buckets))
        assert isinstance(m, Histogram), name
        return m

    def render(self) -> str:
        """Prometheus text exposition format; always newline-terminated
        (the METRICS verb's ``bytes=`` count includes it)."""
        out: list[str] = []
        with self._lock:
            metrics = sorted(self._metrics.items())
        for _, m in metrics:
            m._render(out)  # type: ignore[attr-defined]
        return "\n".join(out) + "\n"
