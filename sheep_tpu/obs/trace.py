"""Span tracing: the flight recorder's write side (ISSUE 10).

``SHEEP_TRACE=<path>`` turns every :func:`span`/:func:`event` call into
one JSON line appended to ``<path>``; unset, both are near-free —
:func:`span` returns a shared no-op singleton (no recorder, no file, no
per-call allocation beyond the caller's own kwargs), so the
instrumentation can live permanently in the hot paths.

File format: JSON Lines, one record per line, append-only::

    {"k":"meta","v":1,"pid":...,"t0":<unix>, "argv":[...]}
    {"k":"span","name":"fold","id":7,"par":3,"tid":2,"t":0.0123,
     "dur":0.456,"a":{"block":4}}
    {"k":"ev","name":"fault","par":3,"tid":2,"t":0.5,"a":{...}}

``t`` is seconds since the recorder opened (monotonic clock — a clock
step mid-run cannot reorder the timeline); spans are written at EXIT (so
``dur`` is exact), which means a parent line follows its children —
readers reconstruct the hierarchy from ``id``/``par``.  Every line is
flushed as it lands, so a kill -9 mid-run leaves a readable prefix plus
at most one torn trailing line — the same contract as the WAL
(serve/wal.py): :func:`read_trace` refuses the tear strict, salvages the
prefix in repair/trust, and refuses mid-file rot in every mode.  A CLEAN
close seals a ``.sum`` sidecar (integrity/sidecar.py) so ``sheep fsck``
can vouch for a finished trace byte-for-byte.

Thread-safety: span nesting is tracked per thread (threading.local), the
file write is one lock-guarded append per line.  Processes do not share
a recorder — a subprocess inheriting ``SHEEP_TRACE`` appends its own
``meta`` segment to the same file (append mode), which readers treat as
a new segment.

The shared overlap accounting lives here too (:func:`overlap_stats`):
every "serialized phase time vs realized wall" number in the repo — the
windowed handoff's ``overlap_frac``, the ext build's read/fold overlap,
the prefetcher's producer busy time — derives from this ONE function
instead of three hand-rolled copies (the satellite dedup of ISSUE 10).
"""

from __future__ import annotations

import contextlib
import io
import itertools
import json
import os
import re
import threading
import time
import warnings

ENV = "SHEEP_TRACE"
TRACE_SUFFIX = ".trace"
TRACE_VERSION = 1

#: rotation cap for long-lived daemons (ISSUE 12): when the active JSONL
#: grows past this many megabytes it is renamed to a numbered segment
#: (``x.trace`` -> ``x.0001.trace``) whose ``.sum`` is sealed on rotation,
#: and a fresh active file continues the SAME clock (t keeps counting
#: from the recorder's open, the new meta line repeats the original wall
#: ``t0``) — readers concatenate the chain.  Unset/0 = never rotate.
MAX_MB_ENV = "SHEEP_TRACE_MAX_MB"

_SEG_RE = re.compile(r"\.(\d{4})\.trace$")


def _segment_name(path: str, n: int) -> str:
    base = path[:-len(TRACE_SUFFIX)] if path.endswith(TRACE_SUFFIX) \
        else path
    return f"{base}.{n:04d}{TRACE_SUFFIX}"


def is_rotated_segment(path: str) -> bool:
    """True for a rotation-sealed segment (``x.0001.trace``): its tail
    was sealed at rotation, so a tear there is mid-chain damage — torn
    tails are legal ONLY on the newest (active) file of a chain."""
    return _SEG_RE.search(path) is not None


def trace_segments(path: str) -> list[str]:
    """The segment chain for an active trace path: rotated segments in
    rotation order, then the active file itself (when it exists)."""
    import glob as _glob
    base = path[:-len(TRACE_SUFFIX)] if path.endswith(TRACE_SUFFIX) \
        else path
    segs = []
    for p in _glob.glob(base + ".[0-9][0-9][0-9][0-9]" + TRACE_SUFFIX):
        m = _SEG_RE.search(p)
        if m:
            segs.append((int(m.group(1)), p))
    out = [p for _, p in sorted(segs)]
    if os.path.exists(path):
        out.append(path)
    return out


# -- request-id propagation (ISSUE 12) --------------------------------------
#
# A fleet request crosses processes (router -> leader -> follower fsync);
# the rid is the join key that lets ``sheep trace --merge`` stitch their
# trace files back into one timeline.  The rid rides a thread-local scope
# so every span/event recorded inside it carries a top-level ``rid``
# field — including spans the SAMPLER skipped around (the scope is set
# whether or not the wrapping span recorded), and downstream spans the
# request opens (WAL fsync, repartition kicks on the request thread).

_rid_tl = threading.local()
_RID_SEED = os.urandom(4).hex()
_rid_counter = itertools.count(1)


def new_rid() -> str:
    """A compact process-unique request id: 8 random hex chars (the
    process) + an 8-hex counter — cheaper than urandom per request and
    unique across routers with overwhelming probability."""
    return f"{_RID_SEED}{next(_rid_counter):08x}"


def current_rid() -> str | None:
    return getattr(_rid_tl, "rid", None)


class _RidScope:
    """Class-based (not generator-based) context manager: this sits on
    the per-request hot path of router AND daemon, and the generator
    protocol's ~1.5us/call was most of the wire-token overhead budget
    (PERF_NOTES r10)."""

    __slots__ = ("rid", "prev")

    def __init__(self, rid: str | None):
        self.rid = rid

    def __enter__(self) -> "_RidScope":
        if self.rid:
            self.prev = getattr(_rid_tl, "rid", None)
            _rid_tl.rid = self.rid
        return self

    def __exit__(self, *exc) -> bool:
        if self.rid:
            _rid_tl.rid = self.prev
        return False


_NOOP_RID_SCOPE = _RidScope(None)


def rid_scope(rid: str | None) -> "_RidScope":
    """Attach ``rid`` to every span/event recorded by this thread inside
    the scope (None = the shared no-op).  Nesting restores the outer rid
    on exit."""
    return _RidScope(rid) if rid else _NOOP_RID_SCOPE


class _NoopSpan:
    """The disabled-mode span: one shared instance, no state, no work.
    Identity-stable so the zero-allocation fast path is testable
    (``span("a") is span("b")`` when tracing is off)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        pass


NOOP_SPAN = _NoopSpan()


def _json_safe(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    try:  # numpy scalars and friends
        import numbers
        if isinstance(v, numbers.Integral):
            return int(v)
        if isinstance(v, numbers.Real):
            return float(v)
    except Exception:
        pass
    return str(v)


class _Span:
    """One live span (enabled mode).  Created by TraceRecorder.span."""

    __slots__ = ("rec", "name", "attrs", "id", "par", "t0", "rid")

    def __init__(self, rec: "TraceRecorder", name: str, attrs: dict):
        self.rec = rec
        self.name = name
        self.attrs = attrs

    def annotate(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "_Span":
        rec = self.rec
        tl = rec._tl
        stack = getattr(tl, "stack", None)
        if stack is None:
            stack = tl.stack = []
        self.par = stack[-1].id if stack else None
        self.id = rec._next_id()
        self.rid = current_rid()
        stack.append(self)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        t1 = time.perf_counter()
        rec = self.rec
        stack = rec._tl.stack
        # tolerate a mispaired exit (a span abandoned by an exception in
        # a generator): pop down to this span, never past it
        while stack and stack[-1] is not self:
            stack.pop()
        if stack:
            stack.pop()
        rec._write_span(self, self.t0, t1 - self.t0)
        return False


class TraceRecorder:
    """Appends span/event lines to one JSONL file; tracks the in-memory
    per-phase rollup so live processes (bench records, serve STATS) can
    embed a summary without re-reading the file."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        # append mode: a resumed/forked run adds its own meta segment; a
        # stale sidecar from a previous clean close can no longer vouch
        # for the growing file, so drop it until the next clean close
        from ..integrity.sidecar import sidecar_path
        with contextlib.suppress(OSError):
            os.unlink(sidecar_path(path))
        self._f: io.TextIOBase | None = open(path, "a",
                                             encoding="ascii",
                                             errors="replace")
        self._lock = threading.Lock()
        self._tl = threading.local()
        self._id = 0
        self._t0 = time.perf_counter()
        self._wall0 = time.time()
        self._phases: dict[str, list] = {}  # name -> [count, total_s]
        self._events: dict[str, int] = {}   # name -> count
        # rotation state (SHEEP_TRACE_MAX_MB): byte budget for the
        # active file, current size, and the next segment number
        # (continuing past any segments an earlier recorder left)
        mb = os.environ.get(MAX_MB_ENV, "")
        try:
            self._max_bytes = int(float(mb) * (1 << 20)) if mb else 0
        except ValueError:
            warnings.warn(f"{MAX_MB_ENV}={mb!r} is not a number; "
                          f"trace rotation disabled")
            self._max_bytes = 0
        try:
            self._nbytes = os.path.getsize(path)
        except OSError:
            self._nbytes = 0
        self._seg = 0
        for p in trace_segments(path):
            m = _SEG_RE.search(p)
            if m:
                self._seg = max(self._seg, int(m.group(1)))
        import sys
        self._emit({"k": "meta", "v": TRACE_VERSION, "pid": os.getpid(),
                    "t0": self._wall0,
                    "argv": [str(a) for a in sys.argv[:6]]})

    def _next_id(self) -> int:
        with self._lock:
            self._id += 1
            return self._id

    def _emit(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"),
                          default=_json_safe) + "\n"
        seal = None
        with self._lock:
            f = self._f
            if f is None:
                return
            try:
                f.write(line)
                f.flush()
                self._nbytes += len(line)
            except (OSError, ValueError):
                pass  # tracing must never break the traced build
            if self._max_bytes and self._nbytes >= self._max_bytes:
                seal = self._rotate_locked()
        if seal is not None:
            # the rotated segment's sidecar seals OUTSIDE the emit lock:
            # the atomic writer's fault hooks may emit a trace event and
            # re-enter _emit (the new active file absorbs it)
            try:
                from ..integrity.sidecar import write_sidecar
                write_sidecar(seal)
            except Exception:
                pass  # an unsealed segment reads as an unsealed partial

    def _rotate_locked(self) -> str | None:
        """Rename the full active file to the next numbered segment and
        reopen a fresh one continuing the SAME clock (t stays relative
        to the recorder's open; the new meta repeats the original wall
        t0 so readers align the chain as one timeline).  Returns the
        rotated segment path for the caller to seal, or None."""
        f = self._f
        try:
            f.flush()
            os.fsync(f.fileno())
        except (OSError, ValueError):
            pass
        with contextlib.suppress(Exception):
            f.close()
        self._f = None
        self._seg += 1
        seg = _segment_name(self.path, self._seg)
        try:
            os.replace(self.path, seg)
        except OSError:
            seg = None
        try:
            self._f = open(self.path, "a", encoding="ascii",
                           errors="replace")
        except OSError:
            return seg  # rotation stands; further lines are dropped
        self._nbytes = 0
        line = json.dumps({"k": "meta", "v": TRACE_VERSION,
                           "pid": os.getpid(), "t0": self._wall0,
                           "seg": self._seg},
                          separators=(",", ":")) + "\n"
        try:
            self._f.write(line)
            self._f.flush()
            self._nbytes += len(line)
        except (OSError, ValueError):
            pass
        return seg

    def span(self, name: str, attrs: dict) -> _Span:
        return _Span(self, name, attrs)

    def event(self, name: str, attrs: dict) -> None:
        stack = getattr(self._tl, "stack", None)
        with self._lock:
            self._events[name] = self._events.get(name, 0) + 1
        rec = {"k": "ev", "name": name,
               "par": stack[-1].id if stack else None,
               "tid": threading.get_ident() & 0xFFFF,
               "t": round(time.perf_counter() - self._t0, 6),
               "a": {k: _json_safe(v) for k, v in attrs.items()}}
        rid = current_rid()
        if rid is not None:
            rec["rid"] = rid
        self._emit(rec)

    def _write_span(self, sp: _Span, t0: float, dur: float) -> None:
        with self._lock:
            acc = self._phases.setdefault(sp.name, [0, 0.0])
            acc[0] += 1
            acc[1] += dur
        rec = {"k": "span", "name": sp.name, "id": sp.id,
               "par": sp.par,
               "tid": threading.get_ident() & 0xFFFF,
               "t": round(t0 - self._t0, 6),
               "dur": round(dur, 6),
               "a": {k: _json_safe(v) for k, v in sp.attrs.items()}}
        if sp.rid is not None:
            rec["rid"] = sp.rid
        self._emit(rec)

    def summary(self) -> dict:
        """In-memory per-phase rollup: {name: {count, total_s}} plus
        "_events" counts — what bench records embed live."""
        with self._lock:
            out = {name: {"count": c, "total_s": round(s, 6)}
                   for name, (c, s) in sorted(self._phases.items())}
            if self._events:
                out["_events"] = dict(sorted(self._events.items()))
            return out

    def close(self, seal: bool = True) -> None:
        """Flush, close, and (on a clean close) seal the ``.sum``
        sidecar that lets ``sheep fsck`` vouch for the finished file."""
        with self._lock:
            f, self._f = self._f, None
        if f is None:
            return
        try:
            f.flush()
            os.fsync(f.fileno())
        except (OSError, ValueError):
            pass
        with contextlib.suppress(Exception):
            f.close()
        if seal:
            try:
                from ..integrity.sidecar import write_sidecar
                write_sidecar(self.path)
            except Exception:
                pass  # a missing sidecar reads as an unsealed partial


# -- the module-level API (env-driven, ~zero cost when disabled) ----------

_recorder: TraceRecorder | None = None
_recorder_path: str | None = None
_atexit_installed = False
_rotate_lock = threading.Lock()


def _current() -> TraceRecorder | None:
    """The active recorder for the CURRENT value of ``SHEEP_TRACE`` —
    one environ lookup on the disabled fast path, recorder open/rotate
    (lock-guarded) when the value changed (tests and in-process A/B
    arms flip it)."""
    global _recorder, _recorder_path, _atexit_installed
    path = os.environ.get(ENV) or None
    if path == _recorder_path:
        return _recorder
    with _rotate_lock:
        if path == _recorder_path:  # lost the race: already rotated
            return _recorder
        new = None
        if path:
            try:
                new = TraceRecorder(path)
            except OSError as exc:
                # an unwritable SHEEP_TRACE must never break the traced
                # build: warn once, run untraced
                warnings.warn(f"SHEEP_TRACE={path!r} is unwritable "
                              f"({exc}); tracing disabled")
        old, _recorder = _recorder, new
        _recorder_path = path
        if _recorder is not None and not _atexit_installed:
            import atexit
            atexit.register(close_recorder)
            _atexit_installed = True
        cur = _recorder
    if old is not None:
        # close OUTSIDE the rotate lock: sealing the sidecar runs
        # through the atomic writer, whose fault hooks may emit a trace
        # event and re-enter here
        old.close()
    return cur


def enabled() -> bool:
    return _current() is not None


def span(name: str, **attrs):
    """A context manager timing one phase.  Disabled: the shared no-op
    singleton (identity-stable, allocation-free).  Enabled: a span line
    with hierarchical parent/thread ids lands at exit."""
    rec = _current()
    if rec is None:
        return NOOP_SPAN
    return rec.span(name, attrs)


def event(name: str, **attrs) -> None:
    """An instantaneous record (ladder decisions, fault firings)."""
    rec = _current()
    if rec is not None:
        rec.event(name, attrs)


SAMPLE_ENV = "SHEEP_TRACE_SAMPLE"

_sample_spec: str | None = None
_sample_every = 1
_sample_counters: dict[str, int] = {}
_sample_lock = threading.Lock()
#: calls between environ re-reads of the sample rate: the environ
#: lookup is ~2us (bytes round-trip through os.environ) and the skip
#: path runs once per REQUEST, so the rate is cached and re-read every
#: this-many calls (an env flip lands within one window)
_SAMPLE_RECHECK = 512
_sample_countdown = 0


def sample_every() -> int:
    """The parsed ``SHEEP_TRACE_SAMPLE`` rate: ``1/N`` (or a bare
    ``N``) means one span per N calls, 1 means every call (the
    default).  Garbage never breaks the traced server: it warns once
    and samples everything.  Calling this directly re-reads the env NOW
    (tests do); the hot path re-reads every :data:`_SAMPLE_RECHECK`
    calls."""
    global _sample_spec, _sample_every, _sample_countdown
    _sample_countdown = _SAMPLE_RECHECK
    spec = os.environ.get(SAMPLE_ENV, "")
    if spec != _sample_spec:
        _sample_spec = spec
        n = 1
        if spec:
            try:
                num, _, den = spec.partition("/")
                n = int(den) if den else int(num)
                if den and int(num) != 1:
                    raise ValueError
                if n < 1:
                    raise ValueError
            except ValueError:
                warnings.warn(f"{SAMPLE_ENV}={spec!r} is not 1/N or N; "
                              f"sampling every span")
                n = 1
        _sample_every = n
        with _sample_lock:
            _sample_counters.clear()
    return _sample_every


def sampled_span(name: str, **attrs):
    """:func:`span` under the ``SHEEP_TRACE_SAMPLE=1/N`` gate (ISSUE
    11): per-REQUEST spans on a loaded server would blow the <2% trace
    overhead budget at tens of thousands of lines per second, so only
    every Nth call of each span name records — enough that traces
    exist under load, cheap enough to leave on.  Disabled tracing or a
    skipped sample returns the shared no-op singleton; a recorded span
    carries ``sample=N`` so readers can re-scale counts."""
    rec = _current()
    if rec is None:
        return NOOP_SPAN
    global _sample_countdown
    _sample_countdown -= 1
    if _sample_countdown <= 0:
        sample_every()  # re-read the env once per window
    n = _sample_every
    if n > 1:
        # deliberately lock-free: a racy lost increment only nudges the
        # sampling cadence, and the skip path runs once per REQUEST on
        # a loaded server — the lock was most of the <2% budget
        c = _sample_counters.get(name, 0)
        _sample_counters[name] = c + 1
        if c % n:
            return NOOP_SPAN
        attrs["sample"] = n
    return rec.span(name, attrs)


@contextlib.contextmanager
def timed(name: str, out: list | None = None, **attrs):
    """:func:`span` that ALWAYS measures: appends the phase's seconds to
    ``out`` (when given) whether or not tracing is enabled.  THE one
    accumulation path for every perf-dict phase series that predates the
    recorder (window_fetch_s / window_fold_s, ext read/fold, prefetch
    busy time) — the legacy record keys are views of these lists now."""
    t0 = time.perf_counter()
    with span(name, **attrs):
        yield
    if out is not None:
        out.append(time.perf_counter() - t0)


def annotate(**attrs) -> None:
    """Merge attributes into the current thread's innermost open span
    (no-op when tracing is disabled or no span is open)."""
    rec = _current()
    if rec is None:
        return
    stack = getattr(rec._tl, "stack", None)
    if stack:
        stack[-1].annotate(**attrs)


def trace_summary() -> dict | None:
    """The live recorder's in-memory rollup (None when disabled) — what
    the bench records embed without re-reading the file."""
    rec = _current()
    return rec.summary() if rec is not None else None


def close_recorder() -> None:
    """Flush + close + seal the active recorder (atexit does this on
    clean interpreter exit; kill -9 leaves the partial-trace contract)."""
    global _recorder, _recorder_path
    with _rotate_lock:
        old, _recorder = _recorder, None
        _recorder_path = None
    if old is not None:
        old.close()  # outside the lock, same reason as _current


# -- shared overlap accounting (the dedup satellite) ----------------------


def overlap_stats(serialized_s: float, wall_s: float) -> dict:
    """Realized overlap of phases that ran concurrently: ``serialized_s``
    is what the phases cost summed as if serial, ``wall_s`` what the
    clock actually saw.  Returns {"overlap_s", "overlap_frac"} rounded
    the way every bench record publishes them.  THE one code path for
    the windowed handoff, the ext build, and the spill prefetcher —
    three copies of this arithmetic is how r06's accounting bug happened
    (PERF_NOTES r07)."""
    overlap = max(0.0, serialized_s - wall_s)
    return {
        "overlap_s": round(overlap, 4),
        "overlap_frac": round(overlap / serialized_s, 4)
        if serialized_s > 0 else 0.0,
    }


# -- read side (sheep trace / fsck) ---------------------------------------


def read_trace(path: str, mode: str | None = None):
    """Parse a trace file.  Returns ``(records, clean_bytes, torn)``.

    Same tear contract as the WAL: a torn TRAILING line (the partial
    line a kill -9 left — unterminated, or unparseable as JSON with
    nothing valid after it) is refused strict / salvaged with a warning
    in repair or trust; an unparseable line with a VALID line after it
    is mid-file rot and refused in every mode.
    """
    from ..integrity.errors import MalformedArtifact
    from ..integrity.sidecar import resolve_policy
    mode = resolve_policy(mode)
    with open(path, "rb") as f:
        data = f.read()
    records: list[dict] = []
    off = 0
    bad = None  # (offset, reason) of the first unreadable line
    while off < len(data):
        nl = data.find(b"\n", off)
        if nl < 0:
            bad = (off, f"{len(data) - off} unterminated trailing bytes")
            break
        raw = data[off:nl]
        try:
            rec = json.loads(raw)
            if not isinstance(rec, dict) or "k" not in rec:
                raise ValueError("not a trace record")
        except (ValueError, UnicodeDecodeError) as exc:
            bad = (off, f"unparseable line ({exc})")
            break
        records.append(rec)
        off = nl + 1
    if bad is None:
        return records, off, False
    # a bad line is only a TEAR if no valid record line follows it
    tail_off, reason = bad
    scan = data.find(b"\n", tail_off)
    while scan >= 0:
        nxt = data.find(b"\n", scan + 1)
        end = nxt if nxt >= 0 else len(data)
        intact = False
        try:
            rec = json.loads(data[scan + 1:end])
            intact = isinstance(rec, dict) and "k" in rec
        except (ValueError, UnicodeDecodeError):
            pass
        if intact:
            raise MalformedArtifact(
                f"{path}: corrupt trace — line at byte {tail_off} is "
                f"damaged ({reason}) but an intact record follows at "
                f"{scan + 1}: mid-file corruption, not a torn tail")
        scan = nxt
    msg = (f"{path}: torn trace — {reason} at byte {tail_off} "
           f"({len(records)} intact record(s) precede it)")
    if mode == "strict":
        raise MalformedArtifact(
            msg + "; refusing in strict mode (repair mode keeps the "
                  "clean prefix)")
    warnings.warn(msg + "; salvaging the clean prefix")
    return records, tail_off, True


def repair_trace(path: str) -> int:
    """Truncate a torn trailing line off the file (mirrors
    serve/wal.repair_wal).  Returns bytes removed (0 when clean).
    Mid-file rot still raises — amputation never resurrects it."""
    _, clean_end, torn = read_trace(path, "repair")
    if not torn:
        return 0
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(clean_end)
        f.flush()
        os.fsync(f.fileno())
    return size - clean_end


def read_trace_chain(path: str, mode: str | None = None) -> list[dict]:
    """Read a rotated segment chain as ONE record stream: every rotated
    segment strictly (their tails were sealed at rotation — a tear there
    is damage, not a kill), then the active file under ``mode`` (where a
    torn tail is the legal kill -9 shape)."""
    records: list[dict] = []
    chain = trace_segments(path)
    if not chain:
        raise OSError(f"no trace file or segments at {path}")
    for p in chain:
        seg_mode = "strict" if p != path else mode
        recs, _, _ = read_trace(p, seg_mode)
        records.extend(recs)
    return records


def rollup(records: list[dict]) -> dict:
    """Aggregate span records into the per-phase rollup:
    {name: {count, total_s, max_s}} plus "_events" counts by name."""
    phases: dict = {}
    events: dict[str, int] = {}
    for r in records:
        k = r.get("k")
        if k == "span":
            acc = phases.setdefault(
                r.get("name", "?"),
                {"count": 0, "total_s": 0.0, "max_s": 0.0})
            acc["count"] += 1
            dur = float(r.get("dur", 0.0))
            acc["total_s"] = round(acc["total_s"] + dur, 6)
            acc["max_s"] = round(max(acc["max_s"], dur), 6)
        elif k == "ev":
            name = r.get("name", "?")
            events[name] = events.get(name, 0) + 1
    out = dict(sorted(phases.items()))
    if events:
        out["_events"] = dict(sorted(events.items()))
    return out
