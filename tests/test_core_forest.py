"""Core forest construction vs an independent brute-force simulation.

The brute-force model literally replays the reference's insert loop
(lib/jtree.cpp:34-55): stream vertices in sequence order, keep connected
components of the inserted subgraph as Python sets with their max-position
element as root, attach roots, count postorder edges.
"""

import numpy as np
import pytest

from sheep_tpu import INVALID_JNID
from sheep_tpu.core import (
    build_forest,
    build_forest_links,
    compute_facts,
    degree_sequence,
    edges_to_positions,
    merge_forests,
    is_valid_forest,
)
from conftest import random_multigraph


def brute_force_forest(tail, head, seq):
    """Simulate the streaming insert loop directly."""
    pos = {int(v): i for i, v in enumerate(seq)}
    n = len(seq)
    # adjacency over positions (directed-doubled, self-loops kept as records)
    adj = [[] for _ in range(n)]
    for t, h in zip(tail.tolist(), head.tolist()):
        if t == h:
            continue  # self-loops never contribute (jtree.cpp:48)
        a, b = pos[t], pos[h]
        adj[a].append(b)
        adj[b].append(a)

    parent = np.full(n, INVALID_JNID, dtype=np.uint32)
    pst = np.zeros(n, dtype=np.uint32)
    comp_of = {}   # position -> component id
    comps = {}     # component id -> (set of positions, root position)
    next_comp = [0]

    for x in range(n):  # insertion order == position order
        cid = next_comp[0]
        next_comp[0] += 1
        comps[cid] = ({x}, x)
        comp_of[x] = cid
        for nbr in adj[x]:
            if nbr < x:  # preorder: already inserted
                ncid = comp_of[nbr]
                if ncid != comp_of[x]:
                    members, root = comps[ncid]
                    parent[root] = x
                    cur_members, _ = comps[comp_of[x]]
                    merged = members | cur_members
                    mcid = comp_of[x]
                    comps[mcid] = (merged, x)
                    for m in members:
                        comp_of[m] = mcid
            else:  # postorder: not yet inserted
                pst[x] += 1
    return parent, pst


@pytest.mark.parametrize("seed", range(25))
def test_forest_matches_bruteforce(seed):
    rng = np.random.default_rng(seed)
    tail, head = random_multigraph(rng)
    seq = degree_sequence(tail, head)
    f = build_forest(tail, head, seq)
    bp, bpst = brute_force_forest(tail, head, seq)
    np.testing.assert_array_equal(f.parent, bp)
    np.testing.assert_array_equal(f.pst_weight, bpst)
    assert is_valid_forest(f, tail, head, seq)


@pytest.mark.parametrize("seed", range(10))
def test_edge_order_irrelevant(seed):
    """The parent array must not depend on edge-record order."""
    rng = np.random.default_rng(100 + seed)
    tail, head = random_multigraph(rng)
    seq = degree_sequence(tail, head)
    f1 = build_forest(tail, head, seq)
    perm = rng.permutation(len(tail))
    f2 = build_forest(tail[perm], head[perm], seq)
    np.testing.assert_array_equal(f1.parent, f2.parent)
    np.testing.assert_array_equal(f1.pst_weight, f2.pst_weight)


@pytest.mark.parametrize("seed", range(15))
@pytest.mark.parametrize("nparts", [2, 3, 5])
def test_partial_build_and_merge(seed, nparts):
    """Edge-disjoint partial forests merge to the whole-graph forest
    (the associativity the distributed reduce relies on)."""
    rng = np.random.default_rng(200 + seed)
    tail, head = random_multigraph(rng, n_max=60, e_max=300)
    seq = degree_sequence(tail, head)
    whole = build_forest(tail, head, seq)

    bounds = [(k * len(tail)) // nparts for k in range(nparts + 1)]
    partials = [
        build_forest(tail[bounds[k]:bounds[k + 1]], head[bounds[k]:bounds[k + 1]], seq)
        for k in range(nparts)
    ]
    merged = merge_forests(*partials)
    np.testing.assert_array_equal(merged.parent, whole.parent)
    np.testing.assert_array_equal(merged.pst_weight, whole.pst_weight)

    # pairwise tournament (scripts/horizontal-dist.sh REDUCTION=2) agrees too
    layer = partials
    while len(layer) > 1:
        nxt = []
        for i in range(0, len(layer) - 1, 2):
            nxt.append(merge_forests(layer[i], layer[i + 1]))
        if len(layer) % 2:
            nxt.append(layer[-1])
        layer = nxt
    np.testing.assert_array_equal(layer[0].parent, whole.parent)


def test_merge_is_idempotent_on_self():
    rng = np.random.default_rng(7)
    tail, head = random_multigraph(rng)
    seq = degree_sequence(tail, head)
    f = build_forest(tail, head, seq)
    # merging a forest with an empty forest preserves it
    empty = build_forest(tail[:0], head[:0], seq)
    m = merge_forests(f, empty)
    np.testing.assert_array_equal(m.parent, f.parent)
    np.testing.assert_array_equal(m.pst_weight, f.pst_weight)


def test_path_graph_chain():
    # path 0-1-2-3 in vid order, uniform degree ties -> seq by vid
    tail = np.array([0, 1, 2], dtype=np.uint32)
    head = np.array([1, 2, 3], dtype=np.uint32)
    seq = degree_sequence(tail, head)
    f = build_forest(tail, head, seq)
    facts = compute_facts(f)
    assert facts.root_cnt == 1
    assert facts.edge_cnt == 3
    # every non-final node's parent is set
    assert int((f.parent == INVALID_JNID).sum()) == 1


def test_self_loops_and_multi_edges():
    tail = np.array([0, 0, 0, 1], dtype=np.uint32)
    head = np.array([0, 1, 1, 1], dtype=np.uint32)
    seq = degree_sequence(tail, head)
    f = build_forest(tail, head, seq)
    # self-loop (0,0) ignored; multi-edge (0,1)x2 counted twice in pst;
    # self-loop (1,1) ignored.
    assert int(f.pst_weight.sum()) == 2
    lo, hi = edges_to_positions(tail, head, seq)
    assert len(lo) == 2
