"""Anti-entropy tests (ISSUE 20): the rot-injection sweep over every
sealed artifact kind (detected -> quarantined -> repaired -> fsck-clean),
the VERIFY frame grammar + old-daemon forward compatibility, kill -9 at
every quarantine/re-sync phase boundary (the marker survives and reads
stay refused), and the router's exclusion of quarantined members from
the read spread."""

import os
import shutil
import socket
import time

import numpy as np
import pytest

from sheep_tpu.cli.graph2tree import _tree_sig
from sheep_tpu.core.forest import build_forest
from sheep_tpu.core.sequence import degree_sequence
from sheep_tpu.integrity.errors import IntegrityError, MalformedArtifact
from sheep_tpu.integrity.fsck import fsck_file, fsck_paths
from sheep_tpu.io import faultfs
from sheep_tpu.io.edges import write_dat
from sheep_tpu.io.faultfs import parse_io_fault_plan
from sheep_tpu.io.seqfile import write_sequence
from sheep_tpu.io.trefile import write_tree
from sheep_tpu.ops.distext import write_histogram
from sheep_tpu.ops.extmem import range_degree_histogram
from sheep_tpu.plan.model import plan_scrub
from sheep_tpu.serve import faults as serve_faults
from sheep_tpu.serve import netfaults, scrub
from sheep_tpu.serve.cluster import ClusterConfig
from sheep_tpu.serve.daemon import ServeConfig, ServeDaemon
from sheep_tpu.serve.faults import ServeKilled, parse_serve_fault_plan
from sheep_tpu.serve.protocol import ServeClient, ServeError
from sheep_tpu.serve.replicate import (Diverged, ReplApplier, Replicator,
                                       ReplProtocolError,
                                       bootstrap_state_dir, encode_append,
                                       encode_hello, encode_verify,
                                       parse_frame)
from sheep_tpu.serve.router import Router, _Cluster
from sheep_tpu.serve.state import ServeCore
from sheep_tpu.utils.synth import rmat_edges


@pytest.fixture(autouse=True)
def _clean_fault_plans():
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()
    yield
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()


def _wait_until(cond, timeout_s=15.0, poll_s=0.02, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(poll_s)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


def _make_state(tmp_path, name, seed=5, log2=7, parts=3):
    tail, head = rmat_edges(log2, 4 << log2, seed=seed)
    g = str(tmp_path / f"{name}.dat")
    write_dat(g, tail, head)
    sd = str(tmp_path / name)
    core = ServeCore.bootstrap(sd, graph_path=g, num_parts=parts)
    return core, sd, tail, head


def _flip(path, offset=None, xor=0x01):
    b = bytearray(open(path, "rb").read())
    off = (len(b) // 2) if offset is None else (offset % len(b))
    b[off] ^= xor
    open(path, "wb").write(bytes(b))


# ---------------------------------------------------------------------------
# the durable quarantine marker
# ---------------------------------------------------------------------------


def test_quarantine_marker_lifecycle(tmp_path):
    sd = str(tmp_path)
    assert scrub.read_quarantine(sd) is None
    rec = scrub.enter_quarantine(sd, "stream-verify", seqno=7, epoch=1,
                                 expect_crc=10, got_crc=11)
    assert rec["phase"] == scrub.PHASE_DIVERGED and rec["seqno"] == 7
    # idempotent: a second entry never rewinds the phase
    scrub.mark_phase(sd, scrub.PHASE_RESYNC)
    again = scrub.enter_quarantine(sd, "other", seqno=99)
    assert again["phase"] == scrub.PHASE_RESYNC
    rec = scrub.mark_phase(sd, scrub.PHASE_VERIFY, crc=5)
    assert rec["crc"] == 5
    # fields from earlier phases persist through the walk
    assert scrub.read_quarantine(sd)["seqno"] == 7
    with pytest.raises(ValueError):
        scrub.mark_phase(sd, "limbo")
    scrub.clear_quarantine(sd)
    assert scrub.read_quarantine(sd) is None
    scrub.clear_quarantine(sd)  # clearing twice is fine


def test_unreadable_marker_reads_as_quarantined(tmp_path):
    """When the evidence of divergence is itself damaged, the dir must
    still refuse to serve — an unreadable marker IS a marker."""
    sd = str(tmp_path)
    with open(scrub.quarantine_path(sd), "w") as f:
        f.write("{torn")
    rec = scrub.read_quarantine(sd)
    assert rec is not None and rec["phase"] == scrub.PHASE_DIVERGED
    assert rec["reason"] == "unreadable-marker"


# ---------------------------------------------------------------------------
# the hash-chained scrub manifest
# ---------------------------------------------------------------------------


def test_scrub_chain_appends_verifies_and_refuses_tampering(tmp_path):
    import json
    sd = str(tmp_path)
    for i in range(3):
        scrub.append_scrub_record(sd, {"at": float(i), "checked": i})
    assert "runs=3" in scrub.verify_scrub_chain(sd)
    runs = scrub.load_scrub_manifest(sd)
    assert runs[1]["prev"] == runs[0]["hash"] and runs[0]["prev"] == ""
    # edit a landed record: its hash no longer covers the body
    runs[1]["checked"] = 999
    with open(scrub.scrub_manifest_path(sd), "w") as f:
        json.dump(runs, f)
    with pytest.raises(MalformedArtifact):
        scrub.verify_scrub_chain(sd)
    # drop a record: the chain link breaks
    runs[1]["checked"] = 1  # restore the body so only the drop breaks it
    with open(scrub.scrub_manifest_path(sd), "w") as f:
        json.dump([runs[0], runs[2]], f)
    with pytest.raises(MalformedArtifact):
        scrub.verify_scrub_chain(sd)


def test_scrub_chain_trim_keeps_verifiable_anchor(tmp_path):
    sd = str(tmp_path)
    for i in range(scrub.SCRUB_CHAIN_KEEP + 9):
        scrub.append_scrub_record(sd, {"at": float(i)})
    runs = scrub.load_scrub_manifest(sd)
    assert len(runs) == scrub.SCRUB_CHAIN_KEEP
    # the trimmed prefix's hash survives as the oldest record's anchor
    assert runs[0]["prev"] != ""
    assert "chain-ok" in scrub.verify_scrub_chain(sd)


# ---------------------------------------------------------------------------
# VERIFY frame grammar + forward compat
# ---------------------------------------------------------------------------


def test_verify_frame_codec_roundtrip():
    line = encode_verify(3, 512, 0xDEADBEEF)
    fr = parse_frame(line)
    assert fr.kind == "VERIFY" and fr.epoch() == 3
    assert fr.seqno() == 512 and int(fr.kv["crc"]) == 0xDEADBEEF
    for bad in ("REPL VERIFY epoch=1 seqno=2",        # missing crc
                "REPL VERIFY epoch=1 crc=5",          # missing seqno
                "REPL VERIFY epoch=x seqno=2 crc=5"):  # non-integer
        with pytest.raises(ReplProtocolError):
            parse_frame(bad)


def test_hello_advertises_verify_by_capability():
    plain = encode_hello("n1", 0, 0, "sig")
    assert "verify" not in plain and "mig" not in plain
    assert encode_hello("n1", 0, 0, "sig", verify=True).endswith(" verify=1")
    # migration delta streams never advertise verify (Replicator)
    assert "verify" not in encode_hello("n1", 0, 0, "sig", mig=True)


def test_verify_mismatch_quarantines_match_acks(tmp_path):
    leader, _, _, _ = _make_state(tmp_path, "lead")
    seqno = leader.insert(np.array([[2, 9]], np.uint32))
    payload = leader.records_from(seqno - 1)[0][1]
    fol, fsd, _, _ = _make_state(tmp_path, "fol")
    sent = []
    applier = ReplApplier(fol, sent.append)
    applier.feed((encode_append(0, seqno, payload) + "\n").encode("ascii"))
    assert fol.applied_seqno == 1
    # matching crc: compared, acked, no quarantine
    good = leader.state_crc()
    assert good == fol.state_crc()
    applier.feed((encode_verify(0, 1, good) + "\n").encode("ascii"))
    assert applier.verifies == 1 and applier.diverged == 0
    assert sent[-1] == "REPL ACK seqno=1"
    # a VERIFY for a seqno we are not at is skipped, never compared
    applier.feed((encode_verify(0, 5, 12345) + "\n").encode("ascii"))
    assert applier.verifies == 1
    # mismatch: durable quarantine BEFORE the stream tears
    seen = []
    applier.on_diverged = lambda s, w, g: seen.append((s, w, g))
    with pytest.raises(Diverged):
        applier.feed((encode_verify(0, 1, good ^ 1) + "\n")
                     .encode("ascii"))
    assert applier.diverged == 1 and fol.quarantined
    assert seen == [(1, good ^ 1, good)]
    rec = scrub.read_quarantine(fsd)
    assert rec["phase"] == scrub.PHASE_DIVERGED
    assert rec["got_crc"] == good and rec["expect_crc"] == good ^ 1
    leader.close()
    fol.close()


def test_old_follower_never_sees_verify_frames(tmp_path, monkeypatch):
    """Forward compat by capability: a HELLO without ``verify=1`` (an
    old daemon) gets the plain PR-7 stream — zero VERIFY frames — while
    a verify-capable HELLO on the same leader gets stamped."""
    monkeypatch.setenv(scrub.VERIFY_N_ENV, "2")
    core, sd, _, _ = _make_state(tmp_path, "lead")
    d = ServeDaemon(core, ServeConfig()).start()
    try:
        lh, lp = d.address

        def stream_bytes(hello_line, n_inserts):
            s = socket.create_connection((lh, lp), timeout=10.0)
            s.sendall((hello_line + "\n").encode("ascii"))
            time.sleep(0.2)
            with ServeClient(lh, lp) as c:
                for i in range(n_inserts):
                    c.insert([(i, i + 3)])
            got = bytearray()
            s.settimeout(0.5)
            try:
                while True:
                    data = s.recv(1 << 16)
                    if not data:
                        break
                    got.extend(data)
            except socket.timeout:
                pass
            s.close()
            return bytes(got)

        old = stream_bytes(
            encode_hello("old", core.epoch, core.applied_seqno, core.sig),
            4)
        assert b"APPEND" in old and b"VERIFY" not in old
        new = stream_bytes(
            encode_hello("new", core.epoch, core.applied_seqno, core.sig,
                         verify=True), 4)
        assert b"APPEND" in new and b"VERIFY" in new
    finally:
        d.shutdown()


# ---------------------------------------------------------------------------
# plan_scrub pricing
# ---------------------------------------------------------------------------


def test_plan_scrub_pricing(monkeypatch):
    monkeypatch.delenv("SHEEP_SCRUB_PIN", raising=False)
    none = plan_scrub(0, 0)
    assert none["decision"] == "stay"
    small = plan_scrub(4, 1 << 20)
    assert small["decision"] == "go" and small["cost_s"] < 1.0
    huge = plan_scrub(4, 1 << 40, horizon_s=1.0)
    assert huge["decision"] == "stay"
    monkeypatch.setenv("SHEEP_SCRUB_PIN", "go")
    pinned = plan_scrub(4, 1 << 40, horizon_s=1.0)
    assert pinned["decision"] == "go" and pinned["provenance"] == "forced"


# ---------------------------------------------------------------------------
# the rot sweep: every sealed artifact kind, detected -> quarantined ->
# repaired -> fsck-clean
# ---------------------------------------------------------------------------


def _leg_artifacts(d):
    """A worker-leg-shaped artifact family in ``d``: .dat -> .seq ->
    .tre -> .hist, each sidecar-sealed."""
    tail, head = rmat_edges(6, 4 << 6, seed=11)
    dat = os.path.join(d, "leg.dat")
    write_dat(dat, tail, head)
    seq = degree_sequence(tail, head)
    seq_p = os.path.join(d, "leg.seq")
    write_sequence(seq, seq_p)
    forest = build_forest(tail, head, seq)
    tre_p = os.path.join(d, "leg.tre")
    write_tree(tre_p, forest.parent, forest.pst_weight, sig=_tree_sig(seq))
    hist_p = os.path.join(d, "leg.hist")
    deg, max_vid, records = range_degree_histogram(
        dat, start_edge=0, end_edge=len(tail))
    write_histogram(hist_p, deg, records, max_vid, 0, len(tail))
    return {".seq": seq_p, ".tre": tre_p, ".hist": hist_p}


@pytest.mark.faults
@pytest.mark.parametrize("kind", [".seq", ".tre", ".hist"])
def test_rot_sweep_leg_artifacts(tmp_path, kind):
    d = str(tmp_path)
    paths = _leg_artifacts(d)
    victim = paths[kind]
    before = open(victim, "rb").read()
    _flip(victim)
    counts = scrub.run_scrub(d, fire_faults=False)
    assert counts["failed"] == 1 and counts["quarantined"] == 1
    assert counts["repaired"] == 1 and counts["unrepaired"] == 0
    # the repair re-derived byte-identical content under the real name
    assert open(victim, "rb").read() == before
    # the quarantined copy stays as evidence, and fsck is clean: the
    # *.quarantined convention reports without failing
    assert os.path.exists(victim + scrub.QUAR_SUFFIX)
    _, failures = fsck_paths([d], mode="strict")
    assert not failures, failures
    # the run chained its record
    assert "chain-ok" in scrub.verify_scrub_chain(d)


@pytest.mark.faults
def test_rot_sweep_snapshot_reseals_from_live_core(tmp_path):
    core, sd, _, _ = _make_state(tmp_path, "lead")
    snaps = [n for n in os.listdir(sd) if n.endswith(".snap")]
    assert snaps
    _flip(os.path.join(sd, snaps[0]))
    counts = scrub.run_scrub(sd, core=core, fire_faults=False)
    assert counts["quarantined"] == 1 and counts["repaired"] == 1
    _, failures = fsck_paths([sd], mode="strict")
    assert not failures, failures
    core.close()


@pytest.mark.faults
def test_rot_sweep_snapshot_fetches_from_leader(tmp_path):
    """No live core over the rotted dir: the repair pulls the leader's
    crc-verified snapshot over the replication wire."""
    lcore, lsd, _, _ = _make_state(tmp_path, "lead")
    d = ServeDaemon(lcore, ServeConfig()).start()
    try:
        lh, lp = d.address
        fsd = str(tmp_path / "fol")
        bootstrap_state_dir(fsd, lh, lp)
        snaps = [n for n in os.listdir(fsd) if n.endswith(".snap")]
        assert snaps
        _flip(os.path.join(fsd, snaps[0]))
        counts = scrub.run_scrub(fsd, leader=(lh, lp), fire_faults=False)
        assert counts["quarantined"] == 1 and counts["repaired"] == 1
        _, failures = fsck_paths([fsd], mode="strict")
        assert not failures, failures
    finally:
        d.shutdown()


@pytest.mark.faults
def test_rot_sweep_archived_wal_retired_by_coverage(tmp_path):
    """A rotted epoch-archived WAL is repaired by PROOF, not bytes: a
    clean later-epoch snapshot covers its records by construction."""
    core, sd, _, _ = _make_state(tmp_path, "lead")
    core.insert(np.array([[1, 5]], np.uint32))
    core.advance_epoch(1)  # archives the epoch-0 WAL + seals epoch-1 snap
    arch = [n for n in os.listdir(sd)
            if n.startswith("serve-e") and n.endswith(".wal")]
    assert arch
    _flip(os.path.join(sd, arch[0]))
    counts = scrub.run_scrub(sd, core=core, fire_faults=False)
    assert counts["quarantined"] == 1 and counts["repaired"] == 1
    detail = dict((p, d) for p, v, d in counts["events"])
    assert any("retired-by-snapshot" in d for d in detail.values())
    # the archive stays quarantined (evidence); fsck stays clean
    assert arch[0] + scrub.QUAR_SUFFIX in os.listdir(sd)
    _, failures = fsck_paths([sd], mode="strict")
    assert not failures, failures
    core.close()


@pytest.mark.faults
def test_rot_fault_plan_flips_published_bytes(tmp_path, monkeypatch):
    """The ``rot@site:nth`` injector: the write succeeds, the sidecar
    vouches, and THEN one published byte flips — exactly the silent
    at-rest decay the scrubber exists to catch."""
    d = str(tmp_path)
    faultfs.install_plan(parse_io_fault_plan("rot@seq:0"))
    tail, head = rmat_edges(6, 4 << 6, seed=11)
    seq = degree_sequence(tail, head)
    p = os.path.join(d, "leg.seq")
    write_sequence(seq, p)  # publish succeeds; rot fires post-seal
    faultfs.clear_plan()
    with pytest.raises(IntegrityError):
        fsck_file(p, "strict")
    # the scrubber re-derives it from the sibling .dat
    write_dat(os.path.join(d, "leg.dat"), tail, head)
    counts = scrub.run_scrub(d, fire_faults=False)
    assert counts["repaired"] == 1
    assert "sum=verified" in fsck_file(p, "strict")


def test_scrub_unrepairable_stays_quarantined_and_reported(tmp_path):
    """No surviving repair input: the artifact STAYS quarantined (never
    silently dropped) and fsck keeps reporting it without failing."""
    d = str(tmp_path)
    tail, head = rmat_edges(6, 4 << 6, seed=11)
    p = os.path.join(d, "leg.seq")
    write_sequence(degree_sequence(tail, head), p)  # no sibling .dat
    _flip(p)
    counts = scrub.run_scrub(d, fire_faults=False)
    assert counts["quarantined"] == 1 and counts["unrepaired"] == 1
    assert counts["repaired"] == 0
    assert not os.path.exists(p)
    assert os.path.exists(p + scrub.QUAR_SUFFIX)
    results, failures = fsck_paths([d], mode="strict")
    assert not failures
    assert any(p + scrub.QUAR_SUFFIX == rp and ok
               for rp, ok, _ in results)


# ---------------------------------------------------------------------------
# fsck: the quarantine convention + reclaim
# ---------------------------------------------------------------------------


def test_fsck_never_loads_quarantined_and_repair_reclaims(tmp_path):
    """A *.quarantined file whose bytes are actually FINE (transient
    controller flake): plain fsck reports it, never loads it, never
    fails on it; ``--repair`` re-verifies on the quarantined name and
    reclaims it back under the real name."""
    d = str(tmp_path)
    tail, head = rmat_edges(6, 4 << 6, seed=11)
    p = os.path.join(d, "leg.seq")
    write_sequence(degree_sequence(tail, head), p)
    qp = scrub.quarantine_artifact(p)
    assert qp == p + scrub.QUAR_SUFFIX and not os.path.exists(p)
    # sidecar rode along under the quarantined name
    assert os.path.exists(qp + ".sum")
    results, failures = fsck_paths([d], mode="strict")
    assert not failures
    assert any("quarantined" in detail and ok
               for _, ok, detail in results)
    # repair mode reclaims the clean bytes
    results, failures = fsck_paths([d], mode="repair")
    assert not failures
    assert any("reclaimed" in detail for _, ok, detail in results)
    assert os.path.exists(p) and not os.path.exists(qp)
    assert "sum=verified" in fsck_file(p, "strict")


def test_reclaim_refuses_still_corrupt_and_clobber(tmp_path):
    d = str(tmp_path)
    tail, head = rmat_edges(6, 4 << 6, seed=11)
    p = os.path.join(d, "leg.seq")
    write_sequence(degree_sequence(tail, head), p)
    qp = scrub.quarantine_artifact(p)
    _flip(qp)
    with pytest.raises(IntegrityError):
        scrub.reclaim_quarantined(qp)
    assert os.path.exists(qp) and not os.path.exists(p)
    # a repair already landed a fresh copy: reclaim must not clobber it
    write_sequence(degree_sequence(tail, head), p)
    with pytest.raises(IntegrityError):
        scrub.reclaim_quarantined(qp)
    assert os.path.exists(p)


def test_fsck_validates_scrub_chain(tmp_path):
    d = str(tmp_path)
    tail, head = rmat_edges(6, 4 << 6, seed=11)
    write_sequence(degree_sequence(tail, head),
                   os.path.join(d, "leg.seq"))
    scrub.append_scrub_record(d, {"at": 1.0, "checked": 1})
    results, failures = fsck_paths([d], mode="strict")
    assert not failures
    assert any("chain-ok" in detail for _, _, detail in results)
    # tamper: fsck now fails on the manifest
    import json
    runs = scrub.load_scrub_manifest(d)
    runs[0]["checked"] = 42
    with open(scrub.scrub_manifest_path(d), "w") as f:
        json.dump(runs, f)
    _, failures = fsck_paths([d], mode="strict")
    assert any("scrub" in str(f) for f in failures), failures


# ---------------------------------------------------------------------------
# the live cluster: divergence -> quarantine -> heal, kill -9 at every
# phase boundary, read refusal throughout
# ---------------------------------------------------------------------------


def _spawn_pair(tmp_path, verify_n=4, **env):
    os.environ[scrub.VERIFY_N_ENV] = str(verify_n)
    lcore, lsd, tail, head = _make_state(tmp_path, "lead")
    fsd = str(tmp_path / "fol")
    lead = ServeDaemon(
        lcore, ServeConfig(),
        cluster=ClusterConfig(node_id="L", role="leader", peers=[fsd],
                              hb_s=0.05, failover_s=30.0,
                              poll_timeout_s=1.0)).start()
    lh, lp = lead.address
    bootstrap_state_dir(fsd, lh, lp)
    fol = ServeDaemon(
        ServeCore.open(fsd), ServeConfig(),
        cluster=ClusterConfig(node_id="F", role="follower", peers=[lsd],
                              hb_s=0.05, failover_s=30.0,
                              poll_timeout_s=1.0)).start()
    _wait_until(lambda: lead.hub.follower_count() == 1,
                what="follower attached")
    return lead, fol, lsd, fsd


@pytest.mark.faults
def test_live_divergence_detected_quarantined_healed(tmp_path, monkeypatch):
    """The tentpole acceptance in-process: CORRUPT one byte of the
    follower's live state, insert through the next verify point — the
    follower detects the crc mismatch within one cadence, quarantines
    durably, refuses reads typed, re-syncs from the leader's snapshot,
    and rejoins state_crc-equal."""
    monkeypatch.setenv(scrub.ALLOW_CORRUPT_ENV, "1")
    lead, fol, lsd, fsd = _spawn_pair(tmp_path, verify_n=4)
    try:
        lh, lp = lead.address
        fh, fp = fol.address
        with ServeClient(lh, lp) as c:
            for i in range(4):
                c.insert([(i, i + 7)])
        _wait_until(lambda: fol.core.applied_seqno == 4,
                    what="follower caught up")
        with ServeClient(fh, fp) as c:
            bad_crc = c.kv("CORRUPT")["crc"]
        assert bad_crc != lead.core.state_crc()
        # the next verify point rides in with these inserts
        with ServeClient(lh, lp) as c:
            for i in range(8):
                c.insert([(i + 50, i + 90)])
        _wait_until(lambda: fol.replicator.quarantine_heals >= 1,
                    what="divergence detected and healed")
        assert fol.core.state_crc() == lead.core.state_crc()
        assert not fol.core.quarantined
        assert scrub.read_quarantine(fsd) is None
        with ServeClient(fh, fp) as c:
            st = c.kv("STATS")
        assert st["diverged"] == 0 and st["quarantine_heals"] >= 1
        assert fol.counters["diverged_reads"] >= 0
    finally:
        lead.shutdown()
        fol.shutdown()


@pytest.mark.faults
def test_quarantined_daemon_refuses_reads_typed(tmp_path):
    core, sd, _, _ = _make_state(tmp_path, "solo")
    d = ServeDaemon(core, ServeConfig()).start()
    try:
        h, p = d.address
        with ServeClient(h, p) as c:
            assert c.part([0, 1]) is not None
            core.quarantined = True
            with pytest.raises(ServeError) as ei:
                c.part([0, 1])
            assert ei.value.code == "diverged"
            # non-read verbs still answer: STATS carries the health
            st = c.kv("STATS")
            assert st["diverged"] == 1
        assert d.counters["diverged_reads"] == 1
    finally:
        core.quarantined = False
        d.shutdown()


@pytest.mark.faults
@pytest.mark.parametrize("site", ["quar-resync", "quar-verify",
                                  "quar-clear"])
def test_kill_at_every_heal_boundary_resumes(tmp_path, site):
    """kill -9 at each quarantine/re-sync phase boundary: the durable
    marker survives, the restarted replica is still quarantined (reads
    refused), and the re-run heal converges to the leader's crc."""
    lcore, lsd, _, _ = _make_state(tmp_path, "lead")
    lead = ServeDaemon(lcore, ServeConfig()).start()
    try:
        lh, lp = lead.address
        with ServeClient(lh, lp) as c:
            for i in range(4):
                c.insert([(i, i + 7)])
        fsd = str(tmp_path / "fol")
        bootstrap_state_dir(fsd, lh, lp)
        fol = ServeCore.open(fsd)
        scrub.enter_quarantine(fsd, "test-divergence", seqno=4)
        fol.quarantined = True
        rep = Replicator(fol, "F", lambda: (lh, lp))  # never start()ed
        serve_faults.install_plan(parse_serve_fault_plan(
            f"kill@{site}:0", kill_mode="raise"))
        with pytest.raises(ServeKilled):
            rep._heal_quarantine((lh, lp))
        serve_faults.clear_plan()
        fol.close()  # the "process" died; durable state only

        # restart: the marker decides — still quarantined at every site
        # before quar-clear, whose kill fires AFTER the marker unlinked
        revived = ServeCore.open(fsd)
        marker = scrub.read_quarantine(fsd)
        if site == "quar-clear":
            assert marker is None
        else:
            assert marker is not None
            assert marker["phase"] in scrub.PHASES
            revived.quarantined = True  # the daemon's startup sweep
            rep2 = Replicator(revived, "F", lambda: (lh, lp))
            rep2._heal_quarantine((lh, lp))
            assert rep2.quarantine_heals == 1
        assert scrub.read_quarantine(fsd) is None
        assert revived.state_crc() == lcore.state_crc(), site
        _, failures = fsck_paths([fsd], mode="strict")
        assert not failures, (site, failures)
        revived.close()
    finally:
        lead.shutdown()


@pytest.mark.faults
@pytest.mark.parametrize("site", ["scrub-quar", "scrub-repair"])
def test_kill_at_scrub_boundaries_reenters_cleanly(tmp_path, site):
    """kill -9 mid-scrub: the artifact is either still quarantined (the
    rename IS durable containment) or already repaired; the next scrub
    pass finishes the job either way."""
    d = str(tmp_path)
    _leg_artifacts(d)
    _flip(os.path.join(d, "leg.seq"))
    serve_faults.install_plan(parse_serve_fault_plan(
        f"kill@{site}:0", kill_mode="raise"))
    with pytest.raises(ServeKilled):
        scrub.run_scrub(d)
    serve_faults.clear_plan()
    # the real artifact is never half-there: either quarantined away
    # or fully repaired + verified
    p = os.path.join(d, "leg.seq")
    if os.path.exists(p):
        fsck_file(p, "strict")
    else:
        assert os.path.exists(p + scrub.QUAR_SUFFIX)
    counts = scrub.run_scrub(d, fire_faults=False)
    assert counts["unrepaired"] == 0
    assert os.path.exists(p)
    _, failures = fsck_paths([d], mode="strict")
    assert not failures, failures


@pytest.mark.faults
def test_daemon_startup_sweeps_quarantine_marker(tmp_path):
    """A daemon restarted over a marked state dir comes up already
    quarantined — kill -9 between marker and heal never serves
    divergent data."""
    core, sd, _, _ = _make_state(tmp_path, "solo")
    core.close()
    scrub.enter_quarantine(sd, "pre-restart")
    d = ServeDaemon(ServeCore.open(sd), ServeConfig()).start()
    try:
        assert d.core.quarantined
        h, p = d.address
        with ServeClient(h, p) as c:
            with pytest.raises(ServeError) as ei:
                c.part([0])
            assert ei.value.code == "diverged"
    finally:
        d.shutdown()


# ---------------------------------------------------------------------------
# router: quarantined members leave the read spread
# ---------------------------------------------------------------------------


def test_read_targets_push_diverged_to_back(tmp_path):
    c = _Cluster("c0", ["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"],
                 poll_timeout_s=0.1)
    bad = ("127.0.0.1", 2)
    c.mark_diverged(bad)
    for _ in range(6):
        targets = c.read_targets()
        assert targets[-1] == bad and bad not in targets[:-1]
    # the mark expires after its TTL: back in the rotation
    with c._lock:
        c._diverged[bad] = time.monotonic() - 1
    assert any(c.read_targets()[0] == bad for _ in range(6))


@pytest.mark.faults
def test_router_skips_quarantined_member(tmp_path):
    """Reads through the router keep answering while one member is
    quarantined: the first ``ERR diverged`` marks it out of the spread
    and every spread read lands on healthy members."""
    lead, fol, lsd, fsd = _spawn_pair(tmp_path)
    router = Router({"c0": [lsd, fsd]}, retries=4,
                    poll_timeout_s=0.5).start()
    try:
        rh, rp = router.address
        fol.core.quarantined = True
        want = [lead.core.part(v) for v in (0, 1, 2)]
        with ServeClient(rh, rp, timeout_s=30.0) as c:
            for _ in range(12):
                assert c.part([0, 1, 2]) == want
        assert router.counters["diverged_skips"] >= 1
        # after the mark, reads stopped landing on the quarantined
        # member: its refusal count stays far below the request count
        assert fol.counters["diverged_reads"] <= 2
    finally:
        fol.core.quarantined = False
        router.shutdown()
        lead.shutdown()
        fol.shutdown()


# ---------------------------------------------------------------------------
# the wire surface: CRC / SCRUB / CORRUPT verbs
# ---------------------------------------------------------------------------


def test_crc_scrub_corrupt_verbs(tmp_path, monkeypatch):
    core, sd, _, _ = _make_state(tmp_path, "solo")
    d = ServeDaemon(core, ServeConfig()).start()
    try:
        h, p = d.address
        with ServeClient(h, p) as c:
            st = c.kv("CRC")
            assert st["crc"] == core.state_crc()
            assert st["seqno"] == core.applied_seqno
            # CORRUPT is refused until the operator opts in
            monkeypatch.delenv(scrub.ALLOW_CORRUPT_ENV, raising=False)
            with pytest.raises(ServeError) as ei:
                c.kv("CORRUPT")
            assert ei.value.code == "unavailable"
            monkeypatch.setenv(scrub.ALLOW_CORRUPT_ENV, "1")
            # ... and needs inserted edges to flip
            with pytest.raises(ServeError):
                c.kv("CORRUPT")
            c.insert([(1, 5)])
            before = core.state_crc()
            out = c.kv("CORRUPT")
            assert out["crc"] != before
            # a forced inline scrub answers with counts and chains
            counts = c.kv("SCRUB")
            assert counts["checked"] >= 1 and counts["failed"] == 0
        assert "chain-ok" in scrub.verify_scrub_chain(sd)
        with ServeClient(h, p) as c:
            st = c.kv("STATS")
        assert st["scrub_runs"] == 1
    finally:
        d.shutdown()
