"""CLI binaries + shell orchestration — subprocess integration tests.

Uses the bundled hep-th graph (8361 verts / 15751 edges) as the de facto
end-to-end smoke test, like the reference README:10-12.  Golden values:
tree facts width 24 / 7610 verts (data/quality/hep.degree.raw) and the
deterministic 2-part ECV(down) of this implementation's stable FFD.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEP = os.path.join(REPO, "data", "hep-th.dat")
BIN = os.path.join(REPO, "bin")

pytestmark = pytest.mark.skipif(not os.path.exists(HEP),
                                reason="hep-th.dat not bundled")


def cli_env(env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"  # the host env may pin a hardware platform
    flags = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags +
                            " --xla_force_host_platform_device_count=8").strip()
    if env_extra:
        env.update(env_extra)
    return env


def run_cli_proc(args, timeout=600, env_extra=None, check=True):
    proc = subprocess.run([sys.executable, "-m", f"sheep_tpu.cli.{args[0]}"]
                          + args[1:], capture_output=True, text=True,
                          timeout=timeout, env=cli_env(env_extra), cwd=REPO)
    if check:
        assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc


def run_cli(args, timeout=600, env_extra=None):
    return run_cli_proc(args, timeout, env_extra).stdout


def stable_lines(out):
    """stdout minus the nondeterministic phase-timing lines."""
    return [ln for ln in out.splitlines()
            if " in: " not in ln and " took: " not in ln]


def test_degree_sequence_cli(tmp_path):
    seq_path = str(tmp_path / "hep.seq")
    out = run_cli(["degree_sequence", HEP, seq_path])
    assert "Sorted in:" in out
    from sheep_tpu.core.sequence import degree_sequence
    from sheep_tpu.io import load_edges
    from sheep_tpu.io.seqfile import read_sequence
    edges = load_edges(HEP)
    np.testing.assert_array_equal(read_sequence(seq_path),
                                  degree_sequence(edges.tail, edges.head))


def test_graph2tree_facts_validate(tmp_path):
    tre = str(tmp_path / "hep.tre")
    out = run_cli(["graph2tree", HEP, "-o", tre, "-f", "-c"])
    assert "TREEFAQS: width:24" in out
    assert "verts:7610" in out and "edges:15751" in out
    assert "Tree is valid." in out
    assert os.path.getsize(tre) == 4 + 8 * 7610


def test_graph2tree_fast_partition_print():
    out = run_cli(["graph2tree", HEP, "-p", "2"])
    assert "Actually created 2 partitions." in out
    assert "First two partition sizes: 3409 and 4201" in out


def test_partition_tree_evaluate(tmp_path):
    tre = str(tmp_path / "hep.tre")
    seq = str(tmp_path / "hep.seq")
    run_cli(["degree_sequence", HEP, seq])
    run_cli(["graph2tree", HEP, "-s", seq, "-o", tre])
    out = run_cli(["partition_tree", "-f", "-g", HEP, seq, tre, "2"])
    assert "ECV(down): 521" in out
    assert "Actually created 2 partitions." in out


def test_merge_trees_equals_whole(tmp_path):
    seq = str(tmp_path / "hep.seq")
    run_cli(["degree_sequence", HEP, seq])
    for part in (1, 2):
        run_cli(["graph2tree", HEP, "-l", f"{part}/2", "-s", seq,
                 "-o", str(tmp_path / f"p{part}.tre")])
    run_cli(["graph2tree", HEP, "-s", seq, "-o", str(tmp_path / "whole.tre")])
    run_cli(["merge_trees", str(tmp_path / "p1.tre"), str(tmp_path / "p2.tre"),
             "-o", str(tmp_path / "merged.tre")])
    whole = open(tmp_path / "whole.tre", "rb").read()
    merged = open(tmp_path / "merged.tre", "rb").read()
    assert whole == merged


def test_graph2tree_jxn_mode():
    out = run_cli(["graph2tree", HEP, "-k", "-e", "-j", "-f", "-c"])
    assert "TREEFAQS: width:551" in out
    assert "Tree is valid." in out


def test_graph2tree_mesh_ir():
    out = run_cli(["graph2tree", HEP, "-i", "-r", "-p", "2", "-f"],
                  env_extra={"SHEEP_WORKERS": "8"})
    assert "TREEFAQS: width:24" in out
    assert "First two partition sizes: 3409 and 4201" in out
    assert "Reduced in:" in out


def test_path_equivalence_serial_vs_mesh(tmp_path):
    """SURVEY §4.6: the same problem through the serial, -i, -r, and -ir
    paths must produce byte-identical trees (the merge is exact given a
    shared sequence; data/pll-10{,-i,-r,-ir} is the reference experiment)."""
    seq = str(tmp_path / "hep.seq")
    run_cli(["degree_sequence", HEP, seq])
    run_cli(["graph2tree", HEP, "-s", seq, "-o", str(tmp_path / "serial.tre")])
    # -r: file-given sequence, mesh reduce
    run_cli(["graph2tree", HEP, "-r", "-s", seq,
             "-o", str(tmp_path / "r.tre")], env_extra={"SHEEP_WORKERS": "8"})
    # -ir: mesh sort + mesh reduce (sequence computed on device)
    run_cli(["graph2tree", HEP, "-i", "-r",
             "-o", str(tmp_path / "ir.tre")], env_extra={"SHEEP_WORKERS": "8"})
    # -i: mesh sort + per-worker partials; merge them back through the CLI
    run_cli(["graph2tree", HEP, "-i", "-s", str(tmp_path / "i.seq"),
             "-o", str(tmp_path / "i")], env_extra={"SHEEP_WORKERS": "2"})
    run_cli(["merge_trees", str(tmp_path / "i00r0.tre"),
             str(tmp_path / "i01r0.tre"), "-o", str(tmp_path / "i.tre")])
    serial = open(tmp_path / "serial.tre", "rb").read()
    for name in ("r.tre", "ir.tre", "i.tre"):
        assert open(tmp_path / name, "rb").read() == serial, name


@pytest.mark.parametrize("mode", ["horizontal", "vertical"])
def test_dist_partition_script(mode):
    # -a selects the vertical/affinity path (vertical-dist.sh + workers);
    # its workers emit the fixed "Reduced in 0.0 seconds." line
    # (vertical-worker.sh:29), which the horizontal path never prints —
    # asserting it pins that -a actually took the vertical path.
    flags = ["-a"] if mode == "vertical" else []
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "dist-partition.sh")]
        + flags + ["-w", "2", "data/hep-th.dat", "2"],
        capture_output=True, text=True, timeout=600, env=cli_env(), cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ECV(down): 521" in proc.stdout
    assert "Mapped in" in proc.stdout and "Reduced in" in proc.stdout
    if mode == "vertical":
        assert "Reduced in 0.0 seconds." in proc.stdout
    else:
        assert "Reduced in 0.0 seconds." not in proc.stdout


def test_dist_partition_script_mesh_multiprocess(cpu_multiprocess):
    """`dist-partition.sh -i -r` with SHEEP_PROCS=2: the script launches
    two graph2tree processes joined into one jax.distributed mesh (the
    mpiexec analog) and the quality goldens hold."""
    env = cli_env({"SHEEP_PROCS": "2",
                   # one local device per process: the mesh must span the
                   # two processes for the build to work at all
                   "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "dist-partition.sh"),
         "-i", "-r", "-w", "2", "data/hep-th.dat", "2"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ECV(down): 521" in proc.stdout
    # the leader prints the phase grammar exactly once
    assert proc.stdout.count("Mapped in") == 1


def test_partition_tree_pre_weight(tmp_path):
    # -u with -g recomputes the reference's USE_PRE_WEIGHT model from the
    # graph (lib/partition.cpp:38-48) and must actually shift the weights:
    # a -u-only partition differs from silently falling back to pst.
    tre = str(tmp_path / "hep.tre")
    seq = str(tmp_path / "hep.seq")
    run_cli(["degree_sequence", HEP, seq])
    run_cli(["graph2tree", HEP, "-s", seq, "-o", tre])
    out_pre = run_cli(["partition_tree", "-u", "-g", HEP, seq, tre, "2"])
    out_pst = run_cli(["partition_tree", "-g", HEP, seq, tre, "2"])
    assert "Actually created 2 partitions." in out_pre
    # Timing lines are nondeterministic; the partition/metric lines must
    # genuinely differ or -u was silently ignored.
    assert stable_lines(out_pre) != stable_lines(out_pst)


def test_graph2tree_l_with_mesh_warns(tmp_path):
    # -l is superseded by -i/-r (the reference clobbers it with the MPI rank
    # mapping, graph2tree.cpp:134-143); the CLI must say so on stderr.
    proc = run_cli_proc(["graph2tree", HEP, "-l", "1/2", "-i", "-r", "-p", "2"])
    assert "superseded" in proc.stderr
    assert "Actually created 2 partitions." in proc.stdout


def test_partition_tree_streamed_eval_golden(tmp_path):
    # Forcing the O(n)-memory streamed evaluator must reproduce the golden
    # hep-th numbers exactly (same metrics as the dense path).
    tre = str(tmp_path / "hep.tre")
    seq = str(tmp_path / "hep.seq")
    run_cli(["degree_sequence", HEP, seq])
    run_cli(["graph2tree", HEP, "-s", seq, "-o", tre])
    out = run_cli(["partition_tree", "-g", HEP, seq, tre, "2"],
                  env_extra={"SHEEP_EVAL_STREAM": "1"})
    assert "ECV(down): 521" in out
    assert "edges cut: 2811" in out


def test_graph2tree_map_only_empty_graph(tmp_path):
    # Regression: the device map-only branch must handle an empty graph
    # (falls back to the host loop, which writes one empty partial per
    # worker) instead of crashing.
    import numpy as np
    from sheep_tpu.io.edges import write_dat

    empty = str(tmp_path / "empty.dat")
    write_dat(empty, np.empty(0, np.uint32), np.empty(0, np.uint32))
    out = run_cli(["graph2tree", empty, "-i", "-o", str(tmp_path / "e")],
                  env_extra={"SHEEP_WORKERS": "2"})
    assert os.path.exists(tmp_path / "e00r0.tre")
    assert os.path.exists(tmp_path / "e01r0.tre")


def test_graph2tree_map_only_worker0_view_consistent(tmp_path):
    # -i -f -c report worker 0's partial view; with the device map the
    # reported facts/validation must describe the written 00r0.tre partial.
    seq = str(tmp_path / "hep.seq")
    run_cli(["degree_sequence", HEP, seq])
    out = run_cli(["graph2tree", HEP, "-i", "-s", seq, "-c", "-f",
                   "-o", str(tmp_path / "w")], env_extra={"SHEEP_WORKERS": "2"})
    assert "Tree is valid." in out
    from sheep_tpu.core.facts import compute_facts
    from sheep_tpu.core.forest import Forest
    from sheep_tpu.io.trefile import read_tree
    parent, pst = read_tree(str(tmp_path / "w00r0.tre"))
    facts = compute_facts(Forest(parent, pst))
    assert f"verts:{facts.vert_cnt}" in out
    assert f"edges:{facts.edge_cnt}" in out


def test_make_parallel_harness_smoke(tmp_path):
    # The L7 benchmark harness (data/make-parallel.sh) greps the phase-line
    # grammar into .raw/.dat/.avg tables; one worker sweep on hep-th must
    # produce non-empty tables (the stdout grammar is an API, SURVEY §5).
    env = cli_env({"SHEEP_BENCH_GRAPHS": "data/hep-th.dat",
                   "SHEEP_BENCH_WORKERS": "1 2",
                   "RDIR": str(tmp_path)})
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "data", "make-parallel.sh"),
         "-m", "-p", "-t", "1"],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    raw = (tmp_path / "hep-th.raw").read_text()
    assert "Mapped" in raw or "Partitioned" in raw, raw[:500]
    avg = (tmp_path / "hep-th.avg").read_text().strip()
    assert len(avg.splitlines()) == 2  # one row per worker count
