"""Worker process for the 2-process jax.distributed test (test_parallel.py).

Each process joins the coordination service via
sheep_tpu.parallel.init_distributed (the reference's `mpiexec` analog,
SURVEY §5: multi-host over DCN), then runs the distributed degree sort over
a global mesh spanning both processes' devices and writes its result.

Usage: python distributed_worker.py COORD_ADDR NUM_PROCS PROC_ID OUT_DIR
"""

from __future__ import annotations

import os
import sys


def main() -> None:
    coord, num, pid, out_dir = (sys.argv[1], int(sys.argv[2]),
                                int(sys.argv[3]), sys.argv[4])
    mode = sys.argv[5] if len(sys.argv) > 5 else "degree"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # Bound the coordinator join: a dead/misaddressed coordinator must
    # fail this worker with a clear error (parallel/mesh.init_distributed)
    # instead of hanging until the pytest-level subprocess timeout.
    os.environ.setdefault("SHEEP_CONNECT_TIMEOUT", "120")
    if mode in ("build", "stream", "chunked", "chunked_stream"):
        return main_build(coord, num, pid, out_dir, mode)

    import numpy as np

    # A sitecustomize may have force-registered a hardware plugin; pin the
    # cpu platform before jax.distributed touches the backend.
    from sheep_tpu.cli.common import ensure_jax_platform
    ensure_jax_platform()
    import jax

    from sheep_tpu.parallel import init_distributed
    init_distributed(coordinator_address=coord, num_processes=num,
                     process_id=pid)
    assert jax.process_count() == num, jax.process_count()
    # The global device view must span every process (DCN-analog mesh).
    assert len(jax.devices()) == num * jax.local_device_count()

    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    # Distributed degree sort (lib/sequence.h:65-93): every process owns an
    # edge-disjoint shard, histograms are psum'd across the whole mesh, and
    # every process computes the identical sequence.
    from sheep_tpu.utils import rmat_edges
    n = 1 << 8
    tail, head = rmat_edges(8, 4 * n, seed=23)

    mesh = Mesh(np.array(jax.devices()), ("workers",))
    w = mesh.size
    e_pad = ((len(tail) + w - 1) // w) * w
    t = np.full(e_pad, 0, dtype=np.int32)
    h = np.full(e_pad, 0, dtype=np.int32)
    t[: len(tail)] = tail
    h[: len(head)] = head

    # Build the globally-sharded arrays from per-process shards.
    shard = NamedSharding(mesh, P("workers"))
    tg = jax.make_array_from_process_local_data(shard, t[
        pid * (e_pad // num): (pid + 1) * (e_pad // num)], (e_pad,))
    hg = jax.make_array_from_process_local_data(shard, h[
        pid * (e_pad // num): (pid + 1) * (e_pad // num)], (e_pad,))

    from jax import lax

    from sheep_tpu.utils.compat import shard_map

    def body(ts, hs):
        local = jnp.zeros(n, jnp.int32).at[ts].add(1).at[hs].add(1)
        return lax.psum(local, "workers")

    deg = shard_map(body, mesh=mesh, in_specs=(P("workers"), P("workers")),
                    out_specs=P())(tg, hg)
    # out_specs=P() replicates the result: every process can read its own
    # addressable shard.  Padding used vid 0; subtract its extra counts.
    deg_local = np.asarray(deg.addressable_shards[0].data).copy()
    deg_local[0] -= 2 * (e_pad - len(tail))

    want = np.bincount(tail, minlength=n) + np.bincount(head, minlength=n)
    np.testing.assert_array_equal(deg_local, want)

    with open(os.path.join(out_dir, f"ok.{pid}"), "w") as f:
        f.write("ok")


def main_build(coord: str, num: int, pid: int, out_dir: str,
               mode: str) -> None:
    """Cross-process pipelines over a mesh spanning both processes
    (global-array staging via parallel.build._stage), checked against the
    sequential oracle: 'build' = the full `-i -r` path, 'stream' = OOM
    block streaming composed with the mesh."""
    from sheep_tpu.cli.common import ensure_jax_platform
    ensure_jax_platform()
    import jax

    from sheep_tpu.parallel import init_distributed
    init_distributed(coordinator_address=coord, num_processes=num,
                     process_id=pid)
    assert jax.process_count() == num, jax.process_count()

    import numpy as np

    from sheep_tpu.core.forest import build_forest
    from sheep_tpu.core.sequence import degree_sequence
    from sheep_tpu.utils import rmat_edges

    tail, head = rmat_edges(9, 4 << 9, seed=31)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    if mode == "build":
        from sheep_tpu.parallel.build import build_graph_distributed
        seq, forest = build_graph_distributed(tail, head)
        np.testing.assert_array_equal(seq, want_seq)
        np.testing.assert_array_equal(forest.parent, want.parent)
        np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)
    elif mode == "chunked":
        # the bounded-dispatch production shape across a 2-process mesh:
        # host chunk loop + stats fetches must be multi-process safe
        from sheep_tpu.parallel import build_graph_chunked_distributed
        seq, forest = build_graph_chunked_distributed(tail, head)
        np.testing.assert_array_equal(seq, want_seq)
        np.testing.assert_array_equal(forest.parent, want.parent)
        np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)
    elif mode == "chunked_stream":
        from sheep_tpu.core.sequence import sequence_positions
        from sheep_tpu.parallel import build_graph_streaming_chunked
        n = int(max(tail.max(), head.max())) + 1
        n = max(n, len(want_seq))
        pos = sequence_positions(want_seq, n - 1)
        block = len(tail) // 3 + 1
        forest, _ = build_graph_streaming_chunked(
            ((tail[a:a + block], head[a:a + block])
             for a in range(0, len(tail), block)),
            n, pos, block_edges=block)
        m = len(want_seq)
        np.testing.assert_array_equal(forest.parent[:m], want.parent)
        np.testing.assert_array_equal(forest.pst_weight[:m],
                                      want.pst_weight)
    else:
        from sheep_tpu.core.sequence import sequence_positions
        from sheep_tpu.parallel import build_graph_streaming_sharded
        n = int(max(tail.max(), head.max())) + 1
        n = max(n, len(want_seq))
        pos = sequence_positions(want_seq, n - 1)
        block = len(tail) // 3 + 1
        forest, _ = build_graph_streaming_sharded(
            ((tail[a:a + block], head[a:a + block])
             for a in range(0, len(tail), block)),
            n, pos, block_edges=block)
        m = len(want_seq)
        np.testing.assert_array_equal(forest.parent[:m], want.parent)
        np.testing.assert_array_equal(forest.pst_weight[:m],
                                      want.pst_weight)

    with open(os.path.join(out_dir, f"ok.{pid}"), "w") as f:
        f.write("ok")


if __name__ == "__main__":
    main()
