"""Fennel partitioners + util analysis tools."""

import os
import subprocess
import sys

import numpy as np
import pytest

from conftest import random_multigraph

from sheep_tpu import INVALID_PART
from sheep_tpu.partition.evaluate import evaluate_partition
from sheep_tpu.partition.fennel import fennel_edges, fennel_vertex

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEP = os.path.join(REPO, "data", "hep-th.dat")


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("num_parts", [2, 4])
def test_fennel_vertex_valid_partition(seed, num_parts):
    rng = np.random.default_rng(seed)
    tail, head = random_multigraph(rng, n_max=50, e_max=200)
    parts = fennel_vertex(tail, head, num_parts)
    deg = np.bincount(tail, minlength=parts.size) + \
        np.bincount(head, minlength=parts.size)
    active = deg > 0
    # every active vertex assigned, every inactive one INVALID
    assert (parts[active] >= 0).all() and (parts[active] < num_parts).all()
    assert (parts[~active] == INVALID_PART).all()


def test_fennel_vertex_respects_capacity_mostly():
    """With generous balance, no part exceeds the capacity bound."""
    rng = np.random.default_rng(9)
    tail, head = random_multigraph(rng, n_max=60, e_max=300,
                                   self_loops=False)
    num_parts = 3
    parts = fennel_vertex(tail, head, num_parts, balance_factor=1.5)
    deg = np.bincount(tail, minlength=parts.size) + \
        np.bincount(head, minlength=parts.size)
    cap = (2 * len(tail) // num_parts) * 1.5
    for p in range(num_parts):
        assert deg[parts == p].sum() <= cap + deg.max()


def test_fennel_vertex_beats_random_on_edges_cut():
    rng = np.random.default_rng(11)
    tail, head = random_multigraph(rng, n_max=80, e_max=200,
                                   self_loops=False)
    parts_f = fennel_vertex(tail, head, 2)
    n = parts_f.size
    parts_r = rng.integers(0, 2, size=n)
    cut_f = int((parts_f[tail] != parts_f[head]).sum())
    cut_r = int((parts_r[tail] != parts_r[head]).sum())
    assert cut_f <= cut_r


def test_fennel_edges_valid():
    rng = np.random.default_rng(21)
    tail, head = random_multigraph(rng, n_max=40, e_max=150)
    eparts = fennel_edges(tail, head, 3)
    assert len(eparts) == len(tail)
    assert (eparts >= 0).all() and (eparts < 3).all()
    # roughly balanced under the hard cap
    counts = np.bincount(eparts, minlength=3)
    assert counts.max() <= (len(tail) // 3) * 1.03 + 1


def test_evaluate_without_sequence():
    rng = np.random.default_rng(31)
    tail, head = random_multigraph(rng, n_max=30, e_max=100)
    parts = fennel_vertex(tail, head, 2)
    rep = evaluate_partition(parts, tail, head, None, 2)
    assert rep.ecv_down == 0 and rep.ecv_up == 0
    assert rep.edges_cut >= 0 and rep.vcom_vol >= 0


def _run_tool(name, args):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    code = (f"import sys; from sheep_tpu.cli.tools import {name}; "
            f"sys.exit({name}(sys.argv[1:]))")
    proc = subprocess.run([sys.executable, "-c", code] + args,
                          capture_output=True, text=True, timeout=600,
                          env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout


@pytest.mark.skipif(not os.path.exists(HEP), reason="hep-th.dat not bundled")
def test_tools_end_to_end(tmp_path):
    tre = str(tmp_path / "hep.tre")
    seqf = str(tmp_path / "hep.seq")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    subprocess.run([sys.executable, "-m", "sheep_tpu.cli.degree_sequence",
                    HEP, seqf], check=True, env=env, cwd=REPO,
                   capture_output=True)
    subprocess.run([sys.executable, "-m", "sheep_tpu.cli.graph2tree", HEP,
                    "-s", seqf, "-o", tre], check=True, env=env, cwd=REPO,
                   capture_output=True)

    dot = str(tmp_path / "hep.dot")
    _run_tool("tree2dot", [tre, dot])
    lines = open(dot).read().splitlines()
    assert lines[0] == "digraph {" and lines[-1] == "}"
    assert len(lines) == 7610 + 2

    adj = str(tmp_path / "hep.adj")
    _run_tool("tree2adj", [tre, adj])
    first = open(adj).readline().split()
    assert first == ["7610", "7029", "011"]  # 7610 - 581 roots = 7029 edges

    gadj = str(tmp_path / "hepg.adj")
    _run_tool("graph2adj", [HEP, gadj])
    first = open(gadj).readline().split()
    assert first == ["7610", "15751", "010"]

    out = _run_tool("vfennel", [HEP, "2"])
    assert "Actually created 2 partitions." in out
    assert "edges cut:" in out and "ECV(hash):" in out
    assert "ECV(down)" not in out  # sequence-free evaluation

    # jnid partition file -> read_partition re-evaluation
    pfile = str(tmp_path / "hep.part")
    from sheep_tpu.core.forest import Forest
    from sheep_tpu.io.trefile import read_tree
    from sheep_tpu.partition.tree_partition import partition_forest
    parent, pst = read_tree(tre)
    jparts = partition_forest(Forest(parent, pst), 2)
    np.savetxt(pfile, jparts, fmt="%d")
    out = _run_tool("read_partition", [HEP, pfile])
    assert "ECV(down): 521" in out


@pytest.mark.parametrize("seed,num_parts,eb", [(0, 2, True), (1, 2, False),
                                               (2, 5, True), (3, 7, False),
                                               (4, 70, True)])
def test_fennel_vertex_native_matches_python(seed, num_parts, eb):
    rng = np.random.default_rng(800 + seed)
    n, e = 120, 600
    tail = rng.integers(0, n, e).astype(np.uint32)
    head = rng.integers(0, n, e).astype(np.uint32)
    py = fennel_vertex(tail, head, num_parts, edge_balanced=eb,
                       impl="python")
    nat = fennel_vertex(tail, head, num_parts, edge_balanced=eb,
                        impl="native")
    np.testing.assert_array_equal(py, nat)


@pytest.mark.parametrize("seed,num_parts", [(0, 2), (1, 5), (2, 70)])
def test_fennel_edges_native_matches_python(seed, num_parts):
    rng = np.random.default_rng(850 + seed)
    n, e = 120, 600
    tail = rng.integers(0, n, e).astype(np.uint32)
    head = rng.integers(0, n, e).astype(np.uint32)
    py = fennel_edges(tail, head, num_parts, impl="python")
    nat = fennel_edges(tail, head, num_parts, impl="native")
    np.testing.assert_array_equal(py, nat)
