"""Mesh-sharded distributed build == sequential oracle, on a virtual
8-device CPU mesh (conftest forces JAX_PLATFORMS=cpu + 8 host devices).

This is the multi-node simulation strategy of SURVEY §4.4: the reference
validates distribution by running W local workers over partial loads and
checking the merged tree matches the serial one; here W mesh workers over
edge shards must reproduce the oracle exactly, for any W, including W that
does not divide |E| (phantom padding) and W > |components|.
"""

import os
import numpy as np
import pytest

import jax

from conftest import random_multigraph

from sheep_tpu.core import build_forest, degree_sequence
from sheep_tpu.parallel import build_graph_distributed, make_mesh


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("workers", [1, 2, 3, 8])
def test_distributed_equals_oracle(workers):
    rng = np.random.default_rng(100 + workers)
    tail, head = random_multigraph(rng, n_max=60, e_max=300)
    seq, forest = build_graph_distributed(tail, head, num_workers=workers)
    want_seq = degree_sequence(tail, head)
    np.testing.assert_array_equal(seq, want_seq)
    want = build_forest(tail, head, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


@pytest.mark.parametrize("trial", range(10))
def test_distributed_random_full_mesh(trial):
    rng = np.random.default_rng(4000 + trial)
    tail, head = random_multigraph(rng)
    seq, forest = build_graph_distributed(tail, head)
    want_seq = degree_sequence(tail, head)
    np.testing.assert_array_equal(seq, want_seq)
    want = build_forest(tail, head, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_edges_fewer_than_workers():
    tail = np.array([0], dtype=np.uint32)
    head = np.array([1], dtype=np.uint32)
    seq, forest = build_graph_distributed(tail, head, num_workers=8)
    assert list(seq) == [0, 1]
    assert list(forest.parent) == [1, 0xFFFFFFFF]
    assert list(forest.pst_weight) == [1, 0]


@pytest.mark.parametrize("workers", [1, 3, 8])
def test_distributed_given_sequence(workers):
    """`-r` without `-i`: an externally-given sequence, including one that
    omits vertices (their edges count as pst of the present endpoint)."""
    rng = np.random.default_rng(700 + workers)
    tail, head = random_multigraph(rng, n_max=40, e_max=160)
    full = degree_sequence(tail, head)
    seq = full[: max(1, len(full) - 3)]  # drop the 3 highest-degree verts
    got_seq, forest = build_graph_distributed(tail, head, seq=seq,
                                              num_workers=workers)
    np.testing.assert_array_equal(got_seq, seq)
    want = build_forest(tail, head, seq,
                        max_vid=int(max(tail.max(), head.max())),
                        impl="python")
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_map_only_partials_merge_to_whole():
    from sheep_tpu.core.forest import merge_forests
    from sheep_tpu.parallel import map_graph_distributed

    rng = np.random.default_rng(42)
    tail, head = random_multigraph(rng, n_max=50, e_max=250)
    seq, partials = map_graph_distributed(tail, head, num_workers=4)
    assert len(partials) == 4
    merged = merge_forests(*partials)
    want = build_forest(tail, head, seq, impl="python")
    np.testing.assert_array_equal(merged.parent, want.parent)
    np.testing.assert_array_equal(merged.pst_weight, want.pst_weight)


def test_hepth_distributed(hep_edges):
    seq, forest = build_graph_distributed(hep_edges.tail, hep_edges.head)
    want_seq = degree_sequence(hep_edges.tail, hep_edges.head)
    np.testing.assert_array_equal(seq, want_seq)
    want = build_forest(hep_edges.tail, hep_edges.head, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def _two_process_env(repo):
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    # one device per process: the mesh must span processes to work at all
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    return coord, env


@pytest.mark.parametrize("mode", ["degree", "build", "stream",
                                  "chunked", "chunked_stream"])
def test_init_distributed_two_process_cpu(tmp_path, mode, cpu_multiprocess):
    """init_distributed (parallel/mesh.py) joins a real 2-process
    coordination service on CPU — the DCN/multi-host analog of the
    reference's mpiexec across nodes (data/slurm-uk2007).  'degree' runs
    the distributed degree sort; 'build' the full -i -r pipeline via
    build_graph_distributed with global-array staging, oracle-checked."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "distributed_worker.py")
    coord, env = _two_process_env(repo)
    procs = [subprocess.Popen(
        [sys.executable, worker, coord, "2", str(pid), str(tmp_path), mode],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for pid in range(2)]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, out + err
    assert os.path.exists(tmp_path / "ok.0")
    assert os.path.exists(tmp_path / "ok.1")


def test_graph2tree_cli_two_process(tmp_path, cpu_multiprocess):
    """`graph2tree -i -r` under the multi-host launcher contract
    (SHEEP_COORDINATOR/_NUM_PROCESSES/_PROCESS_ID): two processes join one
    mesh, only the leader writes, and the tree is byte-identical to the
    serial CLI's."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    graph = os.path.join(repo, "data", "hep-th.dat")
    coord, env = _two_process_env(repo)
    serial_tre = tmp_path / "serial.tre"
    r = subprocess.run(
        [sys.executable, "-m", "sheep_tpu.cli.graph2tree", graph,
         "-o", str(serial_tre)],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    dist_tre = tmp_path / "dist.tre"
    procs = []
    for pid in range(2):
        penv = dict(env)
        penv.update({"SHEEP_COORDINATOR": coord,
                     "SHEEP_NUM_PROCESSES": "2",
                     "SHEEP_PROCESS_ID": str(pid)})
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "sheep_tpu.cli.graph2tree", graph,
             "-i", "-r", "-o", str(dist_tre)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=penv))
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, out + err
    # leader-only phase grammar: process 1 must not print phase lines
    assert "Mapped in:" in outs[0][0] and "Mapped in:" not in outs[1][0]
    assert dist_tre.read_bytes() == serial_tre.read_bytes()


@pytest.mark.parametrize("with_seq", [False, True])
@pytest.mark.parametrize("do_merge", [False, True])
def test_single_worker_mesh_matches_oracle(with_seq, do_merge):
    # A 1-worker mesh routes through the hosted kernel (the shard_map
    # while_loop faults on real hardware); results must be unchanged.
    from sheep_tpu.parallel import (build_graph_distributed,
                                    map_graph_distributed)

    rng = np.random.default_rng(4242)
    tail, head = random_multigraph(rng, 120, 700)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    seq_arg = want_seq if with_seq else None
    if do_merge:
        seq, forest = build_graph_distributed(tail, head, num_workers=1,
                                              seq=seq_arg)
        forests = [forest]
    else:
        seq, forests = map_graph_distributed(tail, head, num_workers=1,
                                             seq=seq_arg)
        assert len(forests) == 1
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forests[0].parent, want.parent)
    np.testing.assert_array_equal(forests[0].pst_weight, want.pst_weight)
