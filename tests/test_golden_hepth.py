"""Golden-value tests on the reference's bundled hep-th graph.

Expected values come from the reference's published experiment logs
(data/quality/hep.degree.raw): tree facts for the degree sequence —
width 24, roots 581, vheight 754, eheight 2330, verts 7610, edges 15751,
halo 3532, core 0, fill 0.
"""

import numpy as np

from sheep_tpu.core import build_forest, compute_facts, degree_sequence, is_valid_forest


def test_hepth_degree_sequence_tree_facts(hep_edges):
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    assert len(seq) == 7610

    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    facts = compute_facts(forest)
    assert facts.vert_cnt == 7610
    assert facts.edge_cnt == 15751
    assert facts.width == 24
    assert facts.root_cnt == 581
    assert facts.vert_height == 754
    assert facts.edge_height == 2330
    assert facts.halo_id == 3532
    assert facts.core_id == 0
    assert facts.fill == 0


def test_hepth_tree_valid(hep_edges):
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    assert is_valid_forest(forest, hep_edges.tail, hep_edges.head, seq,
                           max_vid=hep_edges.max_vid)
