"""Golden-value tests on the reference's bundled hep-th graph.

Expected values come from the reference's published experiment logs
(data/quality/hep.degree.raw): tree facts for the degree sequence —
width 24, roots 581, vheight 754, eheight 2330, verts 7610, edges 15751,
halo 3532, core 0, fill 0.
"""

import numpy as np

from sheep_tpu.core import build_forest, compute_facts, degree_sequence, is_valid_forest


def test_hepth_degree_sequence_tree_facts(hep_edges):
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    assert len(seq) == 7610

    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    facts = compute_facts(forest)
    assert facts.vert_cnt == 7610
    assert facts.edge_cnt == 15751
    assert facts.width == 24
    assert facts.root_cnt == 581
    assert facts.vert_height == 754
    assert facts.edge_height == 2330
    assert facts.halo_id == 3532
    assert facts.core_id == 0
    assert facts.fill == 0


def test_hepth_published_quality_sweep(hep_edges):
    """ECV(down) for 2..9 parts matches the reference's published sweep
    byte-for-byte (data/quality/hep.degree.cost:1-8) — including the FFD
    bin-packing, whose tie order therefore agrees with the published run."""
    from sheep_tpu.partition.evaluate import evaluate_partition
    from sheep_tpu.partition.partition import Partition

    published = [521, 888, 1177, 1342, 1532, 1661, 1818, 1922]
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    got = []
    for p in range(2, 10):
        part = Partition.from_forest(seq, forest, p,
                                     max_vid=hep_edges.max_vid)
        rep = evaluate_partition(part.parts, hep_edges.tail, hep_edges.head,
                                 seq, p, max_vid=hep_edges.max_vid,
                                 file_edges=hep_edges.num_edges)
        got.append(rep.ecv_down)
    assert got == published


def test_hepth_published_bipartition_metrics(hep_edges):
    """Full 2-part evaluator report matches the published run
    (data/quality/hep.degree.raw:14-22)."""
    from sheep_tpu.partition.evaluate import evaluate_partition
    from sheep_tpu.partition.partition import Partition

    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    part = Partition.from_forest(seq, forest, 2, max_vid=hep_edges.max_vid)
    sizes = [(part.parts == 0).sum(), (part.parts == 1).sum()]
    assert sizes == [3409, 4201]
    rep = evaluate_partition(part.parts, hep_edges.tail, hep_edges.head,
                             seq, 2, max_vid=hep_edges.max_vid,
                             file_edges=hep_edges.num_edges)
    assert rep.edges_cut == 2811
    assert rep.vcom_vol == 2061
    assert rep.ecv_hash == 1311
    assert rep.ecv_down == 521
    assert rep.ecv_up == 1539


def test_hepth_tree_valid(hep_edges):
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    assert is_valid_forest(forest, hep_edges.tail, hep_edges.head, seq,
                           max_vid=hep_edges.max_vid)


def test_hepth_quality_sweep_matches_published_column(hep_edges):
    """data/quality/hep.cost col 2 (the published parts=2..40 ECV(down)
    sweep, produced by the reference's make-quality.sh): every row must
    match exactly except ties left toolchain-defined by the reference's
    unstable FFD kid sort (partition.cpp:104-108) — at most one divergent
    row, within 0.5%."""
    import os
    import sys

    from sheep_tpu.partition import Partition, evaluate_partition

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "scripts"))
    from quality_sweep import _REF_HEP_COST, ref_hep_column

    if not os.path.exists(_REF_HEP_COST):
        import pytest
        pytest.skip("reference quality data not mounted")
    ref = ref_hep_column()
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    divergent = []
    for parts, want in sorted(ref.items()):
        part = Partition.from_forest(seq, forest, parts,
                                     max_vid=hep_edges.max_vid)
        rep = evaluate_partition(part.parts, hep_edges.tail, hep_edges.head,
                                 seq, parts, max_vid=hep_edges.max_vid,
                                 file_edges=hep_edges.num_edges)
        if rep.ecv_down != want:
            divergent.append((parts, rep.ecv_down, want))
            assert abs(rep.ecv_down - want) / want <= 0.005, divergent
    assert len(divergent) <= 1, divergent
