"""Direct tests for the scripts/lib.sh helpers.

The shell orchestration layer was only ever exercised indirectly (whole
dist-partition.sh runs, tests/test_cli.py); these tests drive each helper
through a bash -c subprocess so a regression in sheep_wait_all's failure
propagation or sheep_mv_artifact's sidecar-first ordering fails HERE with
a readable assertion instead of as a flaky end-to-end hang.
"""

import os
import subprocess
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(REPO, "scripts", "lib.sh")

pytestmark = pytest.mark.skipif(not os.path.exists(LIB),
                                reason="scripts/lib.sh not present")


def bash(snippet: str, timeout: float = 60, env_extra: dict | None = None):
    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    return subprocess.run(
        ["bash", "-c", f"source {LIB}\n{snippet}"],
        capture_output=True, text=True, timeout=timeout, env=env)


# ---------------------------------------------------------------------------
# sheep_wait_all: a crashed worker must fail the phase
# ---------------------------------------------------------------------------


def test_wait_all_success():
    proc = bash("(exit 0) & p1=$!; (exit 0) & p2=$!\n"
                "sheep_wait_all $p1 $p2")
    assert proc.returncode == 0, proc.stderr


def test_wait_all_propagates_any_failure():
    proc = bash("(exit 0) & p1=$!; (exit 3) & p2=$!; (exit 0) & p3=$!\n"
                "sheep_wait_all $p1 $p2 $p3")
    assert proc.returncode == 1
    assert "failed" in proc.stderr


def test_wait_all_reports_every_failed_pid():
    proc = bash("(exit 1) & p1=$!; (exit 2) & p2=$!\n"
                "sheep_wait_all $p1 $p2")
    assert proc.returncode == 1
    assert proc.stderr.count("failed") == 2


# ---------------------------------------------------------------------------
# sheep_mv_artifact: artifact + sidecar travel together, sidecar first
# ---------------------------------------------------------------------------


def test_mv_artifact_moves_sidecar_too(tmp_path):
    src, dst = tmp_path / "a.tre", tmp_path / "b.tre"
    src.write_bytes(b"tree")
    (tmp_path / "a.tre.sum").write_text("sheep-sum 1\n")
    proc = bash(f"sheep_mv_artifact {src} {dst}")
    assert proc.returncode == 0, proc.stderr
    assert dst.read_bytes() == b"tree"
    assert (tmp_path / "b.tre.sum").exists()
    assert not src.exists() and not (tmp_path / "a.tre.sum").exists()


def test_mv_artifact_without_sidecar(tmp_path):
    src, dst = tmp_path / "a.seq", tmp_path / "b.seq"
    src.write_bytes(b"seq")
    proc = bash(f"sheep_mv_artifact {src} {dst}")
    assert proc.returncode == 0, proc.stderr
    assert dst.read_bytes() == b"seq"
    assert not (tmp_path / "b.seq.sum").exists()


def test_mv_artifact_sidecar_lands_before_artifact(tmp_path):
    # The ordering IS the contract (a polling consumer that sees the
    # artifact must see its checksum): with the artifact itself missing,
    # a failing sheep_mv_artifact must already have moved the sidecar —
    # artifact-first ordering would fail before touching it.
    (tmp_path / "a.tre.sum").write_text("sheep-sum 1\n")
    proc = bash(f"sheep_mv_artifact {tmp_path}/a.tre {tmp_path}/b.tre")
    assert proc.returncode != 0  # the artifact mv failed...
    assert (tmp_path / "b.tre.sum").exists()  # ...after the sidecar moved


# ---------------------------------------------------------------------------
# sheep_wait_for: blocks until the artifact appears
# ---------------------------------------------------------------------------


def test_wait_for_appearing_file(tmp_path):
    target = tmp_path / "late.tre"
    proc = bash(
        f"( sleep 0.3; touch {target} ) &\n"
        f"sheep_wait_for {target} {tmp_path}\n"
        f"[ -f {target} ]",
        env_extra={"USE_INOTIFY": "1"})  # the sleep-poll path
    assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# sheep_heartbeat_start/stop: the liveness beat (supervisor contract)
# ---------------------------------------------------------------------------


def test_heartbeat_beats_and_stops(tmp_path):
    hb = tmp_path / "w.hb"
    proc = bash(
        f"sheep_heartbeat_start {hb}\n"
        f"sleep 0.3\n"
        f"[ -f {hb} ] || exit 9\n"
        f"sheep_heartbeat_stop\n"
        f"stat -c %Y.%N {hb} > {tmp_path}/t1 2>/dev/null || "
        f"stat -c %Y {hb} > {tmp_path}/t1\n"
        f"sleep 0.4\n"
        f"stat -c %Y.%N {hb} > {tmp_path}/t2 2>/dev/null || "
        f"stat -c %Y {hb} > {tmp_path}/t2\n",
        env_extra={"SHEEP_HEARTBEAT_S": "0.1"})
    assert proc.returncode == 0, proc.stderr
    # after stop, the mtime must not advance
    assert (tmp_path / "t1").read_text() == (tmp_path / "t2").read_text()


def test_heartbeat_mtime_advances_while_alive(tmp_path):
    hb = tmp_path / "w.hb"
    proc = bash(
        f"sheep_heartbeat_start {hb}\n"
        f"sleep 0.15; m1=$(stat -c %y {hb})\n"
        f"sleep 0.3; m2=$(stat -c %y {hb})\n"
        f"sheep_heartbeat_stop\n"
        f'[ "$m1" != "$m2" ]',
        env_extra={"SHEEP_HEARTBEAT_S": "0.1"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_heartbeat_start_without_path_is_noop():
    proc = bash('sheep_heartbeat_start ""\nsheep_heartbeat_stop')
    assert proc.returncode == 0, proc.stderr


def test_heartbeat_loop_dies_with_shell(tmp_path):
    # kill -9 the owning shell; the beat loop must self-terminate (kill -0
    # check) instead of beating on a dead worker's behalf forever.  No
    # pipe capture here: the orphaned loop inherits them and a capturing
    # run() would block on EOF while the dead shell is still a zombie.
    hb = tmp_path / "w.hb"
    script = (f"source {LIB}\n"
              f"sheep_heartbeat_start {hb}\n"
              f"sleep 0.15\n"
              f"kill -9 $$\n")
    proc = subprocess.run(["bash", "-c", script], timeout=60,
                          stdout=subprocess.DEVNULL,
                          stderr=subprocess.DEVNULL,
                          env=dict(os.environ, SHEEP_HEARTBEAT_S="0.1"))
    assert proc.returncode != 0  # SIGKILLed
    time.sleep(0.3)  # give a hypothetical orphan time to notice / beat
    try:
        m1 = os.path.getmtime(hb)
    except OSError:
        return  # never beat at all: also silent, also fine
    time.sleep(0.4)
    assert os.path.getmtime(hb) == m1, \
        "orphaned heartbeat loop kept beating after its worker died"
