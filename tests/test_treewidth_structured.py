"""Treewidth-mode exactness on structured graphs with known widths.

The max tree width in jxn mode is (treewidth of the elimination order) + 1:
paths -> 2, cycles -> 3, cliques K_k -> k, stars -> 2 (leaf-first order).
These are order-dependent quantities; the asserted orders make them exact.
"""

import numpy as np

from sheep_tpu.core.jxn import JxnOptions, build_jxn_tree

_OPTS = JxnOptions(make_kids=True, make_pst=True, make_jxn=True)


def _width(tail, head, seq):
    tree = build_jxn_tree(np.asarray(tail, np.uint32),
                          np.asarray(head, np.uint32),
                          np.asarray(seq, np.uint32), _OPTS)
    return int(tree.widths.max())


def test_path_graph_width():
    n = 30
    tail = np.arange(n - 1)
    head = np.arange(1, n)
    assert _width(tail, head, np.arange(n)) == 2  # treewidth 1


def test_cycle_graph_width():
    n = 24
    tail = np.arange(n)
    head = (np.arange(n) + 1) % n
    assert _width(tail, head, np.arange(n)) == 3  # treewidth 2


def test_clique_width():
    k = 9
    tail, head = np.triu_indices(k, 1)
    assert _width(tail, head, np.arange(k)) == k  # treewidth k-1


def test_star_leaf_first_width():
    n = 20
    tail = np.zeros(n - 1, dtype=np.int64)
    head = np.arange(1, n)
    seq = np.concatenate([np.arange(1, n), [0]])  # leaves first, hub last
    assert _width(tail, head, seq) == 2  # treewidth 1


def test_grid_width_bound():
    """k x k grid, row-major order: width == k + 1 (bandwidth elimination)."""
    k = 6
    idx = np.arange(k * k).reshape(k, k)
    tail = np.concatenate([idx[:, :-1].ravel(), idx[:-1, :].ravel()])
    head = np.concatenate([idx[:, 1:].ravel(), idx[1:, :].ravel()])
    assert _width(tail, head, np.arange(k * k)) == k + 1
