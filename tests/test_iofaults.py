"""I/O fault injection through the DISTRIBUTED file path (ISSUE 5).

The acceptance property: an ENOSPC / EIO / short-write fired at ANY write
site of a supervised tournament (``SHEEP_IO_FAULT_PLAN`` grammar, the I/O
sibling of PR-3's ``SHEEP_FAULT_PLAN``) must leave the system in one of
exactly two states:

  * the run COMPLETED anyway (the supervisor's retry absorbed the faulted
    worker write) with a final tree bit-identical to the fault-free run —
    equal ECV(down) included; or
  * the run ABORTED with a typed error (a fault in the supervisor's own
    manifest write), every artifact published before the abort fscks
    clean, and a rerun of the same state dir resumes off the PR-3
    manifest to the bit-identical tree.

In BOTH worlds: no published artifact ever fails fsck, and no write
debris (atomic temps, attempt files) survives into the resumed world's
budget.
"""

import os

import numpy as np
import pytest

from sheep_tpu.core.forest import build_forest
from sheep_tpu.core.sequence import degree_sequence
from sheep_tpu.integrity.fsck import fsck_paths
from sheep_tpu.io import faultfs
from sheep_tpu.io.edges import write_net
from sheep_tpu.io.trefile import read_tree
from sheep_tpu.resources import ResourceError
from sheep_tpu.supervisor import (InlineRunner, SupervisionFailed,
                                  SupervisorConfig, run_supervised)
from sheep_tpu.utils.synth import rmat_edges

pytestmark = pytest.mark.chaos

WORKERS = 2


@pytest.fixture(autouse=True)
def _clean_io_faults():
    faultfs.clear_plan()
    yield
    faultfs.clear_plan()


@pytest.fixture(scope="module")
def small_graph(tmp_path_factory):
    d = tmp_path_factory.mktemp("iofaults")
    tail, head = rmat_edges(6, 4 << 6, seed=5)
    graph = str(d / "g.net")
    write_net(graph, tail, head)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    return graph, tail, head, seq, want


def _ecv_down(tail, head, seq, parent, pst, parts=2):
    from sheep_tpu.core.forest import Forest
    from sheep_tpu.partition.evaluate import evaluate_partition
    from sheep_tpu.partition.partition import Partition

    p = Partition.from_forest(seq, Forest(parent, pst), parts)
    rep = evaluate_partition(p.parts, tail, head, seq, p.num_parts)
    return rep.ecv_down


def _config(**overrides) -> SupervisorConfig:
    kw = dict(workers=WORKERS, deadline_s=10.0, poll_s=0.01,
              backoff_base_s=0.0, heartbeat_s=0.05, grammar=False)
    kw.update(overrides)
    return SupervisorConfig(**kw)


def _run(graph, state_dir):
    manifest = run_supervised(graph, str(state_dir), _config(),
                              runner=InlineRunner(0.05))
    return manifest


def _assert_published_clean(state_dir):
    """Every artifact under the state dir that carries a final name must
    fsck clean — the publish gate may never have let a faulted write
    through.  (Attempt temps are not artifacts; fsck skips them by
    suffix.)"""
    targets = [os.path.join(state_dir, n) for n in os.listdir(state_dir)
               if n.endswith((".tre", ".seq")) ]
    if not targets:
        return
    results, failures = fsck_paths(targets)
    assert not failures, failures


@pytest.fixture(scope="module")
def baseline(small_graph, tmp_path_factory):
    graph, tail, head, seq, want = small_graph
    d = tmp_path_factory.mktemp("base")
    manifest = _run(graph, d / "state")
    with open(manifest.final_tree, "rb") as f:
        tree_bytes = f.read()
    parent, pst = read_tree(manifest.final_tree)
    ecv = _ecv_down(tail, head, seq, parent, pst)
    return tree_bytes, ecv


#: the write-site sweep: every site class a tournament writes, at several
#: indices, under each failure kind.  A worker-side fault (seq/tre/
#: sidecar) is absorbed by retry; a supervisor-side fault (manifest)
#: aborts the run typed and must resume off the manifest.
SWEEP = [
    ("enospc", "seq", 0), ("short", "seq", 0), ("eio", "seq", 0),
    ("enospc", "tre", 0), ("enospc", "tre", 1), ("enospc", "tre", 2),
    ("eio", "tre", 1), ("short", "tre", 0), ("short", "tre", 2),
    ("enospc", "sidecar", 0), ("eio", "sidecar", 1),
    ("short", "sidecar", 2),
    ("enospc", "manifest", 0), ("enospc", "manifest", 2),
    ("eio", "manifest", 1), ("short", "manifest", 3),
]


@pytest.mark.parametrize("kind,site,nth", SWEEP,
                         ids=[f"{k}@{s}:{n}" for k, s, n in SWEEP])
def test_fault_at_every_write_site(small_graph, baseline, tmp_path,
                                   kind, site, nth):
    graph, tail, head, seq, want = small_graph
    want_bytes, want_ecv = baseline
    state = tmp_path / "state"

    faultfs.install_plan(
        faultfs.parse_io_fault_plan(f"{kind}@{site}:{nth}"))
    completed = False
    try:
        manifest = _run(graph, state)
        completed = manifest.done()
    except (SupervisionFailed, ResourceError, OSError):
        pass
    faultfs.clear_plan()

    # invariant 1: nothing published ever fscks dirty, completed or not
    if os.path.isdir(state):
        _assert_published_clean(str(state))

    # invariant 2: the run either completed exactly, or resumes exactly
    if not completed:
        manifest = _run(graph, state)
        assert manifest.done()
    with open(manifest.final_tree, "rb") as f:
        got = f.read()
    assert got == want_bytes, f"{kind}@{site}:{nth} diverged"
    parent, pst = read_tree(manifest.final_tree)
    assert _ecv_down(tail, head, seq, parent, pst) == want_ecv

    # invariant 3: no write debris survives into the final world
    names = os.listdir(state)
    assert not any(n.endswith(".tmp") for n in names), names


def test_worker_fault_is_single_redispatch(small_graph, baseline,
                                           tmp_path):
    """A worker-side ENOSPC costs exactly one extra dispatch of one leg —
    the supervisor never re-runs healthy legs over an I/O fault."""
    graph, tail, head, seq, want = small_graph
    want_bytes, _ = baseline
    faultfs.install_plan(faultfs.parse_io_fault_plan("enospc@tre:0"))
    manifest = _run(graph, tmp_path / "state")
    faultfs.clear_plan()
    assert manifest.done()
    counts = {leg.key: leg.dispatches for leg in manifest.legs}
    assert sum(counts.values()) == len(manifest.legs) + 1, counts
    with open(manifest.final_tree, "rb") as f:
        assert f.read() == want_bytes


def test_slow_everywhere_still_exact(small_graph, baseline, tmp_path):
    """The slow kind (stalled writes) must never fail a run — it exists
    to exercise heartbeat/deadline margins, not recovery."""
    graph, tail, head, seq, want = small_graph
    want_bytes, _ = baseline
    faultfs.install_plan(faultfs.parse_io_fault_plan(
        "slow@seq:0,slow@tre:0,slow@tre:1,slow@manifest:0"))
    manifest = _run(graph, tmp_path / "state")
    faultfs.clear_plan()
    assert manifest.done()
    with open(manifest.final_tree, "rb") as f:
        assert f.read() == want_bytes
