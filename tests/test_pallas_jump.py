"""Fused Pallas jump kernel == the jnp descent, in interpreter mode.

The kernel's compiled-TPU viability is probed on hardware by
scripts/pallas_probe.py; these tests pin its SEMANTICS on CPU via
interpret mode so a future window only has to measure, not debug.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import random_multigraph

from sheep_tpu.ops.pallas_jump import fused_jump, levels_per_call
from sheep_tpu.ops.forest import _jump


@pytest.mark.parametrize("trial", range(6))
def test_fused_jump_equals_jnp(trial):
    rng = np.random.default_rng(600 + trial)
    n = int(rng.integers(50, 4000))
    e = int(rng.integers(10, 20000))
    lo_np = rng.integers(0, n, e)
    hi_np = np.minimum(lo_np + rng.integers(1, n, e), n)
    # sprinkle sentinels (dead links park at n, n)
    dead = rng.random(e) < 0.2
    lo_np[dead] = n
    hi_np[dead] = n
    lo = jnp.asarray(lo_np, jnp.int32)
    hi = jnp.asarray(hi_np, jnp.int32)
    levels = int(rng.integers(1, 11))
    want_lo, want_moved = _jump(lo, hi, n, levels)
    got_lo, got_moved = fused_jump(lo, hi, n, levels, interpret=True)
    np.testing.assert_array_equal(np.asarray(got_lo), np.asarray(want_lo))
    assert int(got_moved) == int(want_moved)


def test_fused_jump_inside_fixpoint(monkeypatch):
    """SHEEP_PALLAS=interpret routes the whole fixpoint through the kernel
    and must still reproduce the oracle forest exactly."""
    from sheep_tpu.core import build_forest, degree_sequence
    from sheep_tpu.ops import build_graph_device

    monkeypatch.setenv("SHEEP_PALLAS", "interpret")
    rng = np.random.default_rng(42)
    tail, head = random_multigraph(rng, n_max=60, e_max=250)
    seq, forest = build_graph_device(tail, head)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_levels_per_call_regimes():
    assert levels_per_call(1 << 16) >= 10   # all tables resident
    assert levels_per_call(1 << 20) >= 1    # at least singles
    assert levels_per_call(1 << 24) == 0    # out of VMEM: jnp path
