"""Router + quorum-vote tests (ISSUE 11): consistent-hash placement,
read spreading, epoch-safe failover retries with zero acked-insert
loss, the un-acked-INSERT ambiguity contract, and the vote rule that
closes the PR-7 symmetric-partition hole (no dual-leader epoch)."""

import os
import time

import numpy as np
import pytest

from sheep_tpu.io import faultfs
from sheep_tpu.io.edges import write_dat
from sheep_tpu.serve import faults as serve_faults
from sheep_tpu.serve import netfaults
from sheep_tpu.serve.cluster import ClusterConfig, request_vote
from sheep_tpu.serve.daemon import ServeConfig, ServeDaemon
from sheep_tpu.serve.protocol import ServeClient, ServeError
from sheep_tpu.serve.replicate import bootstrap_state_dir
from sheep_tpu.serve.router import HashRing, Router, parse_clusters
from sheep_tpu.serve.state import ServeCore
from sheep_tpu.serve.tenants import TenantManager, TenantSpec
from sheep_tpu.utils.synth import rmat_edges


@pytest.fixture(autouse=True)
def _clean_fault_plans():
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()
    yield
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()


def _wait_until(cond, timeout_s=20.0, poll_s=0.02, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(poll_s)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


def _make_state(tmp_path, name, seed=5, log2=7, parts=3):
    tail, head = rmat_edges(log2, 4 << log2, seed=seed)
    g = str(tmp_path / f"{name}.dat")
    write_dat(g, tail, head)
    sd = str(tmp_path / name)
    core = ServeCore.bootstrap(sd, graph_path=g, num_parts=parts)
    return core, sd, tail, head


def _abrupt_kill(daemon):
    """In-process kill -9: sockets die, nothing flushes or demotes."""
    daemon._stop.set()
    daemon._wake()
    if daemon.watcher is not None:
        daemon.watcher.stop()
    for t in daemon._tenant_entries():
        if t.hub is not None:
            t.hub.stop()
    try:
        daemon._listener.close()
    except OSError:
        pass
    for conn in list(daemon._conns.values()):
        try:
            conn.sock.close()
        except OSError:
            pass
    if daemon._hb is not None:
        daemon._hb.stop()
    try:
        os.unlink(os.path.join(daemon.core.state_dir, "serve.addr"))
    except OSError:
        pass


# ---------------------------------------------------------------------------
# the ring + cluster grammar
# ---------------------------------------------------------------------------


def test_hash_ring_deterministic_and_stable():
    r1 = HashRing(["a", "b", "c"])
    r2 = HashRing(["c", "b", "a"])
    for t in (f"tenant{i}" for i in range(64)):
        assert r1.lookup(t) == r2.lookup(t)  # order-independent
    # removing a cluster only moves ITS tenants
    r3 = HashRing(["a", "b"])
    for i in range(128):
        t = f"tenant{i}"
        if r1.lookup(t) != "c":
            assert r3.lookup(t) == r1.lookup(t)


def test_hash_ring_balance():
    ring = HashRing(["a", "b", "c", "d"])
    counts = {"a": 0, "b": 0, "c": 0, "d": 0}
    n = 2000
    for i in range(n):
        counts[ring.lookup(f"graph-{i}")] += 1
    for c in counts.values():  # rough balance: within 2.2x of fair
        assert n / 4 / 2.2 < c < n / 4 * 2.2, counts


def test_parse_clusters_grammar():
    out = parse_clusters("d1/,d2/;x@h:1,h:2")
    assert out == {"c0": ["d1/", "d2/"], "x": ["h:1", "h:2"]}
    for bad in ("", ";;", "x@", "a@p;a@q"):
        with pytest.raises(ValueError):
            parse_clusters(bad)
    with pytest.raises(ValueError):
        HashRing([])


# ---------------------------------------------------------------------------
# routing: placement, read spread, failover retries
# ---------------------------------------------------------------------------


def test_router_places_and_isolates_tenants(tmp_path):
    """Two single-node clusters, four tenants: every tenant's insert
    lands on its ring-assigned cluster and nowhere else; reads through
    the router answer exactly what the backing core answers."""
    ring = HashRing(["c0", "c1"])
    tenants = ["t0", "t1", "t2", "t3"]
    daemons, mgrs = {}, {}
    for cid in ("c0", "c1"):
        core, sd, *_ = _make_state(tmp_path, f"{cid}-dflt", seed=5)
        specs = [TenantSpec(t, str(tmp_path / f"{cid}-{t}"),
                            str(tmp_path / f"{cid}-dflt.dat"), 3)
                 for t in tenants if ring.lookup(t) == cid]
        mgrs[cid] = TenantManager(core, specs)
        daemons[cid] = ServeDaemon(core, ServeConfig(),
                                   tenants=mgrs[cid]).start()
    router = Router({cid: [d.core.state_dir]
                     for cid, d in daemons.items()}).start()
    try:
        rh, rp = router.address
        with ServeClient(rh, rp) as c:
            for t in tenants:
                assert c.tenant(t) == t
                c.insert([(1, 4), (2, 9)])
                cid = ring.lookup(t)
                assert mgrs[cid].get(t).core.applied_seqno == 1
                other = "c1" if cid == "c0" else "c0"
                with pytest.raises(Exception):
                    mgrs[other].get(t)  # not even hosted there
                want = [mgrs[cid].get(t).core.part(v) for v in range(30)]
                assert c.part(list(range(30))) == want
            rs = c.kv("ROUTER")
            assert rs["writes"] == len(tenants)
            assert rs["clusters"] == 2
    finally:
        router.shutdown()
        for d in daemons.values():
            d.shutdown()


def _replicated_cluster(tmp_path, failover_s=0.6):
    lcore, lsd, tail, head = _make_state(tmp_path, "lead")
    fsd = str(tmp_path / "fol")
    lead = ServeDaemon(
        lcore, ServeConfig(),
        cluster=ClusterConfig(node_id="L", role="leader", peers=[fsd],
                              hb_s=0.05, failover_s=failover_s,
                              poll_timeout_s=1.0)).start()
    lh, lp = lead.address
    bootstrap_state_dir(fsd, lh, lp)
    fol = ServeDaemon(
        ServeCore.open(fsd), ServeConfig(),
        cluster=ClusterConfig(node_id="F", role="follower", peers=[lsd],
                              hb_s=0.05, failover_s=failover_s,
                              poll_timeout_s=1.0)).start()
    _wait_until(lambda: lead.hub.follower_count() == 1,
                what="follower attached")
    return lead, fol, lsd, fsd


def test_router_failover_zero_acked_loss(tmp_path):
    """The kill-a-node acceptance, through the router: inserts stream
    through the router, the backing leader dies abruptly, the router
    rides the epoch-fenced promotion — every insert the client saw OK
    for is on the promoted leader, and ambiguous in-flight inserts
    surfaced typed, never silently re-sent across the epoch."""
    lead, fol, lsd, fsd = _replicated_cluster(tmp_path)
    router = Router({"c0": [lsd, fsd]}, retries=8,
                    poll_timeout_s=0.5).start()
    acked = 0
    ambiguous = 0
    refusals = 0
    ex = None
    try:
        rh, rp = router.address
        with ServeClient(rh, rp, timeout_s=60.0) as c:
            for i in range(10):
                c.insert([(i, i + 9)])
                acked += 1
            _abrupt_kill(lead)
            _wait_until(lambda: fol.role == "leader", what="promotion")
            # the ex-leader rejoins as a fenced follower so the write
            # quorum is restorable (the PR-7 contract)
            ex = ServeDaemon(
                ServeCore.open(lsd), ServeConfig(),
                cluster=ClusterConfig(node_id="L", role="leader",
                                      peers=[fsd], hb_s=0.05,
                                      failover_s=0.6,
                                      poll_timeout_s=1.0)).start()
            _wait_until(lambda: fol.hub.follower_count() == 1,
                        what="ex-leader rejoined")
            for i in range(10, 22):
                try:
                    c.insert([(i, i + 9)])
                    acked += 1
                except ServeError as exc:
                    # typed = not applied (or ambiguous, counted apart)
                    if "outcome unknown" in exc.detail:
                        ambiguous += 1
                    else:
                        refusals += 1
                        assert exc.code in ("unavailable", "notleader")
            # reads still answer through the router
            assert c.part([0, 1, 2]) == [fol.core.part(v)
                                         for v in (0, 1, 2)]
            st = c.kv("STATS")
        assert st["role"] == "leader" and st["epoch"] == 1
        # ZERO acked loss: everything the client saw OK for is applied
        # (ambiguous inserts may also be durable — never fewer)
        assert fol.core.applied_seqno >= acked
        assert fol.core.applied_seqno <= acked + ambiguous + refusals
        assert acked >= 15, (acked, ambiguous, refusals)
    finally:
        router.shutdown()
        if ex is not None:
            ex.shutdown()
        fol.shutdown()


def test_router_insert_ambiguity_is_typed(tmp_path):
    """An INSERT whose connection dies before the response is NEVER
    retried by the router: the client gets the typed outcome-unknown
    refusal and owns the decision."""
    core, sd, *_ = _make_state(tmp_path, "solo")
    d = ServeDaemon(core, ServeConfig()).start()
    router = Router({"c0": [sd]}, retries=2).start()
    try:
        rh, rp = router.address
        with ServeClient(rh, rp, timeout_s=30.0) as c:
            c.insert([(1, 5)])  # healthy path, warms the upstream
            applied_before = core.applied_seqno
            _abrupt_kill(d)
            with pytest.raises(ServeError) as ei:
                c.insert([(2, 6)])
            assert ei.value.code == "unavailable"
            assert "outcome unknown" in ei.value.detail
            assert router.counters["insert_unknown"] == 1
        assert core.applied_seqno == applied_before  # nothing re-sent
    finally:
        router.shutdown()


def test_router_spreads_reads_across_members(tmp_path):
    """Read verbs rotate over cluster members: both the leader and the
    follower see PART traffic."""
    lead, fol, lsd, fsd = _replicated_cluster(tmp_path, failover_s=30.0)
    router = Router({"c0": [lsd, fsd]}).start()
    try:
        rh, rp = router.address
        with ServeClient(rh, rp) as c:
            for _ in range(12):
                c.part([0, 1, 2])
        lead_parts = lead.metrics.counter(
            "sheep_serve_requests_total").labels(verb="PART").value
        fol_parts = fol.metrics.counter(
            "sheep_serve_requests_total").labels(verb="PART").value
        assert lead_parts > 0 and fol_parts > 0, (lead_parts, fol_parts)
        assert lead_parts + fol_parts == 12
    finally:
        router.shutdown()
        lead.shutdown()
        fol.shutdown()


# ---------------------------------------------------------------------------
# quorum-vote election (the symmetric-partition fix)
# ---------------------------------------------------------------------------


def test_vote_rule_one_grant_per_epoch(tmp_path):
    """The invariant that forbids same-epoch dual leaders: a voter
    grants at most one candidate per epoch."""
    core, sd, *_ = _make_state(tmp_path, "voter")
    d = ServeDaemon(core, ServeConfig(),
                    cluster=ClusterConfig(node_id="V", role="follower"))
    applied = core.applied_seqno
    assert d.grant_vote(1, "A", applied + 5)
    assert not d.grant_vote(1, "B", applied + 5)   # same epoch: taken
    assert d.grant_vote(1, "A", applied + 5)       # idempotent re-ask
    assert d.grant_vote(2, "B", applied + 5)       # later epoch: fresh
    assert not d.grant_vote(1, "C", applied + 5)   # stale epoch
    assert not d.grant_vote(3, "C", applied - 1) if applied else True
    core.close()


def test_vote_refused_by_leader_and_by_fresh_stream(tmp_path):
    """A live leader refuses to vote itself out, and a follower whose
    stream is FRESH refuses too — which is exactly what stops a
    symmetric-partitioned candidate from promoting while the leader
    still serves the voter."""
    lead, fol, lsd, fsd = _replicated_cluster(tmp_path, failover_s=30.0)
    try:
        # wait for the stream to carry its first frame: freshness is
        # what the refusal keys on
        _wait_until(lambda: fol.replicator is not None
                    and fol.replicator.stream_age_s() is not None,
                    what="first stream frame")
        seq = lead.core.applied_seqno + 10
        # over the wire, like a real candidate would ask
        assert not request_vote(lsd, lead.core.epoch + 1, "X", seq)
        assert not request_vote(fsd, fol.core.epoch + 1, "X", seq)
        assert lead.votes_refused >= 1 and fol.votes_refused >= 1
    finally:
        lead.shutdown()
        fol.shutdown()


def test_failover_election_collects_votes_no_dual_leader(tmp_path):
    """1 leader + 2 followers; kill the leader.  The winning candidate
    must collect the other follower's vote before promoting — the
    cluster converges to EXACTLY one leader, and no epoch ever saw two
    (each voter granted its epoch once)."""
    lcore, lsd, tail, head = _make_state(tmp_path, "lead")
    dirs = {"F0": str(tmp_path / "f0"), "F1": str(tmp_path / "f1")}
    lead = ServeDaemon(
        lcore, ServeConfig(),
        cluster=ClusterConfig(node_id="L", role="leader",
                              peers=list(dirs.values()), hb_s=0.05,
                              failover_s=0.6, poll_timeout_s=1.0)).start()
    lh, lp = lead.address
    fols = {}
    for nid, fsd in dirs.items():
        bootstrap_state_dir(fsd, lh, lp)
        peers = [lsd] + [d for d in dirs.values() if d != fsd]
        fols[nid] = ServeDaemon(
            ServeCore.open(fsd), ServeConfig(),
            cluster=ClusterConfig(node_id=nid, role="follower",
                                  peers=peers, hb_s=0.05,
                                  failover_s=0.6,
                                  poll_timeout_s=1.0)).start()
    try:
        _wait_until(lambda: lead.hub.follower_count() == 2,
                    what="both followers attached")
        with ServeClient(lh, lp) as c:
            for i in range(4):
                c.insert([(i, i + 7)])
        _abrupt_kill(lead)
        _wait_until(lambda: any(f.role == "leader"
                                for f in fols.values()),
                    what="promotion")
        time.sleep(0.5)  # let any second candidate try (and fail)
        leaders = [f for f in fols.values() if f.role == "leader"]
        assert len(leaders) == 1, "dual leader"
        winner = leaders[0]
        loser = next(f for f in fols.values() if f is not winner)
        assert winner.core.epoch == 1
        # no dual-leader EPOCH: the loser never promoted into epoch 1,
        # and the voter granted epoch 1 exactly once
        assert loser.core.epoch <= 1 and loser.role == "follower"
        grants = [e for e in loser.config.events
                  if e[0] == "vote_granted"]
        assert len(grants) <= 1
        assert winner.core.applied_seqno == 4  # zero acked loss
    finally:
        for f in fols.values():
            f.shutdown()
