"""Streaming windowed handoff (ISSUE 8): the hybrid's tail consumes the
reduced live set as ascending hi-quantile windows, each folded through
the resumable native union-find while the next window is still in
flight.  Covered here: the W in {1, 2, 4, 8} parity sweep (bit-identical
parent+pst, equal ECV(down) vs the serial fetch), the accelerator window
queue (device hi-sort + _WindowStream) forced on the cpu backend, clean
serial fallback on a mid-stream fetch failure AND on a mid-fold failure,
the host-seq prep arm on/off, the non-immediate (reduced-multiset) pst
resolver path, and the driver's stream rung + its governor pricing."""

import numpy as np
import pytest

from sheep_tpu.core import build_forest, degree_sequence


@pytest.fixture
def stream_env(monkeypatch):
    monkeypatch.setenv("SHEEP_STREAM_HANDOFF", "1")
    for k in ("SHEEP_HANDOFF_WINDOWS", "SHEEP_STREAM_DEVICE_WINDOWS",
              "SHEEP_STREAM_HOST_SEQ", "SHEEP_HANDOFF_FACTOR",
              "SHEEP_OVERLAP_HANDOFF", "SHEEP_PACK_HANDOFF"):
        monkeypatch.delenv(k, raising=False)
    return monkeypatch


def _graph(log_n=12, seed=3):
    from sheep_tpu.utils.synth import rmat_edges
    n = 1 << log_n
    tail, head = rmat_edges(log_n, 4 * n, seed=seed)
    return n, tail, head


def _ecv_down(seq, forest, tail, head, parts=4):
    from sheep_tpu.partition import Partition, evaluate_partition
    part = Partition.from_forest(seq, forest, num_parts=parts)
    rep = evaluate_partition(part.parts, tail, head, seq, num_parts=parts)
    return int(rep.ecv_down)


def _serial_reference(tail, head, n, stream_env):
    from sheep_tpu.ops import build_graph_hybrid
    stream_env.setenv("SHEEP_STREAM_HANDOFF", "0")
    seq0, f0 = build_graph_hybrid(tail, head, n)
    stream_env.setenv("SHEEP_STREAM_HANDOFF", "1")
    return seq0, f0


def test_windowed_parity_sweep(stream_env):
    """W in {1, 2, 4, 8}: bit-identical parent+pst and equal ECV(down)
    vs the serial-fetch tail (the acceptance sweep)."""
    from sheep_tpu.ops import build_graph_hybrid
    n, tail, head = _graph()
    seq0, f0 = _serial_reference(tail, head, n, stream_env)
    ecv0 = _ecv_down(seq0, f0, tail, head)
    for w in (1, 2, 4, 8):
        stream_env.setenv("SHEEP_HANDOFF_WINDOWS", str(w))
        perf = {}
        seq, f = build_graph_hybrid(tail, head, n, perf=perf)
        assert perf.get("stream_mode") == "windowed", perf
        assert perf.get("fetch_windows") == w
        np.testing.assert_array_equal(seq, seq0)
        np.testing.assert_array_equal(f.parent, f0.parent)
        np.testing.assert_array_equal(f.pst_weight, f0.pst_weight)
        assert _ecv_down(seq, f, tail, head) == ecv0


@pytest.mark.parametrize("packed", [False, True])
def test_device_window_queue_forced_on_cpu(stream_env, packed):
    """The accelerator transfer machinery — device hi-sort + the
    _WindowStream slice queue with prefetch depth 2 — forced on the cpu
    backend (the overlap tests' trick), packed and pair modes."""
    from sheep_tpu.ops import build_graph_hybrid
    n, tail, head = _graph()
    seq0, f0 = _serial_reference(tail, head, n, stream_env)
    stream_env.setenv("SHEEP_STREAM_DEVICE_WINDOWS", "1")
    stream_env.setenv("SHEEP_HANDOFF_WINDOWS", "4")
    # slice small enough that 4 windows get >= 1 slice each (the stream
    # caps W at the slice count)
    stream_env.setenv("SHEEP_OVERLAP_SLICE", "2048")
    if packed:
        stream_env.setenv("SHEEP_PACK_HANDOFF", "1")
    perf = {}
    seq, f = build_graph_hybrid(tail, head, n, perf=perf)
    assert perf.get("stream_mode") == "windowed", perf
    assert perf.get("packed_handoff") is packed
    assert perf.get("fetch_windows") == 4
    np.testing.assert_array_equal(seq, seq0)
    np.testing.assert_array_equal(f.parent, f0.parent)
    np.testing.assert_array_equal(f.pst_weight, f0.pst_weight)


def test_mid_stream_fetch_failure_falls_back_serial(stream_env,
                                                    monkeypatch):
    """A slice fetch dying mid-stream must degrade to the serial fetch
    of the still-alive device arrays — bit-identical result, honest
    stream_mode."""
    import sheep_tpu.ops.build as B
    from sheep_tpu.ops import build_graph_hybrid
    n, tail, head = _graph()
    seq0, f0 = _serial_reference(tail, head, n, stream_env)
    stream_env.setenv("SHEEP_STREAM_DEVICE_WINDOWS", "1")
    stream_env.setenv("SHEEP_HANDOFF_WINDOWS", "4")
    stream_env.setenv("SHEEP_OVERLAP_SLICE", "4096")
    real = B._slice_rows
    calls = {"n": 0}

    def flaky(buf, start, length):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected slice fault")
        return real(buf, start, length)

    monkeypatch.setattr(B, "_slice_rows", flaky)
    perf = {}
    seq, f = build_graph_hybrid(tail, head, n, perf=perf)
    assert str(perf.get("stream_mode", "")).startswith("fallback:"), perf
    np.testing.assert_array_equal(seq, seq0)
    np.testing.assert_array_equal(f.parent, f0.parent)
    np.testing.assert_array_equal(f.pst_weight, f0.pst_weight)


def test_mid_fold_failure_falls_back_serial(stream_env, monkeypatch):
    """The host-side branch too: a fold block raising mid-window falls
    back cleanly to the serial fetch + monolithic fold."""
    import sheep_tpu.core.forest as cf
    from sheep_tpu.ops import build_graph_hybrid
    n, tail, head = _graph()
    seq0, f0 = _serial_reference(tail, head, n, stream_env)
    stream_env.setenv("SHEEP_HANDOFF_WINDOWS", "4")
    real = cf.links_fold
    calls = {"n": 0}

    def flaky_fold(n_, pst=None, impl="auto"):
        fold = real(n_, pst, impl)
        orig_block = fold.block

        def block(lo, hi):
            calls["n"] += 1
            if calls["n"] == 2:
                raise RuntimeError("injected fold fault")
            return orig_block(lo, hi)

        fold.block = block
        return fold

    monkeypatch.setattr(cf, "links_fold", flaky_fold)
    perf = {}
    seq, f = build_graph_hybrid(tail, head, n, perf=perf)
    assert str(perf.get("stream_mode", "")).startswith("fallback:"), perf
    np.testing.assert_array_equal(seq, seq0)
    np.testing.assert_array_equal(f.parent, f0.parent)
    np.testing.assert_array_equal(f.pst_weight, f0.pst_weight)


def test_host_seq_arm_parity(stream_env):
    """The host-seq prep (native counting-sort sequence + device mapping
    only) and the device-seq prep produce bit-identical outputs, and the
    perf record says which tail ran."""
    from sheep_tpu.ops import build_graph_hybrid
    n, tail, head = _graph(seed=11)
    stream_env.setenv("SHEEP_STREAM_HOST_SEQ", "1")
    seq_a, f_a = build_graph_hybrid(tail, head, n)
    stream_env.setenv("SHEEP_STREAM_HOST_SEQ", "0")
    seq_b, f_b = build_graph_hybrid(tail, head, n)
    np.testing.assert_array_equal(seq_a, seq_b)
    np.testing.assert_array_equal(f_a.parent, f_b.parent)
    np.testing.assert_array_equal(f_a.pst_weight, f_b.pst_weight)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    np.testing.assert_array_equal(seq_a, want_seq)
    np.testing.assert_array_equal(f_a.parent, want.parent)
    np.testing.assert_array_equal(f_a.pst_weight, want.pst_weight)


def test_reduced_multiset_uses_prep_pst(stream_env):
    """A small handoff factor forces real reduce rounds (the multiset is
    rewritten), so the fold must consume the prep-time pst resolver, not
    accumulate — still bit-identical."""
    from sheep_tpu.ops import build_graph_hybrid
    n, tail, head = _graph(seed=7)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    stream_env.setenv("SHEEP_HANDOFF_FACTOR", "2")
    stream_env.setenv("SHEEP_HANDOFF_WINDOWS", "4")
    perf = {}
    seq, f = build_graph_hybrid(tail, head, n, perf=perf)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(f.parent, want.parent)
    np.testing.assert_array_equal(f.pst_weight, want.pst_weight)


def test_given_seq_partial_stays_exact(stream_env):
    """An externally given PARTIAL sequence (absent vids -> pst-only
    links that never reach the stream) must keep the absent-vid pst
    contract under the windowed tail."""
    from sheep_tpu.ops import build_graph_hybrid
    n, tail, head = _graph(seed=5)
    full = degree_sequence(tail, head)
    sub = full[: len(full) // 2]
    want = build_forest(tail, head, sub, max_vid=n - 1)
    stream_env.setenv("SHEEP_HANDOFF_WINDOWS", "4")
    seq, f = build_graph_hybrid(tail, head, n, seq=sub)
    np.testing.assert_array_equal(seq, sub)
    np.testing.assert_array_equal(f.parent, want.parent)
    np.testing.assert_array_equal(f.pst_weight, want.pst_weight)


def test_stream_rung_oracle_exact_and_windowed(monkeypatch):
    """The driver's stream rung folds the checkpointable link table
    window-by-window (O(n + window) beyond the input) and matches the
    oracle; shrinking the window forces multiple blocks."""
    from sheep_tpu.runtime import RuntimeConfig, build_graph_resilient
    import sheep_tpu.resources.governor as gov_mod
    n, tail, head = _graph(log_n=11, seed=9)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    monkeypatch.setattr(gov_mod, "SPILL_BLOCK", 1024)
    cfg = RuntimeConfig(ladder=("stream",))
    seq, forest = build_graph_resilient(tail, head, config=cfg)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)
    windows = [e for e in cfg.events if e[0] == "stream-window"]
    assert len(windows) > 2, windows


def test_governor_prices_stream_between_host_and_spill(monkeypatch):
    """Tight budgets route host -> stream before spill: the stream rung
    is priced O(n + window) beyond the input, below the host rung's
    16-bytes-per-link int64 cast, above nothing it needs to yield to
    but the memory floor."""
    import sheep_tpu.resources.governor as gov_mod
    from sheep_tpu.resources.governor import ResourceGovernor, \
        rung_peak_nbytes
    n, links = 1 << 20, 1 << 23
    host_est = rung_peak_nbytes("host", n, links)
    stream_est = rung_peak_nbytes("stream", n, links)
    spill_est = rung_peak_nbytes("spill", n, links)
    assert spill_est < stream_est < host_est
    monkeypatch.setattr(gov_mod, "rss_bytes", lambda: 0)
    gov = ResourceGovernor(mem_budget=(host_est + stream_est) // 2)
    rungs, _ = gov.plan_rungs(["host", "stream", "spill"], n, links)
    assert rungs == ["stream", "spill"]
    tight = ResourceGovernor(mem_budget=spill_est // 2)
    rungs, _ = tight.plan_rungs(["host", "stream", "spill"], n, links)
    assert rungs == ["spill"]  # the floor always survives
