"""Overlapped speculative handoff (ops.build._SpecHandoff + the
reduce_and_fetch_links driver) — VERDICT r04 item 1.

The machinery is accelerator-targeted (default-on off-cpu) but fully
exercisable on the cpu backend by forcing SHEEP_OVERLAP_HANDOFF=1 with
tiny slice/min-size knobs: correctness must be oracle-exact through
every speculation outcome (complete, waited-out, restarted, abandoned,
unions of partial snapshots), because any snapshot — or union of
snapshots — preserves threshold connectivity (ops.forest proof).
"""

from __future__ import annotations

import numpy as np
import pytest

from sheep_tpu.core import build_forest, degree_sequence
from sheep_tpu.core.forest import native_or_none


def _oracle(tail, head):
    seq = degree_sequence(tail, head)
    return seq, build_forest(tail, head, seq)


def _graph(seed=90, n=400, e=6000):
    rng = np.random.default_rng(seed)
    tail = rng.integers(0, n, e).astype(np.uint32)
    head = rng.integers(0, n, e).astype(np.uint32)
    return tail, head


@pytest.fixture
def overlap_env(monkeypatch):
    monkeypatch.setenv("SHEEP_OVERLAP_HANDOFF", "1")
    monkeypatch.setenv("SHEEP_OVERLAP_MIN_MB", "0.0001")
    monkeypatch.setenv("SHEEP_OVERLAP_SLICE", "4096")
    # keep the loop from skipping rounds so the watch hook actually fires
    monkeypatch.delenv("SHEEP_HANDOFF_FACTOR", raising=False)
    return monkeypatch


def test_hybrid_overlap_oracle_exact(overlap_env):
    from sheep_tpu.ops import build_graph_hybrid

    tail, head = _graph()
    want_seq, want = _oracle(tail, head)
    seq, forest = build_graph_hybrid(tail, head, handoff_factor=2)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_hybrid_overlap_matches_overlap_off(overlap_env):
    from sheep_tpu.ops import build_graph_hybrid

    tail, head = _graph(seed=91)
    seq_on, f_on = build_graph_hybrid(tail, head, handoff_factor=2)
    overlap_env.setenv("SHEEP_OVERLAP_HANDOFF", "0")
    seq_off, f_off = build_graph_hybrid(tail, head, handoff_factor=2)
    np.testing.assert_array_equal(seq_on, seq_off)
    np.testing.assert_array_equal(f_on.parent, f_off.parent)
    np.testing.assert_array_equal(f_on.pst_weight, f_off.pst_weight)


def test_reduce_and_fetch_spec_runs_and_is_exact(overlap_env):
    """Drive reduce_and_fetch_links directly and check the speculation
    actually engaged (spec_starts >= 1) and the handoff set rebuilds the
    oracle forest through the native union-find."""
    import jax.numpy as jnp
    from sheep_tpu.ops.build import (prepare_links, reduce_and_fetch_links,
                                     finish_native_host)

    overlap_env.setenv("SHEEP_OVERLAP_SPEC_FACTOR", "1000")
    tail, head = _graph(seed=92, n=1 << 10, e=1 << 14)
    n = 1 << 10
    want_seq, want = _oracle(tail, head)
    _, _, m, lo, hi, pst = prepare_links(
        jnp.asarray(tail, jnp.int32), jnp.asarray(head, jnp.int32), n)
    perf: dict = {}
    kind, a, b, live, rounds = reduce_and_fetch_links(
        lo, hi, n, stop_live=n, perf=perf)
    assert perf.get("spec_starts", 0) >= 1, perf
    assert "loop_s" in perf and "fetch_tail_s" in perf
    if kind == "device":  # converged before threshold — still checkable
        from sheep_tpu.ops.build import fetch_links_host
        a, b, _ = fetch_links_host(a, b, live, n)
    parent, pst_out = finish_native_host(
        np.asarray(a), np.asarray(b), n, np.asarray(pst, np.uint32)[:n])
    m = int(m)
    np.testing.assert_array_equal(parent[:m], want.parent)
    np.testing.assert_array_equal(pst_out[:m], want.pst_weight)


def test_union_of_snapshots_is_sound(overlap_env):
    """The correctness backbone of abandoned-partial reuse: feeding the
    union-find links from TWO different chunk generations (a complete
    later snapshot plus the full earlier one as 'kept partials') yields
    the identical forest."""
    import jax.numpy as jnp
    from sheep_tpu.ops.build import prepare_links, finish_native_host
    from sheep_tpu.ops.forest import reduce_links_hosted

    tail, head = _graph(seed=93, n=512, e=1 << 13)
    n = 512
    want_seq, want = _oracle(tail, head)
    _, _, m, lo, hi, pst = prepare_links(
        jnp.asarray(tail, jnp.int32), jnp.asarray(head, jnp.int32), n)
    snaps = []

    def watch(slo, shi, live):
        snaps.append((np.asarray(slo), np.asarray(shi), int(live)))
        return False

    lo2, hi2, live2, _, _ = reduce_links_hosted(lo, hi, n, stop_live=n,
                                                watch=watch)
    assert snaps, "watch hook never fired"
    early_lo, early_hi, early_live = snaps[0]
    final_lo = np.asarray(lo2)[:int(live2)]
    final_hi = np.asarray(hi2)[:int(live2)]
    mix_lo = np.concatenate([early_lo[:early_live], final_lo])
    mix_hi = np.concatenate([early_hi[:early_live], final_hi])
    keep = mix_lo < n
    parent, pst_out = finish_native_host(
        mix_lo[keep], mix_hi[keep], n, np.asarray(pst, np.uint32)[:n])
    m = int(m)
    np.testing.assert_array_equal(parent[:m], want.parent)
    np.testing.assert_array_equal(pst_out[:m], want.pst_weight)


def test_stream_fetcher_packed_and_pair_modes(overlap_env):
    """_StreamFetcher must deliver the exact snapshot bytes in both the
    6-byte-packed (n < 2^24) and int32-pair (n >= 2^24) modes."""
    import jax.numpy as jnp
    from sheep_tpu.ops.build import _StreamFetcher

    rng = np.random.default_rng(94)
    for n in ((1 << 20), (1 << 24) + 5):
        live = 9000
        pad = 1 << 14
        lo = np.full(pad, n, np.int64)
        hi = np.full(pad, n, np.int64)
        lo[:live] = rng.integers(0, n - 1, live)
        hi[:live] = rng.integers(0, n - 1, live)
        f = _StreamFetcher(jnp.asarray(lo, jnp.int32),
                           jnp.asarray(hi, jnp.int32), n, live,
                           slice_links=2048)
        f.join()
        assert f.finished() and not f.failed
        got_lo, got_hi = f.collect()
        keep = got_lo < n
        np.testing.assert_array_equal(got_lo[keep], lo[:live])
        np.testing.assert_array_equal(got_hi[keep], hi[:live])
        assert f.remaining_bytes() == 0


def test_stream_fetcher_non_pow2_slice_covers_all(overlap_env):
    """A non-power-of-two SHEEP_OVERLAP_SLICE must not skip tail links:
    the fetcher rounds the knob down to a pow2 so slices always tile the
    pow2-padded width (a dropped tail would mean a silently wrong
    forest)."""
    import jax.numpy as jnp
    from sheep_tpu.ops.build import _StreamFetcher

    n = 1 << 20
    pad = 1 << 14
    live = pad - 100  # live links close to the padded width
    rng = np.random.default_rng(96)
    lo = np.full(pad, n, np.int64)
    hi = np.full(pad, n, np.int64)
    lo[:live] = rng.integers(0, n - 1, live)
    hi[:live] = rng.integers(0, n - 1, live)
    f = _StreamFetcher(jnp.asarray(lo, jnp.int32), jnp.asarray(hi, jnp.int32),
                       n, live, slice_links=3000)  # not a pow2
    assert f.slice_len == 2048
    f.join()
    assert f.finished()
    got_lo, got_hi = f.collect()
    keep = got_lo < n
    np.testing.assert_array_equal(got_lo[keep], lo[:live])
    np.testing.assert_array_equal(got_hi[keep], hi[:live])


def test_stream_fetcher_abort_keeps_prefix(overlap_env):
    import jax.numpy as jnp
    from sheep_tpu.ops.build import _StreamFetcher

    n = 1 << 20
    pad = 1 << 14
    rng = np.random.default_rng(95)
    lo = rng.integers(0, n - 1, pad)
    hi = rng.integers(0, n - 1, pad)
    f = _StreamFetcher(jnp.asarray(lo, jnp.int32), jnp.asarray(hi, jnp.int32),
                       n, pad, slice_links=1024)
    f.abort()  # immediate abort: whatever slices landed must be a prefix
    assert f.failed is False, "abort must not poison a healthy stream"
    got_lo, got_hi = f.collect()
    k = len(got_lo)
    assert k % 1024 == 0 and k == f.done_slices * 1024
    np.testing.assert_array_equal(got_lo, lo[:k])
    np.testing.assert_array_equal(got_hi, hi[:k])


def test_spec_handoff_restart_policy():
    """The adaptive abandon/restart rule, unit-level: a fetch whose
    remaining bytes exceed 1.25x the fresh snapshot restarts; kept
    partial buffers survive into complete()."""
    from sheep_tpu.ops.build import _SpecHandoff

    class FakeFetcher:
        def __init__(self, remaining, done=1):
            self._remaining = remaining
            self.done_slices = done
            self.failed = False
        def finished(self):
            return self._remaining == 0
        def remaining_bytes(self):
            return self._remaining
        def abort(self):
            pass
        def join(self):
            self._remaining = 0
        def fetched_bytes(self):
            return 6 * 1000
        def collect(self):
            return (np.zeros(10, np.int32), np.ones(10, np.int32))

    n = 1 << 16
    sp = _SpecHandoff(n)
    started = []
    sp._start = lambda lo, hi, live: started.append(live)  # type: ignore
    # active fetch with a huge remainder vs a still-large current
    # snapshot (above the min_bytes floor): abandon AND restart
    big_live = 2 * sp.min_bytes // sp.bpl
    sp.active = FakeFetcher(remaining=100 * sp.min_bytes)
    assert sp.on_chunk(None, None, big_live) is False
    assert sp.stats["spec_restarts"] == 1 and started == [big_live]
    # huge remainder vs a TINY snapshot: abandon, but the restart honors
    # the same min_bytes floor as first starts (ADVICE r05 — a restart
    # on a tiny snapshot pays a pack dispatch / fresh compile for less
    # than it saves)
    sp.active = FakeFetcher(remaining=10_000_000)
    assert sp.on_chunk(None, None, 1000) is False
    assert sp.stats["spec_restarts"] == 2 and started == [big_live]
    # finished fetch stops the loop
    sp.active = FakeFetcher(remaining=0)
    assert sp.on_chunk(None, None, 500) is True
    assert sp.stats["spec_stopped_loop"] is True


def test_overlap_disabled_on_cpu_by_default(monkeypatch):
    monkeypatch.delenv("SHEEP_OVERLAP_HANDOFF", raising=False)
    from sheep_tpu.ops.build import _overlap_enabled
    import jax
    if jax.devices()[0].platform == "cpu":
        assert _overlap_enabled() is False


@pytest.mark.skipif(native_or_none("auto") is None,
                    reason="native runtime unavailable")
def test_hybrid_overlap_rmat_larger(overlap_env):
    """A larger R-MAT through the full hybrid with speculation forced,
    multi-slice, factor 1 (longest loop, most chances to restart)."""
    from sheep_tpu.ops import build_graph_hybrid
    from sheep_tpu.utils import rmat_edges

    tail, head = rmat_edges(13, 8 << 13, seed=5)
    want_seq, want = _oracle(tail, head)
    seq, forest = build_graph_hybrid(tail, head, handoff_factor=1)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_spec_wait_timeout_falls_back_serial(overlap_env):
    """A wedged stream (join watchdog fires) must fall back to the
    serial fetch, record mode=spec_wait_timeout, count the wasted
    bytes, and still produce the exact link set."""
    import sheep_tpu.ops.build as b

    n = 1 << 12
    rng = np.random.default_rng(97)
    pad = 1 << 13
    lo_np = np.full(pad, n, np.int64)
    hi_np = np.full(pad, n, np.int64)
    live = 6000
    lo_np[:live] = rng.integers(0, n - 1, live)
    hi_np[:live] = np.minimum(lo_np[:live] + 1, n - 1)
    import jax.numpy as jnp
    lo = jnp.asarray(lo_np, jnp.int32)
    hi = jnp.asarray(hi_np, jnp.int32)

    sp = b._SpecHandoff(n)

    class WedgedFetcher:
        failed = False
        done_slices = 1
        def finished(self):
            return False
        def remaining_bytes(self):
            return 1  # tiny remainder -> complete() takes the wait path
        def join(self, timeout=None, mark_failed=True):
            if mark_failed:
                self.failed = True  # watchdog fired
            return True
        def abort(self, timeout=5.0):
            pass
        def fetched_bytes(self):
            return 3 << 20
        def collect(self):
            raise AssertionError("collect must not run on a wedged stream")

    sp.active = WedgedFetcher()
    lo_h, hi_h = sp.complete(lo, hi, live)
    assert sp.stats["spec_mode"] == "spec_wait_timeout"
    assert sp.stats["spec_wasted_mb"] >= 3.0
    # pairwise multiset check: both halves of every link must survive
    order_got = np.lexsort((hi_h, lo_h))
    order_want = np.lexsort((hi_np[:live], lo_np[:live]))
    np.testing.assert_array_equal(lo_h[order_got],
                                  lo_np[:live][order_want])
    np.testing.assert_array_equal(hi_h[order_got],
                                  hi_np[:live][order_want])
    assert len(lo_h) == len(hi_h) == live


def test_abort_slow_stream_does_not_poison(overlap_env):
    """abort() on a slow-but-healthy stream must not mark it failed or
    disable later speculation; landed slices stay collectable."""
    from sheep_tpu.ops.build import _SpecHandoff

    sp = _SpecHandoff(1 << 16)

    class SlowFetcher:
        failed = False
        done_slices = 2
        def join(self, timeout=None, mark_failed=True):
            return True  # still draining, but abort passes mark_failed=False
        def abort(self, timeout=5.0):
            self.join(timeout, mark_failed=False)
        def fetched_bytes(self):
            return 2 << 20
        def collect(self):
            return (np.zeros(100, np.int32), np.ones(100, np.int32))

    sp.active = SlowFetcher()
    sp._abandon()
    assert sp.dead is False, "slow abort must not disable speculation"
    assert len(sp.kept) == 1, "landed partial slices must be kept"


def test_hybrid_overlap_pair_mode_large_n(overlap_env):
    """End-to-end hybrid at n >= 2^24 (sparse edges over a huge vertex
    space): the overlapped stream must take the int32-pair mode (no
    6-byte packing above 2^24) and stay oracle-exact — the shape the
    watcher's 2^24 on-chip step runs."""
    n = (1 << 24) + 1000
    e = 60_000
    rng = np.random.default_rng(98)
    tail = rng.integers(0, n, e).astype(np.uint32)
    head = rng.integers(0, n, e).astype(np.uint32)
    from sheep_tpu.ops import build_graph_hybrid

    want_seq, want = _oracle(tail, head)
    overlap_env.setenv("SHEEP_OVERLAP_SPEC_FACTOR", "100000")
    seq, forest = build_graph_hybrid(tail, head, num_vertices=n,
                                     handoff_factor=1)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)
