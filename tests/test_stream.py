"""Out-of-core streaming build == whole-graph oracle, for any block size."""

import os

import numpy as np
import pytest

from conftest import random_multigraph

from sheep_tpu.core.forest import build_forest
from sheep_tpu.core.sequence import degree_sequence, sequence_positions
from sheep_tpu.io.edges import iter_dat_blocks, load_edges, write_dat
from sheep_tpu.ops import build_graph_streaming, streaming_degree_histogram


def _blocks(tail, head, block):
    for a in range(0, len(tail), block):
        yield tail[a:a + block], head[a:a + block]


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("block", [7, 64, 10_000])
def test_streaming_matches_oracle(seed, block):
    rng = np.random.default_rng(seed)
    tail, head = random_multigraph(rng, n_max=60, e_max=300)
    seq = degree_sequence(tail, head)
    n_vid = int(max(tail.max(), head.max())) + 1
    n = max(n_vid, len(seq))
    pos = sequence_positions(seq, n - 1)
    forest, _ = build_graph_streaming(
        _blocks(tail, head, block), n, pos, block_edges=block)
    want = build_forest(tail, head, seq, max_vid=n - 1, impl="python")
    m = len(seq)
    np.testing.assert_array_equal(forest.parent[:m], want.parent)
    np.testing.assert_array_equal(forest.pst_weight[:m], want.pst_weight)
    # slots past the active positions stay empty roots
    assert (forest.pst_weight[m:] == 0).all()


def test_streaming_degree_histogram():
    rng = np.random.default_rng(17)
    tail, head = random_multigraph(rng, n_max=50, e_max=200)
    n = int(max(tail.max(), head.max())) + 1
    deg = streaming_degree_histogram(_blocks(tail, head, 13), n)
    ref = np.bincount(tail, minlength=n) + np.bincount(head, minlength=n)
    np.testing.assert_array_equal(deg, ref)


def test_iter_dat_blocks_roundtrip(tmp_path):
    rng = np.random.default_rng(3)
    tail = rng.integers(0, 100, 50).astype(np.uint32)
    head = rng.integers(0, 100, 50).astype(np.uint32)
    path = str(tmp_path / "g.dat")
    write_dat(path, tail, head)
    ts, hs = [], []
    for t, h in iter_dat_blocks(path, 7):
        assert len(t) <= 7
        ts.append(t)
        hs.append(h)
    np.testing.assert_array_equal(np.concatenate(ts), tail)
    np.testing.assert_array_equal(np.concatenate(hs), head)
    # partial ranges match the eager loader
    el = load_edges(path, part=2, num_parts=3)
    ts = [t for t, _ in iter_dat_blocks(path, 5, part=2, num_parts=3)]
    np.testing.assert_array_equal(np.concatenate(ts), el.tail)


def test_streaming_end_to_end_hepth(hep_edges):
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    n = max(hep_edges.max_vid + 1, len(seq))
    pos = sequence_positions(seq, n - 1)
    forest, rounds = build_graph_streaming(
        _blocks(hep_edges.tail, hep_edges.head, 4096), n, pos,
        block_edges=4096)
    want = build_forest(hep_edges.tail, hep_edges.head, seq)
    m = len(seq)
    np.testing.assert_array_equal(forest.parent[:m], want.parent)
    np.testing.assert_array_equal(forest.pst_weight[:m], want.pst_weight)


@pytest.mark.parametrize("blocksize", [7, 64, 1000])
def test_streaming_hosted_matches_whole(blocksize):
    from sheep_tpu.ops.stream import build_graph_streaming_hosted

    rng = np.random.default_rng(77)
    tail, head = random_multigraph(rng, 150, 900)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    pos = sequence_positions(seq, int(max(tail.max(), head.max())))

    def blocks():
        for a in range(0, len(tail), blocksize):
            yield tail[a:a + blocksize], head[a:a + blocksize]

    forest, rounds = build_graph_streaming_hosted(
        blocks(), len(seq), pos.astype(np.int64), blocksize)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


@pytest.mark.parametrize("hosted", [False, True])
def test_streaming_sparse_vid_space(hosted):
    # Regression: vids far beyond the active count (zero-degree gaps) must
    # keep their positions — the pos table covers the vid space, not just
    # the n active slots.
    from sheep_tpu.ops import (build_graph_streaming,
                               build_graph_streaming_hosted)

    rng = np.random.default_rng(55)
    vids = rng.choice(5000, size=60, replace=False).astype(np.uint32)
    tail = rng.choice(vids, 300).astype(np.uint32)
    head = rng.choice(vids, 300).astype(np.uint32)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    pos = sequence_positions(seq, 4999).astype(np.int64)

    def blocks():
        for a in range(0, len(tail), 37):
            yield tail[a:a + 37], head[a:a + 37]

    fn = build_graph_streaming_hosted if hosted else build_graph_streaming
    forest, _ = fn(blocks(), len(seq), pos, 37)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_streaming_composes_with_logical_workers():
    # SURVEY §2 OOM row: streaming must compose with worker parallelism.
    # W logical workers each stream their own partial edge range in blocks
    # (the file path's map phase in OOM mode, more partials than cores);
    # merging the W carried forests must equal the whole-graph tree.
    from sheep_tpu.core.forest import merge_forests
    from sheep_tpu.io.edges import partial_range
    from sheep_tpu.ops.stream import build_graph_streaming_hosted

    rng = np.random.default_rng(88)
    tail, head = random_multigraph(rng, 200, 1400)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    pos = sequence_positions(seq, int(max(tail.max(), head.max())))
    workers, blocksize = 3, 53

    partials = []
    for w in range(workers):
        a, b = partial_range(len(tail), w + 1, workers)

        def blocks(a=a, b=b):
            for s in range(a, b, blocksize):
                e = min(s + blocksize, b)
                yield tail[s:e], head[s:e]

        f, _ = build_graph_streaming_hosted(
            blocks(), len(seq), pos.astype(np.int64), blocksize)
        partials.append(f)
    merged = merge_forests(*partials)
    np.testing.assert_array_equal(merged.parent, want.parent)
    np.testing.assert_array_equal(merged.pst_weight, want.pst_weight)


@pytest.mark.parametrize("workers", [2, 3, 8])
@pytest.mark.parametrize("block", [13, 256])
def test_streaming_sharded_matches_oracle(workers, block):
    """OOM streaming composed with the mesh: blocks sharded over the
    'workers' axis, carry merged associatively per block."""
    from sheep_tpu.parallel import build_graph_streaming_sharded

    rng = np.random.default_rng(200 + workers)
    tail, head = random_multigraph(rng, n_max=60, e_max=300)
    seq = degree_sequence(tail, head)
    n_vid = int(max(tail.max(), head.max())) + 1
    n = max(n_vid, len(seq))
    pos = sequence_positions(seq, n - 1)
    forest, _ = build_graph_streaming_sharded(
        _blocks(tail, head, block), n, pos, block_edges=block,
        num_workers=workers)
    want = build_forest(tail, head, seq, max_vid=n - 1, impl="python")
    m = len(seq)
    np.testing.assert_array_equal(forest.parent[:m], want.parent)
    np.testing.assert_array_equal(forest.pst_weight[:m], want.pst_weight)
    assert (forest.pst_weight[m:] == 0).all()


def test_streaming_sharded_hepth(hep_edges):
    from sheep_tpu.parallel import build_graph_streaming_sharded

    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    n = max(hep_edges.max_vid + 1, len(seq))
    pos = sequence_positions(seq, n - 1)
    forest, _ = build_graph_streaming_sharded(
        _blocks(hep_edges.tail, hep_edges.head, 8192), n, pos,
        block_edges=8192, num_workers=8)
    want = build_forest(hep_edges.tail, hep_edges.head, seq)
    m = len(seq)
    np.testing.assert_array_equal(forest.parent[:m], want.parent)
    np.testing.assert_array_equal(forest.pst_weight[:m], want.pst_weight)


@pytest.mark.parametrize("impl", ["python", "auto"])
@pytest.mark.parametrize("block", [7, 64, 10_000])
def test_native_streaming_fold_matches_oracle(impl, block):
    """core.build_forest_streaming: the host OOM carry-fold, both impls."""
    from sheep_tpu.core.forest import build_forest_streaming

    rng = np.random.default_rng(321)
    tail, head = random_multigraph(rng, n_max=60, e_max=300)
    seq = degree_sequence(tail, head)
    n_vid = int(max(tail.max(), head.max())) + 1
    want = build_forest(tail, head, seq, max_vid=n_vid - 1, impl="python")
    forest = build_forest_streaming(
        _blocks(tail, head, block), seq, max_vid=n_vid - 1, impl=impl)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_native_streaming_fold_partial_sequence():
    # links to vids absent from the sequence stay pst-only, exactly like
    # the whole-graph build (jtree.cpp:47-49 contract)
    from sheep_tpu.core.forest import build_forest_streaming

    rng = np.random.default_rng(322)
    tail, head = random_multigraph(rng, n_max=40, e_max=160)
    full = degree_sequence(tail, head)
    seq = full[: max(1, len(full) - 3)]
    n_vid = int(max(tail.max(), head.max())) + 1
    want = build_forest(tail, head, seq, max_vid=n_vid - 1, impl="python")
    forest = build_forest_streaming(
        _blocks(tail, head, 11), seq, max_vid=n_vid - 1)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)
