"""Chaos-hardened tournament supervisor tests (ISSUE 3).

The acceptance property: a kill, corrupt, or hang injected at EVERY
tournament round (SHEEP_FAULT_PLAN grammar) must yield a final tree
bit-identical to the fault-free run — equal ECV(down) included — while
re-dispatching ONLY the faulted leg (dispatch-count assertion); and a
supervisor killed after any leg resumes off the fsck'd manifest,
re-dispatching only the legs that are dirty/missing AND still needed.

All legs run in-process (InlineRunner) so the property sweep is seconds,
not minutes; one subprocess smoke pins the production runner and one
dist-partition.sh -S run pins the shell integration.
"""

import os
import re
import subprocess
import time

import numpy as np
import pytest

from sheep_tpu.core.forest import build_forest
from sheep_tpu.core.sequence import degree_sequence
from sheep_tpu.io.edges import write_net
from sheep_tpu.io.trefile import read_tree
from sheep_tpu.supervisor import (InlineRunner, SupervisionFailed,
                                  SupervisorConfig, SupervisorKilled,
                                  load_manifest, parse_fault_plan,
                                  plan_tournament, run_supervised,
                                  save_manifest, tournament_rounds)
from sheep_tpu.supervisor.chaos import SORT_ROUND
from sheep_tpu.supervisor.heartbeat import HeartbeatWriter, is_stale
from sheep_tpu.utils.synth import rmat_edges

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKERS = 4


@pytest.fixture(scope="module")
def small_graph(tmp_path_factory):
    d = tmp_path_factory.mktemp("supervised")
    tail, head = rmat_edges(7, 4 << 7, seed=11)
    graph = str(d / "g.net")
    write_net(graph, tail, head)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    return graph, tail, head, seq, want


def _config(**overrides) -> SupervisorConfig:
    kw = dict(workers=WORKERS, deadline_s=10.0, poll_s=0.01,
              backoff_base_s=0.0, heartbeat_s=0.05)
    kw.update(overrides)
    return SupervisorConfig(**kw)


def _run(graph, state_dir, **overrides):
    cfg = _config(**overrides)
    manifest = run_supervised(graph, str(state_dir), cfg,
                              runner=InlineRunner(0.05))
    return manifest, cfg


def _ecv_down(tail, head, seq, forest, parts=2):
    from sheep_tpu.partition.evaluate import evaluate_partition
    from sheep_tpu.partition.partition import Partition

    p = Partition.from_forest(seq, forest, parts)
    rep = evaluate_partition(p.parts, tail, head, seq, p.num_parts)
    return rep.ecv_down


def _final(manifest):
    with open(manifest.final_tree, "rb") as f:
        return f.read()


def _all_legs():
    """(round, index) of every leg in the WORKERS-wide tournament,
    sort included."""
    legs = [(SORT_ROUND, 0)] + [(0, i) for i in range(WORKERS)]
    for s, slots in enumerate(tournament_rounds(WORKERS, 2)):
        legs += [(s + 1, i) for i in range(len(slots))]
    return legs


# ---------------------------------------------------------------------------
# units: heartbeat
# ---------------------------------------------------------------------------


def test_heartbeat_writer_beats(tmp_path):
    hb = str(tmp_path / "w.hb")
    with HeartbeatWriter(hb, interval_s=0.05):
        time.sleep(0.02)
        assert os.path.exists(hb)  # first beat lands at start()
        m1 = os.path.getmtime(hb)
        time.sleep(0.2)
        assert os.path.getmtime(hb) > m1
    m2 = os.path.getmtime(hb)
    time.sleep(0.15)
    assert os.path.getmtime(hb) == m2  # stopped means silent


def test_heartbeat_staleness(tmp_path):
    hb = str(tmp_path / "w.hb")
    t0 = time.time()
    # never beat: stale once the deadline from launch passes
    assert not is_stale(hb, launched_at=t0, deadline_s=10, now=t0 + 5)
    assert is_stale(hb, launched_at=t0, deadline_s=10, now=t0 + 11)
    with open(hb, "w") as f:
        f.write("beat")
    assert not is_stale(hb, launched_at=t0, deadline_s=10)
    assert is_stale(hb, launched_at=t0, deadline_s=10,
                    now=os.path.getmtime(hb) + 11)


def test_poll_count_staleness_is_deterministic(tmp_path):
    """stale_after_polls (the chaos-sweep deflake): a silent attempt is
    declared dead after exactly N beat-free polls; an attempt that beats
    between polls resets the count; and the wall clock plays no part —
    the polls can be arbitrarily far apart in real time."""
    from sheep_tpu.supervisor.heartbeat import beat
    from sheep_tpu.supervisor.manifest import Leg
    from sheep_tpu.supervisor.supervise import (SupervisorConfig,
                                                TournamentSupervisor,
                                                _Attempt)

    class _Manifest:
        legs = []
    sup = TournamentSupervisor.__new__(TournamentSupervisor)
    sup.config = SupervisorConfig(stale_after_polls=3, deadline_s=0.0)
    hb = str(tmp_path / "a.hb")
    beat(hb)
    leg = Leg(key="x", kind="map", round=0, index=0, inputs=(),
              output=str(tmp_path / "x.tre"))
    att = _Attempt(leg=leg, number=1, tmp="t", hb=hb, handle=None,
                   started=0.0)
    # poll 0 observes the mtime; 3 consecutive quiet polls -> stale,
    # no matter that deadline_s is 0 (wall clock would have fired at
    # the first poll) or how much real time separates the polls
    assert not sup._attempt_stale(att, now=1e9)
    assert not sup._attempt_stale(att, now=2e9)
    assert not sup._attempt_stale(att, now=3e9)
    assert sup._attempt_stale(att, now=4e9)
    # a fresh beat resets the silence count
    att2 = _Attempt(leg=leg, number=2, tmp="t", hb=hb, handle=None,
                    started=0.0)
    assert not sup._attempt_stale(att2, now=0.0)
    assert not sup._attempt_stale(att2, now=0.0)
    import time as _time
    _time.sleep(0.01)  # mtime must advance
    beat(hb)
    assert not sup._attempt_stale(att2, now=0.0)
    assert att2.quiet_polls == 0


# ---------------------------------------------------------------------------
# units: manifest planning + durability
# ---------------------------------------------------------------------------


def test_tournament_bracket_matches_shell_arithmetic():
    # W=4 R=2: two rounds, slot i of the first merge round owning
    # {i, i+2} — the exact horizontal-dist.sh STEP_SIZE/WORKERS loop
    assert tournament_rounds(4, 2) == [[[0, 2], [1, 3]], [[0, 1]]]
    assert tournament_rounds(2, 2) == [[[0, 1]]]
    # odd widths leave a single-input slot (a rename in the shell driver)
    assert tournament_rounds(3, 2) == [[[0, 2], [1]], [[0, 1]]]
    # reduction >= width collapses to one merge
    assert tournament_rounds(4, 4) == [[[0, 1, 2, 3]]]


def test_plan_tournament_legs(tmp_path):
    m = plan_tournament("g.net", str(tmp_path / "g"),
                        str(tmp_path / "g.tre"), 4, 2)
    keys = [leg.key for leg in m.legs]
    assert keys == ["sort", "r0.00", "r0.01", "r0.02", "r0.03",
                    "r1.00", "r1.01", "r2.00"]
    assert m.leg("r1.00").inputs == [str(tmp_path / "g00r0.tre"),
                                     str(tmp_path / "g02r0.tre")]
    assert m.leg("r2.00").output == str(tmp_path / "g.tre")
    copy = plan_tournament("g.net", str(tmp_path / "h"),
                           str(tmp_path / "h.tre"), 3, 2)
    assert copy.leg("r1.01").kind == "copy"


def test_manifest_roundtrip_and_corruption(tmp_path):
    from sheep_tpu.integrity.errors import IntegrityError

    m = plan_tournament("g.net", str(tmp_path / "g"),
                        str(tmp_path / "g.tre"), 4, 2)
    m.leg("r0.01").state = "done"
    m.sig = "abc123"
    save_manifest(m, str(tmp_path))
    back = load_manifest(str(tmp_path))
    assert back.sig == "abc123"
    assert back.leg("r0.01").state == "done"
    assert [leg.key for leg in back.legs] == [leg.key for leg in m.legs]
    # flip one byte: the sealed manifest must refuse to load
    p = str(tmp_path / "manifest.json")
    with open(p, "r+b") as f:
        f.seek(10)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0x01]))
    with pytest.raises(IntegrityError):
        load_manifest(str(tmp_path))


# ---------------------------------------------------------------------------
# units: chaos grammar
# ---------------------------------------------------------------------------


def test_fault_plan_grammar():
    plan = parse_fault_plan("kill@0:2,corrupt@1:0,hang@2:0,stop@sort:0")
    assert [(f.kind, f.round, f.leg) for f in plan.faults] == \
        [("kill", 0, 2), ("corrupt", 1, 0), ("hang", 2, 0),
         ("stop", SORT_ROUND, 0)]
    # entries fire exactly once
    assert plan.take_dispatch(0, 2) == "kill"
    assert plan.take_dispatch(0, 2) is None
    assert not plan.take_stop(0, 2)
    assert plan.take_stop(SORT_ROUND, 0)
    with pytest.raises(ValueError):
        parse_fault_plan("nuke@0:0")
    with pytest.raises(ValueError):
        parse_fault_plan("kill@0")


def test_fault_plan_from_env(monkeypatch):
    from sheep_tpu.supervisor import plan_from_env

    monkeypatch.delenv("SHEEP_FAULT_PLAN", raising=False)
    assert plan_from_env() is None
    monkeypatch.setenv("SHEEP_FAULT_PLAN", "kill@0:1")
    plan = plan_from_env()
    assert plan is not None and plan.faults[0].kind == "kill"


# ---------------------------------------------------------------------------
# the fault-free supervised run equals the oracle
# ---------------------------------------------------------------------------


def test_supervised_matches_oracle(small_graph, tmp_path, capsys):
    graph, tail, head, seq, want = small_graph
    manifest, cfg = _run(graph, tmp_path / "s")
    parent, pst = read_tree(manifest.final_tree)
    np.testing.assert_array_equal(parent, want.parent)
    np.testing.assert_array_equal(pst, want.pst_weight)
    assert manifest.sig, "map legs must stamp the shared input signature"
    assert all(leg.dispatches == 1 for leg in manifest.legs)
    out = capsys.readouterr().out
    # the reference phase grammar survives supervision (make-parallel greps)
    assert re.search(r"Mapped in [0-9.]+ seconds\.", out)
    assert re.search(r"Reduced in [0-9.]+ seconds\.", out)


def test_supervised_with_given_sequence(small_graph, tmp_path):
    from sheep_tpu.io.seqfile import write_sequence

    graph, tail, head, seq, want = small_graph
    seq_path = str(tmp_path / "given.seq")
    write_sequence(seq, seq_path)
    cfg = _config()
    manifest = run_supervised(graph, str(tmp_path / "s"), cfg,
                              runner=InlineRunner(0.05), seq_file=seq_path)
    assert all(leg.kind != "sort" for leg in manifest.legs)
    parent, _ = read_tree(manifest.final_tree)
    np.testing.assert_array_equal(parent, want.parent)


def test_supervised_exports_out_file(small_graph, tmp_path):
    graph, tail, head, seq, want = small_graph
    out = str(tmp_path / "exported.tre")
    cfg = _config()
    run_supervised(graph, str(tmp_path / "s"), cfg,
                   runner=InlineRunner(0.05), out_file=out)
    parent, _ = read_tree(out)  # sidecar exported too: strict read passes
    np.testing.assert_array_equal(parent, want.parent)


# ---------------------------------------------------------------------------
# THE acceptance property: kill/corrupt/hang at every tournament round
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["kill", "corrupt", "hang"])
def test_fault_at_every_leg_is_bit_identical(small_graph, tmp_path, kind):
    graph, tail, head, seq, want = small_graph
    base_manifest, _ = _run(graph, tmp_path / "base")
    base_bytes = _final(base_manifest)
    ecv0 = _ecv_down(tail, head, seq, want)

    for rnd, leg in _all_legs():
        spec = f"{kind}@{'sort' if rnd == SORT_ROUND else rnd}:{leg}"
        manifest, cfg = _run(
            graph, tmp_path / f"{kind}-{rnd}-{leg}",
            chaos=parse_fault_plan(spec),
            # hang legs are declared dead by POLL-COUNT silence, not by
            # exit status — nor by a short wall deadline, which raced
            # the scheduler on loaded hosts (the chaos-sweep deflake)
            stale_after_polls=25 if kind == "hang" else 0)
        assert _final(manifest) == base_bytes, spec
        parent, pst = read_tree(manifest.final_tree)
        from sheep_tpu.core.forest import Forest
        assert _ecv_down(tail, head, seq, Forest(parent, pst)) == ecv0, spec
        # ONLY the faulted leg re-dispatched
        for m_leg in manifest.legs:
            expect = 2 if (m_leg.round, m_leg.index) == (rnd, leg) else 1
            assert m_leg.dispatches == expect, (spec, m_leg.key)
        if kind == "corrupt":
            assert any(e[0] == "leg-failed" and "fsck" in e[2]
                       for e in cfg.events), spec
        if kind == "hang":
            assert any(e[0] == "stale" for e in cfg.events), spec


# ---------------------------------------------------------------------------
# supervisor death + resume: only fsck-dirty legs re-dispatch
# ---------------------------------------------------------------------------


def test_supervisor_killed_at_every_leg_resumes(small_graph, tmp_path):
    graph, tail, head, seq, want = small_graph
    base_manifest, _ = _run(graph, tmp_path / "base")
    base_bytes = _final(base_manifest)

    for rnd, leg in _all_legs():
        sd = tmp_path / f"stop-{rnd}-{leg}"
        spec = f"stop@{'sort' if rnd == SORT_ROUND else rnd}:{leg}"
        with pytest.raises(SupervisorKilled):
            _run(graph, sd, chaos=parse_fault_plan(spec))
        pre = {m_leg.key: (m_leg.state, m_leg.dispatches)
               for m_leg in load_manifest(str(sd)).legs}
        assert pre[f"r{rnd}.{leg:02d}" if rnd != SORT_ROUND
                   else "sort"][0] == "done"
        manifest, cfg = _run(graph, sd)
        assert _final(manifest) == base_bytes, spec
        assert any(e[0] == "resume" for e in cfg.events)
        # a new supervisor re-dispatches exactly the legs that were not
        # provably complete — never a clean, fsck-passing survivor
        redone = {m_leg.key for m_leg in manifest.legs
                  if m_leg.dispatches > pre[m_leg.key][1]}
        not_done = {key for key, (state, _) in pre.items()
                    if state != "done"}
        assert redone == not_done, spec


def test_resume_redispatches_corrupt_survivor(small_graph, tmp_path):
    """The fsck-driven recovery criterion: after a supervisor crash, a
    corrupted surviving artifact is re-dispatched; every clean survivor
    is not."""
    graph, tail, head, seq, want = small_graph
    sd = tmp_path / "s"
    with pytest.raises(SupervisorKilled):
        _run(graph, sd, chaos=parse_fault_plan("stop@0:2"))
    mm = load_manifest(str(sd))
    victim = mm.leg("r0.00")
    assert victim.state == "done"
    with open(victim.output, "r+b") as f:
        f.seek(6)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    pre = {leg.key: (leg.state, leg.dispatches) for leg in mm.legs}

    manifest, cfg = _run(graph, sd)
    parent, _ = read_tree(manifest.final_tree)
    np.testing.assert_array_equal(parent, want.parent)
    redone = {leg.key for leg in manifest.legs
              if leg.dispatches > pre[leg.key][1]}
    not_done = {key for key, (state, _) in pre.items() if state != "done"}
    assert redone == not_done | {"r0.00"}
    resume = [e for e in cfg.events if e[0] == "resume"]
    assert resume and resume[0][2] == len(not_done | {"r0.00"})


def test_resume_skips_corrupt_artifact_nobody_needs(small_graph, tmp_path):
    """Corrupting a survivor whose consumers all finished must NOT trigger
    a re-map — the artifact is dead weight, not a dependency."""
    graph, tail, head, seq, want = small_graph
    sd = tmp_path / "s"
    with pytest.raises(SupervisorKilled):
        _run(graph, sd, chaos=parse_fault_plan("stop@1:0"))
    mm = load_manifest(str(sd))
    assert mm.leg("r1.00").state == "done"
    victim = mm.leg("r0.00")  # consumed by the already-done r1.00 only
    with open(victim.output, "r+b") as f:
        f.seek(6)
        b = f.read(1)
        f.seek(-1, 1)
        f.write(bytes([b[0] ^ 0xFF]))
    pre = {leg.key: leg.dispatches for leg in mm.legs}
    manifest, _ = _run(graph, sd)
    parent, _ = read_tree(manifest.final_tree)
    np.testing.assert_array_equal(parent, want.parent)
    assert manifest.leg("r0.00").dispatches == pre["r0.00"]


def test_resume_refuses_foreign_state_dir(small_graph, tmp_path):
    graph, tail, head, seq, want = small_graph
    sd = tmp_path / "s"
    _run(graph, sd)
    other = str(tmp_path / "other.net")
    t2, h2 = rmat_edges(6, 4 << 6, seed=99)
    write_net(other, t2, h2)
    with pytest.raises(SupervisionFailed, match="refusing to resume"):
        _run(other, sd)


# ---------------------------------------------------------------------------
# retry budget + speculation
# ---------------------------------------------------------------------------


def test_budget_exhaustion_fails_loudly(small_graph, tmp_path):
    graph, *_ = small_graph
    # kill the same leg on every dispatch: budget 1+1 spent -> loud failure
    chaos = parse_fault_plan(",".join(["kill@0:1"] * 2))
    with pytest.raises(SupervisionFailed, match="budget"):
        _run(graph, tmp_path / "s", chaos=chaos, max_retries=1)
    # the state dir survives for a later resume
    assert os.path.exists(str(tmp_path / "s" / "manifest.json"))


def test_speculation_first_finisher_wins(small_graph, tmp_path):
    """A straggler that still beats gets a speculative twin; the twin
    publishes, the straggler's late artifact is discarded."""
    graph, tail, head, seq, want = small_graph

    class StragglerRunner(InlineRunner):
        def start(self, argv, hb_path, log_path):
            if "1/4" in argv and any(a.endswith(".a1") for a in argv):
                # first dispatch of map leg 0: beats but never finishes
                # within the speculation threshold
                from sheep_tpu.supervisor.supervise import _ThreadHandle

                def target():
                    with HeartbeatWriter(hb_path, 0.02):
                        time.sleep(1.2)
                    return 1
                return _ThreadHandle(target)
            return super().start(argv, hb_path, log_path)

    cfg = _config(speculate_after_s=0.15, deadline_s=10.0)
    manifest = run_supervised(graph, str(tmp_path / "s"), cfg,
                              runner=StragglerRunner(0.05))
    parent, _ = read_tree(manifest.final_tree)
    np.testing.assert_array_equal(parent, want.parent)
    assert manifest.leg("r0.00").dispatches == 2
    kinds = [e[0] for e in cfg.events]
    assert "speculate" in kinds
    assert ("discard", "r0.00", "lost-race") in cfg.events


# ---------------------------------------------------------------------------
# production runner + shell integration smokes
# ---------------------------------------------------------------------------


def _cli_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_supervise_cli_subprocess_runner(small_graph, tmp_path):
    graph, tail, head, seq, want = small_graph
    out = str(tmp_path / "g.tre")
    proc = subprocess.run(
        ["python", "-m", "sheep_tpu.cli.supervise", graph, "-w", "2",
         "-d", str(tmp_path / "state"), "-o", out],
        capture_output=True, text=True, timeout=300, env=_cli_env(),
        cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "leg(s) complete" in proc.stdout
    parent, _ = read_tree(out)
    np.testing.assert_array_equal(parent, want.parent)
    # worker logs land in the state dir (operator surface)
    assert os.listdir(str(tmp_path / "state" / "logs"))


HEP = os.path.join(REPO, "data", "hep-th.dat")


@pytest.mark.skipif(not os.path.exists(HEP), reason="hep-th.dat not bundled")
def test_dist_partition_supervised_golden():
    """dist-partition.sh -S routes the file path through the supervisor
    and must reproduce the golden hep-th quality numbers."""
    proc = subprocess.run(
        ["bash", os.path.join(REPO, "scripts", "dist-partition.sh"),
         "-S", "-w", "2", "data/hep-th.dat", "2"],
        capture_output=True, text=True, timeout=600, env=_cli_env(),
        cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ECV(down): 521" in proc.stdout
    assert "leg(s) complete" in proc.stdout
    # the supervisor's phase grammar keeps the harness contract
    assert "Mapped in" in proc.stdout and "Reduced in" in proc.stdout


# ---------------------------------------------------------------------------
# bracket edge shapes: copy legs (odd widths) and the 1-worker degenerate
# ---------------------------------------------------------------------------


def test_odd_width_copy_leg_survives_corruption(small_graph, tmp_path):
    # W=3 R=2 leaves a single-input slot (a rename in the shell driver,
    # a "copy" leg here); corrupt its output — the supervisor must fsck,
    # discard, and re-copy, and the final tree must still match W=4's.
    graph, tail, head, seq, want = small_graph
    manifest, cfg = _run(graph, tmp_path / "s", workers=3,
                         chaos=parse_fault_plan("corrupt@1:1"))
    assert manifest.leg("r1.01").kind == "copy"
    assert manifest.leg("r1.01").dispatches == 2
    parent, pst = read_tree(manifest.final_tree)
    np.testing.assert_array_equal(parent, want.parent)
    np.testing.assert_array_equal(pst, want.pst_weight)


def test_single_worker_degenerates_to_one_map(small_graph, tmp_path):
    graph, tail, head, seq, want = small_graph
    manifest, _ = _run(graph, tmp_path / "s", workers=1)
    assert [leg.key for leg in manifest.legs] == ["sort", "r0.00"]
    assert manifest.leg("r0.00").output == manifest.final_tree
    parent, _ = read_tree(manifest.final_tree)
    np.testing.assert_array_equal(parent, want.parent)


def test_status_json_machine_readable(small_graph, tmp_path, capsys):
    """`sheep supervise --status --json` (ISSUE 6 satellite): one JSON
    object with leg states, dispatch counts, and budget headroom — the
    contract the serve daemon's liveness probe and outside monitors
    consume instead of scraping the operator table."""
    import json

    from sheep_tpu.cli.supervise import main as supervise_main
    from sheep_tpu.supervisor.status import status_json

    graph, tail, head, seq, want = small_graph
    manifest, _ = _run(graph, tmp_path / "s")

    rec = status_json(str(tmp_path / "s"))
    assert rec["done"] is True
    assert rec["legs_done"] == rec["legs_total"] == len(manifest.legs)
    assert rec["dispatches"] == sum(leg.dispatches for leg in manifest.legs)
    states = {leg["key"]: leg["state"] for leg in rec["legs"]}
    assert all(s == "done" for s in states.values())
    assert rec["disk"]["state_dir_bytes"] > 0
    assert rec["mem"]["rss_bytes"] > 0

    # the CLI face emits parseable JSON and exits 0
    capsys.readouterr()  # drop the supervised run's phase grammar
    rc = supervise_main(["--status", "--json", "-d", str(tmp_path / "s")])
    out = capsys.readouterr().out
    assert rc == 0
    parsed = json.loads(out)
    assert parsed["legs_total"] == rec["legs_total"]
    # --json outside --status is a usage error, not a silent ignore
    assert supervise_main(["--json", graph]) == 2
