"""Integrity-layer tests (ISSUE 2): sidecar checksums, hardened readers,
corruption fuzzing over every artifact class, repair-mode salvage, the
tiered validation oracles, merge-compatibility guards, the `sheep fsck`
CLI, and the corrupt-at-every-boundary runtime property.

The fuzz discipline: for each artifact class, corrupt every byte-region
class (header, record body, sidecar, npz member) and assert a typed
IntegrityError — NEVER silent acceptance of changed bytes.
"""

import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from sheep_tpu import INVALID_JNID
from sheep_tpu.core.forest import Forest, build_forest, merge_forests
from sheep_tpu.core.sequence import degree_sequence
from sheep_tpu.core.validate import check_forest_fast, is_valid_forest
from sheep_tpu.integrity import (ChecksumMismatch, IncompatibleMerge,
                                 IntegrityError, MalformedArtifact,
                                 fsck_paths, read_sidecar, sidecar_path,
                                 verify_bytes, write_sidecar)
from sheep_tpu.io import (load_edges, read_sequence, read_tree, write_edges,
                          write_sequence, write_tree)
from sheep_tpu.utils.synth import rmat_edges

pytestmark = [pytest.mark.faults, pytest.mark.fuzz]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _flip(path, offset, xor=0xFF):
    b = bytearray(open(path, "rb").read())
    b[offset % len(b)] ^= xor
    open(path, "wb").write(bytes(b))


def _truncate(path, nbytes):
    b = open(path, "rb").read()
    open(path, "wb").write(b[: max(0, len(b) - nbytes)])


@pytest.fixture
def small_forest():
    tail, head = rmat_edges(6, 4 << 6, seed=3)
    seq = degree_sequence(tail, head)
    forest = build_forest(tail, head, seq)
    return tail, head, seq, forest


# ---------------------------------------------------------------------------
# sidecar unit behavior
# ---------------------------------------------------------------------------


def test_sidecar_roundtrip_and_fields(tmp_path):
    p = str(tmp_path / "x.tre")
    write_tree(p, np.array([1, INVALID_JNID], np.uint32),
               np.array([0, 2], np.uint32), sig="f00d")
    sc = read_sidecar(p)
    assert sc is not None
    assert sc["version"] == 1
    assert sc["algo"] in ("crc32", "crc32c")
    assert sc["size"] == os.path.getsize(p)
    assert sc["sig"] == "f00d"
    assert verify_bytes(p, open(p, "rb").read()) == "ok"


def test_missing_sidecar_is_accepted_but_reported(tmp_path):
    # foreign files carry no sidecars; strict must still read them
    p = str(tmp_path / "foreign.tre")
    write_tree(p, np.array([INVALID_JNID], np.uint32),
               np.array([1], np.uint32))
    os.unlink(sidecar_path(p))
    read_tree(p)  # no raise
    assert verify_bytes(p, open(p, "rb").read()) == "no-sidecar"


def test_corrupt_sidecar_never_silently_vouches(tmp_path):
    p = str(tmp_path / "x.seq")
    write_sequence(np.arange(9, dtype=np.uint32), p)
    with open(sidecar_path(p), "wb") as f:
        f.write(b"\x00\xffgarbage not a sidecar")
    with pytest.raises(MalformedArtifact, match="sidecar"):
        read_sequence(p)
    # repair degrades to structural-only checks with a warning
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        got = read_sequence(p, integrity="repair")
    np.testing.assert_array_equal(got, np.arange(9))
    assert any("sidecar" in str(x.message) for x in w)


def test_trust_mode_skips_checksums(tmp_path):
    p = str(tmp_path / "x.seq")
    write_sequence(np.array([3, 1, 2], np.uint32), p)
    # poison the sidecar: trust mode must not even look at it
    with open(sidecar_path(p), "w") as f:
        f.write("sheep-sum 1\nalgo crc32\nsize 1\nsum 00000000\n")
    with pytest.raises(ChecksumMismatch):
        read_sequence(p)
    np.testing.assert_array_equal(read_sequence(p, integrity="trust"),
                                  [3, 1, 2])


# ---------------------------------------------------------------------------
# corruption fuzz: every artifact class x every byte-region class
# ---------------------------------------------------------------------------


def _write_artifacts(d, tail, head, seq, forest):
    paths = {}
    paths[".tre"] = str(d / "a.tre")
    write_tree(paths[".tre"], forest.parent, forest.pst_weight, sig="s1")
    paths[".seq"] = str(d / "a.seq")
    write_sequence(seq, paths[".seq"])
    paths[".seqb"] = str(d / "b.seq")
    write_sequence(seq, paths[".seqb"], binary=True)
    paths[".dat"] = str(d / "a.dat")
    write_edges(paths[".dat"], tail, head)
    paths[".net"] = str(d / "a.net")
    write_edges(paths[".net"], tail, head)
    return paths


def _read_artifact(suffix, path):
    if suffix == ".tre":
        return read_tree(path)
    if suffix == ".seq":
        return read_sequence(path)
    if suffix == ".seqb":
        return read_sequence(path, binary=True)
    return load_edges(path)


def _corrupt_sidecar_sum(p):
    """Deterministically flip one hex digit of the recorded checksum."""
    sc = sidecar_path(p)
    lines = open(sc).read().splitlines()
    for i, ln in enumerate(lines):
        if ln.startswith("sum "):
            digit = ln[4]
            lines[i] = "sum " + ("0" if digit != "0" else "1") + ln[5:]
            break
    open(sc, "w").write("\n".join(lines) + "\n")


@pytest.mark.parametrize("suffix", [".tre", ".seq", ".seqb", ".dat", ".net"])
@pytest.mark.parametrize("region", ["header", "body", "tail-truncate",
                                    "sidecar"])
def test_fuzz_corruption_is_always_detected(tmp_path, small_forest,
                                            suffix, region):
    """Flip/truncate each byte-region class of each artifact class and
    assert strict mode raises a typed IntegrityError — never silent
    acceptance of changed bytes."""
    tail, head, seq, forest = small_forest
    paths = _write_artifacts(tmp_path, tail, head, seq, forest)
    p = paths[suffix]
    _read_artifact(suffix, p)  # clean read passes
    if region == "header":
        _flip(p, 1)
    elif region == "body":
        _flip(p, os.path.getsize(p) // 2)
    elif region == "tail-truncate":
        _truncate(p, 3)
    elif region == "sidecar":
        _corrupt_sidecar_sum(p)
    with pytest.raises(IntegrityError):
        _read_artifact(suffix, p)


@pytest.mark.parametrize("member_byte", [30, 200, 999])
def test_fuzz_snapshot_member_corruption_detected(tmp_path, member_byte):
    from sheep_tpu.runtime.snapshot import (Checkpointer, Snapshot,
                                            input_signature, load_snapshot)

    seq = np.arange(32, dtype=np.uint32)
    sig = input_signature(32, seq)
    ck = Checkpointer(str(tmp_path))
    ck.save(Snapshot(n=32, seq=seq, pst=np.ones(32, np.uint32),
                     lo=np.arange(8, dtype=np.int32),
                     hi=np.arange(8, 16, dtype=np.int32),
                     rounds=2, boundary=0, rung="single", input_sig=sig))
    load_snapshot(ck.path)  # clean loads
    _flip(ck.path, member_byte)
    with pytest.raises(IntegrityError):
        load_snapshot(ck.path)
    # even WITHOUT the sidecar, the zip/structural layers must catch it
    os.unlink(sidecar_path(ck.path))
    with pytest.raises(IntegrityError):
        load_snapshot(ck.path)


def test_snapshot_missing_member_detected(tmp_path):
    import zipfile

    from sheep_tpu.runtime.snapshot import load_snapshot

    p = str(tmp_path / "sheep-ckpt.npz")
    with open(p, "wb") as f:
        np.savez(f, version=np.int64(1), n=np.int64(4))  # most members gone
    with pytest.raises(MalformedArtifact, match="corrupt snapshot"):
        load_snapshot(p)


def test_snapshot_structural_lies_detected(tmp_path):
    from sheep_tpu.runtime.snapshot import Checkpointer, Snapshot

    seq = np.arange(8, dtype=np.uint32)
    bad = Snapshot(n=8, seq=seq, pst=np.ones(8, np.uint32),
                   lo=np.array([5], np.int32), hi=np.array([3], np.int32),
                   rounds=0, boundary=0, rung="single", input_sig="x")
    with pytest.raises(MalformedArtifact, match="lo < hi"):
        Checkpointer(str(tmp_path)).save(bad)  # refused BEFORE durable


# ---------------------------------------------------------------------------
# hardened parsers: the specific lies named in the issue
# ---------------------------------------------------------------------------


def test_tre_end_id_lies(tmp_path, small_forest):
    _, _, _, forest = small_forest
    p = str(tmp_path / "t.tre")
    write_tree(p, forest.parent, forest.pst_weight)
    raw = bytearray(open(p, "rb").read())
    raw[0:4] = np.uint32(len(forest.parent) + 9).tobytes()  # claim more
    open(p, "wb").write(bytes(raw))
    os.unlink(sidecar_path(p))  # force the structural layer to catch it
    with pytest.raises(MalformedArtifact, match="end_id"):
        read_tree(p)


def test_tre_non_monotone_parent_rejected(tmp_path):
    p = str(tmp_path / "t.tre")
    # node 2 claims parent 1 (earlier) — a cycle-capable corruption that
    # stays in range, so only the monotonicity check can see it
    write_tree(p, np.array([2, 2, 1], np.uint32), np.zeros(3, np.uint32))
    with pytest.raises(MalformedArtifact, match="strictly later"):
        read_tree(p)


def test_dat_length_not_multiple_of_12(tmp_path):
    p = str(tmp_path / "g.dat")
    write_edges(p, np.array([1], np.uint32), np.array([2], np.uint32))
    os.unlink(sidecar_path(p))
    with open(p, "ab") as f:
        f.write(b"\x01\x02\x03")
    with pytest.raises(MalformedArtifact, match="multiple"):
        load_edges(p)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        el = load_edges(p, integrity="repair")  # drops the torn record
    assert el.num_edges == 1


def test_net_non_integer_tokens(tmp_path):
    p = str(tmp_path / "g.net")
    p_ = open(p, "w")
    p_.write("1 2\n3 four\n5 6\n")
    p_.close()
    with pytest.raises(MalformedArtifact, match="non-integer"):
        load_edges(p)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        el = load_edges(p, integrity="repair")
    np.testing.assert_array_equal(el.tail, [1, 5])
    np.testing.assert_array_equal(el.head, [2, 6])


def test_net_out_of_range_vid(tmp_path):
    p = str(tmp_path / "g.net")
    with open(p, "w") as f:
        f.write(f"1 {1 << 33}\n")
    with pytest.raises(MalformedArtifact, match="uint32"):
        load_edges(p)


def test_seq_binary_text_confusion(tmp_path):
    seq = np.array([7, 0, 3, 1], np.uint32)
    pt = str(tmp_path / "t.seq")
    pb = str(tmp_path / "b.seq")
    write_sequence(seq, pt, binary=False)
    write_sequence(seq, pb, binary=True)
    with pytest.raises(MalformedArtifact, match="BINARY"):
        read_sequence(pb, binary=False)
    with pytest.raises(MalformedArtifact, match="TEXT"):
        read_sequence(pt, binary=True)
    # auto sniff reads both correctly (the fsck path)
    np.testing.assert_array_equal(read_sequence(pt, binary="auto"), seq)
    np.testing.assert_array_equal(read_sequence(pb, binary="auto"), seq)


def test_repair_net_yields_subset_of_clean_multiset(tmp_path):
    """Property (seeded trials, no hypothesis in this container): under
    token-invalidating byte damage, repair-mode .net parsing yields a
    sub-multiset of the clean edge multiset — corruption can only REMOVE
    edges, never invent or rewire them."""
    rng = np.random.default_rng(42)
    tail = rng.integers(0, 97, 300).astype(np.uint32)
    head = rng.integers(0, 97, 300).astype(np.uint32)
    p = str(tmp_path / "g.net")
    write_edges(p, tail, head)
    clean_bytes = open(p, "rb").read()

    def multiset(t, h):
        from collections import Counter
        return Counter(zip(t.tolist(), h.tolist()))

    clean = multiset(tail, head)
    garbage = np.frombuffer(b"@!x#\xff\x00ZQ~", dtype=np.uint8)
    for trial in range(12):
        raw = bytearray(clean_bytes)
        for _ in range(int(rng.integers(1, 8))):
            at = int(rng.integers(0, len(raw)))
            span = int(rng.integers(1, 6))
            for i in range(at, min(at + span, len(raw))):
                raw[i] = int(garbage[int(rng.integers(0, len(garbage)))])
        open(p, "wb").write(bytes(raw))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            el = load_edges(p, integrity="repair")
        got = multiset(el.tail, el.head)
        assert not got - clean, \
            f"trial {trial}: repair invented edges {got - clean}"


# ---------------------------------------------------------------------------
# tiered oracles
# ---------------------------------------------------------------------------


def test_fast_oracle_accepts_valid_and_names_problems(small_forest):
    tail, head, seq, forest = small_forest
    from sheep_tpu.core.forest import edges_to_positions
    lo, hi = edges_to_positions(tail, head, seq)
    assert check_forest_fast(forest, lo, hi) == []

    bad = forest.copy()
    bad.parent[5] = 2  # earlier than 5: monotonicity
    assert any("strictly later" in p for p in check_forest_fast(bad))

    bad = forest.copy()
    bad.parent[0] = len(bad.parent) + 7  # out of range
    assert any("out of range" in p for p in check_forest_fast(bad))

    bad = forest.copy()
    bad.pst_weight = bad.pst_weight.copy()
    bad.pst_weight[1] += 1  # breaks conservation + histogram
    assert check_forest_fast(bad, lo, hi)


@pytest.mark.parametrize("corrupt", [False, True])
def test_exact_oracle_lifted_agrees_with_loop(small_forest, corrupt):
    tail, head, seq, forest = small_forest
    f = forest.copy()
    if corrupt:
        # sever one link: the edge that CREATED parent[j] loses its root
        # path (paths are unique in a forest), so the forest is provably
        # invalid while every fast-tier invariant still holds — only the
        # exact walk can see it
        linked = np.flatnonzero(f.parent != INVALID_JNID)
        j = int(linked[len(linked) // 2])
        f.parent[j] = INVALID_JNID
    got_lifted = is_valid_forest(f, tail, head, seq, exact="lifted")
    got_loop = is_valid_forest(f, tail, head, seq, exact="loop")
    assert got_lifted == got_loop
    assert got_lifted == (not corrupt)


def test_exact_oracle_randomized_agreement():
    rng = np.random.default_rng(7)
    for trial in range(6):
        tail, head = rmat_edges(7, 3 << 7, seed=100 + trial)
        seq = degree_sequence(tail, head)
        forest = build_forest(tail, head, seq)
        assert is_valid_forest(forest, tail, head, seq, exact="lifted")
        assert is_valid_forest(forest, tail, head, seq, exact="loop")
        # random single-pointer corruption: both walkers must agree
        f = forest.copy()
        linked = np.flatnonzero(f.parent != INVALID_JNID)
        if len(linked):
            j = int(rng.choice(linked))
            new_parent = int(rng.integers(j + 1, f.n))
            f.parent[j] = new_parent
            assert is_valid_forest(f, tail, head, seq, exact="lifted") == \
                is_valid_forest(f, tail, head, seq, exact="loop"), trial


def test_validate_loop_env_flag(small_forest, monkeypatch):
    tail, head, seq, forest = small_forest
    monkeypatch.setenv("SHEEP_VALIDATE_LOOP", "1")
    assert is_valid_forest(forest, tail, head, seq)


# ---------------------------------------------------------------------------
# merge-compatibility guards
# ---------------------------------------------------------------------------


def test_merge_forests_refuses_length_mismatch():
    a = Forest(np.array([INVALID_JNID], np.uint32), np.zeros(1, np.uint32))
    b = Forest(np.full(2, INVALID_JNID, np.uint32), np.zeros(2, np.uint32))
    with pytest.raises(IncompatibleMerge, match="differing length"):
        merge_forests(a, b)


def _run_cli(mod, *args, env_extra=None):
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""), JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.run([sys.executable, "-m", mod] + list(args),
                          capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=300)


def test_merge_trees_cli_refuses_mismatched_inputs(tmp_path, small_forest):
    _, _, _, forest = small_forest
    a = str(tmp_path / "a.tre")
    b = str(tmp_path / "b.tre")
    write_tree(a, forest.parent, forest.pst_weight, sig="sig-one")
    # a VALID tree of a different length (the guard, not the parser,
    # must be what refuses it)
    write_tree(b, np.array([1, INVALID_JNID], np.uint32),
               np.array([1, 0], np.uint32))
    out = str(tmp_path / "m.tre")
    r = _run_cli("sheep_tpu.cli.merge_trees", a, b, "-o", out)
    assert r.returncode == 1
    assert "differing" in r.stderr or "node count" in r.stderr
    assert not os.path.exists(out)

    # same length, clashing sidecar signatures
    c = str(tmp_path / "c.tre")
    write_tree(c, forest.parent, forest.pst_weight, sig="sig-two")
    r = _run_cli("sheep_tpu.cli.merge_trees", a, c, "-o", out)
    assert r.returncode == 1
    assert "signature" in r.stderr
    assert not os.path.exists(out)

    # matching signatures merge fine and stamp the sig onward
    d = str(tmp_path / "d.tre")
    write_tree(d, forest.parent, forest.pst_weight, sig="sig-one")
    r = _run_cli("sheep_tpu.cli.merge_trees", a, d, "-o", out)
    assert r.returncode == 0, r.stdout + r.stderr
    assert read_sidecar(out)["sig"] == "sig-one"


# ---------------------------------------------------------------------------
# sheep fsck
# ---------------------------------------------------------------------------


def test_fsck_clean_dir_exits_zero(tmp_path, small_forest):
    tail, head, seq, forest = small_forest
    _write_artifacts(tmp_path, tail, head, seq, forest)
    r = _run_cli("sheep_tpu.cli.fsck", str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 bad" in r.stdout


@pytest.mark.parametrize("victim", [".tre", ".seq", ".seqb", ".dat", ".net"])
def test_fsck_detects_each_fuzzed_class(tmp_path, small_forest, victim):
    tail, head, seq, forest = small_forest
    paths = _write_artifacts(tmp_path, tail, head, seq, forest)
    _flip(paths[victim], os.path.getsize(paths[victim]) // 2)
    r = _run_cli("sheep_tpu.cli.fsck", "-q", str(tmp_path))
    assert r.returncode == 1, r.stdout + r.stderr
    assert "FAIL" in r.stdout
    assert os.path.basename(paths[victim]) in r.stdout


def test_fsck_snapshot_and_usage(tmp_path):
    from sheep_tpu.runtime.snapshot import (Checkpointer, Snapshot,
                                            input_signature)

    seq = np.arange(16, dtype=np.uint32)
    ck = Checkpointer(str(tmp_path))
    ck.save(Snapshot(n=16, seq=seq, pst=np.zeros(16, np.uint32),
                     lo=np.empty(0, np.int32), hi=np.empty(0, np.int32),
                     rounds=0, boundary=0, rung="host",
                     input_sig=input_signature(16, seq)))
    r = _run_cli("sheep_tpu.cli.fsck", ck.path)
    assert r.returncode == 0, r.stdout + r.stderr
    _flip(ck.path, 77)
    r = _run_cli("sheep_tpu.cli.fsck", ck.path)
    assert r.returncode == 1
    r = _run_cli("sheep_tpu.cli.fsck")
    assert r.returncode == 2  # usage
    r = _run_cli("sheep_tpu.cli.fsck", "-m", "bogus", ck.path)
    assert r.returncode == 2


def test_fsck_seed_data_artifacts_clean():
    """Acceptance: fsck exits zero on the repo's own seed artifacts
    (no sidecars there — structural checks only)."""
    r = _run_cli("sheep_tpu.cli.fsck", "-q",
                 os.path.join(REPO, "data", "hep-th.dat"))
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# runtime: corrupt-at-every-boundary (the acceptance property)
# ---------------------------------------------------------------------------


def test_corrupt_snapshot_at_every_boundary(tmp_path):
    """Kill the build at EVERY chunk boundary, bit-flip the snapshot it
    left, then resume: strict policy rejects with a typed IntegrityError;
    repair policy discards the corrupt checkpoint, rebuilds fresh, and
    the final tree is bit-identical with identical ECV(down)."""
    from sheep_tpu.runtime import (BuildKilled, FaultPlan, RuntimeConfig,
                                   build_graph_resilient, clear_plan,
                                   install_plan)
    from sheep_tpu.runtime.snapshot import SNAPSHOT_NAME

    tail, head = rmat_edges(9, 4 << 9, seed=11)

    def _build(d, resume=False, integrity=None):
        cfg = RuntimeConfig(checkpoint_dir=d, resume=resume,
                            ladder=("single", "host"), backoff_base_s=0.0,
                            integrity=integrity)
        seq, forest = build_graph_resilient(tail, head, config=cfg)
        return seq, forest, cfg

    def _ecv(seq, forest):
        from sheep_tpu.partition.evaluate import evaluate_partition
        from sheep_tpu.partition.partition import Partition
        p = Partition.from_forest(seq, forest, 2)
        return evaluate_partition(p.parts, tail, head, seq,
                                  p.num_parts).ecv_down

    seq0, forest0, cfg0 = _build(str(tmp_path / "base"))
    ecv0 = _ecv(seq0, forest0)
    boundaries = [e for e in cfg0.events if e[0] == "checkpoint"]
    assert len(boundaries) >= 3

    for k in range(len(boundaries)):
        d = str(tmp_path / f"cor{k}")
        install_plan(FaultPlan(site="boundary", at=k, kind="kill"))
        with pytest.raises(BuildKilled):
            _build(d)
        clear_plan()
        snap_path = os.path.join(d, SNAPSHOT_NAME)
        assert os.path.exists(snap_path), k
        _flip(snap_path, 64 + 13 * k)

        # strict: detected, refused
        with pytest.raises(IntegrityError):
            _build(d, resume=True, integrity="strict")

        # repair: detected, discarded, rebuilt fresh — bit-identical
        seq1, forest1, cfg1 = _build(d, resume=True, integrity="repair")
        assert any(e[0] == "corrupt-checkpoint" for e in cfg1.events), k
        np.testing.assert_array_equal(seq1, seq0, err_msg=str(k))
        np.testing.assert_array_equal(forest1.parent, forest0.parent,
                                      err_msg=f"corrupt at boundary {k}")
        np.testing.assert_array_equal(forest1.pst_weight,
                                      forest0.pst_weight,
                                      err_msg=f"corrupt at boundary {k}")
        assert _ecv(seq1, forest1) == ecv0, k


def test_checkpoint_clear_removes_sidecar(tmp_path):
    from sheep_tpu.runtime.snapshot import (Checkpointer, Snapshot,
                                            input_signature)

    seq = np.arange(4, dtype=np.uint32)
    ck = Checkpointer(str(tmp_path))
    ck.save(Snapshot(n=4, seq=seq, pst=np.zeros(4, np.uint32),
                     lo=np.empty(0, np.int32), hi=np.empty(0, np.int32),
                     rounds=0, boundary=0, rung="host",
                     input_sig=input_signature(4, seq)))
    assert os.path.exists(sidecar_path(ck.path))
    ck.clear()
    assert os.listdir(tmp_path) == []


def test_fsck_paths_api(tmp_path, small_forest):
    tail, head, seq, forest = small_forest
    paths = _write_artifacts(tmp_path, tail, head, seq, forest)
    results, failures = fsck_paths([str(tmp_path)])
    assert len(results) == len(paths) and not failures
    _truncate(paths[".tre"], 5)
    results, failures = fsck_paths([str(tmp_path)])
    assert len(failures) == 1 and failures[0][0] == paths[".tre"]


# ---------------------------------------------------------------------------
# fsck --repair-sidecar (ISSUE 3 satellite): reseal lost/wrong sidecars
# ---------------------------------------------------------------------------


def test_repair_sidecar_lost(tmp_path, small_forest):
    from sheep_tpu.cli.fsck import main as fsck_main
    from sheep_tpu.integrity.sidecar import verify_file

    tail, head, seq, forest = small_forest
    p = str(tmp_path / "t.tre")
    write_tree(p, forest.parent, forest.pst_weight, sig="feedc0de")
    os.unlink(sidecar_path(p))
    assert fsck_main(["-R", p]) == 0
    sc = read_sidecar(p)
    assert sc is not None
    assert verify_file(p, "strict") == "ok"
    # a reseal can never re-derive the build tie: sig is dropped
    assert "sig" not in sc


def test_repair_sidecar_wrong(tmp_path, small_forest):
    from sheep_tpu.cli.fsck import main as fsck_main
    from sheep_tpu.integrity.sidecar import verify_file

    tail, head, seq, forest = small_forest
    p = str(tmp_path / "t.tre")
    write_tree(p, forest.parent, forest.pst_weight)
    # the crash window: artifact renamed, stale sidecar left behind
    import re as _re
    txt = open(sidecar_path(p)).read()
    open(sidecar_path(p), "w").write(
        _re.sub(r"^sum .*$", "sum 00000001", txt, flags=_re.M))
    assert fsck_main([p]) == 1          # plain fsck refuses
    assert fsck_main(["-R", p]) == 0    # reseal verifies + reseals
    assert verify_file(p, "strict") == "ok"
    assert fsck_main([p]) == 0


def test_repair_sidecar_refuses_garbage(tmp_path):
    from sheep_tpu.cli.fsck import main as fsck_main
    from sheep_tpu.integrity.fsck import repair_sidecar

    p = str(tmp_path / "t.tre")
    with open(p, "wb") as f:
        f.write(b"\x01\x02")  # too short for the end_id header
    assert fsck_main(["-R", p]) == 1
    assert not os.path.exists(sidecar_path(p))  # never vouches for garbage
    with pytest.raises(IntegrityError):
        repair_sidecar(p)


def test_repair_sidecar_unknown_class(tmp_path):
    from sheep_tpu.integrity.fsck import repair_sidecar

    p = str(tmp_path / "t.xyz")
    with open(p, "wb") as f:
        f.write(b"bytes")
    with pytest.raises(MalformedArtifact, match="nothing to reseal"):
        repair_sidecar(p)


def test_repair_sidecar_resealed_tree_still_merges(tmp_path, small_forest):
    # a resealed tree re-enters merges as a foreign (sig-less) input —
    # merge compatibility must accept it against a signed partner
    from sheep_tpu.cli.fsck import main as fsck_main
    from sheep_tpu.cli.merge_trees import main as merge_main

    tail, head, seq, forest = small_forest
    half = len(tail) // 2
    f1 = build_forest(tail[:half], head[:half], seq)
    f2 = build_forest(tail[half:], head[half:], seq)
    p1, p2 = str(tmp_path / "a.tre"), str(tmp_path / "b.tre")
    write_tree(p1, f1.parent, f1.pst_weight, sig="s1")
    write_tree(p2, f2.parent, f2.pst_weight, sig="s1")
    os.unlink(sidecar_path(p2))
    assert fsck_main(["-R", "-q", p2]) == 0
    out = str(tmp_path / "m.tre")
    assert merge_main([p1, p2, "-o", out]) == 0
    merged = Forest(*read_tree(out))
    want = merge_forests(f1, f2)
    np.testing.assert_array_equal(merged.parent, want.parent)


# ---------------------------------------------------------------------------
# .net block-stream verification (ISSUE 3 satellite): like the .dat path
# ---------------------------------------------------------------------------


def _net_blocks_all(path, **kw):
    from sheep_tpu.io.edges import iter_net_blocks

    pairs = list(iter_net_blocks(path, **kw))
    if not pairs:
        return (np.empty(0, np.uint32),) * 2
    return (np.concatenate([t for t, _ in pairs]),
            np.concatenate([h for _, h in pairs]))


def test_net_stream_verify_clean(tmp_path):
    from sheep_tpu.io.edges import write_net

    p = str(tmp_path / "g.net")
    t = np.arange(200, dtype=np.uint32)
    h = (t * 7 + 1) % 301
    write_net(p, t, h.astype(np.uint32))
    tt, hh = _net_blocks_all(p, block_bytes=32)  # tiny blocks: carry path
    np.testing.assert_array_equal(tt, t)
    np.testing.assert_array_equal(hh, h)


def test_net_stream_verify_detects_flip_at_end(tmp_path):
    from sheep_tpu.io.edges import write_net

    p = str(tmp_path / "g.net")
    t = np.arange(200, dtype=np.uint32)
    write_net(p, t, (t + 1).astype(np.uint32))
    # flip a digit to another digit: every block still PARSES, only the
    # end-of-stream checksum can catch it
    with open(p, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(b"7" if b != b"7" else b"8")
    with pytest.raises(ChecksumMismatch, match="end of stream"):
        _net_blocks_all(p, block_bytes=32)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        _net_blocks_all(p, block_bytes=32, integrity="repair")
    assert any("checksum mismatch" in str(x.message) for x in w)
    _net_blocks_all(p, block_bytes=32, integrity="trust")  # no raise


def test_net_stream_verify_size_mismatch_up_front(tmp_path):
    from sheep_tpu.io.edges import iter_net_blocks, write_net

    p = str(tmp_path / "g.net")
    t = np.arange(50, dtype=np.uint32)
    write_net(p, t, (t + 1).astype(np.uint32))
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) - 4)
    with pytest.raises(ChecksumMismatch, match="size"):
        next(iter_net_blocks(p, block_bytes=32))


def test_net_stream_no_sidecar_still_parses(tmp_path):
    from sheep_tpu.io.edges import write_net

    p = str(tmp_path / "g.net")
    t = np.arange(50, dtype=np.uint32)
    write_net(p, t, (t + 1).astype(np.uint32))
    os.unlink(sidecar_path(p))  # foreign file: no sidecar, no verification
    tt, hh = _net_blocks_all(p, block_bytes=32)
    np.testing.assert_array_equal(tt, t)
