"""Unit tests for bench.py's sweep loop fault semantics.

The sweep runs unattended inside the watcher's one hardware window per
round; a wrong continue/stop decision silently costs the round's gating
artifact (round-4 lesson: the first TPU window's sweep died at 2^16
because a timeout that had only cut the secondary path was treated as a
sweep-ending fault).  run_child is injected, so no jax and no
subprocesses here.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rec(log_n, eps=1000.0):
    return json.dumps({"log_n": log_n, "edges_per_sec": eps,
                       "rounds": 0, "best_s": 1.0})


def test_clean_sweep(bench):
    child = lambda log_n: (_rec(log_n), "", 0, None)
    sweep, fault = bench.run_sweep([16, 18], child, 100, 30)
    assert fault is None
    assert [r["log_n"] for r in sweep] == [16, 18]
    assert not any(r.get("partial") for r in sweep)


def test_timeout_with_headline_record_continues(bench):
    # the round-4 window-1 shape: per-size timeout fires AFTER the
    # headline path streamed its record -> keep the size, keep sweeping
    def child(log_n):
        if log_n == 16:
            return (_rec(16), "", None, "timeout")
        return (_rec(log_n), "", 0, None)

    sweep, fault = bench.run_sweep([16, 18, 20], child, 100, 30)
    assert fault is None
    assert [r["log_n"] for r in sweep] == [16, 18, 20]
    assert sweep[0]["partial"] and not sweep[1].get("partial")


def test_timeout_without_record_stops(bench):
    child = lambda log_n: ("", "", None, "timeout")
    sweep, fault = bench.run_sweep([16, 18], child, 100, 30)
    assert sweep == []
    assert fault == {"log_n": 16, "error": "timeout"}


def test_backend_hang_stops_even_with_record(bench):
    # backend_hang means the child never got past init: any stdout is
    # stale/foreign, and later sizes would hang the same way
    calls = []

    def child(log_n):
        calls.append(log_n)
        return (_rec(log_n), "", None, "backend_hang")

    sweep, fault = bench.run_sweep([16, 18], child, 100, 30)
    assert fault == {"log_n": 16, "error": "backend_hang"}
    assert calls == [16]
    # the salvaged record is kept for coverage but marked partial
    assert [r.get("partial") for r in sweep] == [True]


def test_crash_keeps_salvage_and_stops(bench):
    child = lambda log_n: (_rec(log_n), "boom\ndied horribly", 1, None)
    sweep, fault = bench.run_sweep([16, 18], child, 100, 30)
    assert fault["log_n"] == 16 and "died horribly" in fault["error"]
    assert [r.get("partial") for r in sweep] == [True]


def test_unparseable_output_stops(bench):
    child = lambda log_n: ("not json at all", "", 0, None)
    sweep, fault = bench.run_sweep([16], child, 100, 30)
    assert sweep == []
    assert fault == {"log_n": 16, "error": "unparseable child output"}


def test_checkpoint_called_per_record(bench):
    seen = []
    child = lambda log_n: (_rec(log_n), "", 0, None)
    bench.run_sweep([16, 18], child, 100, 30,
                    checkpoint=lambda s: seen.append(len(s)))
    assert seen == [1, 2]


def test_wanted_paths_defaults_and_validation(bench, monkeypatch):
    monkeypatch.delenv("SHEEP_BENCH_PATHS", raising=False)
    assert bench._wanted_paths() is None  # deferred until platform known
    assert bench._wanted_paths("cpu") == ["hybrid", "device", "host"]
    assert bench._wanted_paths("tpu") == ["hybrid", "host"]
    monkeypatch.setenv("SHEEP_BENCH_PATHS", "device")
    assert bench._wanted_paths() == ["device"]
    assert bench._wanted_paths("tpu") == ["device"]  # explicit wins
    monkeypatch.setenv("SHEEP_BENCH_PATHS", "host")  # no headline path
    with pytest.raises(SystemExit):
        bench._wanted_paths()
    monkeypatch.setenv("SHEEP_BENCH_PATHS", "Hybrid")  # case typo
    with pytest.raises(SystemExit):
        bench._wanted_paths("cpu")


def test_last_record_picks_newest_record_line(bench):
    out = "\n".join(["garbage", _rec(16, 1.0), "noise", _rec(16, 2.0),
                     json.dumps({"no_eps": True})])
    assert bench.last_record(out)["edges_per_sec"] == 2.0
    assert bench.last_record(b"") is None
    assert bench.last_record(None) is None


def test_last_onchip_pointer_picks_newest_clean_record(tmp_path):
    """VERDICT r04 item 5: the CPU-fallback record must point at the
    newest committed on-chip sweep — skipping cpu_fallback-tagged and
    _partial records — without ever substituting it into `value`."""
    import json

    import bench

    def w(name, rec):
        (tmp_path / name).write_text(json.dumps(rec) + "\n")

    w("TPU_BENCH_r03.json", {"metric": "device_build_edges_per_sec_x",
                             "value": 1.0, "unit": "edges/sec",
                             "vs_baseline": 0.01,
                             "_utc": "2026-07-30T00:00:00Z"})
    w("TPU_BENCH_r04.json", {"metric": "device_build_edges_per_sec_y",
                             "value": 2.0, "unit": "edges/sec",
                             "vs_baseline": 0.02,
                             "_utc": "2026-07-31T00:00:00Z"})
    # newer but disqualified records must lose
    w("TPU_BENCH_r05.json", {"metric": "device_build_edges_per_sec_cpu_fallback",
                             "value": 9.0, "unit": "edges/sec",
                             "vs_baseline": 0.09,
                             "_utc": "2026-07-31T10:00:00Z"})
    w("TPU_BENCH_r05b.json", {"metric": "device_build_edges_per_sec_z",
                              "value": 9.0, "unit": "edges/sec",
                              "vs_baseline": 0.09, "_partial": True,
                              "_utc": "2026-07-31T11:00:00Z"})
    p = bench._last_onchip_pointer(str(tmp_path))
    assert p is not None
    assert p["value"] == 2.0 and p["source"] == "TPU_BENCH_r04.json"
    assert "NOT this run's measurement" in p["note"]


def test_last_onchip_pointer_empty_dir(tmp_path):
    import bench

    assert bench._last_onchip_pointer(str(tmp_path)) is None
