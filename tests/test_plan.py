"""The history-learning planner (ISSUE 15): the Plan object, the prior
store, and the provenance contract.

Covered here: parity (with no prior store, plan_build reproduces the
governor's pre-planner choices for every budget shape), forced knobs
winning with ``forced`` provenance across the knob surface, the
demonstrated history-corrected decision (a mispriced rung/ext block
fixed by a synthetic prior store, asserted end-to-end through the
driver), prior-store roundtrip + corruption tolerance, harvesting
through ROTATED trace segment chains with a torn newest segment (the
kill -9 shape) and a rotten mid-chain segment, the ``sheep plan``
CLI's determinism and harvest mode, and the enriched ``ladder.plan``
event the store learns from."""

import json
import os

import numpy as np
import pytest

import sheep_tpu.resources.governor as G
from sheep_tpu.plan import (MIN_CORRECT_SAMPLES, PriorStore,
                            available_rungs, plan_build,
                            plan_distext_legs, prior_key, scale_bucket)

pytestmark = pytest.mark.filterwarnings("ignore::UserWarning")


@pytest.fixture
def plan_env(monkeypatch):
    for k in ("SHEEP_MEM_BUDGET", "SHEEP_DISK_BUDGET", "SHEEP_EXT_BLOCK",
              "SHEEP_NATIVE_THREADS", "SHEEP_LEG_CORES",
              "SHEEP_DISTEXT_LEGS", "SHEEP_HANDOFF_WINDOWS",
              "SHEEP_PIPELINE_CHUNKS", "SHEEP_PLATEAU_ADAPT",
              "SHEEP_PLAN_PRIORS", "SHEEP_TRACE", "SHEEP_TRACE_MAX_MB"):
        monkeypatch.delenv(k, raising=False)
    yield monkeypatch


N, LINKS = 1 << 16, 1 << 18


# ---------------------------------------------------------------------------
# parity: no priors => the pre-planner choices, bit for bit
# ---------------------------------------------------------------------------


def test_unbudgeted_plan_keeps_everything(plan_env):
    ladder = ("single", "host", "stream", "spill")
    p = plan_build(N, LINKS, ladder=ladder)
    assert p.rungs == list(ladder)
    assert p.chosen == "single"
    assert all(c["verdict"] == "keep" for c in p.candidates)
    assert p.decision("rungs").provenance == "default"
    assert p.budget_bytes is None


def test_budgeted_plan_matches_governor_for_every_budget(plan_env):
    """The parity sweep: for budgets that keep all / some / only the
    floor, plan_build's kept rungs equal gov.plan_rungs' — the planner
    calls the same arithmetic, it does not fork it."""
    ladder = ["host", "stream", "spill"]
    rss = G.rss_bytes()
    budgets = [rss + G.rung_peak_nbytes("host", N, LINKS) * 2,
               rss + (G.rung_peak_nbytes("host", N, LINKS)
                      + G.rung_peak_nbytes("stream", N, LINKS)) // 2,
               rss + G.rung_peak_nbytes("spill", N, LINKS) + 1,
               rss + 1]
    for budget in budgets:
        gov = G.ResourceGovernor(mem_budget=budget)
        p = plan_build(N, LINKS, ladder=tuple(ladder), governor=gov)
        kept, _ = gov.plan_rungs(list(ladder), N, LINKS)
        assert p.rungs == kept, budget
    # provenance: a priced skip is "priced", never "learned"
    gov = G.ResourceGovernor(mem_budget=budgets[1])
    p = plan_build(N, LINKS, ladder=tuple(ladder), governor=gov)
    if len(p.rungs) < len(ladder):
        assert p.decision("rungs").provenance == "priced"


def test_available_rungs_filter(plan_env, tmp_path):
    full = ("mesh", "single", "host", "stream", "ext", "spill")
    # no devices info: mesh survives; no .dat: ext dropped
    assert available_rungs(full) == ["mesh", "single", "host", "stream",
                                     "spill"]
    assert available_rungs(full, devices=1) == ["single", "host",
                                                "stream", "spill"]
    assert available_rungs(full, num_workers=1)[0] == "single"
    dat = tmp_path / "g.dat"
    dat.write_bytes(b"\x00" * 24)
    assert "ext" in available_rungs(full, edges_path=str(dat))
    assert "ext" not in available_rungs(full,
                                        edges_path=str(tmp_path / "no.dat"))
    assert available_rungs(("nope",)) == ["host"]


# ---------------------------------------------------------------------------
# forced knobs win, provenance says forced (the A/B-arm contract)
# ---------------------------------------------------------------------------


def test_forced_knobs_win_with_forced_provenance(plan_env):
    plan_env.setenv("SHEEP_NATIVE_THREADS", "4")
    plan_env.setenv("SHEEP_EXT_BLOCK", "300")
    plan_env.setenv("SHEEP_HANDOFF_WINDOWS", "8")
    plan_env.setenv("SHEEP_DISTEXT_LEGS", "3")
    plan_env.setenv("SHEEP_PIPELINE_CHUNKS", "0")
    p = plan_build(N, LINKS, ladder=("host", "spill"), with_distext=True)
    d = {name: dec for name, dec in p.decisions.items()}
    assert d["native_threads"].value == 4
    assert d["native_threads"].provenance == "forced"
    assert d["ext_block"].value == 300
    assert d["ext_block"].provenance == "forced"
    assert d["handoff_windows"].value == 8
    assert d["handoff_windows"].provenance == "forced"
    assert d["distext_legs"].value == 3
    assert d["distext_legs"].provenance == "forced"
    assert d["pipeline_chunks"].value is False
    assert d["pipeline_chunks"].provenance == "forced"
    # a forced ext block is never second-guessed even by a prior that
    # screams (the resume-identity rule)
    st = PriorStore()
    for _ in range(4):
        st.observe("mem_ratio", "ext", N, 8.0)
    gov = G.ResourceGovernor(mem_budget=G.rss_bytes() + (64 << 20))
    p2 = plan_build(N, LINKS, ladder=("ext", "spill"), governor=gov,
                    priors=st, edges_path=None)
    assert p2.decision("ext_block").value == 300
    assert p2.decision("ext_block").provenance == "forced"


def test_forced_ladder_provenance(plan_env):
    p = plan_build(N, LINKS, ladder=("host",), ladder_forced=True)
    assert p.decision("rungs").provenance == "forced"


def test_distext_leg_plan_provenance(plan_env):
    out = plan_distext_legs(governor=G.ResourceGovernor())
    assert out["provenance"] == "default" and out["legs"] >= 2
    plan_env.setenv("SHEEP_DISTEXT_LEGS", "5")
    out = plan_distext_legs(governor=G.ResourceGovernor())
    assert out["legs"] == 5 and out["provenance"] == "forced"


# ---------------------------------------------------------------------------
# the history-corrected decision (the acceptance demonstration)
# ---------------------------------------------------------------------------


def test_prior_flips_a_keep_verdict(plan_env):
    """A rung the analytic model keeps is skipped once measured history
    says its real cost runs 4x the price — provenance ``learned``, and
    the explain text names the prior that did it."""
    st = PriorStore()
    st.observe("mem_ratio", "stream", N, 4.0)
    st.observe("mem_ratio", "stream", N, 4.0)
    gov = G.ResourceGovernor(
        mem_budget=G.rss_bytes() + G.rung_peak_nbytes("stream", N, LINKS) * 2)
    base = plan_build(N, LINKS, ladder=("stream", "spill"), governor=gov)
    assert base.chosen == "stream"  # analytic: fits
    p = plan_build(N, LINKS, ladder=("stream", "spill"), governor=gov,
                   priors=st)
    assert p.chosen == "spill"
    d = p.decision("rungs")
    assert d.provenance == "learned"
    assert d.analytic == ["stream", "spill"]
    text = "\n".join(p.explain())
    assert "history corrected" in text
    assert "mem_ratio:stream" in text
    assert p.corrections()


def test_prior_needs_min_samples_to_correct(plan_env):
    st = PriorStore()
    st.observe("mem_ratio", "stream", N, 4.0)  # one sample only
    assert MIN_CORRECT_SAMPLES > 1
    gov = G.ResourceGovernor(
        mem_budget=G.rss_bytes() + G.rung_peak_nbytes("stream", N, LINKS) * 2)
    p = plan_build(N, LINKS, ladder=("stream", "spill"), governor=gov,
                   priors=st)
    assert p.chosen == "stream"  # a single noisy run must not flip plans
    assert p.decision("rungs").provenance != "learned"


def test_prior_corrects_mispriced_ext_block(plan_env):
    """The ROADMAP's named example: a mispriced ext block size fixed by
    a prior trace's measured cost.  History says ext really costs 4x
    the analytic price on this host, so the fitted block halves further
    than the analytic fit — provenance ``learned``."""
    st = PriorStore()
    st.observe("mem_ratio", "ext", N, 4.0)
    st.observe("mem_ratio", "ext", N, 4.0)
    head = 32 * N + G.EXT_RECORD_BYTES * G.ext_block_edges() // 2
    gov = G.ResourceGovernor(mem_budget=G.rss_bytes() + head)
    base = plan_build(N, LINKS, ladder=("ext", "spill"), governor=gov)
    p = plan_build(N, LINKS, ladder=("ext", "spill"), governor=gov,
                   priors=st)
    d = p.decision("ext_block")
    assert d.value < base.decision("ext_block").value
    assert d.provenance == "learned"
    assert d.analytic == base.decision("ext_block").value
    assert d.prior and d.prior["count"] == 2
    text = "\n".join(p.explain())
    assert "mem_ratio:ext" in text


def test_driver_builds_with_learned_ext_block(plan_env, tmp_path):
    """End to end through the driver: a synthetic prior store shrinks
    the ext block, the ladder.plan event records the learned decision,
    and the tree is still oracle-bit-identical (a plan can only ever
    change COST, never the forest)."""
    from sheep_tpu.core import build_forest, degree_sequence
    from sheep_tpu.io.edges import write_dat
    from sheep_tpu.obs import trace as obs_trace
    from sheep_tpu.runtime import RuntimeConfig, build_graph_resilient
    from sheep_tpu.utils.synth import rmat_edges

    tail, head = rmat_edges(12, 1 << 14, seed=3)
    dat = str(tmp_path / "g.dat")
    write_dat(dat, tail, head)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    n = len(want_seq)

    store = PriorStore(str(tmp_path / "p.store"))
    store.observe("mem_ratio", "ext", n, 4.0)
    store.observe("mem_ratio", "ext", n, 4.0)
    store.save()
    plan_env.setenv("SHEEP_PLAN_PRIORS", str(tmp_path / "p.store"))
    budget = G.rss_bytes() + 32 * n \
        + G.EXT_RECORD_BYTES * G.ext_block_edges() // 4
    tpath = str(tmp_path / "b.trace")
    plan_env.setenv("SHEEP_TRACE", tpath)
    try:
        cfg = RuntimeConfig(ladder=("ext", "spill"), edges_path=dat,
                            governor=G.ResourceGovernor(mem_budget=budget))
        seq, forest = build_graph_resilient(tail, head, config=cfg)
    finally:
        obs_trace.close_recorder()
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)
    records, _, _ = obs_trace.read_trace(tpath, "repair")
    plans = [r for r in records if r.get("name") == "ladder.plan"]
    assert plans
    a = plans[0]["a"]
    assert a["n"] == n and a["links"] >= 0  # the harvestable context
    dec = {d["name"]: d for d in a["decisions"]}
    assert dec["ext_block"]["provenance"] == "learned"
    assert dec["ext_block"]["value"] < dec["ext_block"]["analytic"]
    assert "prior" in dec["ext_block"]


# ---------------------------------------------------------------------------
# the prior store itself
# ---------------------------------------------------------------------------


def test_store_roundtrip_and_corruption(tmp_path):
    st = PriorStore(str(tmp_path / "p.store"))
    st.observe("mem_ratio", "ext", 1000, 2.0)
    st.observe("mem_ratio", "ext", 1000, 4.0)
    p = st.lookup("mem_ratio", "ext", 1000)
    assert p["count"] == 2 and p["mean"] == pytest.approx(3.0)
    # same bucket, different exact size
    assert st.lookup("mem_ratio", "ext", 1023) == p
    assert st.lookup("mem_ratio", "ext", 4096) is None  # other bucket
    assert st.lookup("mem_ratio", "ext", 1000, host="other") is None
    st.save()
    again = PriorStore(str(tmp_path / "p.store"))
    assert again.lookup("mem_ratio", "ext", 1000) == p
    # corruption reads as empty, never raises (priors only ever ADD)
    (tmp_path / "p.store").write_text("{nope")
    assert len(PriorStore(str(tmp_path / "p.store"))) == 0


def test_scale_bucket_and_key():
    assert scale_bucket(0) == 0
    assert scale_bucket(1) == 0
    assert scale_bucket(1 << 16) == 16
    assert scale_bucket((1 << 17) - 1) == 16
    k = prior_key("mem_ratio", "ext", 1 << 16, host="h0")
    assert k == "h0:mem_ratio:ext:s16"


# ---------------------------------------------------------------------------
# harvesting across rotated segment chains (the satellite)
# ---------------------------------------------------------------------------


def _emit_planned_build(n, est, rss0, rss1, rung="ext", count=1):
    """Emit `count` synthetic planned-build event pairs into the live
    recorder (the exact shapes the driver writes)."""
    from sheep_tpu.obs import trace as obs
    for _ in range(count):
        obs.event("ladder.plan", rungs=[rung], priced=[], n=n,
                  links=4 * n, rss_bytes=rss0, decisions=[])
        obs.event("rung.ok", rung=rung, rss_bytes=rss1, est_bytes=est,
                  n=n)


def test_harvest_survives_rotation_and_torn_tail(plan_env, tmp_path):
    """The prior store reads through a rotated ``.NNNN.trace`` chain
    with a torn newest segment — the state a SHEEP_TRACE_MAX_MB daemon
    killed mid-line leaves behind."""
    from sheep_tpu.obs import trace as obs
    tpath = str(tmp_path / "d.trace")
    plan_env.setenv(obs.ENV, tpath)
    plan_env.setenv(obs.MAX_MB_ENV, "0.002")  # ~2KB: rotate fast
    n, est = 1 << 16, 10 << 20
    try:
        _emit_planned_build(n, est, rss0=100 << 20, rss1=(100 << 20) + 2 * est,
                            count=40)
    finally:
        obs.close_recorder()
    segs = obs.trace_segments(tpath)
    assert len(segs) >= 3, segs  # rotation really happened
    # tear the newest (active) file mid-line: the kill -9 shape
    with open(tpath, "ab") as f:
        f.write(b'{"k":"ev","name":"rung.ok","a":{"est_b')
    st = PriorStore()
    got = st.harvest_trace(tpath)
    assert got == 40, got  # every rotated segment's samples landed
    p = st.lookup("mem_ratio", "ext", n)
    assert p["count"] == 40 and p["mean"] == pytest.approx(2.0)
    # the chain reader sees one stream too (rollup satellite)
    records = obs.read_trace_chain(tpath, "repair")
    assert sum(1 for r in records if r.get("name") == "rung.ok") == 40


def test_harvest_skips_rotten_mid_chain_segment(plan_env, tmp_path):
    """Mid-file rot in a ROTATED segment loses that segment's samples
    but never the harvest: history degrades to fewer samples."""
    from sheep_tpu.obs import trace as obs
    tpath = str(tmp_path / "d.trace")
    plan_env.setenv(obs.ENV, tpath)
    plan_env.setenv(obs.MAX_MB_ENV, "0.002")
    n, est = 1 << 16, 10 << 20
    try:
        _emit_planned_build(n, est, rss0=0, rss1=2 * est, count=40)
    finally:
        obs.close_recorder()
    segs = obs.trace_segments(tpath)
    assert len(segs) >= 3
    # rot the middle of the FIRST rotated segment (not a legal tear)
    with open(segs[0], "r+b") as f:
        f.seek(os.path.getsize(segs[0]) // 2)
        f.write(b"\x00garbage\x00")
    st = PriorStore()
    got = st.harvest_trace(tpath)
    assert 0 < got < 40, got
    # and read_trace_chain (strict on rotated segments) refuses — the
    # harvester is deliberately more forgiving than the artifact reader
    from sheep_tpu.integrity.errors import IntegrityError
    with pytest.raises(IntegrityError):
        obs.read_trace_chain(tpath, "repair")


def test_harvest_bench_record(tmp_path):
    rec = {"arms": {"ext": {"arm": "ext", "wall_s": 8.0,
                            "records": 1 << 26},
                    "spill": {"arm": "spill", "wall_s": 15.0,
                              "records": 1 << 26},
                    "batch_ab": {"arm": "batch", "wall_s": 1.0}}}
    path = tmp_path / "EXTBENCH_test.json"
    path.write_text(json.dumps(rec))
    st = PriorStore()
    assert st.harvest_bench(str(path)) == 2  # only rung-named arms
    assert st.lookup("rung_s", "ext", 1 << 26)["mean"] == pytest.approx(8.0)
    # garbage harvests zero, never raises
    bad = tmp_path / "bad.json"
    bad.write_text("{")
    assert st.harvest_bench(str(bad)) == 0


# ---------------------------------------------------------------------------
# the CLI
# ---------------------------------------------------------------------------


def _write_dat(tmp_path):
    from sheep_tpu.io.edges import write_dat
    from sheep_tpu.utils.synth import rmat_edges
    tail, head = rmat_edges(10, 1 << 12, seed=9)
    dat = str(tmp_path / "g.dat")
    write_dat(dat, tail, head)
    return dat


def test_plan_cli_explain_deterministic(plan_env, tmp_path, capsys):
    from sheep_tpu.cli.plan import main
    dat = _write_dat(tmp_path)
    plan_env.setenv("SHEEP_MEM_BUDGET", "64M")
    outs = []
    for _ in range(2):
        assert main(["--explain", "--assume-rss", "0", dat]) == 0
        outs.append(capsys.readouterr().out)
    assert outs[0] == outs[1]  # same inputs -> same plan, byte for byte
    assert "chosen rung:" in outs[0]
    assert "[default]" in outs[0] or "[priced]" in outs[0]


def test_plan_cli_json_and_hypothetical(plan_env, capsys):
    from sheep_tpu.cli.plan import main
    assert main(["--json", "-n", str(1 << 16), "-e", str(1 << 18)]) == 0
    view = json.loads(capsys.readouterr().out)
    assert view["chosen"] in view["rungs"]
    assert {d["name"] for d in view["decisions"]} >= {
        "rungs", "native_threads", "ext_block", "handoff_windows"}


def test_plan_cli_harvest_roundtrip(plan_env, tmp_path, capsys):
    from sheep_tpu.cli.plan import main
    from sheep_tpu.obs import trace as obs
    tpath = str(tmp_path / "b.trace")
    plan_env.setenv(obs.ENV, tpath)
    try:
        _emit_planned_build(1 << 16, 10 << 20, rss0=0, rss1=20 << 20,
                            count=3)
    finally:
        obs.close_recorder()
    plan_env.delenv(obs.ENV)
    store = str(tmp_path / "p.store")
    assert main(["--harvest", store, tpath]) == 0
    assert "3 sample(s)" in capsys.readouterr().out
    st = PriorStore(store)
    assert st.lookup("mem_ratio", "ext", 1 << 16)["count"] == 3
    # and the store feeds --priors: under a budget the analytic ext fit
    # keeps the default block but the learned x2 correction halves it —
    # the explain text names the prior that did it
    plan_env.setenv("SHEEP_MEM_BUDGET", "48M")
    assert main(["--explain", "--assume-rss", "0", "--priors", store,
                 "-n", str(1 << 16), "-e", str(1 << 18)]) == 0
    out = capsys.readouterr().out
    assert "mem_ratio:ext" in out
    assert "ext_block" in out and "[learned]" in out


def test_plan_cli_usage_errors(plan_env, capsys):
    from sheep_tpu.cli.plan import main
    assert main([]) == 2
    assert main(["--harvest", "x.store"]) == 2
    assert main(["/nonexistent/g.dat"]) == 1

# ---------------------------------------------------------------------------
# plan_reseq learns fold throughput (ISSUE 19 satellite)
# ---------------------------------------------------------------------------


def test_plan_reseq_learns_fold_throughput(plan_env, tmp_path):
    """The serve-tier re-sequence planner learns the way plan_build
    does: ``reseq.fold`` trace spans harvest into a ``fold_bps`` prior,
    and plan_reseq then prices the rebuild at the MEASURED throughput —
    provenance ``learned`` — with the analytic RESEQ_FOLD_BPS fallback
    whenever history is too thin to correct."""
    import time as _t

    from sheep_tpu.obs import trace as obs
    from sheep_tpu.plan.model import RESEQ_FOLD_BPS, plan_reseq
    from sheep_tpu.plan.priors import fold_bps

    records, inserted = 1 << 20, 1 << 10
    blob = (records + inserted) * 12
    base = plan_reseq(records, inserted, 5, horizon_s=60.0)
    assert base["decision"] == "go" and base["provenance"] == "priced"
    assert base["fold_bps"] == RESEQ_FOLD_BPS

    # real reseq.fold spans harvest into the fold_bps prior
    tpath = str(tmp_path / "r.trace")
    plan_env.setenv("SHEEP_TRACE", tpath)
    try:
        for _ in range(2):
            with obs.span("reseq.fold", bytes=blob, records=records):
                _t.sleep(0.01)
    finally:
        obs.close_recorder()
    st = PriorStore()
    assert st.harvest_trace(tpath) == 2
    p = fold_bps(st, blob)
    assert p and p["count"] == 2 and p["mean"] > 0

    # measured history REPLACES the analytic constant: a host whose
    # folds really run at 4 MB/s prices 16x dearer, provenance learned
    slow = PriorStore()
    slow.observe("fold_bps", "reseq", blob, float(4 << 20))
    slow.observe("fold_bps", "reseq", blob, float(4 << 20))
    out = plan_reseq(records, inserted, 5, horizon_s=60.0, priors=slow)
    assert out["provenance"] == "learned"
    assert out["fold_bps"] == 4 << 20
    assert out["cost_s"] > base["cost_s"]
    assert out["analytic_cost_s"] == base["cost_s"]
    assert out["prior"]["count"] == 2
    assert "measured fold" in out["reason"]
    # ...and the learned price can flip the verdict at a tight horizon
    out2 = plan_reseq(records, inserted, 5, horizon_s=1.0, priors=slow)
    assert out2["decision"] == "stay" and out2["provenance"] == "learned"

    # one noisy sample must not correct (MIN_CORRECT_SAMPLES)
    thin = PriorStore()
    thin.observe("fold_bps", "reseq", blob, float(4 << 20))
    out3 = plan_reseq(records, inserted, 5, horizon_s=60.0, priors=thin)
    assert out3["provenance"] == "priced"
    assert out3["fold_bps"] == RESEQ_FOLD_BPS

    # a prior at a DIFFERENT scale bucket never corrects this blob
    far = PriorStore()
    far.observe("fold_bps", "reseq", blob // 1024, float(4 << 20))
    far.observe("fold_bps", "reseq", blob // 1024, float(4 << 20))
    out4 = plan_reseq(records, inserted, 5, horizon_s=60.0, priors=far)
    assert out4["provenance"] == "priced"
