"""Flight-recorder tests (ISSUE 10): span nesting + thread-safety, the
disabled-mode fast path, kill-at-every-span-boundary trace readability,
the METRICS verb grammar, fsck's ``.trace`` rules, and the trace-on vs
trace-off build parity sweep (bit-identical tree + equal ECV(down) —
observability must never change what it observes).
"""

import json
import os
import threading
import warnings

import numpy as np
import pytest

from sheep_tpu.integrity.errors import IntegrityError, MalformedArtifact
from sheep_tpu.obs import metrics as obs_metrics
from sheep_tpu.obs import trace as obs_trace


@pytest.fixture(autouse=True)
def _clean_trace_env():
    prev = os.environ.pop(obs_trace.ENV, None)
    obs_trace.close_recorder()
    yield
    obs_trace.close_recorder()
    if prev is None:
        os.environ.pop(obs_trace.ENV, None)
    else:
        os.environ[obs_trace.ENV] = prev


def _enable(tmp_path, name="run.trace"):
    path = str(tmp_path / name)
    os.environ[obs_trace.ENV] = path
    return path


def _finish():
    obs_trace.close_recorder()
    os.environ.pop(obs_trace.ENV, None)


# -- span layer ------------------------------------------------------------


def test_disabled_fast_path_is_noop_singleton():
    assert not obs_trace.enabled()
    s1 = obs_trace.span("a", x=1)
    s2 = obs_trace.span("b")
    # identity-stable: the disabled path allocates no span object
    assert s1 is s2 is obs_trace.NOOP_SPAN
    with s1:
        s1.annotate(y=2)  # all no-ops
    obs_trace.event("nothing", z=3)
    obs_trace.annotate(w=4)
    assert obs_trace.trace_summary() is None


def test_span_nesting_ids(tmp_path):
    path = _enable(tmp_path)
    with obs_trace.span("outer", a=1):
        with obs_trace.span("mid"):
            with obs_trace.span("leaf"):
                pass
        obs_trace.event("marker", hit=True)
    _finish()
    records, _, torn = obs_trace.read_trace(path, "strict")
    assert not torn
    by_name = {r["name"]: r for r in records if r.get("k") == "span"}
    outer, mid, leaf = by_name["outer"], by_name["mid"], by_name["leaf"]
    assert outer["par"] is None
    assert mid["par"] == outer["id"]
    assert leaf["par"] == mid["id"]
    # spans land at exit: children precede parents in the file
    names = [r["name"] for r in records if r.get("k") == "span"]
    assert names == ["leaf", "mid", "outer"]
    ev = [r for r in records if r.get("k") == "ev"][0]
    assert ev["name"] == "marker" and ev["par"] == outer["id"]
    assert outer["a"] == {"a": 1}
    # durations nest: the parent covers its children
    assert outer["dur"] >= mid["dur"] >= leaf["dur"] >= 0.0


def test_annotate_reaches_innermost_span(tmp_path):
    path = _enable(tmp_path)
    with obs_trace.span("outer"):
        with obs_trace.span("inner") as sp:
            sp.annotate(k=7)
            obs_trace.annotate(via_module=True)
    _finish()
    records, _, _ = obs_trace.read_trace(path, "strict")
    inner = [r for r in records if r.get("name") == "inner"][0]
    assert inner["a"] == {"k": 7, "via_module": True}


def test_span_thread_safety(tmp_path):
    path = _enable(tmp_path)
    n_threads, per = 8, 25

    def worker(i):
        for k in range(per):
            with obs_trace.span("outer", i=i, k=k):
                with obs_trace.span("inner", i=i, k=k):
                    pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    _finish()
    records, _, torn = obs_trace.read_trace(path, "strict")
    assert not torn
    spans = [r for r in records if r.get("k") == "span"]
    outers = {r["id"]: r for r in spans if r["name"] == "outer"}
    inners = [r for r in spans if r["name"] == "inner"]
    assert len(outers) == n_threads * per and len(inners) == len(outers)
    # every inner's parent is the outer of the SAME (i, k) — interleaved
    # threads never cross-link their stacks
    for r in inners:
        parent = outers[r["par"]]
        assert parent["a"] == r["a"]
        assert parent["tid"] == r["tid"]
    # ids are unique across threads
    ids = [r["id"] for r in spans]
    assert len(set(ids)) == len(ids)


def test_timed_accumulates_without_tracing():
    out = []
    with obs_trace.timed("phase", out=out):
        pass
    with obs_trace.timed("phase", out=out):
        pass
    assert len(out) == 2 and all(s >= 0.0 for s in out)
    assert not obs_trace.enabled()


def test_overlap_stats_shared_accounting():
    # fully serialized: no overlap
    assert obs_trace.overlap_stats(2.0, 2.0) == \
        {"overlap_s": 0.0, "overlap_frac": 0.0}
    # perfect 2x overlap: half the serialized time was concurrent
    st = obs_trace.overlap_stats(2.0, 1.0)
    assert st == {"overlap_s": 1.0, "overlap_frac": 0.5}
    # degenerate inputs never divide by zero or go negative
    assert obs_trace.overlap_stats(0.0, 5.0) == \
        {"overlap_s": 0.0, "overlap_frac": 0.0}
    assert obs_trace.overlap_stats(1.0, 3.0)["overlap_s"] == 0.0


def test_summary_counts_spans_and_events(tmp_path):
    _enable(tmp_path)
    for k in range(3):
        with obs_trace.span("fold", block=k):
            pass
    obs_trace.event("fault", site="x")
    summary = obs_trace.trace_summary()
    assert summary["fold"]["count"] == 3
    assert summary["fold"]["total_s"] >= 0.0
    assert summary["_events"] == {"fault": 1}
    _finish()


# -- crash-safety: the torn-tail contract -----------------------------------


def _write_sample_trace(tmp_path, spans=6):
    path = _enable(tmp_path, "kill.trace")
    for k in range(spans):
        with obs_trace.span("phase", k=k):
            pass
    _finish()
    return path


def test_kill_at_every_byte_boundary_stays_readable(tmp_path):
    """Truncate the file at EVERY byte boundary (the kill -9 sweep): the
    repair read must always succeed with an intact prefix, and strict
    must either succeed (cut on a line boundary) or refuse TYPED."""
    path = _write_sample_trace(tmp_path)
    data = open(path, "rb").read()
    full, _, _ = obs_trace.read_trace(path, "strict")
    cut_path = str(tmp_path / "cut.trace")
    prev_count = None
    for cut in range(len(data) + 1):
        with open(cut_path, "wb") as f:
            f.write(data[:cut])
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            records, _, torn = obs_trace.read_trace(cut_path, "repair")
        # the salvaged prefix is a prefix of the full record list
        assert records == full[:len(records)]
        assert torn == (cut > 0 and not data[:cut].endswith(b"\n"))
        if torn:
            with pytest.raises(MalformedArtifact):
                obs_trace.read_trace(cut_path, "strict")
        # record count grows monotonically with the cut
        if prev_count is not None:
            assert len(records) >= prev_count - 0
        prev_count = len(records)
    assert prev_count == len(full)


def test_mid_file_rot_refused_every_mode(tmp_path):
    path = _write_sample_trace(tmp_path)
    data = open(path, "rb").read().splitlines(keepends=True)
    assert len(data) > 3
    data[1] = b"\x00garbage\n"  # damage a line with intact lines after
    with open(path, "wb") as f:
        f.writelines(data)
    for mode in ("strict", "repair", "trust"):
        with pytest.raises(MalformedArtifact):
            obs_trace.read_trace(path, mode)


def test_repair_trace_truncates_tear(tmp_path):
    path = _write_sample_trace(tmp_path)
    full, _, _ = obs_trace.read_trace(path, "strict")
    with open(path, "ab") as f:
        f.write(b'{"k":"span","name":"torn')
    assert obs_trace.repair_trace(path) == 24
    records, _, torn = obs_trace.read_trace(path, "strict")
    assert records == full and not torn
    assert obs_trace.repair_trace(path) == 0  # idempotent on clean


def test_fsck_trace_rules(tmp_path):
    from sheep_tpu.integrity.fsck import fsck_file
    path = _write_sample_trace(tmp_path)
    detail = fsck_file(path)  # clean close sealed a sidecar
    assert "spans=6" in detail and "sum=verified" in detail
    # torn tail: strict refuses, repair reports truncatable
    with open(path, "ab") as f:
        f.write(b'{"k":"ev"')
    with pytest.raises(IntegrityError):
        fsck_file(path, "strict")
    detail = fsck_file(path, "repair")
    assert "torn_tail=truncatable" in detail
    # a sidecar-less partial trace (the kill -9 shape) still fscks by
    # structure alone
    os.unlink(path + ".sum")
    obs_trace.repair_trace(path)
    detail = fsck_file(path, "strict")
    assert "sum=absent" in detail


def test_clean_close_seals_sidecar_reopen_drops_it(tmp_path):
    path = _write_sample_trace(tmp_path)
    assert os.path.exists(path + ".sum")
    # re-opening for append invalidates the old seal: the recorder must
    # drop it rather than leave a sidecar lying about the bytes
    os.environ[obs_trace.ENV] = path
    with obs_trace.span("more"):
        pass
    assert not os.path.exists(path + ".sum")
    _finish()
    assert os.path.exists(path + ".sum")
    records, _, _ = obs_trace.read_trace(path, "strict")
    assert sum(1 for r in records if r.get("k") == "meta") == 2


# -- parity: tracing must not change the build -------------------------------


def _ecv_down(tail, head, seq, forest, parts=2):
    from sheep_tpu.partition.evaluate import evaluate_partition
    from sheep_tpu.partition.partition import Partition

    p = Partition.from_forest(seq, forest, parts)
    rep = evaluate_partition(p.parts, tail, head, seq, p.num_parts)
    return rep.ecv_down


def test_traced_build_bit_identical_with_equal_ecv(tmp_path):
    from sheep_tpu.core.forest import build_forest
    from sheep_tpu.core.sequence import degree_sequence
    from sheep_tpu.ops import build_graph_hybrid
    from sheep_tpu.runtime import RuntimeConfig, build_graph_resilient
    from sheep_tpu.utils.synth import rmat_edges

    tail, head = rmat_edges(9, 4 << 9, seed=13)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)

    path = _enable(tmp_path, "parity.trace")
    seq_on, f_on = build_graph_resilient(
        tail, head, config=RuntimeConfig(ladder=("single", "host")))
    seq_h_on, fh_on = build_graph_hybrid(tail, head)
    _finish()
    seq_off, f_off = build_graph_resilient(
        tail, head, config=RuntimeConfig(ladder=("single", "host")))
    seq_h_off, fh_off = build_graph_hybrid(tail, head)

    for seq, forest in ((seq_on, f_on), (seq_off, f_off),
                        (seq_h_on, fh_on), (seq_h_off, fh_off)):
        np.testing.assert_array_equal(forest.parent, want.parent)
        np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)
    np.testing.assert_array_equal(seq_h_on, want_seq)
    assert _ecv_down(tail, head, seq_h_on, fh_on) == \
        _ecv_down(tail, head, seq_h_off, fh_off)

    # and the trace actually recorded the build: rung decision + phases
    records, _, torn = obs_trace.read_trace(path, "strict")
    assert not torn
    names = {r.get("name") for r in records if r.get("k") == "span"}
    assert "rung" in names and "prep" in names
    evs = {r.get("name") for r in records if r.get("k") == "ev"}
    assert "ladder.plan" in evs and "rung.ok" in evs
    assert any(r.get("name") == "reduce.chunk" for r in records
               if r.get("k") == "ev")


def test_trace_cli_rollup_and_rung_explanation(tmp_path, capsys):
    from sheep_tpu.cli.trace import main as trace_main
    from sheep_tpu.runtime import RuntimeConfig, build_graph_resilient
    from sheep_tpu.utils.synth import rmat_edges

    tail, head = rmat_edges(8, 4 << 8, seed=3)
    path = _enable(tmp_path, "cli.trace")
    build_graph_resilient(tail, head,
                          config=RuntimeConfig(ladder=("host",)))
    _finish()
    assert trace_main([path]) == 0
    out = capsys.readouterr().out
    assert "phase rollup" in out
    assert "ladder decisions" in out
    assert "ran: rung 'host'" in out
    assert "timeline" in out
    # --json carries the same story machine-readably
    assert trace_main(["--json", path]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["phases"]["rung"]["count"] == 1
    assert any("host" in line for line in rec["ladder"])
    assert rec["wall_s"] > 0


def test_trace_cli_explains_governor_prices(tmp_path, capsys):
    """The acceptance line: `sheep trace` explains which rung ran and
    why — governor price vs measured headroom per rung."""
    import sheep_tpu.resources.governor as G
    from sheep_tpu.cli.trace import main as trace_main
    from sheep_tpu.runtime import RuntimeConfig, build_graph_resilient
    from sheep_tpu.utils.synth import rmat_edges

    tail, head = rmat_edges(9, 4 << 9, seed=7)
    prev = G.rss_bytes
    G.rss_bytes = lambda: 0  # deterministic headroom for the plan
    try:
        n_est = 1 << 9
        budget = (G.rung_peak_nbytes("stream", 2 * n_est, 4 << 9)
                  + G.rung_peak_nbytes("host", 2 * n_est, 4 << 9)) // 2
        path = _enable(tmp_path, "gov.trace")
        cfg = RuntimeConfig(ladder=("host", "stream", "spill"),
                            governor=G.ResourceGovernor(mem_budget=budget))
        build_graph_resilient(tail, head, config=cfg)
        _finish()
    finally:
        G.rss_bytes = prev
    assert trace_main([path]) == 0
    out = capsys.readouterr().out
    assert "governor price" in out
    assert "-> skip" in out or "-> keep" in out
    assert "ran: rung" in out


def test_supervise_status_shows_newest_trace_rollup(tmp_path):
    from sheep_tpu.supervisor.status import newest_trace_rollup
    assert newest_trace_rollup(str(tmp_path)) is None
    _write_sample_trace(tmp_path)
    roll = newest_trace_rollup(str(tmp_path))
    assert roll is not None and not roll["torn"]
    assert roll["phases"]["phase"]["count"] == 6
    # a torn (killed-run) trace still reports, flagged
    with open(roll["path"], "ab") as f:
        f.write(b'{"k":')
    roll = newest_trace_rollup(str(tmp_path))
    assert roll["torn"] is True


# -- metrics registry + METRICS verb ----------------------------------------


def test_registry_counter_gauge_histogram_grammar():
    r = obs_metrics.Registry()
    c = r.counter("x_total", "things")
    c.labels(verb="A").inc()
    c.labels(verb="A").inc()
    c.labels(verb="B").inc()
    g = r.gauge("x_gauge")
    g.set(2.5)
    h = r.histogram("x_seconds")
    for v in (0.0002, 0.003, 0.003, 7.0, 100.0):
        h.observe(v)
    text = r.render()
    assert text.endswith("\n")
    lines = text.splitlines()
    assert "# TYPE x_total counter" in lines
    assert 'x_total{verb="A"} 2' in lines
    assert 'x_total{verb="B"} 1' in lines
    assert "# TYPE x_gauge gauge" in lines
    assert "x_gauge 2.5" in lines
    assert "# TYPE x_seconds histogram" in lines
    # bucket counts are cumulative and monotone, +Inf == count
    buckets = [int(ln.rsplit(" ", 1)[1]) for ln in lines
               if ln.startswith("x_seconds_bucket")]
    assert buckets == sorted(buckets)
    assert buckets[-1] == 5
    assert "x_seconds_count 5" in lines
    # quantile: bucket upper-bound estimate
    assert h.quantile(0.5) == 0.0025 or h.quantile(0.5) == 0.005
    assert h.quantile(0.99) == 10.0  # 100s observation lands in +Inf


def test_histogram_quantile_empty_and_threaded():
    h = obs_metrics.Histogram("h")
    assert h.quantile(0.5) == 0.0

    def hammer():
        for _ in range(500):
            h.observe(0.001)

    ts = [threading.Thread(target=hammer) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert h.count == 2000
    assert h.quantile(0.99) == 0.001


@pytest.fixture
def serve_daemon(tmp_path):
    from sheep_tpu.io.edges import write_dat
    from sheep_tpu.serve.daemon import ServeConfig, ServeDaemon
    from sheep_tpu.serve.state import ServeCore
    from sheep_tpu.utils.synth import rmat_edges

    tail, head = rmat_edges(7, 4 << 7, seed=5)
    g = str(tmp_path / "g.dat")
    write_dat(g, tail, head)
    core = ServeCore.bootstrap(str(tmp_path / "state"), graph_path=g,
                               num_parts=3)
    d = ServeDaemon(core, ServeConfig(deadline_s=10.0)).start()
    yield d
    d.shutdown()


def test_metrics_verb_grammar_and_stats_quantiles(serve_daemon):
    from sheep_tpu.serve.protocol import ServeClient
    h, p = serve_daemon.address
    with ServeClient(h, p) as c:
        c.part([0, 1, 2])
        c.part([3, 4])
        c.insert([(1, 2)])
        body = c.metrics()
        lines = body.splitlines()
        assert "# TYPE sheep_serve_requests_total counter" in lines
        assert 'sheep_serve_requests_total{verb="PART"} 2' in lines
        assert 'sheep_serve_requests_total{verb="INSERT"} 1' in lines
        assert "# TYPE sheep_serve_request_seconds histogram" in lines
        assert "sheep_serve_applied_seqno 1" in lines
        assert any(ln.startswith("sheep_serve_repl_lag_records")
                   for ln in lines)
        # bucket series monotone per verb
        part_buckets = [int(ln.rsplit(" ", 1)[1]) for ln in lines
                        if ln.startswith("sheep_serve_request_seconds_"
                                         "bucket")
                        and 'verb="PART"' in ln]
        assert part_buckets == sorted(part_buckets)
        assert part_buckets[-1] == 2
        # the connection stays line-clean after the payload (pipelining)
        assert c.part([0]) is not None

        # STATS derives per-verb counts + p50/p99 from the SAME registry
        st = c.kv("STATS")
        assert st["req_part"] == 3
        assert st["req_insert"] == 1
        assert st["req_metrics"] == 1
        assert float(st["p50_part_ms"]) > 0
        assert float(st["p99_part_ms"]) >= float(st["p50_part_ms"])
        assert float(st["p99_insert_ms"]) > 0
        # a second scrape shows the first one counted
        body2 = c.metrics()
        assert 'sheep_serve_requests_total{verb="METRICS"} 1' in body2
        assert 'sheep_serve_requests_total{verb="STATS"} 1' in body2


def test_metrics_error_counter_and_bad_lines(serve_daemon):
    from sheep_tpu.serve.protocol import ServeClient, ServeError
    h, p = serve_daemon.address
    with ServeClient(h, p) as c:
        with pytest.raises(ServeError):
            c.part([])  # badreq
        with pytest.raises(ServeError):
            c.kv("SUBTREE 99999999")  # notfound
        body = c.metrics()
        assert 'sheep_serve_errors_total{code="badreq"} 1' in body
        assert 'sheep_serve_errors_total{code="notfound"} 1' in body
        # unparseable lines count under BAD, not as a minted verb
        assert 'verb="BAD"' in body


def test_wal_fsync_spans_traced(tmp_path, serve_daemon):
    from sheep_tpu.serve.protocol import ServeClient
    path = _enable(tmp_path, "serve.trace")
    h, p = serve_daemon.address
    with ServeClient(h, p) as c:
        c.insert([(3, 4)])
        c.insert([(5, 6)])
    summary = obs_trace.trace_summary()
    _finish()
    assert summary["wal.fsync"]["count"] >= 2


# -- span sampler (ISSUE 11) -------------------------------------------------


def test_sample_every_grammar(monkeypatch):
    monkeypatch.delenv(obs_trace.SAMPLE_ENV, raising=False)
    assert obs_trace.sample_every() == 1
    monkeypatch.setenv(obs_trace.SAMPLE_ENV, "1/8")
    assert obs_trace.sample_every() == 8
    monkeypatch.setenv(obs_trace.SAMPLE_ENV, "16")
    assert obs_trace.sample_every() == 16
    with pytest.warns(UserWarning):
        monkeypatch.setenv(obs_trace.SAMPLE_ENV, "2/8")
        assert obs_trace.sample_every() == 1
    with pytest.warns(UserWarning):
        monkeypatch.setenv(obs_trace.SAMPLE_ENV, "garbage")
        assert obs_trace.sample_every() == 1
    monkeypatch.delenv(obs_trace.SAMPLE_ENV, raising=False)
    assert obs_trace.sample_every() == 1


def test_sampled_span_records_one_in_n(tmp_path, monkeypatch):
    """SHEEP_TRACE_SAMPLE=1/N records exactly ceil(k/N) of k spans,
    each carrying sample=N so readers can re-scale; disabled tracing
    stays the shared no-op singleton."""
    monkeypatch.setenv(obs_trace.SAMPLE_ENV, "1/4")
    assert obs_trace.sampled_span("x") is obs_trace.NOOP_SPAN  # untraced
    path = _enable(tmp_path, "sampled.trace")
    obs_trace.sample_every()  # reset the per-name counters
    for _ in range(10):
        with obs_trace.sampled_span("serve.req") as sp:
            sp.annotate(ok=True)  # works on sampled AND no-op spans
    _finish()
    monkeypatch.delenv(obs_trace.SAMPLE_ENV, raising=False)
    records, _, _ = obs_trace.read_trace(path, "strict")
    spans = [r for r in records
             if r.get("k") == "span" and r["name"] == "serve.req"]
    assert len(spans) == 3  # calls 0, 4, 8 of 10
    assert all(s["a"].get("sample") == 4 for s in spans)


def test_serve_requests_sampled_under_load(tmp_path, monkeypatch):
    """The daemon's per-request spans exist under SHEEP_TRACE_SAMPLE
    and carry verb/tenant attributes."""
    import numpy as np
    from sheep_tpu.io.edges import write_dat
    from sheep_tpu.serve import ServeConfig, ServeCore, ServeDaemon
    from sheep_tpu.serve.protocol import ServeClient
    from sheep_tpu.utils.synth import rmat_edges
    tail, head = rmat_edges(6, 4 << 6, seed=3)
    write_dat(str(tmp_path / "g.dat"), tail, head)
    core = ServeCore.bootstrap(str(tmp_path / "s"),
                               graph_path=str(tmp_path / "g.dat"),
                               num_parts=3)
    monkeypatch.setenv(obs_trace.SAMPLE_ENV, "1/5")
    path = _enable(tmp_path, "serve-req.trace")
    obs_trace.sample_every()
    d = ServeDaemon(core, ServeConfig()).start()
    try:
        h, p = d.address
        with ServeClient(h, p) as c:
            for _ in range(20):
                c.part([0, 1, 2])
    finally:
        d.shutdown()
        _finish()
        monkeypatch.delenv(obs_trace.SAMPLE_ENV, raising=False)
    records, _, _ = obs_trace.read_trace(path, "repair")
    spans = [r for r in records
             if r.get("k") == "span" and r["name"] == "serve.req"]
    assert 2 <= len(spans) <= 6, len(spans)  # ~20/5, not 20
    assert all(s["a"]["verb"] == "PART" for s in spans)
    assert all(s["a"]["tenant"] == "default" for s in spans)
