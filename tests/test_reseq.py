"""Crash-safe incremental re-sequencing tests (ISSUE 18): the
incremental degree-histogram parity property (across snapshot/restore
and WAL replay), the sequence-drift detector, kill-at-every-phase-
boundary resume with bit-identical trees, mid-re-sequence failover with
zero acked-insert loss, the replicated swap frame under network faults,
and the fsck generation-chain checks."""

import os
import shutil
import time

import numpy as np
import pytest

from sheep_tpu.core.forest import build_forest
from sheep_tpu.core.sequence import host_degree_histogram
from sheep_tpu.integrity.errors import IntegrityError, MalformedArtifact
from sheep_tpu.integrity.fsck import fsck_paths
from sheep_tpu.io import faultfs
from sheep_tpu.io.edges import write_dat
from sheep_tpu.serve import faults as serve_faults
from sheep_tpu.serve import netfaults, reseq
from sheep_tpu.serve.cluster import ClusterConfig
from sheep_tpu.serve.daemon import ServeConfig, ServeDaemon
from sheep_tpu.serve.faults import ServeKilled, parse_serve_fault_plan
from sheep_tpu.serve.netfaults import parse_netfault_plan
from sheep_tpu.serve.protocol import ServeClient, ServeError
from sheep_tpu.serve.replicate import bootstrap_state_dir
from sheep_tpu.serve.reseq import resume_reseq, run_reseq
from sheep_tpu.serve.state import ServeCore
from sheep_tpu.serve.wal import WalAppender, create_wal, wal_path
from sheep_tpu.utils.synth import rmat_edges


@pytest.fixture(autouse=True)
def _clean_fault_plans():
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()
    yield
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()


def _wait_until(cond, timeout_s=15.0, poll_s=0.02, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(poll_s)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


def _state(tmp_path, name="state", seed=3, log2=7, parts=3, **kw):
    tail, head = rmat_edges(log2, 4 << log2, seed=seed)
    g = str(tmp_path / f"{name}.dat")
    write_dat(g, tail, head)
    sd = str(tmp_path / name)
    core = ServeCore.bootstrap(sd, graph_path=g, num_parts=parts, **kw)
    return core, sd, tail, head


def _skewed_inserts(k, lo=200, span=6, seed=9):
    """An insert stream concentrated on a few fresh vertices — the
    power-law hot spot that moves degree ranks and builds sequence
    drift fast."""
    rng = np.random.default_rng(seed)
    hub = lo + rng.integers(0, span, size=k)
    other = rng.integers(0, lo, size=k)
    return np.stack([hub, other], axis=1).astype(np.uint32)


# ---------------------------------------------------------------------------
# the incremental degree histogram (tentpole part 1)
# ---------------------------------------------------------------------------


def test_degree_histogram_parity_property(tmp_path):
    """The incrementally-maintained histogram equals a full recount
    after every random insert batch — and the property survives both
    recovery paths: snapshot restore and WAL replay."""
    core, sd, tail, head = _state(tmp_path, snap_every=10)
    rng = np.random.default_rng(21)
    for batch in range(6):
        k = int(rng.integers(1, 9))
        rows = rng.integers(0, 200, size=(k, 2)).astype(np.uint32)
        for row in rows:
            core.insert(row.reshape(1, 2))
        assert core.degree_parity(), f"diverged after batch {batch}"
    # the recount oracle really is the full durable edge set
    at = np.concatenate([tail, np.asarray(core.ins_tail, np.uint32)])
    ah = np.concatenate([head, np.asarray(core.ins_head, np.uint32)])
    n = int(max(at.max(), ah.max())) + 1
    want = host_degree_histogram(at, ah, n)
    np.testing.assert_array_equal(core.recount_degrees()[:n], want)
    applied = core.applied_seqno
    core.close()

    # snapshot restore (snap_every=10 sealed at least once mid-stream)
    # + WAL replay of the unsealed tail: parity must hold again
    revived = ServeCore.open(sd)
    assert revived.applied_seqno == applied
    assert revived.degree_parity()
    revived.close()


def test_seq_drift_detector_and_wire_fields(tmp_path):
    """Sequence drift is its own detector, distinct from cut drift: a
    skewed stream trips it, and the accounting rides STATS/ECV."""
    core, sd, _, _ = _state(tmp_path, reseq_min=8, reseq_frac=0.25)
    assert not core.seq_drift_exceeded()
    for row in _skewed_inserts(24):
        core.insert(row.reshape(1, 2))
    assert core.seq_drift > 0
    assert core.seq_drift_exceeded()
    st = core.stats()
    assert st["seq_drift"] == core.seq_drift
    assert st["reseqs"] == 0 and st["seq_gen"] == 0
    ev = core.ecv()
    assert ev["seq_drift"] == core.seq_drift and ev["reseqs"] == 0
    core.close()


# ---------------------------------------------------------------------------
# kill at every phase boundary -> bit-identical resume (tentpole part 4)
# ---------------------------------------------------------------------------


def test_kill_at_every_reseq_boundary_resumes_bit_identical(tmp_path,
                                                            monkeypatch):
    """Kill the re-sequence at EVERY phase boundary (hist, mid-fold
    checkpoint block, swap, seal), reopen from disk, resume: the final
    serving state must be bit-identical (state_crc) to the
    uninterrupted rebuild, and the manifest chain must close."""
    from sheep_tpu.runtime import BuildKilled, FaultPlan
    from sheep_tpu.runtime import clear_plan as rt_clear
    from sheep_tpu.runtime import install_plan as rt_install
    from sheep_tpu.runtime import reset_counters as rt_reset
    monkeypatch.setenv("SHEEP_EXT_BLOCK", "128")  # several fold blocks

    core, sd, _, _ = _state(tmp_path, name="ref")
    ins = _skewed_inserts(20)
    for row in ins:
        core.insert(row.reshape(1, 2))
    core.close()
    base = str(tmp_path / "base")
    shutil.copytree(sd, base)

    control = ServeCore.open(sd)
    res = run_reseq(control, force=True)
    assert res["seq_gen"] == 1 and res["sealed"] == 1
    want_crc = control.state_crc()
    want_ecv = control.ecv()["ecv_down"]
    control.close()

    serve_sites = ("reseq-hist", "reseq-fold", "reseq-swap", "reseq-seal")
    for site in serve_sites + ("ext-boundary",):
        sd_n = str(tmp_path / f"kill-{site}")
        shutil.copytree(base, sd_n)
        victim = ServeCore.open(sd_n)
        if site == "ext-boundary":
            rt_reset()
            rt_install(FaultPlan(site="ext-boundary", at=1, kind="kill"))
            with pytest.raises(BuildKilled):
                run_reseq(victim, force=True)
            rt_clear()
            rt_reset()
        else:
            serve_faults.install_plan(parse_serve_fault_plan(
                f"kill@{site}:0", kill_mode="raise"))
            with pytest.raises(ServeKilled):
                run_reseq(victim, force=True)
            serve_faults.clear_plan()
        victim.close()  # the "process" is dead; durable state only

        revived = ServeCore.open(sd_n)
        out = resume_reseq(revived)
        assert out is not None and not out.get("stale"), (site, out)
        assert revived.seq_gen == 1, site
        assert revived.state_crc() == want_crc, site
        assert revived.ecv()["ecv_down"] == want_ecv, site
        man = reseq.load_manifest(sd_n)
        assert man["phase"] == "done", site
        assert not os.path.exists(reseq.pending_path(sd_n)), site
        # the resumed dir passes fsck including the generation chain
        _, failures = fsck_paths([sd_n], mode="strict")
        assert not failures, (site, failures)
        revived.close()


def test_kill_after_seal_resume_finalizes_bookkeeping(tmp_path):
    """A crash AFTER the new generation sealed but before the manifest
    closed (phase still ``swap``) must finalize on resume, not rebuild:
    the durable snapshot already IS the new generation."""
    core, sd, _, _ = _state(tmp_path)
    for row in _skewed_inserts(12):
        core.insert(row.reshape(1, 2))
    res = run_reseq(core, force=True)
    assert res["seq_gen"] == 1
    # wind the manifest back to the swap phase, as if the process died
    # between seal_snapshot() and save_manifest(phase=done)
    man = reseq.load_manifest(sd)
    man["phase"] = "swap"
    man["chain"] = man["chain"][:1]
    reseq.save_manifest(sd, man)
    core.close()
    revived = ServeCore.open(sd)
    assert revived.seq_gen == 1
    out = resume_reseq(revived)
    assert out == {"resumed": "finalize", "seq_gen": 1}
    assert reseq.load_manifest(sd)["phase"] == "done"
    _, failures = fsck_paths([sd], mode="strict")
    assert not failures, failures
    revived.close()


# ---------------------------------------------------------------------------
# replication: swap frame, failover, netfaults (tentpole part 5)
# ---------------------------------------------------------------------------


def _spawn_pair(tmp_path, **cfg_kw):
    lcore, lsd, tail, head = _state(tmp_path, "lead")
    fsd = str(tmp_path / "fol")
    lead = ServeDaemon(
        lcore, ServeConfig(**cfg_kw),
        cluster=ClusterConfig(node_id="L", role="leader", peers=[fsd],
                              hb_s=0.05, failover_s=0.6,
                              poll_timeout_s=1.0)).start()
    lh, lp = lead.address
    bootstrap_state_dir(fsd, lh, lp)
    fol = ServeDaemon(
        ServeCore.open(fsd), ServeConfig(**cfg_kw),
        cluster=ClusterConfig(node_id="F", role="follower", peers=[lsd],
                              hb_s=0.05, failover_s=0.6,
                              poll_timeout_s=1.0)).start()
    _wait_until(lambda: lead.hub.follower_count() == 1,
                what="follower attached")
    return lead, fol, (tail, head)


def test_replicated_swap_is_a_sequenced_unit(tmp_path):
    """A forced RESEQ on the leader reaches the follower as one
    sequenced swap: the follower adopts the whole new generation
    (snapshot-boundary re-sync) and converges bit-identical — never a
    half-swapped tree."""
    lead, fol, _ = _spawn_pair(tmp_path)
    lh, lp = lead.address
    acked = []
    with ServeClient(lh, lp) as c:
        for row in _skewed_inserts(16):
            c.insert([(int(row[0]), int(row[1]))])
            acked.append((int(row[0]), int(row[1])))
        res = c.kv("RESEQ")
        assert res["seq_gen"] == 1 and res.get("stale", 0) == 0
        st = c.kv("STATS")
        assert st["seq_gen"] == 1 and st["reseqs"] == 1
        assert st["seq_drift"] == 0  # the swap reset the detector
    _wait_until(lambda: fol.core.seq_gen == 1, what="follower adoption")
    _wait_until(lambda: fol.core.applied_seqno == len(acked),
                what="follower caught up")
    np.testing.assert_array_equal(fol.core.parent, lead.core.parent)
    np.testing.assert_array_equal(fol.core.seq, lead.core.seq)
    assert fol.core.sig == lead.core.sig
    # post-swap writes keep replicating on the new generation
    with ServeClient(lh, lp) as c:
        c.insert([(3, 141)])
    _wait_until(lambda: fol.core.applied_seqno == len(acked) + 1,
                what="post-swap insert replicated")
    # both manifests sanction the generation change for fsck
    for d in (lead.core.state_dir, fol.core.state_dir):
        assert reseq.chain_has_sig(d, lead.core.sig), d
    lead.shutdown()
    fol.shutdown()


def test_mid_reseq_failover_loses_no_acked_insert(tmp_path):
    """Kill the leader mid-re-sequence (after the fold, inside the
    swap): the follower — still on the old generation — promotes and
    serves EVERY acked insert; the dead leader's half-done rebuild
    stays its own private manifest state."""
    lead, fol, (tail, head) = _spawn_pair(tmp_path)
    lh, lp = lead.address
    acked = []
    with ServeClient(lh, lp) as c:
        for row in _skewed_inserts(14):
            c.insert([(int(row[0]), int(row[1]))])
            acked.append((int(row[0]), int(row[1])))
    serve_faults.install_plan(parse_serve_fault_plan(
        "kill@reseq-swap:0", kill_mode="raise"))
    with ServeClient(lh, lp, timeout_s=3.0) as c:
        # the killed worker never answers: connection error or timeout
        with pytest.raises((ServeError, OSError)):
            c.kv("RESEQ")
    serve_faults.clear_plan()
    assert reseq.active(lead.core.state_dir)  # manifest mid-flight
    # abrupt leader death, follower promotes with zero acked loss
    lead._stop.set()
    lead._wake()
    if lead.watcher is not None:
        lead.watcher.stop()
    lead.hub.stop()
    try:
        lead._listener.close()
    except OSError:
        pass
    for conn in list(lead._conns.values()):
        try:
            conn.sock.close()
        except OSError:
            pass
    if lead._hb is not None:
        lead._hb.stop()
    try:
        os.unlink(os.path.join(lead.core.state_dir, "serve.addr"))
    except OSError:
        pass
    _wait_until(lambda: fol.role == "leader", what="promotion")
    assert fol.core.applied_seqno == len(acked)
    assert fol.core.seq_gen == 0  # the old generation keeps serving
    at = np.concatenate([tail, np.array([u for u, _ in acked],
                                        np.uint32)])
    ah = np.concatenate([head, np.array([v for _, v in acked],
                                        np.uint32)])
    want = build_forest(at, ah, fol.core.seq,
                        max_vid=len(fol.core.parts) - 1)
    np.testing.assert_array_equal(fol.core.parent, want.parent)
    fol.shutdown()


def test_netfaults_on_replicated_swap_frame(tmp_path):
    """Deterministic wire chaos on the swap announcement: a DROPPED
    RESEQ frame still converges (the gen= stamp on the next APPEND
    forces the snapshot re-sync), and a DUPLICATED frame applies once
    (the second copy finds the follower already on the announced
    generation and ACKs idempotently)."""
    lead, fol, _ = _spawn_pair(tmp_path)
    lh, lp = lead.address
    with ServeClient(lh, lp) as c:
        for row in _skewed_inserts(12):
            c.insert([(int(row[0]), int(row[1]))])
    applied0 = lead.core.applied_seqno

    netfaults.install_plan(parse_netfault_plan("drop@reseq:0"))
    with ServeClient(lh, lp) as c:
        assert c.kv("RESEQ")["seq_gen"] == 1
        # the announce was dropped; the next APPEND carries gen=1, the
        # follower raises ResyncRequired and adopts over a snapshot
        c._ok(f"DEADLINE=20 INSERT 5 77")
    netfaults.clear_plan()
    _wait_until(lambda: fol.core.seq_gen == 1, what="drop-heal adoption")
    _wait_until(lambda: fol.core.applied_seqno == applied0 + 1,
                what="post-drop insert replicated")
    np.testing.assert_array_equal(fol.core.parent, lead.core.parent)

    with ServeClient(lh, lp) as c:
        for row in _skewed_inserts(12, seed=17):
            c.insert([(int(row[0]), int(row[1]))])
    netfaults.install_plan(parse_netfault_plan("dup@reseq:0"))
    with ServeClient(lh, lp) as c:
        assert c.kv("RESEQ")["seq_gen"] == 2
    netfaults.clear_plan()
    _wait_until(lambda: fol.core.seq_gen == 2, what="dup-frame adoption")
    np.testing.assert_array_equal(fol.core.parent, lead.core.parent)
    assert fol.core.sig == lead.core.sig
    lead.shutdown()
    fol.shutdown()


# ---------------------------------------------------------------------------
# fsck: the generation chain (satellite b)
# ---------------------------------------------------------------------------


def test_fsck_reseq_chain_sanctions_and_torn_swap(tmp_path):
    """fsck knows the re-sequence chain: a sealed generation must be
    sanctioned by its manifest; an unsanctioned generation fails; a
    torn mid-swap dir (old-generation WAL records past the
    re-sequenced snapshot boundary) is refused strict and reported
    truncatable in repair."""
    core, sd, _, _ = _state(tmp_path)
    old_sig = core.sig
    for row in _skewed_inserts(10):
        core.insert(row.reshape(1, 2))
    res = run_reseq(core, force=True)
    assert res["seq_gen"] == 1
    snap_applied = core.applied_seqno
    core.close()
    _, failures = fsck_paths([sd], mode="strict")
    assert not failures, failures

    # unsanctioned generation: strip gen 1 from the chain
    man = reseq.load_manifest(sd)
    saved_chain = man["chain"]
    man["chain"] = [c for c in saved_chain if c["gen"] == 0]
    man["phase"] = "hist"
    reseq.save_manifest(sd, man)
    _, failures = fsck_paths([sd], mode="strict")
    assert failures and "never sanctioned" in failures[0][2]
    man["chain"] = saved_chain
    man["phase"] = "done"
    reseq.save_manifest(sd, man)

    # torn mid-swap: an OLD-sig WAL holding a record past the
    # re-sequenced snapshot boundary (the crash window between seal
    # and WAL rotation)
    w = wal_path(sd)
    os.unlink(w)
    create_wal(w, old_sig)
    from sheep_tpu.serve.state import encode_inserts
    with WalAppender(w) as app:
        app.append_at(snap_applied + 1,
                      encode_inserts(np.array([[1, 2]], np.uint32)))
    with pytest.raises(MalformedArtifact) as ei:
        fsck_file = __import__("sheep_tpu.integrity.fsck",
                               fromlist=["fsck_file"]).fsck_file
        fsck_file(w, "strict")
    assert "torn mid-re-sequence swap" in str(ei.value)
    detail = fsck_file(w, "repair")
    assert "torn_records=1" in detail and "truncatable" in detail


def test_reseq_pins_tenant_eviction(tmp_path):
    """A tenant with an in-flight re-sequence manifest refuses
    eviction — evicting would orphan the rebuild mid-phase."""
    from sheep_tpu.serve.tenants import Tenant
    core, sd, _, _ = _state(tmp_path)
    t = Tenant("t", sd, None, 3, core)
    assert t.evictable() in (True, False)  # baseline callable
    man = {"version": reseq.MANIFEST_VERSION, "phase": "fold",
           "cut": 0, "block": 0, "old_sig": core.sig, "new_sig": "",
           "old_gen": 0, "new_gen": 1, "applied_seqno": 0, "plan": {},
           "chain": [{"gen": 0, "sig": core.sig}]}
    reseq.save_manifest(sd, man)
    assert t.evictable() is False
    man["phase"] = "done"
    reseq.save_manifest(sd, man)
    core.close()

# ---------------------------------------------------------------------------
# the orphaned follower: rollback across badrepl (ISSUE 19 satellite)
# ---------------------------------------------------------------------------


def test_orphaned_follower_rolls_back_across_badrepl(tmp_path):
    """PR 18's leftover orphan: a replica that applied a re-sequence
    swap whose leader died before the quorum ack HELLOs the surviving
    leader with a sig the leader's chain has never seen.  That badrepl
    refusal used to retry forever; now the orphan fetches the leader's
    snapshot and — because the leader's sig is in the ORPHAN'S own
    manifest chain (a rollback along its own history, not a foreign
    build input) — adopts it under a durable adoption manifest and
    streams again.  Sound: the swap carried no client writes, so
    nothing acked lives only in the orphaned generation."""
    lcore, lsd, _, _ = _state(tmp_path, "lead")
    for row in _skewed_inserts(12):
        lcore.insert(row.reshape(1, 2))
    lcore.close()
    # the orphan: a bit-identical replica that went one generation
    # AHEAD on a swap the cluster lost with its failed leader
    fsd = str(tmp_path / "orphan")
    shutil.copytree(lsd, fsd)
    orphan = ServeCore.open(fsd)
    res = run_reseq(orphan, force=True)
    assert res["seq_gen"] == 1
    orphan_sig = orphan.sig
    orphan.close()

    lead = ServeDaemon(
        ServeCore.open(lsd), ServeConfig(),
        cluster=ClusterConfig(node_id="L", role="leader", peers=[fsd],
                              hb_s=0.05, failover_s=0.6,
                              poll_timeout_s=1.0)).start()
    lh, lp = lead.address
    assert lead.core.seq_gen == 0  # the cluster never saw gen 1
    fol = ServeDaemon(
        ServeCore.open(fsd), ServeConfig(),
        cluster=ClusterConfig(node_id="F", role="follower", peers=[lsd],
                              hb_s=0.05, failover_s=0.6,
                              poll_timeout_s=1.0)).start()
    assert fol.core.sig == orphan_sig != lead.core.sig
    # the fix: rollback adoption instead of a badrepl retry loop
    _wait_until(lambda: fol.core.sig == lead.core.sig,
                what="orphan rollback adoption")
    assert fol.core.seq_gen == 0
    _wait_until(lambda: lead.hub.follower_count() == 1,
                what="orphan re-attached")
    # ...and the rolled-back replica streams normally again
    with ServeClient(lh, lp) as c:
        c.insert([(3, 141)])
    _wait_until(lambda: fol.core.applied_seqno == lead.core.applied_seqno,
                what="post-rollback insert replicated")
    np.testing.assert_array_equal(fol.core.parent, lead.core.parent)
    # the rollback is SANCTIONED: the orphan's dir passes strict fsck
    _, failures = fsck_paths([fsd], mode="strict")
    assert not failures, failures
    lead.shutdown()
    fol.shutdown()
