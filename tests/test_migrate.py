"""Live tenant migration tests (ISSUE 17): epoch-fenced cutover with
zero acked-insert loss, torn-delta recovery at every frame boundary,
kill -9 at every phase boundary, the 12-case migration netfault sweep
with exact re-dispatch/abort counts, and the rebalancer's hysteresis
(no flapping)."""

import os
import threading
import time

import pytest

from sheep_tpu.io import faultfs
from sheep_tpu.serve import faults as serve_faults
from sheep_tpu.serve import netfaults, rebalance
from sheep_tpu.serve.daemon import ServeConfig, ServeDaemon
from sheep_tpu.serve.migrate import Migration, manifest_path
from sheep_tpu.serve.netfaults import parse_netfault_plan
from sheep_tpu.serve.protocol import ServeClient, ServeError
from sheep_tpu.serve.router import HashRing, Router
from sheep_tpu.serve.state import ServeCore
from sheep_tpu.serve.tenants import TenantManager, TenantSpec
from sheep_tpu.io.edges import write_dat
from sheep_tpu.utils.synth import rmat_edges

TEN = "hot"


@pytest.fixture(autouse=True)
def _clean_fault_plans():
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()
    yield
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()


@pytest.fixture(autouse=True)
def _fast_driver(monkeypatch):
    # keep the driver snappy under test; tests that need a different
    # value override explicitly
    monkeypatch.setenv("SHEEP_MIGRATE_POLL_S", "0.02")
    monkeypatch.setenv("SHEEP_MIGRATE_TIMEOUT_S", "30")


def _wait_until(cond, timeout_s=20.0, poll_s=0.02, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(poll_s)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


def _abrupt_kill(daemon):
    """In-process kill -9: sockets die, nothing flushes or demotes."""
    daemon._stop.set()
    daemon._wake()
    if daemon.watcher is not None:
        daemon.watcher.stop()
    for t in daemon._tenant_entries():
        if t.hub is not None:
            t.hub.stop()
        if t.mig is not None and t.mig.get("replicator") is not None:
            t.mig["replicator"].stop()
    try:
        daemon._listener.close()
    except OSError:
        pass
    for conn in list(daemon._conns.values()):
        try:
            conn.sock.close()
        except OSError:
            pass
    if daemon._hb is not None:
        daemon._hb.stop()
    try:
        os.unlink(os.path.join(daemon.core.state_dir, "serve.addr"))
    except OSError:
        pass


def _ring_name(prefix: str, cluster: str) -> str:
    """A tenant name the two-cluster ring places on ``cluster`` (so
    routed traffic for it needs no override)."""
    ring = HashRing(["c0", "c1"])
    return next(f"{prefix}{i}" for i in range(256)
                if ring.lookup(f"{prefix}{i}") == cluster)


class _Fleet:
    """Two single-node clusters + a durable router; ``TEN`` is spec'd
    on its ring-assigned cluster so migration always moves it to the
    OTHER one.  ``extra`` adds (name, cluster) tenants — names must be
    ring-consistent (see ``_ring_name``)."""

    def __init__(self, tmp_path, log2=6, parts=2, extra=()):
        ring = HashRing(["c0", "c1"])
        self.src = ring.lookup(TEN)
        self.dst = "c1" if self.src == "c0" else "c0"
        tail, head = rmat_edges(log2, 4 << log2, seed=5)
        self.graph = str(tmp_path / "g.dat")
        write_dat(self.graph, tail, head)
        self.tmp = tmp_path
        self.parts = parts
        self.daemons, self.mgrs, self.specs = {}, {}, {}
        want = {self.src: [TEN]}
        for name, cid in extra:
            want.setdefault(cid, []).append(name)
        for cid in ("c0", "c1"):
            core = ServeCore.bootstrap(str(tmp_path / f"{cid}-dflt"),
                                       graph_path=self.graph,
                                       num_parts=parts)
            specs = [TenantSpec(n, str(tmp_path / f"{cid}-{n}"),
                                self.graph, parts)
                     for n in want.get(cid, [])]
            self.specs[cid] = specs
            self.mgrs[cid] = TenantManager(core, specs)
            self.daemons[cid] = ServeDaemon(
                core, ServeConfig(), tenants=self.mgrs[cid]).start()
        self.router = Router(
            {cid: [d.core.state_dir] for cid, d in self.daemons.items()},
            state_dir=str(tmp_path / "router")).start()

    def restart(self, cid):
        """kill -9 + restart cluster ``cid``'s daemon on its state
        dirs (spec'd tenants re-spec'd, adopted ones re-read from the
        durable registry)."""
        _abrupt_kill(self.daemons[cid])
        core = ServeCore.open(self.daemons[cid].core.state_dir)
        self.mgrs[cid] = TenantManager(core, self.specs[cid])
        self.daemons[cid] = ServeDaemon(
            core, ServeConfig(), tenants=self.mgrs[cid]).start()
        return self.daemons[cid]

    def client(self, cid=None):
        addr = self.router.address if cid is None \
            else self.daemons[cid].address
        c = ServeClient(addr[0], addr[1], timeout_s=20.0)
        return c

    def insert_n(self, n, base=0, tenant=TEN):
        with self.client() as c:
            c.tenant(tenant)
            for i in range(base, base + n):
                c.insert([(i % 60, (i * 7 + 1) % 60)])

    def src_core(self):
        return self.mgrs[self.src].get(TEN).core

    def dst_core(self):
        return self.mgrs[self.dst].core_of(TEN)

    def shutdown(self):
        self.router.shutdown()
        for d in self.daemons.values():
            d.shutdown()


def _who_accepts_insert(fleet) -> list[str]:
    """Which clusters ACK an INSERT for TEN right now (the ownership
    probe: must never exceed one)."""
    owners = []
    for cid, d in fleet.daemons.items():
        try:
            with fleet.client(cid) as c:
                c.tenant(TEN)
                c.insert([(0, 1)])
                owners.append(cid)
        except Exception:
            continue
    return owners


# ---------------------------------------------------------------------------
# the happy path: routed MIGRATE, zero loss, fence, remap durability
# ---------------------------------------------------------------------------


def test_routed_migrate_moves_tenant_crc_equal(tmp_path):
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(20)
        src_crc = fleet.src_core().state_crc()
        with fleet.client() as c:
            c.tenant(TEN)
            rec = c.kv(f"MIGRATE {TEN} {fleet.dst} wait=30")
            assert rec["phase"] == "done", rec
            # CRC-equal tenant tree on the target, epoch advanced,
            # nothing lost
            dst = fleet.dst_core()
            assert dst.applied_seqno == 20
            assert dst.state_crc() == src_crc
            assert dst.epoch == fleet.src_core().epoch + 1
            # the source answers a TYPED moved refusal, never silence
            with fleet.client(fleet.src) as direct:
                direct.tenant(TEN)
                with pytest.raises(ServeError) as ei:
                    direct.insert([(1, 2)])
            assert ei.value.code == "moved"
            assert f"dest={fleet.dst}" in ei.value.detail
            # routed writes land on the new home transparently
            c.insert([(7, 9)])
            assert fleet.dst_core().applied_seqno == 21
        # the remap is durable: a restarted router reads tenant-map
        r2 = Router({cid: [d.core.state_dir]
                     for cid, d in fleet.daemons.items()},
                    state_dir=fleet.router.state_dir)
        assert r2.placement_of(TEN) == fleet.dst
        # exactly one owner, and it is the destination
        assert _who_accepts_insert(fleet) == [fleet.dst]
    finally:
        fleet.shutdown()


def test_migrate_under_write_load_zero_acked_loss(tmp_path):
    """A writer hammers routed inserts THROUGH the cutover; every ack
    is exactly one applied record on the final owner — no acked insert
    lost, none applied twice."""
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(10)
        stop = threading.Event()
        acked = []
        errs = []

        def hammer():
            with fleet.client() as c:
                c.tenant(TEN)
                i = 0
                while not stop.is_set():
                    try:
                        c.insert([(i % 60, (i * 3 + 2) % 60)])
                        acked.append(i)
                    except ServeError:
                        # typed refusal = NOT applied; retrying the
                        # same record is epoch-safe
                        continue
                    except (OSError, ConnectionError) as exc:
                        errs.append(str(exc))
                        return
                    i += 1

        th = threading.Thread(target=hammer, daemon=True)
        th.start()
        time.sleep(0.1)
        with fleet.client() as c:
            rec = c.kv(f"MIGRATE {TEN} {fleet.dst} wait=30")
        assert rec["phase"] == "done", rec
        time.sleep(0.15)  # a few post-cut acks through the new home
        stop.set()
        th.join(timeout=10)
        assert not errs, errs
        assert len(acked) > 10
        # one batch = one seqno: equality is BOTH invariants at once
        assert fleet.dst_core().applied_seqno == 10 + len(acked)
        assert _who_accepts_insert(fleet) == [fleet.dst]
    finally:
        fleet.shutdown()


# ---------------------------------------------------------------------------
# torn delta stream: every frame boundary admits nothing partial
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("frame", range(6))
def test_torn_delta_every_frame_boundary(tmp_path, frame):
    """Partition the migration delta stream at frame ``frame`` of 6:
    the tear admits nothing partial (applied stays a contiguous
    prefix), the stream reconnects and re-streams, and the drained
    tree is CRC-equal."""
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(4)
        dh, dp = fleet.daemons[fleet.src].address
        with fleet.client(fleet.dst) as c:
            rec = c.kv(f"MIG ADOPT {TEN} host={dh} port={dp}")
            assert rec["phase"] == "delta"
            # stream attached and drained to the bootstrap point
            _wait_until(lambda: int(c.kv(f"MIG STAT {TEN}")
                                    ["applied"]) >= 4,
                        what="delta stream caught up")
        netfaults.install_plan(
            parse_netfault_plan(f"partition@mdelta:{frame}"))
        fleet.insert_n(6, base=100)
        src_core = fleet.src_core()
        dst_core = fleet.dst_core()
        seen = set()
        _wait_until(lambda: (seen.add(dst_core.applied_seqno) or
                             dst_core.applied_seqno >= 10),
                    what=f"re-streamed past torn frame {frame}")
        # nothing partial was ever admitted: applied only ever grew
        # through contiguous prefixes, never past the source
        assert all(s <= src_core.applied_seqno for s in seen)
        assert dst_core.state_crc() == src_core.state_crc()
    finally:
        fleet.shutdown()


# ---------------------------------------------------------------------------
# kill -9 at every phase boundary: resumable or cleanly abortable
# ---------------------------------------------------------------------------


def test_kill9_target_after_adopt_resumes(tmp_path):
    """Boundary 1 (snap/delta): the target dies right after adopting;
    the restarted target re-reads the durable adoption registry and a
    re-issued MIGRATE completes with zero loss."""
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(12)
        dh, dp = fleet.daemons[fleet.src].address
        with fleet.client(fleet.dst) as c:
            c.kv(f"MIG ADOPT {TEN} host={dh} port={dp}")
        fleet.restart(fleet.dst)
        # the adopted tenant survived the kill (registered, resumable)
        assert TEN in fleet.mgrs[fleet.dst].names()
        with fleet.client() as c:
            rec = c.kv(f"MIGRATE {TEN} {fleet.dst} wait=30")
        assert rec["phase"] == "done", rec
        assert fleet.dst_core().applied_seqno == 12
        assert fleet.dst_core().state_crc() == \
            fleet.src_core().state_crc()
        assert _who_accepts_insert(fleet) == [fleet.dst]
    finally:
        fleet.shutdown()


def test_kill9_source_after_seal_stays_fenced_then_resumes(tmp_path):
    """Boundary 2 (cutover entry): the source dies after sealing the
    fence.  The fence is DURABLE — the restarted source still answers
    typed moved — and a re-driven migration completes.  The tenant is
    never dual-owned."""
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(9)
        dh, dp = fleet.daemons[fleet.src].address
        with fleet.client(fleet.dst) as c:
            c.kv(f"MIG ADOPT {TEN} host={dh} port={dp}")
            _wait_until(lambda: int(c.kv(f"MIG STAT {TEN}")
                                    ["applied"]) >= 9,
                        what="delta drained")
        with fleet.client(fleet.src) as c:
            seal = c.kv(f"MIG SEAL {TEN} dest={fleet.dst}")
        assert int(seal["applied"]) == 9
        fleet.restart(fleet.src)
        # durable fence: still refusing with the destination named
        with fleet.client(fleet.src) as direct:
            direct.tenant(TEN)
            with pytest.raises(ServeError) as ei:
                direct.insert([(1, 2)])
        assert ei.value.code == "moved"
        assert _who_accepts_insert(fleet) == []  # fenced, not dual
        with fleet.client() as c:
            rec = c.kv(f"MIGRATE {TEN} {fleet.dst} wait=30")
        assert rec["phase"] == "done", rec
        assert fleet.dst_core().applied_seqno == 9
        assert _who_accepts_insert(fleet) == [fleet.dst]
    finally:
        fleet.shutdown()


def test_kill9_router_after_cut_finishes_forward(tmp_path):
    """Boundary 3 (post-CUT): once the target's epoch advanced, abort
    is ILLEGAL — a router resuming a cut_done manifest finishes the
    remap forward and never unseals the source."""
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(7)
        dh, dp = fleet.daemons[fleet.src].address
        with fleet.client(fleet.dst) as c:
            c.kv(f"MIG ADOPT {TEN} host={dh} port={dp}")
            _wait_until(lambda: int(c.kv(f"MIG STAT {TEN}")
                                    ["applied"]) >= 7,
                        what="delta drained")
        with fleet.client(fleet.src) as c:
            seal = c.kv(f"MIG SEAL {TEN} dest={fleet.dst}")
        with fleet.client(fleet.dst) as c:
            c.kv(f"MIG CUT {TEN} epoch={int(seal['epoch']) + 1} "
                 f"expect={seal['applied']}")
        # the router died between CUT and remap: hand-land its
        # manifest exactly as Migration._save would have left it
        mig = Migration(fleet.router, TEN, fleet.dst)
        mig.phase = "cutover"
        mig.cut_done = True
        mig.seal_epoch = int(seal["epoch"])
        mig.seal_applied = int(seal["applied"])
        mig._save()
        fleet.router.shutdown()
        r2 = Router({cid: [d.core.state_dir]
                     for cid, d in fleet.daemons.items()},
                    state_dir=fleet.router.state_dir).start()
        fleet.router = r2
        _wait_until(lambda: r2.placement_of(TEN) == fleet.dst,
                    what="resumed router finished the remap")
        _wait_until(lambda: r2.mig_completed == 1,
                    what="resume counted as completed")
        # forward-only: the source fence was NOT lifted
        assert fleet.mgrs[fleet.src].get(TEN).moved_dest == fleet.dst
        assert _who_accepts_insert(fleet) == [fleet.dst]
        before = fleet.dst_core().applied_seqno  # probe inserted one
        with fleet.client() as c:
            c.tenant(TEN)
            c.insert([(3, 4)])
        assert fleet.dst_core().applied_seqno == before + 1
    finally:
        fleet.shutdown()


def test_unreachable_dest_aborts_cleanly_to_source(tmp_path, monkeypatch):
    """A migration that cannot reach its destination aborts back: the
    fence lifts, the source still owns every acked insert, nothing is
    lost."""
    monkeypatch.setenv("SHEEP_MIGRATE_RETRIES", "1")
    monkeypatch.setenv("SHEEP_MIGRATE_TIMEOUT_S", "6")
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(5)
        _abrupt_kill(fleet.daemons[fleet.dst])
        mig = fleet.router.start_migration(TEN, fleet.dst)
        assert mig.done.wait(30)
        assert mig.phase == "aborted", (mig.phase, mig.error)
        assert fleet.router.mig_aborted == 1
        # clean abort: source unfenced (or never fenced), still owner
        assert fleet.mgrs[fleet.src].get(TEN).moved_dest is None
        with fleet.client() as c:
            c.tenant(TEN)
            c.insert([(2, 3)])
        assert fleet.src_core().applied_seqno == 6
    finally:
        fleet.router.shutdown()
        fleet.daemons[fleet.src].shutdown()


# ---------------------------------------------------------------------------
# the 12-case migration netfault sweep, with exact re-dispatch counts
# ---------------------------------------------------------------------------

#: kind@site -> driver re-dispatches the fault must cost (msnap faults
#: surface as one retried ADOPT; mcut drop/partition retry one cutover
#: RPC; slow/dup and every mdelta fault recover BELOW the driver, so
#: zero re-dispatches)
SWEEP = {
    ("drop", "msnap"): 1, ("partition", "msnap"): 1,
    ("slow", "msnap"): 0, ("dup", "msnap"): 0,
    ("drop", "mdelta"): 0, ("partition", "mdelta"): 0,
    ("slow", "mdelta"): 0, ("dup", "mdelta"): 0,
    ("drop", "mcut"): 1, ("partition", "mcut"): 1,
    ("slow", "mcut"): 0, ("dup", "mcut"): 0,
}


@pytest.mark.parametrize("kind,site",
                         sorted(SWEEP), ids=lambda v: str(v))
def test_netfault_sweep(tmp_path, kind, site):
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(8)
        src_crc = fleet.src_core().state_crc()
        if site == "mdelta":
            # delta frames only flow for records past the bootstrap
            # snapshot: adopt first, fault the live stream
            dh, dp = fleet.daemons[fleet.src].address
            with fleet.client(fleet.dst) as c:
                c.kv(f"MIG ADOPT {TEN} host={dh} port={dp}")
                _wait_until(lambda: int(c.kv(f"MIG STAT {TEN}")
                                        ["applied"]) >= 8,
                            what="stream attached")
            netfaults.install_plan(
                parse_netfault_plan(f"{kind}@{site}:0"))
            fleet.insert_n(4, base=200)
            src_crc = fleet.src_core().state_crc()
        else:
            netfaults.install_plan(
                parse_netfault_plan(f"{kind}@{site}:0"))
        mig = fleet.router.start_migration(TEN, fleet.dst)
        assert mig.done.wait(30)
        assert mig.phase == "done", (kind, site, mig.error)
        assert mig.redispatches == SWEEP[(kind, site)], (kind, site)
        assert fleet.router.mig_aborted == 0
        assert fleet.dst_core().state_crc() == src_crc
        assert _who_accepts_insert(fleet) == [fleet.dst]
    finally:
        fleet.shutdown()


# ---------------------------------------------------------------------------
# the rebalancer: hysteresis, cooldown, one-at-a-time (pure decide)
# ---------------------------------------------------------------------------


def _fold(**tenant_requests):
    return {"tenants": {t: {"requests": float(r), "applied": 500,
                            "p99": 0.001, "mig": False}
                        for t, r in tenant_requests.items()},
            "clusters": {}}


def test_rebalancer_hysteresis_holds_inside_band():
    placements = {"a": "c0", "b": "c0", "c": "c1"}
    prev = _fold(a=0, b=0, c=0)
    cur = _fold(a=60, b=50, c=100)  # 110 vs 100: inside 1.5x band
    v = rebalance.decide(prev, cur, 1.0, placements,
                         hysteresis=1.5, min_qps=5.0)
    assert v["action"] == "hold"
    assert "hysteresis" in v["reason"]


def test_rebalancer_migrates_sustained_hot_then_does_not_flap():
    # two tenants on c0 (30 + 20 qps) vs 25 on c1: moving ``b``
    # shrinks the imbalance from 25 to 15, so it prices out
    placements = {"a": "c0", "b": "c0", "c": "c1"}
    prev = _fold(a=0, b=0, c=0)
    cur = _fold(a=30, b=20, c=25)
    v = rebalance.decide(prev, cur, 1.0, placements,
                         hysteresis=1.6, min_qps=5.0)
    assert v["action"] == "migrate"
    assert (v["tenant"], v["src"], v["dest"]) == ("b", "c0", "c1")
    assert v["plan"]["migrate"] == "go"
    # after the move the SAME traffic pattern must hold, not bounce
    # a tenant straight back (no flapping): 45 vs 30 is inside 1.6x
    moved = {"a": "c0", "b": "c1", "c": "c1"}
    v2 = rebalance.decide(prev, cur, 1.0, moved,
                          hysteresis=1.6, min_qps=5.0)
    assert v2["action"] == "hold"
    assert "hysteresis" in v2["reason"]


def test_rebalancer_quiet_fleet_and_gates_hold():
    placements = {"a": "c0", "b": "c0", "c": "c1"}
    prev = _fold(a=0, b=0, c=0)
    cur = _fold(a=3, b=0, c=0)  # skewed but under min qps
    v = rebalance.decide(prev, cur, 1.0, placements,
                         hysteresis=1.5, min_qps=5.0)
    assert v["action"] == "hold" and "quiet" in v["reason"]
    hotcur = _fold(a=400, b=100, c=10)
    v = rebalance.decide(prev, hotcur, 1.0, placements,
                         hysteresis=1.5, min_qps=5.0,
                         migration_inflight=True)
    assert v["action"] == "hold" and "in flight" in v["reason"]
    v = rebalance.decide(prev, hotcur, 1.0, placements,
                         hysteresis=1.5, min_qps=5.0,
                         cooldown_remaining_s=9.0)
    assert v["action"] == "hold" and "cooling" in v["reason"]
    # a tenant mid-migration anywhere holds every verdict
    midmig = _fold(a=400, b=100, c=10)
    midmig["tenants"]["a"]["mig"] = True
    v = rebalance.decide(prev, midmig, 1.0, placements,
                         hysteresis=1.5, min_qps=5.0)
    assert v["action"] == "hold" and "mid-migration" in v["reason"]
    # a single busy tenant on the hot cluster can never price out:
    # moving it only swaps which side is overloaded
    solo = {"a": "c0", "c": "c1"}
    v = rebalance.decide(prev, _fold(a=400, b=0, c=10), 1.0, solo,
                         hysteresis=1.5, min_qps=5.0)
    assert v["action"] == "hold" and "prices out" in v["reason"]


def test_rebalancer_live_tick_migrates_hot_tenant(tmp_path):
    """End to end off the real fleet scrape: the hot tenant on a
    skewed cluster gets live-migrated by the rebalancer's own
    verdict.  The source cluster keeps a warm tenant (so moving the
    hot one strictly shrinks the imbalance) and the destination hosts
    a cold one (so both clusters appear in the placement map)."""
    src0 = HashRing(["c0", "c1"]).lookup(TEN)
    dst0 = "c1" if src0 == "c0" else "c0"
    warm = _ring_name("warm", src0)
    cold = _ring_name("cold", dst0)
    fleet = _Fleet(tmp_path, extra=((warm, src0), (cold, dst0)))
    try:
        fleet.insert_n(5)
        fleet.insert_n(2, tenant=warm)
        fleet.insert_n(1, tenant=cold)
        rb = rebalance.Rebalancer(fleet.router, interval_s=999,
                                  cooldown_s=0.0, hysteresis=1.2,
                                  min_qps=1.0)
        fleet.router.rebalancer = rb
        assert rb.tick() is None  # first fold: no qps baseline yet
        # sustained skew: hot tenant hammers src, warm keeps enough
        # remainder that moving HOT strictly shrinks the imbalance
        # (the default tenant's health/scrape traffic rides one side
        # or the other, so leave wide margins)
        fleet.insert_n(60, base=300)
        fleet.insert_n(20, base=300, tenant=warm)
        fleet.insert_n(1, base=300, tenant=cold)
        v = rb.tick()
        assert v is not None and v["action"] == "migrate", v
        assert (v["tenant"], v["dest"]) == (TEN, fleet.dst)
        mig = fleet.router._migrations[TEN]
        assert mig.done.wait(30) and mig.phase == "done"
        assert fleet.router.placement_of(TEN) == fleet.dst
        # the scrape now shows the verdict counters (the router's own
        # series ride the fan-in relabeled like any member's)
        from sheep_tpu.obs.metrics import parse_prometheus
        samples = {(n, labels.get("action")): val for n, labels, val
                   in parse_prometheus(
                       fleet.router.fleet_metrics().decode("ascii"))}
        assert samples[("sheep_rebalance_verdicts_total",
                        "migrate")] == 1
        assert samples[("sheep_migrate_completed", None)] == 1
        assert samples[("sheep_migrate_aborted", None)] == 0
    finally:
        fleet.shutdown()


# ---------------------------------------------------------------------------
# manifest + marker durability odds and ends
# ---------------------------------------------------------------------------


def test_migrate_rejects_bad_requests(tmp_path):
    fleet = _Fleet(tmp_path)
    try:
        with fleet.client() as c:
            with pytest.raises(ServeError) as ei:
                c.kv(f"MIGRATE {TEN} nosuchcluster")
            assert ei.value.code == "badreq"
            with pytest.raises(ServeError) as ei:
                c.kv(f"MIGRATE {TEN} {fleet.src}")  # already home
            assert ei.value.code == "badreq"
            with pytest.raises(ServeError):
                c.kv("MIGRATE onlyonearg")
    finally:
        fleet.shutdown()


def test_manifest_lands_durably_per_phase(tmp_path):
    fleet = _Fleet(tmp_path)
    try:
        fleet.insert_n(6)
        with fleet.client() as c:
            rec = c.kv(f"MIGRATE {TEN} {fleet.dst} wait=30")
        assert rec["phase"] == "done"
        import json
        with open(manifest_path(fleet.router.state_dir, TEN)) as f:
            m = json.load(f)
        assert m["phase"] == "done" and m["cut_done"] is True
        assert m["tenant"] == TEN and m["dest"] == fleet.dst
    finally:
        fleet.shutdown()
