"""Multi-host distext (ISSUE 16): remote build workers over the fleet
wire.  Covered here: the LEG/OK header grammars and their refusals, the
transport pricer (``plan_transport`` — pin / default / priced both
ways), the end-to-end remote build (2 in-process worker daemons, no
shared state dir, tree bit-identical to the in-RAM oracle with every
dispatch count exactly 1), the torn artifact-return property sweep (the
worker->supervisor stream cut at EVERY frame boundary plus mid-payload
offsets — nothing lands without a verified crc), the full worker-wire
netfault sweep (drop/partition/slow/dup at wleg/wbeat/wart with exact
dispatch counts), SHEEP_FAULT_PLAN chaos under the remote runner, wire
BEAT frames feeding the local heartbeat file, silent-wire speculation
with first-finisher-wins, the ``--status`` remote columns, and the
worker METRICS scrape through ``sheep top``'s fleet view."""

import json
import os
import socket
import threading
import time
import zlib

import pytest

from sheep_tpu.core import build_forest, degree_sequence
from sheep_tpu.io.edges import write_dat
from sheep_tpu.io.trefile import write_tree
from sheep_tpu.ops.distext import run_distext
from sheep_tpu.plan import PROV_DEFAULT, PROV_FORCED, PROV_PRICED, \
    plan_transport
from sheep_tpu.serve import netfaults
from sheep_tpu.serve.netfaults import NetFault, NetFaultPlan
from sheep_tpu.serve.protocol import BadRequest, ServeClient
from sheep_tpu.serve.worker import (WorkerDaemon, parse_leg_header,
                                    parse_result_header,
                                    parse_worker_addrs, payload_crc,
                                    read_worker_addr)
from sheep_tpu.supervisor import (InlineRunner, RemoteRunner,
                                  SupervisorConfig, wire_status_path)

pytestmark = pytest.mark.filterwarnings("ignore::UserWarning")


@pytest.fixture
def worker_env(monkeypatch):
    for k in ("SHEEP_EXT_BLOCK", "SHEEP_EXT_STRATEGY", "SHEEP_MEM_BUDGET",
              "SHEEP_DISK_BUDGET", "SHEEP_IO_FAULT_PLAN",
              "SHEEP_FAULT_PLAN", "SHEEP_DISTEXT_LEGS", "SHEEP_LEG_CORES",
              "SHEEP_WORKERS", "SHEEP_WORKER_ADDRS", "SHEEP_WORKER_BEAT_S",
              "SHEEP_WORKER_SPECULATE_S", "SHEEP_WORKER_TRANSPORT",
              "SHEEP_SERVE_NETFAULT_PLAN", "SHEEP_SPECULATE_S"):
        monkeypatch.delenv(k, raising=False)
    netfaults.clear_plan()
    from sheep_tpu.io import faultfs
    from sheep_tpu.runtime import clear_plan, reset_counters
    faultfs.clear_plan()
    clear_plan()
    reset_counters()
    yield monkeypatch
    netfaults.clear_plan()
    faultfs.clear_plan()
    clear_plan()


@pytest.fixture(scope="module")
def graph(tmp_path_factory):
    """One small graph + its oracle tree bytes, shared by the e2e
    tests (building it is the slow part, not the wire)."""
    from sheep_tpu.cli.graph2tree import _tree_sig
    from sheep_tpu.utils.synth import rmat_edges
    tmp = tmp_path_factory.mktemp("wgraph")
    log_n = 9
    tail, head = rmat_edges(log_n, 4 * (1 << log_n), seed=41)
    path = str(tmp / "g.dat")
    write_dat(path, tail, head)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    oracle = str(tmp / "oracle.tre")
    write_tree(oracle, want.parent, want.pst_weight, sig=_tree_sig(seq))
    with open(oracle, "rb") as f:
        return path, f.read()


@pytest.fixture
def workers(tmp_path):
    """Two in-process worker daemons with separate state dirs — the
    loopback stand-in for two hosts (nothing shared but the wire)."""
    pair = [WorkerDaemon(str(tmp_path / f"w{i}")).start() for i in (1, 2)]
    yield pair
    for w in pair:
        w.shutdown()


def _remote_config(workers, **kw):
    kw.setdefault("workers", 2)
    kw.setdefault("poll_s", 0.01)
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("grammar", False)
    kw.setdefault("worker_addrs", [w.address for w in workers])
    kw.setdefault("worker_beat_s", 0.05)
    return SupervisorConfig(**kw)


def _run_remote(graph_path, state_dir, workers, **kw):
    cfg = _remote_config(workers, **kw)
    m = run_distext(graph_path, str(state_dir), cfg,
                    runner=InlineRunner(0.05), legs=2)
    with open(m.final_tree, "rb") as f:
        return f.read(), m


def _counts(manifest):
    return {leg.key: leg.dispatches for leg in manifest.legs}


# ---------------------------------------------------------------------------
# wire grammars
# ---------------------------------------------------------------------------


def test_parse_worker_addrs():
    assert parse_worker_addrs("") == []
    assert parse_worker_addrs("127.0.0.1:7070") == [("127.0.0.1", 7070)]
    assert parse_worker_addrs(" a:1 ,, b:2 ") == [("a", 1), ("b", 2)]
    for bad in ("justhost", ":7070", "host:"):
        with pytest.raises(ValueError):
            parse_worker_addrs(bad)


def test_parse_leg_header_accepts_well_formed():
    job = parse_leg_header(
        "LEG key=g00.hist kind=hist start=10 end=20 beat=0.5 "
        "bytes=120 crc=7 seqbytes=0 seqcrc=0")
    assert job["key"] == "g00.hist" and job["kind"] == "hist"
    assert (job["start"], job["end"], job["bytes"]) == (10, 20, 120)
    assert job["beat"] == 0.5


@pytest.mark.parametrize("line", [
    "PING",
    "LEG kind=hist start=0 end=1 bytes=12 crc=0",         # no key
    "LEG key=k kind=sort start=0 end=1 bytes=12 crc=0",   # bad kind
    "LEG key=k kind=hist start=5 end=2 bytes=12 crc=0",   # bad range
    "LEG key=k kind=hist start=0 end=2 bytes=12 crc=0",   # bytes != 12*n
    "LEG key=k kind=hist start=0 end=x bytes=12 crc=0",   # non-numeric
    "LEG key=k kind=distmap start=0 end=1 bytes=12 crc=0 seqbytes=0",
])
def test_parse_leg_header_refuses_garbage(line):
    with pytest.raises(BadRequest):
        parse_leg_header(line)


def test_parse_result_header_err_is_typed_conn_loss():
    """A worker's ERR (or stream garbage) funnels into the supervisor's
    typed connection-loss retry path, not an unhandled parse error."""
    good = parse_result_header(
        "OK key=k sumbytes=1 sumcrc=2 bytes=3 crc=4 perfbytes=5 perfcrc=6")
    assert good["bytes"] == 3 and good["perfcrc"] == 6
    for bad in ("ERR legfail boom", "garbage", "OK key=k sumbytes=1"):
        with pytest.raises(ConnectionError):
            parse_result_header(bad)


# ---------------------------------------------------------------------------
# the transport pricer
# ---------------------------------------------------------------------------


def test_plan_transport_no_workers_defaults_local():
    d = plan_transport(1 << 20, 4, 0)
    assert d["transport"] == "local" and d["provenance"] == PROV_DEFAULT


def test_plan_transport_pin_is_forced(monkeypatch):
    for pin in ("ship", "local"):
        d = plan_transport(1 << 20, 4, 2, pin=pin)
        assert d["transport"] == pin and d["provenance"] == PROV_FORCED
    monkeypatch.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    d = plan_transport(1 << 20, 4, 2)
    assert d["transport"] == "ship" and d["provenance"] == PROV_FORCED
    with pytest.raises(ValueError):
        plan_transport(1 << 20, 4, 2, pin="carrier-pigeon")


def test_plan_transport_prices_both_ways():
    # 1 host core, 4 workers: shipping quarters the wave count and the
    # saved waves outweigh the one wire crossing -> ship wins (2
    # workers on 1 core is the exact TIE with these constants — wave
    # savings equal the crossing — and a tie stays local)
    tie = plan_transport(1 << 24, 4, 2, host_cores=1)
    assert tie["transport"] == "local" and tie["ship_s"] == tie["local_s"]
    d = plan_transport(1 << 24, 4, 4, host_cores=1)
    assert d["transport"] == "ship" and d["provenance"] == PROV_PRICED
    assert d["ship_s"] < d["local_s"]
    # plenty of local cores, 1 worker: same wave count both sides, the
    # wire crossing is pure overhead -> local wins (strictly-cheaper
    # rule: a tie must stay local too)
    d = plan_transport(1 << 24, 4, 1, host_cores=8)
    assert d["transport"] == "local" and d["provenance"] == PROV_PRICED
    assert d["ship_s"] >= d["local_s"]


# ---------------------------------------------------------------------------
# end to end over the wire
# ---------------------------------------------------------------------------


def test_remote_build_bit_identical(graph, workers, tmp_path, worker_env):
    """2 worker daemons, separate state dirs, nothing shared with the
    supervisor: the final tree is byte-identical to the in-RAM oracle,
    every leg dispatched exactly once, and each shipped leg's artifact
    + provenance are where the design says."""
    path, oracle = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    got, m = _run_remote(path, tmp_path / "sup", workers)
    assert got == oracle
    assert all(n == 1 for n in _counts(m).values()), _counts(m)
    # the hist/distmap legs went over the wire: provenance JSON per leg
    wires = [f for f in os.listdir(tmp_path / "sup")
             if f.startswith("wire-")]
    assert len(wires) == 4, wires  # 2 hist + 2 distmap legs
    for w in workers:
        made = os.listdir(w.state_dir)
        assert any(f.endswith(".slice.dat") for f in made), made
    row = json.load(open(wire_status_path(str(tmp_path / "sup"),
                                          m.legs[0].output)))
    assert row["dispatches"] == 1 and row["speculations"] == 0
    assert row["worker"].startswith("127.0.0.1:")


def test_remote_config_from_env(worker_env, workers):
    h1, p1 = workers[0].address
    h2, p2 = workers[1].address
    worker_env.setenv("SHEEP_WORKER_ADDRS", f"{h1}:{p1},{h2}:{p2}")
    worker_env.setenv("SHEEP_WORKER_BEAT_S", "0.25")
    worker_env.setenv("SHEEP_WORKER_SPECULATE_S", "3.5")
    cfg = SupervisorConfig.from_env()
    assert cfg.worker_addrs == [(h1, p1), (h2, p2)]
    assert cfg.worker_beat_s == 0.25
    assert cfg.worker_speculate_s == 3.5


def test_transport_pin_local_keeps_legs_local(graph, workers, tmp_path,
                                              worker_env):
    """SHEEP_WORKER_TRANSPORT=local with workers configured: the pin
    wins, no leg touches the wire."""
    path, oracle = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "local")
    got, m = _run_remote(path, tmp_path / "sup", workers)
    assert got == oracle
    assert not [f for f in os.listdir(tmp_path / "sup")
                if f.startswith("wire-")]
    for w in workers:
        assert not [f for f in os.listdir(w.state_dir)
                    if f.endswith(".slice.dat")]


# ---------------------------------------------------------------------------
# torn artifact return: the property sweep
# ---------------------------------------------------------------------------


def _result_stream(key, sum_bytes, art_bytes, perf_bytes):
    head = (f"OK key={key} sumbytes={len(sum_bytes)} "
            f"sumcrc={payload_crc(sum_bytes)} bytes={len(art_bytes)} "
            f"crc={payload_crc(art_bytes)} perfbytes={len(perf_bytes)} "
            f"perfcrc={payload_crc(perf_bytes)}\n").encode("ascii")
    return head, head + sum_bytes + art_bytes + perf_bytes


def _fake_handle(tmp_path, spec):
    """A _RemoteHandle shell wired for _receive alone (no session
    thread): the unit under test is the admission gate."""
    from sheep_tpu.supervisor.remote import _RemoteHandle

    class _R:
        def attempt_done(self, final):
            pass

    h = _RemoteHandle.__new__(_RemoteHandle)
    h._runner = _R()
    h._spec = spec
    h._hb = str(tmp_path / "a.hb")
    h._log = str(tmp_path / "a.log")
    h._rc = None
    h._lock = threading.Lock()
    h._socks = []
    h.cancelled = False
    h.worker = "test:0"
    return h


def _feed(handle, spec, stream):
    a, b = socket.socketpair()
    try:
        a.sendall(stream)
        a.shutdown(socket.SHUT_WR)
        handle._receive(b, spec)
    finally:
        a.close()
        b.close()


def test_torn_return_cut_everywhere_admits_nothing(tmp_path):
    """Cut the worker's result stream at EVERY frame boundary and at
    offsets inside each payload: no prefix lands a single byte at the
    attempt temp, every cut is the typed conn-loss failure, and only
    the complete stream admits — crc-verified, bytes intact."""
    sum_bytes = b"sheep-sum 1\nalgo crc32\nsize 96\nsum DEADBEEF\n"
    art_bytes = os.urandom(96)
    perf_bytes = json.dumps({"perf": {}}).encode()
    tmp = str(tmp_path / "leg.tre.a1")
    spec = {"kind": "hist", "graph": "g", "seq": None, "out": tmp,
            "perf": None, "start": 0, "end": 8, "final": tmp[:-3],
            "attempt": 1, "key": "leg.tre"}
    head, stream = _result_stream(spec["key"], sum_bytes, art_bytes,
                                  perf_bytes)
    # every frame boundary + offsets inside every span
    cuts = sorted({0, 1, len(head) - 1, len(head),
                   len(head) + len(sum_bytes) // 2,
                   len(head) + len(sum_bytes),
                   len(head) + len(sum_bytes) + 1,
                   len(head) + len(sum_bytes) + len(art_bytes) // 2,
                   len(head) + len(sum_bytes) + len(art_bytes),
                   len(stream) - 1})
    for cut in cuts:
        assert cut < len(stream)
        h = _fake_handle(tmp_path, spec)
        with pytest.raises(ConnectionError):
            _feed(h, spec, stream[:cut])
        assert h.poll() is None  # the session loop owns the rc
        assert not os.path.exists(tmp), cut
        assert not os.path.exists(tmp + ".sum"), cut
        assert not os.path.exists(tmp + ".fetch"), cut
    # a complete stream with ONE flipped artifact byte: refused whole
    flipped = bytearray(stream)
    flipped[len(head) + len(sum_bytes) + 5] ^= 0xFF
    h = _fake_handle(tmp_path, spec)
    with pytest.raises(ConnectionError):
        _feed(h, spec, bytes(flipped))
    assert not os.path.exists(tmp)
    # the complete, untampered stream admits bytes-intact
    h = _fake_handle(tmp_path, spec)
    _feed(h, spec, stream)
    assert h.poll() == 0
    with open(tmp, "rb") as f:
        assert f.read() == art_bytes
    with open(tmp + ".sum", "rb") as f:
        assert f.read() == sum_bytes


def test_torn_return_end_to_end_redispatches_exactly_once(
        graph, workers, tmp_path, worker_env):
    """The acceptance property on the REAL wire: tear the first
    artifact return mid-payload (partition@wart) — the crc gate refuses
    it, exactly one leg re-dispatches, the final tree is bit-identical."""
    path, oracle = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    netfaults.install_plan(netfaults.parse_netfault_plan(
        "partition@wart:0"))
    got, m = _run_remote(path, tmp_path / "sup", workers, deadline_s=5.0)
    assert got == oracle
    counts = _counts(m)
    assert sorted(counts.values()) == [1, 1, 1, 1, 1, 2], counts


# ---------------------------------------------------------------------------
# the worker-wire netfault sweep
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind,site,redispatch", [
    ("drop", "wleg", True),        # job never arrives; staleness fires
    ("partition", "wleg", True),   # link dies before dispatch
    ("slow", "wleg", False),       # latency, not loss
    ("dup", "wleg", False),        # twin delivery; first finisher wins
    ("partition", "wbeat", True),  # link dies mid-leg
    ("drop", "wart", True),        # result never sent
    ("partition", "wart", True),   # torn mid-payload; crc refuses
    ("slow", "wart", False),
    ("dup", "wart", False),        # double delivery; second discarded
])
def test_netfault_sweep_exact_counts(graph, workers, tmp_path, worker_env,
                                     kind, site, redispatch):
    path, oracle = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    netfaults.install_plan(netfaults.parse_netfault_plan(
        f"{kind}@{site}:0"))
    got, m = _run_remote(path, tmp_path / "sup", workers, deadline_s=1.0)
    assert got == oracle, (kind, site)
    counts = _counts(m)
    want = [1, 1, 1, 1, 1, 2] if redispatch else [1] * 6
    assert sorted(counts.values()) == want, (kind, site, counts)


def test_chaos_plan_applies_to_remote_legs(graph, workers, tmp_path,
                                           worker_env):
    """SHEEP_FAULT_PLAN kill/corrupt/hang fire at dispatch sites ahead
    of the runner seam, so the chaos story is IDENTICAL under remote
    dispatch: one hurt leg, one re-dispatch, bit-identical tree."""
    from sheep_tpu.supervisor import parse_fault_plan
    path, oracle = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    for kind in ("kill", "corrupt", "hang"):
        kw = dict(chaos=parse_fault_plan(f"{kind}@-2:0"),
                  deadline_s=5.0)
        if kind == "hang":
            kw.update(deadline_s=1e9, stale_after_polls=25)
        got, m = _run_remote(path, tmp_path / f"sup-{kind}", workers, **kw)
        assert got == oracle, kind
        counts = _counts(m)
        assert counts["h.00"] == 2, (kind, counts)
        assert sorted(counts.values()) == [1, 1, 1, 1, 1, 2], (kind,
                                                               counts)


# ---------------------------------------------------------------------------
# heartbeats + speculation over the wire
# ---------------------------------------------------------------------------


def test_beat_frames_touch_local_hb(tmp_path):
    """BEAT frames relay into the attempt's local .hb file — the mtime
    the existing staleness machinery polls."""
    sum_bytes = b"s"
    art_bytes = b"a" * 8
    tmp = str(tmp_path / "x.hist.a1")
    spec = {"kind": "hist", "graph": "g", "seq": None, "out": tmp,
            "perf": None, "start": 0, "end": 1, "final": tmp[:-3],
            "attempt": 1, "key": "x.hist"}
    _, stream = _result_stream(spec["key"], sum_bytes, art_bytes, b"")
    h = _fake_handle(tmp_path, spec)
    assert not os.path.exists(h._hb)
    _feed(h, spec, b"BEAT key=x.hist\nBEAT key=x.hist\n" + stream)
    assert h.poll() == 0
    assert os.path.exists(h._hb)  # the wire beat became a local mtime


def test_silent_wire_speculates_first_finisher_wins(
        graph, workers, tmp_path, worker_env):
    """A worker silently wedged mid-leg (the link stays open, no BEAT
    lands, no result comes) draws a speculative twin after
    ``worker_speculate_s``; the twin's artifact wins the first-finisher
    arbitration and the tree is bit-identical."""
    path, oracle = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    # wedge the FIRST leg that lands on worker 1 (it stalls 5s before
    # executing) and drop every wire beat: from the supervisor's side
    # that worker is silent but connected — neither the staleness nor
    # the conn-loss path can see it, only the silent-wire rule
    wedged = workers[0]
    orig_run = wedged._run_leg
    hits = []

    def stall_once(job, slice_bytes, seq_bytes):
        if not hits:
            hits.append(job["key"])
            time.sleep(5.0)
        return orig_run(job, slice_bytes, seq_bytes)

    wedged._run_leg = stall_once
    netfaults.install_plan(NetFaultPlan(
        faults=[NetFault("drop", "wbeat", i) for i in range(500)]))
    got, m = _run_remote(path, tmp_path / "sup", workers,
                         deadline_s=1e9, worker_speculate_s=0.3)
    assert got == oracle
    counts = _counts(m)
    assert sorted(counts.values()) == [1, 1, 1, 1, 1, 2], counts
    hurt = next(k for k, v in counts.items() if v == 2)
    row = json.load(open(wire_status_path(
        str(tmp_path / "sup"),
        next(leg.output for leg in m.legs if leg.key == hurt))))
    assert row["speculations"] >= 1
    assert row["dispatches"] == 2


# ---------------------------------------------------------------------------
# observability: --status columns + METRICS / sheep top
# ---------------------------------------------------------------------------


def test_status_shows_remote_legs(graph, workers, tmp_path, worker_env):
    from sheep_tpu.supervisor.status import render_status, status_rows
    from sheep_tpu.supervisor.manifest import load_manifest
    path, oracle = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    _, m = _run_remote(path, tmp_path / "sup", workers)
    state_dir = str(tmp_path / "sup")
    rows = status_rows(load_manifest(state_dir), state_dir=state_dir)
    shipped = [r for r in rows if "worker" in r]
    assert len(shipped) == 4  # 2 hist + 2 distmap legs went remote
    for r in shipped:
        assert r["worker"].startswith("127.0.0.1:")
        assert r["wire_dispatches"] == 1 and r["speculations"] == 0
    text = render_status(state_dir)
    assert "WORKER" in text and "WDISP" in text and "SPEC" in text
    assert shipped[0]["worker"] in text
    # merge legs stayed local: their wire columns render as dashes
    merge_row = next(line for line in text.splitlines()
                     if line.startswith("r1.00"))
    assert merge_row.rstrip().endswith("-")


def test_status_table_unchanged_without_remote_legs(graph, tmp_path,
                                                    worker_env):
    """A purely local run's table gains no columns — the feature is
    invisible until a leg actually ships."""
    from sheep_tpu.supervisor.status import render_status
    path, _ = graph
    cfg = SupervisorConfig(workers=2, poll_s=0.01, backoff_base_s=0.0,
                           grammar=False)
    run_distext(path, str(tmp_path / "sup"), cfg,
                runner=InlineRunner(0.05), legs=2)
    text = render_status(str(tmp_path / "sup"))
    assert "WORKER" not in text and "SPEC" not in text


def test_worker_metrics_scrape_and_top_view(graph, workers, tmp_path,
                                            worker_env):
    """Each worker answers METRICS with sheep_worker_* plus the process
    gauges, sheep top's fleet view gives them a workers section, and
    ``top -d <worker-state-dir>`` resolves worker.addr."""
    from sheep_tpu.cli.top import fleet_view, resolve_addr
    from sheep_tpu.obs.metrics import parse_prometheus
    path, _ = graph
    worker_env.setenv("SHEEP_WORKER_TRANSPORT", "ship")
    _run_remote(path, tmp_path / "sup", workers)
    host, port = workers[0].address
    assert resolve_addr(None, workers[0].state_dir) == (host, port)
    assert read_worker_addr(workers[0].state_dir) == (host, port)
    with ServeClient(host, port, timeout_s=5.0) as c:
        body = c.metrics()
    samples = parse_prometheus(body)
    names = {name for name, _, _ in samples}
    assert {"sheep_worker_legs_inflight", "sheep_worker_legs_done",
            "sheep_worker_bytes_shipped"} <= names
    assert "sheep_process_vmrss_bytes" in names
    view = fleet_view(samples)
    w = view["workers"]["local"]
    assert w["legs_done"] >= 1 and w["legs_inflight"] == 0
    assert w["bytes_shipped"] > 0
    assert w["vmrss_mb"] > 0


def test_remote_runner_requires_addrs():
    with pytest.raises(ValueError):
        RemoteRunner([])


def test_remote_runner_delegates_non_distext_argv(tmp_path):
    """merge/copy/histsum argv fall through to the base runner — only
    hist/map legs are shippable."""
    calls = []

    class _Base:
        def start(self, argv, hb, log):
            calls.append(argv)
            return "local-handle"

    r = RemoteRunner([("127.0.0.1", 1)], base=_Base())
    out = r.start(["merge_trees", "a.tre", "b.tre", "-o", "c.tre.a1"],
                  str(tmp_path / "hb"), str(tmp_path / "log"))
    assert out == "local-handle" and len(calls) == 1
