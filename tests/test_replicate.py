"""Replicated-serve tests (ISSUE 7): torn replication streams at every
byte boundary, kill-the-leader-at-every-insert-boundary failover with
bit-identical promoted state, epoch fencing (divergent ex-leader tails
roll back, cross-epoch seqno overlap refused by fsck), deterministic
network fault injection (drop/dup/partition), snapshot bootstrap, and
the live leader/follower cluster over real sockets."""

import os
import shutil
import time

import numpy as np
import pytest

from sheep_tpu.core.forest import build_forest
from sheep_tpu.integrity.errors import IntegrityError
from sheep_tpu.integrity.fsck import fsck_paths
from sheep_tpu.io import faultfs
from sheep_tpu.io.edges import write_dat
from sheep_tpu.serve import faults as serve_faults
from sheep_tpu.serve import netfaults
from sheep_tpu.serve.cluster import (ClusterConfig, choose_successor,
                                     resolve_peer)
from sheep_tpu.serve.daemon import ServeConfig, ServeDaemon
from sheep_tpu.serve.faults import ServeKilled, parse_serve_fault_plan
from sheep_tpu.serve.netfaults import parse_netfault_plan
from sheep_tpu.serve.protocol import ServeClient, ServeError
from sheep_tpu.serve.replicate import (ReplApplier, ReplProtocolError,
                                       bootstrap_state_dir, encode_append,
                                       encode_ping, parse_frame,
                                       payload_crc)
from sheep_tpu.serve.state import (ServeCore, encode_inserts,
                                   load_serve_snapshot)
from sheep_tpu.utils.synth import rmat_edges

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fault_plans():
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()
    yield
    faultfs.clear_plan()
    serve_faults.clear_plan()
    netfaults.clear_plan()


def _wait_until(cond, timeout_s=15.0, poll_s=0.02, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(poll_s)
    raise TimeoutError(f"{what} not reached in {timeout_s}s")


def _make_state(tmp_path, name, seed=5, log2=7, parts=3):
    tail, head = rmat_edges(log2, 4 << log2, seed=seed)
    g = str(tmp_path / f"{name}.dat")
    write_dat(g, tail, head)
    sd = str(tmp_path / name)
    core = ServeCore.bootstrap(sd, graph_path=g, num_parts=parts)
    return core, sd, tail, head


# ---------------------------------------------------------------------------
# frame codec + plan grammars
# ---------------------------------------------------------------------------


def test_netfault_plan_grammar():
    plan = parse_netfault_plan("drop@repl:3, dup@hb:0,partition@*:1")
    assert len(plan.faults) == 3
    assert plan.take("repl", 3) == "drop"
    assert plan.take("repl", 3) is None  # entries fire once
    for bad in ("drop@repl", "boom@repl:1", "drop@nowhere:1"):
        with pytest.raises(ValueError):
            parse_netfault_plan(bad)


def test_frame_codec_roundtrip():
    payload = encode_inserts(np.array([[3, 9], [1, 4]], np.uint32))
    line = encode_append(2, 17, payload)
    f = parse_frame(line)
    assert (f.kind, f.epoch(), f.seqno()) == ("APPEND", 2, 17)
    assert f.payload == payload
    p = parse_frame(encode_ping(1, 5))
    assert (p.kind, p.epoch(), p.seqno()) == ("PING", 1, 5)
    # corruption: flip a payload character -> crc refuses
    bad = line.replace("data=", "data=A", 1)
    with pytest.raises(ReplProtocolError):
        parse_frame(bad)
    for bad in ("PART 1", "REPL APPEND epoch=0", "REPL WHAT a=1",
                "REPL APPEND epoch=0 seqno=-1 crc=0 data="):
        with pytest.raises(ReplProtocolError):
            parse_frame(bad)


def test_choose_successor_rule():
    # highest (applied_seqno, node_id) wins, totally ordered
    assert choose_successor([(5, "a"), (7, "b"), (7, "a")]) == "b"
    assert choose_successor([(7, "a")]) == "a"
    with pytest.raises(ValueError):
        choose_successor([])


def test_cluster_config(monkeypatch):
    monkeypatch.setenv("SHEEP_SERVE_ROLE", "follower")
    monkeypatch.setenv("SHEEP_SERVE_PEERS", "a:1, b/dir ,")
    monkeypatch.setenv("SHEEP_SERVE_REPL_ACKS", "2")
    monkeypatch.setenv("SHEEP_SERVE_MAX_LAG", "16")
    cfg = ClusterConfig.from_env()
    assert cfg.role == "follower"
    assert cfg.peers == ["a:1", "b/dir"]
    assert cfg.repl_acks == 2 and cfg.max_lag == 16 and cfg.clustered
    with pytest.raises(ValueError):
        ClusterConfig(role="king")


def test_resolve_peer(tmp_path):
    assert resolve_peer("127.0.0.1:901") == ("127.0.0.1", 901)
    assert resolve_peer(":902") == ("127.0.0.1", 902)
    sd = tmp_path / "node"
    sd.mkdir()
    assert resolve_peer(str(sd)) is None  # no addr published yet
    (sd / "serve.addr").write_text("10.0.0.7 4242\n")
    assert resolve_peer(str(sd)) == ("10.0.0.7", 4242)
    assert resolve_peer(str(sd / "serve.addr")) == ("10.0.0.7", 4242)
    assert resolve_peer("not-a-port") is None


# ---------------------------------------------------------------------------
# the follower applier: torn streams, duplicates, gaps
# ---------------------------------------------------------------------------


def test_torn_stream_at_every_byte_boundary(tmp_path):
    """Cut the leader->follower byte stream at EVERY byte boundary of a
    3-record frame sequence: the follower applies exactly the frames
    wholly before the cut — never a partial record — and its tree is
    bit-identical to the oracle over the delivered prefix (the
    replication mirror of the PR-6 torn-WAL sweep)."""
    leader, lsd, tail, head = _make_state(tmp_path, "lead")
    ins = np.array([[2, 9], [3, 7], [1, 11]], np.uint32)
    frames = []
    for row in ins:
        seqno = leader.insert(row.reshape(1, 2))
        payload = leader.records_from(seqno - 1)[0][1]
        frames.append(encode_append(leader.epoch, seqno, payload))
    blob = ("\n".join(frames) + "\n").encode("ascii")
    bounds = []
    off = 0
    for fr in frames:
        off += len(fr) + 1
        bounds.append(off)

    base, bsd, _, _ = _make_state(tmp_path, "base")
    base.close()
    # reference trees per delivered-prefix length
    want = []
    for k in range(len(ins) + 1):
        at = np.concatenate([tail, ins[:k, 0]])
        ah = np.concatenate([head, ins[:k, 1]])
        want.append(build_forest(at, ah, base.seq,
                                 max_vid=len(base.parts) - 1).parent)

    for cut in range(len(blob) + 1):
        sd_n = str(tmp_path / f"cut-{cut}")
        shutil.copytree(bsd, sd_n)
        fol = ServeCore.open(sd_n)
        sent = []
        applier = ReplApplier(fol, sent.append)
        applier.feed(blob[:cut])
        n_complete = sum(1 for b in bounds if b <= cut)
        assert fol.applied_seqno == n_complete, f"cut at byte {cut}"
        np.testing.assert_array_equal(fol.parent, want[n_complete])
        # every applied record is covered by a cumulative ACK; frames
        # delivered together apply as ONE burst (batched follower acks:
        # one fsync, one ACK), so the LAST ack covers the whole prefix
        acks = [s for s in sent if s.startswith("REPL ACK")]
        if n_complete:
            assert acks and acks[-1] == f"REPL ACK seqno={n_complete}"
        else:
            assert not acks
        # the remainder of the stream completes the replica exactly
        applier.feed(blob[cut:])
        assert fol.applied_seqno == len(ins)
        np.testing.assert_array_equal(fol.parent, want[-1])
        fol.close()
    leader.close()


def test_batched_follower_acks_one_fsync_per_burst(tmp_path, monkeypatch):
    """APPEND frames delivered together apply as ONE durability burst:
    a single WAL fsync seals the lot and a single cumulative ACK answers
    it (the per-record fsync was the replicated-insert throughput cap) —
    while the ack invariant holds: the fsync strictly precedes the ACK,
    and a record-by-record delivery still acks record by record."""
    leader, _, _, _ = _make_state(tmp_path, "lead")
    frames = []
    for i in range(8):
        seqno = leader.insert(np.array([[i, i + 5]], np.uint32))
        payload = leader.records_from(seqno - 1)[0][1]
        frames.append(encode_append(leader.epoch, seqno, payload))
    follower, _, _, _ = _make_state(tmp_path, "fol")
    sent = []
    applier = ReplApplier(follower, sent.append)
    import sheep_tpu.serve.wal as wal_mod
    real_fsync = os.fsync
    calls = {"n": 0}

    def counting(fd):
        calls["n"] += 1
        return real_fsync(fd)

    monkeypatch.setattr(wal_mod.os, "fsync", counting)
    order = []
    real_send = sent.append

    def sending(line):
        order.append(("ack", calls["n"]))
        return real_send(line)

    applier._send = sending
    applier.feed(("\n".join(frames[:6]) + "\n").encode("ascii"))
    assert follower.applied_seqno == 6
    assert calls["n"] == 1, f"burst of 6 must fsync once, saw {calls}"
    assert applier.bursts == 1
    assert sent == ["REPL ACK seqno=6"]
    assert order == [("ack", 1)]  # the fsync preceded the one ACK
    # record-by-record delivery still acks per record (no batching to do)
    for fr in frames[6:]:
        applier.feed((fr + "\n").encode("ascii"))
    assert follower.applied_seqno == 8
    assert sent[-2:] == ["REPL ACK seqno=7", "REPL ACK seqno=8"]
    assert calls["n"] == 3
    leader.close()
    follower.close()


def test_corrupt_frame_nacks_without_apply(tmp_path):
    leader, _, _, _ = _make_state(tmp_path, "lead")
    seqno = leader.insert(np.array([[2, 9]], np.uint32))
    payload = leader.records_from(0)[0][1]
    line = encode_append(leader.epoch, seqno, payload)
    follower, _, _, _ = _make_state(tmp_path, "fol")
    before = follower.parent.copy()
    sent = []
    applier = ReplApplier(follower, sent.append)
    # flip one payload byte inside the base64: crc must refuse, the
    # follower must NOT apply, and must ask for a re-stream
    broken = line.replace("data=", "data=Q", 1) + "\n"
    applier.feed(broken.encode("ascii"))
    assert follower.applied_seqno == 0
    np.testing.assert_array_equal(follower.parent, before)
    assert applier.frame_errors == 1
    assert sent and sent[-1] == "REPL NACK expect=1"
    # the clean retransmission lands
    applier.feed((line + "\n").encode("ascii"))
    assert follower.applied_seqno == 1
    leader.close()
    follower.close()


def test_dup_and_gap_handling(tmp_path):
    leader, _, _, _ = _make_state(tmp_path, "lead")
    payloads = []
    for i in range(3):
        seqno = leader.insert(np.array([[i, i + 5]], np.uint32))
        payloads.append((seqno, leader.records_from(seqno - 1)[0][1]))
    follower, _, _, _ = _make_state(tmp_path, "fol")
    sent = []
    applier = ReplApplier(follower, sent.append)

    def frame(i):
        s, p = payloads[i]
        return (encode_append(0, s, p) + "\n").encode("ascii")

    applier.feed(frame(0) + frame(0))  # duplicate: applied once
    assert follower.applied_seqno == 1 and applier.dups == 1
    applier.feed(frame(2))  # gap: seqno 3 without 2 -> NACK, no apply
    assert follower.applied_seqno == 1 and applier.gaps == 1
    assert sent[-1] == "REPL NACK expect=2"
    applier.feed(frame(1) + frame(2))  # re-stream heals
    assert follower.applied_seqno == 3
    # a PING advertising a seqno we lack also NACKs (drop detector)
    applier.feed((encode_ping(0, 9) + "\n").encode("ascii"))
    assert sent[-1] == "REPL NACK expect=4"
    leader.close()
    follower.close()


# ---------------------------------------------------------------------------
# the acceptance property: kill the leader at every insert boundary
# ---------------------------------------------------------------------------


@pytest.mark.faults
def test_kill_leader_at_every_insert_boundary_failover(tmp_path):
    """For EVERY insert index and both durability boundaries (site wal:
    record durable before apply; site apply: applied before ack), kill
    the leader and promote the follower: the promoted tree must be
    bit-identical to the batch oracle over exactly the delivered
    inserts, with equal ECV(down), and every insert the client saw
    acked must be present.  The client then retries the unacked
    remainder against the promoted leader and must end bit-identical to
    the uninterrupted run."""
    base, bsd, tail, head = _make_state(tmp_path, "base")
    base.close()
    rng = np.random.default_rng(23)
    ins = rng.integers(0, 140, size=(6, 2)).astype(np.uint32)

    # the uninterrupted oracle
    def oracle_parent(k):
        at = np.concatenate([tail, ins[:k, 0]])
        ah = np.concatenate([head, ins[:k, 1]])
        return build_forest(at, ah, base.seq,
                            max_vid=len(base.parts) - 1).parent

    full_want = oracle_parent(len(ins))

    for site in ("wal", "apply"):
        for nth in range(len(ins)):
            lsd = str(tmp_path / f"L-{site}-{nth}")
            fsd = str(tmp_path / f"F-{site}-{nth}")
            shutil.copytree(bsd, lsd)
            shutil.copytree(bsd, fsd)
            leader = ServeCore.open(lsd)
            follower = ServeCore.open(fsd)
            follower.fire_faults = False  # the plan names the LEADER
            acks = []
            applier = ReplApplier(follower, acks.append)

            def deliver():
                recs = leader.records_from(follower.applied_seqno)
                for s, p in recs or []:
                    applier.feed((encode_append(leader.epoch, s, p)
                                  + "\n").encode("ascii"))

            serve_faults.install_plan(parse_serve_fault_plan(
                f"kill@{site}:{nth}", kill_mode="raise"))
            acked = 0
            killed_at = None
            for i, row in enumerate(ins):
                try:
                    leader.insert(row.reshape(1, 2))
                    deliver()  # sync replication: deliver before ack
                    acked += 1
                except ServeKilled:
                    killed_at = i
                    break
            serve_faults.clear_plan()
            assert killed_at == nth and acked == nth
            leader.close()

            # promotion: epoch fence sealed durably, then serve
            follower.advance_epoch(leader.epoch + 1)
            assert follower.epoch == 1
            # bit-identical to the oracle over the delivered prefix,
            # equal ECV(down), zero acked inserts lost
            np.testing.assert_array_equal(follower.parent,
                                          oracle_parent(nth))
            assert follower.applied_seqno == nth >= acked
            rsd = str(tmp_path / f"ref-{site}-{nth}")
            shutil.copytree(bsd, rsd)  # never mutate the shared base
            ref = ServeCore.open(rsd)
            for row in ins[:nth]:
                ref.insert(row.reshape(1, 2))
            assert follower.ecv()["ecv_down"] == ref.ecv()["ecv_down"]
            ref.close()
            shutil.rmtree(rsd)
            # surviving state dir must fsck clean across the boundary
            _, failures = fsck_paths([fsd], "strict")
            assert not failures, failures

            # the client retries the unacked remainder on the new leader
            for row in ins[nth:]:
                follower.insert(row.reshape(1, 2))
            np.testing.assert_array_equal(follower.parent, full_want)
            follower.close()
            # ... and the promoted dir still recovers bit-identically
            revived = ServeCore.open(fsd)
            assert revived.epoch == 1
            np.testing.assert_array_equal(revived.parent, full_want)
            revived.close()
            shutil.rmtree(lsd)
            shutil.rmtree(fsd)


def test_fenced_ex_leader_divergent_tail_rolls_back(tmp_path):
    """Partition story at the core level: the ex-leader applied records
    past the promotion point that were never acked or replicated; on
    rejoin it must adopt the new leader's snapshot, ROLLING BACK the
    divergent tail, and end bit-identical to the new history."""
    base, bsd, tail, head = _make_state(tmp_path, "base")
    base.close()
    lsd = str(tmp_path / "exlead")
    fsd = str(tmp_path / "newlead")
    shutil.copytree(bsd, lsd)
    shutil.copytree(bsd, fsd)
    ex = ServeCore.open(lsd)
    new = ServeCore.open(fsd)
    shared = np.array([[2, 9], [3, 7]], np.uint32)
    for row in shared:  # replicated prefix on both
        ex.insert(row.reshape(1, 2))
    for s, p in ex.records_from(0):
        new.apply_replicated(s, p)
    ex.insert(np.array([[5, 30]], np.uint32))  # divergent, never acked
    assert ex.applied_seqno == 3 and new.applied_seqno == 2

    new.advance_epoch(1)  # promotion on the other side of the partition
    new.insert(np.array([[8, 40]], np.uint32))  # epoch-1 record, seqno 3

    # heal: ex-leader must refuse to stream (its seqno 3 > epoch_base 2
    # on an older epoch) and instead adopt the snapshot, tail gone
    blob, s_applied, s_epoch = new.snapshot_bytes()
    tmp = str(tmp_path / "xfer.snap")
    open(tmp, "wb").write(blob)
    snap = load_serve_snapshot(tmp, integrity="trust")
    ex.reset_from_snapshot(snap)
    assert (ex.epoch, ex.applied_seqno) == (1, 3)
    np.testing.assert_array_equal(ex.parent, new.parent)
    np.testing.assert_array_equal(ex.pst, new.pst)
    # the rolled-back dir recovers to the SAME adopted state
    ex.close()
    revived = ServeCore.open(lsd)
    assert (revived.epoch, revived.applied_seqno) == (1, 3)
    np.testing.assert_array_equal(revived.parent, new.parent)
    revived.close()
    _, failures = fsck_paths([lsd], "strict")
    assert not failures, failures
    # rolling BACKWARD is refused: the new leader must never adopt the
    # fenced snapshot of an older term
    with pytest.raises(IntegrityError):
        old_blob = open(tmp, "rb").read()
        del old_blob
        stale = load_serve_snapshot(tmp, integrity="trust")
        stale.epoch = 0
        new.reset_from_snapshot(stale)
    new.close()


def test_fsck_refuses_cross_epoch_overlap(tmp_path):
    """The promotion boundary is auditable: a clean promoted dir passes
    fsck; an epoch-0 log forged to reach past the epoch-1 boundary is
    refused as cross-epoch seqno overlap."""
    core, sd, _, _ = _make_state(tmp_path, "node")
    for i in range(4):
        core.insert(np.array([[i, i + 2]], np.uint32))
    core.advance_epoch(1)
    core.insert(np.array([[1, 9]], np.uint32))
    core.close()
    results, failures = fsck_paths([sd], "strict")
    assert not failures, failures
    wals = sorted(d for _, _, d in results)
    assert any("epoch=0" in d for _, _, d in results)
    assert any("epoch=1" in d for _, _, d in results)
    del wals
    # forge: extend the ARCHIVED epoch-0 log past the epoch-1 boundary
    from sheep_tpu.serve.wal import WalAppender, archived_wal_paths
    with WalAppender(archived_wal_paths(sd)[0]) as w:
        w.append(encode_inserts(np.array([[7, 8]], np.uint32)))
    _, failures = fsck_paths([sd], "strict")
    assert failures and "cross-epoch" in failures[0][2]


# ---------------------------------------------------------------------------
# the live cluster over sockets
# ---------------------------------------------------------------------------


def _abrupt_kill(daemon):
    """In-process stand-in for kill -9: no goodbye to anyone — sockets
    die, threads die, nothing flushes or demotes gracefully."""
    daemon._stop.set()
    daemon._wake()
    if daemon.watcher is not None:
        daemon.watcher.stop()
    daemon.hub.stop()
    try:
        daemon._listener.close()
    except OSError:
        pass
    for conn in list(daemon._conns.values()):
        try:
            conn.sock.close()
        except OSError:
            pass
    if daemon._hb is not None:
        daemon._hb.stop()
    try:
        os.unlink(os.path.join(daemon.core.state_dir, "serve.addr"))
    except OSError:
        pass


def _spawn_cluster(tmp_path, n_followers=1, hb_s=0.05, failover_s=0.6,
                   **cluster_kw):
    """One leader + N wire-bootstrapped followers, fully attached."""
    lcore, lsd, tail, head = _make_state(tmp_path, "lead")
    all_dirs = [lsd] + [str(tmp_path / f"f{i}") for i in range(n_followers)]
    lead = ServeDaemon(
        lcore, ServeConfig(),
        cluster=ClusterConfig(node_id="L", role="leader",
                              peers=[d for d in all_dirs if d != lsd],
                              hb_s=hb_s, failover_s=failover_s,
                              poll_timeout_s=1.0, **cluster_kw)).start()
    lh, lp = lead.address
    followers = []
    for i in range(n_followers):
        fsd = all_dirs[1 + i]
        bootstrap_state_dir(fsd, lh, lp)
        fcore = ServeCore.open(fsd)
        fol = ServeDaemon(
            fcore, ServeConfig(),
            cluster=ClusterConfig(node_id=f"F{i}", role="follower",
                                  peers=[d for d in all_dirs if d != fsd],
                                  hb_s=hb_s, failover_s=failover_s,
                                  poll_timeout_s=1.0,
                                  **cluster_kw)).start()
        followers.append(fol)
    _wait_until(lambda: lead.hub.follower_count() == n_followers,
                what="followers attached")
    return lead, followers, (tail, head)


def test_cluster_replicates_redirects_and_fails_over(tmp_path):
    """The cluster acceptance, end to end on real sockets: synchronous
    replication (OK means the follower has it), follower reads with
    parity + typed write redirect, role/epoch/lag in STATS, abrupt
    leader death -> epoch-fenced promotion with zero acked inserts
    lost, and the fenced ex-leader rejoining as a follower (write
    availability restored through the new quorum)."""
    lead, (fol,), (tail, head) = _spawn_cluster(tmp_path)
    lh, lp = lead.address
    fh, fp = fol.address
    acked = []
    with ServeClient(lh, lp) as c:
        rng = np.random.default_rng(3)
        for _ in range(12):
            u, v = (int(x) for x in rng.integers(0, 140, size=2))
            c.insert([(u, v)])
            acked.append((u, v))
        st = c.kv("STATS")
        assert st["role"] == "leader" and st["followers"] == 1
        assert st["applied_seqno"] == len(acked)
    # sync acks: the follower already has every acked insert
    assert fol.core.applied_seqno == len(acked)
    np.testing.assert_array_equal(fol.core.parent, lead.core.parent)

    with ServeClient(fh, fp) as c:
        st = c.kv("STATS")
        assert st["role"] == "follower" and st["repl_lag"] == 0
        assert st["leader"] == f"{lh}:{lp}"
        assert c.part([0, 1, 2]) == [lead.core.part(v) for v in (0, 1, 2)]
        with pytest.raises(ServeError) as ei:
            c.insert([(1, 2)])
        assert ei.value.code == "notleader"
        assert f"{lh}:{lp}" in ei.value.detail

    _abrupt_kill(lead)
    _wait_until(lambda: fol.role == "leader", what="promotion")
    assert fol.core.epoch == 1
    # zero acknowledged inserts lost, bit-identical serving state
    assert fol.core.applied_seqno == len(acked)
    at = np.concatenate([tail, np.array([u for u, _ in acked], np.uint32)])
    ah = np.concatenate([head, np.array([v for _, v in acked], np.uint32)])
    want = build_forest(at, ah, fol.core.seq,
                        max_vid=len(fol.core.parts) - 1)
    np.testing.assert_array_equal(fol.core.parent, want.parent)

    # the fenced ex-leader returns — and demotes instead of splitting
    excore = ServeCore.open(lead.core.state_dir)
    assert excore.epoch == 0
    ex = ServeDaemon(
        excore, ServeConfig(),
        cluster=ClusterConfig(node_id="L", role="leader",
                              peers=[fol.core.state_dir], hb_s=0.05,
                              failover_s=0.6, poll_timeout_s=1.0)).start()
    assert ex.role == "follower"
    assert ("fenced_at_start", 1) in ex.config.events
    _wait_until(lambda: fol.hub.follower_count() == 1,
                what="ex-leader attached as follower")
    # write availability is back: the new quorum acks through the
    # rejoined follower, which also adopts the new epoch
    with ServeClient(fh, fp) as c:
        c.insert([(4, 9)])
        st = c.kv("STATS")
        assert st["role"] == "leader" and st["epoch"] == 1
    _wait_until(lambda: excore.applied_seqno == len(acked) + 1,
                what="ex-leader caught up")
    assert excore.epoch == 1
    np.testing.assert_array_equal(excore.parent, fol.core.parent)
    ex.shutdown()
    fol.shutdown()


def test_quorum_insert_refused_without_followers(tmp_path):
    """A clustered leader whose followers are all gone refuses writes
    typed (the CP choice: an OK no replica holds could be lost to
    failover) and keeps serving reads."""
    core, sd, _, _ = _make_state(tmp_path, "lonely")
    d = ServeDaemon(core, ServeConfig(),
                    cluster=ClusterConfig(
                        node_id="L", role="leader",
                        peers=[str(tmp_path / "ghost")], hb_s=0.05,
                        failover_s=30.0, poll_timeout_s=0.2)).start()
    try:
        h, p = d.address
        with ServeClient(h, p) as c:
            with pytest.raises(ServeError) as ei:
                c._ok("DEADLINE=0.3 INSERT 1 2")
            assert ei.value.code == "unavailable"
            assert "quorum" in ei.value.detail
            assert c.part([0])  # reads unaffected
            assert d.counters["repl_quorum_fails"] == 1
    finally:
        d.shutdown()


def test_netfaults_drop_dup_partition_on_live_stream(tmp_path):
    """Deterministic wire chaos: a dropped frame heals by NACK
    re-stream, a duplicated frame applies once, a partitioned stream
    reconnects — every case converging bit-identical, nothing acked
    lost."""
    lead, (fol,), _ = _spawn_cluster(tmp_path, hb_s=0.05,
                                     failover_s=30.0)
    lh, lp = lead.address
    netfaults.install_plan(parse_netfault_plan(
        "drop@repl:1,dup@repl:3,partition@repl:5"))
    with ServeClient(lh, lp) as c:
        for i in range(8):
            # generous deadline: the dropped frame waits out one hb PING
            # before the NACK re-stream completes the quorum
            c._ok(f"DEADLINE=20 INSERT {i} {i + 9}")
    _wait_until(lambda: fol.core.applied_seqno == 8,
                what="follower converged")
    np.testing.assert_array_equal(fol.core.parent, lead.core.parent)
    assert fol.core.applied_seqno == lead.core.applied_seqno == 8
    rep = fol.replicator
    assert rep is not None and rep.applier is not None
    lead.shutdown()
    fol.shutdown()


def test_snapshot_resync_when_stream_window_passed(tmp_path,
                                                   monkeypatch):
    """A follower that falls behind the leader's retention window must
    bootstrap from a snapshot instead of streaming — and end
    bit-identical anyway."""
    from sheep_tpu.serve import state as state_mod
    monkeypatch.setattr(state_mod, "REPL_TAIL_KEEP", 2)
    lcore, lsd, tail, head = _make_state(tmp_path, "lead")
    # follower dir exists from the same artifacts but never streamed
    fsd = str(tmp_path / "fol")
    shutil.copytree(lsd, fsd)
    for i in range(10):  # retention window now only holds the last 2
        lcore.insert(np.array([[i, i + 3]], np.uint32))
    assert lcore.records_from(0) is None
    lead = ServeDaemon(lcore, ServeConfig(),
                       cluster=ClusterConfig(node_id="L", role="leader",
                                             peers=[fsd], hb_s=0.05,
                                             failover_s=30.0)).start()
    fcore = ServeCore.open(fsd)
    fol = ServeDaemon(fcore, ServeConfig(),
                      cluster=ClusterConfig(node_id="F", role="follower",
                                            peers=[lsd], hb_s=0.05,
                                            failover_s=30.0)).start()
    _wait_until(lambda: fcore.applied_seqno == 10, what="resync")
    assert fol.replicator.resyncs == 1
    np.testing.assert_array_equal(fcore.parent, lcore.parent)
    _, failures = fsck_paths([fsd], "strict")
    assert not failures, failures
    lead.shutdown()
    fol.shutdown()


def test_follower_bounded_staleness_refusal(tmp_path):
    """A follower that cannot reach any leader refuses reads typed
    once its lag bound is configured — bounded staleness, not silent
    time travel."""
    core, sd, _, _ = _make_state(tmp_path, "stale")
    d = ServeDaemon(core, ServeConfig(),
                    cluster=ClusterConfig(
                        node_id="F", role="follower",
                        peers=[str(tmp_path / "ghost")], max_lag=0,
                        hb_s=0.05, failover_s=30.0,
                        poll_timeout_s=0.2)).start()
    try:
        h, p = d.address
        with ServeClient(h, p) as c:
            with pytest.raises(ServeError) as ei:
                c.part([0])
            assert ei.value.code == "stale"
            assert c.kv("STATS")["role"] == "follower"  # STATS always on
    finally:
        d.shutdown()


def test_supervise_status_on_serve_dir(tmp_path):
    """`sheep supervise --status` renders a serve state dir: live role/
    epoch/lag over the wire, dead-daemon fallback from the status file
    and snapshots."""
    from sheep_tpu.supervisor.status import serve_status_json
    lead, (fol,), _ = _spawn_cluster(tmp_path, hb_s=0.05,
                                     failover_s=30.0)
    with ServeClient(*lead.address) as c:
        c.insert([(3, 8)])
    live = serve_status_json(lead.core.state_dir)
    assert live["alive"] and live["role"] == "leader"
    assert live["applied_seqno"] == 1 and live["followers"] == 1
    fstat = serve_status_json(fol.core.state_dir)
    assert fstat["alive"] and fstat["role"] == "follower"
    lead._write_status(force=True)
    _abrupt_kill(lead)
    dead = serve_status_json(lead.core.state_dir)
    assert not dead["alive"]
    assert dead["role"] == "leader" and dead["applied_seqno"] == 1
    assert dead["heartbeat_age_s"] is not None
    fol.shutdown()


def test_pipelined_connection_keeps_order(tmp_path):
    """The selectors loop serializes one connection's requests while
    other connections proceed: a pipelined burst answers in order."""
    import socket as socket_mod
    core, sd, _, _ = _make_state(tmp_path, "pipe")
    d = ServeDaemon(core, ServeConfig()).start()
    try:
        h, p = d.address
        s = socket_mod.create_connection((h, p), timeout=10)
        burst = b"".join(f"PART {i}\n".encode() for i in range(50))
        s.sendall(burst + b"PING\n")
        rf = s.makefile("rb")
        lines = [rf.readline().decode().strip() for _ in range(51)]
        assert lines[-1] == "OK pong"
        for i, line in enumerate(lines[:50]):
            assert line == f"OK {core.part(i)}", (i, line)
        s.close()
    finally:
        d.shutdown()
