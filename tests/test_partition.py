"""Partitioner + evaluator: goldens on hep-th and brute-force parity.

Golden values from the reference's published log data/quality/hep.degree.raw
(degree sequence, balance 1.03, pst weights — the partition_tree defaults).
"""

import numpy as np
import pytest

from sheep_tpu import INVALID_PART
from sheep_tpu.core import build_forest, degree_sequence
from sheep_tpu.core.sequence import sequence_positions
from sheep_tpu.partition import (
    Partition,
    TreePartitionOptions,
    evaluate_partition,
    partition_forest,
)
from sheep_tpu.partition.evaluate import cormen_hash
from conftest import random_multigraph

GOLDEN = {
    2: dict(sizes=(3409, 4201), edges_cut=2811, vcom=2061, ecv_hash=1311,
            ecv_down=521, ecv_up=1539),
    3: dict(sizes=(2323, 2205), edges_cut=3973, vcom=3256, ecv_hash=2042,
            ecv_down=888, ecv_up=2364),
    4: dict(sizes=(1662, 1714), edges_cut=4601, vcom=4075, ecv_hash=2452,
            ecv_down=1177, ecv_up=2893),
}


@pytest.fixture(scope="module")
def hep_setup(hep_edges):
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    forest = build_forest(hep_edges.tail, hep_edges.head, seq)
    return hep_edges, seq, forest


@pytest.mark.parametrize("nparts", [2, 3, 4])
def test_hepth_partition_goldens(hep_setup, nparts):
    el, seq, forest = hep_setup
    p = Partition.from_forest(seq, forest, nparts, max_vid=el.max_vid)
    g = GOLDEN[nparts]
    assert p.max_part + 1 == nparts
    first = int((p.parts == 0).sum())
    second = int((p.parts == 1).sum())
    assert (first, second) == g["sizes"]

    rep = evaluate_partition(p.parts, el.tail, el.head, seq, nparts,
                             max_vid=el.max_vid, file_edges=el.file_edges)
    assert rep.edges_cut == g["edges_cut"]
    assert rep.vcom_vol == g["vcom"]
    assert rep.ecv_hash == g["ecv_hash"]
    assert rep.ecv_down == g["ecv_down"]
    assert rep.ecv_up == g["ecv_up"]


def brute_force_eval(parts, tail, head, seq, num_parts, file_edges):
    """Literal replay of lib/partition.cpp:428-521 with python sets."""
    pos = {int(v): i for i, v in enumerate(seq)}
    adj = {}
    for t, h in zip(tail.tolist(), head.tolist()):
        adj.setdefault(t, []).append(h)
        adj.setdefault(h, []).append(t)

    edges_cut = vcom = ecv_hash = ecv_down = ecv_up = 0
    P = int(max(parts)) + 1
    vert_bal = [0] * P
    hash_bal = [0] * P
    down_bal = [0] * P
    up_bal = [0] * P

    ch = lambda k: int(cormen_hash(np.array([k], dtype=np.uint32))[0])
    for X in sorted(adj):
        Xp = int(parts[X])
        vert_bal[Xp] += 1
        vset = {Xp}
        hset = set()
        dset = set()
        uset = set()
        for Y in adj[X]:
            Yp = int(parts[Y])
            if X < Y and Xp != Yp:
                edges_cut += 1
            vset.add(Yp)
            hp = Xp if ch(X) < ch(Y) else Yp
            hset.add(hp)
            if X < Y:
                hash_bal[hp] += 1
            dset.add(Xp if pos[X] < pos[Y] else Yp)
            uset.add(Xp if pos[X] > pos[Y] else Yp)
            if pos[X] < pos[Y]:
                down_bal[Xp] += 1
            if pos[X] > pos[Y]:
                up_bal[Xp] += 1
        vcom += len(vset) - 1
        ecv_hash += len(hset) - 1
        ecv_down += len(dset) - 1
        ecv_up += len(uset) - 1
    return dict(edges_cut=edges_cut, vcom=vcom, ecv_hash=ecv_hash,
                ecv_down=ecv_down, ecv_up=ecv_up,
                vertex_balance=max(vert_bal), hash_balance=max(hash_bal),
                down_balance=max(down_bal), up_balance=max(up_bal))


@pytest.mark.parametrize("seed", range(12))
def test_evaluator_matches_bruteforce(seed):
    rng = np.random.default_rng(300 + seed)
    tail, head = random_multigraph(rng, n_max=30, e_max=90)
    seq = degree_sequence(tail, head)
    n = int(max(tail.max(), head.max())) + 1
    parts = np.full(n, INVALID_PART, dtype=np.int64)
    parts[seq] = rng.integers(0, 3, size=len(seq))

    rep = evaluate_partition(parts, tail, head, seq, 3)
    bf = brute_force_eval(parts, tail, head, seq, 3, len(tail))
    assert rep.edges_cut == bf["edges_cut"]
    assert rep.vcom_vol == bf["vcom"]
    assert rep.ecv_hash == bf["ecv_hash"]
    assert rep.ecv_down == bf["ecv_down"]
    assert rep.ecv_up == bf["ecv_up"]
    assert rep.vertex_balance == bf["vertex_balance"]
    assert rep.hash_balance == bf["hash_balance"]
    assert rep.down_balance == bf["down_balance"]
    assert rep.up_balance == bf["up_balance"]


@pytest.mark.parametrize("strategy", ["forward", "backward", "depth", "height", "naive"])
@pytest.mark.parametrize("seed", range(4))
def test_strategies_assign_everything(strategy, seed):
    rng = np.random.default_rng(400 + seed)
    tail, head = random_multigraph(rng, n_max=50, e_max=200)
    seq = degree_sequence(tail, head)
    forest = build_forest(tail, head, seq)
    jparts = partition_forest(forest, 3, strategy=strategy)
    assert (jparts != INVALID_PART).all()
    assert jparts.min() >= 0


@pytest.mark.parametrize("seed", range(6))
def test_forward_balance_invariant(seed):
    """forwardPartition respects max_component per bin (partition.cpp:114,133)."""
    rng = np.random.default_rng(500 + seed)
    tail, head = random_multigraph(rng, n_max=60, e_max=400)
    seq = degree_sequence(tail, head)
    forest = build_forest(tail, head, seq)
    opts = TreePartitionOptions()
    jparts = partition_forest(forest, 4, opts)
    w = forest.pst_weight.astype(np.int64)
    total = int(w.sum())
    max_component = int((total // 4) * opts.balance_factor)
    loads = np.bincount(jparts, weights=w)
    # The algorithm guarantees every bin stays within max_component.
    assert (loads <= max_component).all()


def test_partition_writers(tmp_path, hep_setup):
    el, seq, forest = hep_setup
    p = Partition.from_forest(seq, forest, 2, max_vid=el.max_vid)
    prefix = str(tmp_path / "out-p")
    paths = p.write_partitioned_graph(el.tail, el.head, seq, prefix,
                                      max_vid=el.max_vid)
    assert len(paths) == 2
    # downward assignment: every non-loop edge lands in exactly one file
    import os
    tot = 0
    for path in paths:
        with open(path) as f:
            tot += sum(1 for _ in f)
    n_loops = int((el.tail == el.head).sum())
    assert tot == el.num_edges - n_loops

    iso = str(tmp_path / "iso.net")
    p.write_isomorphic_graph(el.tail, el.head, seq, iso, max_vid=el.max_vid)
    assert os.path.getsize(iso) > 0


def test_forward_overweight_node_raises():
    """A node heavier than max_component must fail fast, not hang
    (the reference's live assert at partition.cpp:114)."""
    tail = np.array([0, 1], dtype=np.uint32)
    head = np.array([1, 2], dtype=np.uint32)
    seq = degree_sequence(tail, head)
    forest = build_forest(tail, head, seq)
    with pytest.raises(ValueError, match="max_component"):
        partition_forest(forest, 8)


def test_balance_denominators_truncate(capsys):
    """Printed balance fractions use integer-divided denominators
    (partition.cpp:470: max_bal / (getNodes() / num_parts))."""
    from sheep_tpu.partition.evaluate import EvalReport
    rep = EvalReport(edges_cut=0, vcom_vol=0, ecv_hash=0, ecv_down=0,
                     ecv_up=0, vertex_balance=5, hash_balance=0,
                     down_balance=0, up_balance=0,
                     num_edges=10, num_nodes=9, num_parts=2)
    rep.print()
    out = capsys.readouterr().out
    assert "balance: 5 (1.250000%)" in out  # 5 / (9 // 2), not 5 / 4.5


@pytest.mark.parametrize("impl", ["native", "python"])
@pytest.mark.parametrize("num_parts", [2, 7, 100])
def test_streamed_evaluator_matches_inmemory(num_parts, impl):
    # The O(n)-memory bitmap evaluator must be bit-identical to the dense
    # one, including the >64-part multi-window path (num_parts=100) —
    # through BOTH the native per-block kernel (sheep_eval_block) and the
    # pure-numpy fallback body.  impl="native" raises if the runtime
    # failed to build, so a broken .so can't silently skip the C coverage.
    from sheep_tpu.core.sequence import degree_sequence, sequence_positions
    from sheep_tpu.partition.evaluate import (evaluate_partition,
                                              evaluate_partition_streamed)

    rng = np.random.default_rng(42 + num_parts)
    n = 300
    e = 1500
    tail = rng.integers(0, n, e).astype(np.uint32)
    head = rng.integers(0, n, e).astype(np.uint32)
    seq = degree_sequence(tail, head)
    parts = rng.integers(0, num_parts, n).astype(np.int64)

    dense = evaluate_partition(parts, tail, head, seq, num_parts,
                               max_vid=n - 1, file_edges=e)
    pos = sequence_positions(seq, n - 1).astype(np.int64)

    def blocks():
        for a in range(0, e, 64):
            yield tail[a:a + 64], head[a:a + 64]

    stream = evaluate_partition_streamed(parts, blocks, pos, num_parts, e,
                                         impl=impl)
    assert dense == stream

    # sequence-free overload
    dense_nf = evaluate_partition(parts, tail, head, None, num_parts,
                                  max_vid=n - 1, file_edges=e)
    stream_nf = evaluate_partition_streamed(parts, blocks, None, num_parts, e,
                                            impl=impl)
    assert dense_nf == stream_nf
