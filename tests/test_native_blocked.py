"""Cache-blocked native kernels (sheep_native.cpp, round 6): the
quantile-bucketed grouping, the fused edges->forest entry, and the fused
degree sequence must be bit-identical to the unblocked path and to the
python oracle — including past the cache cliff (>= 2^21) where the
blocked layout actually diverges in memory behavior.
"""

import numpy as np
import pytest

from conftest import random_multigraph

from sheep_tpu import native
from sheep_tpu.core import build_forest, degree_sequence
from sheep_tpu.core.forest import build_forest_links, edges_to_positions
from sheep_tpu.core.sequence import sequence_positions
from sheep_tpu.utils import rmat_edges

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native runtime unavailable")


@pytest.mark.parametrize("trial", range(8))
def test_fused_edges_equals_two_call(trial):
    """build_forest_edges == edges_to_links + build_forest_links on
    random multigraphs (self-loops, duplicates, absent vids)."""
    rng = np.random.default_rng(300 + trial)
    tail, head = random_multigraph(rng, n_max=120, e_max=600)
    seq = degree_sequence(tail, head)
    # absent vids: drop a third of the sequence
    seq = seq[: max(2, len(seq) * 2 // 3)]
    max_vid = int(max(tail.max(), head.max()))
    pos = sequence_positions(seq, max_vid)
    lo, hi = native.edges_to_links(tail, head, pos)
    p2, w2 = native.build_forest_links(lo, hi, len(seq))
    p1, w1 = native.build_forest_edges(tail, head, pos, len(seq))
    np.testing.assert_array_equal(p1, p2)
    np.testing.assert_array_equal(w1, w2)


@pytest.mark.parametrize("trial", range(6))
def test_blocked_toggle_bit_identical_small(trial, monkeypatch):
    rng = np.random.default_rng(400 + trial)
    tail, head = random_multigraph(rng, n_max=100, e_max=500)
    out = {}
    for arm in ("1", "0"):
        monkeypatch.setenv("SHEEP_NATIVE_BLOCKED", arm)
        seq = degree_sequence(tail, head)
        f = build_forest(tail, head, seq)
        out[arm] = (seq, f.parent, f.pst_weight)
    np.testing.assert_array_equal(out["1"][0], out["0"][0])
    np.testing.assert_array_equal(out["1"][1], out["0"][1])
    np.testing.assert_array_equal(out["1"][2], out["0"][2])


def test_degree_sequence_fused_equals_two_call():
    rng = np.random.default_rng(41)
    tail, head = random_multigraph(rng, n_max=200, e_max=2000)
    n = int(max(tail.max(), head.max())) + 1
    fused = native.degree_sequence_from_edges(tail, head, n)
    deg = native.degree_histogram(tail, head, n)
    two_call = native.degree_sequence_from_degrees(deg)
    assert fused is not None and two_call is not None
    np.testing.assert_array_equal(fused, two_call)


def test_degree_sequence_fused_out_of_range_raises():
    with pytest.raises(ValueError):
        native.degree_sequence_from_edges(
            np.array([5], np.uint32), np.array([1], np.uint32), 3)


def test_fused_edges_corrupt_pos_raises():
    # a pos table mapping into positions >= n is corrupt: -3
    tail = np.array([0], np.uint32)
    head = np.array([1], np.uint32)
    pos = np.array([7, 9], np.uint32)  # both beyond n=2
    with pytest.raises(RuntimeError):
        native.build_forest_edges(tail, head, pos, 2)


def test_blocked_pst_in_respected():
    """The precomputed-pst path must pass pst through untouched on the
    blocked kernel too (it skips the histogram entirely)."""
    rng = np.random.default_rng(43)
    tail, head = random_multigraph(rng, n_max=90, e_max=400)
    seq = degree_sequence(tail, head)
    pos = sequence_positions(seq, int(max(tail.max(), head.max())))
    lo, hi = native.edges_to_links(tail, head, pos)
    pst = rng.integers(0, 100, len(seq)).astype(np.uint32)
    p, w = native.build_forest_links(lo, hi, len(seq), pst=pst)
    np.testing.assert_array_equal(w, pst)


def test_blocked_vs_unblocked_past_cache_cliff(monkeypatch):
    """2^21 (past the cliff where the blocked layout's behavior actually
    diverges): both native arms bit-identical."""
    log_n = 21
    n = 1 << log_n
    tail, head = rmat_edges(log_n, 4 * n, seed=9)
    out = {}
    for arm in ("1", "0"):
        monkeypatch.setenv("SHEEP_NATIVE_BLOCKED", arm)
        seq = degree_sequence(tail, head)
        f = build_forest(tail, head, seq, max_vid=n - 1)
        out[arm] = (seq, f.parent, f.pst_weight)
    np.testing.assert_array_equal(out["1"][0], out["0"][0])
    np.testing.assert_array_equal(out["1"][1], out["0"][1])
    np.testing.assert_array_equal(out["1"][2], out["0"][2])


@pytest.mark.slow
def test_native_vs_python_past_cache_cliff():
    """Native (blocked) vs the python oracle at 2^21, bit-identical —
    slow: the python union-find walks ~8.4M links in the interpreter."""
    log_n = 21
    n = 1 << log_n
    tail, head = rmat_edges(log_n, 4 * n, seed=9)
    seq = degree_sequence(tail, head)
    f_native = build_forest(tail, head, seq, max_vid=n - 1, impl="native")
    lo, hi = edges_to_positions(tail, head, seq, n - 1)
    f_python = build_forest_links(lo, hi, len(seq), impl="python")
    np.testing.assert_array_equal(f_native.parent, f_python.parent)
    np.testing.assert_array_equal(f_native.pst_weight, f_python.pst_weight)
