"""Unit tests for the TPU-window watcher's gating logic.

The watcher runs unattended for whole rounds; a wrong done()/_on_accel
decision silently costs the next hardware window (round-3 lesson: every
planned on-chip measurement queue died with the tunnel).  No jax needed.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def watcher():
    spec = importlib.util.spec_from_file_location(
        "tpu_watcher", os.path.join(REPO, "scripts", "tpu_watcher.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_on_accel_rejects_partials_and_cpu(watcher):
    assert not watcher._on_accel(None)
    assert not watcher._on_accel({"platform": "cpu"})
    assert not watcher._on_accel({"platform": "tpu", "_partial": True})
    assert not watcher._on_accel(
        {"metric": "device_build_edges_per_sec_cpu_fallback", "value": 1})
    assert watcher._on_accel({"platform": "tpu"})
    assert watcher._on_accel({"platform": "axon"})
    assert watcher._on_accel({"metric": "device_build_edges_per_sec_rmat"})


def test_step_done_semantics(watcher, tmp_path, monkeypatch):
    monkeypatch.setattr(watcher, "REPO", str(tmp_path))
    plain = watcher.Step("s", ["true"], "OUT.json", 10)
    assert not plain.done()  # no artifact yet
    with open(plain.out_path, "w") as f:
        json.dump({"platform": "cpu", "_step": "s"}, f)
    assert not plain.done()  # cpu record never satisfies
    with open(plain.out_path, "w") as f:
        json.dump({"platform": "tpu", "_step": "s", "_partial": True}, f)
    assert not plain.done()  # timeout salvage never satisfies
    with open(plain.out_path, "w") as f:
        json.dump({"platform": "tpu", "_step": "s"}, f)
    assert plain.done()

    # append-mode steps match on their own _step tag only
    a = watcher.Step("a", ["true"], "LOG.jsonl", 10, append=True)
    b = watcher.Step("b", ["true"], "LOG.jsonl", 10, append=True)
    with open(a.out_path, "w") as f:
        f.write(json.dumps({"platform": "tpu", "_step": "a"}) + "\n")
    assert a.done() and not b.done()


def test_bench_sweep_done_requires_large_sizes(watcher, tmp_path,
                                               monkeypatch):
    # a window that dies after the small sizes leaves an accel-tagged
    # record; it must NOT retire the record sweep until >= 2^22 is in
    monkeypatch.setattr(watcher, "REPO", str(tmp_path))
    step = next(s for s in watcher.build_queue() if s.name == "bench_sweep")
    small = {"metric": "device_build_edges_per_sec_rmat_n2^18_e8x",
             "value": 1.0, "_step": "bench_sweep",
             "sweep": [{"log_n": 16}, {"log_n": 18}]}
    with open(step.out_path, "w") as f:
        json.dump(small, f)
    assert not step.done()
    full = dict(small, sweep=small["sweep"] + [{"log_n": 22}])
    with open(step.out_path, "w") as f:
        json.dump(full, f)
    assert step.done()


def test_queue_is_consistent(watcher):
    q = watcher.build_queue()
    names = [s.name for s in q]
    assert len(names) == len(set(names)), "duplicate step names"
    # round-5 ordering policy: a 900s-bounded canary proves the new
    # overlap+pipeline defaults run on the backend, then the benchmark
    # of record gets the freshest minutes (windows close mid-queue)
    assert names[0] == "canary_16"
    assert q[0].timeout <= 900
    assert names[1] == "bench_sweep"
    assert q[1].sidecar == "bench_progress.json"
    # non-append steps must not share an output file (they overwrite)
    plain_outs = [s.out for s in q if not s.append]
    assert len(plain_outs) == len(set(plain_outs))
    for s in q:
        assert s.timeout > 0
        assert os.path.exists(os.path.join(REPO, s.cmd[1])), s.cmd
