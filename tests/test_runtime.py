"""Fault-tolerance tests: checkpoint/resume equivalence, retry-with-
backoff + adaptive shrinking, and the graceful-degradation ladder.

The acceptance property (ISSUE 1): inject a kill at EVERY chunk boundary
of a small RMAT build, resume each time, and the resumed tree (parent
array + pst weights) and ECV(down) must be bit-identical to the
uninterrupted build; a forced mesh -> host degradation run must match as
well.  All on CPU — the deterministic fault injector
(sheep_tpu.runtime.faults) substitutes for real dispatch faults.
"""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from sheep_tpu.core.forest import build_forest
from sheep_tpu.core.sequence import degree_sequence
from sheep_tpu.runtime import (BuildKilled, DeadlineExceeded, FaultPlan,
                               RetryBudgetExhausted, RetryPolicy,
                               RuntimeConfig, build_graph_resilient,
                               clear_plan, install_plan, run_with_retry)
from sheep_tpu.runtime.faults import (fault_count, fault_point, parse_plan,
                                      reset_counters)
from sheep_tpu.utils.synth import rmat_edges

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    clear_plan()
    reset_counters()
    yield
    clear_plan()
    reset_counters()


@pytest.fixture(scope="module")
def small_graph():
    tail, head = rmat_edges(9, 4 << 9, seed=11)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    return tail, head, seq, want


def _assert_matches(forest, want):
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def _ecv_down(tail, head, seq, forest, parts=2):
    from sheep_tpu.partition.evaluate import evaluate_partition
    from sheep_tpu.partition.partition import Partition

    p = Partition.from_forest(seq, forest, parts)
    rep = evaluate_partition(p.parts, tail, head, seq, p.num_parts)
    return rep.ecv_down


# ---------------------------------------------------------------------------
# unit: atomic writes
# ---------------------------------------------------------------------------


def test_atomic_write_commits_and_cleans(tmp_path):
    from sheep_tpu.io.atomic import atomic_write

    path = tmp_path / "out.bin"
    with atomic_write(str(path), "wb") as f:
        f.write(b"hello")
    assert path.read_bytes() == b"hello"
    # no temp litter after a clean write
    assert os.listdir(tmp_path) == ["out.bin"]


def test_atomic_write_failure_leaves_target_intact(tmp_path):
    from sheep_tpu.io.atomic import atomic_write

    path = tmp_path / "out.bin"
    path.write_bytes(b"old complete data")
    with pytest.raises(RuntimeError):
        with atomic_write(str(path), "wb") as f:
            f.write(b"half a new fi")
            raise RuntimeError("killed mid-write")
    assert path.read_bytes() == b"old complete data"
    assert os.listdir(tmp_path) == ["out.bin"]  # temp removed


def test_tree_and_sequence_writers_are_atomic(tmp_path, monkeypatch):
    # write_tree/write_sequence must go through the atomic path: a crash
    # between bytes must never leave a short file under the final name.
    from sheep_tpu.io.seqfile import read_sequence, write_sequence
    from sheep_tpu.io.trefile import read_tree, write_tree

    parent = np.array([2, 2, 0xFFFFFFFF], np.uint32)
    pst = np.array([1, 0, 3], np.uint32)
    tre = tmp_path / "t.tre"
    write_tree(str(tre), parent, pst)
    p, w = read_tree(str(tre))
    np.testing.assert_array_equal(p, parent)
    np.testing.assert_array_equal(w, pst)

    seqp = tmp_path / "s.seq"
    write_sequence(np.array([3, 1, 2], np.uint32), str(seqp))
    np.testing.assert_array_equal(read_sequence(str(seqp)), [3, 1, 2])
    # no temp litter — just the artifacts and their checksum sidecars
    assert sorted(os.listdir(tmp_path)) == \
        ["s.seq", "s.seq.sum", "t.tre", "t.tre.sum"]


# ---------------------------------------------------------------------------
# unit: fault injection + retry policy
# ---------------------------------------------------------------------------


def test_fault_plan_matching_and_counters():
    install_plan(FaultPlan(site="chunk", at=2, kind="xla", times=2))
    fault_point("chunk")          # 0
    fault_point("mesh_chunk")     # other site unaffected
    fault_point("chunk")          # 1
    for _ in range(2):            # 2, 3 fault
        with pytest.raises(Exception):
            fault_point("chunk")
    fault_point("chunk")          # 4 clean again
    assert fault_count("chunk") == 5
    assert fault_count("mesh_chunk") == 1


def test_fault_plan_env_parse():
    plan = parse_plan("boundary:3:kill")
    assert (plan.site, plan.at, plan.kind, plan.times) == \
        ("boundary", 3, "kill", 1)
    assert parse_plan("chunk:0:xla:-1").times == -1
    with pytest.raises(ValueError):
        parse_plan("chunk")
    with pytest.raises(ValueError):
        parse_plan("chunk:1:nuke")


def test_run_with_retry_shrinks_and_backs_off():
    sleeps = []
    policy = RetryPolicy(max_retries=3, backoff_base_s=0.1,
                         sleep=sleeps.append)
    install_plan(FaultPlan(site="s", at=0, kind="xla", times=2))
    out, j = run_with_retry(policy, "s", lambda jj: np.int32(jj), 8)
    assert j == 2  # 8 -> 4 -> 2 across two faulted attempts
    assert int(out) == 2
    assert sleeps == [0.1, 0.2]  # exponential


def test_run_with_retry_budget_exhausted():
    policy = RetryPolicy(max_retries=2, backoff_base_s=0.0,
                         sleep=lambda s: None)
    install_plan(FaultPlan(site="s", at=0, kind="xla", times=-1))
    with pytest.raises(RetryBudgetExhausted):
        run_with_retry(policy, "s", lambda jj: jj, 8)


def test_run_with_retry_never_catches_kill():
    policy = RetryPolicy(max_retries=5, backoff_base_s=0.0,
                         sleep=lambda s: None)
    install_plan(FaultPlan(site="s", at=0, kind="kill"))
    with pytest.raises(BuildKilled):
        run_with_retry(policy, "s", lambda jj: jj, 8)


def test_watchdog_times_out_hung_dispatch():
    hung = {"n": 0}

    def dispatch(jj):
        hung["n"] += 1
        if hung["n"] == 1:
            time.sleep(2.0)  # first attempt hangs past the watchdog
        return np.int32(jj)

    policy = RetryPolicy(max_retries=2, backoff_base_s=0.0,
                         watchdog_s=0.2, sleep=lambda s: None)
    out, j = run_with_retry(policy, "s", dispatch, 8)
    assert hung["n"] == 2 and j == 4  # retried once, shrunk


# ---------------------------------------------------------------------------
# resilient builds match the oracle (no faults)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ladder", [("single", "host"),
                                    ("mesh", "single", "host")])
def test_resilient_build_matches_oracle(small_graph, ladder):
    tail, head, want_seq, want = small_graph
    cfg = RuntimeConfig(ladder=ladder)
    seq, forest = build_graph_resilient(tail, head, config=cfg)
    np.testing.assert_array_equal(seq, want_seq)
    _assert_matches(forest, want)


def test_resilient_retry_recovers_faulted_dispatch(small_graph):
    tail, head, _, want = small_graph
    cfg = RuntimeConfig(ladder=("single", "host"), backoff_base_s=0.0)
    install_plan(FaultPlan(site="chunk", at=1, kind="xla", times=2))
    _, forest = build_graph_resilient(tail, head, config=cfg)
    _assert_matches(forest, want)
    assert [e for e in cfg.events if e[0] == "retry"], \
        "the injected faults must actually have exercised the retry path"


# ---------------------------------------------------------------------------
# degradation ladder
# ---------------------------------------------------------------------------


def test_ladder_degrades_mesh_to_single(small_graph, tmp_path):
    tail, head, _, want = small_graph
    cfg = RuntimeConfig(checkpoint_dir=str(tmp_path), max_retries=1,
                        backoff_base_s=0.0,
                        ladder=("mesh", "single", "host"))
    install_plan(FaultPlan(site="mesh_chunk", at=0, kind="xla", times=-1))
    _, forest = build_graph_resilient(tail, head, config=cfg)
    _assert_matches(forest, want)
    degrades = [(e[1], e[2]) for e in cfg.events if e[0] == "degrade"]
    assert degrades == [("mesh", "single")]


def test_ladder_forced_mesh_to_host_matches(small_graph, tmp_path):
    # acceptance criterion: a forced mesh -> host degradation run matches
    # the uninterrupted build (parent, pst, and ECV(down))
    tail, head, want_seq, want = small_graph
    cfg = RuntimeConfig(checkpoint_dir=str(tmp_path), max_retries=1,
                        backoff_base_s=0.0,
                        ladder=("mesh", "single", "host"))
    install_plan(
        FaultPlan(site="mesh_chunk,chunk", at=0, kind="xla", times=-1))
    seq, forest = build_graph_resilient(tail, head, config=cfg)
    clear_plan()
    _assert_matches(forest, want)
    degrades = [(e[1], e[2]) for e in cfg.events if e[0] == "degrade"]
    assert degrades == [("mesh", "single"), ("single", "host")]
    assert _ecv_down(tail, head, seq, forest) == \
        _ecv_down(tail, head, want_seq, want)


def test_ladder_respects_device_count(small_graph, monkeypatch):
    # a 1-worker request must not try the mesh rung at all
    tail, head, _, want = small_graph
    cfg = RuntimeConfig(ladder=("mesh", "single", "host"))
    _, forest = build_graph_resilient(tail, head, num_workers=1, config=cfg)
    _assert_matches(forest, want)
    assert not any(e[0] == "degrade" for e in cfg.events)


# ---------------------------------------------------------------------------
# checkpoint/resume equivalence — the acceptance property
# ---------------------------------------------------------------------------


def _resilient(tail, head, d, resume=False, ladder=("single", "host"),
               **kw):
    cfg = RuntimeConfig(checkpoint_dir=d, resume=resume, ladder=ladder,
                        backoff_base_s=0.0, **kw)
    seq, forest = build_graph_resilient(tail, head, config=cfg)
    return seq, forest, cfg


@pytest.mark.parametrize("ladder", [("single", "host"),
                                    ("mesh", "single", "host")])
def test_resume_equivalence_kill_at_every_boundary(small_graph, tmp_path,
                                                   ladder):
    """Kill the build at EVERY chunk boundary in turn; each resumed build
    must be bit-identical (parent, pst, ECV(down)) to the uninterrupted
    one."""
    tail, head, _, want = small_graph
    seq0, forest0, cfg0 = _resilient(tail, head,
                                     str(tmp_path / "base"), ladder=ladder)
    _assert_matches(forest0, want)  # uninterrupted == oracle
    ecv0 = _ecv_down(tail, head, seq0, forest0)
    boundaries = [e for e in cfg0.events if e[0] == "checkpoint"]
    assert len(boundaries) >= 3, \
        f"graph too small to exercise resume ({len(boundaries)} boundaries)"

    for k in range(len(boundaries)):
        d = str(tmp_path / f"kill{k}")
        install_plan(FaultPlan(site="boundary", at=k, kind="kill"))
        with pytest.raises(BuildKilled):
            _resilient(tail, head, d, ladder=ladder)
        clear_plan()
        # a fresh process resumes from the last completed chunk
        seq1, forest1, cfg1 = _resilient(tail, head, d, resume=True,
                                         ladder=ladder)
        assert any(e[0] == "resume" for e in cfg1.events), k
        np.testing.assert_array_equal(seq1, seq0)
        np.testing.assert_array_equal(forest1.parent, forest0.parent,
                                      err_msg=f"kill at boundary {k}")
        np.testing.assert_array_equal(forest1.pst_weight,
                                      forest0.pst_weight,
                                      err_msg=f"kill at boundary {k}")
        assert _ecv_down(tail, head, seq1, forest1) == ecv0, k


def test_resume_without_checkpoint_builds_fresh(small_graph, tmp_path):
    tail, head, _, want = small_graph
    _, forest, cfg = _resilient(tail, head, str(tmp_path), resume=True)
    _assert_matches(forest, want)
    assert not any(e[0] == "resume" for e in cfg.events)


def test_resume_rejects_mismatched_input(small_graph, tmp_path):
    tail, head, _, _ = small_graph
    d = str(tmp_path)
    install_plan(FaultPlan(site="boundary", at=1, kind="kill"))
    with pytest.raises(BuildKilled):
        _resilient(tail, head, d)
    clear_plan()
    other_t, other_h = rmat_edges(9, 4 << 9, seed=99)
    with pytest.raises(ValueError, match="refusing to resume"):
        _resilient(other_t, other_h, d, resume=True)


def test_checkpoint_cleared_on_success(small_graph, tmp_path):
    from sheep_tpu.runtime.snapshot import SNAPSHOT_NAME

    tail, head, _, _ = small_graph
    _resilient(tail, head, str(tmp_path))
    assert not os.path.exists(tmp_path / SNAPSHOT_NAME)


def test_snapshot_roundtrip(tmp_path):
    from sheep_tpu.runtime.snapshot import (Checkpointer, Snapshot,
                                            input_signature)

    seq = np.arange(8, dtype=np.uint32)
    sig = input_signature(8, seq)
    ck = Checkpointer(str(tmp_path), every=2)
    snap = Snapshot(n=8, seq=seq, pst=np.ones(8, np.uint32),
                    lo=np.array([0, 1], np.int32),
                    hi=np.array([3, 7], np.int32),
                    rounds=5, boundary=0, rung="single", input_sig=sig)
    assert ck.want()
    ck.save(snap)
    assert not ck.want()  # cadence: every 2nd boundary persists
    ck.skip()
    assert ck.want()
    back = Checkpointer(str(tmp_path)).load()
    assert back is not None and back.rounds == 5 and back.rung == "single"
    np.testing.assert_array_equal(back.lo, snap.lo)
    back.verify(sig)
    with pytest.raises(ValueError, match="refusing to resume"):
        back.verify(input_signature(8, seq[::-1].copy()))


# ---------------------------------------------------------------------------
# init_distributed connect timeout (satellite)
# ---------------------------------------------------------------------------


def test_init_distributed_unreachable_coordinator_times_out(tmp_path):
    """An unreachable coordinator must fail fast with a clear error, not
    hang the worker until the harness kills it."""
    script = tmp_path / "probe.py"
    script.write_text(
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from sheep_tpu.parallel import init_distributed\n"
        "try:\n"
        "    init_distributed('127.0.0.1:9', 2, 1, connect_timeout_s=2)\n"
        "except RuntimeError as exc:\n"
        "    print(exc)\n"
        "    sys.exit(7)\n"
        "sys.exit(0)\n")
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 7, proc.stdout + proc.stderr
    assert "could not join" in proc.stdout
    assert "127.0.0.1:9" in proc.stdout
    assert time.monotonic() - t0 < 100


# ---------------------------------------------------------------------------
# CLI flags (satellite): --checkpoint-dir / --resume / --max-retries
# ---------------------------------------------------------------------------


def test_graph2tree_checkpoint_flags(tmp_path, small_graph):
    from sheep_tpu.io.edges import write_net

    tail, head, _, _ = small_graph
    graph = tmp_path / "g.net"
    write_net(str(graph), tail, head)
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""), JAX_PLATFORMS="cpu")

    def cli(*args):
        return subprocess.run(
            [sys.executable, "-m", "sheep_tpu.cli.graph2tree", str(graph)]
            + list(args), capture_output=True, text=True, env=env,
            timeout=300)

    r = cli("-o", str(tmp_path / "plain.tre"))
    assert r.returncode == 0, r.stdout + r.stderr
    r = cli("-o", str(tmp_path / "ft.tre"),
            "--checkpoint-dir", str(tmp_path / "ck"), "--max-retries", "2")
    assert r.returncode == 0, r.stdout + r.stderr
    assert (tmp_path / "ft.tre").read_bytes() == \
        (tmp_path / "plain.tre").read_bytes()
    # success clears the snapshot
    assert os.listdir(tmp_path / "ck") == []
    # --resume without a checkpoint location is a reported config error
    r = cli("-o", str(tmp_path / "x.tre"), "--resume")
    assert r.returncode != 0
    assert "checkpoint-dir" in r.stdout + r.stderr


# ---------------------------------------------------------------------------
# checkpoint cadence auto-tuning (ISSUE 3 satellite)
# ---------------------------------------------------------------------------


def test_auto_cadence_retunes_from_measurement(tmp_path):
    from sheep_tpu.runtime.snapshot import Checkpointer

    ck = Checkpointer(str(tmp_path), every=0)
    assert ck.auto and ck.every == 1
    # snapshots as expensive as a chunk -> persist every 10th boundary
    # (10% overhead target)
    assert ck.observe(1.0, 1.0) == 10
    # cheap snapshots -> back to every boundary
    assert ck.observe(0.001, 1.0) == 1
    # pathological cost is capped (bounded progress loss on a crash)
    assert ck.observe(100.0, 0.1) == 64
    assert ck.observe(100.0, 0.1) is None  # unchanged -> no event
    # degenerate measurements never retune
    assert ck.observe(1.0, 0.0) is None
    assert ck.observe(-1.0, 1.0) is None


def test_fixed_cadence_ignores_observations(tmp_path):
    from sheep_tpu.runtime.snapshot import Checkpointer

    ck = Checkpointer(str(tmp_path), every=3)
    assert not ck.auto
    assert ck.observe(9.0, 0.1) is None
    assert ck.every == 3
    with pytest.raises(ValueError):
        Checkpointer(str(tmp_path), every=-1)


def test_auto_cadence_env_spelling(monkeypatch):
    monkeypatch.setenv("SHEEP_CHECKPOINT_EVERY", "auto")
    assert RuntimeConfig.from_env().checkpoint_every == 0
    monkeypatch.setenv("SHEEP_CHECKPOINT_EVERY", "4")
    assert RuntimeConfig.from_env().checkpoint_every == 4


def test_auto_cadence_build_matches_oracle(small_graph, tmp_path):
    tail, head, _, want = small_graph
    cfg = RuntimeConfig(checkpoint_dir=str(tmp_path), checkpoint_every=0,
                        ladder=("single", "host"))
    _, forest = build_graph_resilient(tail, head, config=cfg)
    _assert_matches(forest, want)
    assert any(e[0] == "checkpoint" for e in cfg.events)


def test_auto_cadence_resume_still_bit_identical(small_graph, tmp_path):
    # kill at the first persisted boundary of an auto-cadence build; the
    # resume must stay bit-identical (cadence only changes WHICH
    # boundaries persist, never what a snapshot means)
    tail, head, _, want = small_graph
    d = str(tmp_path)
    install_plan(FaultPlan(site="boundary", at=1, kind="kill"))
    with pytest.raises(BuildKilled):
        build_graph_resilient(tail, head, config=RuntimeConfig(
            checkpoint_dir=d, checkpoint_every=0, ladder=("single", "host")))
    clear_plan()
    cfg = RuntimeConfig(checkpoint_dir=d, checkpoint_every=0, resume=True,
                        ladder=("single", "host"))
    _, forest = build_graph_resilient(tail, head, config=cfg)
    _assert_matches(forest, want)


# ---------------------------------------------------------------------------
# mesh-rung promotion back to the pipelined path (ISSUE 3 satellite)
# ---------------------------------------------------------------------------


def test_promotion_after_healthy_streak(small_graph):
    tail, head, _, want = small_graph
    cfg = RuntimeConfig(ladder=("single", "host"), promote_after=2)
    _, forest = build_graph_resilient(tail, head, config=cfg)
    _assert_matches(forest, want)
    assert any(e[0] == "promote" for e in cfg.events), cfg.events


def test_promotion_disabled_by_zero(small_graph):
    tail, head, _, want = small_graph
    cfg = RuntimeConfig(ladder=("single", "host"), promote_after=0)
    _, forest = build_graph_resilient(tail, head, config=cfg)
    _assert_matches(forest, want)
    assert not any(e[0] == "promote" for e in cfg.events)


def test_promotion_demotes_on_fault_and_recovers(small_graph):
    # fault a dispatch AFTER promotion: the runtime must demote back to
    # the FT wrapper, retry under the full policy, and still match
    tail, head, _, want = small_graph
    cfg = RuntimeConfig(ladder=("single", "host"), promote_after=1,
                        backoff_base_s=0.0)
    install_plan(FaultPlan(site="chunk", at=3, kind="xla", times=1))
    _, forest = build_graph_resilient(tail, head, config=cfg)
    clear_plan()
    _assert_matches(forest, want)
    kinds = [e[0] for e in cfg.events]
    assert "promote" in kinds and "demote" in kinds, cfg.events
    # the post-demotion retry actually ran
    assert kinds.index("demote") < len(kinds)


def test_promotion_env_knob(monkeypatch):
    monkeypatch.setenv("SHEEP_PROMOTE_AFTER", "0")
    assert RuntimeConfig.from_env().promote_after == 0
    monkeypatch.setenv("SHEEP_PROMOTE_AFTER", "5")
    assert RuntimeConfig.from_env().promote_after == 5
