"""Device kernels (sheep_tpu.ops) == sequential oracle (sheep_tpu.core).

The batched fixpoint formulation must produce the *identical* parent array
to the reference's sequential union-find insert loop on every input — this
is SURVEY §7's "hard part #1", tested here on adversarial shapes (stars and
paths exercise the chain/jump rewrites), random multigraphs with self-loops,
and the bundled hep-th graph.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from conftest import random_multigraph

from sheep_tpu import INVALID_JNID

from sheep_tpu.core import (
    build_forest, degree_sequence, merge_forests, edges_to_positions,
)
from sheep_tpu.core.forest import build_forest_links
from sheep_tpu.ops import (
    build_forest_device, degree_sequence_device, merge_forests_device,
    build_graph_device, forest_fixpoint,
)


def assert_forest_equal(got, want, msg=""):
    np.testing.assert_array_equal(got.parent, want.parent, err_msg=msg)
    np.testing.assert_array_equal(got.pst_weight, want.pst_weight, err_msg=msg)


def both_forests(tail, head):
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq)
    got = build_forest_device(tail, head, seq)
    return got, want


# --- adversarial structures -------------------------------------------------

def test_star_center_first():
    # Center eliminated first => elimination tree is a path: the worst case
    # for naive parallel union-find, handled by the star->chain rewrite.
    n = 64
    tail = np.zeros(n - 1, dtype=np.uint32)
    head = np.arange(1, n, dtype=np.uint32)
    seq = np.arange(n, dtype=np.uint32)  # identity order: center at pos 0
    want = build_forest(tail, head, seq)
    got = build_forest_device(tail, head, seq)
    assert_forest_equal(got, want)
    # depth-n chain must not take ~n rounds
    lo, hi = edges_to_positions(tail, head, seq)
    import jax.numpy as jnp
    _, rounds = forest_fixpoint(jnp.asarray(lo, jnp.int32),
                                jnp.asarray(hi, jnp.int32), n)
    assert int(rounds) < 20, f"star took {int(rounds)} rounds"


def test_path_graph():
    n = 100
    tail = np.arange(n - 1, dtype=np.uint32)
    head = np.arange(1, n, dtype=np.uint32)
    assert_forest_equal(*both_forests(tail, head))


def test_complete_graph():
    n = 24
    tail, head = np.triu_indices(n, k=1)
    assert_forest_equal(*both_forests(tail.astype(np.uint32),
                                      head.astype(np.uint32)))


def test_crossing_links_counterexample():
    # The case that breaks naive batched min-attach: link (1,4)'s root lags
    # behind while (3,5) would commit parent[3]=5; truth is parent[3]=4.
    seq = np.arange(6, dtype=np.uint32)
    tail = np.array([1, 2, 1, 3], dtype=np.uint32)
    head = np.array([2, 3, 4, 5], dtype=np.uint32)
    want = build_forest(tail, head, seq)
    got = build_forest_device(tail, head, seq)
    assert want.parent[3] == 4
    assert_forest_equal(got, want)


def test_binary_staircase():
    # Nested components merging at every scale.
    rng = np.random.default_rng(7)
    n = 128
    edges = []
    for width in (2, 4, 8, 16, 32, 64, 128):
        for s in range(0, n, width):
            edges.append((s, s + width - 1))
    tail = np.array([a for a, _ in edges], dtype=np.uint32)
    head = np.array([b for _, b in edges], dtype=np.uint32)
    assert_forest_equal(*both_forests(tail, head))


# --- randomized equivalence -------------------------------------------------

@pytest.mark.parametrize("trial", range(40))
def test_random_multigraph_device_equals_oracle(trial):
    rng = np.random.default_rng(1000 + trial)
    tail, head = random_multigraph(rng)
    assert_forest_equal(*both_forests(tail, head), msg=f"trial {trial}")


@pytest.mark.parametrize("trial", range(10))
def test_random_identity_sequence(trial):
    # Non-degree orders must work too (fileSequence / -s flag paths).
    rng = np.random.default_rng(2000 + trial)
    tail, head = random_multigraph(rng, n_max=60, e_max=200)
    n = int(max(tail.max(), head.max())) + 1
    seq = rng.permutation(n).astype(np.uint32)
    want = build_forest(tail, head, seq)
    got = build_forest_device(tail, head, seq)
    assert_forest_equal(got, want, msg=f"trial {trial}")


# --- device sequence --------------------------------------------------------

@pytest.mark.parametrize("trial", range(15))
def test_degree_sequence_device(trial):
    rng = np.random.default_rng(3000 + trial)
    tail, head = random_multigraph(rng)
    np.testing.assert_array_equal(
        degree_sequence_device(tail, head), degree_sequence(tail, head))


def test_fused_build_matches_pipeline():
    rng = np.random.default_rng(42)
    tail, head = random_multigraph(rng, n_max=80, e_max=400)
    seq, forest = build_graph_device(tail, head)
    want_seq = degree_sequence(tail, head)
    np.testing.assert_array_equal(seq, want_seq)
    assert_forest_equal(forest, build_forest(tail, head, want_seq))


# --- device merge -----------------------------------------------------------

@pytest.mark.parametrize("parts", [2, 3, 8])
def test_merge_device_equals_oracle(parts):
    rng = np.random.default_rng(500 + parts)
    tail, head = random_multigraph(rng, n_max=50, e_max=300)
    seq = degree_sequence(tail, head)
    cuts = np.linspace(0, len(tail), parts + 1).astype(int)
    partials = [
        build_forest(tail[a:b], head[a:b], seq, max_vid=int(max(tail.max(), head.max())))
        for a, b in zip(cuts[:-1], cuts[1:])
    ]
    want = merge_forests(*partials)
    got = merge_forests_device(*partials)
    assert_forest_equal(got, want)
    # and the merged tree equals the whole-graph tree
    assert_forest_equal(got, build_forest(tail, head, seq))


# --- hep-th golden ----------------------------------------------------------

def test_hepth_device_equals_oracle(hep_edges):
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    want = build_forest(hep_edges.tail, hep_edges.head, seq)
    got = build_forest_device(hep_edges.tail, hep_edges.head, seq)
    assert_forest_equal(got, want)


def test_hepth_fixpoint_rounds(hep_edges):
    import jax.numpy as jnp
    seq = degree_sequence(hep_edges.tail, hep_edges.head)
    lo, hi = edges_to_positions(hep_edges.tail, hep_edges.head, seq)
    _, rounds = forest_fixpoint(jnp.asarray(lo, jnp.int32),
                                jnp.asarray(hi, jnp.int32), len(seq))
    assert int(rounds) < 64, f"hep-th took {int(rounds)} fixpoint rounds"


@pytest.mark.parametrize("seed", range(10))
def test_hosted_fixpoint_matches_oracle(seed):
    # The chunked host-orchestrated fixpoint (production path on hardware)
    # must produce the oracle parent array exactly.
    from sheep_tpu.ops.forest import forest_fixpoint_hosted

    rng = np.random.default_rng(900 + seed)
    tail, head = random_multigraph(rng, 80, 400)
    seq = degree_sequence(tail, head)
    want = build_forest(tail, head, seq, impl="python")
    from sheep_tpu.core.forest import edges_to_positions
    lo, hi = edges_to_positions(tail, head, seq)
    n = len(seq)
    pst_only = hi >= n
    lo_d = np.where(pst_only, n, lo)
    hi_d = np.where(pst_only, n, hi)
    parent, rounds = forest_fixpoint_hosted(
        jnp.asarray(lo_d, jnp.int32), jnp.asarray(hi_d, jnp.int32), n)
    parent = np.asarray(parent).astype(np.int64)
    got = np.full(n, INVALID_JNID, dtype=np.uint32)
    got[parent < n] = parent[parent < n].astype(np.uint32)
    np.testing.assert_array_equal(got, want.parent)


@pytest.mark.parametrize("seed,handoff", [(0, 2), (1, 2), (2, 1), (3, 1000)])
def test_build_graph_hybrid_matches_oracle(seed, handoff):
    # handoff=1000 exercises the handoff branch immediately (stop_live
    # huge -> first chunk hands off); small handoffs converge on device.
    from sheep_tpu.ops import build_graph_hybrid

    rng = np.random.default_rng(950 + seed)
    tail, head = random_multigraph(rng, 200, 1200)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    seq, forest = build_graph_hybrid(tail, head, handoff_factor=handoff)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


@pytest.mark.parametrize("handoff", [2, 1000])
def test_build_graph_hybrid_explicit_host_edges(handoff):
    # the accelerator configuration: seq/pst recomputed host-side from the
    # caller's edge copy instead of fetched from the device (auto-detect is
    # gated off on the cpu backend, so pass host_edges explicitly here)
    from sheep_tpu.ops import build_graph_hybrid

    rng = np.random.default_rng(955)
    tail, head = random_multigraph(rng, 200, 1200)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    seq, forest = build_graph_hybrid(tail, head, handoff_factor=handoff,
                                     host_edges=(tail, head))
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


@pytest.mark.parametrize("with_host_edges", [False, True])
@pytest.mark.parametrize("handoff", [2, 1000])
def test_build_graph_hybrid_given_seq(with_host_edges, handoff):
    # the `-s` fast path: no device histogram/sort, links map through the
    # given position table; a SUBSET sequence exercises the absent-vid pst
    # contract (edges to absent vids count toward pst, never the tree)
    from sheep_tpu.ops import build_graph_hybrid

    rng = np.random.default_rng(957)
    tail, head = random_multigraph(rng, 200, 1200)
    full = degree_sequence(tail, head)
    seq = full[: max(2, len(full) * 2 // 3)]
    want = build_forest(tail, head, seq,
                        max_vid=int(max(tail.max(), head.max())))
    he = (tail, head) if with_host_edges else None
    out_seq, forest = build_graph_hybrid(tail, head, handoff_factor=handoff,
                                         host_edges=he, seq=seq)
    np.testing.assert_array_equal(out_seq, seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


@pytest.mark.parametrize("given", [False, True])
@pytest.mark.parametrize("handoff", [2, 1000])
def test_build_graph_hybrid_prefetch_failure_lazy_pst(monkeypatch, handoff,
                                                      given):
    # with host_edges the device skips its pst scatter (with_pst=False) in
    # both the degree-sort and given-seq branches; if the host prefetch
    # then dies, the fallback must materialize pst lazily on device and
    # still be bit-identical to the oracle
    import sheep_tpu.ops.build as build_mod

    def boom(*a, **k):
        raise RuntimeError("prefetch failure injected by test")

    monkeypatch.setattr(build_mod, "_host_seq_pst", boom)
    rng = np.random.default_rng(962)
    tail, head = random_multigraph(rng, 200, 1200)
    full = degree_sequence(tail, head)
    # given-seq uses a SUBSET order so the absent-vid pst contract is in
    # play on the lazy path too
    seq_in = full[: max(2, len(full) * 2 // 3)] if given else None
    want_seq = seq_in if given else full
    want = build_forest(tail, head, want_seq,
                        max_vid=int(max(tail.max(), head.max())))
    seq, forest = build_mod.build_graph_hybrid(
        tail, head, handoff_factor=handoff, host_edges=(tail, head),
        seq=seq_in)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_build_graph_hybrid_device_inputs_no_host_copy():
    # device-array inputs without host_edges exercise the d2h prefetch
    # branch (numpy inputs auto-use the host recompute path)
    import jax.numpy as jnp
    from sheep_tpu.ops import build_graph_hybrid

    rng = np.random.default_rng(960)
    tail, head = random_multigraph(rng, 200, 1200)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    n = int(max(tail.max(), head.max())) + 1
    seq, forest = build_graph_hybrid(
        jnp.asarray(tail), jnp.asarray(head), n, handoff_factor=1000)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_host_seq_pst_matches_device():
    from sheep_tpu.ops.build import _host_seq_pst, prepare_links
    import jax.numpy as jnp

    rng = np.random.default_rng(961)
    tail, head = random_multigraph(rng, 300, 2000)  # includes self-loops
    n = int(max(tail.max(), head.max())) + 1
    seq_d, _, m, _, _, pst_d = prepare_links(
        jnp.asarray(tail), jnp.asarray(head), n)
    seq_h, pst_h = _host_seq_pst(tail, head, n)
    m = int(m)
    assert len(seq_h) == m
    np.testing.assert_array_equal(seq_h, np.asarray(seq_d)[:m])
    np.testing.assert_array_equal(pst_h, np.asarray(pst_d))


def test_pack_links_6b_roundtrip():
    from sheep_tpu.ops.forest import pack_links_6b, unpack_links_6b
    import jax.numpy as jnp

    rng = np.random.default_rng(962)
    lo = rng.integers(0, (1 << 24) - 1, 5000).astype(np.int32)
    hi = rng.integers(0, (1 << 24) - 1, 5000).astype(np.int32)
    buf = np.asarray(pack_links_6b(jnp.asarray(lo), jnp.asarray(hi)))
    assert buf.dtype == np.uint8 and buf.shape == (5000, 6)
    lo2, hi2 = unpack_links_6b(buf)
    np.testing.assert_array_equal(lo2, lo)
    np.testing.assert_array_equal(hi2, hi)


def test_build_graph_hybrid_packed_handoff(monkeypatch):
    # force the packed 6-byte handoff (default-off on the cpu backend)
    from sheep_tpu.ops import build_graph_hybrid

    monkeypatch.setenv("SHEEP_PACK_HANDOFF", "1")
    rng = np.random.default_rng(963)
    tail, head = random_multigraph(rng, 300, 2000)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    seq, forest = build_graph_hybrid(tail, head, handoff_factor=1000)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_build_graph_device_rmat_oracle():
    from sheep_tpu.ops import build_graph_device
    from sheep_tpu.utils import rmat_edges

    tail, head = rmat_edges(12, 4 << 12, seed=3)
    want_seq = degree_sequence(tail, head)
    want = build_forest(tail, head, want_seq)
    seq, forest = build_graph_device(tail, head)
    np.testing.assert_array_equal(seq, want_seq)
    np.testing.assert_array_equal(forest.parent, want.parent)
    np.testing.assert_array_equal(forest.pst_weight, want.pst_weight)


def test_depth_tier_rule():
    """Pin the three-tier lifting-depth boundaries (PERF_NOTES round-4
    A/B): light at full width inside the schedule, +2 mid, +6 below an
    eighth, capped at log2(n)."""
    from sheep_tpu.ops.forest import _depth_tier

    pad, levels, first, cap = 1 << 20, 10, 4, 22
    assert _depth_tier(pad, pad, True, levels, first, cap) == first
    # outside the schedule, full width no longer gets the light tier
    assert _depth_tier(pad, pad, False, levels, first, cap) == levels + 2
    assert _depth_tier(pad // 2, pad, True, levels, first, cap) == levels + 2
    assert _depth_tier(pad // 8 + 1, pad, True, levels, first, cap) \
        == levels + 2
    assert _depth_tier(pad // 8, pad, True, levels, first, cap) == levels + 6
    # small-n cap beats the escalation
    assert _depth_tier(100, 4096, False, levels, first, 9) == 9


def test_vremap_roundtrip_and_composition():
    """vremap_compact relabels monotonically, back-maps exactly, and the
    back tables compose the way reduce_links_hosted chains them."""
    from sheep_tpu.ops.forest import vremap_compact, vremap_back

    rng = np.random.default_rng(41)
    n = 1 << 18
    verts = np.sort(rng.choice(n - 1, size=600, replace=False))
    lo = verts[rng.integers(0, 500, 2048)].astype(np.int32)
    hi = (lo + 1 + rng.integers(0, 50, 2048)).astype(np.int32)
    dead = rng.random(2048) < 0.3
    lo[dead] = n
    hi[dead] = n
    nc1 = 2 * len(lo)
    lo1, hi1, back1 = vremap_compact(jnp.asarray(lo), jnp.asarray(hi),
                                     n, nc1)
    lo1_np, hi1_np = np.asarray(lo1), np.asarray(hi1)
    # monotone relabel: order within live links is preserved, dead -> nc1
    live = lo < n
    assert np.all(lo1_np[live] < hi1_np[live])
    assert np.all(lo1_np[~live] == nc1) and np.all(hi1_np[~live] == nc1)
    rlo, rhi = vremap_back(lo1, hi1, back1)
    np.testing.assert_array_equal(np.asarray(rlo), lo)
    np.testing.assert_array_equal(np.asarray(rhi), hi)
    # second remap into a smaller space + composed back table
    nc2 = 1 << 12
    lo2, hi2, back2 = vremap_compact(lo1, hi1, nc1, nc2)
    back_total = back1[back2]
    rlo2, rhi2 = vremap_back(lo2, hi2, back_total)
    np.testing.assert_array_equal(np.asarray(rlo2), lo)
    np.testing.assert_array_equal(np.asarray(rhi2), hi)


@pytest.mark.parametrize("seed", range(3))
def test_hosted_fixpoint_vremap_sparse_matches_dense(seed, monkeypatch):
    """A sparse live set over a large position space triggers the vertex
    remap (2*cols <= n/4 with n > 2^16); parents must be bit-identical to
    the remap-disabled run and the remap must actually fire."""
    import sheep_tpu.ops.forest as F

    rng = np.random.default_rng(1300 + seed)
    n = 1 << 17
    # chains among ~1500 scattered positions: stays sparse, needs several
    # chunks, and cols pads to the 4096 floor => remap fires immediately
    verts = np.sort(rng.choice(n - 1, size=1500, replace=False))
    idx = rng.integers(0, 1400, 3000)
    lo = verts[idx].astype(np.int32)
    hi = verts[idx + 1 + rng.integers(0, 90, 3000)].astype(np.int32)
    bad = lo >= hi
    lo[bad] = n
    hi[bad] = n

    calls = {"remaps": 0}
    real = F.vremap_compact

    def counting(*a, **k):
        calls["remaps"] += 1
        return real(*a, **k)

    monkeypatch.setattr(F, "vremap_compact", counting)
    monkeypatch.setenv("SHEEP_VREMAP", "1")
    p_on, _ = F.forest_fixpoint_hosted(jnp.asarray(lo), jnp.asarray(hi), n)
    assert calls["remaps"] >= 1, "remap did not trigger on the sparse case"
    monkeypatch.setenv("SHEEP_VREMAP", "0")
    p_off, _ = F.forest_fixpoint_hosted(jnp.asarray(lo), jnp.asarray(hi), n)
    np.testing.assert_array_equal(np.asarray(p_on), np.asarray(p_off))


def test_sort_links_branches_agree(monkeypatch):
    """The packed-int64 and 2-key variadic branches of sort_links must
    produce identical lexicographic results (the packed branch is the cpu
    default, the 2-key branch the accelerator default — tests force cpu,
    so without this check the 2-key branch would be untested).  Eager
    calls: the gate is read at trace time, so a jitted caller would keep
    whichever branch it compiled first."""
    from sheep_tpu.ops.forest import sort_links

    rng = np.random.default_rng(77)
    n = (1 << 22) + 3
    lo = rng.integers(0, n, 5000).astype(np.int32)
    hi = rng.integers(0, n, 5000).astype(np.int32)
    dead = rng.random(5000) < 0.2
    lo[dead] = n
    hi[dead] = n
    out = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("SHEEP_SORT_PACK64", mode)
        slo, shi = sort_links(jnp.asarray(lo), jnp.asarray(hi))
        out[mode] = (np.asarray(slo), np.asarray(shi))
        assert out[mode][0].dtype == np.int32
    np.testing.assert_array_equal(out["0"][0], out["1"][0])
    np.testing.assert_array_equal(out["0"][1], out["1"][1])
    order = np.lexsort((hi, lo))
    np.testing.assert_array_equal(out["1"][0], lo[order])
    np.testing.assert_array_equal(out["1"][1], hi[order])


def test_degree_order_branches_agree(monkeypatch):
    """degree_order's packed and 2-key branches must agree (same gate as
    sort_links; tests run cpu = packed, accelerators get 2-key)."""
    import jax

    from sheep_tpu.ops.sort import degree_order

    rng = np.random.default_rng(78)
    deg = rng.integers(0, 50, 4096).astype(np.int32)
    deg[rng.random(4096) < 0.3] = 0
    out = {}
    for mode in ("0", "1"):
        monkeypatch.setenv("SHEEP_SORT_PACK64", mode)
        jax.clear_caches()  # the gate is trace-time; drop the cached branch
        seq, pos, m = degree_order(jnp.asarray(deg))
        out[mode] = (np.asarray(seq), np.asarray(pos), int(m))
    np.testing.assert_array_equal(out["0"][0], out["1"][0])
    np.testing.assert_array_equal(out["0"][1], out["1"][1])
    assert out["0"][2] == out["1"][2]
